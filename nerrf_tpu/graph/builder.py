"""Temporal dependency-graph construction: event windows → padded device graphs.

Implements the reference's specified graph constructor
(`/root/reference/docs/content/docs/architecture.mdx:32-43`: sliding window
30–60 s, node merging by inode, causality-confidence edge weights; node schema
at `architecture.mdx:144-160`) — re-architected for XLA's static-shape world:

* A window of events lowers to a **fixed-capacity padded graph**
  (`GraphBatch`): `max_nodes`/`max_edges` slots, boolean masks for validity,
  edges sorted by destination so message passing is a segment reduction.
  Snapshots of any window therefore all share one shape → one XLA compilation.
* Nodes are **files keyed by inode** (dedup per spec) and **processes keyed by
  pid**.  Because inode identity survives renames (our loaders carry it), a
  rename is a node *property* (rename_count, suspicious-extension flag), not a
  file→file edge — same information, no dynamic node growth mid-window.
* Edges are **aggregated (process, file) interaction pairs** with per-syscall
  count features and a causality weight (event count within window); the GNN
  classifies these edges as normal/attack, exactly the reference's task
  ("classify edges as normal/attack", `architecture.mdx:49-53`).
* Per-node features realize the threat model's indicator set
  (`threat-model.mdx:176-189`: in/out-degree, temporal delta, byte ratio,
  extension pattern) plus the interned path-feature rows.

All host-side work is vectorized numpy — no per-event Python in the hot path —
so a ~25k-event window (the density projected at `threat-model.mdx:121-137`)
lowers in milliseconds.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Tuple

import numpy as np

from nerrf_tpu.data.loaders import Trace
from nerrf_tpu.tracing import span as trace_span
from nerrf_tpu.schema.events import (
    EXT_VOCAB,
    EventArrays,
    StringTable,
    Syscall,
    _stable_hash,
)

_NS = 1_000_000_000

NODE_TYPE_FILE = 0
NODE_TYPE_PROCESS = 1

# node_aux vocabulary: 0 = pad, 1..EXT_VOCAB = file extension ids,
# then AUX_COMM_BUCKETS process-comm hash buckets.
AUX_COMM_BUCKETS = 32
AUX_COMM_BASE = 1 + EXT_VOCAB
AUX_VOCAB = AUX_COMM_BASE + AUX_COMM_BUCKETS

# Node feature layout (float32):
#   0..7   path_features row (files; zeros for processes)
#   8      read_count    (log1p)
#   9      write_count   (log1p)
#   10     rename_count  (log1p)
#   11     unlink_count  (log1p)
#   12     open_count    (log1p)
#   13     stat/other count (log1p)
#   14     bytes_read    (log1p, MB-ish scale)
#   15     bytes_written (log1p)
#   16     in_degree     (log1p; distinct peers writing to this node)
#   17     out_degree    (log1p; distinct peers this node acts on)
#   18     active_span   (last_seen - first_seen, fraction of window)
#   19     mean inter-event gap (fraction of window)
#   20     write/read byte ratio (the spec's "byte count ratio")
#   21     is_process flag
#   22     renamed-by-writer fraction: of this file's renames, the share
#          done by a process that ALSO wrote the file in-window — the
#          threat model's write→rename motif as a feature.  Separates
#          logrotate's rename-only touch (0.0) from ransomware's
#          encrypt-then-rename (1.0); measured r4: without it the probe
#          model scored rotated logs p≈0.983, inseparable from stealth
#          victims, and the zero-FP cut zeroed benign-comm detection.
#   23     in-place-overwrite flag: some process both read and wrote this
#          file in-window (the no-rename encryption signature; also fires
#          on e.g. postgres data files, which is exactly the benign
#          context the model must weigh).
NODE_FEATURE_DIM = 24

# Edge feature layout (float32):
#   0..5   per-syscall event counts on this (src,dst) pair
#          [openat, write, rename, read, unlink, other]  (log1p)
#   6      bytes moved on the pair (log1p)
#   7      event rate on the pair (events/sec over window, log1p)
#   8      mean inter-event gap on the pair (fraction of window)
#   9      first-seen offset in window [0,1]
#   10     last-seen offset in window [0,1]
#   11     suspicious-extension involvement flag
#   12     causality weight: pair events / total window events
EDGE_FEATURE_DIM = 13


@dataclasses.dataclass(frozen=True)
class GraphConfig:
    """Window + capacity knobs.  Defaults: 45 s window / 15 s stride (inside
    the spec's 30–60 s band), capacities sized ~4× the M1 scale (45-50 files +
    a handful of processes) so padding dominates only mildly.

    Capacity guidance (measured, benchmarks/run_graph_capacity.py): the
    defaults fit the synthetic training corpus (40 Hz benign load) with zero
    drops, but a ~25 k-event window at projected real-eBPF density
    (threat-model.mdx:121-137, ≈550 evt/s) needs ~3.2 k nodes / 4.4 k edges —
    at the defaults ~34 % of events drop.  Online paths at real density
    should use :meth:`fit` (exact count → power-of-two bucket), which bounds
    XLA recompiles to the handful of bucket shapes."""

    window_sec: float = 45.0
    stride_sec: float = 15.0
    max_nodes: int = 256
    max_edges: int = 512

    @staticmethod
    def bucket(need: int, floor: int, headroom: float = 1.25) -> int:
        """THE sizing policy: need × headroom, next power of two, floored.
        Every auto-capacity consumer (fit, model_detect) goes through here
        so the policy cannot silently diverge between paths."""
        need = max(int(np.ceil(need * headroom)), floor)
        return 1 << int(np.ceil(np.log2(need)))

    def fit_counts(self, n_nodes: int, n_edges: int,
                   headroom: float = 1.25) -> "GraphConfig":
        """Capacities sized to given exact needs (bucket policy above)."""
        return dataclasses.replace(
            self,
            max_nodes=self.bucket(n_nodes, self.max_nodes, headroom),
            max_edges=self.bucket(n_edges, self.max_edges, headroom),
        )

    def fit(self, events: "EventArrays", lo_ns: int, hi_ns: int,
            headroom: float = 1.25) -> "GraphConfig":
        """Capacities sized to THIS window's exact node/edge need."""
        n_nodes, n_edges = measure_window(events, lo_ns, hi_ns)
        return self.fit_counts(n_nodes, n_edges, headroom)


def measure_window(events: "EventArrays", lo_ns: int, hi_ns: int) -> Tuple[int, int]:
    """Exact (num_nodes, num_edges) a window needs for zero-drop lowering:
    nodes = unique processes + unique file inodes, edges = unique
    (process, file) pairs — the same universe build_window_graph constructs,
    counted vectorized without building anything."""
    sel = (
        events.valid
        & (events.ts_ns >= lo_ns)
        & (events.ts_ns < hi_ns)
        & (events.syscall != int(Syscall.MARKER))
    )
    pid = events.pid[sel].astype(np.int64)
    inode = events.inode[sel]
    has_file = inode > 0
    n_nodes = len(np.unique(pid)) + len(np.unique(inode[has_file]))
    pairs = np.stack(
        [pid[has_file], inode[has_file].astype(np.int64)], axis=1)
    n_edges = len(np.unique(pairs, axis=0)) if len(pairs) else 0
    return n_nodes, n_edges


@dataclasses.dataclass
class WindowStats:
    """Host-side observability for one lowering (overflow accounting)."""

    num_events: int = 0
    num_nodes: int = 0
    num_edges: int = 0
    dropped_nodes: int = 0
    dropped_edges: int = 0
    dropped_events: int = 0


@dataclasses.dataclass
class GraphBatch:
    """One padded window graph (all arrays fixed-shape, device-ready).

    Edges are sorted by ``edge_dst`` so neighbor aggregation is a single
    segment-sum over a monotone segment-id vector — the layout the Pallas
    aggregation kernel and `jax.ops.segment_sum` both want.
    """

    node_feat: np.ndarray  # float32 [max_nodes, NODE_FEATURE_DIM]
    node_type: np.ndarray  # int32  [max_nodes]
    node_aux: np.ndarray   # int32  [max_nodes] identity bucket (ext / comm)
    node_mask: np.ndarray  # bool   [max_nodes]
    node_key: np.ndarray   # int64  [max_nodes] (inode | pid tag; host-side id)
    node_label: np.ndarray  # float32 [max_nodes]
    edge_src: np.ndarray   # int32  [max_edges]
    edge_dst: np.ndarray   # int32  [max_edges] (sorted ascending on valid prefix)
    edge_feat: np.ndarray  # float32 [max_edges, EDGE_FEATURE_DIM]
    edge_mask: np.ndarray  # bool   [max_edges]
    edge_label: np.ndarray  # float32 [max_edges]
    window_start_ns: int = 0
    window_end_ns: int = 0

    @property
    def num_nodes(self) -> int:
        return int(self.node_mask.sum())

    @property
    def num_edges(self) -> int:
        return int(self.edge_mask.sum())

    def arrays(self) -> dict[str, np.ndarray]:
        return {
            f.name: getattr(self, f.name)
            for f in dataclasses.fields(self)
            if isinstance(getattr(self, f.name), np.ndarray)
        }

    @staticmethod
    def stack(batches: List["GraphBatch"]) -> dict[str, np.ndarray]:
        """Stack same-shape windows into [B, ...] arrays for device transfer."""
        if not batches:
            raise ValueError("cannot stack zero graphs")
        names = batches[0].arrays().keys()
        return {n: np.stack([getattr(b, n) for b in batches]) for n in names}


_PROC_TAG = np.int64(1) << np.int64(62)

_SYSCALL_TO_EDGE_SLOT = {
    int(Syscall.OPENAT): 0,
    int(Syscall.WRITE): 1,
    int(Syscall.RENAME): 2,
    int(Syscall.READ): 3,
    int(Syscall.UNLINK): 4,
}


def _first_appearance_unique(keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Like np.unique but ids are assigned in order of first appearance, so
    node numbering is stable under capacity truncation."""
    uniq_sorted, inv_sorted = np.unique(keys, return_inverse=True)
    first_pos = np.full(len(uniq_sorted), np.iinfo(np.int64).max, np.int64)
    np.minimum.at(first_pos, inv_sorted, np.arange(len(keys)))
    order = np.argsort(first_pos, kind="stable")
    rank = np.empty_like(order)
    rank[order] = np.arange(len(order))
    return uniq_sorted[order], rank[inv_sorted]


def build_window_graph(
    events: EventArrays,
    strings: StringTable,
    lo_ns: int,
    hi_ns: int,
    cfg: GraphConfig,
    labels: Optional[np.ndarray] = None,
) -> Tuple[GraphBatch, WindowStats]:
    """Lower the events in [lo_ns, hi_ns) to one padded window graph."""
    with trace_span("graph_lower") as sp:
        g, stats = _build_window_graph(events, strings, lo_ns, hi_ns, cfg,
                                       labels=labels)
        sp.args.update(events=stats.num_events, nodes=stats.num_nodes,
                       edges=stats.num_edges)
    return g, stats


def _build_window_graph(
    events: EventArrays,
    strings: StringTable,
    lo_ns: int,
    hi_ns: int,
    cfg: GraphConfig,
    labels: Optional[np.ndarray] = None,
) -> Tuple[GraphBatch, WindowStats]:
    stats = WindowStats()
    window_ns = max(hi_ns - lo_ns, 1)

    sel = (
        events.valid
        & (events.ts_ns >= lo_ns)
        & (events.ts_ns < hi_ns)
        & (events.syscall != int(Syscall.MARKER))
    )
    idx = np.nonzero(sel)[0]
    stats.num_events = len(idx)

    g = GraphBatch(
        node_feat=np.zeros((cfg.max_nodes, NODE_FEATURE_DIM), np.float32),
        node_type=np.zeros(cfg.max_nodes, np.int32),
        node_aux=np.zeros(cfg.max_nodes, np.int32),
        node_mask=np.zeros(cfg.max_nodes, np.bool_),
        node_key=np.zeros(cfg.max_nodes, np.int64),
        node_label=np.zeros(cfg.max_nodes, np.float32),
        edge_src=np.zeros(cfg.max_edges, np.int32),
        edge_dst=np.zeros(cfg.max_edges, np.int32),
        edge_feat=np.zeros((cfg.max_edges, EDGE_FEATURE_DIM), np.float32),
        edge_mask=np.zeros(cfg.max_edges, np.bool_),
        edge_label=np.zeros(cfg.max_edges, np.float32),
        window_start_ns=int(lo_ns),
        window_end_ns=int(hi_ns),
    )
    if len(idx) == 0:
        return g, stats

    ts = events.ts_ns[idx]
    pid = events.pid[idx].astype(np.int64)
    inode = events.inode[idx]
    syscall = events.syscall[idx]
    nbytes = events.bytes[idx].astype(np.float64)
    path_id = events.path_id[idx]
    new_path_id = events.new_path_id[idx]
    comm_id = events.comm_id[idx]
    ev_label = (
        labels[idx].astype(np.float32) if labels is not None else np.zeros(len(idx), np.float32)
    )

    # --- node universe: processes (tagged pid) + files (inode>0) -------------
    has_file = inode > 0
    proc_key = pid | _PROC_TAG
    file_key = inode.astype(np.int64)
    all_keys = np.concatenate([proc_key, file_key[has_file]])
    uniq_keys, ids_all = _first_appearance_unique(all_keys)
    n_nodes_total = len(uniq_keys)
    kept_nodes = min(n_nodes_total, cfg.max_nodes)
    stats.dropped_nodes = n_nodes_total - kept_nodes

    proc_node = ids_all[: len(idx)]
    file_node = np.full(len(idx), -1, np.int64)
    file_node[has_file] = ids_all[len(idx) :]

    # events touching a dropped (overflow) node are dropped whole
    ev_ok = (proc_node < kept_nodes) & (~has_file | (file_node < kept_nodes))
    stats.dropped_events = int((~ev_ok).sum())
    if stats.dropped_events:
        keep = np.nonzero(ev_ok)[0]
        (ts, pid, inode, syscall, nbytes, path_id, new_path_id, comm_id,
         ev_label, proc_node, file_node, has_file) = (
            a[keep] for a in (ts, pid, inode, syscall, nbytes, path_id,
                              new_path_id, comm_id, ev_label, proc_node,
                              file_node, has_file)
        )
    if len(ts) == 0:
        return g, stats

    node_is_proc = uniq_keys[:kept_nodes] >= _PROC_TAG
    g.node_mask[:kept_nodes] = True
    g.node_key[:kept_nodes] = np.where(
        node_is_proc, uniq_keys[:kept_nodes] & ~_PROC_TAG, uniq_keys[:kept_nodes]
    )
    g.node_type[:kept_nodes] = np.where(node_is_proc, NODE_TYPE_PROCESS, NODE_TYPE_FILE)
    stats.num_nodes = kept_nodes

    # --- per-node aggregates -------------------------------------------------
    nf = g.node_feat
    t_rel = ((ts - lo_ns) / window_ns).astype(np.float32)

    # event → "actor node" (process) and "object node" (file, may be -1)
    is_read = syscall == int(Syscall.READ)
    is_write = syscall == int(Syscall.WRITE)
    is_rename = syscall == int(Syscall.RENAME)
    is_unlink = syscall == int(Syscall.UNLINK)
    is_open = syscall == int(Syscall.OPENAT)
    other = ~(is_read | is_write | is_rename | is_unlink | is_open)

    def node_count(mask: np.ndarray, node: np.ndarray) -> np.ndarray:
        m = mask & (node >= 0)
        return np.bincount(node[m].astype(np.int64), minlength=kept_nodes).astype(np.float32)

    # file-node counters
    for slot, m in ((8, is_read), (9, is_write), (10, is_rename), (11, is_unlink),
                    (12, is_open), (13, other)):
        nf[:kept_nodes, slot] = np.log1p(node_count(m, file_node) + node_count(m, proc_node))

    def node_sum(values: np.ndarray, mask: np.ndarray, node: np.ndarray) -> np.ndarray:
        m = mask & (node >= 0)
        return np.bincount(
            node[m].astype(np.int64), weights=values[m], minlength=kept_nodes
        ).astype(np.float32)

    bytes_read = node_sum(nbytes, is_read, file_node) + node_sum(nbytes, is_read, proc_node)
    bytes_written = node_sum(nbytes, is_write, file_node) + node_sum(nbytes, is_write, proc_node)
    nf[:kept_nodes, 14] = np.log1p(bytes_read / 1024.0)
    nf[:kept_nodes, 15] = np.log1p(bytes_written / 1024.0)
    nf[:kept_nodes, 20] = bytes_written / (bytes_written + bytes_read + 1.0)

    # temporal span / gaps per node (over both roles)
    both_node = np.concatenate([proc_node, file_node])
    both_t = np.concatenate([t_rel, t_rel])
    ok = both_node >= 0
    first = np.full(kept_nodes, 2.0, np.float32)
    last = np.full(kept_nodes, -1.0, np.float32)
    np.minimum.at(first, both_node[ok].astype(np.int64), both_t[ok])
    np.maximum.at(last, both_node[ok].astype(np.int64), both_t[ok])
    cnt = np.bincount(both_node[ok].astype(np.int64), minlength=kept_nodes)
    span = np.where(cnt > 0, np.maximum(last - first, 0.0), 0.0).astype(np.float32)
    nf[:kept_nodes, 18] = span
    nf[:kept_nodes, 19] = span / np.maximum(cnt, 1)

    # path features: last path seen per file node
    feats_table = strings.features()
    file_ok = file_node >= 0
    nf_rows = file_node[file_ok].astype(np.int64)
    nf[:kept_nodes, 0:8][nf_rows] = feats_table[path_id[file_ok]]
    # renames: mark destination suspicious-extension on the file node too
    ren_ok = is_rename & file_ok
    if ren_ok.any():
        dst_feat = feats_table[new_path_id[ren_ok]]
        rows = file_node[ren_ok].astype(np.int64)
        np.maximum.at(nf[:kept_nodes, 0:8], rows, dst_feat)

    nf[:kept_nodes, 21] = node_is_proc.astype(np.float32)

    # identity buckets (node_aux): files → extension id of the latest path
    # seen (rename destination wins); processes → comm hash bucket.  Gives the
    # GNN the process-identity signal the Event schema carries in `comm`
    # (proto/trace.proto:14) without string features on device.
    aux = np.zeros(kept_nodes, np.int32)
    ext_ids = strings.extension_ids()
    last_pos = np.full(kept_nodes, -1, np.int64)
    fm_idx = np.nonzero(file_ok)[0]
    np.maximum.at(last_pos, file_node[fm_idx].astype(np.int64), fm_idx)
    file_rows = np.nonzero((last_pos >= 0) & ~node_is_proc)[0]
    if len(file_rows):
        lp = last_pos[file_rows]
        choice = np.where(
            is_rename[lp] & (new_path_id[lp] > 0), new_path_id[lp], path_id[lp]
        )
        aux[file_rows] = 1 + ext_ids[choice]
    first_pos = np.full(kept_nodes, len(ts), np.int64)
    np.minimum.at(first_pos, proc_node.astype(np.int64), np.arange(len(ts)))
    proc_rows = np.nonzero(node_is_proc & (first_pos < len(ts)))[0]
    if len(proc_rows):
        comms = [strings.lookup(int(comm_id[first_pos[r]])) for r in proc_rows]
        aux[proc_rows] = AUX_COMM_BASE + np.array(
            [_stable_hash(c) % AUX_COMM_BUCKETS for c in comms], np.int32
        )
    g.node_aux[:kept_nodes] = aux

    # node labels: any attack event touching the node
    node_lab = np.zeros(kept_nodes, np.float32)
    np.maximum.at(node_lab, proc_node.astype(np.int64), ev_label)
    fm = file_node >= 0
    np.maximum.at(node_lab, file_node[fm].astype(np.int64), ev_label[fm])
    g.node_label[:kept_nodes] = node_lab

    # --- edges: aggregated (process, file) pairs -----------------------------
    pair_ok = file_node >= 0
    pe = np.nonzero(pair_ok)[0]
    n_edges = 0
    if len(pe):
        pair_key = proc_node[pe] * np.int64(cfg.max_nodes + 1) + file_node[pe]
        uniq_pairs, pair_id = _first_appearance_unique(pair_key)
        n_pairs_total = len(uniq_pairs)
        kept_edges = min(n_pairs_total, cfg.max_edges)
        stats.dropped_edges = n_pairs_total - kept_edges
        e_ok = pair_id < kept_edges
        pe, pair_id = pe[e_ok], pair_id[e_ok]

        src = (uniq_pairs[:kept_edges] // (cfg.max_nodes + 1)).astype(np.int32)
        dst = (uniq_pairs[:kept_edges] % (cfg.max_nodes + 1)).astype(np.int32)

        ef = np.zeros((kept_edges, EDGE_FEATURE_DIM), np.float32)
        e_sys = syscall[pe]
        slot_of = np.full(int(Syscall.OTHER) + 1, 5, np.int64)
        for sc, slot in _SYSCALL_TO_EDGE_SLOT.items():
            slot_of[sc] = slot
        np.add.at(ef, (pair_id, slot_of[e_sys]), 1.0)
        ef[:, :6] = np.log1p(ef[:, :6])

        pair_bytes = np.bincount(pair_id, weights=nbytes[pe], minlength=kept_edges)
        ef[:, 6] = np.log1p(pair_bytes / 1024.0)
        pair_cnt = np.bincount(pair_id, minlength=kept_edges).astype(np.float32)
        ef[:, 7] = np.log1p(pair_cnt / (window_ns / _NS))
        e_first = np.full(kept_edges, 2.0, np.float32)
        e_last = np.full(kept_edges, -1.0, np.float32)
        np.minimum.at(e_first, pair_id, t_rel[pe])
        np.maximum.at(e_last, pair_id, t_rel[pe])
        e_span = np.maximum(e_last - e_first, 0.0)
        ef[:, 8] = e_span / np.maximum(pair_cnt, 1.0)
        ef[:, 9] = np.where(pair_cnt > 0, e_first, 0.0)
        ef[:, 10] = np.where(pair_cnt > 0, e_last, 0.0)
        susp = np.maximum(
            feats_table[path_id[pe], 4], feats_table[new_path_id[pe], 4]
        )
        np.maximum.at(ef[:, 11], pair_id, susp)
        ef[:, 12] = pair_cnt / max(len(ts), 1)

        e_lab = np.zeros(kept_edges, np.float32)
        np.maximum.at(e_lab, pair_id, ev_label[pe])

        # motif features on the FILE nodes, from per-pair syscall counts
        # (see layout slots 22/23): who renames vs who writes is pair-level
        # information the per-node counters above cannot express
        w_cnt = np.bincount(pair_id[is_write[pe]], minlength=kept_edges)
        r_cnt = np.bincount(pair_id[is_read[pe]], minlength=kept_edges)
        ren_cnt = np.bincount(pair_id[is_rename[pe]], minlength=kept_edges)
        ren_total = np.bincount(dst, weights=ren_cnt.astype(np.float64),
                                minlength=kept_nodes)
        ren_by_writer = np.bincount(
            dst, weights=(ren_cnt * (w_cnt > 0)).astype(np.float64),
            minlength=kept_nodes)
        nf[:kept_nodes, 22] = (
            ren_by_writer / np.maximum(ren_total, 1.0)).astype(np.float32)
        inplace = np.bincount(
            dst, weights=((w_cnt > 0) & (r_cnt > 0)).astype(np.float64),
            minlength=kept_nodes)
        nf[:kept_nodes, 23] = (inplace > 0).astype(np.float32)

        # sort by destination node for segment-reduction message passing
        order = np.argsort(dst, kind="stable")
        g.edge_src[:kept_edges] = src[order]
        g.edge_dst[:kept_edges] = dst[order]
        g.edge_feat[:kept_edges] = ef[order]
        g.edge_label[:kept_edges] = e_lab[order]
        g.edge_mask[:kept_edges] = True
        n_edges = kept_edges

    # degrees from the aggregated edge list
    if n_edges:
        in_deg = np.bincount(g.edge_dst[:n_edges], minlength=kept_nodes)
        out_deg = np.bincount(g.edge_src[:n_edges], minlength=kept_nodes)
        nf[:kept_nodes, 16] = np.log1p(in_deg.astype(np.float32))
        nf[:kept_nodes, 17] = np.log1p(out_deg.astype(np.float32))
    stats.num_edges = n_edges
    # padded edge slots must not corrupt segment reductions: point them at the
    # last node slot with zero features (masked in the model anyway)
    if n_edges < cfg.max_edges:
        g.edge_dst[n_edges:] = cfg.max_nodes - 1
        g.edge_src[n_edges:] = cfg.max_nodes - 1
    return g, stats


def snapshot_windows(
    t0_ns: int, t1_ns: int, cfg: GraphConfig
) -> Iterator[Tuple[int, int]]:
    """Sliding [lo, hi) windows covering [t0, t1]."""
    stride = int(cfg.stride_sec * _NS)
    window = int(cfg.window_sec * _NS)
    lo = t0_ns
    while lo < t1_ns:
        yield lo, lo + window
        lo += stride


def trace_snapshots(
    trace: Trace,
    cfg: GraphConfig,
    labels: Optional[np.ndarray] = None,
) -> List[Tuple[GraphBatch, WindowStats]]:
    """All sliding-window graphs for a trace (the GNN's training samples)."""
    ev = trace.events
    if ev.num_valid == 0:
        return []
    valid_ts = ev.ts_ns[ev.valid]
    out = []
    for lo, hi in snapshot_windows(int(valid_ts.min()), int(valid_ts.max()), cfg):
        out.append(build_window_graph(ev, trace.strings, lo, hi, cfg, labels=labels))
    return out
