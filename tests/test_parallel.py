"""Multi-chip sharding tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from nerrf_tpu.data import make_corpus
from nerrf_tpu.graph import GraphConfig
from nerrf_tpu.models import GraphSAGEConfig, JointConfig, LSTMConfig, NerrfNet
from nerrf_tpu.parallel import (
    MeshConfig,
    init_sharded_state,
    make_mesh,
    make_sharded_train_step,
    shard_batch,
)
from nerrf_tpu.parallel.mesh import param_sharding
from nerrf_tpu.train import TrainConfig, build_dataset
from nerrf_tpu.train.data import DatasetConfig


def _dataset():
    corpus = make_corpus(4, attack_fraction=0.5, base_seed=3, duration_sec=60.0,
                         num_target_files=4, benign_rate_hz=15.0)
    return build_dataset(corpus, DatasetConfig(
        graph=GraphConfig(window_sec=45.0, stride_sec=30.0, max_nodes=32, max_edges=64),
        seq_len=16, max_seqs=16,
    ))


def test_mesh_construction():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    mesh = make_mesh(MeshConfig(dp=-1, tp=2))
    assert mesh.shape == {"dp": 4, "tp": 2, "sp": 1}
    mesh = make_mesh(MeshConfig(dp=2, tp=2, sp=2))
    assert mesh.shape == {"dp": 2, "tp": 2, "sp": 2}
    with pytest.raises(ValueError):
        make_mesh(MeshConfig(dp=3, tp=3))


def test_param_sharding_rules():
    mesh = make_mesh(MeshConfig(dp=4, tp=2))
    model = NerrfNet(JointConfig(
        gnn=GraphSAGEConfig(hidden=128, num_layers=2),
        lstm=LSTMConfig(hidden=128, num_layers=1),
    ))
    ds = _dataset()
    one = {k: jnp.asarray(v[0]) for k, v in ds.arrays.items()}
    from nerrf_tpu.train.loop import model_inputs
    shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), *model_inputs(one))
    )["params"]
    shardings = param_sharding(mesh, shapes)
    flat = jax.tree_util.tree_flatten_with_path(shardings)[0]
    tp_sharded = [kp for kp, s in flat if s.spec == P(None, "tp")]
    replicated = [kp for kp, s in flat if s.spec == P()]
    assert len(tp_sharded) > 10  # big kernels + embeddings
    assert len(replicated) > 5   # biases, layernorms, small heads


@pytest.mark.slow
def test_sharded_train_step_runs_and_matches_semantics():
    """One dp×tp-sharded step on the virtual mesh: runs, loss finite, and the
    sharded loss matches the single-device loss for identical params/batch."""
    ds = _dataset()
    n = (len(ds) // 8) * 8 or 8
    idx = np.arange(n) % len(ds)
    batch_np = {k: v[idx] for k, v in ds.arrays.items()}

    cfg = TrainConfig(
        model=JointConfig(
            gnn=GraphSAGEConfig(hidden=32, num_layers=2, dropout=0.0),
            lstm=LSTMConfig(hidden=32, num_layers=1, dropout=0.0),
        ),
        batch_size=n, num_steps=2, learning_rate=1e-3, warmup_steps=1,
    )
    model = NerrfNet(cfg.model)
    mesh = make_mesh(MeshConfig(dp=4, tp=2))
    state = init_sharded_state(model, cfg, ds.arrays, mesh)
    step = make_sharded_train_step(model, cfg, mesh)
    batch = shard_batch(mesh, batch_np)

    # reference loss on one device with the same (gathered) params
    from nerrf_tpu.train.loop import make_loss_fn
    params_host = jax.device_get(state.params)
    loss_ref, _ = make_loss_fn(model, cfg)(
        jax.tree.map(jnp.asarray, params_host),
        {k: jnp.asarray(v) for k, v in batch_np.items()},
        jax.random.PRNGKey(1),  # dropout 0 → rng irrelevant
    )

    state2, loss, aux, rng2 = step(state, batch, jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))
    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=2e-2)
    # step 0 runs at lr=0 (warmup); take a second step so params actually move
    state2, loss2, _, _ = step(state2, batch, rng2)
    assert np.isfinite(float(loss2))
    # params actually updated
    delta = jax.tree_util.tree_reduce(
        lambda a, p: a + float(jnp.abs(p).sum()),
        jax.tree.map(lambda a, b: a - b, state2.params, jax.tree.map(jnp.asarray, params_host)),
        0.0,
    )
    assert delta > 0
