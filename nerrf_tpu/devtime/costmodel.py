"""Unified per-program cost model: FLOPs / bytes / HBM floor per program.

`program_costs()` resolves an analytic cost for every serve bucket
program (at the exact shapes `serve/service.warmup_batches` compiles —
the admission-reachable set) and for the flat train step.  The FLOP
numerator of record is the analytic jaxpr count
(`nerrf_tpu.bench.flops.analytic_flops`): XLA's
``lower().compile().cost_analysis()`` costs matmuls at their MXU-padded
shapes and double-counts fused producers (~3x high at flagship shapes —
the 195%-MFU lesson documented in `bench/mfu.py`), so it is recorded
here strictly as a cross-check, never the authority.

Bytes are an analytic floor, not a measurement: params + inputs read
once, outputs written once.  Intermediates and re-reads are invisible to
a shape-level trace, so the derived arithmetic intensity is an UPPER
bound — honest for "is this program near the roofline ridge" reading
(a program whose ceiling intensity is below the ridge is definitely
bandwidth-bound).

Everything here traces shapes only (``jax.make_jaxpr``/``eval_shape``):
no device execution, no compile — safe to run at service boot without
touching the zero-recompile contract.  The one exception is the opt-in
``cross_check=True``, which pays one real compile per program.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np

from nerrf_tpu.bench.flops import analytic_flops


@dataclasses.dataclass(frozen=True)
class ProgramCost:
    """One program's analytic cost at one call signature."""

    program: str                 # "serve_eval[<bucket>]" / "train_step"
    flops: float                 # analytic matmul/conv FLOPs per call
    bytes_accessed: float        # analytic floor: params+inputs+outputs
    peak_hbm_bytes: float        # residency floor: params+inputs+outputs
    batch_slots: Optional[int] = None   # padded windows per call (serve)
    # the XLA cost_analysis cross-check (None unless cross_check=True
    # succeeded) — recorded, never the MFU numerator
    xla_flops: Optional[float] = None
    xla_bytes: Optional[float] = None

    @property
    def intensity_flops_per_byte(self) -> Optional[float]:
        """Ceiling arithmetic intensity (analytic flops over the byte
        floor) — compare against `ChipPeaks.ridge_flops_per_byte`."""
        if self.bytes_accessed <= 0:
            return None
        return self.flops / self.bytes_accessed

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        i = self.intensity_flops_per_byte
        d["intensity_flops_per_byte"] = round(i, 2) if i else None
        return d


def _tree_bytes(tree) -> float:
    import jax

    total = 0.0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += float(np.prod(shape, dtype=np.float64)
                       * np.dtype(dtype).itemsize)
    return total


def xla_cost(fn, *args) -> tuple:
    """``(flops, bytes accessed)`` from one real compile's cost analysis —
    the recorded cross-check.  ``(None, None)`` when the backend/jit
    cannot produce it (plain callables, failed lowering): the cross-check
    is optional evidence, never a reason to fail the cost model."""
    try:
        compiled = fn.lower(*args).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax returns [dict]
            cost = cost[0]
        flops = float(cost.get("flops", 0.0)) or None
        byts = float(cost.get("bytes accessed", 0.0)) or None
        return flops, byts
    except Exception:  # noqa: BLE001 — cross-check is best-effort
        return None, None


def program_cost(fn, *args, program: str, batch_slots: Optional[int] = None,
                 cross_check: bool = False) -> Optional[ProgramCost]:
    """Cost one call of ``fn`` at these arg shapes (shape-level trace).
    Returns None when the analytic counter cannot see the program (trace
    failure, zero matmuls) — null, never a fabricated number."""
    import jax

    flops = analytic_flops(fn, *args)
    if not flops:
        return None
    in_bytes = _tree_bytes(args)
    try:
        out_bytes = _tree_bytes(jax.eval_shape(fn, *args))
    except Exception:  # noqa: BLE001 — outputs are part of the floor only
        out_bytes = 0.0
    xf, xb = xla_cost(fn, *args) if cross_check else (None, None)
    return ProgramCost(
        program=program, flops=float(flops),
        bytes_accessed=in_bytes + out_bytes,
        peak_hbm_bytes=in_bytes + out_bytes,
        batch_slots=batch_slots, xla_flops=xf, xla_bytes=xb)


def serve_program_costs(eval_fn, params, cfg,
                        cross_check: bool = False) -> Dict[str, ProgramCost]:
    """``bucket tag → ProgramCost`` for every warmup-compiled serve
    program, at the exact shape-donor batches `warmup_batches` yields —
    the same shapes admission can ever produce (the deep static pass
    proves that closure; tests/test_devtime.py pins this function to it
    and to `train/data.sample_spec`)."""
    from nerrf_tpu.serve.service import warmup_batches

    out: Dict[str, ProgramCost] = {}
    for _bucket, tag, batch in warmup_batches(cfg):
        cost = program_cost(
            eval_fn, params, batch, program=f"serve_eval[{tag}]",
            batch_slots=int(next(iter(batch.values())).shape[0]),
            cross_check=cross_check)
        if cost is not None:
            out[tag] = cost
    return out


def train_step_cost(model, train_cfg, arrays,
                    cross_check: bool = False) -> Optional[ProgramCost]:
    """Analytic cost of ONE flat train step at these dataset shapes.

    Costs a fresh `make_train_step` program (the canonical grad/update
    body every flavor shares) with shape-only state/batch/rng — the live
    loop's step may be a cached executable or a resident closure, neither
    of which re-traces; the cost is identical because the body is."""
    import jax

    from nerrf_tpu.train.loop import init_state, make_train_step

    try:
        n = int(next(iter(arrays.values())).shape[0])
        b = min(train_cfg.batch_size, n)
        batch = {k: jax.ShapeDtypeStruct((b,) + tuple(v.shape[1:]),
                                         np.asarray(v).dtype)
                 for k, v in arrays.items()}
        rng = jax.eval_shape(lambda s: jax.random.PRNGKey(s),
                             jax.ShapeDtypeStruct((), np.uint32))
        # init under eval_shape: param/opt-state SHAPES only — no real
        # initialization runs, so costing a step is boot-cheap
        state = jax.eval_shape(
            lambda r: init_state(model, train_cfg, arrays, r), rng)
        step = make_train_step(model, train_cfg)
        return program_cost(step, state, batch, rng, program="train_step",
                            batch_slots=b, cross_check=cross_check)
    except Exception:  # noqa: BLE001 — a cost model must degrade to null
        return None


def program_costs(eval_fn, params, serve_cfg, model=None, train_cfg=None,
                  arrays=None, cross_check: bool = False
                  ) -> Dict[str, ProgramCost]:
    """The unified cost surface: ``program name → ProgramCost`` for every
    serve bucket program plus (when the training pieces are given) the
    flat train step.  This is the measured cost table a future
    ``nerrf tune`` fits its routing/ladder model over."""
    out = {c.program: c for c in serve_program_costs(
        eval_fn, params, serve_cfg, cross_check=cross_check).values()}
    if model is not None and train_cfg is not None and arrays is not None:
        tc = train_step_cost(model, train_cfg, arrays,
                             cross_check=cross_check)
        if tc is not None:
            out[tc.program] = tc
    return out
