#!/usr/bin/env python3
"""M0/M1-style recovery benchmark, retargeted at this framework.

The reference's benchmark (`/root/reference/benchmarks/m1/scripts/`) measured
a kubectl-exec rename-back loop (44 ms / 45 files / 2,500 MB/s,
`m1_recovery_results.json`) — possible only because its simulator left
plaintext behind the ransom extension.  This harness measures the honest
pipeline end-to-end on real destroyed data:

  seed + snapshot → XOR-encrypt attack → detect → MCTS plan → sandbox gate →
  verified restore,

and emits the reference's metrics schema (recovery duration, files/s, MB/s)
plus the product KPIs (`threat-model.mdx:275-319`): MTTR, data loss,
false-positive undo rate.

Usage: python benchmarks/run_recovery_bench.py [--scale m0|m1] [--out FILE]
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", choices=["m0", "m1"], default="m1")
    ap.add_argument("--out", default=None)
    ap.add_argument("--simulations", type=int, default=800)
    ap.add_argument("--planner", choices=("auto", "host", "device"),
                    default="auto")
    args = ap.parse_args()

    from nerrf_tpu.utils import enable_compilation_cache, ensure_backend_or_cpu

    enable_compilation_cache()
    # bounded reachability check BEFORE the first in-process jax op
    # (ValueNet.create would otherwise block forever on a wedged tunnel)
    ensure_backend_or_cpu("bench", timeout_sec=150.0)
    from nerrf_tpu.pipeline import build_undo_domain, heuristic_detect
    from nerrf_tpu.planner import MCTSConfig, make_planner
    from nerrf_tpu.planner.value_net import ValueNet
    from nerrf_tpu.rollback import (
        FileSimConfig,
        RollbackExecutor,
        SandboxGate,
        SnapshotStore,
        run_file_attack,
    )
    from nerrf_tpu.rollback.filesim import seed_files

    log = lambda *a: print(*a, file=sys.stderr, flush=True)
    # M0: 25 files ~12 MB total; M1: 45 files ~110 MB total (reference
    # metadata.json values)
    cfg = (
        FileSimConfig(num_files=25, min_file_bytes=300_000, max_file_bytes=700_000)
        if args.scale == "m0"
        else FileSimConfig(num_files=45, min_file_bytes=2_000_000, max_file_bytes=5_000_000)
    )

    tmp = Path(tempfile.mkdtemp(prefix=f"nerrf-bench-{args.scale}-"))
    victim = tmp / "victim"
    try:
        seed_files(victim, cfg)
        store = SnapshotStore(tmp / "store")
        manifest = store.snapshot(victim, "pre-attack")
        total_bytes = sum(sz for _, sz, _ in manifest.files.values())
        log(f"[{args.scale}] seeded {len(manifest.files)} files "
            f"({total_bytes / 1e6:.1f} MB), snapshot taken")

        # Daemon-boot warmup, OUTSIDE the recovery window: a deployed nerrf
        # daemon compiles the bucketed device-search executable and the
        # value-net architecture once at startup (planner/device_mcts.py
        # program cache), so an incident plans against a warm program.  The
        # attack hasn't happened yet — nothing incident-specific leaks in.
        value = ValueNet.create()
        planner_cfg = MCTSConfig(num_simulations=args.simulations)
        planner_kind = args.planner
        if planner_kind != "host":
            # auto now means the device program on every backend (see
            # make_planner: 4.2× the host search even on CPU), so the
            # daemon-boot warmup runs for every non-host request — but a
            # failed warmup must not sink the bench when auto can still
            # fall back to the host search (explicit --planner device
            # keeps the hard failure: the operator asked for that program).
            # On failure, pin auto to host HERE: letting make_planner retry
            # the identical build inside the measured window would charge
            # the same compile failure to the artifact's plan time.
            from nerrf_tpu.planner.device_mcts import DeviceMCTS

            t_warm = time.perf_counter()
            try:
                DeviceMCTS.warmup_for(
                    1, 1, cfg=planner_cfg, value_apply=value.apply_fn,
                    value_params=value.params)
                log(f"[{args.scale}] device planner warm "
                    f"({time.perf_counter() - t_warm:.1f}s boot-time compile)")
            except Exception as e:  # noqa: BLE001
                if planner_kind == "device":
                    raise
                log(f"[{args.scale}] device planner warmup failed "
                    f"({type(e).__name__}: {e}); using the host search")
                planner_kind = "host"

        t_attack = time.perf_counter()
        trace, encrypted = run_file_attack(victim, cfg)
        attack_s = time.perf_counter() - t_attack
        log(f"[{args.scale}] attack: {len(encrypted)} files encrypted in {attack_s:.2f}s")

        # --- the measured recovery window (detect → plan → gate → execute) --
        t0 = time.perf_counter()
        detection = heuristic_detect(trace)
        t_detect = time.perf_counter() - t0

        domain = build_undo_domain(detection, manifest, root=str(victim))
        value.fit_to_domain(domain, num_rollouts=256, horizon=32, steps=200)
        planner = make_planner(domain, value, planner_cfg, kind=planner_kind)
        planner_kind = type(planner).__name__
        plan = planner.plan()
        t_plan = time.perf_counter() - t0 - t_detect

        gate = SandboxGate(store, manifest).rehearse(plan, victim, trace=trace)
        if not gate.approved:
            log(f"GATE REJECTED: {gate.reason}")
            return 3
        t_gate = time.perf_counter() - t0 - t_detect - t_plan

        ex = RollbackExecutor(store, manifest, victim)
        report = ex.execute(plan)
        mttr = time.perf_counter() - t0

        # --- KPIs ------------------------------------------------------------
        residual = store.diff(manifest, victim)
        data_loss_b = sum(
            manifest.files[k][1] for k, v in residual.items()
            if v in ("missing", "modified") and k in manifest.files
        )
        # false-positive undos: restored files that the attack never touched
        attacked_names = {e.name[: -len(cfg.ransom_ext)] for e in encrypted}
        fp_reverted = sum(
            1 for d in report.details
            if d["result"] == "restored" and Path(d["target"]).name not in attacked_names
        )
        clean_total = max(len(manifest.files) - len(encrypted), 0)
        fp_rate = fp_reverted / clean_total if clean_total else 0.0
        import jax

        result = {
            "scale": args.scale,
            # provenance: CPU-fallback artifacts must be distinguishable
            # from chip artifacts at the schema level, not just in prose
            "backend": jax.default_backend(),
            "attack": {
                "files": len(encrypted),
                "total_bytes": total_bytes,
                "duration_seconds": round(attack_s, 3),
            },
            "recovery": {
                "recovery_duration_ms": round(report.duration_seconds * 1000, 1),
                "files_recovered": report.files_restored,
                "files_per_second": round(report.files_per_sec, 1),
                "throughput_mbps": round(report.mb_per_sec, 1),
                "verified": report.verified,
            },
            "kpis": {
                "mttr_seconds": round(mttr, 2),
                "mttr_target_seconds": 3600,
                "data_loss_bytes": data_loss_b,
                "data_loss_target_bytes": 128 * 1024 * 1024,
                "false_positive_undos": fp_reverted,
                "false_positive_undo_rate": round(fp_rate, 4),
                "false_positive_rate_target": 0.05,
                "detect_seconds": round(t_detect, 3),
                "plan_seconds": round(t_plan, 3),
                "gate_seconds": round(t_gate, 3),
                "rollouts_per_sec": round(plan.rollouts_per_sec, 1),
                "planner": f"{args.planner}:{planner_kind}",
            },
            "reference_m1_recovery": {
                "note": "reference rename-back loop on intact plaintext "
                        "(benchmarks/m1/results/m1_recovery_results.json)",
                "recovery_duration_ms": 44,
                "files_per_second": 1022.72,
                "throughput_mbps": 2500,
            },
        }
        out = json.dumps(result, indent=2)
        if args.out:
            Path(args.out).write_text(out)
        print(out)
        ok = (
            report.verified
            and mttr < 3600
            and data_loss_b <= 128 * 1024 * 1024
        )
        return 0 if ok else 4
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
