"""Tracing spine: spans, Chrome export, dual-write, train-loop attribution."""

import json
import threading
import time

import pytest

from nerrf_tpu import tracing
from nerrf_tpu.observability import MetricsRegistry


def test_span_records_and_dual_writes():
    reg = MetricsRegistry(namespace="t")
    tr = tracing.Tracer(registry=reg)
    with tr.span("device_step", step=3) as sp:
        time.sleep(0.002)
        sp.args["dispatch_s"] = 0.001
    recs = tr.records()
    assert len(recs) == 1 and recs[0].name == "device_step"
    assert recs[0].dur >= 0.002
    assert recs[0].args == {"step": 3, "dispatch_s": 0.001}
    # dual-write: the same span landed in the per-stage histogram, so
    # Prometheus and the trace agree from one instrumentation point
    assert reg.value(tracing.STAGE_HISTOGRAM,
                     labels={"stage": "device_step"}, stat="count") == 1
    assert reg.value(tracing.STAGE_HISTOGRAM,
                     labels={"stage": "device_step"}, stat="sum") >= 0.002
    text = reg.render()
    assert "# TYPE t_stage_latency_seconds histogram" in text
    assert 'stage="device_step"' in text


def test_chrome_trace_export_round_trips(tmp_path):
    tr = tracing.Tracer(registry=MetricsRegistry())
    with tr.span("graph_lower", events=10):
        with tr.span("inner"):
            pass
    path = tr.write(tmp_path / "trace.json")
    data = json.loads((tmp_path / "trace.json").read_text())
    xs = [e for e in data["traceEvents"] if e.get("ph") == "X"]
    assert {e["name"] for e in xs} == {"graph_lower", "inner"}
    assert all("ts" in e and "dur" in e and "tid" in e for e in xs)
    # thread metadata present so Perfetto names the rows
    assert any(e.get("name") == "thread_name" for e in data["traceEvents"])

    events = tracing.load_chrome_trace(path)
    summary = tracing.stage_summary(events)
    assert summary["graph_lower"]["count"] == 1
    table = tracing.format_stage_table(events)
    assert "graph_lower" in table and "%wall" in table


def test_tracer_thread_safety():
    reg = MetricsRegistry()
    tr = tracing.Tracer(registry=reg)

    def worker(i):
        for _ in range(200):
            with tr.span(f"stage_{i}"):
                pass

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(tr.records()) == 800
    for i in range(4):
        assert reg.value(tracing.STAGE_HISTOGRAM,
                         labels={"stage": f"stage_{i}"}, stat="count") == 200


def test_coverage_is_an_interval_union():
    events = [
        {"name": "a", "ph": "X", "ts": 0.0, "dur": 50.0},
        {"name": "b", "ph": "X", "ts": 25.0, "dur": 50.0},  # overlaps a
        {"name": "c", "ph": "X", "ts": 90.0, "dur": 10.0},
    ]
    assert tracing.wall_clock_us(events) == 100.0
    # union [0,75] ∪ [90,100] = 85 of 100 — overlap counted once
    assert tracing.coverage(events) == pytest.approx(0.85)
    assert tracing.coverage([]) == 0.0


def test_ring_buffer_is_bounded():
    tr = tracing.Tracer(capacity=16, registry=MetricsRegistry())
    for i in range(64):
        with tr.span("s", i=i):
            pass
    recs = tr.records()
    assert len(recs) == 16
    assert recs[-1].args["i"] == 63  # newest kept


def test_ring_wraparound_keeps_exact_tail_in_order():
    """Tail-after-wrap semantics the flight recorder depends on: after the
    ring wraps, records()/chrome_trace() hold EXACTLY the newest
    ``capacity`` spans, in recording order, with timestamps intact."""
    reg = MetricsRegistry()
    tr = tracing.Tracer(capacity=8, registry=reg)
    for i in range(27):
        with tr.span("s", i=i):
            pass
    recs = tr.records()
    assert [r.args["i"] for r in recs] == list(range(19, 27))
    # timestamps stay monotone across the wrap (no epoch reset)
    t0s = [r.t0 for r in recs]
    assert t0s == sorted(t0s)
    xs = [e for e in tr.chrome_trace()["traceEvents"] if e.get("ph") == "X"]
    assert [e["args"]["i"] for e in xs] == list(range(19, 27))
    assert tracing.stage_summary(xs)["s"]["count"] == 8
    # the dual-written histogram is CUMULATIVE (it never wraps): the span
    # count diverges from the ring length by design, all 27 recorded
    assert reg.value(tracing.STAGE_HISTOGRAM, labels={"stage": "s"},
                     stat="count") == 27


def test_wrapped_export_extent_starts_at_the_tail():
    """After a wrap the exported trace's extent must begin at the OLDEST
    *kept* span — evicted spans must not stretch wall_clock_us or dilute
    coverage (the doctor's attribution tables read the export verbatim)."""
    tr = tracing.Tracer(capacity=4, registry=MetricsRegistry())
    for i in range(12):
        with tr.span("s", i=i):
            time.sleep(0.001)
    recs = tr.records()
    xs = [e for e in tr.chrome_trace()["traceEvents"] if e.get("ph") == "X"]
    lo = min(e["ts"] for e in xs)
    assert lo == pytest.approx(recs[0].t0 * 1e6, rel=1e-6)
    assert lo > 0  # strictly after tracer epoch: the head was evicted
    assert tracing.wall_clock_us(xs) < 12 * 50_000  # tail extent, not 12 spans
    # sequential non-overlapping spans: the union over the tail's own
    # extent is dominated by the spans themselves
    assert tracing.coverage(xs) > 0.5


def test_coverage_clamps_spans_to_the_requested_interval():
    """coverage(lo, hi) on a wrapped-style buffer: spans straddling or
    outside [lo, hi] contribute only their clamped overlap — the exact
    semantics the recorder's tail-window attribution relies on."""
    events = [
        {"name": "evicted", "ph": "X", "ts": 0.0, "dur": 40.0},
        {"name": "kept", "ph": "X", "ts": 30.0, "dur": 30.0},   # straddles lo
        {"name": "kept", "ph": "X", "ts": 70.0, "dur": 20.0},
        {"name": "kept", "ph": "X", "ts": 95.0, "dur": 20.0},   # straddles hi
    ]
    # window [50, 100]: [50,60] ∪ [70,90] ∪ [95,100] = 35 of 50
    assert tracing.coverage(events, lo_us=50.0, hi_us=100.0) \
        == pytest.approx(0.7)
    # a window entirely past every span covers nothing; degenerate → 0
    assert tracing.coverage(events, lo_us=200.0, hi_us=300.0) == 0.0
    assert tracing.coverage(events, lo_us=100.0, hi_us=100.0) == 0.0
    # explicit lo only: hi defaults to the spans' own max end (115), so
    # the window is [90, 115] and only the last span's [95, 115] counts
    assert tracing.coverage(events, lo_us=90.0) \
        == pytest.approx(20.0 / 25.0)


def test_train_loop_emits_covering_trace(tmp_path):
    """Acceptance: a 20-step synthetic-corpus run emits a Chrome trace whose
    spans cover ≥95% of the run's wall-clock, and the registry carries the
    stage histograms plus the attribution gauges."""
    from nerrf_tpu.data import make_corpus
    from nerrf_tpu.graph import GraphConfig
    from nerrf_tpu.models import JointConfig
    from nerrf_tpu.observability import DEFAULT_REGISTRY
    from nerrf_tpu.tracing import DEFAULT_TRACER
    from nerrf_tpu.train import TrainConfig, build_dataset
    from nerrf_tpu.train.data import DatasetConfig
    from nerrf_tpu.train.loop import train_nerrfnet

    corpus = make_corpus(2, attack_fraction=0.5, base_seed=5,
                         duration_sec=60.0, num_target_files=4,
                         benign_rate_hz=10.0)
    ds = build_dataset(corpus, DatasetConfig(
        graph=GraphConfig(window_sec=45.0, stride_sec=25.0,
                          max_nodes=64, max_edges=128),
        seq_len=16, max_seqs=16))
    DEFAULT_TRACER.clear()
    was_enabled = DEFAULT_TRACER.enabled
    DEFAULT_TRACER.enabled = True
    try:
        res = train_nerrfnet(ds, None, TrainConfig(
            model=JointConfig().small, batch_size=4, num_steps=20,
            eval_every=10, warmup_steps=2))
    finally:
        DEFAULT_TRACER.enabled = was_enabled
    assert res.steps_per_sec > 0

    path = DEFAULT_TRACER.write(tmp_path / "train_trace.json")
    events = tracing.load_chrome_trace(path)
    names = {e["name"] for e in events}
    assert {"train_setup", "train_loop", "device_step", "eval"} <= names
    assert sum(1 for e in events if e["name"] == "device_step") == 20
    assert tracing.coverage(events) >= 0.95, tracing.format_stage_table(events)
    # non-vacuous attribution: the per-step LEAF spans alone must cover the
    # train_loop interval — the enclosing wrapper spans cannot satisfy this,
    # so silently dropping the per-step instrumentation fails here
    loop = next(e for e in events if e["name"] == "train_loop")
    leaves = [e for e in events if e["name"] in ("device_step", "data_wait")]
    leaf_cov = tracing.coverage(
        leaves, lo_us=loop["ts"], hi_us=loop["ts"] + loop["dur"])
    assert leaf_cov >= 0.9, tracing.format_stage_table(events)

    text = DEFAULT_REGISTRY.render()
    for stage in ("device_step", "eval", "train_loop", "graph_lower"):
        assert f'stage="{stage}"' in text, stage
    assert "nerrf_train_host_blocked_fraction" in text
    assert "nerrf_train_data_wait_fraction" in text
    assert 'nerrf_train_padding_waste_fraction{bucket="64n/128e",kind="node"}' \
        in text
    # the synced device_step spans carry the dispatch split the
    # host-blocked fraction is derived from
    steps = [e for e in events if e["name"] == "device_step"]
    assert all("dispatch_s" in e.get("args", {}) for e in steps)


def test_cli_trace_subcommand(tmp_path, capsys):
    from nerrf_tpu.cli import main

    tr = tracing.Tracer(registry=MetricsRegistry())
    with tr.span("ingest_decode", events=64):
        time.sleep(0.001)
    path = tr.write(tmp_path / "t.json")
    assert main(["trace", "--file", str(path)]) == 0
    out = capsys.readouterr().out
    assert "ingest_decode" in out and "coverage" in out
    # missing / corrupt files fail politely, not with a traceback
    assert main(["trace", "--file", str(tmp_path / "absent.json")]) == 2
    (tmp_path / "empty.json").write_text('{"traceEvents": []}')
    assert main(["trace", "--file", str(tmp_path / "empty.json")]) == 1
    # well-formed JSON that is not a trace: no spans, not a traceback
    (tmp_path / "scalar.json").write_text("3")
    assert main(["trace", "--file", str(tmp_path / "scalar.json")]) == 1
    (tmp_path / "strings.json").write_text('["a", "b"]')
    assert main(["trace", "--file", str(tmp_path / "strings.json")]) == 1
    (tmp_path / "bin.trace").write_bytes(bytes(range(256)))  # not UTF-8
    assert main(["trace", "--file", str(tmp_path / "bin.trace")]) == 2
