"""Online detection service: cross-stream micro-batching, backpressure,
isolation, and bit-parity with the offline model_detect path.

The batching/backpressure tests run with a FAKE score function (the
micro-batcher is model-free by design), so the scheduling logic is covered
without compiling anything; one test at the end compiles the real small
model and asserts the bit-parity acceptance criterion.
"""

import dataclasses
import threading
import time

import numpy as np
import pytest

from nerrf_tpu.data.loaders import Trace
from nerrf_tpu.data.synth import SimConfig, simulate_trace
from nerrf_tpu.observability import MetricsRegistry
from nerrf_tpu.serve import (
    MicroBatcher,
    OnlineDetectionService,
    ServeConfig,
    StreamWindower,
    WindowRequest,
    select_bucket,
)

BUCKET_A = (128, 256, 32)
BUCKET_B = (256, 512, 64)


def _blocks(trace, size=200):
    ev = trace.events
    for i in range(0, len(ev), size):
        yield type(ev)(**{f.name: getattr(ev, f.name)[i:i + size]
                          for f in dataclasses.fields(ev)})


def _sim(seed=3, duration=60.0, attack=True, files=6, rate=6.0):
    return simulate_trace(SimConfig(duration_sec=duration, attack=attack,
                                    attack_start_sec=duration / 3,
                                    num_target_files=files,
                                    benign_rate_hz=rate, seed=seed))


def _fake_service(cfg, registry=None, score=None, start=True):
    """A service whose device program is a stub: covers windowing,
    admission, packing and demux without any compile.  The private-state
    skeleton lives in conftest.make_service_shell (one copy, shared with
    test_registry/test_chaos); this wires the stub batcher onto it."""
    from conftest import make_service_shell

    svc, registry = make_service_shell(cfg, registry=registry)
    score = score or (lambda batch:
                      np.full(batch["node_mask"].shape, 0.9, np.float64))
    svc._batcher = MicroBatcher(score_fn=score, cfg=cfg, registry=registry,
                                on_scored=svc._on_scored,
                                on_failed=svc._on_failed,
                                journal=svc._journal)
    for b in cfg.buckets:
        svc._batcher.mark_warm(b)
    if start:
        svc._batcher.start()
        svc._admission_open = True
    return svc, registry


# -- bucket selection ---------------------------------------------------------

def test_select_bucket_first_fit_and_soft_seq_overflow():
    ladder = (BUCKET_A, BUCKET_B, (1024, 2048, 128))
    assert select_bucket(100, 200, 10, ladder) == BUCKET_A
    assert select_bucket(200, 200, 10, ladder) == BUCKET_B
    # sequence overflow is soft: stay on the smallest graph-fitting rung
    # (padding is compute) and truncate to the densest max_seqs, exactly
    # like the offline path at a fixed DatasetConfig
    assert select_bucket(100, 200, 500, ladder) == BUCKET_A
    # ...but within that rung, the bucket with the most seq slots wins
    assert select_bucket(
        200, 200, 500,
        ((256, 512, 64), (256, 512, 128), (1024, 2048, 256))) \
        == (256, 512, 128)
    assert select_bucket(999, 1000, 10, ladder) == (1024, 2048, 128)
    # node/edge overflow is hard: nothing fits → None (reject, never drop
    # events silently)
    assert select_bucket(5000, 10, 10, ladder) is None


# -- windower: streaming == offline boundaries --------------------------------

def test_windower_matches_snapshot_windows():
    from nerrf_tpu.graph import GraphConfig
    from nerrf_tpu.graph.builder import snapshot_windows

    tr = _sim(seed=11, duration=80.0)
    w = StreamWindower(window_sec=15.0, stride_sec=5.0)
    closed = []
    for block in _blocks(tr, size=137):
        closed += w.feed(block, tr.strings)
    closed += w.flush()
    ts = tr.events.ts_ns[tr.events.valid]
    expect = list(snapshot_windows(
        int(ts.min()), int(ts.max()),
        GraphConfig(window_sec=15.0, stride_sec=5.0)))
    assert [(lo, hi) for _, lo, hi in closed] == expect
    assert [i for i, _, _ in closed] == list(range(len(expect)))
    assert w.late_events == 0
    # the accumulated trace is the whole stream
    assert w.events.num_valid == tr.events.num_valid


def test_windower_window_view_slices_ordered_streams():
    """Admission lowers from an O(log n) slice on in-order streams; the
    slice selects exactly the window's events.  Out-of-order delivery
    falls back to the full array (correct, just slower)."""
    tr = _sim(seed=31, duration=60.0)
    w = StreamWindower(window_sec=15.0, stride_sec=5.0)
    closed = []
    for block in _blocks(tr, size=100):
        closed += w.feed(block, tr.strings)
    closed += w.flush()
    assert closed
    _, lo, hi = closed[len(closed) // 2]
    view = w.window_view(lo, hi)
    full = w.events
    in_window = full.valid & (full.ts_ns >= lo) & (full.ts_ns < hi)
    assert len(view) == int(in_window.sum())
    assert (view.ts_ns == full.ts_ns[in_window]).all()

    # out-of-order feed → fallback to the whole array
    w2 = StreamWindower(window_sec=15.0, stride_sec=5.0)
    blocks = list(_blocks(tr, size=150))
    w2.feed(blocks[1], tr.strings)
    w2.feed(blocks[0], tr.strings)  # older events after newer: late
    assert w2.late_events > 0
    assert len(w2.window_view(lo, hi)) == len(w2.events)


def test_admission_closed_after_stop_drops_counted():
    """stop() hard-closes admission: a still-attached stream's windows are
    dropped with a distinct reason instead of queueing into the stopped
    batcher and wedging leave() for its full timeout."""
    cfg = ServeConfig(buckets=(BUCKET_B,), batch_size=4,
                      batch_close_sec=0.02, window_sec=10.0, stride_sec=5.0)
    svc, reg = _fake_service(cfg)
    svc.join("s0")
    tr = _sim(seed=37, duration=60.0, files=4, rate=6.0)
    blocks = list(_blocks(tr, size=250))
    svc.feed("s0", blocks[0], tr.strings)
    svc.stop(drain=True)
    for b in blocks[1:]:
        svc.feed("s0", b, tr.strings)  # post-stop: drop, don't queue
    assert reg.value("serve_admission_dropped_total",
                     labels={"reason": "closed"}) > 0
    t0 = time.perf_counter()
    det = svc.leave("s0", timeout=30.0)  # must NOT wait the 30 s
    assert time.perf_counter() - t0 < 5.0
    assert det.detector == "serve[max]"


def test_connect_duplicate_id_join_failure_leaves_live_stream_alone():
    """A second actor connecting under an id that is already joined must
    record the join error on ITS run and never tear down the live stream
    it lost the name race to (the drain only leaves streams it joined)."""
    from nerrf_tpu.ingest.service import TraceReplayServer

    cfg = ServeConfig(buckets=(BUCKET_B,), batch_size=4,
                      batch_close_sec=0.02, window_sec=10.0, stride_sec=5.0)
    svc, reg = _fake_service(cfg)
    tr = _sim(seed=43, duration=40.0, files=3, rate=5.0)
    server = TraceReplayServer(tr.events, tr.strings, batch_size=256)
    port = server.start()
    try:
        svc.join("s0")  # the live stream another actor owns
        svc.feed("s0", next(_blocks(tr, size=250)), tr.strings)
        run = svc.connect("s0", f"127.0.0.1:{port}", timeout=10.0)
        assert run.done.wait(timeout=10.0)
        assert isinstance(run.error, ValueError)  # "already joined"
        assert run.result is None
        # the live stream survived and still works end to end
        assert "s0" in svc._streams
        for b in _blocks(tr, size=250):
            svc.feed("s0", b, tr.strings)
        det = svc.leave("s0", timeout=10.0)
        assert det.detector == "serve[max]"
    finally:
        server.stop()
        svc.stop(drain=False)


def test_connect_drain_sets_done_even_when_leave_raises():
    """The error path's cleanup leave() failing (scorer wedged, timeout,
    anything) must still set run.done — a caller waiting on the drain can
    never hang on a doubly-failed stream."""
    cfg = ServeConfig(buckets=(BUCKET_B,), batch_size=4,
                      batch_close_sec=0.02, window_sec=10.0, stride_sec=5.0)
    svc, reg = _fake_service(cfg)

    def exploding_leave(sid, flush=True, timeout=60.0):
        raise RuntimeError("leave timed out / wedged")

    svc.leave = exploding_leave
    try:
        # unroutable target: iter_blocks raises after join succeeded, the
        # drain's cleanup leave() then raises too
        run = svc.connect("s0", "127.0.0.1:1", timeout=2.0)
        assert run.done.wait(timeout=30.0)
        assert run.error is not None
        assert run.result is None
    finally:
        svc.stop(drain=False)


def test_stop_during_backoff_keeps_clean_sessions_error_free():
    """stop() landing inside the reconnect backoff window must end the
    drain WITHOUT one more join() attempt — the RuntimeError a closed
    service raises would overwrite run.error on a stream whose last
    session finalized cleanly."""
    from nerrf_tpu.ingest.service import TraceReplayServer

    cfg = ServeConfig(buckets=(BUCKET_B,), batch_size=4,
                      batch_close_sec=0.02, window_sec=10.0, stride_sec=5.0)
    svc, reg = _fake_service(cfg)
    tr = _sim(seed=47, duration=40.0, files=3, rate=5.0)
    server = TraceReplayServer(tr.events, tr.strings, batch_size=256)
    port = server.start()
    try:
        # long base backoff: the actor is overwhelmingly likely to be
        # inside the sleep when the stop lands
        run = svc.connect("s0", f"127.0.0.1:{port}", timeout=30.0,
                          follow=True, reconnect_sec=30.0)
        deadline = time.perf_counter() + 30.0
        while "s0" not in svc.sink.detections \
                and time.perf_counter() < deadline:
            time.sleep(0.05)
        assert "s0" in svc.sink.detections  # first session finalized
        svc.stop(drain=False)
        assert run.done.wait(timeout=10.0)  # NOT a 30 s backoff later
        assert run.error is None  # the clean session's verdict survived
        assert run.result is not None
    finally:
        server.stop()
        svc.stop(drain=False)


def test_connect_follow_reconnects_sessions():
    """follow=True: the actor finalizes each wire session and reconnects
    (the resident serve-pod contract) until the service stops."""
    from nerrf_tpu.ingest.service import TraceReplayServer

    cfg = ServeConfig(buckets=(BUCKET_B,), batch_size=4,
                      batch_close_sec=0.02, window_sec=10.0, stride_sec=5.0)
    svc, reg = _fake_service(cfg)
    tr = _sim(seed=41, duration=40.0, files=3, rate=5.0)
    server = TraceReplayServer(tr.events, tr.strings, batch_size=256)
    port = server.start()
    try:
        run = svc.connect("s0", f"127.0.0.1:{port}", timeout=30.0,
                          follow=True, reconnect_sec=0.05)
        deadline = time.perf_counter() + 30.0
        while len(svc.sink.detections) < 2 and time.perf_counter() < deadline:
            time.sleep(0.05)
        # at least two sessions finalized: s0 and its reconnect s0#1
        assert {"s0", "s0#1"} <= set(svc.sink.detections)
        svc.stop(drain=False)
        assert run.done.wait(timeout=30.0)  # actor exits once admission closes
    finally:
        server.stop()
        svc.stop(drain=False)


# -- micro-batcher: deterministic packing across buckets ----------------------

def test_batcher_packs_same_bucket_cross_stream_deterministically():
    cfg = ServeConfig(buckets=(BUCKET_A, BUCKET_B), batch_size=4,
                      batch_close_sec=10.0)  # close only on occupancy here
    seen = []

    def score(batch):
        seen.append({k: v.copy() for k, v in batch.items()})
        return np.zeros(batch["node_mask"].shape)

    reg = MetricsRegistry(namespace="test")
    got = []
    mb = MicroBatcher(score_fn=score, cfg=cfg, registry=reg,
                      on_scored=got.extend)
    mb.mark_warm(BUCKET_A), mb.mark_warm(BUCKET_B)

    def req(stream, idx, bucket):
        sample = {"node_mask": np.zeros(bucket[0], np.bool_),
                  "node_type": np.zeros(bucket[0], np.int32),
                  "node_key": np.zeros(bucket[0], np.int64)}
        now = time.perf_counter()
        return WindowRequest(stream=stream, window_idx=idx, lo_ns=0, hi_ns=1,
                             bucket=bucket, sample=sample, t_admit=now,
                             deadline=now + 10)

    # interleaved submission from two streams into two buckets
    order = [("s0", 0, BUCKET_A), ("s1", 0, BUCKET_B), ("s0", 1, BUCKET_B),
             ("s1", 1, BUCKET_A), ("s0", 2, BUCKET_A), ("s1", 2, BUCKET_B),
             ("s1", 3, BUCKET_A), ("s0", 3, BUCKET_B)]
    for stream, idx, bucket in order:
        mb.submit(req(stream, idx, bucket))
    # both buckets reached occupancy 4 → exactly two batches, FIFO packed
    assert mb.drain_once() == 2
    assert len(got) == 8
    by_batch = {}
    for s in got:
        by_batch.setdefault(tuple(s.bucket), []).append((s.stream, s.window_idx))
    assert by_batch[BUCKET_A] == [("s0", 0), ("s1", 1), ("s0", 2), ("s1", 3)]
    assert by_batch[BUCKET_B] == [("s1", 0), ("s0", 1), ("s1", 2), ("s0", 3)]
    # occupancy metric saw 4-window batches, close cause = occupancy
    assert reg.value("serve_batch_occupancy",
                     labels={"bucket": "128n/256e/32s"}, stat="mean") == 4.0
    assert reg.value("serve_batches_total",
                     labels={"bucket": "128n/256e/32s",
                             "cause": "occupancy"}) == 1


def test_queue_depth_gauge_is_locked_post_close_count():
    """Regression (nerrflint lock-discipline): `_emit_batch` used to read
    `_live` without the batcher lock while stream threads mutate it.  The
    post-close queue-depth gauge must equal the locked count of windows
    still pending after the batch was assembled."""
    from nerrf_tpu.serve.config import bucket_tag

    cfg = ServeConfig(buckets=(BUCKET_B,), batch_size=4,
                      batch_close_sec=10.0)
    reg = MetricsRegistry(namespace="test")
    mb = MicroBatcher(score_fn=lambda b: np.zeros(b["node_mask"].shape),
                      cfg=cfg, registry=reg)
    mb.mark_warm(BUCKET_B)
    now = time.perf_counter()
    for i in range(5):
        sample = {"node_mask": np.zeros(BUCKET_B[0], np.bool_),
                  "node_type": np.zeros(BUCKET_B[0], np.int32),
                  "node_key": np.zeros(BUCKET_B[0], np.int64)}
        mb.submit(WindowRequest(stream="s", window_idx=i, lo_ns=0, hi_ns=1,
                                bucket=BUCKET_B, sample=sample, t_admit=now,
                                deadline=now + 10))
    # occupancy close takes 4 of the 5; the gauge must show the 1 leftover
    assert mb.drain_once() == 1
    assert reg.value("serve_queue_depth",
                     labels={"bucket": bucket_tag(BUCKET_B)}) == 1.0
    assert mb.queue_depth(BUCKET_B) == 1


# -- slow-consumer isolation --------------------------------------------------

def test_stalled_stream_cannot_delay_another_buckets_batch_close():
    """Stream A stalls after half a window; stream B's windows must close
    on the deadline and score without A ever completing anything."""
    cfg = ServeConfig(buckets=(BUCKET_A, BUCKET_B), batch_size=8,
                      batch_close_sec=0.05, window_sec=15.0, stride_sec=5.0)
    svc, reg = _fake_service(cfg)
    try:
        svc.join("stalled")
        svc.join("live")
        tr = _sim(seed=5, duration=45.0, files=3, rate=4.0)
        blocks = list(_blocks(tr, size=150))
        # the stalled stream feeds ONE block (never enough to close a
        # window) and then goes silent
        svc.feed("stalled", blocks[0], tr.strings)
        t0 = time.perf_counter()
        for b in blocks:
            svc.feed("live", b, tr.strings)
        det = svc.leave("live", timeout=10.0)
        waited = time.perf_counter() - t0
        assert det.detector == "serve[max]"
        h = svc._streams.get("live")
        assert h is None  # clean leave
        assert reg.value("serve_windows_scored_total") >= 1
        # deadline close fired well under the stalled stream's "never"
        assert waited < 5.0
        causes = [c for c in ("deadline", "occupancy", "flush")
                  if reg.value("serve_batches_total",
                               labels={"bucket": "128n/256e/32s",
                                       "cause": c})
                  or reg.value("serve_batches_total",
                               labels={"bucket": "256n/512e/64s",
                                       "cause": c})]
        assert causes, "no batch ever closed"
    finally:
        svc.stop(drain=False)


# -- drop-oldest under sustained overload -------------------------------------

def test_drop_oldest_under_sustained_overload():
    """With scoring wedged, a 2-slot stream queue must keep only the two
    NEWEST windows and count every eviction."""
    gate = threading.Event()

    def slow_score(batch):
        gate.wait(timeout=30.0)
        return np.zeros(batch["node_mask"].shape)

    cfg = ServeConfig(buckets=(BUCKET_B,), batch_size=8,
                      batch_close_sec=10.0,  # nothing closes during the test
                      stream_queue_slots=2,
                      window_sec=10.0, stride_sec=5.0)
    svc, reg = _fake_service(cfg, score=slow_score)
    try:
        svc.join("s0")
        tr = _sim(seed=9, duration=120.0, files=4, rate=6.0)
        for b in _blocks(tr, size=400):
            svc.feed("s0", b, tr.strings)
        h = svc._streams["s0"]
        assert h.admitted > 4
        assert h.dropped == h.admitted - 2          # all but the newest two
        assert len(h.live) == 2
        # drop-OLDEST: the survivors are exactly the two NEWEST windows
        assert sorted(h.live) == [h.windower.windows_emitted - 2,
                                  h.windower.windows_emitted - 1]
        assert reg.value("serve_admission_dropped_total",
                         labels={"reason": "backpressure"}) == h.dropped
    finally:
        gate.set()
        svc.stop(drain=False)


# -- stream leave mid-batch ---------------------------------------------------

def test_stream_leave_mid_batch_is_clean_and_isolated():
    """Leaving while windows sit queued (scoring wedged) must drop them
    cleanly, return a result from whatever DID score, and leave the other
    stream fully functional."""
    release = threading.Event()
    calls = []

    def gated_score(batch):
        calls.append(1)
        if len(calls) > 1:
            release.wait(timeout=5.0)
        return np.full(batch["node_mask"].shape, 0.9)

    cfg = ServeConfig(buckets=(BUCKET_B,), batch_size=2,
                      batch_close_sec=0.02, window_sec=10.0, stride_sec=5.0)
    svc, reg = _fake_service(cfg, score=gated_score)
    try:
        svc.join("leaver")
        svc.join("stayer")
        tr = _sim(seed=13, duration=60.0, files=4, rate=6.0)
        for b in _blocks(tr, size=300):
            svc.feed("leaver", b, tr.strings)
        time.sleep(0.2)  # first batch through, second wedged in gated_score
        det = svc.leave("leaver", timeout=0.5)
        assert det.detector == "serve[max]"
        assert "leaver" not in svc._streams
        dropped_on_leave = reg.value("serve_admission_dropped_total",
                                     labels={"reason": "leave"})
        release.set()
        # the other stream still works end to end afterwards
        for b in _blocks(tr, size=300):
            svc.feed("stayer", b, tr.strings)
        det2 = svc.leave("stayer", timeout=10.0)
        assert len(det2.file_window_scores) > 0
        # ledger accounting is exact: nothing leaked
        assert dropped_on_leave >= 0
    finally:
        release.set()
        svc.stop(drain=False)


# -- alerts + demux overflow --------------------------------------------------

def test_alert_sink_bounded_overflow_counted():
    cfg = ServeConfig(buckets=(BUCKET_B,), batch_size=4,
                      batch_close_sec=0.02, window_sec=10.0, stride_sec=5.0,
                      alert_queue_slots=2)
    svc, reg = _fake_service(cfg)  # fake score: every window is hot (0.9)
    try:
        svc.join("s0")
        tr = _sim(seed=17, duration=80.0, files=4, rate=6.0)
        for b in _blocks(tr, size=300):
            svc.feed("s0", b, tr.strings)
        svc.leave("s0", timeout=10.0)
        scored = reg.value("serve_windows_scored_total")
        assert scored > 2
        assert len(svc.sink) == 2  # bounded: only the newest alerts kept
        assert reg.value("serve_demux_overflows_total") == scored - 2
        a = svc.sink.drain()[-1]
        assert a.max_prob == pytest.approx(0.9)
        assert a.hot and a.hot[0][0] in ("file", "proc")
    finally:
        svc.stop(drain=False)


# -- oversize rejection -------------------------------------------------------

def test_oversize_window_rejected_not_resized():
    cfg = ServeConfig(buckets=((16, 16, 8),), batch_size=2,
                      batch_close_sec=0.02, window_sec=30.0, stride_sec=15.0)
    svc, reg = _fake_service(cfg)
    try:
        svc.join("s0")
        tr = _sim(seed=19, duration=90.0, files=8, rate=10.0)
        for b in _blocks(tr, size=400):
            svc.feed("s0", b, tr.strings)
        svc.leave("s0", timeout=5.0)
        assert reg.value("serve_admission_dropped_total",
                         labels={"reason": "oversize"}) > 0
        # nothing was compiled/scored at an unconfigured shape
        assert reg.value("serve_recompiles_total",
                         labels={"bucket": "16n/16e/8s"}) == 0
    finally:
        svc.stop(drain=False)


# -- the acceptance criterion: bit-parity with offline model_detect ----------

@pytest.fixture(scope="module")
def small_model():
    import jax

    from nerrf_tpu.models import JointConfig, NerrfNet
    from nerrf_tpu.serve import init_untrained_params

    cfg = ServeConfig(buckets=(BUCKET_B,), batch_size=4,
                      window_sec=15.0, stride_sec=5.0)
    model = NerrfNet(JointConfig().small)
    params = init_untrained_params(model, cfg)
    del jax
    return model, params, cfg


def test_single_stream_bit_parity_with_model_detect(small_model):
    from nerrf_tpu.pipeline import model_detect

    model, params, cfg = small_model
    svc = OnlineDetectionService(params, model, cfg=cfg,
                                 registry=MetricsRegistry(namespace="test"))
    svc.start()
    try:
        tr = _sim(seed=3, duration=60.0)
        svc.join("s0")
        for b in _blocks(tr, size=200):
            svc.feed("s0", b, tr.strings)
        det = svc.leave("s0", timeout=60.0)
    finally:
        svc.stop()
    offline = model_detect(
        Trace(events=tr.events, strings=tr.strings, ground_truth=None,
              labels=None, name="s0"),
        params, model, ds_cfg=cfg.dataset_config(BUCKET_B),
        auto_capacity=False, batch_size=cfg.batch_size)
    # bit-identical: same floats, same dicts, same threshold
    assert det.file_scores == offline.file_scores
    assert det.file_window_scores == offline.file_window_scores
    assert det.proc_scores == offline.proc_scores
    assert det.file_bytes == offline.file_bytes
    assert det.threshold == offline.threshold
    assert det.detector == "serve[max]"


def test_two_streams_share_batches_with_parity(small_model):
    """Windows of two concurrent streams pack into shared batches (measured
    occupancy > 1 at the bucket) and each stream's result still matches its
    own offline detection exactly."""
    from nerrf_tpu.pipeline import model_detect

    model, params, cfg = small_model
    cfg = dataclasses.replace(cfg, batch_close_sec=0.25)
    reg = MetricsRegistry(namespace="test")
    svc = OnlineDetectionService(params, model, cfg=cfg, registry=reg)
    svc.start()
    traces = {"a": _sim(seed=23, duration=45.0),
              "b": _sim(seed=29, duration=45.0, attack=False)}
    dets = {}
    try:
        for sid in traces:
            svc.join(sid)
        # interleave the two streams' blocks, as concurrent drains would
        blocks = {sid: list(_blocks(traces[sid], size=150))
                  for sid in traces}
        for i in range(max(len(b) for b in blocks.values())):
            for sid in traces:
                if i < len(blocks[sid]):
                    svc.feed(sid, blocks[sid][i], traces[sid].strings)
        for sid in traces:
            dets[sid] = svc.leave(sid, timeout=60.0)
    finally:
        svc.stop()
    tag = "256n/512e/64s"
    assert reg.value("serve_batch_occupancy", labels={"bucket": tag},
                     stat="mean") > 1.0
    assert reg.value("serve_recompiles_total", labels={"bucket": tag}) == 0
    for sid, tr in traces.items():
        offline = model_detect(
            Trace(events=tr.events, strings=tr.strings, ground_truth=None,
                  labels=None, name=sid),
            params, model, ds_cfg=cfg.dataset_config(BUCKET_B),
            auto_capacity=False, batch_size=cfg.batch_size)
        assert dets[sid].file_scores == offline.file_scores, sid
        assert dets[sid].file_window_scores == offline.file_window_scores, sid


def test_stop_joins_nondaemon_devtime_cost_thread(small_model):
    """Regression for the jax-on-daemon-thread hazard (thread-lifecycle
    lint): the background cost-registration thread runs jax tracing, so it
    must be NON-daemon (a daemon thread still inside jax at interpreter
    teardown segfaults) and stop() must join it out — service stop leaves
    no nerrf-devtime-costs thread running."""
    model, params, cfg = small_model
    # warmup skipped: this test exercises thread lifecycle, not programs
    cfg = dataclasses.replace(cfg, warmup_on_start=False)
    svc = OnlineDetectionService(params, model, cfg=cfg,
                                 registry=MetricsRegistry(namespace="test"))
    svc.start()
    try:
        t = svc._devtime_thread
        assert t is not None and t.name == "nerrf-devtime-costs"
        assert not t.daemon
    finally:
        svc.stop(drain=False)
    assert svc._devtime_thread is None
    assert not any(th.name == "nerrf-devtime-costs" and th.is_alive()
                   for th in threading.enumerate())


def test_raising_alert_sink_never_wedges_leave():
    """Demux fail-open: ledger resolution runs LAST (so leave() never
    returns before a window's alert is emitted) but must be UNCONDITIONAL
    — a raising sink loses at most that window's alert, never the
    resolution, else every leave() would hang to its timeout."""
    cfg = ServeConfig(buckets=(BUCKET_B,), batch_size=4,
                      batch_close_sec=0.02, window_sec=10.0, stride_sec=5.0)
    svc, reg = _fake_service(cfg)  # fake score: every window is hot
    svc.sink.emit = lambda alert: (_ for _ in ()).throw(
        RuntimeError("operator console down"))
    try:
        svc.join("s0")
        tr = _sim(seed=11, duration=60.0, files=4, rate=6.0)
        for b in _blocks(tr, size=300):
            svc.feed("s0", b, tr.strings)
        t0 = time.perf_counter()
        det = svc.leave("s0", timeout=30.0)
        assert time.perf_counter() - t0 < 10.0  # resolved, not timed out
    finally:
        svc.stop(drain=False)
    assert reg.value("serve_windows_scored_total") > 0
    assert det.file_scores  # every scored window reached the detection
    # each lost alert is journaled as a counted demux_drop
    drops = [r for r in svc._journal.tail()
             if r.kind == "demux_drop"
             and r.data.get("reason") == "emit_error"]
    assert drops and "RuntimeError" in drops[0].data["error"]
