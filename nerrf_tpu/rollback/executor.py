"""Rollback executor: applies an UndoPlan to the real filesystem, verified.

Generalizes the reference's recovery mechanism — a rename-back loop with
millisecond timing (`/root/reference/benchmarks/m1/scripts/m1_rollback.sh:74-133`)
— into verified restoration: for each planned file reversion, restore the
pre-attack bytes from the content-addressed snapshot store, remove the
ransom-named artifact, and verify the result by sha256 against the snapshot
manifest (the spec's hash-validation step, `architecture.mdx:83-86`).
Process kills are recorded (and only executed for real when ``allow_kill`` is
set — the benchmark simulates victims in-process).
"""

from __future__ import annotations

import dataclasses
import time
from pathlib import Path
from typing import Dict, List, Optional

from nerrf_tpu.planner.domain import ActionKind, UndoPlan
from nerrf_tpu.rollback.store import Manifest, SnapshotStore, sha256_file


@dataclasses.dataclass
class RollbackReport:
    files_restored: int = 0
    files_failed: int = 0
    files_skipped: int = 0
    bytes_restored: int = 0
    procs_killed: int = 0
    duration_seconds: float = 0.0
    verified: bool = False
    details: List[Dict] = dataclasses.field(default_factory=list)

    @property
    def files_per_sec(self) -> float:
        return self.files_restored / self.duration_seconds if self.duration_seconds else 0.0

    @property
    def mb_per_sec(self) -> float:
        return (self.bytes_restored / 1e6) / self.duration_seconds if self.duration_seconds else 0.0

    def to_dict(self) -> Dict:
        return {
            "files_restored": self.files_restored,
            "files_failed": self.files_failed,
            "files_skipped": self.files_skipped,
            "bytes_restored": self.bytes_restored,
            "procs_killed": self.procs_killed,
            "duration_seconds": self.duration_seconds,
            "files_per_sec": round(self.files_per_sec, 2),
            "mb_per_sec": round(self.mb_per_sec, 2),
            "verified": self.verified,
        }


class RollbackExecutor:
    def __init__(
        self,
        store: SnapshotStore,
        manifest: Manifest,
        root: str | Path,
        ransom_ext: str = ".lockbit3",
        allow_kill: bool = False,
        journal=None,
    ) -> None:
        if journal is None:
            from nerrf_tpu.flight.journal import DEFAULT_JOURNAL

            journal = DEFAULT_JOURNAL
        self.store = store
        self.manifest = manifest
        self.root = Path(root)
        self.ransom_ext = ransom_ext
        self.allow_kill = allow_kill
        self._journal = journal

    def _step_unsafe(self, rel: str) -> Optional[str]:
        """Fail-closed preconditions for one REVERT_FILE step; returns the
        one-line refusal reason, or None when the step is safe to apply.

        * path escape — a manifest rel like ``../x`` (hostile or corrupted
          manifest) would make restore/unlink write OUTSIDE the sandbox
          root; every path this step will touch must resolve under root.
        * pre-image mismatch — the store blob about to be written must
          hash to the digest the manifest promises; a corrupted or
          tampered blob must never reach the victim tree (restore-then-
          verify would catch it AFTER the damage is done).
        """
        digest = self.manifest.files[rel][0]
        root = self.root.resolve()
        for candidate in (self.root / rel, self.root / (rel + self.ransom_ext)):
            # resolve the PARENT (the leaf may not exist yet): symlinked or
            # dot-dotted components both normalize away here
            resolved = candidate.parent.resolve() / candidate.name
            if not resolved.is_relative_to(root):
                return f"path escapes sandbox root: {candidate}"
        blob = self.store.dir / "blobs" / digest
        if not blob.is_file():
            return f"snapshot blob missing: {digest[:12]}"
        if sha256_file(blob) != digest:
            return f"pre-image hash mismatch: blob {digest[:12]} is corrupt"
        return None

    def _rel_of(self, path: str) -> Optional[str]:
        """Map a planned (possibly ransom-named) path to a manifest entry.

        Plan targets are absolute paths under the *original* victim root, but
        the executor may run against a different root (the sandbox gate's
        clone), so resolution tries ever-shorter path suffixes against the
        manifest — longest match wins, which keeps nested layouts unambiguous.
        """
        parts = Path(path).parts
        for k in range(len(parts)):
            rel = "/".join(parts[k:])
            if rel in self.manifest.files:
                return rel
            if rel.endswith(self.ransom_ext):
                orig = rel[: -len(self.ransom_ext)]
                if orig in self.manifest.files:
                    return orig
        return None

    def execute(self, plan: UndoPlan) -> RollbackReport:
        rep = RollbackReport()
        t0 = time.perf_counter()
        for action in plan.actions:
            if action.kind == ActionKind.REVERT_FILE:
                rel = self._rel_of(action.target)
                if rel is None:
                    rep.files_skipped += 1
                    rep.details.append({"target": action.target, "result": "no-snapshot"})
                    continue
                unsafe = self._step_unsafe(rel)
                if unsafe is not None:
                    # fail THIS step closed and keep executing the plan:
                    # one bad step must not strand the other victims
                    # mid-restore, and the refusal is journaled so the
                    # flight/doctor planes can see why a restore shrank
                    rep.files_failed += 1
                    rep.details.append(
                        {"target": action.target, "result": f"refused:{unsafe}"})
                    self._journal.record(
                        "rollback_step_failed", target=action.target,
                        rel=rel, reason=unsafe)
                    continue
                try:
                    restored = self.store.restore_file(self.manifest, rel, self.root)
                    # remove the ransom-named artifact the attack left behind
                    artifact = self.root / (rel + self.ransom_ext)
                    if artifact.is_file():
                        artifact.unlink()
                    ok = self.store.verify_file(self.manifest, rel, self.root)
                    if ok:
                        rep.files_restored += 1
                        rep.bytes_restored += self.manifest.files[rel][1]
                        rep.details.append({"target": str(restored), "result": "restored"})
                    else:
                        rep.files_failed += 1
                        rep.details.append({"target": str(restored), "result": "hash-mismatch"})
                except OSError as e:
                    rep.files_failed += 1
                    rep.details.append({"target": action.target, "result": f"error:{e}"})
            elif action.kind == ActionKind.KILL_PROCESS:
                rep.procs_killed += 1
                killed = False
                if self.allow_kill:
                    try:
                        import os
                        import signal

                        pid = int(action.target.split(":", 1)[0])
                        os.kill(pid, signal.SIGKILL)
                        killed = True
                    except (ValueError, ProcessLookupError, PermissionError):
                        pass
                rep.details.append({
                    "target": action.target,
                    "result": "killed" if killed else "kill-recorded",
                })
        rep.duration_seconds = time.perf_counter() - t0
        rep.verified = rep.files_failed == 0 and rep.files_restored > 0
        return rep
