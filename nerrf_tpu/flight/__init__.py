"""Incident flight recorder + end-to-end SLO plane for the serve path.

Three cooperating pieces, all stdlib-only (like the metrics registry and
the tracing spine they ride on):

  * `journal` — a bounded, thread-safe ring of typed `JournalRecord`s
    (batch closes, admission/demux drops, registry lifecycle verdicts,
    readiness flips, config/model fingerprints), each stamped with a
    monotonic sequence number and the window/trace IDs it touched.  The
    structured companion to the span ring: spans say *where time went*,
    the journal says *what the system decided*.
  * `slo` — end-to-end SLO accounting: every window carries its event time
    through admit → pack → device → demux, producing per-stream
    ``nerrf_slo_e2e_seconds`` histograms, per-stage budget-burn gauges and
    exemplar trace IDs (the slowest recent window per stream) so a slow
    alert links back to its exact batch's span tree.
  * `recorder` — declarative anomaly triggers (trailing-p99 breach, drop
    burst, shadow-disagreement spike, guardrail veto, uncaught exception)
    that atomically dump a self-contained diagnostic bundle: journal tail,
    Chrome-trace export, metrics snapshot, model lineage, environment
    fingerprint.  Rate-limited per trigger and bounded on disk; readable
    offline by ``nerrf doctor <bundle>`` (`doctor.py`).

docs/flight-recorder.md is the operator guide.
"""

from nerrf_tpu.flight.journal import (
    DEFAULT_JOURNAL,
    EventJournal,
    JournalRecord,
    fingerprint,
    make_trace_id,
)
from nerrf_tpu.flight.recorder import (
    FlightConfig,
    FlightRecorder,
    install_crash_handlers,
)
from nerrf_tpu.flight.slo import SLO_BUCKETS, SLOTracker

__all__ = [
    "DEFAULT_JOURNAL",
    "EventJournal",
    "JournalRecord",
    "FlightConfig",
    "FlightRecorder",
    "SLOTracker",
    "SLO_BUCKETS",
    "fingerprint",
    "install_crash_handlers",
    "make_trace_id",
]
