"""Pipeline-wide span tracing: where did the step time go?

The metrics registry (`nerrf_tpu.observability`) answers "how many"; this
module answers "where did the time go" — the load-bearing question for a
TPU training/inference stack, where the failure mode is an idle accelerator
hidden behind a healthy-looking throughput counter (the first-class signal
of the GPU/TPU GNN benchmarking and Podracer literatures: host-blocked vs
device vs data-wait vs padding waste).

Zero-dependency by design (stdlib only, like the registry): `span()` is a
thread-safe context manager that records host-side spans into a bounded
ring buffer and **dual-writes** every span into the metrics registry as a
``stage_latency_seconds{stage=...}`` histogram — one instrumentation point
keeps Prometheus and traces consistent by construction.

Exports are Chrome trace-event JSON (`chrome://tracing` / Perfetto
loadable: ``{"traceEvents": [{"ph": "X", ...}]}``), so a host trace drops
into the same UI as an XLA device trace taken with
`observability.trace_profile`.  Device-side mirroring: model code wraps the
GNN layers / LSTM scan / fused aggregation in `jax.named_scope` with the
same stage names, and `device_annotation` adds a
`jax.profiler.TraceAnnotation` around host regions — so host spans and XLA
trace rows line up by name in Perfetto.

Span naming scheme (dot-separated, coarse → fine):

    ingest_decode      EventBatch frame → native decode (ingest client)
    graph_lower        one window of events → padded GraphBatch (builder)
    store_compact      trace-store delta → bucket segments
    store_query        trace-store window read
    bucket_pad         trace → capacity-bucketed padded window samples
    calibrate          held-out file-threshold calibration
    data_wait          host blocked waiting for input data
    device_step        one train step, fetch-synced (dispatch + blocked)
    eval               held-out evaluation pass
    checkpoint         full-state checkpoint save
    mcts_plan          one planner search; mcts_leaf_eval = device batch
    serve_admit        one stream window measured/lowered/enqueued (serve)
    serve_batch_close  a bucket's shared batch assembled (occupancy/deadline)
    serve_device_score one shared padded batch through the eval program
    serve_demux        scored batch fanned back to streams + alert sink

The ring buffer records unconditionally (bounded memory, ~µs overhead);
``DEFAULT_TRACER.enabled`` additionally opts hot loops into per-step
*synced* spans (`train/loop.py` fetches the loss inside the span so
``device_step`` measures the device, not the dispatch queue) — off by
default because the sync defeats step pipelining.  Enable via
``NERRF_TRACE=1`` or the CLI's ``--trace-out``.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional

# The one histogram every span dual-writes into (per-stage label).
STAGE_HISTOGRAM = "stage_latency_seconds"
_STAGE_HELP = "host-side span latency per pipeline stage"

# Latency buckets sized for the pipeline's spread: µs-scale decodes up to
# multi-minute compiles/evals.
STAGE_BUCKETS = (0.0005, 0.002, 0.01, 0.05, 0.25, 1.0, 5.0, 30.0, 120.0)


class Span:
    """One recorded host-side region.  ``t0``/``dur`` are perf-counter
    seconds relative to the owning tracer's epoch; ``args`` is the mutable
    attribute dict the ``with`` body may extend (exported verbatim into the
    Chrome event's ``args``)."""

    __slots__ = ("name", "t0", "dur", "tid", "args")

    def __init__(self, name: str, args: Dict) -> None:
        self.name = name
        self.t0 = 0.0
        self.dur = 0.0
        self.tid = threading.get_ident()
        self.args = args


class Tracer:
    """Thread-safe ring-buffered span recorder with Chrome-trace export."""

    def __init__(self, capacity: int = 65536, registry=None) -> None:
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=capacity)
        self._registry = registry
        self._thread_names: Dict[int, str] = {}
        # perf_counter origin for span timestamps; the wall-clock anchor
        # travels in the export so traces from different processes can be
        # aligned offline
        self._t0_perf = time.perf_counter()
        self._t0_epoch = time.time()
        self.enabled = os.environ.get("NERRF_TRACE") == "1"

    # -- recording -----------------------------------------------------------

    def _reg(self):
        if self._registry is None:
            from nerrf_tpu.observability import DEFAULT_REGISTRY

            self._registry = DEFAULT_REGISTRY
        return self._registry

    @contextlib.contextmanager
    def span(self, stage: str, device: bool = False, **args):
        """Record a host-side span named ``stage``.

        Always records (ring buffer + ``stage_latency_seconds`` histogram);
        the yielded :class:`Span` exposes ``args`` for attributes the body
        learns mid-flight.  ``device=True`` additionally opens a
        `jax.profiler.TraceAnnotation` of the same name (only when jax is
        already imported — this module must not force backend init), so the
        region shows up host-side in an XLA profiler trace under the same
        label as the device ops it dispatched.
        """
        sp = Span(stage, args)
        ann = None
        if device:
            jax = sys.modules.get("jax")
            if jax is not None:
                try:
                    ann = jax.profiler.TraceAnnotation(stage)
                    ann.__enter__()
                except Exception:
                    ann = None
        t0 = time.perf_counter()
        sp.t0 = t0 - self._t0_perf
        try:
            yield sp
        finally:
            sp.dur = time.perf_counter() - t0
            if ann is not None:
                with contextlib.suppress(Exception):
                    ann.__exit__(None, None, None)
            with self._lock:
                self._spans.append(sp)
                # latest name wins: CPython recycles thread idents, so a
                # cached dead thread's name must not label a new thread
                self._thread_names[sp.tid] = threading.current_thread().name
            self._reg().histogram_observe(
                STAGE_HISTOGRAM, sp.dur, buckets=STAGE_BUCKETS,
                labels={"stage": stage}, help=_STAGE_HELP)

    # -- inspection / export -------------------------------------------------

    def records(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def chrome_trace(self) -> dict:
        """Chrome trace-event JSON object (Perfetto / chrome://tracing)."""
        pid = os.getpid()
        with self._lock:
            spans = list(self._spans)
            names = dict(self._thread_names)
        events: List[dict] = [{
            "ph": "M", "pid": pid, "name": "process_name",
            "args": {"name": "nerrf host"},
        }]
        for tid, tname in names.items():
            events.append({"ph": "M", "pid": pid, "tid": tid,
                           "name": "thread_name", "args": {"name": tname}})
        for s in spans:
            ev = {
                "name": s.name, "ph": "X", "pid": pid, "tid": s.tid,
                "ts": round(s.t0 * 1e6, 3),       # µs, tracer-epoch origin
                "dur": round(s.dur * 1e6, 3),
            }
            if s.args:
                ev["args"] = dict(s.args)
            events.append(ev)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "nerrf_tpu.tracing",
                "epoch_anchor_unix_sec": self._t0_epoch,
            },
        }

    def write(self, path) -> str:
        """Write the Chrome-trace JSON to ``path`` (returns the path)."""
        path = os.fspath(path)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path


# The process-wide tracer every pipeline component records into (the span
# analogue of observability.DEFAULT_REGISTRY).
DEFAULT_TRACER = Tracer()


def span(stage: str, device: bool = False, **args):
    """``DEFAULT_TRACER.span`` — the one-import instrumentation point."""
    return DEFAULT_TRACER.span(stage, device=device, **args)


def set_enabled(on: bool = True) -> None:
    """Opt hot loops into per-step synced attribution spans (see module
    docstring); the CLI's ``--trace-out`` calls this before the command."""
    DEFAULT_TRACER.enabled = bool(on)


@contextlib.contextmanager
def device_annotation(name: str):
    """`jax.profiler.TraceAnnotation` + `jax.named_scope` of one name, when
    jax is importable — a no-op otherwise.  For host regions that dispatch
    device work outside a recorded span."""
    jax = sys.modules.get("jax")
    if jax is None:
        yield
        return
    with jax.profiler.TraceAnnotation(name), jax.named_scope(name):
        yield


# -- trace-file analysis (the `nerrf trace` subcommand's engine) -------------


def load_chrome_trace(path) -> List[dict]:
    """Complete ("X") events from a Chrome-trace JSON file — accepts both
    the object form ({"traceEvents": [...]}) and a bare event list."""
    with open(os.fspath(path)) as f:
        data = json.load(f)
    events = data.get("traceEvents", []) if isinstance(data, dict) else data
    if not isinstance(events, list):
        return []
    return [e for e in events if isinstance(e, dict) and e.get("ph") == "X"]


def stage_summary(events: Iterable[dict]) -> Dict[str, dict]:
    """Per-stage latency stats from "X" events: count, total/mean/p50/max ms."""
    by_name: Dict[str, List[float]] = {}
    for e in events:
        by_name.setdefault(e["name"], []).append(float(e.get("dur", 0.0)))
    out: Dict[str, dict] = {}
    for name, durs in by_name.items():
        durs.sort()
        n = len(durs)
        out[name] = {
            "count": n,
            "total_ms": sum(durs) / 1e3,
            "mean_ms": sum(durs) / n / 1e3,
            "p50_ms": durs[n // 2] / 1e3,
            "max_ms": durs[-1] / 1e3,
        }
    return out


def wall_clock_us(events: Iterable[dict]) -> float:
    """Trace extent: max(ts+dur) − min(ts) over the "X" events, in µs."""
    lo, hi = None, None
    for e in events:
        t0 = float(e["ts"])
        t1 = t0 + float(e.get("dur", 0.0))
        lo = t0 if lo is None else min(lo, t0)
        hi = t1 if hi is None else max(hi, t1)
    return 0.0 if lo is None else hi - lo


def coverage(events: Iterable[dict],
             lo_us: Optional[float] = None,
             hi_us: Optional[float] = None) -> float:
    """Fraction of [lo, hi] covered by the union of span intervals (nested
    and overlapping spans count once).  Defaults to the trace's own extent —
    the acceptance check "spans cover ≥ X% of wall-clock"."""
    ivals = sorted(
        (float(e["ts"]), float(e["ts"]) + float(e.get("dur", 0.0)))
        for e in events
    )
    if not ivals:
        return 0.0
    if lo_us is None:
        lo_us = ivals[0][0]
    if hi_us is None:
        hi_us = max(b for _, b in ivals)
    if hi_us <= lo_us:
        return 0.0
    covered = 0.0
    cur_a, cur_b = None, None
    for a, b in ivals:
        a, b = max(a, lo_us), min(b, hi_us)
        if b <= a:
            continue
        if cur_b is None or a > cur_b:
            if cur_b is not None:
                covered += cur_b - cur_a
            cur_a, cur_b = a, b
        else:
            cur_b = max(cur_b, b)
    if cur_b is not None:
        covered += cur_b - cur_a
    return covered / (hi_us - lo_us)


def format_stage_table(events: Iterable[dict]) -> str:
    """Human-readable per-stage latency table (sorted by total time)."""
    events = list(events)
    summary = stage_summary(events)
    wall_ms = wall_clock_us(events) / 1e3
    header = (f"{'stage':<24} {'count':>7} {'total_ms':>10} {'mean_ms':>9} "
              f"{'p50_ms':>9} {'max_ms':>9} {'%wall':>6}")
    lines = [header, "-" * len(header)]
    for name, s in sorted(summary.items(), key=lambda kv: -kv[1]["total_ms"]):
        pct = 100.0 * s["total_ms"] / wall_ms if wall_ms > 0 else 0.0
        lines.append(
            f"{name:<24} {s['count']:>7} {s['total_ms']:>10.2f} "
            f"{s['mean_ms']:>9.3f} {s['p50_ms']:>9.3f} {s['max_ms']:>9.2f} "
            f"{pct:>5.1f}%")
    lines.append(f"wall: {wall_ms:.2f} ms, span coverage: "
                 f"{100.0 * coverage(events):.1f}%")
    return "\n".join(lines)
