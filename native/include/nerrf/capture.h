/* Live kernel capture: eBPF raw_syscalls tracepoint -> ring buffer -> C API.
 *
 * The working equivalent of the reference's BPF loader + ring reader
 * (`/root/reference/tracker/pkg/bpf/loader.go:13-45`,
 * `tracker/cmd/tracker/main.go:69-156`), with two deliberate differences:
 *
 *  1. No clang, no libbpf headers: the capture programs are hand-assembled
 *     eBPF bytecode (src/bpfasm.h) loaded through raw bpf(2) syscalls, so
 *     the daemon is self-contained — it needs a kernel, not a toolchain.
 *     bpf/tracepoints.c remains the readable C source of truth; the
 *     assembler emits the same semantics (asserted by tests that decode
 *     both paths).
 *
 *  2. One program on raw_syscalls/sys_enter with an in-kernel syscall-id
 *     dispatch, instead of five per-syscall tracepoints: Firecracker-style
 *     kernels (like this one) ship without CONFIG_FTRACE_SYSCALLS, so the
 *     per-syscall events directory does not exist; raw_syscalls always
 *     does.  The dispatch drops non-tracked syscalls in a few instructions.
 *
 * Capability detection is explicit: nerrf_capture_probe() distinguishes
 * "no permission" from "kernel support missing" so callers (daemon, tests,
 * e2e) can skip cleanly instead of failing.
 */
#ifndef NERRF_CAPTURE_H_
#define NERRF_CAPTURE_H_

#include <stdint.h>

#include "nerrf/event_record.h"

#ifdef __cplusplus
extern "C" {
#endif

typedef struct nerrf_capture nerrf_capture;

enum nerrf_capture_status {
  NERRF_CAPTURE_OK = 0,
  NERRF_CAPTURE_EPERM = 1,      /* bpf()/perf_event_open denied */
  NERRF_CAPTURE_NOSUPPORT = 2,  /* no tracefs / no raw_syscalls tracepoint */
  NERRF_CAPTURE_ERROR = 3,      /* anything else; see errbuf */
};

/* Cheap preflight: can this process load+attach the capture programs?
 * Writes a human-readable reason into errbuf on non-OK. */
int nerrf_capture_probe(char *errbuf, int errlen);

/* Load maps + program, attach to raw_syscalls/sys_enter.  `self_pid` > 0
 * pre-populates the in-kernel pid-exclusion hash map with that pid (the
 * daemon's gRPC writes must not echo into the stream).  NULL on failure
 * (reason in errbuf). */
nerrf_capture *nerrf_capture_open(uint32_t ringbuf_bytes, int self_pid,
                                  char *errbuf, int errlen);

/* Add/remove a pid from the in-kernel exclusion map.  The daemon excludes
 * every connected gRPC client (SO_PEERCRED) — a subscriber's own socket
 * writes would otherwise feed back as captured events, amplifying without
 * bound.  Returns 0 on success. */
int nerrf_capture_exclude_pid(nerrf_capture *c, int pid);
int nerrf_capture_unexclude_pid(nerrf_capture *c, int pid);

/* Pollable fd (the ring buffer map) for callers running their own loop. */
int nerrf_capture_fd(const nerrf_capture *c);

typedef void (*nerrf_event_cb)(void *user,
                               const struct nerrf_event_record *rec);

/* Wait up to timeout_ms for data, then drain every completed record through
 * cb.  Returns records consumed, 0 on timeout, -1 on error. */
int nerrf_capture_poll(nerrf_capture *c, int timeout_ms, nerrf_event_cb cb,
                       void *user);

/* Sum of the per-CPU kernel-side drop counters (ring buffer full). */
uint64_t nerrf_capture_dropped(const nerrf_capture *c);

void nerrf_capture_close(nerrf_capture *c);

#ifdef __cplusplus
}
#endif

#endif /* NERRF_CAPTURE_H_ */
