"""Dataset assembly: traces → stacked, padded window samples.

One training sample = one sliding-window graph (`GraphBatch`) plus the
per-file event sequences inside that window (`SequenceBatch`), with a
host-computed ``seq_node_idx`` routing each sequence to its file node (inode
match).  All samples share one static shape, so the whole dataset stacks into
flat [B, ...] arrays that shard trivially over a device mesh's data axis.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from nerrf_tpu.data.labels import derive_event_labels
from nerrf_tpu.data.loaders import Trace
from nerrf_tpu.data.sequences import SEQ_FEATURE_DIM, SequenceBatch, build_file_sequences
from nerrf_tpu.graph.builder import (
    GraphBatch,
    GraphConfig,
    NODE_TYPE_FILE,
    build_window_graph,
    snapshot_windows,
)


@dataclasses.dataclass(frozen=True)
class DatasetConfig:
    graph: GraphConfig = GraphConfig()
    seq_len: int = 100
    max_seqs: int = 128
    # windows with fewer events than this are skipped (no signal, all padding)
    min_events: int = 4


@dataclasses.dataclass
class WindowDataset:
    """Flat [B, ...] arrays ready for device transfer."""

    arrays: dict[str, np.ndarray]

    def __len__(self) -> int:
        return len(self.arrays["node_feat"])

    @property
    def num_samples(self) -> int:
        return len(self)

    def take(self, idx: np.ndarray) -> "WindowDataset":
        return WindowDataset({k: v[idx] for k, v in self.arrays.items()})

    def split(self, frac: float, seed: int = 0) -> tuple["WindowDataset", "WindowDataset"]:
        n = len(self)
        order = np.random.default_rng(seed).permutation(n)
        k = int(n * (1 - frac))
        return self.take(order[:k]), self.take(order[k:])

    @staticmethod
    def concatenate(parts: List["WindowDataset"]) -> "WindowDataset":
        keys = parts[0].arrays.keys()
        return WindowDataset(
            {k: np.concatenate([p.arrays[k] for p in parts]) for k in keys}
        )


def _seq_node_index(g: GraphBatch, seqs: SequenceBatch) -> np.ndarray:
    """Match each sequence's inode to its file-node slot in g (-1 if absent)."""
    out = np.full(len(seqs), -1, np.int32)
    file_slots = np.nonzero(g.node_mask & (g.node_type == NODE_TYPE_FILE))[0]
    if len(file_slots) == 0 or len(seqs) == 0:
        return out
    key_to_slot = {int(g.node_key[s]): int(s) for s in file_slots}
    for i, ino in enumerate(seqs.inode):
        out[i] = key_to_slot.get(int(ino), -1)
    return out


def window_sample(trace: Trace, lo: int, hi: int, cfg: DatasetConfig,
                  labels: Optional[np.ndarray] = None):
    """Lower ONE window [lo, hi) to a padded sample → ``(sample, stats)``.

    ``sample`` is None when the window carries fewer than ``cfg.min_events``
    events (all padding, no signal).  This is THE per-window lowering, shared
    by the offline dataset path (`windows_of_trace`) and the online serving
    windower (`nerrf_tpu.serve.windower`) — splitting it would let the two
    paths drift and break the serve path's bit-parity with `model_detect`.
    """
    g, stats = build_window_graph(trace.events, trace.strings, lo, hi,
                                  cfg.graph, labels=labels)
    if stats.num_events < cfg.min_events:
        return None, stats
    seqs = build_file_sequences(trace, labels=labels, seq_len=cfg.seq_len,
                                lo_ns=lo, hi_ns=hi)
    if len(seqs) > cfg.max_seqs:
        # keep the most event-dense sequences (they carry the signal)
        density = seqs.mask.sum(axis=1)
        keep = np.argsort(-density, kind="stable")[: cfg.max_seqs]
        keep.sort()
        seqs = SequenceBatch(feat=seqs.feat[keep], mask=seqs.mask[keep],
                             label=seqs.label[keep], inode=seqs.inode[keep])
    seqs = seqs.pad_to(cfg.max_seqs)
    seq_valid = seqs.mask.any(axis=1)
    sample = dict(g.arrays())
    sample.update(
        seq_feat=seqs.feat.astype(np.float32),
        seq_mask=seqs.mask,
        seq_label=seqs.label.astype(np.float32),
        seq_valid=seq_valid,
        seq_node_idx=_seq_node_index(g, seqs),
    )
    return sample, stats


def sample_spec(cfg: DatasetConfig) -> dict[str, tuple[tuple[int, ...], str]]:
    """The static shape contract of `window_sample`: ``key → (shape, dtype)``
    for every array a window lowered at ``cfg`` carries, derived from the
    config alone — no trace, no lowering, no jax.

    This is the shape authority the deep static pass (`nerrf lint --deep`,
    nerrf_tpu/analysis/programs/) proves the serve ladder's signature
    closure against: admission can only ever produce batches of these
    shapes, so warmup compiling exactly these shapes IS the zero-recompile
    contract.  `tests/test_programs.py` cross-checks it against a real
    `window_sample` output so the two can never drift silently."""
    from nerrf_tpu.data.sequences import SEQ_FEATURE_DIM
    from nerrf_tpu.graph.builder import EDGE_FEATURE_DIM, NODE_FEATURE_DIM

    n, e = cfg.graph.max_nodes, cfg.graph.max_edges
    s, t = cfg.max_seqs, cfg.seq_len
    return {
        "node_feat": ((n, NODE_FEATURE_DIM), "float32"),
        "node_type": ((n,), "int32"),
        "node_aux": ((n,), "int32"),
        "node_mask": ((n,), "bool"),
        "node_key": ((n,), "int64"),
        "node_label": ((n,), "float32"),
        "edge_src": ((e,), "int32"),
        "edge_dst": ((e,), "int32"),
        "edge_feat": ((e, EDGE_FEATURE_DIM), "float32"),
        "edge_mask": ((e,), "bool"),
        "edge_label": ((e,), "float32"),
        "seq_feat": ((s, t, SEQ_FEATURE_DIM), "float32"),
        "seq_mask": ((s, t), "bool"),
        "seq_label": ((s,), "float32"),
        "seq_valid": ((s,), "bool"),
        "seq_node_idx": ((s,), "int32"),
    }


def windows_of_trace(trace: Trace, cfg: DatasetConfig,
                     stats_out: Optional[list] = None) -> List[dict[str, np.ndarray]]:
    """All window samples for one trace.

    ``stats_out``, when given, receives one ``WindowStats`` per *emitted*
    sample so callers (corpus generation) can account for capacity overflow —
    the r2 corpus was silently truncating attack-burst windows at the
    256n/512e defaults, which is exactly the signal a detector needs.
    """
    labels = derive_event_labels(trace)
    ev = trace.events
    if ev.num_valid == 0:
        return []
    valid_ts = ev.ts_ns[ev.valid]
    out = []
    for lo, hi in snapshot_windows(int(valid_ts.min()), int(valid_ts.max()), cfg.graph):
        sample, stats = window_sample(trace, lo, hi, cfg, labels=labels)
        if sample is None:
            continue
        if stats_out is not None:
            stats_out.append(stats)
        out.append(sample)
    return out


def padding_waste_fractions(arrays) -> dict[str, float]:
    """Fraction of padded capacity carrying no real data, per dimension.

    Static shapes mean a padded slot costs exactly as much device compute
    as a real one, so this IS the step-time attribution for bucket sizing:
    train loops stamp it as the ``train_padding_waste_fraction`` gauge and
    the bench artifacts carry it per bucket."""
    masks = (("node", "node_mask"), ("edge", "edge_mask"),
             ("seq", "seq_valid"))
    return {kind: round(float(1.0 - np.asarray(arrays[key]).mean()), 4)
            for kind, key in masks if key in arrays}


def fit_dataset_config(traces: List[Trace],
                       cfg: Optional[DatasetConfig] = None) -> DatasetConfig:
    """A DatasetConfig whose graph capacities fit every window of ``traces``
    with zero drops (GraphConfig.fit_counts bucket policy, corpus-wide max).
    Evaluation datasets must use this: scoring a model on windows that
    silently truncate the attack burst measures the truncation, not the
    model (r2 verdict weak #3)."""
    from nerrf_tpu.graph.builder import measure_window

    cfg = cfg or DatasetConfig()
    max_n = max_e = 0
    for tr in traces:
        ev = tr.events
        if ev.num_valid == 0:
            continue
        ts = ev.ts_ns[ev.valid]
        for lo, hi in snapshot_windows(int(ts.min()), int(ts.max()), cfg.graph):
            n, e = measure_window(ev, lo, hi)
            max_n, max_e = max(max_n, n), max(max_e, e)
    return dataclasses.replace(cfg, graph=cfg.graph.fit_counts(max_n, max_e))


def build_dataset(traces: List[Trace], cfg: Optional[DatasetConfig] = None) -> WindowDataset:
    cfg = cfg or DatasetConfig()
    samples: List[dict[str, np.ndarray]] = []
    for tr in traces:
        samples.extend(windows_of_trace(tr, cfg))
    if not samples:
        raise ValueError("no window samples produced — traces empty?")
    keys = samples[0].keys()
    return WindowDataset({k: np.stack([s[k] for s in samples]) for k in keys})
