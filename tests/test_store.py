"""Trace store: delta compaction, window queries, persistence, and
native ⇄ Python on-disk format compatibility."""

import numpy as np
import pytest

from nerrf_tpu.data import SimConfig, simulate_trace
from nerrf_tpu.graph.store import TraceStore, store_native_available

needs_native = pytest.mark.skipif(
    not store_native_available(), reason="libnerrf_tracestore.so not built"
)

ENGINES = ["python"] + (["native"] if store_native_available() else [])


@pytest.fixture(scope="module")
def trace():
    return simulate_trace(
        SimConfig(num_target_files=6, duration_sec=90.0, attack_start_sec=30.0,
                  min_file_bytes=32 * 1024, max_file_bytes=64 * 1024,
                  chunk_bytes=16 * 1024, benign_rate_hz=10.0, seed=9)
    )


def _open(tmp_path, engine, **kw):
    return TraceStore(tmp_path / "store", use_native=(engine == "native"), **kw)


def _resolved(events, strings, n=200):
    out = []
    for i in np.flatnonzero(events.valid)[:n]:
        i = int(i)
        out.append((
            int(events.ts_ns[i]), int(events.syscall[i]),
            strings.lookup(int(events.comm_id[i])),
            strings.lookup(int(events.path_id[i])),
            strings.lookup(int(events.new_path_id[i])),
            int(events.bytes[i]),
        ))
    return out


@pytest.mark.parametrize("engine", ENGINES)
def test_append_flush_query_roundtrip(tmp_path, trace, engine):
    with _open(tmp_path, engine) as st:
        n = st.append(trace.events, trace.strings)
        assert n == trace.events.num_valid
        assert st.delta_rows == n
        segs = st.flush()
        assert segs >= 3  # 90 s trace over 30 s buckets
        assert st.delta_rows == 0 and st.num_segments == segs

        lo = int(trace.events.ts_ns.min())
        hi = int(trace.events.ts_ns.max()) + 1
        ev, strings = st.query(lo, hi)
        assert ev.num_valid == n
        assert _resolved(ev, strings) == _resolved(
            trace.events.sort_by_time(), trace.strings)


@pytest.mark.parametrize("engine", ENGINES)
def test_window_query_and_bounds(tmp_path, trace, engine):
    with _open(tmp_path, engine) as st:
        st.append(trace.events, trace.strings)
        st.flush()
        lo = int(trace.events.ts_ns.min())
        mid = lo + 30 * 10**9
        ev, _ = st.query(lo, mid)
        mask = (trace.events.ts_ns >= lo) & (trace.events.ts_ns < mid) & trace.events.valid
        assert ev.num_valid == int(mask.sum())
        assert st.query_count(0, lo) == 0
        assert np.all(np.diff(ev.ts_ns) >= 0)


@pytest.mark.parametrize("engine", ENGINES)
def test_reopen_persists_and_compacts(tmp_path, trace, engine):
    ev1 = trace.events.slice(0, len(trace.events) // 2)
    ev2 = trace.events.slice(len(trace.events) // 2, len(trace.events))
    with _open(tmp_path, engine) as st:
        st.append(ev1, trace.strings)
        st.flush()
        segs_before = st.num_segments
    # second half lands in overlapping buckets → same segment count after merge
    with _open(tmp_path, engine) as st:
        st.append(ev2, trace.strings)
        st.flush()
        assert st.num_segments >= segs_before
        lo = int(trace.events.ts_ns.min())
        hi = int(trace.events.ts_ns.max()) + 1
        ev, strings = st.query(lo, hi)
        assert ev.num_valid == trace.events.num_valid
        assert _resolved(ev, strings) == _resolved(
            trace.events.sort_by_time(), trace.strings)


@pytest.mark.parametrize("engine", ENGINES)
def test_unflushed_delta_visible_to_query(tmp_path, trace, engine):
    with _open(tmp_path, engine) as st:
        st.append(trace.events, trace.strings)
        lo = int(trace.events.ts_ns.min())
        hi = int(trace.events.ts_ns.max()) + 1
        assert st.query_count(lo, hi) == trace.events.num_valid


@needs_native
def test_cross_engine_format(tmp_path, trace):
    """A store written natively opens (and reads identically) in Python, and
    vice versa."""
    lo = int(trace.events.ts_ns.min())
    hi = int(trace.events.ts_ns.max()) + 1

    with _open(tmp_path, "native") as st:
        st.append(trace.events, trace.strings)
        st.flush()
        ev_n, str_n = st.query(lo, hi)
    with _open(tmp_path, "python") as st:
        ev_p, str_p = st.query(lo, hi)
        assert _resolved(ev_n, str_n) == _resolved(ev_p, str_p)
        # append more from the python side, then read back natively
        st.append(trace.events, trace.strings)
        st.flush()
    with _open(tmp_path, "native") as st:
        assert st.query_count(lo, hi) == 2 * trace.events.num_valid


@pytest.mark.parametrize("engine", ENGINES)
def test_bucket_size_persists_across_reopen(tmp_path, trace, engine):
    """Reopening with a different bucket_sec must not skip on-disk segments:
    the stored BUCKET wins."""
    lo = int(trace.events.ts_ns.min())
    hi = int(trace.events.ts_ns.max()) + 1
    with _open(tmp_path, engine, bucket_sec=60.0) as st:
        st.append(trace.events, trace.strings)
        st.flush()
        n = st.query_count(lo, hi)
    with _open(tmp_path, engine, bucket_sec=30.0) as st:  # mismatched request
        assert st.bucket_ns == 60 * 10**9
        assert st.query_count(lo, hi) == n
        # mid-window query crossing the would-be-30s boundary
        assert st.query_count(lo + 30 * 10**9, lo + 60 * 10**9) == int(
            ((trace.events.ts_ns >= lo + 30 * 10**9)
             & (trace.events.ts_ns < lo + 60 * 10**9)
             & trace.events.valid).sum())


@pytest.mark.parametrize("engine", ENGINES)
def test_torn_strings_log_tail_recovers(tmp_path, trace, engine):
    """A crash-torn strings.log tail is truncated on reopen; earlier ids and
    later appends stay consistent."""
    with _open(tmp_path, engine) as st:
        st.append(trace.events, trace.strings)
        st.flush()
        n_strings = st.num_strings
    slog = tmp_path / "store" / "strings.log"
    with open(slog, "ab") as f:  # tear: length prefix + partial payload
        f.write(b"\x40\x00\x00\x00partial")
    with _open(tmp_path, engine) as st:
        assert st.num_strings == n_strings
        st.append(trace.events, trace.strings)  # re-interns, no new ids
        st.flush()
        assert st.num_strings == n_strings
        lo = int(trace.events.ts_ns.min())
        hi = int(trace.events.ts_ns.max()) + 1
        ev, strings = st.query(lo, hi)
        # every original event is now present exactly twice, resolving to the
        # same strings as before the tear
        from collections import Counter

        got = Counter(_resolved(ev, strings, n=ev.num_valid))
        want = Counter(_resolved(trace.events, trace.strings,
                                 n=trace.events.num_valid))
        assert got == {k: 2 * v for k, v in want.items()}
    # reopen once more: the log must parse cleanly end-to-end
    with _open(tmp_path, engine) as st:
        assert st.num_strings == n_strings


@pytest.mark.parametrize("engine", ENGINES)
def test_store_feeds_graph_constructor(tmp_path, trace, engine):
    """Store → window query → graph build: the L3 read path."""
    from nerrf_tpu.graph import GraphConfig, build_window_graph

    with _open(tmp_path, engine) as st:
        st.append(trace.events, trace.strings)
        st.flush()
        lo = int(trace.events.ts_ns.min())
        hi = lo + 45 * 10**9
        ev, strings = st.query(lo, hi)
        g, stats = build_window_graph(
            ev, strings, lo, hi, GraphConfig(max_nodes=128, max_edges=256)
        )
        assert stats.num_nodes > 0 and stats.num_edges > 0


@pytest.mark.parametrize("writer", ENGINES)
@pytest.mark.parametrize("reader", ENGINES)
def test_negative_timestamps_cross_engine(tmp_path, writer, reader):
    """Pre-epoch ts_ns produce negative bucket names ('-30000000000--1-0.seg');
    both engines must write AND reopen them identically (the Python parser
    once split on '-' from the left and silently skipped these on reopen)."""
    from nerrf_tpu.schema.events import EventArrays, StringTable

    strings = StringTable()
    recs = [
        {"ts_ns": -25 * 10**9, "pid": 1, "comm": "a", "syscall": "write",
         "path": "/x", "bytes": 1},
        {"ts_ns": -1, "pid": 1, "comm": "a", "syscall": "write",
         "path": "/y", "bytes": 2},
        {"ts_ns": 5 * 10**9, "pid": 2, "comm": "b", "syscall": "openat",
         "path": "/z"},
    ]
    ev = EventArrays.from_records(recs, strings)
    with _open(tmp_path, writer) as st:
        st.append(ev, strings)
        st.flush()
        assert st.query_count(-(10**12), 10**12) == 3
    with _open(tmp_path, reader) as st:
        got, gs = st.query(-(10**12), 10**12)
        assert got.num_valid == 3
        assert _resolved(got, gs) == _resolved(ev.sort_by_time(), strings)
        # appending after reopen must compact into, not orphan, the
        # negative-bucket segments
        st.append(ev, strings)
        st.flush()
        assert st.query_count(-(10**12), 10**12) == 6
