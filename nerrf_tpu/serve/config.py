"""Serving configuration: capacity-bucket ladder, batch-close policy,
backpressure knobs.

The online service admits windows from many streams and packs those that
land in the same capacity bucket into one shared padded device batch, so
the knobs here trade latency (batch-close deadline) against occupancy
(windows per device program launch) against memory (queue bounds).  See
docs/serving.md for the measured guidance.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from nerrf_tpu.graph import GraphConfig
from nerrf_tpu.train.data import DatasetConfig

# (max_nodes, max_edges, max_seqs) capacity bucket.
Bucket = Tuple[int, int, int]

# Default serving ladder: the warmup cross-product ladder
# (pipeline.DETECTOR_WARMUP_BUCKETS) prefixed with the corpus-fitted
# training bucket — live replay/test streams at synthetic density land
# there, while real-eBPF density climbs the warmup rungs.  Every bucket in
# the configured set is compiled at service start; a window that fits NO
# configured bucket is rejected at admission (counted), never compiled —
# that is the no-recompiles-after-warmup contract.
def _default_buckets() -> Tuple[Bucket, ...]:
    from nerrf_tpu.pipeline import DETECTOR_WARMUP_BUCKETS

    return ((256, 512, 128),) + tuple(DETECTOR_WARMUP_BUCKETS)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Knobs of the online detection service (one device program per
    capacity bucket, shared across streams)."""

    # capacity buckets compiled at start; admission rejects windows that
    # fit none of them (no recompiles outside this set, ever)
    buckets: Tuple[Bucket, ...] = dataclasses.field(
        default_factory=_default_buckets)
    # padded device batch shape: every launch is exactly this many window
    # slots (short batches are zero-padded, same as offline model_detect)
    batch_size: int = 8
    # close a bucket's batch when this many windows are pending (0 → use
    # batch_size)...
    target_occupancy: int = 0
    # ...or when the oldest pending window has waited this long, whichever
    # first (the deadline half of the batch-close policy)
    batch_close_sec: float = 0.05
    # per-window end-to-end budget (admit → demux); windows scored after it
    # still deliver, but count into serve_late_windows_total
    window_deadline_sec: float = 2.0
    # per-stream bounded admission queue; overflowing drops that stream's
    # OLDEST pending window (newest evidence wins under sustained overload)
    stream_queue_slots: int = 64
    # bounded alert fan-out queue; a slow alert consumer drops (counted),
    # never blocks the demux thread
    alert_queue_slots: int = 256
    # closed-but-not-demuxed batches allowed per bucket; bounds device-side
    # queueing so one hot bucket cannot monopolize the program queue
    max_inflight_batches: int = 2
    # windowing (mirrors GraphConfig defaults; serving must window exactly
    # like the offline path or parity dies)
    window_sec: float = 45.0
    stride_sec: float = 15.0
    seq_len: int = 100
    min_events: int = 4
    # detection operating point
    agg: str = "max"
    threshold: Optional[float] = None
    # compile every configured bucket at start() (readiness gates on it)
    warmup_on_start: bool = True
    # poison-batch bisection: a failed shared batch is split-and-retried
    # to isolate the offending window(s) instead of dropping every
    # cohabiting stream's windows; 0 disables (whole cohort fails, the
    # pre-bisection behavior)
    bisect_failed_batches: bool = True
    # quarantine: after this many of one stream's windows are PROVEN
    # batch poison (bisection pinned the failure to the window while a
    # sibling scored — an all-fail batch indicts the device, not a
    # stream), the stream itself is quarantined — admission drops its
    # windows (reason="quarantined") so it cannot keep burning device
    # retries for everyone else; 0 disables stream quarantine
    quarantine_strikes: int = 8
    # a quarantined stream is released (strikes reset, journaled) after
    # this long — an upstream fix must not need a pod restart to take
    # effect; 0 makes quarantine permanent for the stream's lifetime
    quarantine_release_sec: float = 300.0
    # scorer watchdog: a single device call stuck longer than this marks
    # the batcher wedged — readiness fails (probes can restart the pod)
    # and leave() stops waiting, instead of every stream hanging on a
    # dead scorer thread; 0 disables
    scorer_wedge_sec: float = 60.0
    # detection-quality plane (nerrf_tpu/quality): trailing score/feature
    # drift sketches compared against the live version's reference
    # profile, exported as nerrf_quality_* gauges + cadenced
    # quality_stats journal records (the flight recorder's quality_drift
    # trigger edge).  Host-side numpy at the demux boundary only; stays
    # a single None check per window until a version with a profile is
    # serving (null-not-fake); False drops the plane for minimal
    # embedders
    quality_monitoring: bool = True
    # SLO-aware shedding (docs/fleet.md): when a stream's bounded queue
    # overflows AND the capacity-headroom predictor says the whole
    # service is under pressure (headroom below shed_headroom_margin),
    # the victim window comes from the stream currently burning the most
    # SLO budget (trailing slo_budget_burn_ratio, flight/slo) instead of
    # from the admitting stream — budget-burners lose evidence first,
    # healthy streams keep bit-parity.  Drop-oldest stays as the
    # intra-stream bound (and as the whole policy when this is False or
    # headroom shows slack: a single stream overrunning its own queue in
    # an otherwise idle fleet is its own problem, not its neighbors')
    slo_aware_shedding: bool = True
    # predicted headroom (in streams) below which shedding goes
    # SLO-ranked; requires devtime_accounting (the headroom predictor)
    shed_headroom_margin: float = 1.0
    # trailing window of the devtime accountant's rate/cost/utilization
    # state (seconds).  The headroom prediction follows traffic shifts at
    # this horizon: production keeps the steady 60s default, while paced
    # soaks (benchmarks/run_fleet_bench.py) shrink it so scale-in slack
    # registers within the bench's wall clock
    devtime_window_sec: float = 60.0
    # device-efficiency plane (nerrf_tpu/devtime): live per-program MFU /
    # utilization / useful-FLOPs gauges and the capacity-headroom
    # predictor, fed from the scorer's measured device seconds.  Host-side
    # numpy only (no extra device work, no recompiles); False drops the
    # plane entirely for minimal embedders
    devtime_accounting: bool = True

    @property
    def occupancy(self) -> int:
        return self.target_occupancy or self.batch_size

    def dataset_config(self, bucket: Bucket) -> DatasetConfig:
        """The DatasetConfig a window lowered into ``bucket`` uses — THE
        shape authority: warmup, admission lowering, and the offline parity
        reference (model_detect with auto_capacity=False) must all build
        through here so the compiled program cache is keyed consistently."""
        n, e, s = bucket
        return DatasetConfig(
            graph=GraphConfig(window_sec=self.window_sec,
                              stride_sec=self.stride_sec,
                              max_nodes=n, max_edges=e),
            seq_len=self.seq_len, max_seqs=s, min_events=self.min_events)


def bucket_tag(bucket: Bucket) -> str:
    """Human/metric label for a bucket, matching warmup_detector's tags."""
    return f"{bucket[0]}n/{bucket[1]}e/{bucket[2]}s"


def select_bucket(need_nodes: int, need_edges: int, need_seqs: int,
                  buckets: Tuple[Bucket, ...]) -> Optional[Bucket]:
    """Smallest configured bucket covering the window's exact needs
    (GraphConfig.fit's power-of-two rungs ARE the ladder entries, so
    first-fit on the capacity-sorted ladder lands on the same bucket fit
    would, without ever minting a shape outside the compiled set).

    Node/edge overflow is a hard miss (lowering would silently drop
    events — the blindness auto-capacity exists to prevent), so a window
    whose graph fits NO configured bucket returns None and the caller must
    reject it, never resize.  Sequence overflow is soft: the lowering keeps
    the ``max_seqs`` *densest* per-file sequences (train/data.py), exactly
    like the offline path at a fixed DatasetConfig — so when no bucket
    covers the file count, the smallest graph-fitting rung still wins (a
    padded slot costs as much device compute as a real one; climbing to an
    8× graph rung to buy sequence slots is the wrong trade), taking the
    most sequence slots available WITHIN that rung."""
    fits_graph = [b for b in sorted(buckets)
                  if b[0] >= need_nodes and b[1] >= need_edges]
    if not fits_graph:
        return None
    for b in fits_graph:
        if b[2] >= need_seqs:
            return b
    rung = fits_graph[0][:2]
    return max((b for b in fits_graph if b[:2] == rung),
               key=lambda b: b[2])
