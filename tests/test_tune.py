"""`nerrf tune`: corpus → fitted cost model → ladder/routing search →
versioned artifact, and the deployment surfaces that consume it.

The golden-corpus fixture is hand-authored (no service, no clock): a
skewed window mix — 80 small windows padding 3× up the static bottom
rung, a 900-node body, an 1800-node tail — with measured per-bucket
costs for the two rungs that served it.  Everything downstream of
`tune()` must be a pure function of this dict.
"""

import dataclasses
import json

import pytest

from nerrf_tpu.tune import (
    ARTIFACT_KIND,
    ARTIFACT_SCHEMA,
    TuneError,
    apply_to_model_config,
    apply_to_serve_config,
    build_artifact,
    demand_points,
    fit_cost_model,
    load_artifact,
    save_artifact,
    tune,
    validate_artifact,
)

# -- fixture corpora ----------------------------------------------------------


def _dist(values):
    from nerrf_tpu.quality.sketch import COUNT_EDGES, Sketch

    sk = Sketch.empty(COUNT_EDGES)
    sk.observe([float(v) for v in values])
    return {"sketch": sk.to_dict(), "total": sk.total, "quantiles": {}}


def golden_corpus():
    nodes = [300] * 80 + [900] * 15 + [1800] * 5
    edges = [2 * n - 10 for n in nodes]
    files = [20] * 80 + [60] * 15 + [120] * 5
    return {
        "schema": 1, "kind": "nerrf_tune_corpus",
        "source": "golden-fixture",
        "windows_observed": 100, "windows_rejected": 0,
        "window_size_distribution": {
            "nodes": _dist(nodes), "edges": _dist(edges),
            "files": _dist(files)},
        "rejected_window_size_distribution": None,
        "bucket_cost": {
            "1024n/2048e/128s": {"windows": 80, "batches": 10,
                                 "device_seconds_mean": 0.04,
                                 "device_seconds_p99": 0.06,
                                 "occupancy_mean": 8.0},
            "2048n/4096e/256s": {"windows": 20, "batches": 4,
                                 "device_seconds_mean": 0.09,
                                 "device_seconds_p99": 0.12,
                                 "occupancy_mean": 5.0}},
        "provenance": {"segments": 1},
    }


# -- the fit + search pipeline ------------------------------------------------


def test_golden_corpus_deterministic_artifact():
    """Same corpus → bit-identical artifact (the ISSUE's determinism
    gate), with the pinned ladder/routing the fixture is golden FOR: a
    3× -tighter 512 rung for the bulk, the measured rungs kept for body
    and tail, per-rung kernel routing replacing the global constant."""
    art = tune(golden_corpus())
    art2 = tune(json.loads(json.dumps(golden_corpus())))
    assert art == art2
    assert art["kind"] == ARTIFACT_KIND and art["schema"] == ARTIFACT_SCHEMA
    assert art["buckets"] == [[512, 1024, 32], [1024, 2048, 128],
                              [2048, 4096, 128]]
    assert dict(art["routing"])[512] == "dense_adj"
    assert set(dict(art["routing"])) == {512, 1024, 2048}
    exp = art["expected"]
    assert (exp["tuned_device_seconds_per_window"]
            < exp["static_device_seconds_per_window"])
    assert exp["improvement"] == pytest.approx(0.2478, abs=2e-3)
    # the measured rung stays evidence-tier "measured"; extrapolated
    # rungs say so
    assert art["fit"]["rung_sources"]["1024n/2048e/128s"] == "measured"
    assert art["fit"]["rung_sources"]["512n/1024e/32s"] == "measured_fit"


def test_static_ladder_is_in_the_candidate_set():
    """tuned can never be worse than static under the fitted model —
    with the corpus's own rungs passed as the static ladder, improvement
    is still >= 0 (the search returns static when nothing beats it)."""
    art = tune(golden_corpus(),
               static_buckets=((1024, 2048, 128), (2048, 4096, 256)))
    assert art["expected"]["improvement"] >= 0.0


def test_thin_corpus_anchors_on_analytic_prior():
    """A rung the corpus never measured but the devtime surface traced
    is priced from the analytic anchor (level) + fitted delta — and the
    artifact SAYS so, so an operator can see which rungs rest on a
    prior rather than evidence."""
    corpus = golden_corpus()
    del corpus["bucket_cost"]["2048n/4096e/256s"]
    analytic = {"1024n/2048e/128s": 2.0e9, "512n/1024e/128s": 6.0e8,
                "2048n/4096e/256s": 7.0e9}
    model = fit_cost_model(corpus, analytic=analytic)
    assert model.analytic_alpha is not None
    assert model.source((512, 1024, 32), "fused") == "analytic_prior"
    assert model.source((1024, 2048, 128),
                        model.auto_mode((1024, 2048, 128))) == "measured"
    art = tune(corpus, analytic=analytic)
    assert "analytic_prior" in art["fit"]["rung_sources"].values()


def test_demand_points_see_single_marginal_tails():
    """The comonotone coupling takes EVERY marginal's bin boundaries: a
    tail that lives only in the edges marginal (attack bursts — few
    nodes, thousands of event edges) must surface as a demand point, or
    the search would propose ladders whose edge capacity rejects real
    traffic."""
    corpus = golden_corpus()
    nodes = [100] * 90 + [150] * 10
    edges = [200] * 90 + [3000] * 10
    files = [20] * 100
    corpus["window_size_distribution"] = {
        "nodes": _dist(nodes), "edges": _dist(edges), "files": _dist(files)}
    points = demand_points(corpus)
    assert any(p.edges >= 3000 and p.nodes <= 256 for p in points)


def test_search_covers_file_demand_instead_of_truncating():
    """Sequence capacity is a search dimension, but seq-truncation is
    priced like rejection: the tuned ladder's tallest seq rung must
    cover the file tail (here 120 files → a 128-seq rung), never "win"
    by silently dropping sequences."""
    art = tune(golden_corpus())
    assert max(b[2] for b in art["buckets"]) >= 128


def test_refusals_are_one_line_tune_errors():
    empty = dict(golden_corpus(), windows_observed=0)
    with pytest.raises(TuneError, match="empty"):
        tune(empty)
    no_cost = dict(golden_corpus(), bucket_cost=None)
    with pytest.raises(TuneError, match="bucket_cost"):
        tune(no_cost)
    with pytest.raises(TuneError, match="kind"):
        tune({"kind": "something_else"})
    for err in (TuneError("a"), ):
        assert "\n" not in str(err)


def test_cli_tune_refuses_empty_corpus(tmp_path, capsys):
    import nerrf_tpu.cli as cli

    p = tmp_path / "corpus.json"
    p.write_text(json.dumps(dict(golden_corpus(), windows_observed=0)))
    assert cli.main(["tune", str(p)]) == 1
    err = capsys.readouterr().err
    assert "refusing to tune" in err


def test_cli_tune_emits_loadable_artifact(tmp_path, repo_root, monkeypatch):
    import nerrf_tpu.cli as cli
    from nerrf_tpu.tune import load_kernel_bench_crossover

    monkeypatch.chdir(repo_root)  # the CLI's default --kernel-bench path
    corpus = tmp_path / "corpus.json"
    corpus.write_text(json.dumps(golden_corpus()))
    out = tmp_path / "tuned.json"
    assert cli.main(["tune", str(corpus), "--out", str(out)]) == 0
    art = load_artifact(out)
    validate_artifact(art)
    kb = load_kernel_bench_crossover(
        "benchmarks/results/kernel_bench_cpu.json")
    assert kb is not None  # the checked-in artifact carries the crossover
    assert art == tune(golden_corpus(), kernel_bench=kb)


# -- artifact contract --------------------------------------------------------


def test_artifact_roundtrip_and_validation(tmp_path):
    art = tune(golden_corpus())
    path = tmp_path / "tuned.json"
    save_artifact(path, art)
    assert load_artifact(path) == art

    with pytest.raises(TuneError):
        load_artifact(tmp_path / "missing.json")
    with pytest.raises(TuneError, match="kind"):
        validate_artifact(dict(art, kind="other"))
    with pytest.raises(TuneError, match="schema"):
        validate_artifact(dict(art, schema=ARTIFACT_SCHEMA + 1))
    with pytest.raises(TuneError):
        validate_artifact(dict(art, buckets=[]))
    with pytest.raises(TuneError):
        validate_artifact(dict(art, routing=[[512, "nonsense_mode"]]))


def test_artifact_applies_to_serve_and_model_config():
    from nerrf_tpu.models import JointConfig
    from nerrf_tpu.serve import ServeConfig

    art = tune(golden_corpus())
    cfg = apply_to_serve_config(art, ServeConfig(batch_size=4))
    assert cfg.batch_size == 4  # only the ladder is replaced
    assert [list(b) for b in cfg.buckets] == art["buckets"]

    joint = apply_to_model_config(art, JointConfig().small)
    assert joint.gnn.routing == tuple(
        (cap, mode) for cap, mode in art["routing"])
    # routing rides the model repr into serve program cache keys: a
    # tuned boot can never collide with an untuned executable
    from nerrf_tpu.compilecache.aot import serve_program_key
    assert (serve_program_key(joint, "512n/1024e/32s")
            != serve_program_key(JointConfig().small, "512n/1024e/32s"))


def test_routing_table_overrides_global_constant():
    from nerrf_tpu.models.graphsage import GraphSAGEConfig

    cfg = GraphSAGEConfig(routing=((512, "dense_adj"), (4096, "fused")))
    assert cfg.resolved_aggregation(300) == "dense_adj"
    assert cfg.resolved_aggregation(2000) == "fused"
    with pytest.raises(ValueError):
        GraphSAGEConfig(routing=((512, "not_a_mode"),))


# -- the tuned ladder through the deployment contracts ------------------------


@pytest.fixture(scope="module")
def tuned_serve_cfg():
    return apply_to_serve_config(tune(golden_corpus()))


def test_tuned_rungs_are_pallas_budget_clean(tuned_serve_cfg):
    """Every tuned rung clears the same per-core VMEM audit `nerrf lint
    --deep` enforces — the search's budget gate is the lint's, so this
    can only fail if they drift apart."""
    from nerrf_tpu.analysis.programs.pallas_budget import PallasBudget
    from nerrf_tpu.graph.builder import NODE_FEATURE_DIM
    from nerrf_tpu.models.graphsage import GraphSAGEConfig
    from nerrf_tpu.ops.pallas_segment import kernel_vmem_blocks

    width = max(GraphSAGEConfig().hidden, NODE_FEATURE_DIM)
    for n, e, _s in tuned_serve_cfg.buckets:
        findings = PallasBudget().audit(kernel_vmem_blocks(n, e, width),
                                        shape=(n, e, width))
        assert findings == [], f"rung {n}n/{e}e over VMEM budget"


def test_tuned_ladder_passes_program_closure(repo_root):
    """The admission/warmup/program-closure contract holds unchanged on
    a tuned ladder: every tuned rung is warmup-reachable and every
    admission signature is inside the warmup-compiled set."""
    from nerrf_tpu.analysis.astutil import Project, collect_files
    from nerrf_tpu.analysis.programs.closure import SignatureClosure

    project = Project(repo_root, collect_files(repo_root, ("nerrf_tpu",)))
    cfg = apply_to_serve_config(tune(golden_corpus()))
    found = SignatureClosure(serve_cfg=cfg, trace_extremes=False).run(project)
    assert found == []


# -- corpus plumbing (satellite: rejected-window recording) -------------------


def test_rejected_windows_flow_into_corpus_and_demand(tmp_path):
    """Admission-rejected window sizes reach the corpus as their own
    distribution (satellite 1) and the search's demand includes them —
    demand beyond the top rung is what pulls a ladder up."""
    from nerrf_tpu.archive import ArchiveConfig, ArchiveWriter, export_tune

    w = ArchiveWriter(ArchiveConfig(out_dir=str(tmp_path / "arch")))
    for _ in range(4):
        w.observe_window("1024n/2048e/128s", nodes=300, edges=600, files=20,
                         stages={"device": 0.01}, e2e_sec=0.05)
    w.observe_rejected(nodes=9000, edges=20000, files=600)
    w.close()
    corpus = export_tune(str(tmp_path / "arch"))
    assert corpus["windows_rejected"] == 1
    assert corpus["rejected_window_size_distribution"] is not None
    points = demand_points(corpus)
    assert any(p.nodes > 4096 for p in points)


def test_build_artifact_fingerprints_corpus():
    c = golden_corpus()
    a = build_artifact(((256, 512, 64),), ((256, "fused"),),
                       {"improvement": 0.0}, {}, corpus=c)
    b = build_artifact(((256, 512, 64),), ((256, "fused"),),
                       {"improvement": 0.0}, {},
                       corpus=dict(c, windows_observed=101))
    assert a["corpus_fingerprint"] != b["corpus_fingerprint"]
    validate_artifact(a)


def test_aot_export_stamps_tuned_manifest(tmp_path):
    """`export_executables` records the tuned stamp in the manifest so
    an AOT cache dir self-describes which artifact produced it."""
    from nerrf_tpu.compilecache import aot

    stamp = {"corpus_fingerprint": "abc123", "routing": [[512, "fused"]]}
    art = tune(golden_corpus())
    assert art["corpus_fingerprint"]
    # manifest plumbing only — no compile: exercised via the helper that
    # assembles the manifest dict if exposed, else via signature presence
    import inspect
    assert "tuned_stamp" in inspect.signature(
        aot.export_executables).parameters
    assert "tuned" in inspect.signature(
        aot.export_for_checkpoint).parameters


def test_save_artifact_atomic_under_crash(tmp_path, monkeypatch):
    """Serve boots from this file (`--tuned`): a crash mid-write must
    never leave a torn JSON on the final name — the stage-and-replace
    publish keeps the previous artifact fully loadable."""
    from nerrf_tpu.tune import artifact as am

    path = tmp_path / "tuned.json"
    art = tune(golden_corpus())
    save_artifact(path, art)

    real_write = am.Path.write_text

    def crashing_write(self, text, *a, **kw):
        if self.name.endswith(".tmp"):
            real_write(self, text[: len(text) // 2], *a, **kw)
            raise OSError("disk full mid-publish")
        return real_write(self, text, *a, **kw)

    monkeypatch.setattr(am.Path, "write_text", crashing_write)
    newer = dict(art, corpus_fingerprint="f" * 16)
    with pytest.raises(OSError):
        save_artifact(path, newer)
    monkeypatch.undo()
    # the published artifact is the OLD one, intact and valid
    assert load_artifact(path) == art
    # and the survivor is still replaceable once the disk recovers
    save_artifact(path, newer)
    assert load_artifact(path) == newer
