"""Firecracker API client (native + fallback) against a fake unix-socket
Firecracker: request framing, workflow sequence, error surfacing."""

import http.server
import json
import socketserver
import threading

import pytest

from nerrf_tpu.rollback.fc import FirecrackerAPI, fc_native_available

ENGINES = ["python"] + (["native"] if fc_native_available() else [])


class _FakeFirecracker:
    """Unix-socket HTTP server recording the API calls it receives."""

    def __init__(self, sock_path):
        self.calls = []
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _record(self, method):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length).decode() if length else ""
                outer.calls.append(
                    (method, self.path, json.loads(body) if body else None))

            def _reply(self, status, payload=b""):
                self.send_response(status)
                if payload:
                    self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.send_header("Connection", "close")
                self.end_headers()
                if payload:
                    self.wfile.write(payload)

            def do_GET(self):  # noqa: N802
                self._record("GET")
                self._reply(200, json.dumps(
                    {"id": "fake-fc", "state": "Running",
                     "vmm_version": "1.0-fake"}).encode())

            def do_PUT(self):  # noqa: N802
                self._record("PUT")
                if self.path == "/bad":
                    self._reply(400, b'{"fault_message": "nope"}')
                else:
                    self._reply(204)

            def do_PATCH(self):  # noqa: N802
                self._record("PATCH")
                self._reply(204)

            def log_message(self, *a):
                del a

        class Server(socketserver.ThreadingMixIn, socketserver.UnixStreamServer):
            daemon_threads = True

            def get_request(self):
                request, _ = super().get_request()
                # BaseHTTPRequestHandler wants a (host, port)-ish client addr
                return request, ("127.0.0.1", 0)

        self.server = Server(str(sock_path), Handler)
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)
        self.thread.start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture
def fake_fc(tmp_path):
    sock = tmp_path / "fc.sock"
    srv = _FakeFirecracker(sock)
    yield sock, srv
    srv.close()


@pytest.mark.parametrize("engine", ENGINES)
def test_workflow_sequence(fake_fc, engine):
    sock, srv = fake_fc
    api = FirecrackerAPI(str(sock), use_native=(engine == "native"))
    info = api.describe()
    assert info["id"] == "fake-fc"
    api.configure_machine(vcpus=2, mem_mib=512)
    api.set_boot_source("/img/vmlinux")
    api.add_drive("rootfs", "/img/rootfs.ext4", root=True)
    api.start()
    api.pause()
    api.snapshot("/snap/vmstate", "/snap/mem")

    methods = [(m, p) for m, p, _ in srv.calls]
    assert methods == [
        ("GET", "/"),
        ("PUT", "/machine-config"),
        ("PUT", "/boot-source"),
        ("PUT", "/drives/rootfs"),
        ("PUT", "/actions"),
        ("PATCH", "/vm"),
        ("PUT", "/snapshot/create"),
    ]
    bodies = {p: b for _, p, b in srv.calls if b}
    assert bodies["/machine-config"] == {"vcpu_count": 2, "mem_size_mib": 512}
    assert bodies["/drives/rootfs"]["is_root_device"] is True
    assert bodies["/actions"] == {"action_type": "InstanceStart"}


@pytest.mark.parametrize("engine", ENGINES)
def test_api_error_is_surfaced(fake_fc, engine):
    sock, _ = fake_fc
    api = FirecrackerAPI(str(sock), use_native=(engine == "native"))
    with pytest.raises(RuntimeError, match="HTTP 400"):
        api._expect("PUT", "/bad", {"x": 1})


@pytest.mark.parametrize("engine", ENGINES)
def test_connect_failure(tmp_path, engine):
    api = FirecrackerAPI(str(tmp_path / "absent.sock"),
                         use_native=(engine == "native"))
    with pytest.raises(OSError):
        api.request("GET", "/")
