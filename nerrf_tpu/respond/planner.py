"""Batched incident planning: vmapped DeviceMCTS over incident roots.

`DeviceMCTS` runs one search as one XLA program; at serve scale incidents
arrive faster than sequential `plan()` calls amortize their dispatch, and
a pod chip sits mostly idle during any single small search.  The Anakin
answer (Podracer, arXiv 2104.06272) is to colocate and *vectorize*: vmap
the whole select→expand→evaluate→backup program over a batch of incident
root states, so B searches advance in lockstep inside one executable.

This module adds NO search logic.  `_batched_programs` wraps the existing
`_programs` closures — the single-incident planner's exact init/search
functions — in ``jax.jit(jax.vmap(...))``, with the per-incident `_Ctx`
batched and the simulation count broadcast.  A batch slot is therefore
bit-for-bit the single planner's computation with a leading batch axis,
which is what makes the bench's B=1 parity gate meaningful.

Compile discipline mirrors serve's bucket ladder: incidents are padded
into (file, proc) shape buckets by `DeviceMCTS` itself, batches are padded
up a fixed batch-slot ladder, and each (bucket, slot) executable resolves
through the `CompileCache` (`respond_program_key`) at warmup — zero
recompiles after warmup, counted honestly by `recompiles` when traffic
somehow escapes the ladder (admission clamps make that a bug, not a
tail case).  Pad slots re-run the first incident's context on a
pre-stopped root state: terminal at the root, the search visits it M
times without growing the tree — constant work, no output.
"""

from __future__ import annotations

import functools
import time
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nerrf_tpu.planner.device_mcts import DeviceMCTS, _Ctx, _programs
from nerrf_tpu.planner.domain import UndoDomain, UndoPlan
from nerrf_tpu.planner.mcts import MCTSConfig, extract_plan
from nerrf_tpu.utils import sync_result


def respond_program_key(F: int, P: int, batch: int, cfg: MCTSConfig,
                        max_steps: float = 64.0) -> dict:
    """Caller-side CompileCache key material for one (bucket, slot) search
    executable — the respond counterpart of aot.serve_program_key.  The
    aval signature already pins shapes; this pins the search *semantics*
    baked into the traced program as constants (PUCT exploration weight,
    the episode step-horizon, sim budget via M) so a config change can
    never reuse a stale executable.  Audited by the `cache-key-coverage`
    deep rule (analysis/programs/entries.py: respond_search)."""
    return {
        "kind": "respond_search",
        "bucket": f"{F}f/{P}p",
        "batch": int(batch),
        "sims": int(cfg.num_simulations),
        "c_puct": float(cfg.c_puct),
        "max_steps": float(max_steps),
    }


@functools.lru_cache(maxsize=32)
def _batched_programs(F: int, P: int, M: int, max_steps: float,
                      c_puct: float, value_apply, batch: int):
    """(init_batch, search_batch) for one (shape-bucket, value-fn, B)
    signature: the single-incident `_programs` closures vmapped over the
    leading incident axis.  ``value_params`` ride unbatched (one shared
    value function for the whole tier — per-incident fitting is an
    offline luxury the online path does not pay for)."""
    base = _programs(F, P, M, max_steps, c_puct, value_apply)
    ctx_axes = _Ctx(file_scores=0, file_loss=0, proc_scores=0, prior=0,
                    real=0, value_params=None)
    init_b = jax.jit(jax.vmap(base.init_tree))
    search_b = jax.jit(jax.vmap(base.search_chunk,
                                in_axes=(0, None, ctx_axes)))
    return init_b, search_b


def _stack_ctx(ctxs: Sequence[_Ctx]) -> _Ctx:
    """Batch per-incident contexts along a new leading axis; value params
    are shared (identical object per _batched_programs contract), so the
    first incident's ride along unbatched."""
    return _Ctx(
        file_scores=jnp.stack([c.file_scores for c in ctxs]),
        file_loss=jnp.stack([c.file_loss for c in ctxs]),
        proc_scores=jnp.stack([c.proc_scores for c in ctxs]),
        prior=jnp.stack([c.prior for c in ctxs]),
        real=jnp.stack([c.real for c in ctxs]),
        value_params=ctxs[0].value_params,
    )


def _bucket_dims(d: UndoDomain) -> Tuple[int, int, float]:
    """(Fp, Pp, max_steps): the compile-bucket signature of one domain,
    without paying a DeviceMCTS construction to learn it."""
    return (DeviceMCTS._bucket(d.F, DeviceMCTS.FILE_BUCKET_FLOOR),
            DeviceMCTS._bucket(d.P, DeviceMCTS.PROC_BUCKET_FLOOR),
            float(d.max_steps))


def _pack_batch(domains: Sequence[UndoDomain], F: int, P: int,
                pad_to: int, value_params) -> Tuple[jnp.ndarray, _Ctx]:
    """Host-side wave assembly: (padded roots [B, D], batched _Ctx) built
    directly from the domains in numpy, one device transfer per field —
    the Anakin discipline (pack on host, cross the link once).  Per-lane
    layout is bit-identical to DeviceMCTS.__post_init__/_pad_state (pad
    files born done, pad procs born killed, zero scores — the parity
    tests pin this).  Lanes past ``len(domains)`` repeat lane 0 with the
    root pre-stopped: terminal at node 0, so a pad lane's search visits a
    dead root M times and grows nothing — constant work, no output."""
    n, B, D = len(domains), pad_to, F + P + 3
    fs = np.zeros((B, F), np.float32)
    fl = np.zeros((B, F), np.float32)
    ps = np.zeros((B, P), np.float32)
    pr = np.zeros((B, F + P + 1), np.float32)
    real = np.zeros((B, 2), np.float32)
    roots = np.ones((B, D), np.float32)
    for i in range(B):
        d = domains[i] if i < n else domains[0]
        f, p = d.F, d.P
        fs[i, :f] = d.file_scores
        fl[i, :f] = d.file_loss_mb
        ps[i, :p] = d.proc_scores
        dp = d.priors()
        pr[i, :f] = dp[:f]
        pr[i, F:F + p] = dp[f:f + p]
        pr[i, -1] = dp[-1]
        real[i] = (f, p)
        s = d.initial_state()
        roots[i, :f] = s[:f]
        roots[i, F:F + p] = s[f:f + p]
        roots[i, F + P:] = s[f + p:]
    roots[n:, -1] = 1.0  # pad lanes: root already stopped
    ctx = _Ctx(file_scores=jnp.asarray(fs), file_loss=jnp.asarray(fl),
               proc_scores=jnp.asarray(ps), prior=jnp.asarray(pr),
               real=jnp.asarray(real), value_params=value_params)
    return jnp.asarray(roots), ctx


def _action_map(F: int, P: int, f: int, p: int) -> np.ndarray:
    """Domain action index → padded action index (files | procs | stop) —
    DeviceMCTS._action_map without the instance."""
    return np.concatenate(
        [np.arange(f), F + np.arange(p), [F + P]]).astype(np.int64)


class BatchedDeviceMCTS:
    """The respond tier's planner: one vmapped search program per batch
    slot, warmed through the CompileCache at start.

    ``value_apply``/``value_params`` follow DeviceMCTS's preferred pure
    form and are SHARED across all incidents in a batch (None = the
    closed-form heuristic, the online default — bit-par with the offline
    planner run the same way)."""

    def __init__(self, cfg: Optional[MCTSConfig] = None,
                 batch_slots: Sequence[int] = (1, 2, 4, 8),
                 value_apply=None, value_params=None,
                 cache=None, registry=None) -> None:
        if registry is None:
            from nerrf_tpu.observability import DEFAULT_REGISTRY

            registry = DEFAULT_REGISTRY
        self.cfg = cfg or MCTSConfig()
        self.batch_slots = tuple(sorted(set(int(b) for b in batch_slots)))
        if not self.batch_slots or self.batch_slots[0] < 1:
            raise ValueError(f"bad batch_slots: {batch_slots}")
        self.value_apply = value_apply
        self.value_params = value_params if value_apply is not None else ()
        self._cache = cache
        self._reg = registry
        # (F, P, max_steps, B) → compiled search executable (or live jit)
        self._warmed: dict = {}
        # (F, P, max_steps) → prototype DeviceMCTS for program resolution;
        # plan_batch never constructs per-incident planners (host packing
        # in _pack_batch is the whole per-incident cost)
        self._protos: dict = {}
        self._recompiles = 0
        self.warmup_info: List[dict] = []

    # -- warmup ------------------------------------------------------------

    def _slot_for(self, n: int) -> int:
        for b in self.batch_slots:
            if n <= b:
                return b
        return self.batch_slots[-1]

    def _programs_for(self, dm: DeviceMCTS, B: int):
        """Resolve (init, search) for one prototype planner + batch slot,
        through the CompileCache when one is bound."""
        dims = dm._dims
        key = (dims["F"], dims["P"], float(dm.domain.max_steps), B)
        init_b, search_b = _batched_programs(
            dims["F"], dims["P"], self.cfg.num_simulations + 1,
            float(dm.domain.max_steps), float(self.cfg.c_puct),
            dm.value_apply, B)
        if key in self._warmed:
            return init_b, self._warmed[key]
        search = search_b
        if self._cache is not None:
            roots = jnp.stack(
                [jnp.asarray(dm._pad_state(dm.domain.initial_state()))] * B)
            tree = init_b(roots)
            ctx = _stack_ctx([dm._ctx] * B)
            search, info = self._cache.load_or_compile(
                search_b, (tree, jnp.asarray(1, jnp.int32), ctx),
                program=f"respond_search[{dims['F']}f/{dims['P']}p/b{B}]",
                extra=respond_program_key(dims["F"], dims["P"], B, self.cfg,
                                          float(dm.domain.max_steps)))
            self.warmup_info.append(
                {"bucket": f"{dims['F']}f/{dims['P']}p", "batch": B,
                 "source": info.source, "seconds": round(info.seconds, 3)})
        return init_b, search

    def warmup_for(self, num_files: int, num_procs: int,
                   max_steps: int = 64) -> float:
        """Compile (or cache-load) every batch slot's executable for the
        bucket covering (num_files, num_procs); returns seconds.  The
        resident daemon's boot step — after this, planning any incident
        the admission clamps allow hits a warm program."""
        t0 = time.perf_counter()
        dm = DeviceMCTS.warmup_for(
            num_files, num_procs, self.cfg, value_apply=self.value_apply,
            value_params=self.value_params, max_steps=max_steps)
        dims = dm._dims
        self._protos[(dims["F"], dims["P"],
                      float(dm.domain.max_steps))] = dm
        for B in self.batch_slots:
            init_b, search = self._programs_for(dm, B)
            roots = jnp.stack(
                [jnp.asarray(dm._pad_state(dm.domain.initial_state()))] * B)
            tree = init_b(roots)
            ctx = _stack_ctx([dm._ctx] * B)
            # execute one 1-sim chunk: compile-AND-run proof, same gate as
            # DeviceMCTS.warmup
            # nerrflint: ok[sync-in-hot-loop] deliberate warmup fence — each batch slot's compile must complete before serving, one sync per slot at startup only
            sync_result(search(tree, jnp.asarray(1, jnp.int32), ctx))
            self._warmed[(dims["F"], dims["P"],
                          float(dm.domain.max_steps), B)] = search
        return time.perf_counter() - t0

    @property
    def recompiles(self) -> int:
        """Searches that ran outside the warmed (bucket, slot) set."""
        return self._recompiles

    # -- planning ----------------------------------------------------------

    def plan_batch(self, domains: Sequence[UndoDomain]) -> List[UndoPlan]:
        """Plan every domain in one (or a few) vmapped searches.

        All domains must land in ONE (file, proc) shape bucket — the
        admission clamps guarantee it for router traffic; mixed-bucket
        callers get a loud error rather than a silent recompile storm.
        Counts above the largest batch slot are processed in slot-sized
        waves."""
        if not domains:
            return []
        dims0 = _bucket_dims(domains[0])
        for d in domains[1:]:
            got = _bucket_dims(d)
            if got != dims0:
                raise ValueError(
                    f"mixed shape buckets in one batch: {got} vs {dims0} "
                    "(clamp domains at admission — RespondConfig.max_files/"
                    "max_procs)")
        out: List[UndoPlan] = []
        top = self.batch_slots[-1]
        for i in range(0, len(domains), top):
            out.extend(self._plan_wave(list(domains[i:i + top]), dims0))
        return out

    def _plan_wave(self, domains: List[UndoDomain],
                   dims: Tuple[int, int, float]) -> List[UndoPlan]:
        cfg = self.cfg
        F, P, max_steps = dims
        n = len(domains)
        B = self._slot_for(n)
        key = (F, P, max_steps, B)
        if key not in self._warmed:
            # honesty counter: this wave compiles a fresh executable — the
            # zero-recompile contract says warmup should have covered it
            self._recompiles += 1
            self._reg.counter_inc(
                "respond_recompiles_total",
                help="batched searches that ran outside the warmed "
                     "(bucket, batch-slot) ladder — should stay 0 after "
                     "warmup")
        proto = self._protos.get((F, P, max_steps))
        if proto is None:
            proto = DeviceMCTS(domains[0], cfg,
                               value_apply=self.value_apply,
                               value_params=self.value_params)
            self._protos[(F, P, max_steps)] = proto
        init_b, search = self._programs_for(proto, B)

        t0 = time.perf_counter()
        vp = () if self.value_params is None else self.value_params
        roots, ctx = _pack_batch(domains, F, P, B, vp)
        tree = init_b(roots)

        # identical chunk schedule to DeviceMCTS.plan — REQUIRED for the
        # B=1 parity contract (a different slicing of num_simulations
        # would be a different fori_loop trip sequence)
        done = 0
        chunk = min(128, cfg.num_simulations)
        while done < cfg.num_simulations:
            m = min(chunk, cfg.num_simulations - done)
            tree = search(tree, jnp.asarray(m, jnp.int32), ctx)
            done += m
            if time.perf_counter() - t0 > cfg.timeout_seconds:
                break
        tree = jax.device_get(tree)
        elapsed = time.perf_counter() - t0

        plans: List[UndoPlan] = []
        for i, d in enumerate(domains):
            amap = _action_map(F, P, d.F, d.P)
            plans.append(extract_plan(
                d, cfg,
                children=tree.children[i][:, amap],
                visits=tree.visits[i], value_sum=tree.value_sum[i],
                is_terminal=tree.terminal[i], expanded=tree.expanded[i],
                sims=int(tree.visits[i][0]), elapsed=elapsed, root=0))
        return plans
