"""Fused bidirectional SAGE-aggregation kernel vs the XLA composition.

Interpret mode on the CPU mesh (tests/conftest.py), like test_pallas_ops.py;
the compiled Mosaic path is exercised on real TPU by the queue's chip-gated
test leg.  The reference semantics throughout:

    out[n] = Σ_{e: dst(e)=n} ŵf(e)·msg[src(e)] + Σ_{e: src(e)=n} ŵr(e)·msg[dst(e)]

with pre-normalized weights, over the builder's dst-sorted edge list and the
model's src-sorted view.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nerrf_tpu.ops import pallas_segment, segment


@pytest.fixture(autouse=True)
def _clean_switchboard():
    yield
    pallas_segment.unregister()  # also disables the TPU auto-probe


def _graph(E, N, seed, zero_frac=0.0):
    """Random graph in both sorted views + both weight vectors in both
    orders — the full sage_aggregate argument tuple (minus msg)."""
    rng = np.random.default_rng(seed)
    src = rng.integers(0, N, E).astype(np.int32)
    dst = np.sort(rng.integers(0, N, E)).astype(np.int32)
    w = rng.uniform(0.1, 1.0, E).astype(np.float32)
    if zero_frac:
        w[rng.random(E) < zero_frac] = 0.0  # masked edges
    order = np.argsort(src)
    wf_d = (w * rng.uniform(0.5, 2.0, E)).astype(np.float32)
    wr_d = (w * rng.uniform(0.5, 2.0, E)).astype(np.float32)
    return tuple(jnp.asarray(a) for a in (
        dst, src, src[order], dst[order],
        wf_d, wf_d[order], wr_d[order], wr_d))


def _ref(msg, edges, n):
    dst, src, src_s, dst_s, wf_d, _wf_s, wr_s, _wr_d = edges
    m = msg.astype(jnp.float32)
    fwd = jax.ops.segment_sum(wf_d[:, None] * jnp.take(m, src, axis=0),
                              dst, num_segments=n)
    rev = jax.ops.segment_sum(wr_s[:, None] * jnp.take(m, dst_s, axis=0),
                              src_s, num_segments=n)
    return fwd + rev


def _rand(shape, seed):
    return jnp.asarray(np.random.default_rng(seed).normal(size=shape),
                       jnp.float32)


@pytest.mark.parametrize("E,N,F", [(37, 11, 5), (128, 128, 128),
                                   (300, 150, 33), (513, 257, 130)])
def test_fused_matches_xla_composition(E, N, F):
    edges = _graph(E, N, seed=E)
    msg = _rand((N, F), E + 1)
    got = pallas_segment.sage_aggregate_fused(msg, *edges, N, True)
    np.testing.assert_allclose(got, _ref(msg, edges, N),
                               rtol=1e-5, atol=1e-5)


def test_fused_masked_edges_contribute_nothing():
    # zero-weight (masked) edges must vanish even though their rows are
    # still gathered inside the kernel
    edges = _graph(200, 64, seed=3, zero_frac=0.4)
    msg = _rand((64, 20), 4)
    np.testing.assert_allclose(
        pallas_segment.sage_aggregate_fused(msg, *edges, 64, True),
        _ref(msg, edges, 64), rtol=1e-5, atol=1e-5)


def test_fused_empty_segments_are_exactly_zero():
    # every edge lands on nodes {0, 1}; all other rows must be exact zeros
    # (pre-normalized weights: no eps-division residue)
    E, N, F = 40, 50, 7
    rng = np.random.default_rng(5)
    src = rng.integers(0, 2, E).astype(np.int32)
    dst = np.sort(rng.integers(0, 2, E)).astype(np.int32)
    w = rng.uniform(0.1, 1.0, E).astype(np.float32)
    order = np.argsort(src)
    edges = tuple(jnp.asarray(a) for a in (
        dst, src, src[order], dst[order], w, w[order], w[order], w))
    out = pallas_segment.sage_aggregate_fused(_rand((N, F), 6), *edges, N, True)
    assert float(jnp.max(jnp.abs(out[2:]))) == 0.0
    np.testing.assert_allclose(out, _ref(_rand((N, F), 6), edges, N),
                               rtol=1e-5, atol=1e-5)


def test_fused_degenerate_shapes():
    out = pallas_segment.sage_aggregate_fused(
        jnp.zeros((5, 4), jnp.float32),
        *[jnp.zeros((0,), jnp.int32)] * 4,
        *[jnp.zeros((0,), jnp.float32)] * 4, 5, True)
    assert out.shape == (5, 4) and float(jnp.sum(jnp.abs(out))) == 0.0


def test_fused_vjp_matches_xla_grad():
    edges = _graph(150, 40, seed=7, zero_frac=0.2)
    msg = _rand((40, 9), 8)

    g = jax.grad(lambda m: jnp.sum(
        pallas_segment.sage_aggregate_fused(m, *edges, 40, True) ** 2))(msg)
    want = jax.grad(lambda m: jnp.sum(_ref(m, edges, 40) ** 2))(msg)
    np.testing.assert_allclose(g, want, rtol=1e-4, atol=1e-4)


def test_fused_under_vmap_and_grad():
    # the training path vmaps the model over the window batch — the fused
    # kernel (scalar-prefetch grid + VMEM scratch) must batch and
    # differentiate there
    B, E, N, F = 3, 150, 40, 9
    per = [_graph(E, N, seed=10 + b) for b in range(B)]
    edges = tuple(jnp.stack([p[i] for p in per]) for i in range(8))
    msg = _rand((B, N, F), 20)

    f = jax.vmap(lambda m, *e: pallas_segment.sage_aggregate_fused(
        m, *e, N, True))
    rf = jax.vmap(lambda m, *e: _ref(m, e, N))
    np.testing.assert_allclose(f(msg, *edges), rf(msg, *edges),
                               rtol=1e-5, atol=1e-5)
    g = jax.grad(lambda m: jnp.sum(f(m, *edges) ** 2))(msg)
    want = jax.grad(lambda m: jnp.sum(rf(m, *edges) ** 2))(msg)
    np.testing.assert_allclose(g, want, rtol=1e-4, atol=1e-4)


def test_switchboard_routes_and_reports_pallas_fused(monkeypatch):
    pallas_segment.register(interpret=True)
    assert segment.active_impls()["sage_aggregate"] == "pallas_fused"
    calls = []
    real = segment._SAGE_FUSED_IMPL
    monkeypatch.setattr(segment, "_SAGE_FUSED_IMPL",
                        lambda *a: calls.append(1) or real(*a))
    edges = _graph(60, 30, seed=30)
    msg = _rand((30, 8), 31)
    got = segment.sage_aggregate(msg, *edges, 30)
    assert calls, "registered fused kernel must serve sage_aggregate"
    np.testing.assert_allclose(got, _ref(msg, edges, 30),
                               rtol=1e-5, atol=1e-5)

    segment.use_pallas(None, None)
    assert segment.active_impls()["sage_aggregate"] == "xla"
    np.testing.assert_allclose(segment.sage_aggregate(msg, *edges, 30),
                               _ref(msg, edges, 30), rtol=1e-5, atol=1e-5)


def test_graphsage_fused_mode_through_pallas_kernel():
    """The whole model in aggregation='fused' with the interpret-mode Pallas
    kernel registered must match the segment oracle — the end-to-end wiring
    (pre-normalized views, c_sum/s_f/s_r decomposition, bf16 casts), not
    just the bare op."""
    from nerrf_tpu.data import SimConfig, simulate_trace
    from nerrf_tpu.graph import GraphConfig
    from nerrf_tpu.models.graphsage import GraphSAGEConfig, GraphSAGET
    from nerrf_tpu.train.data import DatasetConfig, build_dataset

    tr = simulate_trace(SimConfig(duration_sec=60.0, attack=True,
                                  attack_start_sec=20.0, num_target_files=4,
                                  benign_rate_hz=20.0, seed=2))
    ds = build_dataset([tr], DatasetConfig(
        graph=GraphConfig(window_sec=45.0, stride_sec=20.0,
                          max_nodes=64, max_edges=128),
        seq_len=24, max_seqs=32))
    gin = ("node_feat", "node_type", "node_aux", "node_mask", "edge_src",
           "edge_dst", "edge_feat", "edge_mask")
    args = tuple(np.asarray(ds.arrays[k][0]) for k in gin)
    cfg = GraphSAGEConfig(hidden=32, num_layers=2, dropout=0.0,
                          dtype=jnp.float32, aggregation="segment")
    model_s = GraphSAGET(cfg)
    params = model_s.init(jax.random.PRNGKey(0), *args)["params"]
    want = model_s.apply({"params": params}, *args)

    pallas_segment.register(interpret=True)
    model_f = GraphSAGET(dataclasses.replace(cfg, aggregation="fused"))
    got = model_f.apply({"params": params}, *args)
    for k in ("edge_logit", "node_logit"):
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want[k]),
                                   rtol=1e-2, atol=1e-3)

    # and the TRAINING path: parameter gradients through the registered
    # kernel's custom VJP (the adjoint's wf_s/wr_d weight exchange) must
    # match the segment oracle — a view-wiring bug that keeps the forward
    # right but breaks the adjoint would only ever surface here
    def loss(model):
        return lambda p: jnp.sum(
            model.apply({"params": p}, *args)["node_logit"] ** 2)

    g_fused = jax.grad(loss(model_f))(params)
    pallas_segment.unregister()
    g_seg = jax.grad(loss(model_s))(params)
    errs = jax.tree_util.tree_map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), g_seg, g_fused)
    assert max(jax.tree_util.tree_leaves(errs)) < 1e-3, errs
