"""Evaluation metrics for the detector quality gates.

The reference's CI gates are ROC-AUC ≥ 0.90 for the GNN
(`/root/reference/ROADMAP.md:26,69`) and F1 ≥ 0.95 for the LSTM
(`architecture.mdx:59`).  Implemented in numpy (host-side eval; scores come
back from device as flat arrays).
"""

from __future__ import annotations

import numpy as np


def roc_auc(labels: np.ndarray, scores: np.ndarray) -> float:
    """Rank-based AUC (Mann-Whitney).  Returns 0.5 for degenerate inputs."""
    labels = np.asarray(labels).astype(np.float64).ravel()
    scores = np.asarray(scores).astype(np.float64).ravel()
    pos = labels > 0.5
    n_pos = int(pos.sum())
    n_neg = len(labels) - n_pos
    if n_pos == 0 or n_neg == 0:
        return 0.5
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty(len(scores), np.float64)
    ranks[order] = np.arange(1, len(scores) + 1)
    # midrank ties
    sorted_scores = scores[order]
    i = 0
    while i < len(sorted_scores):
        j = i
        while j + 1 < len(sorted_scores) and sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        if j > i:
            ranks[order[i : j + 1]] = (i + j) / 2.0 + 1.0
        i = j + 1
    rank_sum = ranks[pos].sum()
    return float((rank_sum - n_pos * (n_pos + 1) / 2.0) / (n_pos * n_neg))


def f1_score(labels: np.ndarray, preds: np.ndarray) -> float:
    labels = np.asarray(labels).ravel() > 0.5
    preds = np.asarray(preds).ravel() > 0.5
    tp = int((labels & preds).sum())
    fp = int((~labels & preds).sum())
    fn = int((labels & ~preds).sum())
    if tp == 0:
        return 0.0
    prec = tp / (tp + fp)
    rec = tp / (tp + fn)
    return float(2 * prec * rec / (prec + rec))


def best_f1(labels: np.ndarray, scores: np.ndarray, n_thresholds: int = 101):
    """Best F1 over a threshold sweep; returns (f1, threshold)."""
    scores = np.asarray(scores).ravel()
    if len(scores) == 0:
        return 0.0, 0.5
    lo, hi = float(scores.min()), float(scores.max())
    best, best_t = 0.0, 0.5
    for t in np.linspace(lo, hi, n_thresholds):
        f = f1_score(labels, scores > t)
        if f > best:
            best, best_t = f, float(t)
    return best, best_t
