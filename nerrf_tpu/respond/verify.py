"""Plan verification: replay before surfacing.

Every `UndoPlan` the batched planner emits is rehearsed through the
rollback sandbox gate (`rollback.sandbox.SandboxGate`: clone the victim
tree, optionally replay the captured trace for determinism, execute the
plan against the clone, diff against the pre-attack manifest) BEFORE it is
surfaced to any consumer.  A plan that cannot be verified — no snapshot
context bound, replay divergence, residual diff, failed restores, even an
empty plan — is quarantined with a journaled ``plan_rejected`` reason and
never surfaced.  Fail closed: an unverifiable plan executed against a live
host is exactly the blast radius this tier exists to prevent.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Dict, Optional, Tuple

from nerrf_tpu.planner.domain import UndoPlan
from nerrf_tpu.rollback.sandbox import GateResult, SandboxGate
from nerrf_tpu.rollback.store import Manifest, SnapshotStore


@dataclasses.dataclass
class VerifyContext:
    """The graph-snapshot handle an incident carries: everything the gate
    needs to rehearse a plan for that incident's stream.

    ``leaves_behind`` is the per-scenario residue policy — attack
    artifacts the plan intentionally does not remove (ransom notes,
    staging blobs, dropped cron entries).  File *names*, matched against
    the diff's extra entries exactly like the gate's default ransom-note
    policy."""

    store: SnapshotStore
    manifest: Manifest
    victim_root: Path
    trace: Optional[object] = None
    ransom_ext: str = ".lockbit3"
    leaves_behind: Tuple[str, ...] = ("README_LOCKBIT.txt",)


@dataclasses.dataclass
class VerifiedPlan:
    """The verifier's output for one incident: surfaced iff verified."""

    incident: object
    plan: UndoPlan
    verified: bool
    reason: str
    gate: Optional[GateResult] = None

    def to_dict(self) -> Dict:
        return {
            "stream": self.incident.stream,
            "trace_id": self.incident.trace_id,
            "verified": self.verified,
            "reason": self.reason,
            "actions": len(self.plan.actions),
            "expected_reward": self.plan.expected_reward,
        }


class PlanVerifier:
    """Replays plans through the sandbox gate and journals both verdicts."""

    def __init__(self, registry=None, journal=None) -> None:
        if registry is None:
            from nerrf_tpu.observability import DEFAULT_REGISTRY

            registry = DEFAULT_REGISTRY
        if journal is None:
            from nerrf_tpu.flight.journal import DEFAULT_JOURNAL

            journal = DEFAULT_JOURNAL
        self._reg = registry
        self._journal = journal

    def _reject(self, incident, plan: UndoPlan, reason: str,
                gate: Optional[GateResult] = None) -> VerifiedPlan:
        self._reg.counter_inc(
            "respond_plans_total", labels={"outcome": "rejected"},
            help="undo plans leaving the respond planner, by outcome "
                 "(emitted pre-verification, then verified or rejected)")
        # the journaled reason IS the quarantine record: every rejected
        # plan must be explainable offline (doctor's respond section)
        self._journal.record(
            "plan_rejected", stream=incident.stream,
            window_id=incident.window_idx, trace_id=incident.trace_id,
            reason=reason, actions=len(plan.actions))
        return VerifiedPlan(incident=incident, plan=plan, verified=False,
                            reason=reason, gate=gate)

    def verify(self, incident, plan: UndoPlan) -> VerifiedPlan:
        ctx: Optional[VerifyContext] = incident.context
        if ctx is None:
            return self._reject(
                incident, plan,
                "no snapshot context bound for this stream — cannot replay")
        if not plan.actions:
            return self._reject(incident, plan, "planner emitted no actions")
        try:
            gate = SandboxGate(ctx.store, ctx.manifest,
                               ransom_ext=ctx.ransom_ext).rehearse(
                plan, ctx.victim_root, trace=ctx.trace,
                ignore_extra=tuple(ctx.leaves_behind))
        except Exception as e:  # noqa: BLE001 — a raising gate is a rejection
            return self._reject(
                incident, plan, f"gate raised {type(e).__name__}: {e}")
        if not gate.approved:
            return self._reject(incident, plan, gate.reason, gate=gate)
        self._reg.counter_inc(
            "respond_plans_total", labels={"outcome": "verified"},
            help="undo plans leaving the respond planner, by outcome "
                 "(emitted pre-verification, then verified or rejected)")
        self._journal.record(
            "plan_verified", stream=incident.stream,
            window_id=incident.window_idx, trace_id=incident.trace_id,
            actions=len(plan.actions),
            files_restored=gate.rehearsal.files_restored,
            replay_ops=gate.replay_ops)
        return VerifiedPlan(incident=incident, plan=plan, verified=True,
                            reason=gate.reason, gate=gate)
