#!/usr/bin/env python3
"""Serve-plane load harness: N concurrent Tracker streams through the
online detection service's shared device micro-batches.

Measures the quantities docs/serving.md commits to: sustained
streams×events/s through the full wire path (replay server → grpcio →
native decode → windowing → shared padded batch → demux), p50/p99
window-to-alert latency, batch occupancy at the dominant bucket, and
recompiles after warmup (must be 0).  Every run also asserts the
acceptance-criterion parity leg: one stream's DetectionResult must be
bit-identical to the offline `pipeline.model_detect` on the same trace at
the same bucket.

    python benchmarks/run_serve_bench.py                 # 8 streams
    python benchmarks/run_serve_bench.py --smoke         # 2 streams, ~5 s
    python benchmarks/run_serve_bench.py --out results/serve_bench_cpu.json

Prints ONE JSON line (the artifact) on stdout; exits 1 if parity fails or
a recompile happened after warmup.

The cold-start leg (this PR's tentpole): the main service boots through a
COLD persistent compile cache (every bucket compiles fresh and persists),
then a second service boots from the now-populated cache and must reach
readiness with every bucket sourced from a deserialized executable —
gated at ≥5× lower warmup wall than the cold boot, with the warm
service's single-stream result still bit-identical to model_detect (a
cached executable changes where the program comes from, never what it
computes).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def _capacity_ramp(log=lambda *a: None, per_window_cost: float = 0.005,
                   rate_hz: float = 40.0, probe_sec: float = 0.8,
                   max_streams: int = 10,
                   band: tuple = (0.5, 2.0)) -> dict:
    """Measure the saturation stream count of a known-cost scorer and
    gate the headroom model's prediction against it.

    The scorer sleeps ``per_window_cost`` seconds per REAL window in the
    batch (a deterministic device), each synthetic stream offers
    ``rate_hz`` windows/s, so the analytic saturation point is
    1/(rate_hz * per_window_cost) streams.  The prediction comes from a
    `HeadroomTracker` fed exactly what the serve integration feeds it
    (admits + measured batch seconds) during the first probe — if the
    model is right, prediction and measurement agree within ``band``."""
    import queue as queue_mod

    import numpy as np

    from nerrf_tpu.devtime import HeadroomTracker
    from nerrf_tpu.serve import ServeConfig
    from nerrf_tpu.serve.batcher import MicroBatcher, WindowRequest

    tag = "ramp"
    tracker = HeadroomTracker(window_sec=30.0)
    scored_q: "queue_mod.Queue" = queue_mod.Queue()

    def score_fn(batch):
        mask = np.asarray(batch["node_mask"])
        occ = int(mask.any(axis=1).sum())
        t0 = time.perf_counter()
        time.sleep(per_window_cost * occ)
        tracker.observe_batch(tag, time.perf_counter() - t0, occ)
        return np.zeros(mask.shape, np.float32), None

    cfg = ServeConfig(buckets=((4, 4, 1),), batch_size=8,
                      batch_close_sec=0.02, stream_queue_slots=1 << 30,
                      devtime_accounting=False)
    delivered = [0]
    batcher = MicroBatcher(
        score_fn=score_fn, cfg=cfg,
        on_scored=lambda scored: delivered.__setitem__(
            0, delivered[0] + len(scored)))
    batcher.mark_warm((4, 4, 1))
    batcher.start()
    sample = {"node_mask": np.ones(4, bool),
              "node_type": np.zeros(4, np.int32),
              "node_key": np.zeros(4, np.int64)}
    seq = [0]

    def submit(stream: str) -> None:
        seq[0] += 1
        now = time.perf_counter()
        batcher.submit(WindowRequest(
            stream=stream, window_idx=seq[0], lo_ns=0, hi_ns=1,
            bucket=(4, 4, 1), sample=dict(sample), t_admit=now,
            deadline=now + 60.0, trace_id=f"ramp-{seq[0]}"))
        tracker.observe_admit(stream, tag)

    predicted = None
    measured = None
    ratios = {}
    try:
        for k in range(1, max_streams + 1):
            offered = 0
            start = delivered[0]
            interval = 1.0 / (rate_hz * k)
            t_end = time.monotonic() + probe_sec
            nxt = time.monotonic()
            i = 0
            while time.monotonic() < t_end:
                submit(f"r{i % k}")
                offered += 1
                i += 1
                nxt += interval
                lag = nxt - time.monotonic()
                if lag > 0:
                    time.sleep(lag)
            # MEASURED saturation signal: how much of the offered load was
            # scored by the time the probe ended.  An unsaturated scorer
            # trails by only the in-flight batch; a saturated one builds
            # backlog linearly over the probe
            got_at_end = delivered[0] - start
            ratio = got_at_end / max(offered, 1)
            ratios[k] = round(ratio, 3)
            # drain the backlog so it cannot leak into the next probe
            drain_by = time.monotonic() + max(
                4.0, offered * per_window_cost * 2)
            while delivered[0] - start < offered and \
                    time.monotonic() < drain_by:
                time.sleep(0.01)
            if k == 1 and predicted is None:
                # the prediction is made at the FIRST, clearly unsaturated
                # probe — exactly the operating point a live pod predicts
                # from (measured admits + measured device seconds)
                est = tracker.estimate()
                predicted = (est.saturation_streams
                             if est is not None else None)
            log(f"[serve-bench] capacity probe k={k}: offered {offered}, "
                f"delivery ratio {ratio:.2f}")
            if ratio < 0.85:
                measured = k
                break
    finally:
        batcher.stop(drain=False)
    analytic = 1.0 / (rate_hz * per_window_cost)
    within = (predicted is not None and measured is not None
              and band[0] <= predicted / measured <= band[1])
    out = {
        "per_window_cost_sec": per_window_cost,
        "stream_rate_hz": rate_hz,
        "analytic_saturation_streams": round(analytic, 2),
        "predicted_saturation_streams":
            round(predicted, 2) if predicted is not None else None,
        "measured_saturation_streams": measured,
        "band": list(band),
        "prediction_within_band": bool(within),
        "delivery_ratio_by_streams": ratios,
    }
    log(f"[serve-bench] capacity: predicted saturation "
        f"{out['predicted_saturation_streams']} streams, measured "
        f"{measured}, analytic {out['analytic_saturation_streams']} "
        f"(within band: {within})")
    return out


def _archive_leg(params, model, cfg, cache_dir, ref_events, ref_strings,
                 log=lambda *a: None) -> dict:
    """Archive-on vs archive-off latency on one warmed service + the
    zero-loss / offline-report / forced-rotation gates (docs/archive.md)."""
    import shutil
    import tempfile

    from nerrf_tpu.compilecache import CompileCache
    from nerrf_tpu.flight.journal import EventJournal
    from nerrf_tpu.observability import MetricsRegistry
    from nerrf_tpu.serve import OnlineDetectionService

    reg = MetricsRegistry(namespace="bench_arch")
    jrn = EventJournal(capacity=8192, registry=reg)
    window_log: list = []
    svc = OnlineDetectionService(
        params, model, cfg=cfg, registry=reg, journal=jrn,
        window_log=window_log,
        compile_cache=CompileCache(root=cache_dir, registry=reg,
                                   journal=jrn, log=log))
    svc.start(log=log)
    arch_dir = tempfile.mkdtemp(prefix="nerrf-archive-bench-")
    rot_dir = tempfile.mkdtemp(prefix="nerrf-archive-rot-")
    try:
        return _archive_leg_body(svc, arch_dir, rot_dir, reg, jrn,
                                 window_log, ref_events, ref_strings, log)
    finally:
        svc.stop()
        shutil.rmtree(arch_dir, ignore_errors=True)
        shutil.rmtree(rot_dir, ignore_errors=True)


def _archive_leg_body(svc, arch_dir, rot_dir, reg, jrn, window_log,
                      ref_events, ref_strings, log) -> dict:
    import dataclasses

    from nerrf_tpu.archive import (
        ArchiveConfig,
        ArchiveSpool,
        ArchiveWriter,
        SpoolConfig,
        build_report,
        export_tune,
        verify_archive,
    )
    from nerrf_tpu.observability import MetricsRegistry

    def feed_pass(stream: str):
        svc.join(stream)
        n0 = len(window_log)
        for i in range(0, len(ref_events), 256):
            blk = type(ref_events)(
                **{f.name: getattr(ref_events, f.name)[i:i + 256]
                   for f in dataclasses.fields(ref_events)})
            svc.feed(stream, blk, ref_strings)
        svc.leave(stream, timeout=120.0)
        lats = sorted(e[2] for e in window_log[n0:])
        from nerrf_tpu.flight.slo import percentile

        return len(lats), percentile(lats, 0.99)

    off_windows, off_p99 = feed_pass("off0")
    seq0 = jrn.seq
    writer = ArchiveWriter(
        ArchiveConfig(out_dir=arch_dir, snapshot_every_sec=0.5),
        registry=reg, journal=jrn, log=log)
    svc.attach_archive(writer)
    on_windows, on_p99 = feed_pass("on0")
    seq1 = jrn.seq
    writer.close()
    svc.stop()  # before reading counters: demux fully drained

    # zero record loss: every journal seq minted while subscribed is on
    # disk (the archive IS the journal over the run, not a sample of it)
    from nerrf_tpu.archive import iter_records

    archived_seqs = {r["seq"] for r in iter_records(arch_dir)
                     if r.get("seq") is not None}
    expected = set(range(seq0 + 1, seq1 + 1))
    lost = sorted(expected - archived_seqs)
    dropped = reg.value("archive_dropped_total",
                        labels={"reason": "queue_full"}) + reg.value(
        "archive_dropped_total", labels={"reason": "io_error"})

    # offline report + tune export vs the live run's own measurements
    report = build_report(arch_dir)
    tune = export_tune(arch_dir)
    verify = verify_archive(arch_dir)
    tune_windows = tune["windows_observed"]
    cost_rows = tune.get("bucket_cost") or {}
    tune_ok = (tune_windows == on_windows
               and all(row["device_seconds_mean"] and
                       row["device_seconds_mean"] > 0
                       for row in cost_rows.values()))
    report_ok = (verify["ok"]
                 and report["slo"]["windows_scored"] == on_windows
                 and (report["slo"]["e2e_ms"] or {}).get("p99") is not None
                 and report["efficiency"]["programs"] is not None)

    # forced rotation against a tiny bound: the spool must stay inside
    # its configured disk budget while sealing + pruning continuously
    bound = 16 * 1024
    seg_bytes = 4 * 1024
    spool = ArchiveSpool(
        SpoolConfig(out_dir=rot_dir, segment_max_bytes=seg_bytes,
                    max_total_bytes=bound),
        registry=MetricsRegistry(namespace="bench_rot"), log=log)
    for i in range(600):
        spool.append({"kind": "rotation_probe", "i": i, "pad": "x" * 64})
    spool.close()
    disk = sum(os.path.getsize(os.path.join(rot_dir, n))
               for n in os.listdir(rot_dir))
    rot_ok = (spool.pruned > 0 and spool.sealed > 2
              and disk <= bound + seg_bytes
              and verify_archive(rot_dir)["ok"])

    # noise band: archiving is a queue put + sketch per window — its p99
    # must ride the run's existing jitter, not add to it.  CPU-rig noise
    # on identical code spans ~×1.5 at these window counts, so the band
    # is ×2 with a small absolute floor for sub-100ms p99s
    within = (on_p99 is not None and off_p99 is not None
              and on_p99 <= off_p99 * 2.0 + 0.05)
    out = {
        "off": {"windows": off_windows,
                "p99_ms": round(off_p99 * 1e3, 1) if off_p99 else None},
        "on": {"windows": on_windows,
               "p99_ms": round(on_p99 * 1e3, 1) if on_p99 else None},
        "p99_within_noise_band": bool(within),
        "records_expected": len(expected),
        "records_archived": len(archived_seqs & expected),
        "records_lost": lost[:8],
        "zero_record_loss": not lost and dropped == 0,
        "report_offline_ok": bool(report_ok),
        "tune_export": {
            "windows_observed": tune_windows,
            "windows_scored_live": on_windows,
            "bucket_cost": cost_rows or None,
            "validated_against_live": bool(tune_ok)},
        "rotation": {"bound_bytes": bound, "disk_bytes": disk,
                     "segments_sealed": spool.sealed,
                     "segments_pruned": spool.pruned,
                     "disk_bounded": bool(rot_ok)},
    }
    log(f"[serve-bench] archive leg: p99 off/on "
        f"{out['off']['p99_ms']}/{out['on']['p99_ms']}ms "
        f"(band ok: {within}), {len(archived_seqs & expected)}/"
        f"{len(expected)} records archived, rotation bounded: {rot_ok}")
    return out


def run(streams: int = 8, sim_seconds: float = 90.0,
        bucket=(256, 512, 128), batch_size: int = 8,
        close_ms: float = 250.0, smoke: bool = False,
        log=lambda *a: print(*a, file=sys.stderr, flush=True)) -> dict:
    """Importable harness body (the tier-1 smoke test calls this
    in-process).  Returns the artifact dict."""
    if smoke:
        streams, sim_seconds = 2, 30.0
    log = log or (lambda *a: None)
    import jax

    from nerrf_tpu.data.synth import SimConfig, simulate_trace
    from nerrf_tpu.flight.journal import EventJournal
    from nerrf_tpu.ingest.service import TraceReplayServer, TrackerClient
    from nerrf_tpu.models import JointConfig, NerrfNet
    from nerrf_tpu.observability import MetricsRegistry
    from nerrf_tpu.pipeline import model_detect
    from nerrf_tpu.serve import (
        OnlineDetectionService,
        ServeConfig,
        bucket_tag,
        init_untrained_params,
    )

    backend = jax.default_backend()
    cfg = ServeConfig(
        buckets=(tuple(bucket),), batch_size=batch_size,
        batch_close_sec=close_ms / 1000.0,
        window_sec=15.0, stride_sec=5.0,
        # the harness measures scoring, not overload shedding: queues deep
        # enough that nothing drops (drop behavior is tier-1 tested)
        stream_queue_slots=512, alert_queue_slots=4096,
        window_deadline_sec=2.0)
    model = NerrfNet(JointConfig().small)
    params = init_untrained_params(model, cfg)
    registry = MetricsRegistry(namespace="bench")
    # isolated journal: the flight smoke leg below must see exactly THIS
    # run's batch-close records, not another in-process user's
    journal = EventJournal(capacity=8192, registry=registry)
    window_log: list = []
    # cold-start leg: the service boots through an EMPTY persistent cache,
    # so this warmup is the fresh-compile figure AND it populates the
    # cache the second-boot leg below deserializes from
    import tempfile

    from nerrf_tpu.compilecache import CompileCache

    cache_dir = tempfile.mkdtemp(prefix="nerrf-aot-bench-")
    svc = OnlineDetectionService(
        params, model, cfg=cfg, registry=registry,
        window_log=window_log, journal=journal,
        compile_cache=CompileCache(root=cache_dir, registry=registry,
                                   journal=journal, log=log))
    t0 = time.perf_counter()
    svc.start(log=log)
    warmup_wall = round(time.perf_counter() - t0, 2)
    cold = {"wall_seconds": warmup_wall,
            "sources": dict(svc.warmup_source),
            "per_bucket_seconds": dict(svc.warmup_seconds)}
    log(f"[serve-bench] cold boot {warmup_wall}s {svc.warmup_seconds} "
        f"{svc.warmup_source}")

    # one replay server per stream — every event crosses the real wire
    traces, servers, targets = [], [], []
    for i in range(streams):
        tr = simulate_trace(SimConfig(
            duration_sec=sim_seconds, attack=(i % 2 == 0),
            attack_start_sec=sim_seconds / 3, num_target_files=4,
            benign_rate_hz=6.0, seed=1000 + 97 * i))
        srv = TraceReplayServer(tr.events, tr.strings, batch_size=256)
        port = srv.start()
        traces.append(tr)
        servers.append(srv)
        targets.append(f"127.0.0.1:{port}")
    events_total = int(sum(tr.events.num_valid for tr in traces))

    t0 = time.perf_counter()
    runs = [svc.connect(f"s{i}", targets[i], timeout=300.0)
            for i in range(streams)]
    for r in runs:
        r.done.wait(timeout=600.0)
    wall = time.perf_counter() - t0
    errors = {r.stream: repr(r.error) for r in runs if r.error}

    # parity leg: stream s0's serve result vs offline model_detect on the
    # SAME bytes the service decoded (an independent drain of the same
    # replay server reconstructs them through the same bridge path)
    ref_events, ref_strings = TrackerClient(targets[0]).stream(timeout=60.0)
    from nerrf_tpu.data.loaders import Trace

    offline = model_detect(
        Trace(events=ref_events, strings=ref_strings, ground_truth=None,
              labels=None, name="s0"),
        params, model, ds_cfg=cfg.dataset_config(tuple(bucket)),
        auto_capacity=False, batch_size=batch_size)
    served = runs[0].result
    parity = (
        served is not None
        and served.file_scores == offline.file_scores
        and served.file_window_scores == offline.file_window_scores
        and served.proc_scores == offline.proc_scores
        and served.file_bytes == offline.file_bytes
        and served.threshold == offline.threshold)
    for srv in servers:
        srv.stop()
    svc.stop()

    # ---- flight-recorder smoke leg -----------------------------------------
    # A deliberately injected p99 latency spike and a drop burst must each
    # produce exactly ONE rate-limited bundle, the spike bundle's journal
    # tail must contain the offending window's batch-close record, and
    # `nerrf doctor` must reconstruct the timeline from the bundle alone.
    import shutil
    import tempfile

    from nerrf_tpu.flight import FlightConfig, FlightRecorder
    from nerrf_tpu.flight.doctor import format_report, read_bundle

    flight_dir = tempfile.mkdtemp(prefix="nerrf-flight-smoke-")
    deadline = cfg.window_deadline_sec
    exemplar_trace, _ = svc.slo.exemplar("s0")
    recorder = FlightRecorder(
        FlightConfig(out_dir=flight_dir, p99_breach_sec=deadline,
                     p99_min_count=8, min_interval_sec=300.0,
                     drop_burst_n=10, drop_burst_sec=5.0,
                     # efficiency-plane leg: the p99 bundle must embed a
                     # short live jax.profiler trace (jax_trace/) that
                     # `nerrf doctor` summarizes
                     profile_on_p99_sec=0.2),
        registry=registry, journal=journal, slo=svc.slo,
        info=svc.flight_info, log=log)
    # latency spike on the stream's worst REAL window: every observation
    # past min_count breaches trailing p99, but the rate limit admits one
    for _ in range(16):
        recorder.observe_window("s0", exemplar_trace, deadline * 5.0)
    # drop burst: a run of admission drops inside the sliding window
    for i in range(12):
        journal.record("admission_drop", stream="s0", window_id=10_000 + i,
                       trace_id=exemplar_trace, reason="backpressure",
                       injected=True)
    recorder.close()
    flight = {"bundles": 0, "triggers": [], "doctor_ok": False,
              "p99_bundle_has_offending_batch_close": False,
              "p99_bundle_has_profiler_trace": False,
              "suppressed": int(registry.value(
                  "flight_triggers_suppressed_total",
                  labels={"trigger": "p99_breach"}) + registry.value(
                  "flight_triggers_suppressed_total",
                  labels={"trigger": "drop_burst"}))}
    try:
        names = sorted(p for p in os.listdir(flight_dir)
                       if p.startswith("bundle-"))
        flight["bundles"] = len(names)
        flight["triggers"] = sorted(n.rsplit("-", 1)[-1] for n in names)
        doctor_ok = bool(names)
        for name in names:
            bundle = read_bundle(os.path.join(flight_dir, name))
            report = format_report(bundle)
            if bundle["missing"] or "incident timeline" not in report:
                doctor_ok = False
            if name.endswith("p99_breach"):
                # the spike window's batch-close record is in the tail,
                # joinable by its trace ID
                flight["p99_bundle_has_offending_batch_close"] = any(
                    r.kind == "batch_close"
                    and exemplar_trace in r.data.get("trace_ids", [])
                    for r in bundle["records"])
                # profile-on-breach: exactly this bundle embeds a trace
                # the doctor summarizes offline
                flight["p99_bundle_has_profiler_trace"] = bool(
                    bundle.get("profile")
                    and "profiler trace:" in report)
        flight["doctor_ok"] = doctor_ok
    finally:
        shutil.rmtree(flight_dir, ignore_errors=True)

    # ---- device-efficiency leg ---------------------------------------------
    # The devtime plane's trailing snapshot over the run just measured:
    # per-bucket device seconds, useful-FLOPs fractions, and MFU — which
    # MUST be null off-chip (null-not-fake) and non-null on a TPU.
    devtime = svc.devtime.snapshot() if svc.devtime is not None else None

    # Capacity headroom validated against MEASURED saturation: ramp paced
    # synthetic streams through the real micro-batcher (deterministic
    # sleep-cost scorer) until delivery falls behind offered load, and
    # gate the headroom model's prediction (made from the FIRST, clearly
    # unsaturated probe) within a band of the measured saturation point.
    capacity = _capacity_ramp(log=log)

    # ---- second-boot leg: warm readiness from the persistent cache ---------
    # A fresh service (fresh registry/journal — a new pod, same cache
    # volume) must reach ready with every bucket DESERIALIZED, ≥5× faster
    # than the cold boot, and still score bit-identically to model_detect.
    import dataclasses

    warm_reg = MetricsRegistry(namespace="bench2")
    warm_jrn = EventJournal(capacity=2048, registry=warm_reg)
    warm_svc = OnlineDetectionService(
        params, model, cfg=cfg, registry=warm_reg, journal=warm_jrn,
        compile_cache=CompileCache(root=cache_dir, registry=warm_reg,
                                   journal=warm_jrn, log=log))
    t0 = time.perf_counter()
    warm_svc.start(log=log)
    warm_wall = round(time.perf_counter() - t0, 2)
    warm = {"wall_seconds": warm_wall,
            "sources": dict(warm_svc.warmup_source),
            "per_bucket_seconds": dict(warm_svc.warmup_seconds)}
    log(f"[serve-bench] warm boot {warm_wall}s {warm_svc.warmup_seconds} "
        f"{warm_svc.warmup_source}")
    try:
        warm_svc.join("s0")
        ev = ref_events
        for i in range(0, len(ev), 256):
            blk = type(ev)(**{f.name: getattr(ev, f.name)[i:i + 256]
                              for f in dataclasses.fields(ev)})
            warm_svc.feed("s0", blk, ref_strings)
        warm_det = warm_svc.leave("s0", timeout=120.0)
    finally:
        warm_svc.stop()
    warm_parity = (
        warm_det is not None
        and warm_det.file_scores == offline.file_scores
        and warm_det.file_window_scores == offline.file_window_scores
        and warm_det.proc_scores == offline.proc_scores
        and warm_det.threshold == offline.threshold)
    from nerrf_tpu.flight.doctor import compile_provenance

    def _resolutions(jrn):
        # per-program resolution provenance (fresh-compile vs
        # cache-deserialize seconds, separate from the donor-batch
        # execution both legs pay) — same projection the doctor renders.
        # FIRST record per program wins: the boot-time resolution is what
        # this leg measures; a later fail-open "live" record (a staged
        # executable failing at score time, seconds=0.0) must not
        # overwrite it and deflate the resolution_speedup gate
        out = {}
        for c in compile_provenance(jrn.tail()):
            out.setdefault(c["program"], {"source": c["source"],
                                          "seconds": c["seconds"]})
        return out

    cold["resolutions"] = _resolutions(journal)
    warm["resolutions"] = _resolutions(warm_jrn)
    res_cold = sum(v["seconds"] or 0.0 for v in cold["resolutions"].values())
    res_warm = sum(v["seconds"] or 0.0 for v in warm["resolutions"].values())
    compile_block = {
        "cache": "persistent content-addressed AOT cache "
                 "(nerrf_tpu/compilecache, cold → populated → warm boot)",
        "cold": cold,
        "warm": warm,
        "resolution_speedup": round(res_cold / max(res_warm, 1e-9), 1),
        "warm_all_cache": set(warm["sources"].values()) == {"cache"},
        "warmup_speedup": round(cold["wall_seconds"]
                                / max(warm["wall_seconds"], 1e-9), 1),
        "warm_parity_bit_identical_to_model_detect": bool(warm_parity),
    }
    log(f"[serve-bench] warm boot speedup {compile_block['warmup_speedup']}x"
        f" (parity={warm_parity})")

    # ---- telemetry-archive leg ---------------------------------------------
    # Three acceptance gates (docs/archive.md): (1) archive-on p99 within
    # the noise band of archive-off on the SAME event stream through the
    # same warmed service; (2) zero record loss — every journal record
    # appended while the writer was subscribed is on disk; (3) the
    # offline report + tune export agree with what the live run measured.
    # A fourth, spool-only gate forces rotation against a tiny bound and
    # checks the disk bound held.  The cache_dir cleanup moved here from
    # the warm leg's finally (the archive boot reuses the populated
    # cache) — the try/finally keeps the no-leaked-tempdir invariant
    try:
        archive = _archive_leg(params, model, cfg, cache_dir, ref_events,
                               ref_strings, log=log)
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)

    tag = bucket_tag(tuple(bucket))
    lat_ms = sorted(1e3 * entry[2] for entry in window_log)

    def pct(p):
        return round(lat_ms[min(int(p * len(lat_ms)), len(lat_ms) - 1)], 1) \
            if lat_ms else None

    occ_mean = registry.value("serve_batch_occupancy",
                              labels={"bucket": tag}, stat="mean")
    recompiles = registry.value("serve_recompiles_total",
                                labels={"bucket": tag})
    scored = registry.value("serve_windows_scored_total")
    result = {
        "metric": "serve_events_per_sec_sustained",
        "value": round(events_total / wall, 1),
        "unit": f"events/s across {streams} concurrent wire streams",
        "backend": backend,
        "smoke": smoke or None,
        "streams": streams,
        "events_total": events_total,
        "wall_seconds": round(wall, 2),
        "windows_scored": int(scored),
        "windows_admitted": int(registry.value(
            "serve_windows_admitted_total")),
        "late_windows": int(registry.value("serve_late_windows_total")),
        "admission_dropped": {
            reason: int(registry.value("serve_admission_dropped_total",
                                       labels={"reason": reason}))
            for reason in ("backpressure", "oversize", "leave", "closed")},
        "batch": {
            "size": batch_size,
            "close_ms": close_ms,
            "dominant_bucket": tag,
            "occupancy_mean": round(occ_mean, 2),
            "batches": int(registry.value(
                "serve_batch_occupancy", labels={"bucket": tag},
                stat="count")),
        },
        "window_to_alert_latency_ms": {
            "p50": pct(0.50), "p99": pct(0.99),
            "max": round(lat_ms[-1], 1) if lat_ms else None},
        "recompiles_after_warmup": int(recompiles),
        # per-stream end-to-end SLO: exact trailing percentiles + exemplar
        # trace IDs (the registry carries the same data as the
        # nerrf_slo_e2e_seconds / nerrf_slo_budget_burn_ratio series)
        "slo": {"metric": "nerrf_slo_e2e_seconds", **svc.slo.snapshot()},
        "flight": flight,
        # device-efficiency plane (nerrf_tpu/devtime): per-program
        # trailing MFU (null off-chip, by contract), device seconds,
        # useful-FLOPs fractions, headroom — plus the capacity ramp's
        # prediction-vs-measured-saturation verdict
        "devtime": devtime,
        "capacity": capacity,
        "compile": compile_block,
        # telemetry-archive plane (nerrf_tpu/archive): archive-on vs
        # archive-off p99 on the same stream, the zero-record-loss
        # identity, the offline report/tune-export agreement, and the
        # forced-rotation disk bound
        "archive": archive,
        "warmup_seconds": {"wall": warmup_wall, **svc.warmup_seconds},
        "parity": {
            "stream": "s0",
            "bit_identical_to_model_detect": bool(parity),
            "files_scored": len(offline.file_scores)},
        "stream_errors": errors or None,
        "provenance": "python benchmarks/run_serve_bench.py"
                      + (" --smoke" if smoke else ""),
    }
    return result


def _devtime_ok(result: dict) -> bool:
    """Efficiency-leg gate: device seconds + useful fractions measured
    for the dominant bucket, and the MFU/null contract matches the
    backend (null off-chip, present on chip)."""
    dt = result.get("devtime") or {}
    programs = dt.get("programs") or {}
    useful = dt.get("useful_flops_fraction") or {}
    if not programs or not useful:
        return False
    if not all(p["calls"] > 0 and p["device_seconds"] > 0
               for p in programs.values()):
        return False
    if not all(0.0 < u <= 1.0 for u in useful.values()):
        return False
    on_chip = result.get("backend") == "tpu"
    for p in programs.values():
        if on_chip and p["mfu"] is None:
            return False
        if not on_chip and p["mfu"] is not None:
            return False  # a fabricated MFU off-chip is the failure mode
    return True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--streams", type=int, default=8)
    ap.add_argument("--seconds", type=float, default=90.0,
                    help="simulated seconds of trace per stream")
    ap.add_argument("--bucket", default="256x512x128", metavar="NxExS")
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--close-ms", type=float, default=250.0)
    ap.add_argument("--smoke", action="store_true",
                    help="2 streams, short traces (~5 s of serving)")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="also write the artifact JSON here")
    args = ap.parse_args(argv)

    result = run(streams=args.streams, sim_seconds=args.seconds,
                 bucket=tuple(int(x) for x in args.bucket.split("x")),
                 batch_size=args.batch_size, close_ms=args.close_ms,
                 smoke=args.smoke)
    line = json.dumps(result)
    print(line)
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
        with open(args.out, "w") as f:
            f.write(json.dumps(result, indent=2) + "\n")
    ok = (result["parity"]["bit_identical_to_model_detect"]
          and result["recompiles_after_warmup"] == 0
          and not result["stream_errors"]
          # flight-recorder acceptance: the injected spike + drop burst
          # produced exactly one bundle each, doctor-readable offline
          and result["flight"]["bundles"] == 2
          and result["flight"]["doctor_ok"]
          and result["flight"]["p99_bundle_has_offending_batch_close"]
          # efficiency-plane acceptance: the p99 bundle embeds exactly one
          # doctor-readable profiler trace, per-bucket device seconds and
          # useful-FLOPs fractions were measured, MFU is null off-chip
          # and present on chip (never fabricated), and the headroom
          # prediction lands within the gated band of measured saturation
          and result["flight"]["p99_bundle_has_profiler_trace"]
          and _devtime_ok(result)
          and result["capacity"]["prediction_within_band"]
          # cold-start acceptance: the second boot deserializes every
          # bucket (no re-tracing), the compile-vs-deserialize RESOLUTION
          # ratio is ≥5×, and a cached executable scores bit-identically
          # to model_detect.  The gated quantity is the resolution ratio
          # (what the cache controls); the wall ratio keeps a floor only,
          # because the shape-donor execution both boots pay is a fixed
          # cost that compresses it — decisively at smoke size, and on
          # any host whose XLA compiles this ladder in seconds (this
          # rig's 256n bucket compiles in ~3 s where the gate's original
          # calibration paid ~10 s)
          and result["compile"]["warm_all_cache"]
          and result["compile"]["resolution_speedup"] >= 5.0
          and result["compile"]["warmup_speedup"] >= (1.5 if args.smoke
                                                      else 2.5)
          and result["compile"]["warm_parity_bit_identical_to_model_detect"]
          # archive acceptance: armed archiving rides the run's noise
          # band, loses zero journal records, reports/exports offline in
          # agreement with the live run, and holds its disk bound under
          # forced rotation
          and result["archive"]["p99_within_noise_band"]
          and result["archive"]["zero_record_loss"]
          and result["archive"]["report_offline_ok"]
          and result["archive"]["tune_export"]["validated_against_live"]
          and result["archive"]["rotation"]["disk_bounded"])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
