"""Offline fleet reports over archived telemetry — no live process.

`build_report` reconstructs the operator-facing summaries every live
plane exports — SLO conformance, capacity headroom, detection-quality
drift, device efficiency, training health, incident inventory — from
archive segments alone: the journal stream gives the events (breaches,
drops, quarantines, bundles, train health), the cumulative workload
sketches give the distributions (window sizes, stage latencies, device
seconds per program), and the cadenced metrics snapshots give the gauge
trajectories (headroom, MFU).  Sketches and totals are merged across
``run`` ids by count/sum addition — exact, so a report over a merged
multi-host archive is the same arithmetic as a single-host one.

`compare_reports` diffs two runs and flags regressions (`nerrf report
--compare A B` — the cross-run CI gate), and `export_tune` emits the
observed window-size distribution + per-bucket measured cost table the
future `nerrf tune` cost-model fit consumes (the TpuGraphs-style
dataset, arXiv:2308.13490: measured per-configuration cost over the
production workload distribution).
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Dict, List, Optional, Tuple

from nerrf_tpu.archive.spool import iter_records, list_segments

#: compare_reports default thresholds: (ratio regressions fire past ×R,
#: rate regressions past +abs).  Deliberately loose — a cross-run diff
#: on a noisy CPU rig must flag real regressions, not scheduler jitter.
#: Kept as module constants for callers that want the defaults by name;
#: `CompareConfig` is the tunable form (`nerrf report --compare` flags).
P99_REGRESSION_RATIO = 1.5
COST_REGRESSION_RATIO = 1.5
LOSS_REGRESSION_RATIO = 1.25
RATE_REGRESSION_ABS = 0.02
PSI_BREACH = 0.25


@dataclasses.dataclass(frozen=True)
class CompareConfig:
    """Tolerance knobs for `compare_reports` — one field per regression
    class, CLI-settable (`--p99-ratio` etc.) so a queue's gate can be
    tightened or loosened without editing code.  The thresholds used are
    stamped into the comparison output, so a gate failure names the bar
    it was judged against."""

    p99_ratio: float = P99_REGRESSION_RATIO      # e2e p99 ×R
    cost_ratio: float = COST_REGRESSION_RATIO    # device s/batch ×R
    loss_ratio: float = LOSS_REGRESSION_RATIO    # final train loss ×R
    rate_abs: float = RATE_REGRESSION_ABS        # breach/drop rate +abs
    psi_breach: float = PSI_BREACH               # score-drift PSI bar

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

_NAME_TAG = re.compile(r"^([a-z_]+)\[(.+)\]$")


def _merge_sketches(sketch_records: List[dict]) -> Tuple[dict, dict]:
    """Last cumulative sketch record per run, merged across runs by
    count/sum addition → ({name: Sketch}, {name: {count, sum}})."""
    from nerrf_tpu.quality.sketch import Sketch

    last_per_run: Dict[tuple, dict] = {}
    for rec in sketch_records:  # segment order: later wins per run
        # keyed by (src, run): a merged archive keeps each source's runs
        # distinct even if two hosts ever minted the same run id
        last_per_run[(rec.get("src"), rec.get("run") or "?")] = rec
    sketches: Dict[str, object] = {}
    totals: Dict[str, dict] = {}
    for rec in last_per_run.values():
        data = rec.get("data") or {}
        for name, d in (data.get("sketches") or {}).items():
            try:
                sk = Sketch.from_dict(d)
            except (ValueError, KeyError, TypeError):
                continue
            have = sketches.get(name)
            sketches[name] = sk if have is None else have.merge(sk)
        for name, t in (data.get("totals") or {}).items():
            agg = totals.setdefault(name, {"count": 0, "sum": 0.0})
            agg["count"] += int(t.get("count") or 0)
            agg["sum"] += float(t.get("sum") or 0.0)
    return sketches, totals


def _tagged(mapping: dict, prefix: str) -> Dict[str, object]:
    """``{tag: value}`` for every ``prefix[tag]`` key in ``mapping``."""
    out = {}
    for name, v in mapping.items():
        m = _NAME_TAG.match(name)
        if m and m.group(1) == prefix:
            out[m.group(2)] = v
    return out


def _gauge_series(snapshots: List[dict], name: str) -> List[float]:
    """Every value of one (possibly labeled) gauge across the snapshot
    cadence, in time order — min/last trajectories for the report."""
    out = []
    for rec in snapshots:
        series = ((rec.get("data") or {}).get("gauges") or {}).get(name)
        if series:
            out.extend(float(v) for v in series.values())
    return out


def _q(sketch, qs=(0.5, 0.9, 0.99)) -> Optional[dict]:
    return None if sketch is None else sketch.quantiles(qs)


def _ms(q: Optional[dict]) -> Optional[dict]:
    if q is None:
        return None
    return {k: (None if v is None else round(v * 1e3, 2))
            for k, v in q.items()}


def build_report(paths, since: Optional[float] = None,
                 until: Optional[float] = None) -> dict:
    """The offline fleet report over one or more archive directories."""
    if isinstance(paths, (str,)) or hasattr(paths, "__fspath__"):
        paths = [paths]
    records = list(iter_records(paths, since=since, until=until))
    kinds: Dict[str, int] = {}
    by_kind: Dict[str, List[dict]] = {}
    for rec in records:
        k = str(rec.get("kind"))
        kinds[k] = kinds.get(k, 0) + 1
        by_kind.setdefault(k, []).append(rec)
    sketches, totals = _merge_sketches(by_kind.get("workload_sketch", []))
    snapshots = by_kind.get("metrics_snapshot", [])
    times = [r["t_wall"] for r in records if r.get("t_wall") is not None]
    segments = sum(len(list_segments(p)) for p in paths)
    runs = sorted({r.get("run") for r in records if r.get("run")})

    # -- SLO conformance ------------------------------------------------------
    windows = sum(t["count"] for n, t in totals.items()
                  if n.startswith("windows["))
    breaches = by_kind.get("slo_breach", [])
    breaches_by_stream: Dict[str, int] = {}
    for rec in breaches:
        s = rec.get("stream") or "?"
        breaches_by_stream[s] = breaches_by_stream.get(s, 0) + 1
    deadline = None
    for rec in by_kind.get("config", []):
        deadline = (rec.get("data") or {}).get("window_deadline_sec",
                                               deadline)
    slo = {
        "windows_scored": windows,
        "deadline_sec": deadline,
        "breaches": len(breaches),
        "breach_rate": round(len(breaches) / windows, 4) if windows else None,
        "breaches_by_stream": breaches_by_stream or None,
        "e2e_ms": _ms(_q(sketches.get("e2e_latency_seconds"))),
        "stage_ms": {tag: _ms(_q(sk)) for tag, sk in sorted(
            _tagged(sketches, "stage_seconds").items())} or None,
    }

    # -- capacity headroom ----------------------------------------------------
    headroom = _gauge_series(snapshots, "capacity_headroom_streams")
    occ_totals = _tagged(totals, "occupancy")
    capacity = {
        "headroom_streams_min": round(min(headroom), 2) if headroom else None,
        "headroom_streams_last": round(headroom[-1], 2) if headroom
                                 else None,
        "saturation_events": kinds.get("capacity_saturation", 0),
        "occupancy_mean": {
            tag: round(t["sum"] / t["count"], 2)
            for tag, t in sorted(occ_totals.items()) if t["count"]} or None,
    }

    # -- detection-quality drift ----------------------------------------------
    per_stream: Dict[str, dict] = {}
    worst_feature = None
    for rec in by_kind.get("quality_stats", []):
        d = rec.get("data") or {}
        s = rec.get("stream") or "?"
        psi = d.get("worst_score_psi")
        ent = per_stream.setdefault(s, {"last_score_psi": None,
                                        "max_score_psi": None})
        if psi is not None:
            ent["last_score_psi"] = round(float(psi), 4)
            ent["max_score_psi"] = round(
                max(float(psi), ent["max_score_psi"] or 0.0), 4)
        f = d.get("worst_feature_psi")
        if f is not None:
            worst_feature = max(float(f), worst_feature or 0.0)
    drift = {
        "quality_stats_records": kinds.get("quality_stats", 0),
        "streams": per_stream or None,
        "worst_score_psi": max(
            (e["max_score_psi"] for e in per_stream.values()
             if e["max_score_psi"] is not None), default=None),
        "worst_feature_psi": (round(worst_feature, 4)
                              if worst_feature is not None else None),
        "drift_bundles": sum(
            1 for r in by_kind.get("bundle", [])
            if (r.get("data") or {}).get("trigger") == "quality_drift"),
    }

    # -- device efficiency ----------------------------------------------------
    dev_totals = _tagged(totals, "device_seconds")
    dev_sketches = _tagged(sketches, "device_seconds")
    programs = {}
    for tag, t in sorted(dev_totals.items()):
        q = _q(dev_sketches.get(tag))
        programs[tag] = {
            "windows": int((_tagged(totals, "windows").get(tag) or
                            {"count": 0})["count"]),
            "batches": t["count"],
            "device_seconds_total": round(t["sum"], 4),
            "device_seconds_mean": (round(t["sum"] / t["count"], 6)
                                    if t["count"] else None),
            "device_seconds_p99_ms": (_ms(q) or {}).get("p99"),
        }
    mfu = _gauge_series(snapshots, "device_mfu")
    efficiency = {
        "programs": programs or None,
        "mfu_last": round(mfu[-1], 4) if mfu else None,
    }

    # -- training health ------------------------------------------------------
    health = by_kind.get("train_health", [])
    last_health = (health[-1].get("data") or {}) if health else {}
    nonfinite = 0
    max_grad = None
    for rec in health:
        d = rec.get("data") or {}
        nf = d.get("nonfinite") or {}
        nonfinite += int(sum(nf.values())) if nf else 0
        g = d.get("grad_norm")
        if g is not None:
            max_grad = max(float(g), max_grad or 0.0)
    halted = [(r.get("data") or {}).get("halted")
              for r in by_kind.get("train_done", [])]
    train = {
        "train_starts": kinds.get("train_start", 0),
        "health_records": len(health),
        "last": {k: last_health.get(k) for k in
                 ("step", "loss", "grad_norm", "update_ratio",
                  "steps_per_sec", "data_wait_fraction")} if health
                else None,
        "max_grad_norm": max_grad,
        "nonfinite_total": nonfinite,
        "halted": next((h for h in halted if h), None),
        "step_seconds_p50_ms": (_ms(_q(sketches.get("train_step_seconds")))
                                or {}).get("p50"),
    }

    # -- workload (the tune export's raw material) ----------------------------
    workload = {
        "window_nodes": _q(sketches.get("window_nodes")),
        "window_edges": _q(sketches.get("window_edges")),
        "window_files": _q(sketches.get("window_files")),
    }

    # -- incident inventory ---------------------------------------------------
    drops: Dict[str, int] = {}
    for rec in by_kind.get("admission_drop", []) \
            + by_kind.get("demux_drop", []):
        reason = (rec.get("data") or {}).get("reason") or rec.get("kind")
        drops[str(reason)] = drops.get(str(reason), 0) + 1
    incidents = {
        "bundles": [{"trigger": (r.get("data") or {}).get("trigger"),
                     "path": (r.get("data") or {}).get("path")}
                    for r in by_kind.get("bundle", [])] or None,
        "exceptions": kinds.get("exception", 0),
        "quarantines": kinds.get("stream_quarantined", 0),
        "reconnects": kinds.get("reconnect", 0),
        "device_batch_failures": kinds.get("device_batch_failed", 0),
        "drops": drops or None,
    }

    return {
        "span": {
            "dirs": [str(p) for p in paths],
            "segments": segments,
            "records": len(records),
            "runs": runs,
            "from_unix": min(times) if times else None,
            "to_unix": max(times) if times else None,
            "kinds": dict(sorted(kinds.items())),
        },
        "slo": slo,
        "capacity": capacity,
        "drift": drift,
        "efficiency": efficiency,
        "train": train,
        "workload": workload,
        "incidents": incidents,
    }


def format_report(report: dict) -> str:
    """Human rendering of `build_report` (the `nerrf report` default)."""
    lines: List[str] = []
    span = report["span"]
    dur = (span["to_unix"] - span["from_unix"]
           if span["from_unix"] is not None and span["to_unix"] is not None
           else None)
    lines.append(
        f"telemetry archive report: {span['records']} records / "
        f"{span['segments']} segment(s) over "
        f"{dur:.0f}s" if dur is not None else
        f"telemetry archive report: {span['records']} records / "
        f"{span['segments']} segment(s)")
    lines.append("  dirs: " + ", ".join(span["dirs"]))
    if span["runs"]:
        lines.append(f"  runs: {', '.join(span['runs'])}")

    slo = report["slo"]
    lines.append("")
    lines.append(f"SLO conformance ({slo['windows_scored']} windows, "
                 f"deadline {slo['deadline_sec']}s):")
    if slo["e2e_ms"]:
        q = slo["e2e_ms"]
        lines.append(f"  e2e p50/p90/p99: {q.get('p50')}/{q.get('p90')}/"
                     f"{q.get('p99')} ms (sketch resolution)")
    lines.append(f"  breaches: {slo['breaches']}"
                 + (f" (rate {slo['breach_rate']})"
                    if slo["breach_rate"] is not None else ""))
    for stage, q in (slo["stage_ms"] or {}).items():
        lines.append(f"  stage {stage:<8} p50/p99: "
                     f"{q.get('p50')}/{q.get('p99')} ms")

    cap = report["capacity"]
    lines.append("")
    lines.append(
        f"capacity: headroom min/last "
        f"{cap['headroom_streams_min']}/{cap['headroom_streams_last']} "
        f"streams, {cap['saturation_events']} saturation event(s)")
    for tag, m in (cap["occupancy_mean"] or {}).items():
        lines.append(f"  occupancy[{tag}] mean: {m}")

    drift = report["drift"]
    lines.append("")
    lines.append(
        f"drift: worst score PSI {drift['worst_score_psi']}, worst "
        f"feature PSI {drift['worst_feature_psi']} over "
        f"{drift['quality_stats_records']} quality_stats record(s), "
        f"{drift['drift_bundles']} drift bundle(s)")

    eff = report["efficiency"]
    lines.append("")
    lines.append("device efficiency:")
    for tag, p in (eff["programs"] or {}).items():
        lines.append(
            f"  {tag:<20} {p['windows']:>6} windows "
            f"{p['batches']:>6} batches  mean "
            f"{p['device_seconds_mean']}s  p99 "
            f"{p['device_seconds_p99_ms']}ms")
    if not eff["programs"]:
        lines.append("  (no device-seconds sketches archived)")
    if eff["mfu_last"] is not None:
        lines.append(f"  MFU (last snapshot): {eff['mfu_last']}")

    tr = report["train"]
    lines.append("")
    if tr["health_records"]:
        last = tr["last"] or {}
        lines.append(
            f"training health: {tr['health_records']} record(s), last "
            f"step {last.get('step')} loss {last.get('loss')} "
            f"grad {last.get('grad_norm')} at "
            f"{last.get('steps_per_sec')} steps/s; max grad "
            f"{tr['max_grad_norm']}, nonfinite {tr['nonfinite_total']}"
            + (f"; HALTED: {tr['halted']}" if tr["halted"] else ""))
    elif tr["train_starts"]:
        # a short run can finish before the monitor's journal cadence
        # cuts a single train_health record — the start/done markers are
        # still evidence worth printing
        lines.append(
            f"training health: {tr['train_starts']} run(s) archived, no "
            f"cadenced health records in range (run shorter than the "
            f"journal cadence)"
            + (f"; HALTED: {tr['halted']}" if tr["halted"] else ""))
    else:
        lines.append("training health: no train records in range")

    inc = report["incidents"]
    lines.append("")
    lines.append(
        f"incidents: {len(inc['bundles'] or [])} bundle(s), "
        f"{inc['exceptions']} exception(s), {inc['quarantines']} "
        f"quarantine(s), {inc['reconnects']} reconnect(s), "
        f"{inc['device_batch_failures']} device batch failure(s)")
    for b in inc["bundles"] or []:
        lines.append(f"  bundle {b['trigger']}: {b['path']}")
    if inc["drops"]:
        lines.append("  drops: " + " ".join(
            f"{k}={v}" for k, v in sorted(inc["drops"].items())))
    return "\n".join(lines)


# -- cross-run regression diff ------------------------------------------------


def compare_reports(a: dict, b: dict,
                    cfg: Optional[CompareConfig] = None) -> dict:
    """Diff run B against baseline run A; every flagged regression is one
    dict with what/baseline/candidate — the `--compare` CI gate fails on
    a non-empty list.  The thresholds actually applied (``cfg``, default
    `CompareConfig()`) are stamped into the result so the verdict is
    self-describing."""
    cfg = cfg or CompareConfig()
    regressions: List[dict] = []

    def flag(what: str, base, cand) -> None:
        regressions.append({"what": what, "baseline": base,
                            "candidate": cand})

    pa = ((a["slo"].get("e2e_ms") or {}).get("p99"))
    pb = ((b["slo"].get("e2e_ms") or {}).get("p99"))
    if pa and pb and pb > pa * cfg.p99_ratio:
        flag(f"e2e p99 regressed ×{pb / pa:.2f} "
             f"(threshold ×{cfg.p99_ratio:g})", pa, pb)
    ra = a["slo"].get("breach_rate") or 0.0
    rb = b["slo"].get("breach_rate") or 0.0
    if rb > ra + cfg.rate_abs:
        flag("SLO breach rate regressed", ra, rb)

    drops_a = sum((a["incidents"].get("drops") or {}).values())
    drops_b = sum((b["incidents"].get("drops") or {}).values())
    wa = max(a["slo"].get("windows_scored") or 0, 1)
    wb = max(b["slo"].get("windows_scored") or 0, 1)
    if drops_b / wb > drops_a / wa + cfg.rate_abs:
        flag("window drop rate regressed",
             round(drops_a / wa, 4), round(drops_b / wb, 4))

    progs_a = a["efficiency"].get("programs") or {}
    progs_b = b["efficiency"].get("programs") or {}
    for tag in sorted(set(progs_a) & set(progs_b)):
        ca = progs_a[tag].get("device_seconds_mean")
        cb = progs_b[tag].get("device_seconds_mean")
        if ca and cb and cb > ca * cfg.cost_ratio:
            flag(f"device seconds per batch regressed ×{cb / ca:.2f} "
                 f"on {tag}", ca, cb)

    psi_a = a["drift"].get("worst_score_psi") or 0.0
    psi_b = b["drift"].get("worst_score_psi") or 0.0
    if psi_b >= cfg.psi_breach > psi_a:
        flag(f"score drift crossed the {cfg.psi_breach:g} PSI breach",
             psi_a, psi_b)

    la = (a["train"].get("last") or {}).get("loss")
    lb = (b["train"].get("last") or {}).get("loss")
    if la and lb and lb > la * cfg.loss_ratio:
        flag(f"final train loss regressed ×{lb / la:.2f}", la, lb)
    if b["train"].get("halted") and not a["train"].get("halted"):
        flag("training halted in candidate", None, b["train"]["halted"])

    return {"baseline": a["span"]["dirs"], "candidate": b["span"]["dirs"],
            "thresholds": cfg.to_dict(),
            "regressions": regressions, "ok": not regressions}


def format_compare(cmp: dict) -> str:
    lines = [f"compare: baseline {', '.join(cmp['baseline'])} vs "
             f"candidate {', '.join(cmp['candidate'])}"]
    th = cmp.get("thresholds")
    if th:
        lines.append("  thresholds: " + " ".join(
            f"{k}={v:g}" for k, v in sorted(th.items())))
    if cmp["ok"]:
        lines.append("  no regressions flagged")
    for r in cmp["regressions"]:
        lines.append(f"  REGRESSION: {r['what']} "
                     f"(baseline {r['baseline']} → {r['candidate']})")
    return "\n".join(lines)


# -- the tune-ready corpus ----------------------------------------------------


def export_tune(paths, since: Optional[float] = None,
                until: Optional[float] = None) -> dict:
    """The dataset the learned-ladder cost-model fit consumes: the
    observed window-size distribution (mergeable sketches + quantiles)
    and the per-bucket measured cost table (windows, batches, mean/p99
    device seconds, mean occupancy) straight from production telemetry —
    what the live gauges showed, now durable and mergeable."""
    if isinstance(paths, (str,)) or hasattr(paths, "__fspath__"):
        paths = [paths]
    sketch_records = list(iter_records(paths, since=since, until=until,
                                       kinds=("workload_sketch",)))
    sketches, totals = _merge_sketches(sketch_records)
    dist = {}
    rejected = {}
    for feat in ("nodes", "edges", "files"):
        sk = sketches.get(f"window_{feat}")
        if sk is not None:
            dist[feat] = {"sketch": sk.to_dict(), "total": sk.total,
                          "quantiles": sk.quantiles((0.5, 0.9, 0.99))}
        rj = sketches.get(f"rejected_window_{feat}")
        if rj is not None and rj.total:
            rejected[feat] = {"sketch": rj.to_dict(), "total": rj.total,
                              "quantiles": rj.quantiles((0.5, 0.9, 0.99))}
    dev_totals = _tagged(totals, "device_seconds")
    win_totals = _tagged(totals, "windows")
    occ_totals = _tagged(totals, "occupancy")
    dev_sketches = _tagged(sketches, "device_seconds")
    table = {}
    for tag in sorted(set(dev_totals) | set(win_totals)):
        dt = dev_totals.get(tag) or {"count": 0, "sum": 0.0}
        occ = occ_totals.get(tag)
        q = _q(dev_sketches.get(tag), qs=(0.5, 0.99))
        table[tag] = {
            "windows": (win_totals.get(tag) or {"count": 0})["count"],
            "batches": dt["count"],
            "device_seconds_mean": (round(dt["sum"] / dt["count"], 6)
                                    if dt["count"] else None),
            "device_seconds_p99": (q or {}).get("p99"),
            "occupancy_mean": (round(occ["sum"] / occ["count"], 3)
                               if occ and occ["count"] else None),
        }
    rej_total = totals.get("rejected_windows")
    return {
        "schema": 1,
        "kind": "nerrf_tune_corpus",
        "source": [str(p) for p in paths],
        "windows_observed": sum(t["count"] for t in win_totals.values()),
        "windows_rejected": int(rej_total["count"]) if rej_total else 0,
        "window_size_distribution": dist or None,
        # demand beyond the top rung (admission-rejected window sizes) —
        # what a ladder extension would capture; tune merges this into
        # its demand points so rejected traffic pulls rungs up
        "rejected_window_size_distribution": rejected or None,
        "bucket_cost": table or None,
        "provenance": "nerrf archive export --tune",
    }


def report_main(paths, since=None, until=None, compare=None,
                as_json=False, out=print, gate=False,
                compare_cfg: Optional[CompareConfig] = None) -> int:
    """The `nerrf report` body; returns a CLI exit code (compare mode:
    1 when a regression is flagged).

    ``gate=True`` is the continuous-regression form (`--gate`): the same
    compare verdict framed for queue pre-flights — a one-line GATE
    PASS/FAIL verdict, and a *missing or empty baseline* passes with a
    note instead of erroring, so the first run before an
    artifact-of-record is banked doesn't hard-fail the queue."""
    from nerrf_tpu.flight.journal import SchemaVersionError

    try:
        if compare:
            if gate:
                try:
                    a = build_report([compare[0]], since=since,
                                     until=until)
                except (FileNotFoundError, SchemaVersionError) as e:
                    out(f"GATE PASS (no banked baseline at "
                        f"{compare[0]}: {e})")
                    return 0
                if not a["span"]["records"]:
                    out(f"GATE PASS (baseline {compare[0]} holds no "
                        f"records in range — nothing banked yet)")
                    return 0
            else:
                a = build_report([compare[0]], since=since, until=until)
            b = build_report([compare[1]], since=since, until=until)
            cmp = compare_reports(a, b, cfg=compare_cfg)
            out(json.dumps(cmp, indent=2) if as_json else
                format_compare(cmp))
            if gate:
                out("GATE PASS" if cmp["ok"] else
                    "GATE FAIL: " + "; ".join(
                        r["what"] for r in cmp["regressions"]))
            return 0 if cmp["ok"] else 1
        report = build_report(paths, since=since, until=until)
        out(json.dumps(report, indent=2) if as_json else
            format_report(report))
        return 0 if report["span"]["records"] else 1
    except SchemaVersionError as e:
        out(f"cannot read archive: {e}")
        return 2
    except FileNotFoundError as e:
        out(f"not an archive directory: {e}")
        return 2
