from nerrf_tpu.rollback.store import SnapshotStore, Manifest
from nerrf_tpu.rollback.executor import RollbackExecutor, RollbackReport
from nerrf_tpu.rollback.sandbox import SandboxGate, GateResult
from nerrf_tpu.rollback.filesim import FileSimConfig, run_file_attack

__all__ = [
    "SnapshotStore",
    "Manifest",
    "RollbackExecutor",
    "RollbackReport",
    "SandboxGate",
    "GateResult",
    "FileSimConfig",
    "run_file_attack",
]
