"""Mergeable fixed-bin distribution sketches + population-stability math.

One primitive for every drift comparison in the repo: a histogram over a
FIXED bin ladder.  Fixed bins make the sketch

  * **mergeable by construction** — merging is elementwise count
    addition, associative and commutative, so per-window increments,
    per-stream trailing windows, per-host aggregates and the
    calibration-time reference all compose without coordination (the
    pod-scale serving item can sum sketches across hosts exactly);
  * **exactly subtractable** — a trailing window evicts a window by
    subtracting its increment, so "the last N windows" is O(bins) per
    eviction, never a re-scan;
  * **comparable** — PSI between two sketches over the same ladder is a
    closed-form sum, no re-binning.

Quantiles are bin-resolution approximations (right edge of the bin the
rank lands in) — good enough for dashboards and journal records; exact
values stay with the exact paths (calibration, guardrails means).

PSI (population stability index), the standard drift score:

    PSI = Σ_i (p_i − q_i) · ln(p_i / q_i)

with ε-floored bin proportions so an empty bin cannot blow it up.
Conventional reading: < 0.1 stable, 0.1–0.25 moderate shift, > 0.25
major shift (the default trigger threshold in flight.FlightConfig).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

# Node-probability ladder: 20 uniform bins over [0, 1].
SCORE_EDGES = tuple(round(i * 0.05, 2) for i in range(1, 20))
# Count ladder (nodes/edges/files per window): powers of two — matches
# how the bucket ladder quantizes capacity, so a one-rung shift in the
# window population is a one-bin shift here.
COUNT_EDGES = tuple(float(1 << i) for i in range(13))  # 1 .. 4096
# Fraction ladder (event-type mix): 10 uniform bins over [0, 1].
FRACTION_EDGES = tuple(round(i * 0.1, 1) for i in range(1, 10))


@dataclasses.dataclass
class Sketch:
    """Counts over ``len(edges) + 1`` bins; bin i holds values in
    ``(edges[i-1], edges[i]]`` (first bin: ``<= edges[0]``, last bin:
    ``> edges[-1]``)."""

    edges: tuple
    counts: np.ndarray  # int64 [len(edges) + 1]

    @classmethod
    def empty(cls, edges: Sequence[float]) -> "Sketch":
        edges = tuple(float(e) for e in edges)
        if list(edges) != sorted(set(edges)):
            raise ValueError(f"sketch edges must be strictly ascending: "
                             f"{edges}")
        return cls(edges=edges, counts=np.zeros(len(edges) + 1, np.int64))

    # -- building -------------------------------------------------------------

    def bin_counts(self, values) -> np.ndarray:
        """The increment one batch of values contributes (does NOT mutate
        this sketch) — the unit a trailing window appends and later
        subtracts."""
        idx = np.searchsorted(np.asarray(self.edges),
                              np.asarray(values, np.float64), side="left")
        return np.bincount(idx, minlength=len(self.edges) + 1) \
            .astype(np.int64)

    def observe(self, values) -> np.ndarray:
        """Add a batch of values; returns the increment (for trailing
        callers that must subtract it later)."""
        inc = self.bin_counts(values)
        self.counts += inc
        return inc

    def add_counts(self, inc: np.ndarray) -> None:
        self.counts += np.asarray(inc, np.int64)

    def sub_counts(self, inc: np.ndarray) -> None:
        self.counts = np.maximum(self.counts - np.asarray(inc, np.int64), 0)

    def merge(self, other: "Sketch") -> "Sketch":
        """Elementwise count addition — associative and commutative, the
        property pod-scale aggregation and profile merging rely on.
        Refuses mismatched ladders (re-binning would fabricate data)."""
        if self.edges != other.edges:
            raise ValueError(
                f"cannot merge sketches over different bin ladders "
                f"({len(self.edges)} vs {len(other.edges)} edges)")
        return Sketch(edges=self.edges, counts=self.counts + other.counts)

    # -- reading --------------------------------------------------------------

    @property
    def total(self) -> int:
        return int(self.counts.sum())

    def proportions(self, alpha: float = 0.5) -> np.ndarray:
        """Laplace-smoothed bin proportions — the PSI operand.

        Add-α smoothing rather than an ε floor: with an ε floor, every
        reference bin a SMALL live sample happens to miss contributes
        ``p·ln(p/ε)`` (large), so trailing windows still filling up read
        as major drift — measured 0.75 PSI on identical distributions at
        30 windows.  α = 0.5 (Jeffreys) shrinks empty-bin contributions
        toward the sample's actual resolution instead."""
        total = float(self.counts.sum())
        return (self.counts + alpha) / (total + alpha * len(self.counts))

    def quantile(self, q: float) -> Optional[float]:
        """Bin-resolution quantile: the right edge of the bin the rank
        lands in (the last bin reports its left edge — it is unbounded).
        None when empty."""
        total = self.counts.sum()
        if total == 0:
            return None
        rank = q * total
        cum = np.cumsum(self.counts)
        i = int(np.searchsorted(cum, rank, side="left"))
        i = min(i, len(self.counts) - 1)
        if i < len(self.edges):
            return float(self.edges[i])
        return float(self.edges[-1])

    def quantiles(self, qs=(0.5, 0.9, 0.99)) -> Dict[str, Optional[float]]:
        return {f"p{int(q * 100)}": self.quantile(q) for q in qs}

    # -- roundtrip ------------------------------------------------------------

    def to_dict(self) -> dict:
        return {"edges": list(self.edges),
                "counts": [int(c) for c in self.counts]}

    @classmethod
    def from_dict(cls, d: dict) -> "Sketch":
        edges = tuple(float(e) for e in d["edges"])
        counts = np.asarray(d["counts"], np.int64)
        if len(counts) != len(edges) + 1:
            raise ValueError(
                f"corrupt sketch: {len(counts)} counts for {len(edges)} "
                f"edges (want {len(edges) + 1})")
        return cls(edges=edges, counts=counts)


def psi(reference: Sketch, live: Sketch, alpha: float = 0.5) -> float:
    """Population stability index of ``live`` against ``reference``
    (same ladder).  Symmetric in spirit but conventionally reported
    live-vs-reference; Laplace-smoothed so empty bins stay finite AND
    small live samples are not biased toward "drift" (see
    `Sketch.proportions`)."""
    if reference.edges != live.edges:
        raise ValueError("PSI requires both sketches on the same bin ladder")
    p = reference.proportions(alpha)
    q = live.proportions(alpha)
    return float(np.sum((q - p) * np.log(q / p)))


def top_drifting(reference: Dict[str, Sketch], live: Dict[str, Sketch],
                 alpha: float = 0.5) -> List[tuple]:
    """``[(feature, psi), ...]`` sorted worst-first, over the features
    both sides carry — the `nerrf quality` table and the doctor's drift
    section."""
    out = []
    for name in sorted(set(reference) & set(live)):
        try:
            out.append((name, psi(reference[name], live[name], alpha)))
        except ValueError:
            continue  # ladder drift between schema versions: skip, not crash
    out.sort(key=lambda t: -t[1])
    return out
