import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nerrf_tpu.data import SimConfig, simulate_trace
from nerrf_tpu.data.sequences import SEQ_FEATURE_DIM, build_file_sequences
from nerrf_tpu.graph import GraphConfig
from nerrf_tpu.models import (
    GraphSAGEConfig,
    GraphSAGET,
    ImpactLSTM,
    JointConfig,
    LSTMConfig,
    NerrfNet,
)
from nerrf_tpu.models.graphsage import count_params
from nerrf_tpu.train.data import DatasetConfig, build_dataset
from nerrf_tpu.train.loop import model_inputs


def _trace():
    return simulate_trace(
        SimConfig(duration_sec=90.0, attack=True, attack_start_sec=30.0,
                  num_target_files=5, min_file_bytes=64 * 1024,
                  max_file_bytes=96 * 1024, chunk_bytes=32 * 1024,
                  benign_rate_hz=20.0, seed=1)
    )


def _dataset():
    cfg = DatasetConfig(
        graph=GraphConfig(window_sec=45.0, stride_sec=20.0, max_nodes=64, max_edges=128),
        seq_len=24, max_seqs=32,
    )
    return build_dataset([_trace()], cfg)


def test_graphsage_forward_shapes_and_masking():
    ds = _dataset()
    a = ds.arrays
    model = GraphSAGET(GraphSAGEConfig(hidden=32, num_layers=3))
    args = (a["node_feat"][0], a["node_type"][0], a["node_aux"][0], a["node_mask"][0],
            a["edge_src"][0], a["edge_dst"][0], a["edge_feat"][0], a["edge_mask"][0])
    params = model.init(jax.random.PRNGKey(0), *args)["params"]
    out = model.apply({"params": params}, *args)
    assert out["edge_logit"].shape == (128,)
    assert out["node_logit"].shape == (64,)
    assert out["node_emb"].shape == (64, 32)
    # masked slots forced to large-negative logits
    em = np.asarray(a["edge_mask"][0])
    assert np.all(np.asarray(out["edge_logit"])[~em] == -30.0)
    assert np.isfinite(np.asarray(out["edge_logit"])).all()


def test_graphsage_rev_view_matches_unsorted_path():
    """The src-sorted reverse-aggregation view is a pure reordering: node
    outputs must match the unsorted segment path up to float summation
    order (it exists so both directions ride the banded Pallas kernel)."""
    from nerrf_tpu.models.graphsage import SageBlock

    ds = _dataset()
    a = ds.arrays
    rng = np.random.default_rng(5)
    h = jnp.asarray(rng.normal(size=(64, 16)), jnp.float32)
    e_emb = jnp.asarray(rng.normal(size=(128, 16)), jnp.float32)
    src = a["edge_src"][0]
    dst = a["edge_dst"][0]
    w = jnp.asarray(rng.uniform(0.1, 1.0, 128), jnp.float32)

    block = SageBlock(16, dtype=jnp.float32)
    params = block.init(jax.random.PRNGKey(1), h, e_emb, src, dst, w, 64)["params"]
    plain = block.apply({"params": params}, h, e_emb, src, dst, w, 64)

    order = jnp.argsort(src)
    rev_view = (jnp.take(src, order), jnp.take(dst, order),
                jnp.take(e_emb, order, axis=0), jnp.take(w, order))
    viewed = block.apply({"params": params}, h, e_emb, src, dst, w, 64,
                         rev_view=rev_view)
    np.testing.assert_allclose(np.asarray(plain), np.asarray(viewed),
                               rtol=1e-4, atol=1e-5)


def test_graphsage_param_count_matches_spec():
    """Spec: ~28 layers, ~2M params (architecture.mdx:52)."""
    ds = _dataset()
    a = ds.arrays
    model = GraphSAGET(GraphSAGEConfig())  # full-size config
    args = (a["node_feat"][0], a["node_type"][0], a["node_aux"][0], a["node_mask"][0],
            a["edge_src"][0], a["edge_dst"][0], a["edge_feat"][0], a["edge_mask"][0])
    params = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), *args)
    )["params"]
    n = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))
    assert 1_800_000 <= n <= 2_600_000, n
    assert GraphSAGEConfig().num_layers == 28


def test_lstm_padding_invariance():
    """Left-padding must not change the prediction for the same events."""
    rng = np.random.default_rng(0)
    T, F = 16, SEQ_FEATURE_DIM
    ev = rng.normal(size=(1, 6, F)).astype(np.float32)
    short = np.zeros((1, T, F), np.float32)
    short[:, T - 6:] = ev
    mask_short = np.zeros((1, T), np.bool_)
    mask_short[:, T - 6:] = True
    longpad = np.zeros((1, T + 8, F), np.float32)
    longpad[:, T + 8 - 6:] = ev
    mask_long = np.zeros((1, T + 8), np.bool_)
    mask_long[:, T + 8 - 6:] = True

    model = ImpactLSTM(LSTMConfig(hidden=16, num_layers=1, dropout=0.0))
    params = model.init(jax.random.PRNGKey(1), jnp.asarray(short), jnp.asarray(mask_short))["params"]
    o1 = model.apply({"params": params}, jnp.asarray(short), jnp.asarray(mask_short))
    o2 = model.apply({"params": params}, jnp.asarray(longpad), jnp.asarray(mask_long))
    np.testing.assert_allclose(
        np.asarray(o1["seq_logit"]), np.asarray(o2["seq_logit"]), rtol=2e-2, atol=2e-2
    )


def test_sequences_builder():
    tr = _trace()
    seqs = build_file_sequences(tr, labels=tr.labels, seq_len=24)
    assert seqs.feat.shape[1:] == (24, SEQ_FEATURE_DIM)
    assert len(seqs) == len(np.unique(seqs.inode))
    # attacked files labelled
    assert seqs.label.max() == 1.0 and seqs.label.min() == 0.0
    # left padding: mask is a suffix
    for i in range(len(seqs)):
        m = seqs.mask[i]
        first = np.argmax(m)
        assert m[first:].all()
    # no feature mass on padded steps
    assert np.abs(seqs.feat[~seqs.mask]).sum() == 0.0


def test_nerrfnet_joint_forward():
    ds = _dataset()
    a = {k: jnp.asarray(v[0]) for k, v in ds.arrays.items()}
    model = NerrfNet(JointConfig().small)
    params = model.init(jax.random.PRNGKey(0), *model_inputs(a))["params"]
    out = model.apply({"params": params}, *model_inputs(a))
    assert set(out) >= {"edge_logit", "node_logit", "seq_logit", "seq_emb", "node_emb"}
    assert out["seq_logit"].shape == (32,)
    assert np.isfinite(np.asarray(out["seq_logit"])).all()


def test_nerrfnet_jit_recompile_free():
    """Different windows, same shapes → one compilation."""
    ds = _dataset()
    model = NerrfNet(JointConfig().small)
    a0 = {k: jnp.asarray(v[0]) for k, v in ds.arrays.items()}
    params = model.init(jax.random.PRNGKey(0), *model_inputs(a0))["params"]
    fwd = jax.jit(lambda p, *args: model.apply({"params": p}, *args))
    fwd(params, *model_inputs(a0))
    n0 = fwd._cache_size()
    for i in range(1, min(4, len(ds))):
        ai = {k: jnp.asarray(v[i]) for k, v in ds.arrays.items()}
        fwd(params, *model_inputs(ai))
    assert fwd._cache_size() == n0 == 1


def test_gnn_aggregation_paths_parity():
    """All three aggregation shapes — dense_adj (one [N,N] matmul per
    layer), fused (one sage_aggregate kernel per layer) and segment
    (gather + banded segment-mean) — must compute the same aggregation on
    the same param tree: the bench times dense/fused, training checkpoints
    must load into any of them."""
    import dataclasses

    import jax

    from nerrf_tpu.models.graphsage import GraphSAGEConfig, GraphSAGET

    ds = _dataset()
    gin = ("node_feat", "node_type", "node_aux", "node_mask", "edge_src",
           "edge_dst", "edge_feat", "edge_mask")
    args = tuple(np.asarray(ds.arrays[k][1]) for k in gin)
    cfg_s = GraphSAGEConfig(hidden=32, num_layers=4, dropout=0.0,
                            aggregation="segment")
    gs = GraphSAGET(cfg_s)
    p = gs.init(jax.random.PRNGKey(0), *args)["params"]
    os_ = gs.apply({"params": p}, *args)
    for mode in ("dense_adj", "fused"):
        gm = GraphSAGET(dataclasses.replace(cfg_s, aggregation=mode))
        pm = gm.init(jax.random.PRNGKey(0), *args)["params"]
        assert (jax.tree_util.tree_structure(p)
                == jax.tree_util.tree_structure(pm)), mode
        om = gm.apply({"params": p}, *args)
        for k in ("edge_logit", "node_logit"):
            err = np.max(np.abs(np.asarray(om[k], np.float32)
                                - np.asarray(os_[k], np.float32)))
            assert err < 0.15, (mode, k, err)  # bf16 reorder noise, 4 layers


def test_gnn_fused_mode_gradient_parity():
    """The fused path must TRAIN identically, not just infer: parameter
    gradients through the fused-mode wiring (pre-normalized views + the
    XLA composition this CPU suite dispatches to) must match the segment
    oracle in f32.  The fused KERNEL's custom VJP is covered separately:
    tests/test_ops_fused.py runs model-level gradients with the
    interpret-mode Pallas kernel registered."""
    import dataclasses

    import jax

    from nerrf_tpu.models.graphsage import GraphSAGEConfig, GraphSAGET

    ds = _dataset()
    gin = ("node_feat", "node_type", "node_aux", "node_mask", "edge_src",
           "edge_dst", "edge_feat", "edge_mask")
    args = tuple(np.asarray(ds.arrays[k][1]) for k in gin)
    cfg = GraphSAGEConfig(hidden=16, num_layers=2, dropout=0.0,
                          dtype=jnp.float32, aggregation="segment")
    m_s = GraphSAGET(cfg)
    m_f = GraphSAGET(dataclasses.replace(cfg, aggregation="fused"))
    p = m_s.init(jax.random.PRNGKey(1), *args)["params"]

    def loss(model):
        return lambda pp: jnp.sum(
            model.apply({"params": pp}, *args)["node_logit"] ** 2)

    gseg = jax.grad(loss(m_s))(p)
    gfus = jax.grad(loss(m_f))(p)
    errs = jax.tree_util.tree_map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), gseg, gfus)
    worst = max(jax.tree_util.tree_leaves(errs))
    assert worst < 1e-3, errs


def test_lstm_impl_paths_parity():
    """fused (one scan, both directions, hoisted input projections) and
    rnn (flax RNN/OptimizedLSTMCell) must agree exactly in f32 on shared
    params, including ragged lengths and an all-pad row."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from nerrf_tpu.models.lstm import ImpactLSTM, LSTMConfig

    rng = np.random.default_rng(0)
    B, T, F = 6, 20, 12
    feat = rng.normal(size=(B, T, F)).astype(np.float32)
    lengths = np.array([20, 13, 7, 1, 0, 19])
    mask = np.zeros((B, T), bool)
    for i, L in enumerate(lengths):
        if L:
            mask[i, T - L:] = True  # left-padded: valid suffix
    feat = feat * mask[..., None]

    cfg_f = LSTMConfig(hidden=16, num_layers=2, dropout=0.0,
                       dtype=jnp.float32, impl="fused")
    cfg_r = dataclasses.replace(cfg_f, impl="rnn")
    mf, mr = ImpactLSTM(cfg_f), ImpactLSTM(cfg_r)
    p = mf.init(jax.random.PRNGKey(0), feat, mask)["params"]
    pr = mr.init(jax.random.PRNGKey(0), feat, mask)["params"]
    assert (jax.tree_util.tree_structure(p)
            == jax.tree_util.tree_structure(pr))
    of = mf.apply({"params": p}, feat, mask)
    orr = mr.apply({"params": p}, feat, mask)
    for k in ("seq_logit", "seq_emb"):
        err = np.max(np.abs(np.asarray(of[k]) - np.asarray(orr[k])))
        assert err < 1e-4, (k, err)
