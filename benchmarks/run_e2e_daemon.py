#!/usr/bin/env python3
"""End-to-end artifact with the NATIVE DAEMON in the loop (VERDICT r4 #3).

Until r5, every trace a detector consumed was handed over in-process; no
model had ever scored bytes that crossed the real wire.  This harness
closes that: a real-file incident (`nerrf simulate` attacks actual files
on disk) is streamed by `nerrf-trackerd --replay` through its hand-rolled
HTTP/2 gRPC server, drained by the deployed ingest CLI (stock grpcio →
native C++ decode → time-bucketed trace store), read back OUT of the
store, and only THAT copy drives detect → plan → sandbox gate → undo on
the still-encrypted files.

  simulate ──> trace.jsonl ──> trackerd --replay ══HTTP/2══> nerrf ingest
       │                                                        │
       └─ victim files (encrypted, on disk)          wire_store segments
                                                              │
          undo <── wire_trace.jsonl <── TraceStore.query ─────┘

This is the reference's tracker-in-loop intent (`tracker/scripts/test.sh:
76-82` drives the Go daemon with grpcurl) carried through to recovery —
which the reference never built.  Live CAP_BPF capture replaces --replay
on hosts that allow it (`tests/test_capture.py` covers that path).

Usage:
  python benchmarks/run_e2e_daemon.py --out benchmarks/results/e2e_daemon.json
  ... [--files 20] [--rate 500] [--model-dir runs/probe-corpus-cpu/model]
"""

from __future__ import annotations

import argparse
import json
import re
import shutil
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def _log(msg):
    print(f"[e2e] {msg}", file=sys.stderr, flush=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="benchmarks/results/e2e_daemon.json")
    ap.add_argument("--incident", default="/tmp/nerrf_e2e_daemon")
    ap.add_argument("--files", type=int, default=20)
    ap.add_argument("--rate", type=int, default=500,
                    help="replay pacing, events/s (VERDICT asks ~500)")
    ap.add_argument("--model-dir", default=None,
                    help="detector checkpoint; default: probe checkpoint "
                         "when present, else heuristic")
    ap.add_argument("--seed", type=int, default=11)
    args = ap.parse_args(argv)

    daemon = REPO / "native" / "build" / "nerrf-trackerd"
    if not daemon.exists():
        r = subprocess.run(["make", "-C", str(REPO / "native"),
                            "build/nerrf-trackerd"],
                           capture_output=True, text=True)
        if r.returncode != 0:
            _log(f"daemon build failed: {r.stderr[-400:]}")
            return 1

    model_dir = args.model_dir
    if model_dir is None:
        probe = REPO / "runs" / "probe-corpus-cpu" / "model"
        model_dir = str(probe) if probe.exists() else None

    t0 = time.time()
    inc = Path(args.incident)
    if inc.exists():
        shutil.rmtree(inc)

    # --- 1. real-file incident ---------------------------------------------
    _log(f"simulate: {args.files} files under {inc}/victim")
    r = subprocess.run(
        [sys.executable, "-m", "nerrf_tpu.cli", "simulate",
         "--incident", str(inc), "--files", str(args.files),
         "--seed", str(args.seed)],
        cwd=REPO, capture_output=True, text=True)
    assert r.returncode == 0, r.stderr[-800:]
    n_src = sum(1 for _ in open(inc / "trace.jsonl"))

    # --- 2. native daemon replays the incident over HTTP/2 ------------------
    proc = subprocess.Popen(
        [str(daemon), "--listen", "127.0.0.1:0",
         "--replay", str(inc / "trace.jsonl"),
         "--replay-rate", str(args.rate)],
        stderr=subprocess.PIPE, text=True)
    port = None
    deadline = time.time() + 10
    lines = []
    while time.time() < deadline:
        line = proc.stderr.readline()
        lines.append(line)
        m = re.search(r"\(port (\d+)\)", line)
        if m:
            port = int(m.group(1))
            break
    assert port, f"daemon never reported a port: {lines}"
    _log(f"trackerd replaying {n_src} events at ~{args.rate}/s on :{port}")

    # --- 3. deployed ingest: grpcio -> native decode -> store ---------------
    t_ing = time.time()
    r = subprocess.run(
        [sys.executable, "-m", "nerrf_tpu.cli", "ingest",
         "--target", f"127.0.0.1:{port}",
         "--store-dir", str(inc / "wire_store"),
         "--metrics-port", "-1", "--timeout", "120"],
        cwd=REPO, capture_output=True, text=True, timeout=180)
    proc.terminate()
    proc.wait(timeout=10)
    assert r.returncode == 0, r.stderr[-800:]
    ingest = json.loads(r.stdout)
    wire_seconds = round(time.time() - t_ing, 1)
    _log(f"ingest: {ingest['events']} events, "
         f"{ingest['segments_written']} segments in {wire_seconds}s")

    # --- 4. read back out of the store; wire parity --------------------------
    from nerrf_tpu.graph.store import TraceStore
    from nerrf_tpu.schema.events import events_to_jsonl

    with TraceStore(inc / "wire_store") as st:
        events, strings = st.query(0, 2**63 - 1)
    n_wire = int(events.num_valid)
    (inc / "wire_trace.jsonl").write_text(events_to_jsonl(events, strings))
    _log(f"store read-back: {n_wire} events (source {n_src})")
    assert n_wire == n_src, f"wire loss: {n_src} sent, {n_wire} stored"

    # --- 5. detect -> plan -> gate -> undo on the WIRE copy ------------------
    undo_cmd = [sys.executable, "-m", "nerrf_tpu.cli", "undo",
                "--incident", str(inc),
                "--trace", str(inc / "wire_trace.jsonl")]
    if model_dir:
        undo_cmd += ["--model-dir", model_dir]
    t_undo = time.time()
    r = subprocess.run(undo_cmd, cwd=REPO, capture_output=True, text=True,
                       timeout=1200)
    undo_log = r.stderr[-2000:]
    _log(undo_log.strip().splitlines()[-1] if undo_log.strip() else "(no log)")
    assert r.returncode == 0, undo_log

    report = json.loads((inc / "report.json").read_text())
    gate = json.loads((inc / "gate.json").read_text())
    plan = json.loads((inc / "plan.json").read_text())

    artifact = {
        "flow": "simulate -> trackerd --replay (HTTP/2) -> ingest -> "
                "store -> detect -> plan -> gate -> undo",
        "daemon": "native/build/nerrf-trackerd (hand-rolled h2grpc)",
        "detector": f"checkpoint:{model_dir}" if model_dir else "heuristic",
        "events": {"source": n_src, "wire": n_wire, "lost": n_src - n_wire},
        "replay_rate_hz": args.rate,
        "wire_seconds": wire_seconds,
        "store_segments": ingest["segments_written"],
        "detection_flagged": len(plan.get("actions", [])),
        "gate_approved": gate.get("approved"),
        "undo": {
            "files_restored": report.get("files_restored"),
            "verified": report.get("verified"),
            "data_loss_bytes": report.get("data_loss_bytes", 0),
            "mttr_seconds": report.get("mttr_seconds"),
            "undo_wall_seconds": round(time.time() - t_undo, 1),
        },
        "provenance": "python benchmarks/run_e2e_daemon.py",
        "wall_seconds": round(time.time() - t0, 1),
    }
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(artifact, indent=2) + "\n")
    print(json.dumps({"events_wire": n_wire,
                      "verified": report.get("verified"),
                      "mttr_seconds": report.get("mttr_seconds")}))
    return 0 if report.get("verified") else 1


if __name__ == "__main__":
    raise SystemExit(main())
