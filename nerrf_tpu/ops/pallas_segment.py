"""Hand-tiled Pallas TPU kernels for sparse neighbor aggregation.

The reference framework never built its AI subsystem, so it has no sparse ops;
the north star requires neighbor aggregation and sampling gathers as Pallas
kernels (SURVEY.md §7 step 2).  On TPU the fastest formulation of a segment
reduction at our graph sizes (N ≤ a few thousand nodes, E ≤ a few thousand
edges, F ≤ 512 features) is *not* a scatter at all — scatters serialize on the
VPU — but a one-hot contraction that rides the 128×128 MXU:

    out[n, f] = Σ_e [seg_ids[e] == n] · data[e, f]

i.e. ``onehotᵀ @ data``.  The kernel tiles (segments × features) over the grid
and accumulates over edge tiles, building each one-hot block in VMEM with a
broadcasted iota compare (never materializing the full [E, N] matrix in HBM).
The same trick gives the row gather ``table[idx]`` as ``onehot @ table``.

Both kernels are order-independent (no sorted-ids requirement) and carry
custom VJPs — the adjoint of a segment-sum is a row gather and vice versa, so
the backward passes reuse the same two kernels.

Use :func:`register` to install these as the implementation behind
``nerrf_tpu.ops.segment_sum`` / ``gather_rows``; ``segment.py`` auto-registers
on first use when the active backend is TPU (opt out: ``NERRF_NO_PALLAS=1``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Tile sizes: lane dim is always 128; 128 edge rows per accumulation step
# keeps the one-hot block square on the MXU.
_TN = 128  # segment (output-row) tile
_TE = 128  # edge (contraction) tile
_TF = 128  # feature tile


def _pad_to(x: jnp.ndarray, axis: int, mult: int, value) -> jnp.ndarray:
    size = x.shape[axis]
    rem = (-size) % mult
    if rem == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, rem)
    return jnp.pad(x, widths, constant_values=value)


def _band_ptrs(ids, n_pad):
    """Band pointers over a nondecreasing [Ep, 1] id column padded with
    ``n_pad``: edges for segment tile i live in edge tiles [t0[i], t1[i]).
    Shared by every banded kernel (sorted segment sum, fused SAGE) so the
    out-of-band convention cannot desynchronize between them."""
    bounds = jnp.searchsorted(
        ids[:, 0], jnp.arange(0, n_pad + 1, _TN, dtype=jnp.int32))
    return ((bounds[:-1] // _TE).astype(jnp.int32),
            ((bounds[1:] + _TE - 1) // _TE).astype(jnp.int32))


# --- segment sum -------------------------------------------------------------


def _accumulate_onehot(ids_ref, data_ref, out_ref, seg_base):
    """out += onehot(ids, seg_base..seg_base+TN)ᵀ @ data — the shared MXU
    contraction body of both segment-sum kernels."""
    ids = ids_ref[:]  # [TE, 1] int32
    cols = jax.lax.broadcasted_iota(jnp.int32, (_TE, _TN), 1) + seg_base
    onehot = (ids == cols).astype(jnp.float32)  # [TE, TN]
    out_ref[:] += jax.lax.dot_general(
        onehot,
        data_ref[:].astype(jnp.float32),
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _segment_sum_kernel(ids_ref, data_ref, out_ref):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    _accumulate_onehot(ids_ref, data_ref, out_ref, pl.program_id(0) * _TN)


def _segment_sum_call(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    E, F = data.shape
    # nerrflint: ok[recompile-hazard] num_segments is a static shape arg;
    if E == 0 or F == 0 or num_segments == 0:  # degenerate: nothing to tile
        return jnp.zeros((num_segments, F), data.dtype)
    ids = _pad_to(segment_ids.astype(jnp.int32).reshape(-1, 1), 0, _TE, -1)
    dat = _pad_to(_pad_to(data, 0, _TE, 0), 1, _TF, 0)
    n_pad = num_segments + ((-num_segments) % _TN)
    Ep, Fp = dat.shape

    grid = (n_pad // _TN, Fp // _TF, Ep // _TE)
    out = pl.pallas_call(
        _segment_sum_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TE, 1), lambda i, j, k: (k, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_TE, _TF), lambda i, j, k: (k, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (_TN, _TF), lambda i, j, k: (i, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, Fp), jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=2 * Ep * n_pad * Fp,
            bytes_accessed=4 * (Ep * Fp + n_pad * Fp) + 4 * Ep,
            transcendentals=0,
        ),
        interpret=interpret,
    )(ids, dat)
    return out[:num_segments, :F].astype(data.dtype)


# --- sorted (banded) segment sum ---------------------------------------------
#
# The dense kernel above contracts every (segment-tile × edge-tile) pair —
# O(N·E·F) MXU work, fine at toy capacity but quadratic at the ~25k-event
# density (VERDICT r1: the crossover risk).  The graph builder emits edges
# sorted by destination with padding slots pointing at the last node
# (builder.py:458-478), so ``edge_dst`` is globally nondecreasing — and then
# each segment tile only receives contributions from a contiguous *band* of
# edge tiles.  This variant prefetches the per-segment-tile band pointers as
# scalars, skips the dot for grid cells outside the band, and freezes the
# input block index once past the band so Mosaic elides the repeated copies:
# MXU work and HBM traffic become O((E + N)·F) for bounded in-degree skew.


def _segment_sum_sorted_kernel(t0_ref, t1_ref, ids_ref, data_ref, out_ref):
    i = pl.program_id(0)
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    @pl.when(t0_ref[i] + k < t1_ref[i])
    def _():
        _accumulate_onehot(ids_ref, data_ref, out_ref, i * _TN)


def _segment_sum_sorted_call(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """Banded segment sum; ``segment_ids`` must be nondecreasing."""
    E, F = data.shape
    # nerrflint: ok[recompile-hazard] num_segments is a static shape arg;
    if E == 0 or F == 0 or num_segments == 0:  # degenerate: nothing to tile
        return jnp.zeros((num_segments, F), data.dtype)
    n_pad = num_segments + ((-num_segments) % _TN)
    # pad ids with n_pad: ≥ every valid id (keeps the vector sorted) and
    # beyond the last column tile (matches no output row)
    ids = _pad_to(segment_ids.astype(jnp.int32).reshape(-1, 1), 0, _TE, n_pad)
    dat = _pad_to(_pad_to(data, 0, _TE, 0), 1, _TF, 0)
    Ep, Fp = dat.shape
    n_tiles, f_tiles, e_tiles = n_pad // _TN, Fp // _TF, Ep // _TE

    t0, t1 = _band_ptrs(ids, n_pad)

    def _edge_tile(i, k, t0r, t1r):
        # freeze on the band's last tile once k passes it → consecutive
        # identical block indices, whose copies Mosaic elides; the final
        # clamp keeps even empty-band-past-the-end tiles (t0 == t1 ==
        # e_tiles) inside the valid block range
        return jnp.minimum(
            jnp.minimum(t0r[i] + k, jnp.maximum(t1r[i] - 1, t0r[i])),
            e_tiles - 1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_tiles, f_tiles, e_tiles),
        in_specs=[
            pl.BlockSpec((_TE, 1),
                         lambda i, j, k, t0r, t1r: (_edge_tile(i, k, t0r, t1r), 0)),
            pl.BlockSpec((_TE, _TF),
                         lambda i, j, k, t0r, t1r: (_edge_tile(i, k, t0r, t1r), j)),
        ],
        out_specs=pl.BlockSpec((_TN, _TF), lambda i, j, k, t0r, t1r: (i, j)),
    )
    out = pl.pallas_call(
        _segment_sum_sorted_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_pad, Fp), jnp.float32),
        # typical-case banded cost (band ≈ 2 edge tiles per segment tile);
        # the dense kernels' estimates are the quadratic upper bound
        cost_estimate=pl.CostEstimate(
            flops=2 * 2 * _TE * n_pad * Fp,
            bytes_accessed=4 * (2 * n_pad * _TE // _TN * Fp + n_pad * Fp)
            + 4 * Ep,
            transcendentals=0,
        ),
        interpret=interpret,
    )(t0, t1, ids, dat)
    return out[:num_segments, :F].astype(data.dtype)


# --- sorted row gather (the banded sum's adjoint) ----------------------------
#
# grad_data[e] = g[ids[e]] with *nondecreasing* ids: edge tile k only reads
# rows from the contiguous band of segment tiles spanned by
# ids[k·TE .. (k+1)·TE).  A tile holds 128 edges, so the band covers at most
# 128 segment tiles and for dense-ish sorted ids (the builder's layout)
# typically one or two; the grid's band dimension spans the worst case and
# runtime-skips past each tile's actual band, with the block index frozen so
# the repeated copies are elided.  The backward of the banded segment sum
# therefore stays linear as well (the dense gather would hand the quadratic
# cost right back in training, where ~2/3 of the FLOPs live).


def _gather_sorted_kernel(s0_ref, s1_ref, nt_ref, idx_ref, table_ref, out_ref):
    k = pl.program_id(0)
    b = pl.program_id(2)

    @pl.when(b == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    @pl.when((s0_ref[k] + b < s1_ref[k]) & (s0_ref[k] + b < nt_ref[0]))
    def _():
        _gather_onehot(idx_ref, table_ref, out_ref, (s0_ref[k] + b) * _TN)


def _gather_sorted_call(
    table: jnp.ndarray, idx: jnp.ndarray, *, interpret: bool = False
) -> jnp.ndarray:
    """Row gather ``table[idx]`` for nondecreasing ``idx``."""
    N, F = table.shape
    E = idx.shape[0]
    if E == 0 or F == 0 or N == 0:  # degenerate: nothing to tile
        return jnp.zeros((E, F), table.dtype)
    n_pad = N + ((-N) % _TN)
    # pad ids with n_pad: keeps the vector sorted, matches no table row
    ids = _pad_to(idx.astype(jnp.int32).reshape(-1, 1), 0, _TE, n_pad)
    tab = _pad_to(_pad_to(table, 0, _TN, 0), 1, _TF, 0)
    Ep = ids.shape[0]
    Np, Fp = tab.shape
    e_tiles, f_tiles, n_tiles = Ep // _TE, Fp // _TF, Np // _TN

    # per-edge-tile band of segment tiles: [s0, s1); width is typically 1-2
    # for dense-ish sorted ids but can reach min(TE, n_tiles) when sparse,
    # so the grid spans the worst case and runtime-skips the rest
    first = ids[::_TE, 0]
    last = ids[_TE - 1::_TE, 0]
    s0 = (first // _TN).astype(jnp.int32)
    s1 = (last // _TN + 1).astype(jnp.int32)
    nt = jnp.full((1,), n_tiles, jnp.int32)

    def _seg_tile(k, b, s0r, s1r, ntr):
        # freeze on the band's last tile once b passes it (identical block
        # indices → elided copies); the final clamp keeps all-pad edge
        # tiles (whose band starts at n_tiles) inside the valid range
        return jnp.minimum(
            jnp.minimum(s0r[k] + b, jnp.maximum(s1r[k] - 1, s0r[k])),
            ntr[0] - 1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(e_tiles, f_tiles, n_tiles),
        in_specs=[
            pl.BlockSpec((_TE, 1), lambda k, j, b, s0r, s1r, ntr: (k, 0)),
            pl.BlockSpec((_TN, _TF),
                         lambda k, j, b, s0r, s1r, ntr:
                         (_seg_tile(k, b, s0r, s1r, ntr), j)),
        ],
        out_specs=pl.BlockSpec((_TE, _TF),
                               lambda k, j, b, s0r, s1r, ntr: (k, j)),
    )
    out = pl.pallas_call(
        _gather_sorted_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((Ep, Fp), jnp.float32),
        # typical-case banded cost (band ≈ 2 segment tiles per edge tile)
        cost_estimate=pl.CostEstimate(
            flops=2 * 2 * _TN * Ep * Fp,
            bytes_accessed=4 * (2 * Ep * _TN // _TE * Fp + Ep * Fp) + 4 * Ep,
            transcendentals=0,
        ),
        interpret=interpret,
    )(s0, s1, nt, ids, tab)
    return out[:E, :F].astype(table.dtype)


# --- row gather --------------------------------------------------------------


def _gather_onehot(idx_ref, table_ref, out_ref, row_base):
    """out += onehot(idx, row_base..row_base+TN) @ table — the shared MXU
    body of both gather kernels."""
    idx = idx_ref[:]  # [TE, 1] int32
    cols = jax.lax.broadcasted_iota(jnp.int32, (_TE, _TN), 1) + row_base
    onehot = (idx == cols).astype(jnp.float32)  # [TE, TN]
    out_ref[:] += jnp.dot(
        onehot, table_ref[:].astype(jnp.float32), preferred_element_type=jnp.float32
    )


def _gather_kernel(idx_ref, table_ref, out_ref):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    _gather_onehot(idx_ref, table_ref, out_ref, pl.program_id(2) * _TN)


def _gather_call(
    table: jnp.ndarray, idx: jnp.ndarray, *, interpret: bool = False
) -> jnp.ndarray:
    N, F = table.shape
    E = idx.shape[0]
    if E == 0 or F == 0 or N == 0:  # degenerate: nothing to tile
        return jnp.zeros((E, F), table.dtype)
    ids = _pad_to(idx.astype(jnp.int32).reshape(-1, 1), 0, _TE, -1)
    tab = _pad_to(_pad_to(table, 0, _TN, 0), 1, _TF, 0)
    Ep = ids.shape[0]
    Np, Fp = tab.shape

    grid = (Ep // _TE, Fp // _TF, Np // _TN)
    out = pl.pallas_call(
        _gather_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TE, 1), lambda i, j, k: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_TN, _TF), lambda i, j, k: (k, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (_TE, _TF), lambda i, j, k: (i, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((Ep, Fp), jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=2 * Ep * Np * Fp,
            bytes_accessed=4 * (Np * Fp + Ep * Fp) + 4 * Ep,
            transcendentals=0,
        ),
        interpret=interpret,
    )(ids, tab)
    return out[:E, :F].astype(table.dtype)


# --- fused bidirectional SAGE aggregation ------------------------------------
#
# The segment path above serves the GNN as ~6 small kernels per layer (two
# row gathers + two segment-mean numerator/denominator pairs), each paying
# the runtime's ~0.27 ms fixed launch cost (r5 profile) — at 28 layers that
# is ~168 sequential launches per window, and gather/scatter launch overhead
# is exactly what dominates TPU GNN runtimes in the accelerator benchmarking
# literature (arXiv:2210.12247).  The dense_adj alternative is one matmul
# per layer but materializes an [N, N] adjacency: 64 MB and O(N²·H) MXU work
# at the deployed 4096-node bucket, for graphs with E ≪ N².
#
# This kernel is the third shape: ONE `pallas_call` per layer, O(E·H) work.
# Both directions of the bidirectional weighted-mean aggregate
#
#     out[n] = Σ_{e: dst(e)=n} ŵf(e)·msg[src(e)] + Σ_{e: src(e)=n} ŵr(e)·msg[dst(e)]
#
# are computed blocked-CSR style over the builder's dst-sorted edge list and
# the model's precomputed src-sorted view: per output tile of 128 nodes, the
# contributing edges live in a contiguous *band* of edge tiles (scalar-
# prefetched band pointers, exactly like the banded segment sum above).  For
# each in-band edge tile the kernel gathers the 128 source rows of `msg`
# into a VMEM scratch with dynamic row loads, then scatter-accumulates them
# onto the output tile as one weighted one-hot MXU contraction.  Gather +
# weight + accumulate all happen in VMEM; the weights arrive pre-normalized
# (ŵ = w / max(Σw, ε), computed once per forward, NOT per layer), so no
# normalization pass is needed and empty segments stay exactly zero.
#
# The adjoint of out = (Wf + Wr)@msg is (Wfᵀ + Wrᵀ)@g — the SAME operation
# with the two directions' weights exchanged across the two sorted views
# (Wfᵀ scatters to src, i.e. rides the src-sorted band with the fwd weights;
# Wrᵀ symmetrically) — so the backward pass is one more call to this kernel
# and training stays at one kernel per layer per pass.


def _sage_band_tile(i, k, t0, t1, e_tiles):
    """Edge tile for band step ``k`` of output tile ``i``: freeze on the
    band's last tile once past it (identical consecutive block indices →
    Mosaic elides the copies) and clamp into the valid block range."""
    return jnp.minimum(
        jnp.minimum(t0[i] + k, jnp.maximum(t1[i] - 1, t0[i])), e_tiles - 1)


def _sage_kernel(t0f_ref, t1f_ref, t0r_ref, t1r_ref, srcg_ref, dstg_ref,
                 dstid_ref, wf_ref, srcid_ref, wr_ref, msg_ref,
                 out_ref, scratch_ref):
    i = pl.program_id(1)  # output (node) tile
    k = pl.program_id(2)  # band step

    @pl.when(k == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    def _accumulate_direction(t0_ref, t1_ref, gidx_ref, ids_ref, w_ref):
        tile = t0_ref[i] + k

        @pl.when(tile < t1_ref[i])
        def _():
            # gather the tile's 128 source rows into VMEM scratch (indices
            # stream from SMEM scalar prefetch; padded edges index row 0
            # and carry weight 0, so they contribute nothing)
            def body(e, carry):
                r = gidx_ref[tile * _TE + e]
                scratch_ref[pl.ds(e, 1), :] = msg_ref[pl.ds(r, 1), :]
                return carry

            jax.lax.fori_loop(0, _TE, body, 0)
            # weighted one-hot scatter-accumulate on the MXU: fold the
            # pre-normalized edge weight into the one-hot block
            ids = ids_ref[:]  # [TE, 1] int32
            cols = jax.lax.broadcasted_iota(jnp.int32, (_TE, _TN), 1) + i * _TN
            ow = (ids == cols).astype(jnp.float32) * w_ref[:]
            out_ref[:] += jax.lax.dot_general(
                ow, scratch_ref[:],
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    _accumulate_direction(t0f_ref, t1f_ref, srcg_ref, dstid_ref, wf_ref)
    _accumulate_direction(t0r_ref, t1r_ref, dstg_ref, srcid_ref, wr_ref)


def _sage_call(msg, dst_ids, src_by_dst, w_dst, src_ids, dst_by_src, w_src,
               num_nodes, *, interpret=False):
    """One fused pass: ``Σ w_dst·msg[src_by_dst] → dst_ids`` plus
    ``Σ w_src·msg[dst_by_src] → src_ids``.  ``dst_ids`` and ``src_ids`` must
    be nondecreasing; ``msg`` must have ``num_nodes`` rows."""
    N, F = msg.shape
    E = dst_ids.shape[0]
    # nerrflint: ok[recompile-hazard] num_nodes is a static shape arg;
    if E == 0 or F == 0 or num_nodes == 0:  # degenerate: nothing to tile
        return jnp.zeros((num_nodes, F), msg.dtype)
    n_pad = num_nodes + ((-num_nodes) % _TN)
    # segment ids pad with n_pad: keeps both vectors sorted and matches no
    # output row; gather indices pad with 0 (a valid row) under weight 0
    dstid = _pad_to(dst_ids.astype(jnp.int32).reshape(-1, 1), 0, _TE, n_pad)
    srcid = _pad_to(src_ids.astype(jnp.int32).reshape(-1, 1), 0, _TE, n_pad)
    srcg = _pad_to(src_by_dst.astype(jnp.int32), 0, _TE, 0)
    dstg = _pad_to(dst_by_src.astype(jnp.int32), 0, _TE, 0)
    wf = _pad_to(w_dst.astype(jnp.float32).reshape(-1, 1), 0, _TE, 0.0)
    wr = _pad_to(w_src.astype(jnp.float32).reshape(-1, 1), 0, _TE, 0.0)
    # f32 msg block: single dynamic rows of bf16 would fight the (16, 128)
    # tiling; the one-per-layer [N, F] upcast is noise next to the matmuls
    dat = _pad_to(_pad_to(msg.astype(jnp.float32), 0, _TN, 0), 1, _TF, 0)
    Ep = dstid.shape[0]
    Np, Fp = dat.shape
    f_tiles, n_tiles, e_tiles = Fp // _TF, n_pad // _TN, Ep // _TE

    t0f, t1f = _band_ptrs(dstid, n_pad)
    t0r, t1r = _band_ptrs(srcid, n_pad)

    def _fwd_tile(j, i, k, t0f, t1f, t0r, t1r, sg, dg):
        return (_sage_band_tile(i, k, t0f, t1f, e_tiles), 0)

    def _rev_tile(j, i, k, t0f, t1f, t0r, t1r, sg, dg):
        return (_sage_band_tile(i, k, t0r, t1r, e_tiles), 0)

    # grid order (feature, node, band): the full-height msg block's index
    # depends only on the OUTERMOST dim, so it is copied in once per
    # feature tile and stays VMEM-resident across every node tile and band
    # step; the output tile stays resident across its band.
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(f_tiles, n_tiles, e_tiles),
        in_specs=[
            pl.BlockSpec((_TE, 1), _fwd_tile),                    # dst ids
            pl.BlockSpec((_TE, 1), _fwd_tile),                    # ŵ fwd
            pl.BlockSpec((_TE, 1), _rev_tile),                    # src ids
            pl.BlockSpec((_TE, 1), _rev_tile),                    # ŵ rev
            pl.BlockSpec((Np, _TF),
                         lambda j, i, k, *refs: (0, j)),          # msg
        ],
        out_specs=pl.BlockSpec((_TN, _TF), lambda j, i, k, *refs: (i, j)),
        scratch_shapes=[pltpu.VMEM((_TE, _TF), jnp.float32)],
    )
    out = pl.pallas_call(
        _sage_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_pad, Fp), jnp.float32),
        # typical-case banded cost, two directions (band ≈ 2 edge tiles per
        # node tile per direction)
        cost_estimate=pl.CostEstimate(
            flops=2 * 2 * 2 * _TE * n_pad * Fp,
            bytes_accessed=4 * (Np * Fp + n_pad * Fp + 4 * Ep) + 8 * Ep,
            transcendentals=0,
        ),
        interpret=interpret,
    )(t0f, t1f, t0r, t1r, srcg, dstg, dstid, wf, srcid, wr, dat)
    return out[:num_nodes, :F].astype(msg.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(9, 10))
def sage_aggregate_fused(msg, dst_ids, src_by_dst, src_ids, dst_by_src,
                         wf_d, wf_s, wr_s, wr_d, num_nodes, interpret=False):
    """Fused bidirectional SAGE aggregation, one kernel per call.

    ``(dst_ids, src_by_dst, wf_d)`` is the builder's dst-sorted edge list
    with pre-normalized forward weights; ``(src_ids, dst_by_src, wr_s)`` the
    src-sorted view with pre-normalized reverse weights.  ``wf_s``/``wr_d``
    are the same two weight vectors carried in the *other* view's order —
    unused forward, they are exactly what the adjoint needs (transposing a
    direction swaps which sorted band it rides), keeping backward at one
    kernel too.  Differentiable in ``msg`` only; ids and weights are graph
    structure."""
    return _sage_call(msg, dst_ids, src_by_dst, wf_d, src_ids, dst_by_src,
                      wr_s, num_nodes, interpret=interpret)


def _sage_fwd(msg, dst_ids, src_by_dst, src_ids, dst_by_src,
              wf_d, wf_s, wr_s, wr_d, num_nodes, interpret):
    out = _sage_call(msg, dst_ids, src_by_dst, wf_d, src_ids, dst_by_src,
                     wr_s, num_nodes, interpret=interpret)
    return out, (dst_ids, src_by_dst, src_ids, dst_by_src, wf_s, wr_d)


def _sage_bwd(num_nodes, interpret, res, g):
    dst_ids, src_by_dst, src_ids, dst_by_src, wf_s, wr_d = res
    # (Wf + Wr)ᵀ @ g: Wfᵀ scatters to src — the src-sorted band with the
    # forward weights; Wrᵀ scatters to dst — the dst-sorted band with the
    # reverse weights.  Same kernel, weights exchanged across the views.
    gmsg = _sage_call(g, dst_ids, src_by_dst, wr_d, src_ids, dst_by_src,
                      wf_s, num_nodes, interpret=interpret)
    return (gmsg, None, None, None, None, None, None, None, None)


sage_aggregate_fused.defvjp(_sage_fwd, _sage_bwd)


# --- custom VJPs (adjoint of sum is gather, and vice versa) ------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def segment_sum(data, segment_ids, num_segments, interpret=False):
    """MXU one-hot segment-sum: rows of ``data`` [E, F] → buckets [N, F]."""
    return _segment_sum_call(data, segment_ids, num_segments, interpret=interpret)


def _segment_sum_fwd(data, segment_ids, num_segments, interpret):
    return _segment_sum_call(data, segment_ids, num_segments, interpret=interpret), (
        segment_ids,
    )


def _segment_sum_bwd(num_segments, interpret, res, g):
    (segment_ids,) = res
    return _gather_call(g, segment_ids, interpret=interpret), None


segment_sum.defvjp(_segment_sum_fwd, _segment_sum_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def segment_sum_sorted(data, segment_ids, num_segments, interpret=False):
    """Banded MXU segment-sum for nondecreasing ``segment_ids`` (the graph
    builder's sorted-by-dst edge layout).  Same contract as
    :func:`segment_sum`, linear instead of quadratic MXU work."""
    return _segment_sum_sorted_call(
        data, segment_ids, num_segments, interpret=interpret)


def _segment_sum_sorted_fwd(data, segment_ids, num_segments, interpret):
    return _segment_sum_sorted_call(
        data, segment_ids, num_segments, interpret=interpret), (segment_ids,)


def _segment_sum_sorted_bwd(num_segments, interpret, res, g):
    (segment_ids,) = res
    # adjoint is a gather by the same nondecreasing ids — banded too
    return _gather_sorted_call(g, segment_ids, interpret=interpret), None


segment_sum_sorted.defvjp(_segment_sum_sorted_fwd, _segment_sum_sorted_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def gather_rows(table, idx, interpret=False):
    """MXU one-hot row gather: ``table[idx]`` without an XLA scatter/gather."""
    return _gather_call(table, idx, interpret=interpret)


def _gather_fwd(table, idx, interpret):
    return _gather_call(table, idx, interpret=interpret), (idx, table.shape[0])


def _gather_bwd(interpret, res, g):
    idx, num_rows = res
    return _segment_sum_call(g, idx, num_rows, interpret=interpret), None


gather_rows.defvjp(_gather_fwd, _gather_bwd)


# --- static resource inventory (the deep-lint surface) -----------------------


def kernel_vmem_blocks(num_nodes: int, num_edges: int,
                       num_features: int) -> dict:
    """Per-kernel VMEM block inventory at the given (padded-up) problem
    shape: ``{kernel: [(block, shape, dtype, copies), ...]}``.

    THE static description of what each kernel keeps resident in VMEM per
    grid cell, mirroring the BlockSpecs/scratch_shapes above — kept next
    to the kernels so a tiling change and its budget model move in one
    diff.  ``copies=2`` marks grid-streamed blocks (Mosaic double-buffers
    the HBM→VMEM copies); scratch and accumulator blocks are single.  The
    deep static pass (`nerrf lint --deep`, pallas-budget) costs this
    against the per-core VMEM budget for every serve-ladder bucket, so an
    over-VMEM tile combination fails on CPU in seconds instead of as a
    Mosaic allocation error minutes into a chip run."""
    n_pad = num_nodes + ((-num_nodes) % _TN)
    del num_edges, num_features  # tiled away (_TE rows / _TF lanes per block)
    return {
        "segment_sum": [
            ("ids", (_TE, 1), "int32", 2),
            ("data", (_TE, _TF), "float32", 2),
            ("out", (_TN, _TF), "float32", 1),
        ],
        "segment_sum_sorted": [
            ("ids", (_TE, 1), "int32", 2),
            ("data", (_TE, _TF), "float32", 2),
            ("out", (_TN, _TF), "float32", 1),
        ],
        "gather_rows": [
            ("ids", (_TE, 1), "int32", 2),
            ("table", (_TN, _TF), "float32", 2),
            ("out", (_TE, _TF), "float32", 1),
        ],
        "gather_rows_sorted": [
            ("ids", (_TE, 1), "int32", 2),
            ("table", (_TN, _TF), "float32", 2),
            ("out", (_TE, _TF), "float32", 1),
        ],
        # the fused kernel keeps the FULL-HEIGHT message block resident
        # across every node tile and band step (grid order f, n, e) — the
        # one block here whose footprint grows with the bucket, and the
        # reason the budget check exists
        "sage_fused": [
            ("band_ptrs", (4, max(n_pad // _TN, 1)), "int32", 1),
            ("ids+weights", (4 * _TE, 1), "int32", 2),
            ("msg", (n_pad, _TF), "float32", 2),
            ("out", (_TN, _TF), "float32", 1),
            ("scratch", (_TE, _TF), "float32", 1),
        ],
    }


def tile_constants() -> dict:
    """The kernel tile sizes, exported for the deep pass's divisibility
    check (lane dim 128, f32 sublane 8 — docs/kernel-paths.md)."""
    return {"TN": _TN, "TE": _TE, "TF": _TF}


# --- registration ------------------------------------------------------------


def _sorted_kernels_compile(interpret: bool) -> bool:
    """Compile-probe the banded kernels (fwd + banded adjoint, under vmap
    and grad, on small smoke shapes).  The scalar-prefetch grid is newer
    Mosaic surface than the dense kernels; if this backend rejects it, the
    switchboard must fall back to dense rather than sink every training
    path at first step.  A smoke probe can't rule out shape-specific
    rejections — NERRF_NO_SORTED_PALLAS=1 remains the hard escape hatch."""
    if interpret:  # interpreter mode can't hit Mosaic rejection
        return True
    try:
        # Probe AT the flagship training shapes (2048e/1024n graphs,
        # hidden=160 — configs/joint-100h.json + corpus auto-fit), not a
        # tiny smoke shape: Mosaic rejections can be shape-specific, and a
        # probe that passes at E=160 while every real train step dies at
        # E=1024 defends nothing (r2 advisor finding).  One extra compile
        # per process; the persistent compilation cache makes it one per
        # machine.
        E, N, F = 2048, 1024, 160
        ids = jnp.asarray(np.sort(np.random.default_rng(0).integers(
            0, N, (2, E))), jnp.int32)
        data = jnp.asarray(np.random.default_rng(1).normal(
            size=(2, E, F)), jnp.float32)

        def loss(d):
            out = jax.vmap(
                lambda dd, ii: segment_sum_sorted(dd, ii, N, interpret)
            )(d, ids)
            return jnp.sum(out * out)

        # fetch, not block_until_ready (a no-op on the axon platform):
        # an execute-time kernel failure must raise inside this try or the
        # probe would falsely register the banded kernels as available
        from nerrf_tpu.utils import sync_result

        sync_result(jax.jit(jax.grad(loss))(data))
        return True
    except Exception as e:
        import sys

        print(f"[nerrf_tpu.ops] banded sorted-segment kernels unavailable "
              f"on this backend ({type(e).__name__}: {e}); using the dense "
              "one-hot kernels for sorted calls too", file=sys.stderr)
        return False


def _fused_sage_compiles(interpret: bool) -> bool:
    """Compile-probe the fused SAGE kernel (fwd + adjoint, under vmap and
    grad, at the flagship training shapes — same rationale as the banded
    probe above: Mosaic rejections can be shape-specific, and this kernel
    leans on newer surface still (SMEM scalar-prefetched gather indices,
    VMEM scratch, per-edge dynamic row loads).  If the backend rejects it
    the switchboard keeps the XLA composition for `sage_aggregate` calls.
    ``NERRF_NO_FUSED_PALLAS=1`` is the hard escape hatch."""
    if interpret:  # interpreter mode can't hit Mosaic rejection
        return True
    try:
        E, N, F = 2048, 1024, 160
        rng = np.random.default_rng(7)
        dst = np.sort(rng.integers(0, N, (2, E))).astype(np.int32)
        src = rng.integers(0, N, (2, E)).astype(np.int32)
        order = np.argsort(src, axis=1)
        src_s = np.take_along_axis(src, order, 1)
        dst_s = np.take_along_axis(dst, order, 1)
        w = rng.uniform(0.1, 1.0, (2, E)).astype(np.float32)
        w_s = np.take_along_axis(w, order, 1)
        msg = jnp.asarray(rng.normal(size=(2, N, F)), jnp.float32)
        args = tuple(jnp.asarray(a) for a in
                     (dst, src, src_s, dst_s, w, w_s, w_s, w))

        def loss(m):
            out = jax.vmap(
                lambda mm, d, s, ss, ds, a, b, c, e: sage_aggregate_fused(
                    mm, d, s, ss, ds, a, b, c, e, N, interpret)
            )(m, *args)
            return jnp.sum(out * out)

        from nerrf_tpu.utils import sync_result

        sync_result(jax.jit(jax.grad(loss))(msg))
        return True
    except Exception as e:
        import sys

        print(f"[nerrf_tpu.ops] fused SAGE-aggregation kernel unavailable "
              f"on this backend ({type(e).__name__}: {e}); sage_aggregate "
              "falls back to the XLA composition", file=sys.stderr)
        return False


def register(interpret: bool = False) -> None:
    """Install the Pallas kernels behind ``nerrf_tpu.ops``' switchboard.

    ``NERRF_NO_SORTED_PALLAS=1`` withholds the banded sorted kernel (dense
    one-hot then serves sorted calls too) and ``NERRF_NO_FUSED_PALLAS=1``
    the fused SAGE-aggregation kernel; otherwise each is compile-probed on
    this backend first and dropped silently if Mosaic rejects it."""
    import os

    from nerrf_tpu.ops import segment as _seg

    sorted_fn = None
    if (os.environ.get("NERRF_NO_SORTED_PALLAS") != "1"
            and _sorted_kernels_compile(interpret)):
        sorted_fn = lambda data, ids, n: segment_sum_sorted(
            data, ids, n, interpret)
    sage_fn = None
    if (os.environ.get("NERRF_NO_FUSED_PALLAS") != "1"
            and _fused_sage_compiles(interpret)):
        sage_fn = lambda msg, *edges_and_n: sage_aggregate_fused(
            msg, *edges_and_n, interpret)
    _seg.use_pallas(
        lambda data, ids, n: segment_sum(data, ids, n, interpret),
        lambda table, idx: gather_rows(table, idx, interpret),
        sorted_sum_fn=sorted_fn,
        sage_fn=sage_fn,
    )


def unregister() -> None:
    from nerrf_tpu.ops import segment as _seg

    _seg.use_pallas(None, None)
