"""Experiment runner: named config → corpus → train → checkpoint → report."""

import json

import pytest

from nerrf_tpu.train.run import run_experiment


@pytest.mark.slow
def test_run_toy_experiment_produces_artifacts(tmp_path):
    report = run_experiment("toy-graphsage", tmp_path, num_steps=60)
    assert (tmp_path / "experiment.json").exists()
    assert (tmp_path / "model" / "model_config.json").exists()
    on_disk = json.loads((tmp_path / "metrics.json").read_text())
    assert on_disk["experiment"] == "toy-graphsage"
    assert report["metrics"]["edge_auc"] > 0.5
    # checkpoint round-trips into the undo path's loader
    from nerrf_tpu.train.checkpoint import load_checkpoint

    params, cfg = load_checkpoint(tmp_path / "model")
    assert cfg.gnn.num_layers == 8  # toy experiment's model size


@pytest.mark.slow
def test_run_sharded_experiment_on_virtual_mesh(tmp_path):
    """multihost-online runs dp×tp sharded on the 8-device virtual mesh."""
    report = run_experiment("multihost-online", tmp_path, num_steps=4)
    assert report["devices"] == 8
    assert report["steps_per_sec"] > 0
