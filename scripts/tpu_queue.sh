#!/bin/bash
# Round-5 chip-work queue: waits for the TPU tunnel, then runs the offline
# artifact producers serially (single-core host: nothing here overlaps).
# Order matters — training first (its checkpoint feeds the adversarial
# eval), then the evals, then the benchmark of record last so it exercises
# warm compilation caches.
#
#   1/10. joint-100h training on the r4+ corpus     → runs/joint-100h
#   2/10. joint-dense training (4096n/8192e bucket) → runs/joint-dense
#   3/10. adversarial eval vs the 100h checkpoint   → adversarial_r5.json
#   4/10. graph capacity + Pallas crossover         → graph_capacity.json
#   5/10. aggregation kernel microbench             → kernel_bench_tpu.json
#   6/10. planner throughput probe                  → mcts_tpu.log
#   7/10. recovery benches (device planner)         → m{0,1}_recovery.json
#   8/10. stream detector quality + calibration     → stream_probe_tpu.json
#   9/10. chip-gated compiled-kernel test           → pallas_tpu.log
#  10/10. bench.py smoke (MFU + 4096-bucket leg)    → /tmp/bench_smoke.json
#
# Safe to re-run; each step is idempotent or overwrite-only.  Nothing here
# git-commits — artifacts are reviewed and committed by hand.
# Logs: /tmp/tpu_queue.log + per-step logs in /tmp.
cd "$(dirname "$0")/.."
log() { echo "[queue $(date +%H:%M:%S)] $*" >> /tmp/tpu_queue.log; }
log "watcher started (r5)"
# pre-flight: static analysis (purity/recompile/lock/metrics rules) runs
# in seconds on CPU with no jax import — a queue that would burn hours of
# chip time on code with a known recompile or race hazard fails here
if ! python scripts/nerrflint.py > /tmp/nerrflint.log 2>&1; then
  log "PRE-FLIGHT FAIL: nerrflint found unbaselined findings (/tmp/nerrflint.log)"
  exit 1
fi
log "pre-flight: nerrflint clean"
# pre-flight: the deep program contracts (zero-recompile closure of the
# serve ladder, donation discipline, collective/sharding consistency,
# Pallas VMEM budgets, cache-key coverage) proven on CPU via abstract
# tracing — a contract break fails here in <30 s instead of hours into
# chip work.  Runs BEFORE the tunnel wait: it needs no accelerator.
if ! timeout 120 python scripts/nerrflint.py --deep > /tmp/nerrflint_deep.log 2>&1; then
  log "PRE-FLIGHT FAIL: deep program-contract pass (/tmp/nerrflint_deep.log)"
  exit 1
fi
log "pre-flight: deep program contracts verified (closure/donation/sharding/pallas/cache-key)"
# pre-flight: chaos smoke on CPU — the serve path survives the seeded
# fault schedule (poison bisection, backoff reconnect, ENOSPC'd dump
# retry, corrupt-cache fail-open) with zero recompiles and unfaulted
# bit-parity (docs/chaos.md).  Needs no accelerator, so it runs BEFORE
# the tunnel wait: a survival regression fails here, not mid-queue.
if ! timeout 560 env JAX_PLATFORMS=cpu python benchmarks/run_chaos_bench.py \
  --smoke > /tmp/chaos_smoke.json 2>> /tmp/tpu_queue.log
then
  log "PRE-FLIGHT FAIL: chaos smoke survival gates (/tmp/chaos_smoke.json)"
  exit 1
fi
log "pre-flight: chaos smoke survival gates pass"
# pre-flight: quality drift-injection smoke on CPU — injected
# distribution shift fires exactly one doctor-readable quality_drift
# bundle, unshifted traffic stays below the PSI breach with bit-parity
# intact (docs/quality.md); runs BEFORE any tunnel time
if ! timeout 560 env JAX_PLATFORMS=cpu python benchmarks/run_quality_bench.py \
  --smoke > /tmp/quality_smoke.json 2>> /tmp/tpu_queue.log
then
  log "PRE-FLIGHT FAIL: quality drift-injection gates (/tmp/quality_smoke.json)"
  exit 1
fi
log "pre-flight: quality drift-injection gates pass"
# pre-flight: trainwatch smoke on CPU — a tiny train run with the
# health plane armed: clean legs bit-identical with zero bundles and a
# cache-deserialized step, the injected nonfinite step fires exactly one
# doctor-readable train_divergence bundle (docs/training-health.md);
# proves the divergence edge BEFORE hours of chip training rely on it
if ! timeout 560 env JAX_PLATFORMS=cpu python benchmarks/run_train_health_bench.py \
  --smoke > /tmp/train_health_smoke.json 2>> /tmp/tpu_queue.log
then
  log "PRE-FLIGHT FAIL: trainwatch divergence gates (/tmp/train_health_smoke.json)"
  exit 1
fi
log "pre-flight: trainwatch divergence gates pass"
# pre-flight: respond smoke on CPU — the incident-response tier end to
# end: four adversarial families staged, detected, planned in vmapped
# batches (B=1 bit-parity, zero recompiles), every plan sandbox-verified
# or quarantined with a journaled reason (docs/response.md); runs
# BEFORE any tunnel time
if ! timeout 560 env JAX_PLATFORMS=cpu python benchmarks/run_respond_bench.py \
  --smoke > /tmp/respond_smoke.json 2>> /tmp/tpu_queue.log
then
  log "PRE-FLIGHT FAIL: respond smoke gates (/tmp/respond_smoke.json)"
  exit 1
fi
log "pre-flight: respond smoke gates pass"
# pre-flight: continuous-learning smoke on CPU — the closed loop on the
# real serve path: replay buffer fed at the demux seam, injected drift
# fires the quality_drift trigger, exactly one retrain publishes with
# provenance, the existing gates promote it, quality recovers, and a
# divergent retrain aborts publishing nothing (docs/learning.md); runs
# BEFORE any tunnel time
if ! timeout 900 env JAX_PLATFORMS=cpu python benchmarks/run_learn_bench.py \
  --smoke > /tmp/learn_smoke.json 2>> /tmp/tpu_queue.log
then
  log "PRE-FLIGHT FAIL: continuous-learning closed-loop gates (/tmp/learn_smoke.json)"
  exit 1
fi
log "pre-flight: continuous-learning closed-loop gates pass"
# pre-flight: archive smoke on CPU — a short serve run with the
# telemetry archive armed, then `nerrf report` must reconstruct the run
# (windows scored, e2e quantiles) from the segments alone and `archive
# verify` must find them intact (docs/archive.md); runs BEFORE any
# tunnel time
rm -rf /tmp/archive_smoke
if ! { timeout 300 env JAX_PLATFORMS=cpu python -m nerrf_tpu.cli serve-detect \
    --trace datasets/traces/toy_trace.csv --no-probe --metrics-port -1 \
    --archive-dir /tmp/archive_smoke --buckets 256x512x128 --no-aot-cache \
    > /tmp/archive_serve.json 2>> /tmp/tpu_queue.log \
  && timeout 120 env JAX_PLATFORMS=cpu python -m nerrf_tpu.cli archive verify \
    /tmp/archive_smoke >> /tmp/tpu_queue.log 2>&1 \
  && timeout 120 env JAX_PLATFORMS=cpu python -m nerrf_tpu.cli report \
    /tmp/archive_smoke --json > /tmp/archive_report.json 2>> /tmp/tpu_queue.log \
  && python -c "
import json
r = json.load(open('/tmp/archive_report.json'))
assert r['span']['records'] > 0 and r['slo']['windows_scored'] > 0
assert (r['slo']['e2e_ms'] or {}).get('p99') is not None
" ; }
then
  log "PRE-FLIGHT FAIL: archive report gates (/tmp/archive_report.json)"
  exit 1
fi
log "pre-flight: archive report reconstructs the run offline"
# pre-flight: tune smoke on CPU — `nerrf tune` fits a tuned ladder +
# per-rung routing from the archived serve run above, then a fresh boot
# on the artifact must score windows with ZERO post-warmup recompiles
# (docs/tuning.md); proves the learned-ladder loop before chip time
if ! { timeout 120 env JAX_PLATFORMS=cpu python -m nerrf_tpu.cli tune \
    /tmp/archive_smoke --out /tmp/tuned_smoke.json >> /tmp/tpu_queue.log 2>&1 \
  && timeout 300 env JAX_PLATFORMS=cpu python -m nerrf_tpu.cli serve-detect \
    --trace datasets/traces/toy_trace.csv --no-probe --metrics-port -1 \
    --tuned /tmp/tuned_smoke.json --no-aot-cache \
    > /tmp/tuned_serve.json 2>> /tmp/tpu_queue.log \
  && python -c "
import json
r = json.load(open('/tmp/tuned_serve.json'))
assert r['windows_scored'] > 0 and r['recompiles_after_warmup'] == 0
" ; }
then
  log "PRE-FLIGHT FAIL: tuned-ladder boot gates (/tmp/tuned_serve.json)"
  exit 1
fi
log "pre-flight: tuned-ladder boot scores windows, zero post-warmup recompiles"
# pre-flight: archive-compare regression gate on CPU — the archived
# smoke run above vs this host's banked artifact-of-record
# (docs/fleet.md).  `report --compare --gate` exits nonzero when the
# candidate regressed beyond the CompareConfig tolerances, failing the
# queue BEFORE any tunnel time; a missing bank (first run on a host)
# passes with a note, and a green gate re-banks the run so every later
# queue run is measured against the best-known-good
BASELINE="${NERRF_ARCHIVE_BASELINE:-/var/tmp/nerrf_archive_baseline}"
if ! timeout 120 env JAX_PLATFORMS=cpu python -m nerrf_tpu.cli report \
  --compare "$BASELINE" /tmp/archive_smoke --gate >> /tmp/tpu_queue.log 2>&1
then
  log "PRE-FLIGHT FAIL: archive-compare gate vs $BASELINE (/tmp/tpu_queue.log)"
  exit 1
fi
mkdir -p "$(dirname "$BASELINE")"
rm -rf "$BASELINE"
cp -r /tmp/archive_smoke "$BASELINE"
rm -rf /tmp/archive_smoke
log "pre-flight: archive-compare gate green (banked at $BASELINE)"
# pre-flight: devtime cost table on CPU — the analytic cost model must
# resolve for the whole serve ladder + train step with every
# chip-relative column null (docs/device-efficiency.md); fails in
# seconds, before any tunnel time
if ! timeout 300 env JAX_PLATFORMS=cpu python -m nerrf_tpu.cli profile costs \
  --smoke --no-probe --json > /tmp/devtime_smoke.json 2>> /tmp/tpu_queue.log
then
  log "PRE-FLIGHT FAIL: devtime cost table (/tmp/devtime_smoke.json)"
  exit 1
fi
log "pre-flight: devtime cost table resolves (chip-relative columns null on CPU)"
# the gate must exercise the full enumerate->compile->execute path: the
# relay has been seen half-up (enumeration answering, remote_compile
# refusing), which passes an enumeration-only check and then wedges the
# first real step for half an hour.  One definition of reachable:
# probe_backend (fresh uncached compile, process-group kill on timeout —
# a bare `timeout` TERMs only the direct child and leaves runtime helper
# processes holding the tunnel).
tpu_ok() {
  python -c "
import sys
from nerrf_tpu.utils import probe_backend
ok, detail, _ = probe_backend(timeout_sec=150)
sys.exit(0 if ok and detail.startswith('tpu') else 1)
" 2>/dev/null
}
wait_for_tpu() {
  # probe attempts are the round's evidence when the tunnel never comes
  # up (VERDICT r3 item 1) — one line per failed probe, timestamped
  local n=0
  while ! tpu_ok; do
    n=$((n + 1))
    log "tpu probe #$n failed (enumerate->compile->execute did not complete)"
    sleep 120
  done
  log "TPU is up (fresh compile path verified after $n failed probes)"
}
wait_for_tpu
# pre-flight: compile-cache round-trip ON THE CHIP — warm the serve
# ladder once into the persistent AOT cache (the one cold sweep this
# host will ever pay), then assert the second sweep reports
# source=cache for every ladder bucket.  A key-stability or
# executable-serialization regression on this backend fails here, before
# hours of queue work re-pay compiles that should be disk reads
# (docs/compile-cache.md).
log "pre-flight: compile-cache warm sweep (serve ladder, cold)"
timeout 2400 python -m nerrf_tpu.cli cache warm \
  > /tmp/cache_cold.json 2>> /tmp/tpu_queue.log
if ! timeout 600 python -m nerrf_tpu.cli cache warm --expect-cache \
  > /tmp/cache_warm.json 2>> /tmp/tpu_queue.log
then
  log "PRE-FLIGHT FAIL: compile-cache second sweep not source=cache for every bucket (/tmp/cache_warm.json)"
  exit 1
fi
log "pre-flight: compile cache round-trips (second sweep source=cache)"
# first chip-side MFU table (docs/device-efficiency.md): the same cost
# table the CPU pre-flight proved, now with measured seconds/call and a
# non-null MFU column — the round's first device-efficiency numbers,
# before any long training burns the tunnel window.  Advisory: a failure
# logs and the queue continues (the table is evidence, not a gate).
log "chip-side devtime MFU table (serve ladder, measured)"
timeout 1800 python -m nerrf_tpu.cli profile costs --measure 4 --no-probe \
  > /tmp/devtime_mfu.txt 2>> /tmp/tpu_queue.log \
  && log "devtime MFU table written (/tmp/devtime_mfu.txt)" \
  || log "devtime MFU table FAILED (advisory; /tmp/tpu_queue.log)"
# require the regenerated zero-drop corpus with the stealth variants:
# training the flagship on an older corpus would leave it blind to exactly
# the scenarios the adversarial eval measures (VERDICT r3 item 3)
while ! python - <<'EOF' 2>/dev/null
import json, sys
m = json.load(open("datasets/corpus100/manifest.json"))
sc = m.get("scenario_counts", {})
sys.exit(0 if m.get("complete") and m.get("auto_fit")
         and m.get("dropped", {}).get("windows", 1) == 0
         and sc.get("inplace-stealth", 0) > 0
         and sc.get("benign-atomic-rewrite", 0) > 0 else 1)
EOF
do
  log "waiting for the zero-drop corpus100 (stealth variants)"; sleep 60
done
log "1/10 joint-100h training"
# the corpus is ~10 GB and rotates shards through the chip each epoch; over
# a ~0.5 GB/s tunnel the wall clock is transfer-bound, so budget generously
# and rely on resume-from-checkpoint for the retry.  The tunnel has twice
# come up for only minutes and died: re-verify it before EVERY attempt so a
# flap doesn't burn a 2 h timeout against a dead link — a failed attempt
# goes back to waiting, not straight into the next attempt.
# NERRF_REQUIRE_ACCEL: if the tunnel flaps between wait_for_tpu and the
# run's own in-process probe, fail fast and come back to waiting — never
# burn a 7200 s timeout grinding flagship shapes on this host's one core
for attempt in 1 2 3; do
  wait_for_tpu
  NERRF_REQUIRE_ACCEL=1 timeout 7200 python -m nerrf_tpu.train.run \
    --experiment joint-100h \
    --out runs/joint-100h --ckpt-every 2000 > /tmp/joint100.log 2>&1
  rc=$?
  log "joint-100h attempt $attempt rc=$rc"
  [ $rc -eq 0 ] && break
done
if [ -f runs/joint-100h/metrics.json ]; then
  mkdir -p benchmarks/results
  cp runs/joint-100h/metrics.json benchmarks/results/joint100h_r5.json
  log "copied joint100h artifact"
fi
log "2/10 joint-dense training (deployed 4096n/8192e bucket)"
for attempt in 1 2; do
  wait_for_tpu
  NERRF_REQUIRE_ACCEL=1 timeout 7200 python -m nerrf_tpu.train.run \
    --experiment joint-dense \
    --out runs/joint-dense --ckpt-every 1000 > /tmp/jointdense.log 2>&1
  rc=$?
  log "joint-dense attempt $attempt rc=$rc"
  [ $rc -eq 0 ] && break
done
if [ -f runs/joint-dense/metrics.json ]; then
  mkdir -p benchmarks/results
  cp runs/joint-dense/metrics.json benchmarks/results/joint_dense_r5.json
  log "copied joint-dense artifact"
fi
log "3/10 adversarial eval (flagship checkpoint when present)"
wait_for_tpu
if [ -f runs/joint-100h/model/model_config.json ]; then
  timeout 3600 python benchmarks/run_adversarial_eval.py \
    --out benchmarks/results/adversarial_r5.json \
    --model-dir runs/joint-100h/model > /tmp/adv_r5.log 2>&1
else
  timeout 3600 python benchmarks/run_adversarial_eval.py \
    --out benchmarks/results/adversarial_r5.json > /tmp/adv_r5.log 2>&1
fi
log "adversarial rc=$?"
log "4/10 graph capacity (pallas crossover)"
wait_for_tpu
timeout 1800 python benchmarks/run_graph_capacity.py \
  --out benchmarks/results/graph_capacity.json > /tmp/graphcap.log 2>&1
log "graphcap rc=$?"
log "5/10 aggregation kernel microbench ({segment,dense_adj,fused} x bucket)"
wait_for_tpu
timeout 1800 python benchmarks/run_kernel_bench.py \
  --out benchmarks/results/kernel_bench_tpu.json > /tmp/kernel_bench.log 2>&1
log "kernel bench rc=$?"
log "6/10 planner throughput probe"
timeout 1200 python benchmarks/run_planner_probe.py > /tmp/mcts_tpu.log 2>&1
log "mcts rc=$?"
log "7/10 recovery benches (device planner in the KPI path)"
wait_for_tpu
timeout 1800 python benchmarks/run_recovery_bench.py --scale m0 \
  --out benchmarks/results/m0_recovery.json > /tmp/recovery_m0.log 2>&1
log "m0 recovery rc=$?"
timeout 1800 python benchmarks/run_recovery_bench.py --scale m1 \
  --out benchmarks/results/m1_recovery.json > /tmp/recovery_m1.log 2>&1
log "m1 recovery rc=$?"
log "8/10 stream detector quality + calibration on chip"
wait_for_tpu
timeout 2400 python benchmarks/run_stream_eval.py --steps 1500 \
  --out benchmarks/results/stream_probe_tpu.json > /tmp/stream_tpu.log 2>&1
log "stream quality rc=$?"
log "9/10 chip-gated compiled-kernel test"
wait_for_tpu
NERRF_TEST_REAL_BACKEND=1 timeout 1200 python -m pytest \
  tests/test_pallas_ops.py -q -k compiled_on_tpu > /tmp/pallas_tpu.log 2>&1
log "pallas chip test rc=$?"
log "10/10 bench.py smoke (validates the driver's benchmark of record: MFU + 4096-bucket leg)"
wait_for_tpu
timeout 3600 python bench.py > /tmp/bench_smoke.json 2> /tmp/bench_smoke.log
log "bench rc=$?"
log "queue done"
