"""Metrics registry, Prometheus rendering, HTTP endpoint, pipeline wiring."""

import json
import urllib.request

from nerrf_tpu.observability import (
    DEFAULT_REGISTRY,
    MetricsRegistry,
    MetricsServer,
)


def test_counter_gauge_histogram_render():
    reg = MetricsRegistry(namespace="t")
    reg.counter_inc("events_total", 3, help="events seen")
    reg.counter_inc("events_total", 2)
    reg.counter_inc("events_total", 1, labels={"source": "ring"})
    reg.gauge_set("segments", 4.0)
    reg.histogram_observe("latency_seconds", 0.003, buckets=(0.001, 0.01, 0.1))
    reg.histogram_observe("latency_seconds", 0.05, buckets=(0.001, 0.01, 0.1))
    text = reg.render()
    assert "# TYPE t_events_total counter" in text
    assert "t_events_total 5" in text
    assert 't_events_total{source="ring"} 1' in text
    assert "# HELP t_events_total events seen" in text
    assert "t_segments 4" in text
    assert 't_latency_seconds_bucket{le="0.01"} 1' in text
    assert 't_latency_seconds_bucket{le="+Inf"} 2' in text
    assert "t_latency_seconds_count 2" in text
    assert reg.value("events_total") == 5


def test_metrics_server_serves_scrape_and_health():
    reg = MetricsRegistry(namespace="srv")
    reg.counter_inc("pings_total", 7)
    with MetricsServer(registry=reg, port=0) as srv:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=5).read().decode()
        assert "srv_pings_total 7" in body
        health = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/healthz", timeout=5).read())
        assert health["status"] == "ok"


def test_pipeline_components_report_to_default_registry(tmp_path):
    """Stream → ingest → store: the wired counters move."""
    from nerrf_tpu.data import SimConfig, simulate_trace
    from nerrf_tpu.graph.store import TraceStore
    from nerrf_tpu.ingest.service import TraceReplayServer, TrackerClient

    before_events = DEFAULT_REGISTRY.value("ingest_events_total")
    before_comp = DEFAULT_REGISTRY.value("store_compactions_total")

    trace = simulate_trace(SimConfig(num_target_files=4, duration_sec=20.0,
                                     benign_rate_hz=8.0, seed=21))
    server = TraceReplayServer(trace.events, trace.strings)
    port = server.start()
    try:
        events, strings = TrackerClient(f"127.0.0.1:{port}").stream(timeout=30.0)
    finally:
        server.stop()
    assert DEFAULT_REGISTRY.value("ingest_events_total") - before_events == \
        events.num_valid
    assert DEFAULT_REGISTRY.value("tracker_frames_sent_total") > 0

    with TraceStore(tmp_path / "store") as st:
        st.append(events, strings)
        st.flush()
    assert DEFAULT_REGISTRY.value("store_compactions_total") > before_comp
    assert "nerrf_store_segments" in DEFAULT_REGISTRY.render()
