// Native ingest bridge: ring records / protobuf EventBatch frames → packed
// structure-of-arrays columns.  See include/nerrf/ingest.h for the contract.
//
// The protobuf path is a hand-rolled wire-format parser specialized to the
// nerrf.trace schema (proto/trace.proto): at ≥1k evt/s sustained — the
// reference tracker's throughput gate (/root/reference/ROADMAP.md:60) — a
// generic reflective decode is wasted work; every Event field is a varint or
// a length-delimited blob, and we know all fifteen of them.

#include "nerrf/ingest.h"

#include <cstring>
#include <deque>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

#include "nerrf/event_record.h"

namespace {

// --- string intern pool -----------------------------------------------------

class InternPool {
 public:
  InternPool() { intern(""); }

  int32_t intern(std::string_view s) {
    auto it = index_.find(s);
    if (it != index_.end()) return it->second;
    storage_.emplace_back(s);
    const std::string &owned = storage_.back();
    int32_t id = static_cast<int32_t>(storage_.size() - 1);
    index_.emplace(std::string_view(owned), id);
    total_bytes_ += owned.size();
    return id;
  }

  int64_t size() const { return static_cast<int64_t>(storage_.size()); }
  int64_t bytes() const { return total_bytes_; }

  int64_t dump(uint8_t *data, size_t data_cap, int64_t *offsets,
               size_t off_cap) const {
    if (off_cap < storage_.size() + 1 ||
        data_cap < static_cast<size_t>(total_bytes_))
      return -1;
    int64_t off = 0;
    size_t i = 0;
    for (const std::string &s : storage_) {
      offsets[i++] = off;
      std::memcpy(data + off, s.data(), s.size());
      off += static_cast<int64_t>(s.size());
    }
    offsets[i] = off;
    return size();
  }

 private:
  // deque never reallocates existing elements, so string_view keys into the
  // owned strings stay valid for the pool's lifetime.
  std::deque<std::string> storage_;
  std::unordered_map<std::string_view, int32_t> index_;
  int64_t total_bytes_ = 0;
};

int32_t syscall_id_of(std::string_view name) {
  struct Entry {
    std::string_view name;
    int32_t id;
  };
  static constexpr Entry kTable[] = {
      {"openat", NERRF_SC_OPENAT}, {"write", NERRF_SC_WRITE},
      {"rename", NERRF_SC_RENAME}, {"read", NERRF_SC_READ},
      {"unlink", NERRF_SC_UNLINK}, {"close", NERRF_SC_CLOSE},
      {"exec", NERRF_SC_EXEC},     {"connect", NERRF_SC_CONNECT},
      {"stat", NERRF_SC_STAT},     {"mkdir", NERRF_SC_MKDIR},
      {"chmod", NERRF_SC_CHMOD},   {"fsync", NERRF_SC_FSYNC},
      {"marker", NERRF_SC_MARKER},
  };
  for (const Entry &e : kTable)
    if (e.name == name) return e.id;
  return NERRF_SC_OTHER;
}

std::string_view cstr_view(const char *buf, size_t cap) {
  size_t n = 0;
  while (n < cap && buf[n] != '\0') ++n;
  return std::string_view(buf, n);
}

// --- protobuf wire-format primitives ----------------------------------------

struct Cursor {
  const uint8_t *p;
  const uint8_t *end;
  bool ok = true;

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 64) {
      uint8_t b = *p++;
      v |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
    ok = false;
    return 0;
  }

  std::string_view bytes_field() {
    uint64_t n = varint();
    if (!ok || n > static_cast<uint64_t>(end - p)) {
      ok = false;
      return {};
    }
    std::string_view out(reinterpret_cast<const char *>(p), n);
    p += n;
    return out;
  }

  void skip(uint32_t wire_type) {
    switch (wire_type) {
      case 0:  // varint
        varint();
        break;
      case 1:  // fixed64
        if (end - p < 8) ok = false;
        else p += 8;
        break;
      case 2:  // length-delimited
        bytes_field();
        break;
      case 5:  // fixed32
        if (end - p < 4) ok = false;
        else p += 4;
        break;
      default:
        ok = false;
    }
  }
};

int64_t zigzag64(uint64_t v) {
  return static_cast<int64_t>(v >> 1) ^ -static_cast<int64_t>(v & 1);
}

int64_t parse_timestamp_ns(std::string_view msg) {
  Cursor c{reinterpret_cast<const uint8_t *>(msg.data()),
           reinterpret_cast<const uint8_t *>(msg.data()) + msg.size()};
  int64_t seconds = 0;
  int64_t nanos = 0;
  while (c.ok && c.p < c.end) {
    uint64_t key = c.varint();
    if (!c.ok) break;
    uint32_t field = static_cast<uint32_t>(key >> 3);
    uint32_t wt = static_cast<uint32_t>(key & 7);
    if (field == 1 && wt == 0) seconds = static_cast<int64_t>(c.varint());
    else if (field == 2 && wt == 0) nanos = static_cast<int64_t>(c.varint());
    else c.skip(wt);
  }
  return seconds * 1000000000LL + nanos;
}

int64_t parse_decimal_i64(std::string_view s) {
  int64_t v = 0;
  for (char ch : s) {
    if (ch < '0' || ch > '9') return 0;  // non-numeric inode strings → 0
    v = v * 10 + (ch - '0');
  }
  return v;
}

bool parse_event(std::string_view msg, InternPool &pool,
                 nerrf_columns_t *cols, size_t row) {
  Cursor c{reinterpret_cast<const uint8_t *>(msg.data()),
           reinterpret_cast<const uint8_t *>(msg.data()) + msg.size()};
  // proto3 defaults
  cols->ts_ns[row] = 0;
  cols->pid[row] = 0;
  cols->tid[row] = 0;
  cols->comm_id[row] = 0;
  cols->syscall_id[row] = NERRF_SC_OTHER;
  cols->path_id[row] = 0;
  cols->new_path_id[row] = 0;
  cols->flags[row] = 0;
  cols->ret_val[row] = 0;
  cols->bytes[row] = 0;
  cols->inode[row] = 0;
  cols->mode[row] = 0;
  cols->uid[row] = 0;
  cols->gid[row] = 0;

  while (c.ok && c.p < c.end) {
    uint64_t key = c.varint();
    if (!c.ok) break;
    uint32_t field = static_cast<uint32_t>(key >> 3);
    uint32_t wt = static_cast<uint32_t>(key & 7);
    switch (field) {
      case 1:  // ts
        if (wt == 2) cols->ts_ns[row] = parse_timestamp_ns(c.bytes_field());
        else c.skip(wt);
        break;
      case 2:
        cols->pid[row] = static_cast<int32_t>(c.varint());
        break;
      case 3:
        cols->tid[row] = static_cast<int32_t>(c.varint());
        break;
      case 4:
        cols->comm_id[row] = pool.intern(c.bytes_field());
        break;
      case 5:
        cols->syscall_id[row] = syscall_id_of(c.bytes_field());
        break;
      case 6:
        cols->path_id[row] = pool.intern(c.bytes_field());
        break;
      case 7:
        cols->new_path_id[row] = pool.intern(c.bytes_field());
        break;
      case 8:
        cols->flags[row] = static_cast<int32_t>(c.varint());
        break;
      case 9:  // sint64 → zigzag
        cols->ret_val[row] = zigzag64(c.varint());
        break;
      case 10:
        cols->bytes[row] = static_cast<int64_t>(c.varint());
        break;
      case 11:
        cols->inode[row] = parse_decimal_i64(c.bytes_field());
        break;
      case 12:
        cols->mode[row] = static_cast<int32_t>(c.varint());
        break;
      case 13:
        cols->uid[row] = static_cast<int32_t>(c.varint());
        break;
      case 14:
        cols->gid[row] = static_cast<int32_t>(c.varint());
        break;
      case 15:  // dependencies: not columnar; graph edges derive from order
        c.skip(wt);
        break;
      default:
        c.skip(wt);
    }
  }
  if (!c.ok) return false;
  if (cols->tid[row] == 0) cols->tid[row] = cols->pid[row];
  cols->valid[row] = 1;
  return true;
}

}  // namespace

// --- C ABI -------------------------------------------------------------------

struct nerrf_ingest {
  InternPool pool;
};

extern "C" {

nerrf_ingest_t *nerrf_ingest_new(void) { return new nerrf_ingest(); }

void nerrf_ingest_free(nerrf_ingest_t *ing) { delete ing; }

int64_t nerrf_decode_ring(nerrf_ingest_t *ing, const uint8_t *buf, size_t len,
                          uint64_t boot_epoch_ns, nerrf_columns_t *cols,
                          size_t cap) {
  if (!ing || !buf || !cols || len % NERRF_EVENT_RECORD_SIZE != 0) return -1;
  size_t n = len / NERRF_EVENT_RECORD_SIZE;
  if (n > cap) return -1;
  for (size_t i = 0; i < n; ++i) {
    nerrf_event_record rec;
    std::memcpy(&rec, buf + i * NERRF_EVENT_RECORD_SIZE, sizeof(rec));
    cols->ts_ns[i] = static_cast<int64_t>(boot_epoch_ns + rec.ts_ns);
    cols->pid[i] = static_cast<int32_t>(rec.pid);
    cols->tid[i] = static_cast<int32_t>(rec.tid);
    cols->comm_id[i] = ing->pool.intern(cstr_view(rec.comm, NERRF_COMM_LEN));
    cols->syscall_id[i] = static_cast<int32_t>(rec.syscall_id);
    cols->path_id[i] = ing->pool.intern(cstr_view(rec.path, NERRF_PATH_LEN));
    cols->new_path_id[i] =
        ing->pool.intern(cstr_view(rec.new_path, NERRF_PATH_LEN));
    cols->flags[i] = 0;  // ring records carry no flags (reference parity)
    cols->ret_val[i] = rec.ret_val;
    cols->bytes[i] = static_cast<int64_t>(rec.bytes);
    cols->inode[i] = 0;
    cols->mode[i] = 0;
    cols->uid[i] = 0;
    cols->gid[i] = 0;
    cols->valid[i] = 1;
  }
  return static_cast<int64_t>(n);
}

int64_t nerrf_decode_batch(nerrf_ingest_t *ing, const uint8_t *buf, size_t len,
                           nerrf_columns_t *cols, size_t cap) {
  if (!ing || !buf || !cols) return -1;
  Cursor c{buf, buf + len};
  size_t row = 0;
  while (c.ok && c.p < c.end) {
    uint64_t key = c.varint();
    if (!c.ok) break;
    uint32_t field = static_cast<uint32_t>(key >> 3);
    uint32_t wt = static_cast<uint32_t>(key & 7);
    if (field == 1 && wt == 2) {  // repeated Event events = 1
      std::string_view ev = c.bytes_field();
      if (!c.ok) break;
      if (row >= cap) return -1;
      if (!parse_event(ev, ing->pool, cols, row)) return -1;
      ++row;
    } else {
      c.skip(wt);
    }
  }
  if (!c.ok) return -1;
  return static_cast<int64_t>(row);
}

int64_t nerrf_pool_size(const nerrf_ingest_t *ing) {
  return ing ? ing->pool.size() : -1;
}

int64_t nerrf_pool_bytes(const nerrf_ingest_t *ing) {
  return ing ? ing->pool.bytes() : -1;
}

int64_t nerrf_pool_dump(const nerrf_ingest_t *ing, uint8_t *data,
                        size_t data_cap, int64_t *offsets, size_t off_cap) {
  return ing ? ing->pool.dump(data, data_cap, offsets, off_cap) : -1;
}

}  // extern "C"
