"""Fault plans: the declarative half of the chaos plane.

A `FaultPlan` is a seed plus a list of `FaultSpec`s, each naming one
**fault point** (a site threaded through the real code path — see
`chaos.points.SITES` for the catalog) and how it should fire.  Plans are
plain JSON so a game-day schedule is reviewable, diffable, and replayable:

    {
      "seed": 7,
      "faults": [
        {"site": "serve.poison_window", "prob": 0.05,
         "match": {"stream": "s1"}},
        {"site": "ingest.wire_error", "every": 40,
         "match": {"stream": "w0"}},
        {"site": "serve.device_latency", "every": 9, "delay_sec": 0.2,
         "after_sec": 5.0, "for_sec": 20.0}
      ]
    }

Triggers (all optional; every present clause must hold for a spec to fire):

  * ``at``       — fire on exactly the Nth check of this spec (1-based);
  * ``every``    — fire on every Nth check;
  * ``prob``     — seeded probabilistic.  When the call site supplies a
    ``key`` (the window's trace ID, a cache fingerprint), the draw is a
    pure hash of (seed, site, key) — the SAME window fires the SAME way
    on every retry and every replay of the plan.  Without a key the draw
    hashes the per-spec check counter, so a seeded plan still replays
    deterministically under an identical check order;
  * ``match``    — equality over the call-site context (stream, bucket,
    window_idx, program, …): aim a fault at one stream or one window.

Bounds: ``after_sec``/``for_sec`` gate on time since arming (a fault that
switches on mid-soak and off again), ``max_fires`` caps total firings.

Determinism is the point: the same plan + seed + traffic produces the
same injected-fault set, so a soak failure reproduces and a bisection
retry sees the same poison it saw the first time.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Dict, List, Optional, Tuple

# fault modes a spec can carry; what each means is the call site's
# contract (see chaos.points: error → raise ChaosFault, stall/latency →
# sleep delay_sec, corrupt → the caller mangles flip_bytes of its payload)
MODES = ("error", "stall", "corrupt")


class ChaosFault(RuntimeError):
    """The injected failure.  A distinct type so recovery paths (and
    tests) can tell an injected fault from an organic one in journals and
    error strings, while still flowing through every generic ``except
    Exception`` fail-open path exactly like the real thing."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One armed fault: site + trigger + bounds + fault parameters."""

    site: str
    mode: str = "error"
    # triggers — all present clauses must hold
    at: Optional[int] = None
    every: Optional[int] = None
    prob: Optional[float] = None
    match: Optional[Dict[str, object]] = None
    # bounds
    after_sec: float = 0.0
    for_sec: Optional[float] = None
    max_fires: Optional[int] = None
    # fault parameters
    message: str = ""
    delay_sec: float = 0.25
    flip_bytes: int = 16

    def validate(self, known_sites: Optional[Tuple[str, ...]] = None) -> None:
        if known_sites is not None and self.site not in known_sites:
            raise ValueError(
                f"unknown fault site {self.site!r} "
                f"(known: {', '.join(sorted(known_sites))})")
        if self.mode not in MODES:
            raise ValueError(f"unknown fault mode {self.mode!r} "
                             f"(one of {MODES})")
        if self.at is None and self.every is None and self.prob is None \
                and self.match is None:
            raise ValueError(
                f"spec for {self.site!r} has no trigger (at/every/prob/"
                f"match) — it would fire on every check; say every=1 if "
                f"that is really what you want")
        if self.prob is not None and not 0.0 <= self.prob <= 1.0:
            raise ValueError(f"prob must be in [0,1], got {self.prob}")
        for field, val in (("at", self.at), ("every", self.every),
                           ("max_fires", self.max_fires)):
            if val is not None and int(val) < 1:
                raise ValueError(f"{field} must be >= 1, got {val}")

    def to_dict(self) -> dict:
        out = {"site": self.site, "mode": self.mode}
        for f in dataclasses.fields(self):
            if f.name in ("site", "mode"):
                continue
            v = getattr(self, f.name)
            if v != f.default:
                out[f.name] = v
        return out


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A seed + the armed specs.  Immutable: arming takes a plan, and the
    controller's mutable state (hit counters, fire counts) lives outside
    it, so one plan object replays any number of times."""

    seed: int = 0
    faults: Tuple[FaultSpec, ...] = ()

    def validate(self, known_sites: Optional[Tuple[str, ...]] = None
                 ) -> "FaultPlan":
        for spec in self.faults:
            spec.validate(known_sites)
        return self

    def to_dict(self) -> dict:
        return {"seed": self.seed,
                "faults": [s.to_dict() for s in self.faults]}

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        if not isinstance(d, dict):
            # a plan whose top level is the faults ARRAY is an easy
            # hand-editing mistake; it must read as INVALID, not crash
            raise ValueError(
                f"a fault plan is a JSON object "
                f'{{"seed": N, "faults": […]}}, got {type(d).__name__}')
        known = {f.name for f in dataclasses.fields(FaultSpec)}
        faults = []
        for i, raw in enumerate(d.get("faults", [])):
            extra = set(raw) - known
            if extra:
                raise ValueError(
                    f"fault[{i}]: unknown field(s) {sorted(extra)} "
                    f"(known: {sorted(known)})")
            if "site" not in raw:
                raise ValueError(f"fault[{i}] has no 'site'")
            spec = FaultSpec(**{k: (tuple(v) if isinstance(v, list) else v)
                                for k, v in raw.items()})
            faults.append(spec)
        return cls(seed=int(d.get("seed", 0)), faults=tuple(faults))

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        return cls.from_dict(json.loads(text))


def load_plan(path: str | os.PathLike) -> FaultPlan:
    with open(os.fspath(path)) as f:
        return FaultPlan.from_json(f.read())


def hash01(seed: int, site: str, key: str) -> float:
    """Pure draw in [0,1): the probabilistic trigger's coin.  Keyed draws
    are replay- and retry-stable by construction — the same (seed, site,
    key) is the same coin forever."""
    h = hashlib.blake2s(f"{seed}:{site}:{key}".encode(),
                        digest_size=8).digest()
    return int.from_bytes(h, "big") / 2.0 ** 64


def corrupt_payload(payload: bytes, seed: int, site: str,
                    flip_bytes: int = 16) -> bytes:
    """Deterministically mangle ``flip_bytes`` positions of a payload
    (seeded by the plan, spread over the buffer) — the corrupt-mode
    helper for byte-shaped fault points (cache payloads, sidecars)."""
    if not payload:
        return payload
    out = bytearray(payload)
    n = max(1, min(int(flip_bytes), len(out)))
    for i in range(n):
        h = hashlib.blake2s(f"{seed}:{site}:{i}".encode(),
                            digest_size=8).digest()
        pos = int.from_bytes(h[:4], "big") % len(out)
        out[pos] ^= h[4] or 0xA5
    return bytes(out)
