#!/bin/bash
# Round-2 chip-work queue: waits for the TPU tunnel, then runs the offline
# artifact producers serially (100h training, adversarial eval, graph
# capacity crossover, planner throughput probe, bench.py smoke →
# /tmp/bench_smoke.json).  Safe to re-run; each step is idempotent or
# overwrite-only.  Logs: /tmp/tpu_queue.log + per-step logs.
cd "$(dirname "$0")/.."
log() { echo "[queue $(date +%H:%M:%S)] $*" >> /tmp/tpu_queue.log; }
log "watcher started"
while true; do
  if timeout 90 python -c "import jax; assert jax.default_backend()=='tpu'" 2>/dev/null; then
    log "TPU is back"; break
  fi
  sleep 120
done
while [ ! -f datasets/corpus100/manifest.json ]; do
  log "waiting for corpus100 generation"; sleep 60
done
log "1/5 joint-100h training"
# both prior tunnel wedges struck during this step's shard upload (now
# chunked); resume-from-checkpoint makes one retry cheap
for attempt in 1 2; do
  timeout 3600 python -m nerrf_tpu.train.run --experiment joint-100h \
    --out runs/joint-100h-r2 --ckpt-every 2000 > /tmp/joint100.log 2>&1
  rc=$?
  log "joint-100h attempt $attempt rc=$rc"
  [ $rc -eq 0 ] && break
done
if [ -f runs/joint-100h-r2/metrics.json ]; then
  mkdir -p benchmarks/results
  cp runs/joint-100h-r2/metrics.json benchmarks/results/joint100h_r2.json
  log "copied joint100h artifact"
fi
log "2/5 adversarial eval"
if [ -f runs/joint-100h-r2/model/model_config.json ]; then
  timeout 2400 python benchmarks/run_adversarial_eval.py \
    --out benchmarks/results/adversarial_r2.json \
    --model-dir runs/joint-100h-r2/model > /tmp/adv5.log 2>&1
else
  timeout 2400 python benchmarks/run_adversarial_eval.py \
    --out benchmarks/results/adversarial_r2.json > /tmp/adv5.log 2>&1
fi
log "adversarial rc=$?"
log "3/5 graph capacity (pallas crossover)"
timeout 1200 python benchmarks/run_graph_capacity.py \
  --out benchmarks/results/graph_capacity.json > /tmp/graphcap.log 2>&1
log "graphcap rc=$?"
log "4/5 planner throughput probe"
timeout 1200 python benchmarks/run_planner_probe.py > /tmp/mcts_tpu.log 2>&1
log "mcts rc=$?"
log "5/5 bench.py smoke (validates the driver's benchmark of record)"
timeout 2400 python bench.py > /tmp/bench_smoke.json 2> /tmp/bench_smoke.log
log "bench rc=$?"
log "queue done"
