"""Device-efficiency plane (nerrf_tpu/devtime): chip-peak resolution,
cost-model drift pins against the real warmup ladder, live accounting
gauges, headroom math over synthetic arrival mixes, and the fail-open
profiler capture plane."""

import os

import numpy as np
import pytest

from nerrf_tpu.devtime import (
    ChipPeaks,
    DeviceTimeAccountant,
    HeadroomTracker,
    capture_trace,
    chip_peaks,
    predict_headroom,
    profiled,
    program_cost,
    resolve_kind,
    serve_program_costs,
    trace_summary,
    train_step_cost,
)
from nerrf_tpu.flight.journal import EventJournal
from nerrf_tpu.observability import MetricsRegistry


# ---------------------------------------------------------------------------
# chip peaks: exact-match-first resolution
# ---------------------------------------------------------------------------

# every device_kind string the TPU runtime publishes for supported chips
PUBLISHED_KINDS = {
    "TPU v2": 45.0,
    "TPU v3": 123.0,
    "TPU v4": 275.0,
    "TPU v4i": 138.0,
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v5": 197.0,
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,
    "TPU v6e": 918.0,
}


def test_peaks_exact_match_over_all_published_kinds():
    for kind, tflops in PUBLISHED_KINDS.items():
        got = resolve_kind(kind)
        assert got is not None, kind
        assert got.tflops_bf16 == tflops, kind
        assert got.hbm_gbps > 0
        assert got.ridge_flops_per_byte > 0


def test_peaks_substring_fallback_prefers_longest_key():
    # a decorated kind must land on the v5e row, never the shorter "v5"
    got = resolve_kind("TPU v5 lite podslice")
    assert got.tflops_bf16 == 197.0 and got.kind == "tpu v5 lite"
    # and a decorated v5p must not fall into plain v5
    assert resolve_kind("TPU v5p superpod").tflops_bf16 == 459.0


def test_peaks_null_not_fake_for_unknown():
    assert resolve_kind("") is None
    assert resolve_kind("cpu") is None
    assert resolve_kind("TPU v99") is None  # future chip: None, no guess

    class FakeCpu:
        device_kind = "cpu"
        platform = "cpu"

    assert chip_peaks(FakeCpu()) is None


def test_bench_mfu_delegates_to_the_table():
    from nerrf_tpu.bench.mfu import chip_peak_tflops

    class Dev:
        device_kind = "TPU v5 lite"
        platform = "tpu"

    assert chip_peak_tflops(Dev()) == 197.0

    class Cpu:
        device_kind = ""
        platform = "cpu"

    assert chip_peak_tflops(Cpu()) is None


# ---------------------------------------------------------------------------
# cost model: drift-pinned to the real warmup ladder + sample_spec
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def small_serve():
    from nerrf_tpu.models import JointConfig, NerrfNet
    from nerrf_tpu.serve import ServeConfig, init_untrained_params
    from nerrf_tpu.train.loop import make_eval_fn

    cfg = ServeConfig(buckets=((64, 128, 32),))
    model = NerrfNet(JointConfig().small)
    params = init_untrained_params(model, cfg)
    return cfg, model, params, make_eval_fn(model)


def test_serve_costs_cover_exactly_the_warmup_ladder(small_serve):
    """The cost model's program set IS the warmup-compiled set: every
    bucket `warmup_batches` yields gets a cost, at the donor batch's
    exact shapes — which in turn must match `sample_spec` (the shape
    authority the deep pass proves admission against).  Any drift between
    the three surfaces fails here."""
    from nerrf_tpu.serve.config import bucket_tag
    from nerrf_tpu.serve.service import warmup_batches
    from nerrf_tpu.train.data import sample_spec

    cfg, _model, params, eval_fn = small_serve
    costs = serve_program_costs(eval_fn, params, cfg)
    ladder = {tag: batch for _b, tag, batch in warmup_batches(cfg)}
    assert set(costs) == set(ladder) != set()
    for bucket in cfg.buckets:
        tag = bucket_tag(bucket)
        spec = sample_spec(cfg.dataset_config(bucket))
        batch = ladder[tag]
        assert set(batch) == set(spec)
        for key, (shape, dtype) in spec.items():
            assert batch[key].shape == (cfg.batch_size,) + shape, key
            assert str(batch[key].dtype) == dtype, key
        cost = costs[tag]
        assert cost.program == f"serve_eval[{tag}]"
        assert cost.flops > 0
        assert cost.bytes_accessed > 0
        assert cost.intensity_flops_per_byte > 0
        assert cost.batch_slots == cfg.batch_size
        assert cost.xla_flops is None  # cross-check is opt-in


def test_program_cost_null_not_fake_for_matmul_free_fn():
    import jax.numpy as jnp

    cost = program_cost(lambda x: jnp.sum(x) + 1.0,
                        np.ones((8, 8), np.float32), program="nop")
    assert cost is None


def test_train_step_cost_at_dataset_shapes(small_serve):
    from nerrf_tpu.serve.service import _tiny_trace
    from nerrf_tpu.train.data import windows_of_trace
    from nerrf_tpu.train.loop import TrainConfig

    cfg, model, params, eval_fn = small_serve
    samples = windows_of_trace(_tiny_trace("devtime-test"),
                               cfg.dataset_config((64, 128, 32)))
    arrays = {k: np.stack([s[k] for s in samples]) for k in samples[0]}
    cost = train_step_cost(model, TrainConfig(model=model.cfg), arrays)
    assert cost is not None
    assert cost.program == "train_step"
    assert cost.flops > 0 and cost.bytes_accessed > 0
    # a train step (fwd+bwd+update of a batch) must out-cost a single
    # window's share of the eval program at the same shapes
    eval_cost = serve_program_costs(eval_fn, params, cfg)["64n/128e/32s"]
    per_window_eval = eval_cost.flops / eval_cost.batch_slots
    assert cost.flops > per_window_eval


# ---------------------------------------------------------------------------
# live accounting: gauges + null-not-fake MFU
# ---------------------------------------------------------------------------

def _fake_cost(flops=1e9, byts=1e6, program="serve_eval[t]"):
    from nerrf_tpu.devtime import ProgramCost

    return ProgramCost(program=program, flops=flops, bytes_accessed=byts,
                       peak_hbm_bytes=byts, batch_slots=8)


def test_accountant_mfu_present_only_with_known_peaks():
    for peaks, expect_mfu in ((ChipPeaks("test", 1.0, 100.0), True),
                              (None, False)):
        reg = MetricsRegistry(namespace="t")
        jrn = EventJournal(registry=reg)
        acc = DeviceTimeAccountant(registry=reg, journal=jrn, peaks=peaks)
        acc.register_cost("serve_eval[t]", _fake_cost())
        # 1e9 flops in 0.01 s = 100 GFLOP/s = 10% of the 1-TFLOP peak
        acc.observe_batch("serve_eval[t]", "t", 0.01, occupancy=4, slots=8,
                          real_density=0.5)
        mfu = reg.value("device_mfu", labels={"program": "serve_eval[t]"})
        if expect_mfu:
            assert mfu == pytest.approx(0.1, rel=0.01)
            assert reg.value("device_roofline_ridge") == pytest.approx(10.0)
        else:
            assert mfu == 0.0  # never set: absent, not fabricated
        # platform-free gauges export either way
        assert reg.value("device_util_fraction") > 0
        assert reg.value("device_useful_flops_fraction",
                         labels={"bucket": "t"}) == pytest.approx(0.25)
        assert reg.value("device_roofline_intensity",
                         labels={"program": "serve_eval[t]"}) == \
            pytest.approx(1e9 / 1e6)


def test_accountant_snapshot_surfaces_per_program_truth():
    reg = MetricsRegistry(namespace="t")
    acc = DeviceTimeAccountant(registry=reg, journal=EventJournal(),
                               peaks=ChipPeaks("test", 1.0, 100.0))
    acc.register_cost("p", _fake_cost(program="p"))
    for _ in range(3):
        acc.observe_batch("p", "t", 0.02, occupancy=8, slots=8)
    snap = acc.snapshot()
    assert snap["platform_peaks"]["tflops_bf16"] == 1.0
    p = snap["programs"]["p"]
    assert p["calls"] == 3
    assert p["device_seconds"] == pytest.approx(0.06, rel=0.01)
    assert p["mfu"] == pytest.approx(3e9 / 0.06 / 1e12, rel=0.01)
    assert snap["useful_flops_fraction"]["t"] == 1.0
    assert 0 < snap["util_fraction"] <= 1.0


def test_accountant_util_and_useful_age_out_stale_programs(monkeypatch):
    """Regression: utilization must not keep a quiet program's old busy
    seconds in the sum forever (per-observe eviction only touches the
    observed program), and snapshot's useful-FLOPs must apply the same
    trailing filter as its programs block."""
    import time as _time

    clock = [1000.0]
    monkeypatch.setattr(_time, "monotonic", lambda: clock[0])
    reg = MetricsRegistry(namespace="t")
    acc = DeviceTimeAccountant(registry=reg, journal=EventJournal(),
                               peaks=None, window_sec=60.0)
    # program A burns 50 busy-seconds, then traffic moves elsewhere
    for _ in range(5):
        acc.observe_batch("A", "a", 10.0, occupancy=8, slots=8)
    clock[0] += 600.0  # ten quiet minutes
    acc.observe_batch("B", "b", 0.001, occupancy=1, slots=8)
    assert reg.value("device_util_fraction") < 0.01  # not 0.83
    snap = acc.snapshot()
    assert snap["programs"]["A"]["calls"] == 0
    assert "a" not in snap["useful_flops_fraction"]  # aged out with A
    assert "b" in snap["useful_flops_fraction"]


def test_accountant_saturation_journal_record():
    reg = MetricsRegistry(namespace="t")
    jrn = EventJournal(registry=reg)
    acc = DeviceTimeAccountant(registry=reg, journal=jrn, peaks=None,
                               headroom_update_sec=0.0,
                               saturation_margin_streams=1.0)
    # one stream whose demand is ~2x the device: headroom < 0
    for i in range(20):
        acc.observe_admit("s0", "t")
        acc.observe_batch("p", "t", 0.2, occupancy=1, slots=8)
    kinds = [r.kind for r in jrn.tail()]
    assert "capacity_saturation" in kinds
    sat = [r for r in jrn.tail() if r.kind == "capacity_saturation"][-1]
    assert sat.data["headroom_streams"] < 1.0
    assert reg.value("capacity_headroom_streams") == \
        pytest.approx(sat.data["headroom_streams"], abs=0.5)


# ---------------------------------------------------------------------------
# headroom math: synthetic mixes vs the analytic saturation point
# ---------------------------------------------------------------------------

def test_headroom_uniform_mix_hits_analytic_saturation():
    # 4 streams, 2 windows/s each into one bucket costing 25 ms/window:
    # util = 0.2, per-stream demand 0.05 → saturation at exactly 20
    est = predict_headroom(
        {f"s{i}": 2.0 for i in range(4)},
        {f"s{i}": {"b": 1.0} for i in range(4)},
        {"b": 0.025})
    assert est.util == pytest.approx(0.2)
    assert est.saturation_streams == pytest.approx(20.0)
    assert est.headroom_streams == pytest.approx(16.0)


def test_headroom_skewed_rates():
    # rates 1/2/4/8 w/s, same 20 ms bucket: util = 0.3, mean demand
    # 0.075 → headroom (1-0.3)/0.075 = 9.333…
    est = predict_headroom(
        {"a": 1.0, "b": 2.0, "c": 4.0, "d": 8.0},
        {s: {"b": 1.0} for s in "abcd"},
        {"b": 0.02})
    assert est.util == pytest.approx(0.3)
    assert est.headroom_streams == pytest.approx((1 - 0.3) / 0.075)


def test_headroom_one_bucket_hot_mix():
    # two streams split across buckets; one bucket 10x more expensive:
    # util = 2·(0.5·0.1 + 0.5·0.01) = 0.11, mean demand 0.055
    est = predict_headroom(
        {"a": 1.0, "b": 1.0},
        {s: {"hot": 0.5, "cold": 0.5} for s in "ab"},
        {"hot": 0.1, "cold": 0.01})
    assert est.util == pytest.approx(0.11)
    assert est.per_bucket_util["hot"] == pytest.approx(0.1)
    assert est.saturation_streams == pytest.approx(2 + (1 - 0.11) / 0.055)


def test_headroom_degenerate_cases_return_null():
    # zero traffic
    assert predict_headroom({}, {}, {"b": 0.1}) is None
    assert predict_headroom({"s": 0.0}, {"s": {"b": 1.0}}, {"b": 0.1}) \
        is None
    # unknown bucket: never a fake number
    assert predict_headroom({"s": 1.0}, {"s": {"mystery": 1.0}},
                            {"b": 0.1}) is None
    # missing mix for an active stream
    assert predict_headroom({"s": 1.0}, {}, {"b": 0.1}) is None


def test_headroom_tracker_windows_arrivals_and_costs():
    trk = HeadroomTracker(window_sec=100.0)
    # 2 streams x 10 windows over 10 synthetic seconds = 1 w/s each;
    # measured cost 50 ms/window → saturation at 20 streams
    for i in range(10):
        t = float(i)
        trk.observe_admit("a", "b", t=t)
        trk.observe_admit("b", "b", t=t)
        trk.observe_batch("b", 0.1, 2, t=t + 0.5)
    est = trk.estimate(now=10.0)
    assert est is not None
    assert est.streams == 2
    assert est.saturation_streams == pytest.approx(20.0, rel=0.05)
    # no batches yet → no cost → null
    assert HeadroomTracker().estimate(now=1.0) is None


# ---------------------------------------------------------------------------
# profiler capture plane (the first tests trace_profile ever had)
# ---------------------------------------------------------------------------

def test_capture_produces_readable_trace_dir(tmp_path):
    import jax
    import jax.numpy as jnp

    jrn = EventJournal()
    out = str(tmp_path / "trace")
    with profiled(out, journal=jrn) as active:
        assert active == out
        jax.jit(lambda x: x * 2)(jnp.ones((16, 16))).block_until_ready()
    summary = trace_summary(out)
    assert summary is not None and summary["files"] > 0
    assert summary["bytes"] > 0
    kinds = [r.kind for r in jrn.tail()]
    assert "profile_capture" in kinds
    assert "profile_failed" not in kinds


def test_capture_disabled_is_a_noop(tmp_path):
    jrn = EventJournal()
    out = str(tmp_path / "trace")
    with profiled(out, enabled=False, journal=jrn) as active:
        assert active is None
    assert capture_trace(out, seconds=0.0, enabled=False, journal=jrn) \
        is None
    assert not os.path.exists(out)
    assert jrn.tail() == []


def test_capture_start_failure_is_fail_open_with_journal(tmp_path,
                                                         monkeypatch):
    import jax

    def boom(*a, **k):
        raise RuntimeError("profiler already active")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    jrn = EventJournal()
    out = str(tmp_path / "trace")
    with profiled(out, journal=jrn) as active:
        assert active is None  # fail-open: caller proceeds traceless
    recs = [r for r in jrn.tail() if r.kind == "profile_failed"]
    assert len(recs) == 1
    assert recs[0].data["phase"] == "start"
    assert "profiler already active" in recs[0].data["error"]
    assert capture_trace(out, seconds=0.0, journal=jrn) is None


def test_trace_summary_null_for_absent_or_empty(tmp_path):
    assert trace_summary(tmp_path / "nope") is None
    empty = tmp_path / "empty"
    empty.mkdir()
    assert trace_summary(empty) is None


# ---------------------------------------------------------------------------
# flight-recorder integration: profile-on-p99-breach into the bundle
# ---------------------------------------------------------------------------

def _breach_recorder(tmp_path, profile_sec):
    from nerrf_tpu.flight import FlightConfig, FlightRecorder

    reg = MetricsRegistry(namespace="t")
    jrn = EventJournal(registry=reg)
    rec = FlightRecorder(
        FlightConfig(out_dir=str(tmp_path / "bundles"),
                     p99_breach_sec=0.1, p99_min_count=4,
                     min_interval_sec=300.0,
                     profile_on_p99_sec=profile_sec),
        registry=reg, journal=jrn)
    for _ in range(6):
        rec.observe_window("s0", "tid-1", 1.0)
    rec.close()
    bundles = sorted((tmp_path / "bundles").glob("bundle-*"))
    assert len(bundles) == 1
    return bundles[0]


def test_p99_bundle_embeds_profiler_trace_and_doctor_reads_it(tmp_path):
    from nerrf_tpu.flight.doctor import format_report, read_bundle

    bundle_dir = _breach_recorder(tmp_path, profile_sec=0.1)
    assert (bundle_dir / "jax_trace").is_dir()
    bundle = read_bundle(bundle_dir)
    assert bundle["missing"] == []
    assert bundle["profile"] and bundle["profile"]["files"] > 0
    man_prof = bundle["manifest"]["profile"]
    assert man_prof["dir"] == "jax_trace"
    assert man_prof["seconds"] == 0.1
    report = format_report(bundle)
    assert "profiler trace:" in report
    assert "jax_trace/" in report


def test_p99_bundle_without_optin_has_no_trace(tmp_path):
    from nerrf_tpu.flight.doctor import format_report, read_bundle

    bundle_dir = _breach_recorder(tmp_path, profile_sec=0.0)
    assert not (bundle_dir / "jax_trace").exists()
    bundle = read_bundle(bundle_dir)
    assert bundle["profile"] is None
    assert bundle["manifest"]["profile"] is None
    assert "profiler trace:" not in format_report(bundle)


def test_profile_capture_failure_still_ships_the_bundle(tmp_path,
                                                        monkeypatch):
    import jax

    from nerrf_tpu.flight.doctor import read_bundle

    monkeypatch.setattr(
        jax.profiler, "start_trace",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("busy")))
    bundle_dir = _breach_recorder(tmp_path, profile_sec=0.1)
    bundle = read_bundle(bundle_dir)
    assert bundle["missing"] == []  # the bundle itself is intact
    assert bundle["profile"] is None
    assert "error" in bundle["manifest"]["profile"]
    # the fail-open record is in the bundled journal tail
    assert any(r.kind == "profile_failed" for r in bundle["records"])


# ---------------------------------------------------------------------------
# serve integration: the scorer-side observation path
# ---------------------------------------------------------------------------

def test_service_observe_devtime_derives_tag_occupancy_density():
    from conftest import make_service_shell

    from nerrf_tpu.serve import ServeConfig

    cfg = ServeConfig(buckets=((64, 128, 32),))
    svc, reg = make_service_shell(cfg)
    acc = DeviceTimeAccountant(registry=reg, journal=svc._journal,
                               peaks=None)
    acc.register_cost("serve_eval[64n/128e/32s]", _fake_cost(
        program="serve_eval[64n/128e/32s]"))
    svc._devtime = acc
    mask = np.zeros((8, 64), bool)
    mask[0, :32] = True   # one real window, half-dense
    mask[1, :16] = True   # one real window, quarter-dense
    batch = {"node_feat": np.zeros((8, 64, 5), np.float32),
             "edge_src": np.zeros((8, 128), np.int32),
             "seq_feat": np.zeros((8, 32, 100, 8), np.float32),
             "node_mask": mask}
    svc._observe_devtime(batch, 0.05)
    # occupancy 2/8 x mean density of the OCCUPIED slots (0.375)
    assert reg.value("device_useful_flops_fraction",
                     labels={"bucket": "64n/128e/32s"}) == \
        pytest.approx((2 / 8) * 0.375)
    snap = acc.snapshot()
    assert snap["programs"]["serve_eval[64n/128e/32s]"]["calls"] == 1
