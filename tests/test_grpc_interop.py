"""Foreign-client interop: the hand-rolled HTTP/2+HPACK gRPC server
(native/src/h2grpc.cc) must speak to a STOCK third-party gRPC stack.

This is the reference's grpcurl flow (`tracker/scripts/test.sh:76-82`) done
with the real grpcio library (VERDICT r3 item 6): a hand-rolled H2 server
that has only ever met its own clients would never see an interop bug in
SETTINGS handling, connection/stream flow-control windows, or HPACK dynamic
table state.  The daemon runs in `--synthetic` mode — the full
encode→batch→broadcast→HTTP/2 path with a fabricated workload — so the
test needs no BPF permission and never skips on capability.

Unlike test_capture.py (live kernel events, skips without CAP_BPF), the
only skips here are a failed native build or a missing grpcio.
"""

import re
import subprocess
import time
from pathlib import Path

import pytest

grpc = pytest.importorskip("grpc")

REPO = Path(__file__).resolve().parent.parent
DAEMON = REPO / "native" / "build" / "nerrf-trackerd"
_METHOD = "/nerrf.trace.Tracker/StreamEvents"


@pytest.fixture(scope="module")
def synthetic_daemon():
    from nerrf_tpu.ingest.service import spawn_trackerd

    try:
        proc, port = spawn_trackerd(["--synthetic", "2000",
                                     "--max-seconds", "120"])
    except RuntimeError as e:
        pytest.skip(str(e))
    yield port
    proc.terminate()
    proc.wait(timeout=10)


def test_stock_grpc_client_streams_events(synthetic_daemon):
    """≥100 events must arrive through grpcio's own HTTP/2 machinery and
    decode as valid EventBatch frames."""
    from nerrf_tpu.ingest import trace_pb2

    port = synthetic_daemon
    events = []
    with grpc.insecure_channel(f"127.0.0.1:{port}") as channel:
        call = channel.unary_stream(
            _METHOD,
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=lambda b: b,
        )(trace_pb2.Empty(), timeout=30.0)
        for frame in call:
            batch = trace_pb2.EventBatch()
            batch.ParseFromString(frame)
            events.extend(batch.events)
            if len(events) >= 150:
                call.cancel()
                break
    assert len(events) >= 100, f"only {len(events)} events arrived"
    # the synthetic workload is the canonical triple; field content must
    # round-trip through protobuf exactly
    syscalls = {e.syscall for e in events}
    assert {"openat", "write", "rename"} <= syscalls
    renames = [e for e in events if e.syscall == "rename"]
    assert renames and all(e.new_path.endswith(".lockbit3") for e in renames)
    writes = [e for e in events if e.syscall == "write"]
    assert writes and all(e.bytes == 4096 for e in writes)
    assert all(e.pid == 4242 for e in events)
    assert all(e.comm == "synthload" for e in events)
    # wall-clock timestamps (monotonic→wall corrected server-side)
    now = time.time()
    assert all(abs(e.ts.seconds - now) < 3600 for e in events[:10])


def test_stock_grpc_client_ingest_bridge_path(synthetic_daemon):
    """The deployed ingest path — TrackerClient (grpcio) → native C++ frame
    decode — against the native daemon."""
    from nerrf_tpu.ingest.service import TrackerClient
    from nerrf_tpu.schema.events import Syscall

    client = TrackerClient(f"127.0.0.1:{synthetic_daemon}")
    events, strings = client.stream(max_events=150, timeout=30.0)
    assert events.num_valid >= 100
    seen = {int(s) for s in events.syscall[events.valid]}
    assert {int(Syscall.OPENAT), int(Syscall.WRITE),
            int(Syscall.RENAME)} <= seen
    paths = {strings.lookup(int(i)) for i in events.path_id[events.valid]}
    assert any(p.startswith("/app/uploads/doc_") for p in paths)


def test_two_concurrent_stock_clients(synthetic_daemon):
    """Per-subscriber queues + H2 stream multiplexing: two grpcio channels
    must each receive an independent copy of the stream."""
    from nerrf_tpu.ingest.service import TrackerClient

    results = []
    import threading

    def drain():
        c = TrackerClient(f"127.0.0.1:{synthetic_daemon}")
        ev, _ = c.stream(max_events=80, timeout=30.0)
        results.append(ev.num_valid)

    ts = [threading.Thread(target=drain) for _ in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=40)
    assert len(results) == 2 and all(n >= 80 for n in results), results


# ---- server reflection ------------------------------------------------------
# The reference registers the standard reflection service so grpcurl works
# schema-free (`tracker/cmd/tracker/main.go:135`; debug flow
# `docs/content/docs/tracker/implementation.mdx:592-602`).  No
# grpcio-reflection package exists in this environment, so this is a
# hand-rolled reflection CLIENT: encode ServerReflectionRequest / decode
# ServerReflectionResponse with the (public, trivial) protobuf wire format
# and verify the returned descriptors with protobuf's own descriptor_pb2.

_REFLECT = "/grpc.reflection.v1alpha.ServerReflection/ServerReflectionInfo"


def _tag(field, wire=2):
    return bytes([(field << 3) | wire])


def _ld(field, payload: bytes) -> bytes:
    assert len(payload) < 128
    return _tag(field) + bytes([len(payload)]) + payload


def _fields(buf: bytes):
    """Yield (field, payload) for length-delimited fields of one message."""
    i = 0
    while i < len(buf):
        key = buf[i]
        i += 1
        field, wire = key >> 3, key & 7
        if wire == 2:
            ln = 0
            shift = 0
            while True:
                b = buf[i]
                i += 1
                ln |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            yield field, buf[i:i + ln]
            i += ln
        elif wire == 0:
            while buf[i] & 0x80:
                i += 1
            i += 1
        else:
            raise AssertionError(f"unexpected wire type {wire}")


def _reflect(port, request: bytes, timeout=15.0) -> dict:
    with grpc.insecure_channel(f"127.0.0.1:{port}") as channel:
        call = channel.stream_stream(
            _REFLECT,
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )(iter([request]), timeout=timeout)
        return dict(_fields(next(iter(call))))


def test_reflection_list_services(synthetic_daemon):
    """grpcurl's `list` flow: ListServiceResponse must name the Tracker."""
    resp = _reflect(synthetic_daemon, _ld(7, b""))
    assert 6 in resp, f"no list_services_response arm in {resp}"
    names = [dict(_fields(svc))[1].decode()
             for f, svc in _fields(resp[6]) if f == 1]
    assert "nerrf.trace.Tracker" in names


def test_reflection_file_containing_symbol(synthetic_daemon):
    """grpcurl's `describe nerrf.trace.Tracker`: the descriptor bytes must
    parse as the real trace.proto, imports included."""
    from google.protobuf import descriptor_pb2

    resp = _reflect(synthetic_daemon,
                    _ld(4, b"nerrf.trace.Tracker"))
    assert 4 in resp, f"no file_descriptor_response arm in {resp}"
    files = {}
    for f, fd_bytes in _fields(resp[4]):
        if f == 1:
            fdp = descriptor_pb2.FileDescriptorProto()
            fdp.ParseFromString(fd_bytes)
            files[fdp.name] = fdp
    assert "trace.proto" in files
    trace = files["trace.proto"]
    assert trace.package == "nerrf.trace"
    assert [s.name for s in trace.service] == ["Tracker"]
    assert [m.name for m in trace.service[0].method] == ["StreamEvents"]
    # transitive deps travel with the file (grpcurl needs timestamp.proto
    # to resolve Event.ts)
    assert "google/protobuf/timestamp.proto" in files


def test_reflection_file_by_filename_and_not_found(synthetic_daemon):
    from google.protobuf import descriptor_pb2

    resp = _reflect(synthetic_daemon, _ld(3, b"trace.proto"))
    assert 4 in resp
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.ParseFromString(next(b for f, b in _fields(resp[4]) if f == 1))
    assert {m.name for m in fdp.message_type} >= {"Event", "EventBatch",
                                                  "Empty"}

    missing = _reflect(synthetic_daemon, _ld(4, b"no.such.Symbol"))
    assert 7 in missing, f"expected error_response, got {missing}"


# ---- reflection on the Python replay server ---------------------------------
# The same grpcurl list/describe flows against TraceReplayServer (the
# reference daemon's replay flavor), served from the descriptor bytes
# already checked in as trace_pb2 — no grpcio-reflection dependency.


@pytest.fixture(scope="module")
def replay_server():
    from nerrf_tpu.data import SimConfig, simulate_trace
    from nerrf_tpu.ingest.service import TraceReplayServer

    tr = simulate_trace(SimConfig(duration_sec=10.0, attack=False,
                                  num_target_files=2, benign_rate_hz=4.0,
                                  seed=1))
    server = TraceReplayServer(tr.events, tr.strings)
    port = server.start()
    yield port
    server.stop()


def test_replay_server_reflection_list_services(replay_server):
    resp = _reflect(replay_server, _ld(7, b""))
    assert 6 in resp, f"no list_services_response arm in {resp}"
    names = [dict(_fields(svc))[1].decode()
             for f, svc in _fields(resp[6]) if f == 1]
    assert "nerrf.trace.Tracker" in names
    # both reflection flavors are themselves listed (grpcurl shows them)
    assert "grpc.reflection.v1alpha.ServerReflection" in names


def test_replay_server_reflection_file_containing_symbol(replay_server):
    from google.protobuf import descriptor_pb2

    resp = _reflect(replay_server, _ld(4, b"nerrf.trace.Tracker"))
    assert 4 in resp, f"no file_descriptor_response arm in {resp}"
    files = {}
    for f, fd_bytes in _fields(resp[4]):
        if f == 1:
            fdp = descriptor_pb2.FileDescriptorProto()
            fdp.ParseFromString(fd_bytes)
            files[fdp.name] = fdp
    assert "trace.proto" in files
    trace = files["trace.proto"]
    assert trace.package == "nerrf.trace"
    assert [s.name for s in trace.service] == ["Tracker"]
    assert [m.name for m in trace.service[0].method] == ["StreamEvents"]
    # transitive deps travel with the file (grpcurl needs timestamp.proto
    # to resolve Event.ts)
    assert "google/protobuf/timestamp.proto" in files


def test_replay_server_reflection_v1_and_errors(replay_server):
    from google.protobuf import descriptor_pb2

    # the newer v1 service name answers identically (modern grpcurl tries
    # it first)
    with grpc.insecure_channel(f"127.0.0.1:{replay_server}") as channel:
        call = channel.stream_stream(
            "/grpc.reflection.v1.ServerReflection/ServerReflectionInfo",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )(iter([_ld(3, b"trace.proto")]), timeout=15.0)
        resp = dict(_fields(next(iter(call))))
    assert 4 in resp
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.ParseFromString(next(b for f, b in _fields(resp[4]) if f == 1))
    assert {m.name for m in fdp.message_type} >= {"Event", "EventBatch",
                                                  "Empty"}
    missing = _reflect(replay_server, _ld(4, b"no.such.Symbol"))
    assert 7 in missing, f"expected error_response, got {missing}"


def test_replay_server_reflection_streams_coexist(replay_server):
    """Reflection must not disturb the event stream: both RPCs on one
    server, one after the other."""
    from nerrf_tpu.ingest.service import TrackerClient

    resp = _reflect(replay_server, _ld(7, b""))
    assert 6 in resp
    events, _ = TrackerClient(f"127.0.0.1:{replay_server}").stream(
        max_events=50, timeout=30.0)
    assert events.num_valid > 0


def test_replay_mode_delivers_trace_with_parity(tmp_path):
    """--replay streams a real incident trace through the daemon: every
    event must arrive through stock grpcio, with syscalls/paths intact and
    the stream ending in a clean grpc-status 0 (not a RST).  This is the
    transport leg of the end-to-end wire artifact
    (benchmarks/run_e2e_daemon.py)."""
    from nerrf_tpu.data import SimConfig, simulate_trace
    from nerrf_tpu.ingest.service import TrackerClient, spawn_trackerd
    from nerrf_tpu.schema.events import events_to_jsonl

    tr = simulate_trace(SimConfig(duration_sec=20.0, attack=True,
                                  attack_start_sec=5.0, seed=8))
    n_src = int(tr.events.num_valid)
    trace_path = tmp_path / "trace.jsonl"
    trace_path.write_text(events_to_jsonl(tr.events, tr.strings))

    proc, port = spawn_trackerd(["--replay", str(trace_path),
                                 "--replay-rate", "5000",
                                 "--max-seconds", "60"])
    try:
        events, strings = TrackerClient(f"127.0.0.1:{port}").stream(
            max_events=n_src + 100, timeout=30.0)
        assert int(events.num_valid) == n_src
        new_paths = {strings.lookup(int(i))
                     for i in events.new_path_id[events.valid]}
        assert any(p.endswith(".lockbit3") for p in new_paths)
    finally:
        proc.terminate()
        proc.wait(timeout=10)
