"""Test configuration: force an 8-device virtual CPU mesh before JAX imports.

Multi-chip sharding is validated on virtual devices (the CI host has at most
one real TPU chip); see SURVEY.md §4 for the test strategy.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
