#!/usr/bin/env python3
"""Injected-divergence bench: the training-health plane end to end.

Proves trainwatch's one-sentence contract on the REAL training loop: a
healthy run is untouched by the health plane (bit-identical loss history,
zero bundles, zero recompiles), and a poisoned step produces exactly one
doctor-readable ``train_divergence`` bundle whose journal tail joins the
offending step — plus the compile-cache key discipline (telemetry on/off
resolve to DISTINCT fingerprints, repeat runs deserialize).

Legs (tiny model, streaming batch path — ``NERRF_RESIDENT_MAX_BYTES=0``
pins the loop to the path that carries the chaos point):

  1. **clean A** — telemetry + monitor + flight recorder armed, step
     routed through a fresh compile cache (source=fresh).  Zero bundles,
     /readyz 503 before the first step and 200 after.
  2. **clean B** — identical config, same cache: the loss history must be
     BIT-IDENTICAL to A (the health plane observes, never perturbs), the
     step must deserialize (source=cache — zero recompiles), zero
     bundles.
  3. **telemetry off** — same config with ``telemetry=False``: the cache
     must MISS (source=fresh, distinct fingerprint) — a telemetry-off
     executable's output treedef lacks the telemetry leaves and must
     never serve a telemetry-on run (the deep-lint cache-key-coverage
     axis, proven here on the live cache).
  4. **faulted** — a seeded ``train.nonfinite_grad`` chaos spec poisons
     one step's input with NaN: the in-step nonfinite telemetry fires,
     EXACTLY one ``train_divergence`` bundle lands, `nerrf doctor` reads
     it offline (training-health section + the offending step in the
     journal tail), the loop halts, /readyz turns 503 — and the step
     still resolved source=cache (a fault changes no shapes).

    python benchmarks/run_train_health_bench.py
    python benchmarks/run_train_health_bench.py --smoke
    python benchmarks/run_train_health_bench.py --out results/train_health_bench_cpu.json

Prints ONE JSON line (the artifact); exit 1 if any gate fails.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

FAULT_AT = 8  # hit counter: the chaos spec fires on the FAULT_AT-th step


def _readyz(port: int) -> tuple:
    """(status_code, reason) from a live /readyz probe."""
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/readyz", timeout=5) as r:
            return r.status, json.loads(r.read()).get("reason")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()).get("reason")


def run(steps: int = 48, smoke: bool = False,
        log=lambda *a: print(*a, file=sys.stderr, flush=True)) -> dict:
    """Importable harness body; returns the artifact dict."""
    if smoke:
        steps = 24
    log = log or (lambda *a: None)
    import dataclasses

    import jax

    from nerrf_tpu import chaos
    from nerrf_tpu.chaos import FaultPlan, FaultSpec
    from nerrf_tpu.compilecache import CompileCache
    from nerrf_tpu.data import make_corpus
    from nerrf_tpu.flight import FlightConfig, FlightRecorder
    from nerrf_tpu.flight.doctor import format_report, read_bundle
    from nerrf_tpu.flight.journal import DEFAULT_JOURNAL
    from nerrf_tpu.graph import GraphConfig
    from nerrf_tpu.models import GraphSAGEConfig, JointConfig, LSTMConfig
    from nerrf_tpu.observability import MetricsServer
    from nerrf_tpu.train import TrainConfig, build_dataset, train_nerrfnet
    from nerrf_tpu.train.data import DatasetConfig
    from nerrf_tpu.trainwatch import TrainHealthConfig, TrainHealthMonitor

    backend = jax.default_backend()
    work = tempfile.mkdtemp(prefix="nerrf-train-health-bench-")
    prev_resident = os.environ.get("NERRF_RESIDENT_MAX_BYTES")
    # pin the loop to the streaming batch path: the resident/scheduled
    # flavors build their batches on device, where the chaos point's
    # host-side poison cannot reach
    os.environ["NERRF_RESIDENT_MAX_BYTES"] = "0"

    corpus = make_corpus(3, attack_fraction=0.5, base_seed=7,
                         duration_sec=60.0, num_target_files=4,
                         benign_rate_hz=10.0)
    ds = build_dataset(corpus, DatasetConfig(
        graph=GraphConfig(window_sec=45.0, stride_sec=25.0,
                          max_nodes=64, max_edges=128),
        seq_len=16, max_seqs=16))
    model_cfg = JointConfig(
        gnn=GraphSAGEConfig(hidden=8, num_layers=1),
        lstm=LSTMConfig(hidden=8, num_layers=1))
    cfg = TrainConfig(model=model_cfg, batch_size=4, num_steps=steps,
                      eval_every=1, warmup_steps=4, telemetry=True)
    cache = CompileCache(root=os.path.join(work, "aot"), log=log)
    journal = DEFAULT_JOURNAL  # the loop journals train_* into the default

    def leg(name: str, leg_cfg, with_monitor: bool = True,
            probe_ready: bool = False) -> dict:
        out_dir = os.path.join(work, name)
        seq0 = journal.seq
        monitor = recorder = server = None
        ready_before = ready_after = None
        try:
            if with_monitor:
                monitor = TrainHealthMonitor(
                    TrainHealthConfig(journal_every=4, min_history=4))
                recorder = FlightRecorder(FlightConfig(out_dir=out_dir),
                                          info=monitor.flight_info, log=log)
                monitor.attach_flight(recorder)
                monitor.start()
                if probe_ready:
                    server = MetricsServer(port=0,
                                           ready_check=monitor.ready)
                    ready_before = _readyz(server.port)
            res = train_nerrfnet(ds, None, leg_cfg, monitor=monitor,
                                 compile_cache=cache)
            if server is not None:
                ready_after = _readyz(server.port)
        finally:
            if monitor is not None:
                monitor.stop()
            if recorder is not None:
                recorder.close()
            if server is not None:
                server.close()
        compiles = [r.data for r in journal.tail(kinds=("compile",),
                                                 since_seq=seq0)
                    if r.data.get("program") == "train_step"]
        bundles = sorted(p for p in (os.listdir(out_dir)
                                     if os.path.isdir(out_dir) else [])
                         if p.startswith("bundle-") and
                         not p.endswith(".tmp"))
        out = {
            "history": [round(h["loss"], 8) for h in res.history],
            "steps_logged": len(res.history),
            "bundles": len(bundles),
            "bundle_names": bundles,
            "compile_sources": [c.get("source") for c in compiles],
            "fingerprints": sorted({c.get("fingerprint")
                                    for c in compiles}),
            "snapshot": monitor.snapshot() if monitor is not None else None,
            "out_dir": out_dir,
        }
        if ready_before is not None:
            out["readyz_before"] = ready_before
            out["readyz_after"] = ready_after
        log(f"[train-health-bench] leg {name}: "
            f"{out['steps_logged']} logged steps, "
            f"bundles {out['bundles']}, "
            f"compile {out['compile_sources']}")
        return out

    try:
        clean_a = leg("clean_a", cfg, probe_ready=True)
        clean_b = leg("clean_b", cfg)
        off = leg("off", dataclasses.replace(cfg, telemetry=False),
                  with_monitor=False)
        ctl = chaos.arm(FaultPlan(seed=3, faults=(
            FaultSpec(site="train.nonfinite_grad", mode="corrupt",
                      at=FAULT_AT),)))
        try:
            faulted = leg("faulted", cfg, probe_ready=True)
        finally:
            chaos.disarm()
        faults_fired = len(ctl.fired)

        # offline doctor readability + the journal-tail join of the
        # offending step (the fault_injected record's step must appear in
        # the bundle the trigger dumped)
        doctor = {"ok": False, "joins_offending_step": False,
                  "trigger": None}
        if faulted["bundles"] == 1:
            b = read_bundle(os.path.join(faulted["out_dir"],
                                         faulted["bundle_names"][0]))
            report = format_report(b)
            doctor["trigger"] = faulted["bundle_names"][0].rsplit(
                "-", 1)[-1]
            doctor["ok"] = (not b["missing"]
                            and "training health:" in report
                            and "loss tail" in report)
            injected = [r for r in b["records"]
                        if r.kind == "fault_injected"
                        and r.data.get("site") == "train.nonfinite_grad"]
            diverged = (faulted.get("snapshot") or {}).get("diverged") or {}
            doctor["joins_offending_step"] = bool(
                injected
                and injected[0].data.get("step") == diverged.get("step"))
            doctor["offending_step"] = diverged.get("step")
    finally:
        if prev_resident is None:
            os.environ.pop("NERRF_RESIDENT_MAX_BYTES", None)
        else:
            os.environ["NERRF_RESIDENT_MAX_BYTES"] = prev_resident
        for name in ("clean_a", "clean_b", "off", "faulted"):
            shutil.rmtree(os.path.join(work, name), ignore_errors=True)
        shutil.rmtree(work, ignore_errors=True)

    for d in (clean_a, clean_b, off, faulted):
        d.pop("out_dir", None)
    return {
        "metric": "train_health_divergence_detection",
        "value": (faulted.get("snapshot") or {}).get("diverged", {}),
        "unit": "divergence latched by the injected nonfinite step "
                f"(chaos spec at hit {FAULT_AT})",
        "backend": backend,
        "smoke": smoke or None,
        "steps": steps,
        "clean_a": clean_a,
        "clean_b": clean_b,
        "telemetry_off": off,
        "faulted": faulted,
        "faults_fired": faults_fired,
        "doctor": doctor,
        "provenance": "python benchmarks/run_train_health_bench.py"
                      + (" --smoke" if smoke else ""),
    }


def gates(result: dict) -> list:
    """Every acceptance gate, as (name, ok) — shared by main() and the
    artifact-of-record test."""
    a, b = result["clean_a"], result["clean_b"]
    off, f = result["telemetry_off"], result["faulted"]
    on_fp = set(a["fingerprints"]) | set(b["fingerprints"])
    return [
        ("clean_zero_bundles", a["bundles"] == 0 and b["bundles"] == 0),
        ("clean_history_bit_identical",
         bool(a["history"]) and a["history"] == b["history"]),
        ("clean_first_run_compiles_fresh",
         a["compile_sources"] == ["fresh"]),
        ("clean_second_run_zero_recompiles",
         b["compile_sources"] == ["cache"]),
        ("telemetry_off_distinct_fingerprint",
         off["compile_sources"] == ["fresh"]
         and not (set(off["fingerprints"]) & on_fp)),
        ("readyz_503_before_first_step",
         (a.get("readyz_before") or [None])[0] == 503),
        ("readyz_200_after_clean_run",
         (a.get("readyz_after") or [None])[0] == 200),
        ("faulted_exactly_one_bundle", f["bundles"] == 1),
        ("faulted_bundle_is_train_divergence",
         result["doctor"].get("trigger") == "train_divergence"),
        ("faulted_bundle_doctor_ok", result["doctor"].get("ok") is True),
        ("faulted_journal_joins_offending_step",
         result["doctor"].get("joins_offending_step") is True),
        ("faulted_zero_recompiles", f["compile_sources"] == ["cache"]),
        ("faulted_halted_early",
         f["steps_logged"] < result["steps"]),
        ("faulted_readyz_503_on_divergence",
         (f.get("readyz_after") or [None])[0] == 503),
        ("exactly_one_fault_fired", result["faults_fired"] == 1),
    ]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=48)
    ap.add_argument("--smoke", action="store_true",
                    help="shorter legs (CPU pre-flight)")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="also write the artifact JSON here")
    args = ap.parse_args(argv)

    result = run(steps=args.steps, smoke=args.smoke)
    print(json.dumps(result))
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as fh:
            fh.write(json.dumps(result, indent=2) + "\n")
    failed = [name for name, ok in gates(result) if not ok]
    for name in failed:
        print(f"[train-health-bench] GATE FAILED: {name}", file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
