"""Bidirectional LSTM impact predictor.

Realizes the reference's specified sequence model
(`/root/reference/docs/content/docs/architecture.mdx:55-59`: BiLSTM, 256
hidden, 2 layers, input = last 100 events per file, output = encrypt/
ransomware probability, target F1 ≥ 0.95).  TPU-native shape: the recurrence
is a single fused `lax.scan` per layer — both directions ride one scan
(stacked on a leading axis; one batched matmul per timestep), and the
input-side gate projections are hoisted out of the scan as one big matmul
over all timesteps.  The r5 chip profile measured a ~0.27 ms fixed cost per
sequential kernel on the runtime, so cutting in-scan ops from 4 matmuls per
timestep (2 dirs x input+recurrent) to 1 batched recurrent matmul is worth
~2x on the whole sequence tower.  Param tree is bit-compatible with the
previous `flax.linen.RNN(OptimizedLSTMCell)` implementation
(``OptimizedLSTMCell_{2i}``=fwd / ``_{2i+1}``=bwd, ``ii..io``/``hi..ho``
leaves), which remains available as ``LSTMConfig.impl="rnn"`` and is
parity-tested against the fused path.  Sequences are left-padded with a
step mask; pooling is mask-aware so padding never leaks into the
prediction.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class LSTMConfig:
    hidden: int = 256
    num_layers: int = 2
    dropout: float = 0.1
    dtype: Any = jnp.bfloat16
    # "fused": both directions in one scan, input projections hoisted (the
    # TPU-shaped path; r5 chip measurement).  "rnn": the original flax
    # RNN/OptimizedLSTMCell pair — same math, same param tree (bit-equal in
    # f32, parity-tested), and ~1.5x faster on CPU where per-op overhead is
    # cheap but the batched-einsum layout is not.  "auto" (default): fused
    # on the TPU backend, rnn elsewhere.
    impl: str = "auto"

    @property
    def small(self) -> "LSTMConfig":
        return dataclasses.replace(self, hidden=32, num_layers=1)

    def resolved_impl(self) -> str:
        """The implementation the forward actually uses on this process's
        default backend — single definition of the "auto" rule, shared
        with the bench's kernel_path attribution."""
        if self.impl != "auto":
            return self.impl
        return "fused" if jax.default_backend() == "tpu" else "rnn"


class _GateParams(nn.Module):
    """Param holder replicating one flax LSTMCell dense block (``ii``…,
    ``hi``…): same names, shapes, and initializers, so checkpoints trained
    on either implementation load into the other."""

    features: int
    use_bias: bool
    recurrent: bool

    @nn.compact
    def __call__(self, in_features: int):
        init = (nn.initializers.orthogonal() if self.recurrent
                else nn.initializers.lecun_normal())
        k = self.param("kernel", init, (in_features, self.features))
        b = (self.param("bias", nn.initializers.zeros, (self.features,))
             if self.use_bias else None)
        return k, b


class _CellParams(nn.Module):
    """One LSTM cell's param tree (``ii..io`` input kernels, ``hi..ho``
    recurrent kernels + biases), concatenated per side for the fused path."""

    hidden: int

    @nn.compact
    def __call__(self, in_features: int):
        ki, kh, bh = [], [], []
        for gate in ("i", "f", "g", "o"):
            k, _ = _GateParams(self.hidden, use_bias=False, recurrent=False,
                               name=f"i{gate}")(in_features)
            ki.append(k)
            k, b = _GateParams(self.hidden, use_bias=True, recurrent=True,
                               name=f"h{gate}")(self.hidden)
            kh.append(k)
            bh.append(b)
        return (jnp.concatenate(ki, axis=1), jnp.concatenate(kh, axis=1),
                jnp.concatenate(bh, axis=0))


def _flip_valid(x, lengths):
    """Reverse each sequence within its valid prefix (prefix-first layout);
    positions at or beyond ``lengths`` become zero."""
    T = x.shape[-2] if x.ndim >= 2 else x.shape[0]
    t = jnp.arange(T)
    src = lengths[..., None] - 1 - t  # [..., T]
    ok = src >= 0
    src = jnp.where(ok, src, 0).astype(jnp.int32)
    g = jnp.take_along_axis(x, src[..., None], axis=-2)
    return g * ok[..., None].astype(x.dtype)


class ImpactLSTM(nn.Module):
    """[B, T, F] event sequences → encrypt-probability logits [B] + embedding.

    Returns dict with `seq_logit` [B] and `seq_emb` [B, 2*hidden].
    """

    cfg: LSTMConfig

    def _fused_bilayer(self, x, lengths, layer: int):
        """One BiLSTM layer as a single scan: [B,T,H_in] → (fwd, bwd)."""
        cfg = self.cfg
        dt = cfg.dtype
        H = cfg.hidden
        in_f = x.shape[-1]
        # Param scopes named exactly like the RNN implementation's cells
        # (creation order there: layer0 fwd, layer0 bwd, layer1 fwd, ...).
        cells = []
        for d in range(2):
            ki, kh, bh = _CellParams(
                H, name=f"OptimizedLSTMCell_{2 * layer + d}")(in_f)
            cells.append((ki.astype(dt), kh.astype(dt), bh.astype(dt)))

        xr = _flip_valid(x, lengths)
        # hoisted input projections: one matmul per direction over ALL
        # timesteps — nothing input-dependent remains inside the scan
        xin = jnp.stack([x.astype(dt) @ cells[0][0],
                         xr.astype(dt) @ cells[1][0]])      # [2,B,T,4H]
        wh = jnp.stack([cells[0][1], cells[1][1]])          # [2,H,4H]

        batch_shape = xin.shape[:-2][1:]  # [B] (or () for unbatched input)
        # bias must broadcast against [2, *batch_shape, 4H] whatever the
        # batch rank — a fixed [:, None, :] breaks the unbatched case
        bias = jnp.stack([cells[0][2], cells[1][2]]).reshape(
            (2,) + (1,) * len(batch_shape) + (-1,))
        h0 = jnp.zeros((2,) + batch_shape + (H,), dt)
        c0 = jnp.zeros_like(h0)
        xs = jnp.moveaxis(xin, -2, 0)                       # [T,2,B,4H]

        def step(carry, x_t):
            h, c = carry
            gates = x_t + jnp.einsum("d...h,dhg->d...g", h, wh) + bias
            gi, gf, gg, go = jnp.split(gates, 4, axis=-1)
            c = nn.sigmoid(gf) * c + nn.sigmoid(gi) * jnp.tanh(gg)
            h = nn.sigmoid(go) * jnp.tanh(c)
            return (h, c), h

        # named scope mirrors the host tracing spine: the recurrence's XLA
        # trace rows appear as lstm_scan in Perfetto next to the
        # device_step host span
        with jax.named_scope("lstm_scan"):
            (_, _), hs = jax.lax.scan(step, (h0, c0), xs)   # [T,2,B,H]
        hs = jnp.moveaxis(hs, 0, -2)                        # [2,B,T,H]
        fwd = hs[0]
        bwd = _flip_valid(hs[1], lengths)  # back to original time order
        return fwd, bwd

    @nn.compact
    def __call__(
        self,
        seq_feat,  # [B, T, F] float32
        seq_mask,  # [B, T] bool (True = real event)
        *,
        deterministic: bool = True,
    ) -> Dict[str, jnp.ndarray]:
        cfg = self.cfg
        dt = cfg.dtype
        x = nn.Dense(cfg.hidden, dtype=dt, name="in_proj")(seq_feat.astype(dt))
        x = nn.gelu(x)
        x = x * seq_mask[..., None].astype(dt)

        # left-padded input → flip to prefix-first layout, so "lengths"
        # bounds the valid prefix for both implementations
        lengths = seq_mask.sum(axis=-1).astype(jnp.int32)
        x = jnp.flip(x, axis=-2)
        mask_pf = jnp.flip(seq_mask, axis=-1)[..., None].astype(dt)
        impl = cfg.resolved_impl()
        for i in range(cfg.num_layers):
            with jax.named_scope(f"lstm_layer_{i}"):
                if impl == "fused":
                    fwd, bwd = self._fused_bilayer(x, lengths, i)
                else:
                    fwd = nn.RNN(nn.OptimizedLSTMCell(cfg.hidden, dtype=dt),
                                 name=f"fwd_{i}")(x, seq_lengths=lengths)
                    bwd = nn.RNN(nn.OptimizedLSTMCell(cfg.hidden, dtype=dt),
                                 reverse=True, keep_order=True,
                                 name=f"bwd_{i}")(x, seq_lengths=lengths)
                y = jnp.concatenate([fwd, bwd], axis=-1)
                x = nn.Dense(cfg.hidden, dtype=dt, name=f"merge_{i}")(y)
                x = nn.gelu(x)
                x = x * mask_pf

        # mask-aware mean pool over valid steps
        pooled = (x * mask_pf).sum(axis=-2) / jnp.maximum(
            mask_pf.sum(axis=-2), 1.0)
        pooled = nn.LayerNorm(dtype=dt, name="pool_ln")(pooled)
        if cfg.dropout > 0:
            pooled = nn.Dropout(cfg.dropout, deterministic=deterministic)(pooled)
        logit = nn.Dense(1, dtype=jnp.float32, name="head")(pooled)[:, 0]
        return {"seq_logit": logit, "seq_emb": pooled.astype(jnp.float32)}
