"""Model lifecycle registry: store invariants (atomic publish/promote,
concurrent publish safety, rollback repoint), shadow disagreement math,
guardrail verdicts, the deterministic in-process hot-swap, and the
checkpoint atomicity/corruption satellites it builds on.

Everything here runs with a FAKE score function reading the service's
live param pointer — the swap/shadow mechanics are model-free by design;
the compiled-model parity across a real swap is the swap bench's job
(benchmarks/run_swap_bench.py, smoke-run from bench.py)."""

import dataclasses
import json
import threading
import time

import numpy as np
import pytest

from nerrf_tpu.models import GraphSAGEConfig, JointConfig, LSTMConfig
from nerrf_tpu.observability import MetricsRegistry
from nerrf_tpu.registry import (
    PROMOTE,
    VETO,
    WAIT,
    ModelManager,
    ModelRegistry,
    RegistryConfig,
    evaluate,
    make_stats,
)
from nerrf_tpu.serve import MicroBatcher, ServeConfig

BUCKET = (256, 512, 64)


def _leaf_params(value: float):
    """A tiny param pytree whose single leaf encodes the 'model': the fake
    score function scores every node with it, so scores prove which
    version scored a window."""
    return {"dense": {"w": np.full((2, 2), value, np.float32)}}


@pytest.fixture()
def ckpt_dir(tmp_path):
    """A real (tiny) checkpoint directory via the real saver."""
    from nerrf_tpu.train.checkpoint import save_checkpoint

    path = tmp_path / "ckpt"
    save_checkpoint(path, _leaf_params(0.25), JointConfig().small,
                    calibration={"node_threshold": 0.42})
    return path


# -- checkpoint atomicity + corruption satellites -----------------------------

def test_save_checkpoint_is_atomic_under_crash(tmp_path, monkeypatch):
    """A crash mid-save must leave the previous checkpoint fully intact
    and no half-written directory at the target path."""
    from nerrf_tpu.train import checkpoint as ck

    path = tmp_path / "model"
    ck.save_checkpoint(path, _leaf_params(1.0), JointConfig().small)
    before = json.loads((path / "model_config.json").read_text())

    real_write = ck.Path.write_text

    def crashing_write(self, *a, **kw):
        if self.name == "model_config.json":
            raise OSError("disk full mid-sidecar")
        return real_write(self, *a, **kw)

    monkeypatch.setattr(ck.Path, "write_text", crashing_write)
    with pytest.raises(OSError):
        ck.save_checkpoint(path, _leaf_params(2.0), JointConfig().small)
    monkeypatch.undo()
    # the OLD checkpoint is still complete and loadable
    params, cfg = ck.load_checkpoint(path)
    assert float(np.asarray(params["dense"]["w"]).ravel()[0]) == 1.0
    assert json.loads((path / "model_config.json").read_text()) == before
    # and no torn temp dir was left where a watcher would find it
    assert not (tmp_path / ".model.tmp").exists()
    # the next save over the survivor still works
    ck.save_checkpoint(path, _leaf_params(3.0), JointConfig().small)
    params, _ = ck.load_checkpoint(path)
    assert float(np.asarray(params["dense"]["w"]).ravel()[0]) == 3.0


def test_save_checkpoint_recovers_parked_previous_after_rename_crash(tmp_path):
    """A crash in the window between the two final renames parks the only
    good checkpoint at .<name>.old; the next save must recover it (never
    rmtree it) before starting."""
    import os

    from nerrf_tpu.train import checkpoint as ck

    path = tmp_path / "model"
    ck.save_checkpoint(path, _leaf_params(1.0), JointConfig().small)
    # simulate the crash state: path renamed away, new tmp never landed
    os.rename(path, tmp_path / ".model.old")
    assert not path.exists()
    ck.save_checkpoint(path, _leaf_params(2.0), JointConfig().small)
    params, _ = ck.load_checkpoint(path)
    assert float(np.asarray(params["dense"]["w"]).ravel()[0]) == 2.0
    assert not (tmp_path / ".model.old").exists()


def test_load_checkpoint_corrupt_and_missing_sidecar_error_clearly(tmp_path):
    from nerrf_tpu.train.checkpoint import (
        load_calibration,
        load_checkpoint,
        save_checkpoint,
    )

    # missing sidecar (empty dir): one clear line, not a raw FileNotFound
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(FileNotFoundError, match="not a checkpoint"):
        load_checkpoint(empty)
    with pytest.raises(FileNotFoundError, match="not a checkpoint"):
        load_calibration(empty)

    # corrupt JSON
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "model_config.json").write_text("{not json")
    with pytest.raises(ValueError, match="corrupt checkpoint sidecar"):
        load_checkpoint(bad)

    # missing meta key (the old raw-KeyError path)
    torn = tmp_path / "torn"
    save_checkpoint(torn, _leaf_params(1.0), JointConfig().small)
    meta = json.loads((torn / "model_config.json").read_text())
    del meta["lstm"]
    (torn / "model_config.json").write_text(json.dumps(meta))
    with pytest.raises(ValueError, match="missing or malformed"):
        load_checkpoint(torn)


# -- store: publish / promote / rollback --------------------------------------

def test_store_publish_promote_rollback_roundtrip(tmp_path, ckpt_dir):
    reg = ModelRegistry(tmp_path / "registry")
    assert reg.versions("det") == []
    assert reg.live_version("det") is None
    v1 = reg.publish("det", ckpt_dir, source="test")
    v2 = reg.publish("det", ckpt_dir)
    assert (v1, v2) == (1, 2)
    assert reg.versions("det") == [1, 2]
    # publish never touches LIVE
    assert reg.live_version("det") is None
    reg.promote("det", v1)
    assert reg.live_version("det") == 1
    reg.promote("det", v2)
    live = reg.live("det")
    assert live["version"] == 2 and live["previous"] == 1
    # one-command rollback repoints at the recorded previous
    rec = reg.rollback("det")
    assert rec["version"] == 1 and rec["kind"] == "rollback"
    assert reg.live_version("det") == 1
    # the rolled-past version directory is untouched (post-mortem material)
    assert (reg.version_dir("det", 2) / "model_config.json").exists()
    params, cfg, calib, ver = reg.load("det")
    assert ver == 1 and calib["node_threshold"] == 0.42
    st = reg.status("det")
    assert [v["version"] for v in st["versions"]] == [1, 2]
    assert [v["live"] for v in st["versions"]] == [True, False]


def test_store_publish_journals_into_injected_journal(tmp_path, ckpt_dir):
    """Embedders with an isolated EventJournal (the serve bench, the fake
    swap service in these tests) must see their own publish records there —
    not silently in the process-wide DEFAULT_JOURNAL."""
    from nerrf_tpu.flight.journal import DEFAULT_JOURNAL, EventJournal

    journal = EventJournal(capacity=32)
    reg = ModelRegistry(tmp_path / "registry", journal=journal)
    before = DEFAULT_JOURNAL.seq
    v1 = reg.publish("det", ckpt_dir, source="isolated")
    recs = journal.tail(kinds=("registry_publish",))
    assert [(r.data["lineage"], r.data["version"]) for r in recs] == \
        [("det", v1)]
    assert recs[0].data["source"] == "isolated"
    # nothing leaked into the shared ring
    assert DEFAULT_JOURNAL.seq == before


def test_store_publish_gates_bad_checkpoints(tmp_path, ckpt_dir):
    reg = ModelRegistry(tmp_path / "registry")
    # feature-layout drift is rejected at PUBLISH, not discovered at apply
    meta = json.loads((ckpt_dir / "model_config.json").read_text())
    meta["features"]["node"] = 999
    (ckpt_dir / "model_config.json").write_text(json.dumps(meta))
    with pytest.raises(ValueError, match="feature layout changed"):
        reg.publish("det", ckpt_dir)
    assert reg.versions("det") == []  # nothing half-published
    # promoting a version that does not exist is refused
    with pytest.raises(FileNotFoundError, match="no v7"):
        reg.promote("det", 7)


def test_store_concurrent_publish_yields_distinct_versions(tmp_path, ckpt_dir):
    reg = ModelRegistry(tmp_path / "registry")
    versions, errors = [], []

    def worker():
        try:
            versions.append(reg.publish("det", ckpt_dir))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors
    assert sorted(versions) == [1, 2, 3, 4, 5, 6]
    assert reg.versions("det") == [1, 2, 3, 4, 5, 6]
    for v in versions:
        assert (reg.version_dir("det", v) / "model_config.json").exists()


# -- guardrails: disagreement math + verdicts ---------------------------------

def test_shadow_stats_disagreement_and_drift_math():
    cfg = RegistryConfig(shadow_min_windows=2, canary_windows=2)
    stats = make_stats(cfg, threshold=0.5)
    mask = np.array([True, True, True, True, False])
    live = np.array([0.9, 0.1, 0.6, 0.4, 0.99])
    # two of four real nodes flip across 0.5; padded slot ignored
    shad = np.array([0.8, 0.2, 0.4, 0.6, 0.01])
    stats.observe(live, shad, mask)
    assert stats.disagreement_rate == pytest.approx(0.5)
    # (0.1 + 0.1 + 0.2 + 0.2) / 4 real nodes
    assert stats.score_drift == pytest.approx(0.15)
    stats.observe(live, live, mask)  # identical → no flips, no drift
    assert stats.disagreement_rate == pytest.approx(0.25)
    snap = stats.snapshot()
    assert snap["windows"] == 2 and snap["nodes"] == 8
    assert snap["recent_window_rates"] == [0.5, 0.0]


def test_guardrail_verdicts_wait_promote_veto():
    cfg = RegistryConfig(shadow_min_windows=3, canary_windows=2,
                         max_disagreement_rate=0.1, max_score_drift=0.05,
                         canary_max_disagreement=0.2)
    mask = np.ones(10, bool)
    agree = np.full(10, 0.9)

    stats = make_stats(cfg)
    verdict, reason = evaluate(stats, cfg)
    assert verdict == WAIT and "0/3" in reason
    for _ in range(3):
        stats.observe(agree, agree, mask)
    verdict, reason = evaluate(stats, cfg)
    assert verdict == PROMOTE

    # aggregate disagreement veto
    stats = make_stats(cfg)
    flipped = np.full(10, 0.1)
    for _ in range(3):
        stats.observe(agree, flipped, mask)
    verdict, reason = evaluate(stats, cfg)
    assert verdict == VETO and "disagreement" in reason

    # drift veto: same decisions, distribution walked 0.3 toward the cut
    stats = make_stats(cfg)
    drifted = np.full(10, 0.6)
    for _ in range(3):
        stats.observe(agree, drifted, mask)
    verdict, reason = evaluate(stats, cfg)
    assert verdict == VETO and "drift" in reason

    # canary veto: clean on average, one recent window diverges
    cfg2 = RegistryConfig(shadow_min_windows=3, canary_windows=2,
                          max_disagreement_rate=0.2, max_score_drift=1.0,
                          canary_max_disagreement=0.25)
    stats = make_stats(cfg2)
    half_flip = np.concatenate([np.full(5, 0.1), np.full(5, 0.9)])
    for _ in range(5):
        stats.observe(agree, agree, mask)
    stats.observe(agree, half_flip, mask)   # lands in the canary tail
    verdict, reason = evaluate(stats, cfg2)
    assert verdict == VETO and "canary" in reason


# -- the in-process swap: deterministic, atomic, stamped ----------------------

def _fake_swap_service(cfg, registry):
    """A service whose device program reads the LIVE param pointer exactly
    like the real _score_fn does (captured once per batch under the swap
    lock) — covers swap atomicity, version stamping, and rollback without
    compiling anything."""
    from conftest import make_service_shell

    svc, registry = make_service_shell(cfg, registry=registry)
    svc._params = _leaf_params(0.25)
    svc._live_version = 1

    def score(batch):
        with svc._swap_lock:
            params = svc._params
            version = svc._live_version
            shadow = svc._shadow
        value = float(np.asarray(params["dense"]["w"]).ravel()[0])
        probs = np.full(batch["node_mask"].shape, value, np.float64)
        if shadow is not None and svc._manager is not None:
            s_value = float(
                np.asarray(shadow[0]["dense"]["w"]).ravel()[0])
            s_probs = np.full(batch["node_mask"].shape, s_value, np.float64)
            mask = np.asarray(batch["node_mask"]).astype(bool)
            for j in range(probs.shape[0]):
                if mask[j].any():
                    svc._manager.observe_shadow(
                        probs[j], s_probs[j], mask[j], shadow[1])
        return probs, version

    svc._batcher = MicroBatcher(score_fn=score, cfg=cfg, registry=registry,
                                on_scored=svc._on_scored,
                                on_failed=svc._on_failed,
                                journal=svc._journal)
    svc._admission_open = True
    for b in cfg.buckets:
        svc._batcher.mark_warm(b)
    svc._batcher.start()
    return svc


def _feed_trace(svc, sid, seed=3, duration=60.0):
    from nerrf_tpu.data.synth import SimConfig, simulate_trace

    tr = simulate_trace(SimConfig(duration_sec=duration, attack=True,
                                  attack_start_sec=duration / 3,
                                  num_target_files=4, benign_rate_hz=6.0,
                                  seed=seed))
    ev = tr.events
    svc.join(sid)
    for i in range(0, len(ev), 200):
        block = type(ev)(**{f.name: getattr(ev, f.name)[i:i + 200]
                            for f in dataclasses.fields(ev)})
        svc.feed(sid, block, tr.strings)
    return svc.leave(sid, timeout=30.0)


def test_swap_is_deterministic_and_stamps_versions(tmp_path, ckpt_dir):
    """Every window scored before the swap carries v1 scores+stamp, every
    window after carries v2 — and rollback restores v1 exactly."""
    cfg = ServeConfig(buckets=(BUCKET,), batch_size=4, batch_close_sec=0.02,
                      window_sec=10.0, stride_sec=5.0)
    reg = MetricsRegistry(namespace="test")
    svc = _fake_swap_service(cfg, reg)
    try:
        det1 = _feed_trace(svc, "before")
        assert det1.detector == "serve[max]@v1"
        assert set(det1.file_scores.values()) == {0.25}

        svc.swap_params(_leaf_params(0.75), version=2)
        det2 = _feed_trace(svc, "after")
        assert det2.detector == "serve[max]@v2"
        assert set(det2.file_scores.values()) == {0.75}
        # same trace, same windows — only the model changed
        assert det1.file_scores.keys() == det2.file_scores.keys()

        # alerts carry the stamp too (0.75 >= default 0.5 cut)
        alerts = svc.sink.drain()
        assert alerts and all(a.model_version == 2 for a in alerts)

        svc.swap_params(_leaf_params(0.25), version=1)  # rollback repoint
        det3 = _feed_trace(svc, "rolled-back")
        assert det3.detector == "serve[max]@v1"
        assert det3.file_scores == det1.file_scores
        assert det3.file_window_scores == det1.file_window_scores
    finally:
        svc.stop(drain=False)


def test_swap_rejects_incompatible_pytrees():
    cfg = ServeConfig(buckets=(BUCKET,), batch_size=4)
    svc = _fake_swap_service(cfg, MetricsRegistry(namespace="test"))
    try:
        with pytest.raises(ValueError, match="tree structure"):
            svc.swap_params({"other": np.zeros(3)}, version=2)
        with pytest.raises(ValueError, match="compiled"):
            svc.swap_params({"dense": {"w": np.zeros((3, 3), np.float32)}},
                            version=2)
        # the failed swaps changed nothing
        assert svc.live_version == 1
    finally:
        svc.stop(drain=False)


def test_swap_threshold_travels_and_rollback_restores_boot_cut():
    """A calibrated version moves the operating point with the weights; a
    swap to an UNCALIBRATED version restores the boot-time cut instead of
    leaking the outgoing version's calibration."""
    cfg = ServeConfig(buckets=(BUCKET,), batch_size=4)
    svc = _fake_swap_service(cfg, MetricsRegistry(namespace="test"))
    try:
        assert svc.cfg.threshold is None  # the boot operating point
        svc.swap_params(_leaf_params(0.5), version=2, threshold=0.9)
        assert svc.cfg.threshold == 0.9
        svc.swap_params(_leaf_params(0.25), version=1)  # uncalibrated v1
        assert svc.cfg.threshold is None  # boot cut restored, not 0.9
    finally:
        svc.stop(drain=False)


# -- manager: poll → shadow → auto-promote / veto → rollback ------------------

def _manager_setup(tmp_path, svc, reg, **cfg_kw):
    from nerrf_tpu.train.checkpoint import save_checkpoint

    store = ModelRegistry(tmp_path / "registry")
    for i, value in enumerate((0.25, 0.75), start=1):
        ck = tmp_path / f"src{i}"
        save_checkpoint(ck, _leaf_params(value), JointConfig().small)
        store.publish("det", ck)
    store.promote("det", 1)
    kw = dict(poll_sec=60.0, shadow_min_windows=3, canary_windows=2)
    kw.update(cfg_kw)
    mgr = ModelManager(store, "det", cfg=RegistryConfig(**kw), registry=reg)
    mgr._version = 1
    # bypass model-architecture comparison (the fake service has no model)
    mgr.attach(svc)
    return store, mgr


def test_manager_follows_promote_and_rollback_pointer(tmp_path):
    cfg = ServeConfig(buckets=(BUCKET,), batch_size=4, batch_close_sec=0.02,
                      window_sec=10.0, stride_sec=5.0)
    reg = MetricsRegistry(namespace="test")
    svc = _fake_swap_service(cfg, reg)
    try:
        store, mgr = _manager_setup(tmp_path, svc, reg, auto_promote=False)
        assert reg.value("model_info",
                         labels={"lineage": "det", "version": "v1"}) == 1.0
        # v2 published but not promoted → staged as shadow, live unchanged
        out = mgr.poll()
        assert out["action"] == "shadow_start" and svc.live_version == 1
        # manual promote (the `nerrf models promote` path) → hot-swap
        store.promote("det", 2)
        out = mgr.poll()
        assert out["action"] == "swap" and out["direction"] == "forward"
        assert svc.live_version == 2
        assert svc._shadow is None  # promoted candidate retired as shadow
        det = _feed_trace(svc, "v2")
        assert set(det.file_scores.values()) == {0.75}
        # `nerrf models rollback` → pointer back → swap back
        store.rollback("det")
        out = mgr.poll()
        assert out["direction"] == "rollback" and svc.live_version == 1
        det = _feed_trace(svc, "v1-again")
        assert set(det.file_scores.values()) == {0.25}
        assert reg.value("model_info",
                         labels={"lineage": "det", "version": "v2"}) == 0.0
        assert reg.value("model_info",
                         labels={"lineage": "det", "version": "v1"}) == 1.0
        assert reg.value("registry_swaps_total",
                         labels={"lineage": "det",
                                 "direction": "rollback"}) == 1.0
        # the rolled-back-from version must never be re-staged (and so can
        # never be silently re-promoted): not by this manager...
        assert mgr.poll()["action"] == "none"
        assert svc._shadow is None
        # ...and not by a freshly restarted one either (empty in-memory
        # veto set; the LIVE pointer's recorded predecessor is the floor)
        mgr2 = ModelManager(store, "det",
                            cfg=RegistryConfig(poll_sec=60.0),
                            registry=MetricsRegistry(namespace="test2"))
        mgr2._version = 1
        mgr2.attach(svc)
        assert mgr2.poll()["action"] == "none"
        assert svc._shadow is None
    finally:
        mgr.close()
        svc.stop(drain=False)


def test_boot_and_attach_serialize_with_the_poll_lock(tmp_path):
    """Regression (nerrflint lock-discipline): boot() and attach() used to
    write `_version` bare while the poll thread moves it under
    `_poll_lock`.  Both must now serialize against an in-flight poll — a
    held poll lock blocks them, and release lets them land the stamp."""
    from nerrf_tpu.train.checkpoint import save_checkpoint

    store = ModelRegistry(tmp_path / "registry")
    ck = tmp_path / "src"
    save_checkpoint(ck, _leaf_params(0.5), JointConfig().small)
    store.publish("det", ck)
    store.promote("det", 1)
    mgr = ModelManager(store, "det", cfg=RegistryConfig(poll_sec=60.0),
                       registry=MetricsRegistry(namespace="test"))
    mgr._poll_lock.acquire()
    try:
        t = threading.Thread(target=mgr.boot, daemon=True)
        t.start()
        t.join(timeout=0.5)
        assert t.is_alive(), "boot() must wait for the poll lock"
    finally:
        mgr._poll_lock.release()
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert mgr.live_version == 1


def test_manager_shadow_auto_promotes_agreeing_candidate(tmp_path):
    """A candidate that scores identically passes every guardrail: the
    manager promotes it in the REGISTRY (LIVE repoints) and swaps."""
    cfg = ServeConfig(buckets=(BUCKET,), batch_size=4, batch_close_sec=0.02,
                      window_sec=10.0, stride_sec=5.0)
    reg = MetricsRegistry(namespace="test")
    svc = _fake_swap_service(cfg, reg)
    try:
        from nerrf_tpu.train.checkpoint import save_checkpoint

        store = ModelRegistry(tmp_path / "registry")
        for i in (1, 2):  # v2 has IDENTICAL params → zero disagreement
            ck = tmp_path / f"src{i}"
            save_checkpoint(ck, _leaf_params(0.25), JointConfig().small)
            store.publish("det", ck)
        store.promote("det", 1)
        mgr = ModelManager(store, "det",
                           cfg=RegistryConfig(poll_sec=60.0,
                                              shadow_min_windows=3,
                                              canary_windows=2),
                           registry=reg)
        mgr._version = 1
        mgr.attach(svc)
        assert mgr.poll()["action"] == "shadow_start"
        _feed_trace(svc, "load")  # shadow observes every scored window
        assert reg.value("registry_shadow_windows_total",
                         labels={"lineage": "det"}) >= 3
        out = mgr.poll()
        assert out["action"] == "auto_promote"
        assert store.live_version("det") == 2  # promoted IN THE REGISTRY
        assert svc.live_version == 2
        assert reg.value("registry_promotions_total",
                         labels={"lineage": "det", "kind": "auto"}) == 1.0
    finally:
        mgr.close()
        svc.stop(drain=False)


def test_manager_vetoes_disagreeing_candidate_and_never_restages(tmp_path):
    cfg = ServeConfig(buckets=(BUCKET,), batch_size=4, batch_close_sec=0.02,
                      window_sec=10.0, stride_sec=5.0)
    reg = MetricsRegistry(namespace="test")
    svc = _fake_swap_service(cfg, reg)
    try:
        store, mgr = _manager_setup(tmp_path, svc, reg,
                                    max_disagreement_rate=0.02)
        assert mgr.poll()["action"] == "shadow_start"
        _feed_trace(svc, "load")  # 0.25 vs 0.75 across the 0.5 cut: flips
        out = mgr.poll()
        assert out["action"] == "veto" and out["vetoed"] == 2
        assert svc.live_version == 1          # live never changed
        assert store.live_version("det") == 1  # registry never changed
        assert svc._shadow is None             # candidate unstaged
        assert reg.value("registry_shadow_vetoes_total",
                         labels={"lineage": "det"}) == 1.0
        # the vetoed version is remembered, not re-staged forever
        assert mgr.poll()["action"] == "none"
    finally:
        mgr.close()
        svc.stop(drain=False)


# -- readiness payload --------------------------------------------------------

def test_readyz_payload_carries_model_version(tmp_path):
    import urllib.request

    from nerrf_tpu.observability import MetricsServer

    cfg = ServeConfig(buckets=(BUCKET,), batch_size=4)
    reg = MetricsRegistry(namespace="test")
    svc = _fake_swap_service(cfg, reg)
    try:
        ok, reason, extra = svc.ready()
        assert ok and extra["model_version"] == "v1"
        with MetricsServer(registry=reg, ready_check=svc.ready) as srv:
            body = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/readyz", timeout=5).read())
        assert body["status"] == "ready"
        assert body["model_version"] == "v1"
    finally:
        svc.stop(drain=False)


# -- CLI ----------------------------------------------------------------------

def test_cli_models_lifecycle_roundtrip(tmp_path, ckpt_dir, capsys):
    import nerrf_tpu.cli as cli

    regdir = str(tmp_path / "registry")
    assert cli.main(["models", "publish", "--registry", regdir,
                     "--lineage", "det", "--model-dir", str(ckpt_dir),
                     "--promote"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["published"] == 1 and out["live"]["version"] == 1
    assert cli.main(["models", "publish", "--registry", regdir,
                     "--lineage", "det", "--model-dir", str(ckpt_dir)]) == 0
    capsys.readouterr()
    assert cli.main(["models", "promote", "--registry", regdir,
                     "--lineage", "det", "--version", "2"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["live"]["version"] == 2
    assert cli.main(["models", "rollback", "--registry", regdir,
                     "--lineage", "det"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["live"]["version"] == 1 and out["live"]["kind"] == "rollback"
    assert cli.main(["models", "status", "--registry", regdir,
                     "--lineage", "det"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert [v["version"] for v in out["versions"]] == [1, 2]
    assert cli.main(["models", "list", "--registry", regdir]) == 0
    out = json.loads(capsys.readouterr().out)
    assert "det" in out["lineages"]


def test_manager_prunes_dead_veto_entries(tmp_path):
    """Veto entries at/below the staging floor can never match again
    (the filter only considers v > floor): poll() drops them so a
    long-lived manager's veto set does not grow by one per rejected
    candidate forever."""
    from nerrf_tpu.train.checkpoint import save_checkpoint

    store = ModelRegistry(tmp_path / "registry")
    ck = tmp_path / "src"
    save_checkpoint(ck, _leaf_params(0.25), JointConfig().small)
    store.publish("det", ck)
    store.promote("det", 1)
    mgr = ModelManager(store, "det", cfg=RegistryConfig(poll_sec=60.0),
                       registry=MetricsRegistry(namespace="test"))
    try:
        mgr._version = 1
        mgr._vetoed.update({0, 1, 99})  # 0 and 1 are at/below the floor
        mgr.poll()
        assert mgr._vetoed == {99}
    finally:
        mgr.close()
