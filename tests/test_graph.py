import numpy as np

from nerrf_tpu.data import SimConfig, simulate_trace
from nerrf_tpu.graph import (
    EDGE_FEATURE_DIM,
    GraphBatch,
    GraphConfig,
    NODE_FEATURE_DIM,
    build_window_graph,
    trace_snapshots,
)
from nerrf_tpu.graph.builder import NODE_TYPE_FILE, NODE_TYPE_PROCESS
from nerrf_tpu.schema.events import EventArrays, StringTable


def _small_trace():
    return simulate_trace(
        SimConfig(duration_sec=120.0, attack=True, attack_start_sec=40.0,
                  num_target_files=6, min_file_bytes=64 * 1024,
                  max_file_bytes=128 * 1024, chunk_bytes=32 * 1024,
                  benign_rate_hz=25.0, seed=5)
    )


def test_window_graph_shapes_and_masks():
    tr = _small_trace()
    cfg = GraphConfig(window_sec=45.0, max_nodes=64, max_edges=128)
    t0 = int(tr.events.ts_ns.min())
    g, stats = build_window_graph(
        tr.events, tr.strings, t0, t0 + 45_000_000_000, cfg, labels=tr.labels
    )
    assert g.node_feat.shape == (64, NODE_FEATURE_DIM)
    assert g.edge_feat.shape == (128, EDGE_FEATURE_DIM)
    assert g.num_nodes == stats.num_nodes > 0
    assert g.num_edges == stats.num_edges > 0
    # masked-out slots are zero
    assert g.node_feat[~g.node_mask].sum() == 0
    # valid edges reference valid nodes
    e = g.edge_mask
    assert g.node_mask[g.edge_src[e]].all() and g.node_mask[g.edge_dst[e]].all()
    # edges sorted by destination for segment reduction
    assert np.all(np.diff(g.edge_dst[e]) >= 0)
    # padded edge slots point at the last node slot (segment-sum safe)
    if (~e).any():
        assert (g.edge_dst[~e] == cfg.max_nodes - 1).all()


def test_node_types_and_keys():
    tr = _small_trace()
    cfg = GraphConfig(max_nodes=128, max_edges=256)
    ts = tr.events.ts_ns
    g, _ = build_window_graph(tr.events, tr.strings, int(ts.min()), int(ts.max()) + 1,
                              cfg, labels=tr.labels)
    types = g.node_type[g.node_mask]
    assert (types == NODE_TYPE_PROCESS).sum() >= 5  # the benign services + attacker
    assert (types == NODE_TYPE_FILE).sum() > 10
    # process keys are pids (small), file keys are inodes (>=1000)
    keys = g.node_key[g.node_mask]
    assert keys[types == NODE_TYPE_PROCESS].max() < 10000
    assert keys[types == NODE_TYPE_FILE].min() >= 1000
    # is_process feature flag agrees with node_type
    assert np.array_equal(
        g.node_feat[g.node_mask, 21] > 0.5, types == NODE_TYPE_PROCESS
    )


def test_attack_window_labels_and_features():
    tr = _small_trace()
    gt = tr.ground_truth
    cfg = GraphConfig(max_nodes=128, max_edges=256)
    g, _ = build_window_graph(tr.events, tr.strings, gt.start_ns, gt.end_ns + 1,
                              cfg, labels=tr.labels)
    # attacker edges labelled, and some suspicious-extension involvement seen
    assert g.edge_label[g.edge_mask].max() == 1.0
    assert g.edge_feat[g.edge_mask, 11].max() == 1.0
    # renamed target files: rename counter set on some file node
    files = g.node_mask & (g.node_type == NODE_TYPE_FILE)
    assert g.node_feat[files, 10].max() > 0
    # node labels mark the attacking process
    procs = g.node_mask & (g.node_type == NODE_TYPE_PROCESS)
    assert g.node_label[procs].max() == 1.0


def test_benign_window_unlabelled():
    tr = _small_trace()
    t0 = int(tr.events.ts_ns.min())
    g, _ = build_window_graph(
        tr.events, tr.strings, t0, t0 + 30_000_000_000,
        GraphConfig(max_nodes=128, max_edges=256), labels=tr.labels
    )
    assert g.edge_label.max() == 0.0 and g.node_label.max() == 0.0


def test_empty_window():
    tr = _small_trace()
    g, stats = build_window_graph(
        tr.events, tr.strings, 0, 1000, GraphConfig(), labels=tr.labels
    )
    assert stats.num_events == g.num_nodes == g.num_edges == 0


def test_capacity_overflow_accounting():
    tr = _small_trace()
    ts = tr.events.ts_ns
    cfg = GraphConfig(max_nodes=8, max_edges=4)
    g, stats = build_window_graph(tr.events, tr.strings, int(ts.min()), int(ts.max()) + 1,
                                  cfg, labels=tr.labels)
    assert g.num_nodes <= 8 and g.num_edges <= 4
    assert stats.dropped_nodes > 0
    assert stats.dropped_events > 0
    # still structurally sound
    e = g.edge_mask
    assert g.node_mask[g.edge_src[e]].all() and g.node_mask[g.edge_dst[e]].all()


def test_trace_snapshots_cover_trace_and_stack():
    tr = _small_trace()
    cfg = GraphConfig(window_sec=45.0, stride_sec=20.0, max_nodes=64, max_edges=128)
    snaps = trace_snapshots(tr, cfg, labels=tr.labels)
    assert len(snaps) >= 5
    # at least one window sees the attack
    assert max(g.edge_label.max() for g, _ in snaps) == 1.0
    stacked = GraphBatch.stack([g for g, _ in snaps])
    assert stacked["node_feat"].shape == (len(snaps), 64, NODE_FEATURE_DIM)
    assert stacked["edge_mask"].shape == (len(snaps), 128)


def test_determinism():
    tr = _small_trace()
    ts = tr.events.ts_ns
    cfg = GraphConfig(max_nodes=64, max_edges=128)
    g1, _ = build_window_graph(tr.events, tr.strings, int(ts.min()), int(ts.max()), cfg, labels=tr.labels)
    g2, _ = build_window_graph(tr.events, tr.strings, int(ts.min()), int(ts.max()), cfg, labels=tr.labels)
    for k, v in g1.arrays().items():
        assert np.array_equal(v, g2.arrays()[k]), k


def test_rename_is_node_property_not_new_node():
    """Inode dedup: rename keeps one file node (spec: 'Node merging (inode
    deduplication)', architecture.mdx:39)."""
    st = StringTable()
    recs = [
        {"ts_ns": 1_000_000_000, "pid": 1, "syscall": "write", "path": "/d/a.dat",
         "inode": 500, "bytes": 10},
        {"ts_ns": 2_000_000_000, "pid": 1, "syscall": "rename", "path": "/d/a.dat",
         "new_path": "/d/a.lockbit3", "inode": 500},
        {"ts_ns": 3_000_000_000, "pid": 1, "syscall": "write", "path": "/d/a.lockbit3",
         "inode": 500, "bytes": 10},
    ]
    ev = EventArrays.from_records(recs, st)
    g, _ = build_window_graph(ev, st, 0, 4_000_000_000, GraphConfig(max_nodes=8, max_edges=8))
    assert g.num_nodes == 2  # one process + one file
    files = g.node_mask & (g.node_type == NODE_TYPE_FILE)
    assert files.sum() == 1
    # the file carries both the rename count and the suspicious-ext flag
    assert g.node_feat[files, 10] > 0
    assert g.node_feat[files, 4].max() == 1.0


def test_measure_window_matches_builder_exactly():
    """measure_window's vectorized count must equal what build_window_graph
    actually constructs when nothing is dropped (same node/edge universe)."""
    from nerrf_tpu.data.synth import SimConfig, simulate_trace
    from nerrf_tpu.graph.builder import (
        GraphConfig, build_window_graph, measure_window,
    )

    tr = simulate_trace(SimConfig(duration_sec=60.0, benign_rate_hz=30.0,
                                  num_target_files=10, attack=True,
                                  attack_start_sec=20.0, seed=11))
    ev = tr.events
    lo = int(ev.ts_ns[ev.valid].min())
    hi = lo + 45 * 10**9
    need_n, need_e = measure_window(ev, lo, hi)
    g, stats = build_window_graph(
        ev, tr.strings, lo, hi,
        GraphConfig(max_nodes=4 * need_n, max_edges=4 * need_e))
    assert stats.dropped_nodes == 0 and stats.dropped_events == 0
    assert stats.num_nodes == need_n
    assert stats.num_edges == need_e


def test_graphconfig_fit_gives_zero_drops_at_high_density():
    """The auto-sizing policy: 25k-event windows (real-eBPF density) drop a
    third of their events at training defaults; fit() must eliminate that."""
    from nerrf_tpu.data.synth import SimConfig, simulate_trace
    from nerrf_tpu.graph.builder import GraphConfig, build_window_graph

    tr = simulate_trace(SimConfig(duration_sec=50.0, benign_rate_hz=400.0,
                                  num_target_files=30, attack=True,
                                  attack_start_sec=10.0, seed=12))
    ev = tr.events
    lo = int(ev.ts_ns[ev.valid].min())
    hi = lo + 45 * 10**9
    base = GraphConfig()
    _, base_stats = build_window_graph(ev, tr.strings, lo, hi, base)
    assert base_stats.dropped_events > 0  # defaults overflow at this density

    fit = base.fit(ev, lo, hi)
    assert fit.max_nodes >= base.max_nodes and (fit.max_nodes & (fit.max_nodes - 1)) == 0
    _, stats = build_window_graph(ev, tr.strings, lo, hi, fit)
    assert stats.dropped_nodes == 0 and stats.dropped_events == 0


def test_model_detect_auto_capacity_covers_dense_traces():
    """The online detector must see all evidence at live-capture density:
    auto_capacity bumps the window capacities so nothing drops."""
    import dataclasses as dc

    from nerrf_tpu.data.synth import SimConfig, simulate_trace
    from nerrf_tpu.graph.builder import GraphConfig, measure_window, snapshot_windows
    from nerrf_tpu.models import JointConfig, NerrfNet
    from nerrf_tpu.train.data import DatasetConfig
    from nerrf_tpu.pipeline import model_detect
    import jax

    tr = simulate_trace(SimConfig(duration_sec=50.0, benign_rate_hz=300.0,
                                  num_target_files=20, attack=True,
                                  attack_start_sec=10.0, seed=13))
    cfg = JointConfig(gnn=dc.replace(JointConfig().gnn, hidden=16, num_layers=2),
                      lstm=dc.replace(JointConfig().lstm, hidden=16, num_layers=1))
    model = NerrfNet(cfg)
    ds = DatasetConfig(graph=GraphConfig(max_nodes=64, max_edges=128),
                       seq_len=20, max_seqs=16)
    ev = tr.events
    ts = ev.ts_ns[ev.valid]
    dense_needs = max(measure_window(ev, lo, hi)[0] for lo, hi in
                      snapshot_windows(int(ts.min()), int(ts.max()), ds.graph))
    assert dense_needs > 64  # the configured capacity would drop nodes

    # init params at the small shape; detection at fitted shape must work
    from nerrf_tpu.train.data import windows_of_trace
    sample = windows_of_trace(tr, ds)[0]
    import jax.numpy as jnp
    from nerrf_tpu.train.loop import model_inputs
    one = {k: jnp.asarray(v) for k, v in sample.items()}
    params = model.init(jax.random.PRNGKey(0), *model_inputs(one))["params"]

    det = model_detect(tr, params, model, ds_cfg=ds, batch_size=2,
                       auto_capacity=True)
    # every encrypted file is scoreable (present in the detection universe)
    enc = [p for p in det.file_scores if p.endswith(".lockbit3")]
    assert len(enc) >= 15, f"only {len(enc)} ransom files visible"


def test_window_score_aggregation_rules():
    """`robust` must ignore a single-window outlier but keep consistently
    hot files at full score; both rules agree on single-window files."""
    from nerrf_tpu.pipeline import DetectionResult, aggregate_window_scores

    assert aggregate_window_scores([0.9, 0.1, 0.1], "max") == 0.9
    assert aggregate_window_scores([0.9, 0.1, 0.1], "robust") == 0.1
    assert aggregate_window_scores([0.9, 0.8, 0.7], "robust") == 0.8
    assert aggregate_window_scores([0.6], "robust") == 0.6
    assert aggregate_window_scores([], "max") == 0.0

    det = DetectionResult(
        file_scores={"/a": 0.9, "/b": 0.95},
        proc_scores={}, file_bytes={}, detector="model[max]",
        file_window_scores={"/a": [0.9, 0.05], "/b": [0.95, 0.9, 0.85]})
    r = det.rescored("robust")
    assert r.file_scores["/a"] == 0.05      # outlier window neutralized
    assert r.file_scores["/b"] == 0.9       # persistent threat kept
    assert r.detector.endswith("[robust]")
    # heuristic results (no window scores) pass through unchanged
    h = DetectionResult({"/x": 1.0}, {}, {})
    assert h.rescored("robust") is h


def test_model_detect_gates_undo_candidacy_on_mutation():
    """Files nothing ever wrote/renamed/unlinked (recon reads like
    /etc/passwd) must not appear in file_scores — they have no pre-attack
    state to restore, so flagging them is a false-positive undo by
    definition.  Their window scores stay visible for diagnostics."""
    import dataclasses as dc

    import jax
    import jax.numpy as jnp

    from nerrf_tpu.data.synth import SimConfig, simulate_trace
    from nerrf_tpu.graph.builder import GraphConfig
    from nerrf_tpu.models import JointConfig, NerrfNet
    from nerrf_tpu.pipeline import model_detect
    from nerrf_tpu.schema.events import MUTATING_SYSCALLS
    from nerrf_tpu.train.data import DatasetConfig, windows_of_trace
    from nerrf_tpu.train.loop import model_inputs

    tr = simulate_trace(SimConfig(duration_sec=60.0, benign_rate_hz=20.0,
                                  num_target_files=8, attack=True,
                                  attack_start_sec=15.0, seed=21))
    cfg = JointConfig(gnn=dc.replace(JointConfig().gnn, hidden=16, num_layers=2),
                      lstm=dc.replace(JointConfig().lstm, hidden=16, num_layers=1))
    model = NerrfNet(cfg)
    ds = DatasetConfig(graph=GraphConfig(max_nodes=256, max_edges=512),
                       seq_len=20, max_seqs=16)
    one = {k: jnp.asarray(v) for k, v in windows_of_trace(tr, ds)[0].items()}
    params = model.init(jax.random.PRNGKey(0), *model_inputs(one))["params"]

    det = model_detect(tr, params, model, ds_cfg=ds, batch_size=2)
    from nerrf_tpu.pipeline import _inode_to_path

    ev, st = tr.events, tr.strings
    ino_path = _inode_to_path(tr)
    mutated = set()
    for i in range(len(ev)):
        if ev.valid[i] and int(ev.syscall[i]) in MUTATING_SYSCALLS:
            if ev.inode[i] != 0:
                mutated.add(ino_path[int(ev.inode[i])])
            for f in (ev.path_id[i], ev.new_path_id[i]):
                p = st.lookup(int(f))
                if p:
                    mutated.add(p)
    # the trace's recon phase reads /etc/passwd etc.; they must be scored
    # in windows but absent from undo candidacy
    assert det.file_window_scores, "window scores must be retained"
    non_mutated_scored = [p for p in det.file_window_scores
                          if p not in mutated]
    assert non_mutated_scored, "scenario should include read-only files"
    for p in det.file_scores:
        assert p in mutated, f"non-mutated file {p} nominated for undo"
    # rescoring must not resurrect filtered files
    assert set(det.rescored("robust").file_scores) == set(det.file_scores)
