"""End-to-end SLO accounting for the serve path.

Every scored window already carries its event time through the pipeline
(`WindowRequest.t_admit` → assembled `t_packed` → scorer pickup `t_device`
→ demux).  `SLOTracker.observe` turns those stamps into the operator-facing
SLO plane:

  * ``nerrf_slo_e2e_seconds{stream=...}`` — per-stream admit→demux latency
    histograms (the per-stream refinement of the un-labelled
    ``serve_window_latency_seconds``);
  * ``nerrf_slo_stage_seconds{stage=...}`` — where inside the budget the
    time went: ``queue`` (admit→batch close), ``pack`` (close→scorer
    pickup), ``device`` (the program + fetch), ``demux`` (fan-back);
  * ``nerrf_slo_budget_burn_ratio{stream,stage}`` — TRAILING mean stage
    cost as a fraction of the window deadline, so a dashboard shows WHICH
    stage is eating the budget before p99 breaches (trailing, not
    all-time: a regression must move the gauge within one trailing
    window, not fight a day of healthy history);
  * ``nerrf_slo_breaches_total{stream}`` — windows that blew the deadline;
  * exemplars — the slowest window in each stream's trailing set, by trace
    ID, so a slow alert links back to its exact batch's span tree and
    journal records (``slo_breach`` journal records carry the same ID).
    Trailing by construction: an exemplar ages out with its window, so it
    always points at evidence the span/journal rings can still hold.

Cardinality is bounded: the tracker keeps at most ``max_streams`` streams
(LRU on observation).  A resident serve pod's reconnect sessions mint new
stream IDs forever (``name#<n>``); when a stream ages out, its in-memory
state AND its per-stream registry series are retired
(`MetricsRegistry.remove_series`), so neither host memory nor the
/metrics exposition grows with session churn.

Percentiles: the registry histograms are fixed-bucket (Prometheus-side
quantiles); `snapshot()` additionally reports *exact* trailing p50/p99 per
stream from the in-memory window, which is what the serve bench's artifact
and the flight recorder's p99 trigger consume.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

# e2e ladder: sub-deadline through multi-second stalls (the serve path's
# LATENCY_BUCKETS, extended down for sub-close-deadline fast paths)
SLO_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0)
STAGES = ("queue", "pack", "device", "demux")


def percentile(sorted_vals, p: float) -> Optional[float]:
    """Nearest-rank percentile over an ascending list (None when empty).
    The ONE definition both the SLO plane and the flight recorder's p99
    trigger use — they must never disagree about the same data."""
    if not sorted_vals:
        return None
    return sorted_vals[min(int(p * len(sorted_vals)), len(sorted_vals) - 1)]


class _StreamWindow:
    """One stream's trailing accounting: (e2e, trace_id, stages) entries
    plus running trailing stage sums (evictions subtract, so the burn
    gauge is O(1) per observation)."""

    __slots__ = ("window", "stage_sums", "count", "breaches")

    def __init__(self) -> None:
        self.window: deque = deque()  # (e2e, trace_id, {stage: sec})
        self.stage_sums: Dict[str, float] = {s: 0.0 for s in STAGES}
        self.count = 0
        self.breaches = 0

    def worst(self):
        if not self.window:
            return None, None
        e2e, trace_id, _ = max(self.window, key=lambda t: t[0])
        return trace_id, e2e


class SLOTracker:
    """Per-stream trailing SLO accounting + registry export."""

    def __init__(self, deadline_sec: float, registry=None, journal=None,
                 trailing: int = 256, max_streams: int = 256) -> None:
        if registry is None:
            from nerrf_tpu.observability import DEFAULT_REGISTRY

            registry = DEFAULT_REGISTRY
        if journal is None:
            from nerrf_tpu.flight.journal import DEFAULT_JOURNAL

            journal = DEFAULT_JOURNAL
        self.deadline_sec = max(float(deadline_sec), 1e-9)
        self._reg = registry
        self._journal = journal
        self._trailing = max(trailing, 1)
        self._max_streams = max(max_streams, 1)
        self._lock = threading.Lock()
        # insertion order IS the LRU order: observe() re-inserts its
        # stream at the end, so the first key is the coldest
        self._streams: Dict[str, _StreamWindow] = {}

    def observe(self, stream: str, trace_id: Optional[str],
                window_id: Optional[int], stages: Dict[str, float],
                e2e_sec: float) -> None:
        """One scored window's stamps.  ``stages`` maps stage name →
        seconds (missing/negative stages are clamped to 0 — clock reads
        from different threads can jitter a µs below zero)."""
        e2e_sec = max(float(e2e_sec), 0.0)
        clamped = {s: max(float(stages.get(s, 0.0)), 0.0) for s in STAGES}
        breach = e2e_sec > self.deadline_sec
        with self._lock:
            w = self._streams.pop(stream, None) or _StreamWindow()
            self._streams[stream] = w  # re-insert: newest at the end
            w.window.append((e2e_sec, trace_id, clamped))
            for s in STAGES:
                w.stage_sums[s] += clamped[s]
            if len(w.window) > self._trailing:
                _, _, old = w.window.popleft()
                for s in STAGES:
                    w.stage_sums[s] = max(w.stage_sums[s] - old[s], 0.0)
            w.count += 1
            if breach:
                w.breaches += 1
            n = len(w.window)
            burns = {s: (w.stage_sums[s] / n) / self.deadline_sec
                     for s in STAGES}
            evicted = None
            if len(self._streams) > self._max_streams:
                evicted = next(iter(self._streams))
                del self._streams[evicted]
        if evicted is not None:
            self._retire_series(evicted)
        self._reg.histogram_observe(
            "slo_e2e_seconds", e2e_sec, buckets=SLO_BUCKETS,
            labels={"stream": stream},
            help="per-stream end-to-end window latency, admit through demux")
        for stage in STAGES:
            self._reg.histogram_observe(
                "slo_stage_seconds", clamped[stage],
                buckets=SLO_BUCKETS, labels={"stage": stage},
                help="per-stage share of the window's end-to-end latency")
            self._reg.gauge_set(
                "slo_budget_burn_ratio", burns[stage],
                labels={"stream": stream, "stage": stage},
                help="trailing mean stage latency as a fraction of the "
                     "per-window deadline budget")
        if breach:
            self._reg.counter_inc(
                "slo_breaches_total", labels={"stream": stream},
                help="windows whose end-to-end latency blew the deadline")
            self._journal.record(
                "slo_breach", stream=stream, window_id=window_id,
                trace_id=trace_id, e2e_sec=round(e2e_sec, 6),
                deadline_sec=self.deadline_sec,
                stages={k: round(clamped[k], 6) for k in STAGES})

    def _retire_series(self, stream: str) -> None:
        """Drop an aged-out stream's per-stream registry series — the
        cardinality bound for long-lived pods with reconnect-session IDs."""
        self._reg.remove_series("slo_e2e_seconds", {"stream": stream})
        self._reg.remove_series("slo_breaches_total", {"stream": stream})
        for stage in STAGES:
            self._reg.remove_series(
                "slo_budget_burn_ratio", {"stream": stream, "stage": stage})

    # -- reading -------------------------------------------------------------

    def exemplar(self, stream: str):
        """(trace_id, e2e_seconds) of the worst window in ``stream``'s
        TRAILING set — ages out with its window, so the ID always joins to
        evidence the span/journal rings can still hold."""
        with self._lock:
            w = self._streams.get(stream)
            return (None, None) if w is None else w.worst()

    def trailing_p99(self, stream: str) -> Optional[float]:
        with self._lock:
            w = self._streams.get(stream)
            vals = sorted(e for e, _, _ in w.window) if w is not None else []
        return percentile(vals, 0.99)

    def snapshot(self) -> dict:
        """Per-stream exact trailing stats — the bench artifact's ``slo``
        block and the flight bundle's manifest both embed this."""
        with self._lock:
            streams = {
                s: (sorted(e for e, _, _ in w.window), w.count, w.breaches,
                    *w.worst(), dict(w.stage_sums), len(w.window))
                for s, w in self._streams.items()}
        out = {}
        for s, (vals, count, breaches, worst_trace, worst_e2e,
                sums, n) in sorted(streams.items()):
            out[s] = {
                "count": count,
                "breaches": breaches,
                "p50_ms": _ms(percentile(vals, 0.50)),
                "p99_ms": _ms(percentile(vals, 0.99)),
                "max_ms": _ms(vals[-1] if vals else None),
                "exemplar_trace_id": worst_trace,
                "exemplar_ms": _ms(worst_e2e if worst_trace else None),
                "budget_burn": {k: round((v / n) / self.deadline_sec, 4)
                                for k, v in sorted(sums.items())} if n
                               else {},
            }
        return {"deadline_sec": self.deadline_sec, "per_stream": out}


def _ms(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v * 1e3, 1)
