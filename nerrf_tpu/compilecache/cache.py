"""Persistent, content-addressed compilation cache for AOT executables.

BENCH_r04 measured 130 s to compile ``train_step`` and ~57 s for
``stream_step``; the serve path gates readiness on compiling the whole
bucket ladder at boot.  Compile cost is the central systems problem for
this workload class (TpuGraphs, arXiv:2308.13490), and the fix is the
graph-reuse discipline PyGraph applies to CUDA graphs (arXiv:2503.19779):
key every lowered program by WHAT it computes, persist the compiled
artifact, and never compile the same program twice on the same platform.

`CompileCache` wraps ``jit_fn.lower(*args).compile()`` +
``jax.experimental.serialize_executable``:

  * **content-addressed** — an entry's directory name IS the canonical
    fingerprint of (program name, argument avals + pytree layout, caller
    ``extra`` material such as model architecture and donation spec,
    jax/jaxlib/libtpu versions, backend platform + device kind + device
    count, host ISA fingerprint on CPU).  Any drift along any axis is a
    different fingerprint, so a stale executable can never be reused — the
    worst a corrupt cache can do is cost one fresh compile;
  * **atomic** — entries are written to a tmp directory and renamed into
    place (rename(2) is atomic on one filesystem), so concurrent
    processes sharing a cache volume see whole entries or nothing;
  * **bounded** — ``prune()`` applies an LRU disk bound (last-use is an
    ``os.utime`` stamp on the entry dir, refreshed on every hit);
  * **fail-open** — every failure mode (no backend support, version
    skew, truncated payload, unpicklable tree, read-only volume) falls
    back to the live jit path, journals the cause, and never raises into
    the caller.  A cache can make boot fast; it must never break serving.

Metrics: ``nerrf_compile_cache_{hits,misses,bytes}_total`` and
``nerrf_compile_seconds{program,source=cache|fresh}``.  Journal records of
kind ``compile`` carry (program, fingerprint, source, seconds, reason) —
`nerrf doctor <bundle>` reconstructs compile provenance from them offline.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import shutil
import tempfile
import time
from pathlib import Path
from typing import Dict, List, Optional, Tuple

PAYLOAD = "executable.bin"
TREES = "trees.pkl"
META = "meta.json"

# compile-seconds histogram ladder: sub-second deserialize hits up to the
# measured 130 s flagship compile
COMPILE_SECONDS_BUCKETS = (0.05, 0.25, 1.0, 5.0, 15.0, 60.0, 180.0, 600.0)

# default disk bound for a cache root (override per instance / `nerrf
# cache prune --max-bytes`): big enough for every ladder bucket at serve
# shapes plus the train programs, small enough for a pod cache volume
DEFAULT_MAX_BYTES = 2 << 30


@dataclasses.dataclass(frozen=True)
class CompileInfo:
    """Provenance of one load_or_compile resolution."""

    program: str
    fingerprint: str
    source: str              # "cache" | "fresh" | "live"
    seconds: float           # deserialize (cache) or lower+compile (fresh)
    reason: Optional[str] = None   # miss/fallback cause, None on a hit


def aval_signature(args: tuple, kwargs: dict) -> dict:
    """Canonical (shape, dtype, treedef) description of a call signature —
    the cache key's view of the arguments.  Weak-typed scalars hash by
    their numpy dtype, which is what the lowered program sees.  Public:
    the deep static pass (analysis/programs/cachekey.py) fingerprints
    candidate programs through exactly this view, so its coverage proof
    and the runtime cache can never disagree about what a key sees."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))

    def leaf_sig(leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = getattr(leaf, "dtype", None)
        return [list(shape), str(dtype) if dtype is not None
                else type(leaf).__name__]

    return {"tree": str(treedef), "leaves": [leaf_sig(l) for l in leaves]}


def _host_isa_fingerprint() -> str:
    """Host ISA identity for CPU executables: XLA:CPU AOT artifacts are
    specialized to the compiling machine (SIGILL risk on a narrower host —
    see utils.enable_compilation_cache, which learned this live), so CPU
    cache keys carry the same machine|model|flags digest."""
    import platform

    flags = model = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if not flags and line.startswith(("flags", "Features")):
                    flags = " ".join(sorted(line.split(":", 1)[1].split()))
                if not model and line.startswith(("model name", "CPU part")):
                    model = line.split(":", 1)[1].strip()
                if flags and model:
                    break
    except OSError:
        pass
    return hashlib.sha256(
        f"{platform.machine()}|{model}|{flags}".encode()).hexdigest()[:12]


def environment_key() -> dict:
    """The environment axes that invalidate an executable: jax/jaxlib (and
    libtpu when present) versions, backend platform, device kind + count,
    and — on CPU, where the artifact is ISA-specific — the host ISA."""
    import jax
    import jaxlib

    dev = jax.devices()[0]
    key = {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "device_count": jax.device_count(),
    }
    try:  # pragma: no cover — only present on real TPU hosts
        import libtpu  # type: ignore

        key["libtpu"] = getattr(libtpu, "__version__", "unknown")
    except ImportError:
        pass
    if dev.platform == "cpu":
        key["host_isa"] = _host_isa_fingerprint()
    return key


def compute_fingerprint(program: str, avals: dict, extra: Optional[dict],
                        env: Optional[dict] = None) -> Tuple[str, dict]:
    """→ (fingerprint, key_material).  The material is stamped into the
    entry's meta.json so `nerrf cache ls|verify` can explain every entry."""
    material = {
        "program": program,
        "avals": avals,
        "extra": extra or {},
        "env": env if env is not None else environment_key(),
    }
    canon = json.dumps(material, sort_keys=True, separators=(",", ":"),
                       default=str)
    return hashlib.blake2s(canon.encode(), digest_size=16).hexdigest(), \
        material


def default_cache_dir() -> str:
    """The standard on-host cache root (the serve manifest mounts a volume
    here): $NERRF_AOT_CACHE_DIR, else ~/.cache/nerrf_tpu/aot.  No host
    subdirectory — the key material carries the ISA axis instead, so one
    volume can serve heterogeneous hosts without ever cross-loading."""
    return os.environ.get("NERRF_AOT_CACHE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "nerrf_tpu", "aot")


class CompileCache:
    """One cache root.  Fail-open by contract: `get`/`put` return
    None/False on any failure; `load_or_compile` always returns a callable
    (worst case the live jit fn) plus a `CompileInfo` saying what happened.

    ``seed_dirs`` are read-only secondary roots — a checkpoint's
    ``executables/`` sidecar published by the registry.  A primary miss
    that hits a seed copies the entry in (atomic) and loads it, so a pod
    booting from a published version warms its local cache on first use.
    """

    def __init__(self, root: str | Path | None = None,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 seed_dirs: Tuple[str, ...] = (),
                 registry=None, journal=None, log=None) -> None:
        self.root = Path(root if root is not None
                         else default_cache_dir()).absolute()
        self.max_bytes = int(max_bytes)
        self.seed_dirs = tuple(Path(d).absolute() for d in seed_dirs if d)
        self._registry = registry
        self._journal = journal
        self._log = log or (lambda msg: None)
        self._env: Optional[dict] = None  # resolved lazily (needs a backend)

    # -- wiring ---------------------------------------------------------------

    def _reg(self):
        if self._registry is None:
            from nerrf_tpu.observability import DEFAULT_REGISTRY

            self._registry = DEFAULT_REGISTRY
        return self._registry

    def _jrn(self):
        if self._journal is None:
            from nerrf_tpu.flight.journal import DEFAULT_JOURNAL

            self._journal = DEFAULT_JOURNAL
        return self._journal

    def env(self) -> dict:
        if self._env is None:
            self._env = environment_key()
        return self._env

    def add_seed_dir(self, path) -> None:
        """Register a read-only secondary root (a published version's
        ``executables/`` sidecar) for future misses to fall back to."""
        p = Path(path).absolute()
        if p not in self.seed_dirs:
            self.seed_dirs = self.seed_dirs + (p,)

    def entry_dir(self, fingerprint: str) -> Path:
        return self.root / fingerprint

    # -- observability --------------------------------------------------------

    def _record(self, info: CompileInfo) -> None:
        reg = self._reg()
        if info.source == "cache":
            reg.counter_inc(
                "compile_cache_hits_total",
                labels={"program": info.program},
                help="compiled programs served from the persistent cache")
        else:
            reg.counter_inc(
                "compile_cache_misses_total",
                labels={"program": info.program,
                        "reason": info.reason or "absent"},
                help="cache lookups that fell back to a live compile, by "
                     "miss cause")
        reg.histogram_observe(
            "compile_seconds", info.seconds,
            buckets=COMPILE_SECONDS_BUCKETS,
            labels={"program": info.program, "source": info.source},
            help="wall seconds to obtain an executable, cache-deserialize "
                 "vs fresh XLA compile")
        self._jrn().record(
            "compile", program=info.program, fingerprint=info.fingerprint,
            source=info.source, seconds=round(info.seconds, 3),
            **({"reason": info.reason} if info.reason else {}))

    # -- read side ------------------------------------------------------------

    def get(self, fingerprint: str):
        """→ a loaded `jax.stages.Compiled`, or None (fail-open: any
        unreadable/corrupt/foreign entry is a miss, never an error)."""
        entry = self._find_entry(fingerprint)
        if entry is None:
            return None
        try:
            from jax.experimental import serialize_executable as se

            from nerrf_tpu import chaos

            # chaos fault point (no-op disarmed): bit rot / torn write in
            # the entry payload — deserialize must fail here and take the
            # evict-and-compile-live fail-open path below, never serve a
            # damaged executable
            payload = chaos.mangle(
                "compilecache.corrupt_payload",
                (entry / PAYLOAD).read_bytes(), key=fingerprint)
            in_tree, out_tree = pickle.loads((entry / TREES).read_bytes())
            compiled = se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception as e:  # noqa: BLE001 — fail-open by contract
            self._log(f"compile cache: entry {fingerprint} unreadable "
                      f"({type(e).__name__}: {e}); compiling live")
            # evict the corrupt entry (primary root only — seeds are
            # read-only) so the fresh compile that follows can repair it;
            # without this, `put` would keep deferring to the broken copy
            # and every future boot would re-pay the compile
            primary = self.entry_dir(fingerprint)
            if entry == primary:
                shutil.rmtree(primary, ignore_errors=True)
            return None
        try:  # LRU stamp; never worth failing a hit over
            os.utime(entry)
        except OSError:
            pass
        return compiled

    def _find_entry(self, fingerprint: str) -> Optional[Path]:
        primary = self.entry_dir(fingerprint)
        if (primary / PAYLOAD).is_file() and (primary / TREES).is_file():
            return primary
        for seed in self.seed_dirs:
            cand = seed / fingerprint
            if (cand / PAYLOAD).is_file() and (cand / TREES).is_file():
                return self._adopt(cand, fingerprint) or cand
        return None

    def _adopt(self, seed_entry: Path, fingerprint: str) -> Optional[Path]:
        """Copy a seed entry into the primary root (atomic, best-effort) so
        subsequent boots on this host hit locally."""
        target = self.entry_dir(fingerprint)
        try:
            self.root.mkdir(parents=True, exist_ok=True)
            tmp = Path(tempfile.mkdtemp(prefix=".adopt-", dir=self.root))
            try:
                for name in (PAYLOAD, TREES, META):
                    src = seed_entry / name
                    if src.is_file():
                        shutil.copy2(src, tmp / name)
                # an invalid husk at the target (crash mid-eviction) makes
                # rename fail ENOTEMPTY forever — and because the seed hit
                # succeeds, put() never runs to repair it, so every boot
                # would re-read across the (possibly remote) seed volume.
                # Replace it, exactly as put() does.
                if target.exists() and not (
                        (target / PAYLOAD).is_file()
                        and (target / TREES).is_file()):
                    shutil.rmtree(target, ignore_errors=True)
                os.rename(tmp, target)
            except OSError:
                shutil.rmtree(tmp, ignore_errors=True)
                return None
            return target
        except OSError:
            return None

    # -- write side -----------------------------------------------------------

    def put(self, fingerprint: str, compiled, material: dict,
            program: str, compile_seconds: float) -> Optional[str]:
        """Serialize + persist one compiled program (atomic tmp-then-
        rename).  Returns None on success, or the failure cause —
        "unserializable" (backend executables that do not support
        serialization) vs "unwritable" (read-only volume, disk full) —
        the distinction operators need to diagnose which; never raises."""
        try:
            from jax.experimental import serialize_executable as se

            payload, in_tree, out_tree = se.serialize(compiled)
            trees = pickle.dumps((in_tree, out_tree))
        except Exception as e:  # noqa: BLE001 — fail-open by contract
            self._log(f"compile cache: cannot serialize {program} "
                      f"({type(e).__name__}: {e}); running uncached")
            return "unserializable"
        meta = {
            "schema_version": 1,
            "program": program,
            "fingerprint": fingerprint,
            "key": material,
            "payload_bytes": len(payload),
            "compile_seconds": round(compile_seconds, 3),
            "created_at": time.time(),
        }
        try:
            self.root.mkdir(parents=True, exist_ok=True)
            tmp = Path(tempfile.mkdtemp(prefix=".put-", dir=self.root))
            try:
                (tmp / PAYLOAD).write_bytes(payload)
                (tmp / TREES).write_bytes(trees)
                (tmp / META).write_text(json.dumps(meta, indent=2))
                target = self.entry_dir(fingerprint)
                if (target / PAYLOAD).is_file() and \
                        (target / TREES).is_file():
                    # concurrent writer won with a complete entry; keep it
                    shutil.rmtree(tmp, ignore_errors=True)
                else:
                    # absent, or an invalid husk (partial delete, missing
                    # trees) that _find_entry skips — replace so a damaged
                    # entry is repaired by the very compile it caused
                    if target.exists():
                        shutil.rmtree(target, ignore_errors=True)
                    os.rename(tmp, target)
            except OSError:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
        except OSError as e:
            self._log(f"compile cache: cannot persist {program} "
                      f"({type(e).__name__}: {e}); result stays in-process")
            return "unwritable"
        self._reg().counter_inc(
            "compile_cache_bytes_total", float(len(payload)),
            help="serialized executable bytes written into the cache")
        self.prune()
        return None

    # -- the one entry point --------------------------------------------------

    def load_or_compile(self, jit_fn, args: tuple, kwargs: dict | None = None,
                        program: str = "program",
                        extra: Optional[dict] = None):
        """→ (callable, CompileInfo).

        Hit: the deserialized `Compiled` (no tracing, no XLA).  Miss:
        ``jit_fn.lower(*args, **kwargs).compile()``, persisted for next
        time.  Total failure (lower/compile/serialize machinery broken):
        the live ``jit_fn`` itself, source="live" — serving always works.
        """
        kwargs = kwargs or {}
        try:
            avals = aval_signature(args, kwargs)
            fp, material = compute_fingerprint(program, avals, extra,
                                               env=self.env())
        except Exception as e:  # noqa: BLE001 — fail-open by contract
            info = CompileInfo(program=program, fingerprint="",
                               source="live", seconds=0.0,
                               reason=f"fingerprint: {type(e).__name__}: {e}")
            self._record(info)
            return jit_fn, info
        t0 = time.perf_counter()
        compiled = self.get(fp)
        if compiled is not None:
            info = CompileInfo(program=program, fingerprint=fp,
                               source="cache",
                               seconds=time.perf_counter() - t0)
            self._record(info)
            return compiled, info
        reason = "absent"
        t0 = time.perf_counter()
        try:
            compiled = self._compile_fresh(jit_fn, args, kwargs)
        except Exception as e:  # noqa: BLE001 — fail-open by contract
            info = CompileInfo(
                program=program, fingerprint=fp, source="live",
                seconds=time.perf_counter() - t0,
                reason=f"lower/compile: {type(e).__name__}: {e}")
            self._record(info)
            self._log(f"compile cache: AOT path failed for {program} "
                      f"({info.reason}); using the live jit function")
            return jit_fn, info
        seconds = time.perf_counter() - t0
        put_err = self.put(fp, compiled, material, program, seconds)
        if put_err:
            reason = put_err
        info = CompileInfo(program=program, fingerprint=fp, source="fresh",
                           seconds=seconds, reason=reason)
        self._record(info)
        return compiled, info

    @staticmethod
    def _compile_fresh(jit_fn, args: tuple, kwargs: dict):
        """``lower().compile()`` with JAX's own persistent compilation
        cache suspended.  Serializing an executable that was ITSELF loaded
        from that cache produces a payload whose compiled symbols are
        unresolvable in any other process ("Symbols not found" at
        deserialize — measured live on XLA:CPU), so a to-be-serialized
        compile must always be fresh.  Costs one full compile when only
        jax's cache was warm; this cache then persists the self-contained
        result, so it is paid at most once per program.

        Suspension has to go through the ``jax_enable_compilation_cache``
        flag AND ``compilation_cache.reset_cache()``: jax memoizes its
        is-the-cache-used verdict process-wide on first compile, so just
        clearing ``jax_compilation_cache_dir`` is a silent no-op once
        anything has compiled (measured live: the e2e pre-flight caught
        poisoned payloads written exactly that way)."""
        import jax

        prev_dir = getattr(jax.config, "jax_compilation_cache_dir", None)
        prev_on = getattr(jax.config, "jax_enable_compilation_cache", True)
        reset = lambda: None  # noqa: E731 — default when cc is private/absent
        if prev_dir and prev_on:
            try:
                from jax._src import compilation_cache as _cc

                reset = _cc.reset_cache
            except Exception:  # noqa: BLE001 — older/newer jax layouts
                pass
            jax.config.update("jax_enable_compilation_cache", False)
            reset()  # drop the memoized verdict so the flag is re-read
        try:
            return jit_fn.lower(*args, **kwargs).compile()
        finally:
            if prev_dir and prev_on:
                # restore the OPERATOR'S value, never a hardcoded True —
                # and only when we flipped it (prev_on)
                jax.config.update("jax_enable_compilation_cache", prev_on)
                reset()  # re-arm jax's cache for everyone else

    # -- maintenance (the `nerrf cache` surface) ------------------------------

    def entries(self) -> List[dict]:
        """Inventory, oldest-last-used first: [{fingerprint, program,
        bytes, created_at, last_used, valid}, ...]."""
        out = []
        if not self.root.is_dir():
            return out
        for d in sorted(self.root.iterdir()):
            if not d.is_dir() or d.name.startswith("."):
                continue
            meta = {}
            try:
                meta = json.loads((d / META).read_text())
            except (OSError, ValueError):
                pass
            size = 0
            for f in d.iterdir():
                try:
                    size += f.stat().st_size
                except OSError:
                    pass
            try:
                last_used = d.stat().st_mtime
            except OSError:
                last_used = 0.0
            out.append({
                "fingerprint": d.name,
                "program": meta.get("program"),
                "bytes": size,
                "created_at": meta.get("created_at"),
                "compile_seconds": meta.get("compile_seconds"),
                "last_used": last_used,
                "valid": (d / PAYLOAD).is_file() and (d / TREES).is_file(),
            })
        out.sort(key=lambda e: e["last_used"])
        return out

    def prune(self, max_bytes: Optional[int] = None) -> List[str]:
        """LRU disk bound: evict oldest-last-used entries until the root
        fits.  Returns evicted fingerprints.  Best-effort — an entry that
        cannot be removed (NFS silly-rename, permissions) is skipped."""
        limit = self.max_bytes if max_bytes is None else int(max_bytes)
        entries = self.entries()
        total = sum(e["bytes"] for e in entries)
        evicted = []
        for e in entries:
            if total <= limit:
                break
            try:
                shutil.rmtree(self.entry_dir(e["fingerprint"]))
            except OSError:
                continue
            total -= e["bytes"]
            evicted.append(e["fingerprint"])
        if evicted:
            self._jrn().record("compile_cache_prune", evicted=len(evicted),
                               kept_bytes=total, limit_bytes=limit)
        return evicted

    def verify(self) -> List[dict]:
        """Integrity pass: every entry's files present, meta parseable, and
        the stamped fingerprint matching the directory name.  Returns the
        problems ([] = clean); read-only (deleting is `prune`'s job)."""
        problems = []
        if not self.root.is_dir():
            return problems
        for d in sorted(self.root.iterdir()):
            if not d.is_dir() or d.name.startswith("."):
                continue
            for name in (PAYLOAD, TREES, META):
                if not (d / name).is_file():
                    problems.append({"fingerprint": d.name,
                                     "problem": f"missing {name}"})
            meta_file = d / META
            if meta_file.is_file():
                try:
                    meta = json.loads(meta_file.read_text())
                    if meta.get("fingerprint") != d.name:
                        problems.append(
                            {"fingerprint": d.name,
                             "problem": "meta fingerprint mismatch "
                                        f"({meta.get('fingerprint')})"})
                    want = meta.get("payload_bytes")
                    payload = d / PAYLOAD
                    if want is not None and payload.is_file() and \
                            payload.stat().st_size != want:
                        problems.append(
                            {"fingerprint": d.name,
                             "problem": f"payload truncated "
                                        f"({payload.stat().st_size} != "
                                        f"{want} bytes)"})
                except (OSError, ValueError) as e:
                    problems.append({"fingerprint": d.name,
                                     "problem": f"meta unreadable: {e}"})
        return problems
