#!/usr/bin/env python3
"""Tune-loop harness: archive a skewed serve mix, fit the learned ladder,
re-serve on it, and gate that tuned beats static.

The closed loop docs/tuning.md documents, end to end in one process:

1. **Measure** — a service on a deliberately coarse static ladder serves
   a skewed stream mix (small windows dominating, so most traffic pads
   far up the bottom rung) with the telemetry archive spooling; this is
   exactly a production pod's day.
2. **Fit** — `nerrf archive export --tune` emits the corpus; `tune.tune`
   fits the cost model and searches ladder + per-rung kernel routing.
3. **Gate (deterministic)** — the tuned ladder must STRICTLY beat the
   static one on expected padded device seconds per window *under the
   same fitted model*.  Both sides of the comparison come from one fit
   over one corpus, so the verdict is a pure function of the archived
   run — no wall-clock dependence in the gate itself.
4. **Re-serve** — a fresh service boots on the tuned ladder with the
   routing table applied: zero recompiles after warmup across the tuned
   rungs, and one stream's DetectionResult stays bit-identical to the
   offline `pipeline.model_detect` at the tuned bucket (the
   admission/warmup/program-closure contracts hold on ANY ladder this
   emits).

    python benchmarks/run_tune_bench.py                  # full mix
    python benchmarks/run_tune_bench.py --smoke          # 2 streams
    python benchmarks/run_tune_bench.py --out results/tune_bench_cpu.json

Prints ONE JSON line (the artifact) on stdout; exits 1 when tuned fails
to beat static, parity breaks, or the tuned boot recompiles.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

# Deliberately coarse: the bottom rung is 1024 nodes, so the small-window
# mix below pads ~16× up — the padding waste the tuner exists to recover
# (a 256-ish rung).  The top rung keeps the ladder admission-complete for
# the mix's tail.
STATIC_LADDER = ((1024, 2048, 128), (4096, 8192, 256))


def _feed(svc, stream, events, strings, block=256, timeout=180.0):
    svc.join(stream)
    for i in range(0, len(events), block):
        blk = type(events)(**{f.name: getattr(events, f.name)[i:i + block]
                              for f in dataclasses.fields(events)})
        svc.feed(stream, blk, strings)
    return svc.leave(stream, timeout=timeout)


def run(streams: int = 6, sim_seconds: float = 90.0,
        batch_size: int = 8, close_ms: float = 100.0, smoke: bool = False,
        log=lambda *a: print(*a, file=sys.stderr, flush=True)) -> dict:
    """Importable harness body (the tier-1 smoke test calls this
    in-process).  Returns the artifact dict."""
    if smoke:
        streams, sim_seconds = 2, 30.0
    log = log or (lambda *a: None)
    import shutil

    import jax

    from nerrf_tpu.archive import ArchiveConfig, ArchiveWriter, export_tune
    from nerrf_tpu.data.loaders import Trace
    from nerrf_tpu.data.synth import SimConfig, simulate_trace
    from nerrf_tpu.flight.journal import EventJournal
    from nerrf_tpu.models import JointConfig, NerrfNet
    from nerrf_tpu.observability import MetricsRegistry
    from nerrf_tpu.pipeline import model_detect
    from nerrf_tpu.serve import (
        OnlineDetectionService,
        ServeConfig,
        bucket_tag,
        init_untrained_params,
    )
    from nerrf_tpu.tune import (
        apply_to_model_config,
        apply_to_serve_config,
        load_kernel_bench_crossover,
        tune,
    )

    backend = jax.default_backend()
    static_cfg = ServeConfig(
        buckets=STATIC_LADDER, batch_size=batch_size,
        batch_close_sec=close_ms / 1000.0,
        window_sec=15.0, stride_sec=5.0,
        stream_queue_slots=512, alert_queue_slots=4096,
        window_deadline_sec=5.0)
    model = NerrfNet(JointConfig().small)
    params = init_untrained_params(model, static_cfg)

    # ---- 1: measured leg — skewed mix through the static ladder ------------
    reg = MetricsRegistry(namespace="tunebench")
    jrn = EventJournal(capacity=8192, registry=reg)
    svc = OnlineDetectionService(params, model, cfg=static_cfg,
                                 registry=reg, journal=jrn)
    svc.start(log=log)
    arch_dir = tempfile.mkdtemp(prefix="nerrf-tune-bench-")
    writer = ArchiveWriter(
        ArchiveConfig(out_dir=arch_dir, snapshot_every_sec=0.5),
        registry=reg, journal=jrn, log=log)
    svc.attach_archive(writer)

    # the skew: every stream is SMALL traffic (tens of nodes per window),
    # padding ~16× on the static bottom rung; stream parameters vary so
    # the demand distribution has body and tail, not one spike
    traces = []
    t0 = time.perf_counter()
    errors = {}
    for i in range(streams):
        tr = simulate_trace(SimConfig(
            duration_sec=sim_seconds, attack=(i % 2 == 0),
            attack_start_sec=sim_seconds / 3,
            num_target_files=3 + 4 * (i % 3),
            benign_rate_hz=4.0 + 10.0 * (i % 3), seed=2000 + 131 * i))
        traces.append(tr)
        try:
            _feed(svc, f"s{i}", tr.events, tr.strings)
        except Exception as e:  # noqa: BLE001 — a stream error is a gate
            errors[f"s{i}"] = repr(e)
    measure_wall = round(time.perf_counter() - t0, 2)
    svc.stop()
    writer.close()
    windows_measured = int(reg.value("serve_windows_scored_total"))
    log(f"[tune-bench] measured leg: {windows_measured} windows over "
        f"{streams} streams in {measure_wall}s on "
        f"{[bucket_tag(b) for b in STATIC_LADDER]}")

    # ---- 2: corpus → fit → tuned artifact ----------------------------------
    corpus = export_tune(arch_dir)
    shutil.rmtree(arch_dir, ignore_errors=True)
    kb = load_kernel_bench_crossover(os.path.relpath(
        Path(__file__).resolve().parent / "results" /
        "kernel_bench_cpu.json"))
    art = tune(corpus, model_cfg=model.cfg, kernel_bench=kb,
               max_rungs=3, static_buckets=STATIC_LADDER)
    expected = art["expected"]
    tuned_buckets = tuple(tuple(b) for b in art["buckets"])
    log(f"[tune-bench] tuned ladder {[bucket_tag(b) for b in tuned_buckets]}"
        f" routing {art['routing']}: expected "
        f"{expected['static_device_seconds_per_window']:.4g}s → "
        f"{expected['tuned_device_seconds_per_window']:.4g}s per window "
        f"({expected['improvement']:.1%})")

    # ---- 3: re-serve on the tuned ladder -----------------------------------
    tuned_cfg = apply_to_serve_config(art, static_cfg)
    tuned_model = NerrfNet(apply_to_model_config(art, model.cfg))
    reg2 = MetricsRegistry(namespace="tunebench2")
    jrn2 = EventJournal(capacity=8192, registry=reg2)
    svc2 = OnlineDetectionService(params, tuned_model, cfg=tuned_cfg,
                                  registry=reg2, journal=jrn2)
    t0 = time.perf_counter()
    svc2.start(log=log)
    tuned_warmup_wall = round(time.perf_counter() - t0, 2)
    # p0 re-drives the full skewed stream across the tuned rungs (the
    # zero-recompile evidence); p1 is the parity stream — low-rate and
    # file-poor so EVERY window (flush partials included) lands in the
    # smallest tuned rung, the one bucket offline model_detect will use
    parity_tr = simulate_trace(SimConfig(
        duration_sec=min(sim_seconds, 45.0), attack=False,
        num_target_files=3, benign_rate_hz=1.5, seed=7))
    served = None
    try:
        _feed(svc2, "p0", traces[0].events, traces[0].strings)
        served = _feed(svc2, "p1", parity_tr.events, parity_tr.strings)
    except Exception as e:  # noqa: BLE001
        errors["reserve"] = repr(e)
    finally:
        svc2.stop()
    recompiles = sum(
        int(reg2.value("serve_recompiles_total",
                       labels={"bucket": bucket_tag(b)}) or 0)
        for b in tuned_cfg.buckets)

    # parity: the tuned service's stream vs offline model_detect at the
    # SAME tuned bucket with the SAME routing-bearing model config — a
    # tuned ladder changes where windows land and which kernel aggregates,
    # never what a landed window scores
    parity = False
    parity_bucket = None
    if served is not None:
        parity_bucket = sorted(tuned_cfg.buckets)[0]
        offline = model_detect(
            Trace(events=parity_tr.events, strings=parity_tr.strings,
                  ground_truth=None, labels=None, name="p1"),
            params, tuned_model,
            ds_cfg=tuned_cfg.dataset_config(parity_bucket),
            auto_capacity=False, batch_size=batch_size)
        parity = (
            served.file_scores == offline.file_scores
            and served.file_window_scores == offline.file_window_scores
            and served.proc_scores == offline.proc_scores
            and served.file_bytes == offline.file_bytes
            and served.threshold == offline.threshold)
    log(f"[tune-bench] tuned re-serve: warmup {tuned_warmup_wall}s, "
        f"recompiles {recompiles}, parity at "
        f"{bucket_tag(parity_bucket) if parity_bucket else None}: {parity}")

    tuned_beats_static = (
        expected["tuned_device_seconds_per_window"]
        < expected["static_device_seconds_per_window"])
    return {
        "metric": "tuned_vs_static_expected_device_seconds_per_window",
        "value": round(expected["improvement"], 4),
        "unit": "fractional improvement, fitted cost model "
                "(deterministic given the corpus)",
        "backend": backend,
        "smoke": smoke or None,
        "streams": streams,
        "windows_measured": windows_measured,
        "measure_wall_seconds": measure_wall,
        "static_ladder": [bucket_tag(b) for b in STATIC_LADDER],
        "tuned_ladder": [bucket_tag(b) for b in tuned_buckets],
        "routing": art["routing"],
        "expected": expected,
        "tuned_beats_static": bool(tuned_beats_static),
        "fit": {k: art["fit"][k] for k in
                ("alpha", "beta", "dense_gamma", "measured_points",
                 "demand_points", "candidates_scored")},
        "kernel_bench_prior": art["fit"]["provenance"]["kernel_bench"],
        "corpus_fingerprint": art["corpus_fingerprint"],
        "reserve": {
            "warmup_wall_seconds": tuned_warmup_wall,
            "recompiles_after_warmup": recompiles,
            "parity_bucket": bucket_tag(parity_bucket)
                if parity_bucket else None,
            "parity_bit_identical_to_model_detect": bool(parity),
        },
        "stream_errors": errors or None,
        "provenance": "python benchmarks/run_tune_bench.py"
                      + (" --smoke" if smoke else ""),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--streams", type=int, default=6)
    ap.add_argument("--seconds", type=float, default=90.0,
                    help="simulated seconds of trace per stream")
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--close-ms", type=float, default=100.0)
    ap.add_argument("--smoke", action="store_true",
                    help="2 streams, short traces")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="also write the artifact JSON here")
    args = ap.parse_args(argv)

    result = run(streams=args.streams, sim_seconds=args.seconds,
                 batch_size=args.batch_size, close_ms=args.close_ms,
                 smoke=args.smoke)
    print(json.dumps(result))
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as f:
            f.write(json.dumps(result, indent=2) + "\n")
    ok = (result["tuned_beats_static"]
          and result["reserve"]["recompiles_after_warmup"] == 0
          and result["reserve"]["parity_bit_identical_to_model_detect"]
          and not result["stream_errors"])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
