"""The respond tier: queue semantics, batched-planner parity and compile
discipline, the adversarial scenario corpus, and the verify-before-surface
contract."""

import json

import numpy as np
import pytest

from nerrf_tpu.flight.journal import EventJournal
from nerrf_tpu.observability import MetricsRegistry
from nerrf_tpu.pipeline import heuristic_detect
from nerrf_tpu.planner import MCTSConfig, UndoDomain
from nerrf_tpu.planner.device_mcts import DeviceMCTS
from nerrf_tpu.respond import (
    FAMILIES,
    BatchedDeviceMCTS,
    Incident,
    IncidentQueue,
    PlanVerifier,
    RespondConfig,
    ResponseRouter,
    schedule,
    stage_incident,
)
from nerrf_tpu.serve.alerts import WindowAlert, calibrated_severity

CFG = MCTSConfig(num_simulations=32)


def _domain(seed=0, F=10, P=2, max_steps=64):
    rng = np.random.default_rng(seed)
    scores = np.where(np.arange(F) % 2 == 0, 0.95, 0.03).astype(np.float32)
    return UndoDomain(
        file_paths=[f"/srv/data/f_{i}.lockbit3" for i in range(F)],
        file_scores=scores,
        file_loss_mb=rng.uniform(1.0, 4.0, F).astype(np.float32),
        proc_names=[f"{4000 + p}:python3" for p in range(P)],
        proc_scores=np.array([0.97] + [0.05] * (P - 1), np.float32),
        max_steps=max_steps,
    )


def _alert(stream="s", severity=0.9, hot=None):
    return WindowAlert(stream=stream, window_idx=3, lo_ns=0, hi_ns=1,
                       max_prob=0.95, hot=hot or [("file", 101, 0.95)],
                       t_admit=0.0, t_scored=0.0, late=False,
                       trace_id="t-1", severity=severity)


# -- severity (satellite: one calibrated number at the demux boundary) -----


def test_calibrated_severity_formula():
    assert calibrated_severity(0.5, 0.5) == 0.0  # at threshold: floor
    assert calibrated_severity(1.0, 0.5) == 1.0  # saturated: ceiling
    assert calibrated_severity(0.75, 0.5) == pytest.approx(0.5)
    # comparable across operating points: same headroom fraction, same
    # severity even though the raw scores differ
    assert calibrated_severity(0.95, 0.9) == pytest.approx(
        calibrated_severity(0.55, 0.1))
    assert calibrated_severity(0.3, 0.5) == 0.0  # below threshold clamps
    assert calibrated_severity(2.0, 0.5) == 1.0  # garbage in, [0,1] out


def test_alert_carries_severity_field():
    a = _alert(severity=calibrated_severity(0.95, 0.5))
    assert a.severity == pytest.approx(0.9)


# -- incident queue --------------------------------------------------------


def test_incident_queue_bounds_and_journals_eviction():
    reg, jr = MetricsRegistry(), EventJournal(registry=MetricsRegistry())
    q = IncidentQueue(slots=2, registry=reg, journal=jr)
    incs = [Incident.from_alert(_alert(stream=f"s{i}")) for i in range(3)]
    assert q.put(incs[0]) and q.put(incs[1])
    assert not q.put(incs[2])  # overflow: oldest evicted
    taken = q.take(8)
    assert [i.stream for i in taken] == ["s1", "s2"]  # s0 was dropped
    drops = [r for r in jr.tail(kinds=("incident_enqueued",))
             if r.data.get("dropped")]
    assert len(drops) == 1 and drops[0].stream == "s0"
    assert drops[0].data["reason"] == "queue_full"
    assert reg.value("respond_incidents_total",
                     labels={"outcome": "evicted"}) == 1.0


def test_incident_queue_take_close_window():
    q = IncidentQueue(slots=4, registry=MetricsRegistry(),
                      journal=EventJournal(registry=MetricsRegistry()))
    assert q.take(4) == []  # empty, no close window: immediate
    inc = Incident.from_alert(_alert())
    q.put(inc)
    got = q.take(4, close_sec=5.0)  # first item already there: no wait
    assert len(got) == 1 and got[0] is inc


def test_incident_from_alert_pseudo_targets():
    inc = Incident.from_alert(_alert(hot=[("file", 7, 0.9),
                                          ("proc", 4913, 0.8)]))
    assert inc.domain.file_paths == ["ino:7"]
    assert inc.domain.proc_names == ["4913:alert"]
    assert inc.context is None  # verification will fail closed


# -- batched planner -------------------------------------------------------


def test_batched_plan_single_incident_matches_offline_planner():
    """B=1 through the vmapped program must be bit-identical to the
    offline DeviceMCTS plan — same actions in order, same reward, same
    rollout count.  This is the bench's parity gate as a unit test."""
    d = _domain(seed=3)
    offline = DeviceMCTS(d, CFG).plan()
    batched = BatchedDeviceMCTS(CFG, batch_slots=(1, 2)).plan_batch([d])[0]
    assert [(a.kind, a.target) for a in batched.actions] == \
        [(a.kind, a.target) for a in offline.actions]
    assert batched.expected_reward == offline.expected_reward
    assert batched.rollouts == offline.rollouts == CFG.num_simulations


def test_batched_plan_padded_slot_matches_full_slot():
    """3 incidents in a 4-slot (one pad lane) must plan exactly as the
    same incidents would alone — the pre-stopped pad root cannot bleed
    into real lanes."""
    ds = [_domain(seed=s) for s in (1, 2, 3)]
    solo = [DeviceMCTS(d, CFG).plan() for d in ds]
    packed = BatchedDeviceMCTS(CFG, batch_slots=(4,)).plan_batch(ds)
    for s, p in zip(solo, packed):
        assert [(a.kind, a.target) for a in p.actions] == \
            [(a.kind, a.target) for a in s.actions]
        assert p.expected_reward == s.expected_reward


def test_batched_planner_zero_recompiles_after_warmup():
    reg = MetricsRegistry()
    b = BatchedDeviceMCTS(CFG, batch_slots=(1, 2), registry=reg)
    b.warmup_for(10, 2)
    for n in (1, 2):
        b.plan_batch([_domain(seed=10 + i) for i in range(n)])
    assert b.recompiles == 0
    assert reg.value("respond_recompiles_total") == 0.0

    cold = BatchedDeviceMCTS(CFG, batch_slots=(2,), registry=reg)
    cold.plan_batch([_domain(seed=1)])  # no warmup: counted honestly
    assert cold.recompiles == 1
    assert reg.value("respond_recompiles_total") == 1.0


def test_batched_planner_rejects_mixed_buckets():
    b = BatchedDeviceMCTS(CFG)
    with pytest.raises(ValueError, match="mixed shape buckets"):
        b.plan_batch([_domain(max_steps=64), _domain(max_steps=32)])


def test_batched_planner_waves_above_top_slot():
    b = BatchedDeviceMCTS(CFG, batch_slots=(1, 2))
    b.warmup_for(10, 2)
    plans = b.plan_batch([_domain(seed=s) for s in range(5)])
    assert len(plans) == 5 and b.recompiles == 0
    assert all(p.rollouts == CFG.num_simulations for p in plans)


# -- scenario corpus -------------------------------------------------------


def test_schedule_is_deterministic_and_seed_sensitive():
    a, b = schedule(7, 12), schedule(7, 12)
    assert a == b
    assert schedule(8, 12) != a
    assert {s.family for s in schedule(7, 40)} == set(FAMILIES)
    assert all(a[i].at_sec <= a[i + 1].at_sec for i in range(len(a) - 1))


@pytest.mark.parametrize("family", FAMILIES)
def test_staged_family_is_detected_and_damage_is_real(tmp_path, family):
    staged = stage_incident(tmp_path, family, seed=1, files=4)
    # the snapshot predates the damage: the live tree diverges from it
    diff = staged.store.diff(staged.manifest, staged.victim_root)
    assert diff, f"{family} staged no on-disk damage"
    det = heuristic_detect(staged.trace)
    assert det.flagged_files(), f"{family} evades the heuristic detector"
    assert det.proc_scores


def test_staged_incident_same_seed_same_trace(tmp_path):
    a = stage_incident(tmp_path / "a", "cron-persistence", seed=5, files=4)
    b = stage_incident(tmp_path / "b", "cron-persistence", seed=5, files=4)
    sa, sb = a.trace.strings, b.trace.strings
    ops = [(int(s), sa.lookup(int(p)).rsplit("/", 1)[-1], int(n))
           for s, p, n in zip(a.trace.events.syscall,
                              a.trace.events.path_id,
                              a.trace.events.bytes)]
    ops_b = [(int(s), sb.lookup(int(p)).rsplit("/", 1)[-1], int(n))
             for s, p, n in zip(b.trace.events.syscall,
                                b.trace.events.path_id,
                                b.trace.events.bytes)]
    assert ops == ops_b


# -- verification ----------------------------------------------------------


@pytest.mark.parametrize("family", FAMILIES)
def test_every_family_yields_a_verified_plan(tmp_path, family):
    """The tier's end-to-end promise, per family: detect → batched plan →
    sandbox-verified undo plan."""
    staged = stage_incident(tmp_path, family, seed=2, files=4)
    det = heuristic_detect(staged.trace)
    inc = Incident.from_detection(family, det,
                                  context=staged.verify_context())
    plan = BatchedDeviceMCTS(CFG, batch_slots=(1,)).plan_batch(
        [inc.domain])[0]
    vp = PlanVerifier(registry=MetricsRegistry(),
                      journal=EventJournal(
                          registry=MetricsRegistry())).verify(inc, plan)
    assert vp.verified, f"{family}: {vp.reason}"
    assert vp.gate.rehearsal.files_restored > 0


def test_unverifiable_plan_quarantined_with_journaled_reason():
    reg, jr = MetricsRegistry(), EventJournal(registry=MetricsRegistry())
    inc = Incident.from_alert(_alert())  # no snapshot context
    plan = BatchedDeviceMCTS(CFG, batch_slots=(1,)).plan_batch(
        [inc.domain])[0]
    vp = PlanVerifier(registry=reg, journal=jr).verify(inc, plan)
    assert not vp.verified
    assert "no snapshot context" in vp.reason
    rejects = jr.tail(kinds=("plan_rejected",))
    assert len(rejects) == 1
    assert rejects[0].data["reason"] == vp.reason
    assert reg.value("respond_plans_total",
                     labels={"outcome": "rejected"}) == 1.0
    assert jr.tail(kinds=("plan_verified",)) == []  # never surfaced


def test_rejected_empty_plan(tmp_path):
    staged = stage_incident(tmp_path, "mass-rename", seed=3, files=4)
    from nerrf_tpu.planner.domain import UndoPlan

    inc = Incident.from_detection("s", heuristic_detect(staged.trace),
                                  context=staged.verify_context())
    empty = UndoPlan(actions=[], expected_reward=0.0, rollouts=0,
                     rollouts_per_sec=0.0, planning_seconds=0.0)
    vp = PlanVerifier(registry=MetricsRegistry(),
                      journal=EventJournal(
                          registry=MetricsRegistry())).verify(inc, empty)
    assert not vp.verified and "no actions" in vp.reason


# -- router ----------------------------------------------------------------


def test_router_end_to_end_and_severity_gate(tmp_path):
    reg = MetricsRegistry()
    jr = EventJournal(registry=MetricsRegistry())
    cfg = RespondConfig(num_simulations=32, batch_close_sec=0.02,
                        severity_min=0.5)
    r = ResponseRouter(cfg, registry=reg, journal=jr).start()
    try:
        assert not r.offer_alert(_alert(severity=0.2))  # below the gate
        staged = stage_incident(tmp_path, "mass-rename", seed=4, files=4)
        det = heuristic_detect(staged.trace)
        assert r.submit_detection("victim", det,
                                  context=staged.verify_context())
        assert r.drain(timeout=120.0)
        results = r.results()
        assert len(results) == 1 and results[0].verified
        stats = r.stats()
        assert stats["planned"] == 1 and stats["verified"] == 1
        assert stats["recompiles"] == 0  # warmup covered the live traffic
    finally:
        r.stop()
    assert r._thread is None  # joined, not leaked
    kinds = [rec.kind for rec in jr.tail()]
    for kind in ("incident_enqueued", "plan_emitted", "plan_verified"):
        assert kind in kinds
    assert reg.value("respond_incidents_total",
                     labels={"outcome": "below_min"}) == 1.0


def test_router_batches_concurrent_incidents(tmp_path):
    cfg = RespondConfig(num_simulations=32, batch_close_sec=0.25,
                        batch_slots=(1, 2, 4))
    r = ResponseRouter(cfg, registry=MetricsRegistry(),
                       journal=EventJournal(registry=MetricsRegistry()))
    r.start()
    try:
        staged = stage_incident(tmp_path, "log-tamper", seed=6, files=4)
        det = heuristic_detect(staged.trace)
        ctx = staged.verify_context()
        for i in range(3):
            r.submit_detection(f"s{i}", det, context=ctx)
        assert r.drain(timeout=180.0)
        stats = r.stats()
        assert stats["planned"] == 3 and stats["recompiles"] == 0
        # the close window coalesced at least two incidents into one wave
        assert stats["batches"] < 3
        assert all(vp.verified for vp in r.results())
    finally:
        r.stop()


# -- the checked-in artifact of record ---------------------------------------


def test_checked_in_respond_artifact_meets_acceptance(repo_root):
    """The respond CPU artifact of record passes every gate the bench
    enforces live: all four attack families detected and answered with a
    sandbox-verified plan, the contextless incident quarantined with a
    journaled reason, B=1 batched plan bit-identical to the offline
    planner, zero recompiles after warmup, and the throughput gate
    (device-call amortization + lane-parallel projection ≥3x on the CPU
    rig; measured wall speedup on lane-parallel backends)."""
    import sys

    sys.path.insert(0, str(repo_root / "benchmarks"))
    from run_respond_bench import gates

    art = json.loads((repo_root / "benchmarks" / "results" /
                      "respond_bench_cpu.json").read_text())
    failed = [name for name, ok in gates(art) if not ok]
    assert failed == []
    # headline facts behind the gates stay visible here
    fams = art["corpus"]["families"]
    assert set(fams) == {"mass-rename", "exfil-staging",
                         "cron-persistence", "log-tamper"}
    assert all(f["verified_rate"] == 1.0 for f in fams.values())
    assert art["parity"]["bit_identical"] is True
    assert art["recompiles_after_warmup"] == 0
    assert art["throughput"]["device_call_amortization"] >= 3.0
    assert art["corpus"]["quarantine"]["journaled_reasons"]
