"""cache-key-coverage: the stale-executable hazard class, statically.

PR 7's compile cache fingerprints (program, argument avals, caller
``extra`` material, environment).  Anything else a lowered program
depends on — a closure-captured array baked in as a constant, a config
scalar that constant-folds into the HLO but is missing from ``extra`` —
is a *stale-cache hazard*: two processes that differ along that axis
compute the same fingerprint and one of them deserializes the other's
(wrong) executable.  This is the hazard class the poisoned-payload bug
PR 7's pre-flight caught belongs to; this rule makes the whole class a
CPU pre-flight failure.

Two checks per cache-keyed entry point:

  * **closure captures** — `jax.make_jaxpr` over abstract avals; every
    constant ≥ ``min_const_bytes`` baked into the jaxpr is flagged (the
    fingerprint hashes argument avals; a capture is not an argument —
    the `make_train_step_resident` rule exists precisely so dataset
    arrays ride as jit *parameters*).
  * **axis sensitivity** — each entry carries config variants whose
    argument avals are IDENTICAL but whose lowered programs differ
    (pos_weight, aggregation routing...).  For every variant pair:
    jaxprs differ ⇒ fingerprints must differ.  A pair with different
    programs and equal fingerprints is an uncovered key axis — the
    ``extra`` material (`step_key_extra` / `serve_program_key`) has a
    hole.
"""

from __future__ import annotations

from typing import List, Optional

from nerrf_tpu.analysis.engine import Finding, Rule
from nerrf_tpu.analysis.programs.abstract import (
    CacheKeyEntry,
    big_consts,
    finding,
    program_identity,
)

# the env axis is orthogonal to what this rule checks (same process, same
# backend for every variant) — a fixed stub keeps the pass device-free
_ENV_STUB = {"static": "analysis"}


class CacheKeyCoverage(Rule):
    id = "cache-key-coverage"
    description = ("closure captures and config axes a jaxpr depends on "
                   "that the CompileCache fingerprint cannot see")
    deep = True

    def __init__(self, entries: Optional[List[CacheKeyEntry]] = None) -> None:
        self._entries = entries

    def run(self, project) -> List[Finding]:
        if self._entries is None:
            from nerrf_tpu.analysis.programs.entries import cache_key_entries

            entries = cache_key_entries()
        else:
            entries = self._entries
        out: List[Finding] = []
        for entry in entries:
            out.extend(self._check(entry))
        return out

    def _check(self, entry: CacheKeyEntry) -> List[Finding]:
        import jax

        from nerrf_tpu.compilecache.cache import (
            aval_signature,
            compute_fingerprint,
        )

        out: List[Finding] = []
        traced = []
        for label, build, extra in entry.variants:
            try:
                fn, args = build()
                closed = jax.make_jaxpr(fn)(*args)
            except Exception as e:  # noqa: BLE001 — report, don't crash
                out.append(finding(
                    self.id, entry.path, 1,
                    anchor=f"cachekey:{entry.name}:{label}:trace",
                    message=f"{entry.name}[{label}]: abstract trace "
                            f"failed ({type(e).__name__}: {e})",
                    hint="the cache-key audit needs the program to trace "
                         "over ShapeDtypeStructs"))
                continue
            avals = aval_signature(args, {})
            fp, _ = compute_fingerprint(entry.name, avals, extra,
                                        env=_ENV_STUB)
            traced.append((label, program_identity(closed), fp))
            # every variant: a capture present only under a non-base
            # config is just as much a stale-cache hazard (the engine
            # dedups identical anchors when both variants carry it)
            for shape, dtype, nbytes in big_consts(
                    closed, entry.min_const_bytes):
                out.append(finding(
                    self.id, entry.path, 1,
                    anchor=f"cachekey:{entry.name}:const:"
                           f"{'x'.join(map(str, shape)) or 'scalar'}:"
                           f"{dtype}",
                    message=f"{entry.name}: a {nbytes}-byte "
                            f"closure-captured {dtype}{list(shape)} "
                            f"constant is baked into the jaxpr but "
                            f"invisible to the cache fingerprint — "
                            f"a process with a different capture "
                            f"would reuse this executable",
                    hint="pass the array as a jit parameter "
                         "(the make_train_step_resident rule) or "
                         "fold a digest of it into the program's "
                         "`extra` key material"))
        base = traced[0] if traced else None
        for label, ident, fp in traced[1:]:
            b_label, b_ident, b_fp = base
            if ident != b_ident and fp == b_fp:
                out.append(finding(
                    self.id, entry.path, 1,
                    anchor=f"cachekey:{entry.name}:{label}:uncovered",
                    message=f"{entry.name}: config axis `{label}` "
                            f"changes the lowered program but not the "
                            f"cache fingerprint — a run on the other "
                            f"side of this axis deserializes a stale "
                            f"executable",
                    hint="add the axis to the program's key material "
                         "(train: step_key_extra; serve: "
                         "serve_program_key) — conservative over-keying "
                         "costs one compile, a stale hit costs "
                         "correctness"))
        return out
