"""Structured event journal: the serve path's bounded decision log.

`EventJournal` is a thread-safe ring of typed `JournalRecord`s — the
"what happened" companion to the tracing spine's "where did the time go"
span ring.  Every record carries a process-monotonic sequence number (so
the tail is orderable even across clock steps), wall-clock and
perf-counter timestamps (the perf stamp aligns with span ``ts`` values for
offline joins), a record ``kind``, and the stream/window/trace IDs it
touched.

Record kinds in use (producers in parentheses):

    batch_close       a bucket's shared batch assembled (serve/batcher)
    batch_failed      a device batch's scoring raised (serve/batcher)
    batch_bisect      a failed batch split to isolate poison (serve/batcher)
    device_batch_failed  a window's terminal device failure, post-bisection
                      (serve/service; counted by the drop-burst trigger)
    stream_quarantined   a stream hit its poison-strike limit (serve/service)
    stream_released   a quarantined stream's timed release (serve/service)
    scorer_wedged     the scorer watchdog tripped / recovered
    scorer_recovered  (serve/batcher; readiness fails while wedged)
    reconnect         a resident stream's wire session restarted, with
                      backoff delay (serve/service)
    admission_drop    window dropped at admission, with reason (serve/service)
    demux_drop        alert evicted from the full sink (serve/alerts)
    readiness         admission opened/closed (serve/service)
    config            serve config fingerprint at start (serve/service)
    slo_breach        a window blew its e2e deadline (flight/slo)
    fault_injected    a chaos-plane fault fired at an armed point (chaos)
    chaos_armed/disarmed  the chaos plane's arm state changed (chaos)
    registry_publish  a checkpoint became an immutable version (registry/store)
    registry_shadow   candidate staged for shadow scoring (registry/manager)
    registry_promote  candidate promoted to LIVE (registry/manager)
    registry_veto     guardrail vetoed a candidate (registry/manager)
    registry_swap     live params hot-swapped, incl. rollbacks (registry/manager)
    quality_reference a reference quality profile bound/cleared (quality/monitor)
    quality_stats     cadenced drift stats: worst score/feature PSI, margin
                      mass (quality/monitor; the quality_drift trigger edge)
    train_start/done  training-run config+model fingerprints (train/loop)
    train_health      cadenced training health: loss, grad norm, update
                      ratio, throughput, data-wait fraction, nonfinite
                      flags (trainwatch/monitor; the train_divergence /
                      train_starvation / train_stall trigger evidence)
    fleet_scale       controller scaled the replica set out/in, with the
                      headroom evidence that justified it (fleet/controller)
    fleet_rebalance   stream slots remapped across replicas via the
                      deterministic slot map (fleet/controller)
    fleet_shed        admission shed a budget-burning stream's window
                      under pressure, with the burn ranking snapshot
                      (serve/service; fleet/controller)
    incident_enqueued a WindowAlert cleared respond admission and entered
                      the incident queue (respond/router); queue-full
                      evictions land as drops with reason
    plan_emitted      the batched planner produced an UndoPlan for an
                      incident, pre-verification (respond/router)
    plan_verified     sandbox replay approved the plan: it is surfaced
                      (respond/verify)
    plan_rejected     verification refused the plan — quarantined with the
                      gate's reason, never surfaced (respond/verify)
    rollback_step_failed  the executor refused one plan step fail-closed:
                      path escaped the sandbox root or the snapshot blob's
                      pre-image hash mismatched (rollback/executor)
    exception         uncaught exception captured by the crash hook
    bundle            a flight-recorder bundle was written (flight/recorder)

The ring records unconditionally: appends are a lock + deque append +
counter increment (~µs), bounded memory by construction.  Listeners (the
flight recorder's trigger engine) are invoked OUTSIDE the journal lock so
a slow listener can never block producers against each other.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

# Journal jsonl schema version, stamped on every serialized record as
# ``"v": "<major>.<minor>"``.  Archived segments and flight bundles
# outlive the process that wrote them, so readers apply the usual
# compatibility ladder: a MINOR bump adds fields (old readers ignore
# them, new readers tolerate their absence); a MAJOR bump changes the
# meaning of existing fields, and an older reader must refuse rather
# than misreport evidence.  Bump the minor when adding record fields,
# the major only when a field's meaning changes.
SCHEMA_VERSION = (1, 0)

#: Every record kind a journal producer emits today (producers in the
#: module docstring above, plus compile/profile/capacity records from
#: compilecache/, devtime/ and the archive plane).  The schema roundtrip
#: test iterates this tuple — a new kind that is not registered here is
#: a kind the archive/doctor readers have never been proven against.
KNOWN_KINDS = (
    "batch_close", "batch_failed", "batch_bisect", "device_batch_failed",
    "stream_quarantined", "stream_released", "scorer_wedged",
    "scorer_recovered", "reconnect", "admission_drop", "demux_drop",
    "readiness", "config", "slo_breach", "fault_injected", "chaos_armed",
    "chaos_disarmed", "registry_publish", "registry_shadow",
    "registry_promote", "registry_veto", "registry_swap",
    "registry_shadow_stats", "quality_reference", "quality_stats",
    "capacity_saturation", "compile", "compile_cache_prune",
    "profile_capture", "profile_failed", "train_start", "train_done",
    "train_health", "fleet_scale", "fleet_rebalance", "fleet_shed",
    "incident_enqueued", "plan_emitted", "plan_verified", "plan_rejected",
    "rollback_step_failed",
    "alert_disposition", "retrain_triggered", "retrain_done",
    "retrain_aborted",
    "archive_meta", "metrics_snapshot", "workload_sketch", "replay_window",
    "exception", "bundle",
)


class SchemaVersionError(ValueError):
    """A serialized record's schema MAJOR is newer than this reader."""


def _format_version(v: tuple) -> str:
    return f"{v[0]}.{v[1]}"


def check_schema_version(v, what: str = "journal record") -> None:
    """Reader-side gate: tolerate same/older majors and newer minors
    (additive fields), refuse a newer MAJOR with a one-line error —
    misreading re-defined fields is worse than not reading at all.
    ``None`` (a record written before versioning) passes."""
    if v is None:
        return
    try:
        major = int(str(v).split(".", 1)[0])
    except (TypeError, ValueError):
        raise SchemaVersionError(
            f"{what} carries an unparseable schema version {v!r}") from None
    if major > SCHEMA_VERSION[0]:
        raise SchemaVersionError(
            f"{what} schema v{v} is newer than this reader's "
            f"v{_format_version(SCHEMA_VERSION)} — upgrade nerrf_tpu to "
            f"read it")


def make_trace_id(stream: str, window_idx: int, lo_ns: int) -> str:
    """Deterministic per-window trace ID: the same (stream, window, epoch)
    always maps to the same ID, so journal records, spans, alerts and
    offline reprocessing join on it without coordination."""
    h = hashlib.blake2s(f"{stream}:{window_idx}:{lo_ns}".encode(),
                        digest_size=6).hexdigest()
    return f"w-{h}"


def fingerprint(obj) -> str:
    """Short stable fingerprint of a config/params-identity object (repr
    based — for dataclass configs repr is canonical and total)."""
    return hashlib.blake2s(repr(obj).encode(), digest_size=6).hexdigest()


@dataclasses.dataclass
class JournalRecord:
    """One journal entry.  ``data`` is the kind-specific payload (bucket,
    occupancy, reason, version, …) — JSON-serializable by contract."""

    seq: int
    t_wall: float           # unix seconds (human timeline)
    t_perf: float           # perf-counter seconds (joins with span ts)
    kind: str
    stream: Optional[str] = None
    window_id: Optional[int] = None
    trace_id: Optional[str] = None
    data: Dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        d = {"v": _format_version(SCHEMA_VERSION), "seq": self.seq,
             "t_wall": self.t_wall, "t_perf": self.t_perf,
             "kind": self.kind}
        if self.stream is not None:
            d["stream"] = self.stream
        if self.window_id is not None:
            d["window_id"] = self.window_id
        if self.trace_id is not None:
            d["trace_id"] = self.trace_id
        if self.data:
            d["data"] = self.data
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "JournalRecord":
        check_schema_version(d.get("v"))
        return cls(seq=int(d["seq"]), t_wall=float(d["t_wall"]),
                   t_perf=float(d.get("t_perf", 0.0)), kind=str(d["kind"]),
                   stream=d.get("stream"), window_id=d.get("window_id"),
                   trace_id=d.get("trace_id"), data=dict(d.get("data") or {}))


class EventJournal:
    """Bounded, thread-safe, listener-fanning record ring."""

    def __init__(self, capacity: int = 4096, registry=None) -> None:
        self._lock = threading.Lock()
        self._records: deque = deque(maxlen=max(capacity, 1))
        self._seq = 0
        self._registry = registry
        self._listeners: List[Callable[[JournalRecord], None]] = []

    def _reg(self):
        if self._registry is None:
            from nerrf_tpu.observability import DEFAULT_REGISTRY

            self._registry = DEFAULT_REGISTRY
        return self._registry

    # -- producing -----------------------------------------------------------

    def record(self, kind: str, stream: Optional[str] = None,
               window_id: Optional[int] = None,
               trace_id: Optional[str] = None, **data) -> JournalRecord:
        with self._lock:
            self._seq += 1
            rec = JournalRecord(
                seq=self._seq, t_wall=time.time(),
                t_perf=time.perf_counter(), kind=kind, stream=stream,
                window_id=window_id, trace_id=trace_id, data=data)
            self._records.append(rec)
            listeners = list(self._listeners)
        self._reg().counter_inc(
            "flight_journal_records_total", labels={"kind": kind},
            help="structured journal records appended, by record kind")
        # listeners run OUTSIDE the lock: a trigger evaluating (or a bundle
        # dumping) must never serialize unrelated producers
        for fn in listeners:
            try:
                fn(rec)
            except Exception:  # noqa: BLE001 — observers are advisory
                pass
        return rec

    def subscribe(self, fn: Callable[[JournalRecord], None]) -> None:
        with self._lock:
            self._listeners.append(fn)

    def unsubscribe(self, fn: Callable[[JournalRecord], None]) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    # -- reading -------------------------------------------------------------

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    def tail(self, n: Optional[int] = None,
             kinds: Optional[tuple] = None,
             since_seq: Optional[int] = None) -> List[JournalRecord]:
        """Newest-last slice of the ring: at most ``n`` records, optionally
        filtered by kind and/or a minimum (exclusive) sequence number."""
        with self._lock:
            recs = list(self._records)
        if kinds is not None:
            recs = [r for r in recs if r.kind in kinds]
        if since_seq is not None:
            recs = [r for r in recs if r.seq > since_seq]
        return recs[-n:] if n is not None else recs

    def clear(self) -> None:
        with self._lock:
            self._records.clear()

    def to_jsonl(self, n: Optional[int] = None) -> str:
        return "".join(json.dumps(r.to_dict()) + "\n" for r in self.tail(n))

    def write(self, path, n: Optional[int] = None) -> str:
        path = os.fspath(path)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            f.write(self.to_jsonl(n))
        return path


def load_journal(path) -> List[JournalRecord]:
    """Parse a journal.jsonl back into records (the doctor's reader).
    Malformed lines are skipped, not fatal — a bundle written mid-crash is
    still evidence.  A NEWER-MAJOR schema stamp is NOT malformed: it
    propagates (`SchemaVersionError`) so the doctor/report can refuse
    with one line instead of silently misreading re-defined fields."""
    out: List[JournalRecord] = []
    with open(os.fspath(path)) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(JournalRecord.from_dict(json.loads(line)))
            except SchemaVersionError:
                raise
            except (ValueError, KeyError, TypeError):
                continue
    return out


# The process-wide journal every pipeline component records into (the
# decision-log analogue of observability.DEFAULT_REGISTRY and
# tracing.DEFAULT_TRACER).
DEFAULT_JOURNAL = EventJournal()
