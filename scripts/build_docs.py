#!/usr/bin/env python3
"""Static docs-site builder: docs/*.md → docs/site/*.html, zero dependencies.

The reference ships a ~3.1k-line Next.js/fumadocs site (`docs/package.json`);
its *capability* is a browsable, navigable HTML rendering of the guides.
This builder produces that surface from the same markdown with nothing but
the stdlib — no node, no npm, no network — which is the right weight for an
infra repo: the content is the product, the chrome is 200 lines.

    python scripts/build_docs.py            # writes docs/site/
    python scripts/build_docs.py --check    # build to a temp dir (CI)

Supported markdown: ATX headings, fenced code blocks, inline code, links,
bold/italic, unordered/ordered lists, tables, blockquotes, hrs.
"""

from __future__ import annotations

import argparse
import html
import re
import shutil
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
DOCS = REPO / "docs"

# page order for the sidebar (index first, then the operator's journey)
ORDER = ["index", "quick-start", "architecture", "models", "planner",
         "rollback", "ingest", "scaling", "configuration", "operations",
         "benchmarks"]

_CSS = """
:root { --fg:#1a1f24; --bg:#ffffff; --accent:#0b63c5; --muted:#5a6572;
        --code-bg:#f4f6f8; --border:#dde3e9; }
* { box-sizing: border-box; }
body { margin:0; font:16px/1.65 system-ui,-apple-system,Segoe UI,sans-serif;
       color:var(--fg); background:var(--bg); display:flex; }
nav { width:230px; min-height:100vh; border-right:1px solid var(--border);
      padding:1.2rem .9rem; position:sticky; top:0; align-self:flex-start; }
nav h2 { font-size:.95rem; margin:.2rem 0 .8rem; }
nav a { display:block; color:var(--muted); text-decoration:none;
        padding:.22rem .5rem; border-radius:6px; font-size:.92rem; }
nav a:hover { background:var(--code-bg); }
nav a.active { color:var(--accent); font-weight:600; background:var(--code-bg); }
main { max-width:860px; padding:2rem 2.6rem 4rem; }
h1,h2,h3 { line-height:1.25; }
h1 { font-size:1.8rem; border-bottom:1px solid var(--border); padding-bottom:.4rem; }
a { color:var(--accent); }
code { background:var(--code-bg); border-radius:4px; padding:.12em .35em;
       font:.88em ui-monospace,Menlo,monospace; }
pre { background:var(--code-bg); border:1px solid var(--border);
      border-radius:8px; padding: .9rem 1.1rem; overflow-x:auto; }
pre code { background:none; padding:0; }
table { border-collapse:collapse; margin:1rem 0; font-size:.92rem; }
th,td { border:1px solid var(--border); padding:.4rem .7rem; text-align:left; }
th { background:var(--code-bg); }
blockquote { border-left:3px solid var(--accent); margin:.8rem 0;
             padding:.1rem 1rem; color:var(--muted); }
hr { border:none; border-top:1px solid var(--border); margin:2rem 0; }
"""


def _inline(s: str) -> str:
    s = html.escape(s, quote=False)
    s = re.sub(r"`([^`]+)`", r"<code>\1</code>", s)
    s = re.sub(r"\*\*([^*]+)\*\*", r"<strong>\1</strong>", s)
    s = re.sub(r"(?<![\w*])\*([^*]+)\*(?![\w*])", r"<em>\1</em>", s)
    s = re.sub(r"\[([^\]]+)\]\(([^)]+)\)",
               lambda m: f'<a href="{_rewrite_href(m.group(2))}">{m.group(1)}</a>', s)
    return s


def _rewrite_href(href: str) -> str:
    if href.endswith(".md") and "/" not in href:
        return href[:-3] + ".html"
    return href


def md_to_html(text: str) -> str:
    out: list[str] = []
    lines = text.splitlines()
    i = 0
    in_list = None  # "ul" | "ol"

    def close_list():
        nonlocal in_list
        if in_list:
            out.append(f"</{in_list}>")
            in_list = None

    while i < len(lines):
        line = lines[i]
        if line.startswith("```"):
            close_list()
            i += 1
            block = []
            while i < len(lines) and not lines[i].startswith("```"):
                block.append(lines[i])
                i += 1
            i += 1
            out.append("<pre><code>" + html.escape("\n".join(block))
                       + "</code></pre>")
            continue
        if re.match(r"^\|.*\|\s*$", line):
            close_list()
            rows = []
            while i < len(lines) and re.match(r"^\|.*\|\s*$", lines[i]):
                rows.append([c.strip() for c in lines[i].strip().strip("|").split("|")])
                i += 1
            out.append("<table>")
            header = True
            for r, cells in enumerate(rows):
                if all(re.fullmatch(r":?-{2,}:?", c) for c in cells):
                    continue  # separator row
                tag = "th" if header else "td"
                header = False
                out.append("<tr>" + "".join(
                    f"<{tag}>{_inline(c)}</{tag}>" for c in cells) + "</tr>")
            out.append("</table>")
            continue
        m = re.match(r"^(#{1,4})\s+(.*)", line)
        if m:
            close_list()
            lvl = len(m.group(1))
            out.append(f"<h{lvl}>{_inline(m.group(2))}</h{lvl}>")
            i += 1
            continue
        if re.match(r"^\s*([-*])\s+", line):
            if in_list != "ul":
                close_list()
                out.append("<ul>")
                in_list = "ul"
            item = [re.sub(r"^\s*[-*]\s+", "", line)]
            i += 1
            # continuation lines (indented)
            while i < len(lines) and re.match(r"^\s{2,}\S", lines[i]) \
                    and not re.match(r"^\s*[-*]\s+", lines[i]):
                item.append(lines[i].strip())
                i += 1
            out.append(f"<li>{_inline(' '.join(item))}</li>")
            continue
        if re.match(r"^\s*\d+\.\s+", line):
            if in_list != "ol":
                close_list()
                out.append("<ol>")
                in_list = "ol"
            item = [re.sub(r"^\s*\d+\.\s+", "", line)]
            i += 1
            while i < len(lines) and re.match(r"^\s{2,}\S", lines[i]) \
                    and not re.match(r"^\s*\d+\.\s+", lines[i]):
                item.append(lines[i].strip())
                i += 1
            out.append(f"<li>{_inline(' '.join(item))}</li>")
            continue
        if line.startswith(">"):
            close_list()
            quote = []
            while i < len(lines) and lines[i].startswith(">"):
                quote.append(lines[i].lstrip("> "))
                i += 1
            out.append(f"<blockquote>{_inline(' '.join(quote))}</blockquote>")
            continue
        if re.match(r"^\s*(---+|\*\*\*+)\s*$", line):
            close_list()
            out.append("<hr>")
            i += 1
            continue
        if not line.strip():
            close_list()
            i += 1
            continue
        # paragraph: greedily join consecutive text lines
        close_list()
        para = [line]
        i += 1
        while i < len(lines) and lines[i].strip() and not re.match(
                r"^(#{1,4}\s|```|\||\s*[-*]\s+|\s*\d+\.\s+|>|\s*---)", lines[i]):
            para.append(lines[i])
            i += 1
        out.append(f"<p>{_inline(' '.join(para))}</p>")
    close_list()
    return "\n".join(out)


def _title_of(md: str, fallback: str) -> str:
    for line in md.splitlines():
        m = re.match(r"^#\s+(.*)", line)
        if m:
            return m.group(1)
    return fallback


def build(out_dir: Path) -> list[Path]:
    out_dir.mkdir(parents=True, exist_ok=True)
    pages = {p.stem: p.read_text() for p in DOCS.glob("*.md")}
    order = [n for n in ORDER if n in pages] + sorted(
        n for n in pages if n not in ORDER)
    titles = {n: _title_of(pages[n], n.replace("-", " ").title())
              for n in order}
    written = []
    for name in order:
        nav = "\n".join(
            f'<a href="{n}.html"{" class=\"active\"" if n == name else ""}>'
            f"{html.escape(titles[n])}</a>" for n in order)
        body = md_to_html(pages[name])
        doc = f"""<!doctype html>
<html lang="en"><head><meta charset="utf-8">
<meta name="viewport" content="width=device-width, initial-scale=1">
<title>{html.escape(titles[name])} — NERRF-TPU</title>
<style>{_CSS}</style></head>
<body><nav><h2>NERRF-TPU</h2>{nav}</nav>
<main>{body}</main></body></html>
"""
        path = out_dir / f"{name}.html"
        path.write_text(doc)
        written.append(path)
    return written


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=str(DOCS / "site"))
    ap.add_argument("--check", action="store_true",
                    help="build into a temp dir and report (CI mode)")
    args = ap.parse_args(argv)
    if args.check:
        with tempfile.TemporaryDirectory() as tmp:
            pages = build(Path(tmp))
            print(f"docs site builds: {len(pages)} pages")
        return 0
    out = Path(args.out)
    if out.exists():
        shutil.rmtree(out)
    pages = build(out)
    print(f"wrote {len(pages)} pages to {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
