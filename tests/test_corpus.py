"""Disk-sharded 100 h-corpus machinery: generation, reader, shard-rotation
training (train/corpus.py + train/loop.py:train_sharded_stream)."""

import json

import numpy as np
import pytest

from nerrf_tpu.train.corpus import CorpusSpec, ShardedCorpus, generate_corpus
from nerrf_tpu.train.data import DatasetConfig
from nerrf_tpu.graph import GraphConfig

SMALL = DatasetConfig(
    graph=GraphConfig(window_sec=45.0, stride_sec=15.0,
                      max_nodes=64, max_edges=128),
    seq_len=30, max_seqs=32,
)


@pytest.fixture(scope="module")
def corpus_dir(tmp_path_factory):
    out = tmp_path_factory.mktemp("corpus")
    spec = CorpusSpec(hours=0.2, duration_sec=120.0, num_target_files=6,
                      benign_rate_hz=8.0, shard_windows=12,
                      eval_fraction=0.34)
    generate_corpus(out, spec, dataset=SMALL)
    return out


def test_generate_manifest_and_shards(corpus_dir):
    man = json.loads((corpus_dir / "manifest.json").read_text())
    assert man["complete"]
    assert man["hours"] == pytest.approx(0.2, abs=0.05)
    assert man["train_windows"] > 0 and man["eval_windows"] > 0
    # regeneration short-circuits (idempotent)
    man2 = generate_corpus(corpus_dir, CorpusSpec(hours=0.2))
    assert man2["train_windows"] == man["train_windows"]


def test_reader_dtypes_and_eval_split(corpus_dir):
    sc = ShardedCorpus(corpus_dir)
    assert sc.train_shards and sc.eval_shards
    raw = sc.load_shard(sc.train_shards[0])
    assert raw["node_feat"].dtype == np.float16  # wire/disk format
    assert raw["node_aux"].dtype.kind in "iu"    # embedding ids stay ints
    assert raw["node_mask"].dtype == np.bool_
    up = sc.load_shard(sc.train_shards[0], upcast=True)
    assert up["node_feat"].dtype == np.float32
    ev = sc.eval_dataset()
    assert len(ev) > 0
    assert ev.arrays["seq_feat"].dtype == np.float32


def test_shard_rotation_trains(corpus_dir):
    from nerrf_tpu.models import JointConfig
    from nerrf_tpu.train.loop import TrainConfig, train_sharded_stream

    sc = ShardedCorpus(corpus_dir)
    cfg = TrainConfig(model=JointConfig().small, batch_size=4, num_steps=10,
                      eval_every=0, seed=3)
    res = train_sharded_stream(sc, cfg, eval_ds=sc.eval_dataset(),
                               passes_per_shard=1)
    assert np.isfinite(res.metrics["edge_auc"])
    assert res.steps_per_sec > 0


def test_chunked_upload_matches_single_put(corpus_dir):
    """Chunked shard upload (upload_chunk_bytes) must be a pure transport
    change: slicing + on-device reassembly yields the same training
    trajectory as one whole-array device_put (review finding: the chunked
    branch was otherwise never exercised — every test shard is < 64 MB)."""
    from nerrf_tpu.models import JointConfig
    from nerrf_tpu.train.loop import TrainConfig, train_sharded_stream

    sc = ShardedCorpus(corpus_dir)
    cfg = TrainConfig(model=JointConfig().small, batch_size=4, num_steps=6,
                      eval_every=1, seed=5)
    whole = train_sharded_stream(sc, cfg, passes_per_shard=1)
    # 1 KB chunks force every array through the slice+concatenate path
    chunked = train_sharded_stream(sc, cfg, passes_per_shard=1,
                                   upload_chunk_bytes=1 << 10)
    w = [h["loss"] for h in whole.history]
    c = [h["loss"] for h in chunked.history]
    assert len(w) == len(c) > 0
    np.testing.assert_allclose(w, c, rtol=1e-6)


def test_reader_failure_propagates(corpus_dir, tmp_path):
    """A corrupt shard must fail the run, not hang it (review finding)."""
    import shutil

    from nerrf_tpu.models import JointConfig
    from nerrf_tpu.train.loop import TrainConfig, train_sharded_stream

    bad = tmp_path / "bad_corpus"
    shutil.copytree(corpus_dir, bad)
    for name in json.loads((bad / "manifest.json").read_text())["shards"]:
        if name["kind"] == "shard":
            (bad / name["name"] / "node_feat.npy").write_bytes(b"garbage")
    sc = ShardedCorpus(bad)
    cfg = TrainConfig(model=JointConfig().small, batch_size=4, num_steps=10,
                      eval_every=0)
    with pytest.raises(RuntimeError, match="shard read failed"):
        train_sharded_stream(sc, cfg)


def test_auto_fit_guarantees_zero_drops(corpus_dir):
    """The r3 contract: generation measures the densest window, sizes
    capacities up from the seed config, and records zero drops — the r2
    corpus silently truncated attack bursts at fixed 256n/512e."""
    man = json.loads((corpus_dir / "manifest.json").read_text())
    fit = man["auto_fit"]
    cap = man["graph_capacity"]
    assert man["dropped"] == {"events": 0, "nodes": 0, "edges": 0,
                              "windows": 0}
    assert cap["max_nodes"] >= fit["max_window_nodes"]
    assert cap["max_edges"] >= fit["max_window_edges"]
    # shard arrays really are at the fitted capacities
    shard = next(s["name"] for s in man["shards"] if s["kind"] == "shard")
    nf = np.load(corpus_dir / shard / "node_feat.npy", mmap_mode="r")
    assert nf.shape[1] == cap["max_nodes"]


def test_auto_fit_off_keeps_seed_capacities(tmp_path):
    """auto_fit=False must preserve the caller's exact capacities (the
    measuring pre-pass is skipped entirely)."""
    spec = CorpusSpec(hours=0.05, duration_sec=90.0, num_target_files=4,
                      benign_rate_hz=6.0, shard_windows=8,
                      eval_fraction=0.0, auto_fit=False)
    man = generate_corpus(tmp_path / "c", spec, dataset=SMALL)
    assert man["auto_fit"] is None
    assert man["graph_capacity"] == {"max_nodes": 64, "max_edges": 128}
