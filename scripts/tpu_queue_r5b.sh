#!/bin/bash
# Round-5 chip-queue CONTINUATION (4 steps: the original queue's steps
# 7-9 reordered, plus the step-6 m1-recovery rerun).  Steps 1-6 landed
# before the tunnel wedged at 18:22; the
# remaining chip work is re-ordered so the round's #1 deliverable — the
# clean bench.py line of record (MFU + 4096 leg) — runs FIRST in the next
# tunnel window instead of behind a ~40 min stream-eval.  Same probe gate
# and the same attempt log (/tmp/tpu_queue.log) so the tunnel-evidence
# chain stays in one file.
#
#   1/4. bench.py of record            → /tmp/bench_smoke.json
#   2/4. chip-gated compiled-kernel test → pallas_tpu.log
#   3/4. stream detector quality on chip → stream_probe_tpu.json
#   4/4. m1 recovery rerun (the mid-queue wedge degraded the committed
#        artifact's planner leg to CPU)  → m1_recovery.json
cd "$(dirname "$0")/.."
log() { echo "[queue $(date +%H:%M:%S)] $*" >> /tmp/tpu_queue.log; }
log "continuation watcher started (r5b: bench-first reorder)"
# same pre-flight as tpu_queue.sh: fail fast on static-analysis errors
# instead of burning the tunnel window
if ! python scripts/nerrflint.py > /tmp/nerrflint.log 2>&1; then
  log "PRE-FLIGHT FAIL: nerrflint found unbaselined findings (/tmp/nerrflint.log)"
  exit 1
fi
log "pre-flight: nerrflint clean"
# same deep pre-flight as tpu_queue.sh: program contracts proven on CPU
# (needs no accelerator, so it runs before the tunnel wait)
if ! timeout 120 python scripts/nerrflint.py --deep > /tmp/nerrflint_deep.log 2>&1; then
  log "PRE-FLIGHT FAIL: deep program-contract pass (/tmp/nerrflint_deep.log)"
  exit 1
fi
log "pre-flight: deep program contracts verified (closure/donation/sharding/pallas/cache-key)"
# same chaos pre-flight as tpu_queue.sh: survival gates proven on CPU
# before any tunnel time is spent (docs/chaos.md)
if ! timeout 560 env JAX_PLATFORMS=cpu python benchmarks/run_chaos_bench.py \
  --smoke > /tmp/chaos_smoke.json 2>> /tmp/tpu_queue.log
then
  log "PRE-FLIGHT FAIL: chaos smoke survival gates (/tmp/chaos_smoke.json)"
  exit 1
fi
log "pre-flight: chaos smoke survival gates pass"
# same quality pre-flight as tpu_queue.sh: the drift-injection gates
# proven on CPU before chip time (docs/quality.md)
if ! timeout 560 env JAX_PLATFORMS=cpu python benchmarks/run_quality_bench.py \
  --smoke > /tmp/quality_smoke.json 2>> /tmp/tpu_queue.log
then
  log "PRE-FLIGHT FAIL: quality drift-injection gates (/tmp/quality_smoke.json)"
  exit 1
fi
log "pre-flight: quality drift-injection gates pass"
# same trainwatch pre-flight as tpu_queue.sh: the injected-divergence
# gates proven on CPU before chip training relies on the divergence
# edge (docs/training-health.md)
if ! timeout 560 env JAX_PLATFORMS=cpu python benchmarks/run_train_health_bench.py \
  --smoke > /tmp/train_health_smoke.json 2>> /tmp/tpu_queue.log
then
  log "PRE-FLIGHT FAIL: trainwatch divergence gates (/tmp/train_health_smoke.json)"
  exit 1
fi
log "pre-flight: trainwatch divergence gates pass"
# same respond pre-flight as tpu_queue.sh: the detect→plan→verify loop
# proven on CPU before chip time (docs/response.md)
if ! timeout 560 env JAX_PLATFORMS=cpu python benchmarks/run_respond_bench.py \
  --smoke > /tmp/respond_smoke.json 2>> /tmp/tpu_queue.log
then
  log "PRE-FLIGHT FAIL: respond smoke gates (/tmp/respond_smoke.json)"
  exit 1
fi
log "pre-flight: respond smoke gates pass"
# same continuous-learning pre-flight as tpu_queue.sh: the closed
# drift→retrain→promote loop proven on CPU before chip time
# (docs/learning.md)
if ! timeout 900 env JAX_PLATFORMS=cpu python benchmarks/run_learn_bench.py \
  --smoke > /tmp/learn_smoke.json 2>> /tmp/tpu_queue.log
then
  log "PRE-FLIGHT FAIL: continuous-learning closed-loop gates (/tmp/learn_smoke.json)"
  exit 1
fi
log "pre-flight: continuous-learning closed-loop gates pass"
# same archive pre-flight as tpu_queue.sh: a short archived serve run,
# then the offline report must reconstruct it from segments alone
# (docs/archive.md)
rm -rf /tmp/archive_smoke
if ! { timeout 300 env JAX_PLATFORMS=cpu python -m nerrf_tpu.cli serve-detect \
    --trace datasets/traces/toy_trace.csv --no-probe --metrics-port -1 \
    --archive-dir /tmp/archive_smoke --buckets 256x512x128 --no-aot-cache \
    > /tmp/archive_serve.json 2>> /tmp/tpu_queue.log \
  && timeout 120 env JAX_PLATFORMS=cpu python -m nerrf_tpu.cli archive verify \
    /tmp/archive_smoke >> /tmp/tpu_queue.log 2>&1 \
  && timeout 120 env JAX_PLATFORMS=cpu python -m nerrf_tpu.cli report \
    /tmp/archive_smoke --json > /tmp/archive_report.json 2>> /tmp/tpu_queue.log \
  && python -c "
import json
r = json.load(open('/tmp/archive_report.json'))
assert r['span']['records'] > 0 and r['slo']['windows_scored'] > 0
" ; }
then
  log "PRE-FLIGHT FAIL: archive report gates (/tmp/archive_report.json)"
  exit 1
fi
log "pre-flight: archive report reconstructs the run offline"
# same tune pre-flight as tpu_queue.sh: fit a tuned ladder from the
# archived run above, boot it, require zero post-warmup recompiles
# (docs/tuning.md)
if ! { timeout 120 env JAX_PLATFORMS=cpu python -m nerrf_tpu.cli tune \
    /tmp/archive_smoke --out /tmp/tuned_smoke.json >> /tmp/tpu_queue.log 2>&1 \
  && timeout 300 env JAX_PLATFORMS=cpu python -m nerrf_tpu.cli serve-detect \
    --trace datasets/traces/toy_trace.csv --no-probe --metrics-port -1 \
    --tuned /tmp/tuned_smoke.json --no-aot-cache \
    > /tmp/tuned_serve.json 2>> /tmp/tpu_queue.log \
  && python -c "
import json
r = json.load(open('/tmp/tuned_serve.json'))
assert r['windows_scored'] > 0 and r['recompiles_after_warmup'] == 0
" ; }
then
  log "PRE-FLIGHT FAIL: tuned-ladder boot gates (/tmp/tuned_serve.json)"
  exit 1
fi
log "pre-flight: tuned-ladder boot scores windows, zero post-warmup recompiles"
# same archive-compare gate as tpu_queue.sh: the archived smoke run vs
# this host's banked artifact-of-record; regression fails the queue
# before tunnel time, a green gate re-banks the run (docs/fleet.md)
BASELINE="${NERRF_ARCHIVE_BASELINE:-/var/tmp/nerrf_archive_baseline}"
if ! timeout 120 env JAX_PLATFORMS=cpu python -m nerrf_tpu.cli report \
  --compare "$BASELINE" /tmp/archive_smoke --gate >> /tmp/tpu_queue.log 2>&1
then
  log "PRE-FLIGHT FAIL: archive-compare gate vs $BASELINE (/tmp/tpu_queue.log)"
  exit 1
fi
mkdir -p "$(dirname "$BASELINE")"
rm -rf "$BASELINE"
cp -r /tmp/archive_smoke "$BASELINE"
rm -rf /tmp/archive_smoke
log "pre-flight: archive-compare gate green (banked at $BASELINE)"
# same devtime pre-flight as tpu_queue.sh: the cost table must resolve
# on CPU with chip-relative columns null (docs/device-efficiency.md)
if ! timeout 300 env JAX_PLATFORMS=cpu python -m nerrf_tpu.cli profile costs \
  --smoke --no-probe --json > /tmp/devtime_smoke.json 2>> /tmp/tpu_queue.log
then
  log "PRE-FLIGHT FAIL: devtime cost table (/tmp/devtime_smoke.json)"
  exit 1
fi
log "pre-flight: devtime cost table resolves (chip-relative columns null on CPU)"
tpu_ok() {
  python -c "
import sys
from nerrf_tpu.utils import probe_backend
ok, detail, _ = probe_backend(timeout_sec=150)
sys.exit(0 if ok and detail.startswith('tpu') else 1)
" 2>/dev/null
}
wait_for_tpu() {
  local n=0
  while ! tpu_ok; do
    n=$((n + 1))
    log "tpu probe #$n failed (enumerate->compile->execute did not complete)"
    sleep 120
  done
  log "TPU is up (fresh compile path verified after $n failed probes)"
}
log "1/4 bench.py of record (MFU + 4096-bucket leg)"
wait_for_tpu
# same compile-cache pre-flight as tpu_queue.sh: one cold warm sweep,
# then the second must deserialize every ladder bucket (fail fast before
# the tunnel window is spent on redundant compiles)
timeout 2400 python -m nerrf_tpu.cli cache warm \
  > /tmp/cache_cold.json 2>> /tmp/tpu_queue.log
if ! timeout 600 python -m nerrf_tpu.cli cache warm --expect-cache \
  > /tmp/cache_warm.json 2>> /tmp/tpu_queue.log
then
  log "PRE-FLIGHT FAIL: compile-cache second sweep not source=cache for every bucket (/tmp/cache_warm.json)"
  exit 1
fi
log "pre-flight: compile cache round-trips (second sweep source=cache)"
# first chip-side MFU table (docs/device-efficiency.md) ahead of the
# bench: measured seconds/call + non-null MFU per serve bucket.
# Advisory — the table is evidence, not a gate.
timeout 1800 python -m nerrf_tpu.cli profile costs --measure 4 --no-probe \
  > /tmp/devtime_mfu.txt 2>> /tmp/tpu_queue.log \
  && log "devtime MFU table written (/tmp/devtime_mfu.txt)" \
  || log "devtime MFU table FAILED (advisory; /tmp/tpu_queue.log)"
timeout 3600 python bench.py > /tmp/bench_smoke.json 2> /tmp/bench_smoke.log
log "bench rc=$?"
log "2/4 chip-gated compiled-kernel test"
wait_for_tpu
NERRF_TEST_REAL_BACKEND=1 timeout 1200 python -m pytest \
  tests/test_pallas_ops.py -q -k compiled_on_tpu > /tmp/pallas_tpu.log 2>&1
log "pallas chip test rc=$?"
log "3/4 stream detector quality + calibration on chip"
wait_for_tpu
timeout 2400 python benchmarks/run_stream_eval.py --steps 1500 \
  --out benchmarks/results/stream_probe_tpu.json > /tmp/stream_tpu.log 2>&1
log "stream quality rc=$?"
log "4/4 m1 recovery rerun (device planner on chip)"
wait_for_tpu
timeout 1800 python benchmarks/run_recovery_bench.py --scale m1 \
  --out benchmarks/results/m1_recovery.json > /tmp/recovery_m1.log 2>&1
log "m1 recovery rc=$?"
log "continuation queue done"
