#!/usr/bin/env python3
"""Environment doctor: verify everything the framework needs, report clearly.

The runnable counterpart of the reference's 372-line distro-installer
(`/root/reference/tracker/scripts/install-deps.sh`): rather than mutating the
host, it *checks* — Python deps, JAX backend and device count, the native
toolchain, the built (or buildable) C++ libraries, protoc, and optional
capture/sandbox capabilities (BPF clang target, /dev/kvm + firecracker) —
and prints one line per requirement plus a machine-readable JSON summary.

Exit code 0 iff every REQUIRED row passes.

Check-only by default (native rows verify existing build artifacts); pass
``--build`` to compile the native libraries first.

Usage: python scripts/check_env.py [--json] [--build]
"""

from __future__ import annotations

import importlib
import json
import os
import shutil
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # the doctor runs from anywhere
    sys.path.insert(0, REPO)

REQUIRED_MODULES = ["jax", "flax", "optax", "orbax.checkpoint", "numpy",
                    "grpc", "google.protobuf"]
OPTIONAL_MODULES = ["torch", "pandas", "pyarrow", "yaml", "chex", "einops"]


def check(name, fn, required=True):
    try:
        detail = fn()
        return {"name": name, "ok": True, "required": required,
                "detail": str(detail or "")}
    except Exception as e:
        return {"name": name, "ok": False, "required": required,
                "detail": f"{type(e).__name__}: {e}"}


def _module(mod):
    def fn():
        m = importlib.import_module(mod)
        return getattr(m, "__version__", "present")
    return fn


def _jax_backend():
    # Probe in a bounded subprocess (shared helper — a dead accelerator
    # tunnel makes jax.devices() block forever in-process, and a doctor
    # that hangs is worse than a failing check).  The classifier separates
    # "relay process dead" from "relay alive but its compile service is
    # not" (the half-up state where enumeration answers and the first
    # workload compile wedges) — different operator actions.
    from nerrf_tpu.utils import classify_backend_state

    state, detail = classify_backend_state(timeout_sec=150)
    if state != "healthy":
        raise RuntimeError(
            f"accelerator {state}: {detail} — CPU fallback: "
            "jax.config.update('jax_platforms', 'cpu')")
    return detail


def _toolchain(tool):
    def fn():
        path = shutil.which(tool)
        if not path:
            raise FileNotFoundError(tool)
        return path
    return fn


_BUILD = "--build" in sys.argv

_NATIVE_LIBS = ("libnerrf_ingest.so", "libnerrf_tracestore.so",
                "libnerrf_fcdriver.so")


def _native_libs():
    """Check-only by default; --build compiles first (the rest of the repo
    also builds these on demand at first import)."""
    if _BUILD:
        out = subprocess.run(["make", "-s", "all"],
                             cwd=os.path.join(REPO, "native"),
                             capture_output=True, text=True, timeout=180)
        if out.returncode != 0:
            raise RuntimeError(out.stderr.strip()[-200:])
    build = os.path.join(REPO, "native", "build")
    missing = [l for l in _NATIVE_LIBS
               if not os.path.exists(os.path.join(build, l))]
    if missing:
        raise FileNotFoundError(
            f"{', '.join(missing)} (run `make -C native` or pass --build)")
    return ", ".join(_NATIVE_LIBS)


def _bpf_target():
    if _BUILD:
        out = subprocess.run(["make", "-s", "bpf"],
                             cwd=os.path.join(REPO, "native"),
                             capture_output=True, text=True, timeout=120)
        if out.returncode != 0:
            raise RuntimeError("clang BPF target unavailable (host capture only)")
    path = os.path.join(REPO, "native", "build", "tracepoints.o")
    if not os.path.exists(path):
        raise FileNotFoundError(
            "tracepoints.o not built (needs clang; `make -C native bpf`)")
    return "tracepoints.o"


def _kvm():
    if not os.path.exists("/dev/kvm"):
        raise FileNotFoundError("/dev/kvm (filesystem-clone sandbox will be used)")
    if shutil.which("firecracker") is None:
        raise FileNotFoundError("firecracker binary")
    return "microVM sandbox available"


def main() -> int:
    rows = []
    for mod in REQUIRED_MODULES:
        rows.append(check(f"python:{mod}", _module(mod)))
    for mod in OPTIONAL_MODULES:
        rows.append(check(f"python:{mod}", _module(mod), required=False))
    rows.append(check("jax:backend", _jax_backend))
    for tool in ("g++", "make"):
        rows.append(check(f"toolchain:{tool}", _toolchain(tool)))
    for tool in ("clang", "protoc", "cmake", "ninja"):
        rows.append(check(f"toolchain:{tool}", _toolchain(tool), required=False))
    rows.append(check("native:libraries", _native_libs))
    rows.append(check("native:bpf-target", _bpf_target, required=False))
    rows.append(check("sandbox:kvm+firecracker", _kvm, required=False))

    def _capture_probe():
        daemon = os.path.join(REPO, "native", "build", "nerrf-trackerd")
        if not os.path.exists(daemon):
            raise FileNotFoundError("nerrf-trackerd not built (make -C native)")
        r = subprocess.run([daemon, "--probe"], capture_output=True, text=True,
                           timeout=30)
        if r.returncode == 0:
            return "live kernel capture available"
        raise PermissionError(
            {2: "no CAP_BPF (replay mode still works)",
             3: "kernel support missing (replay mode still works)"}.get(
                r.returncode, f"probe rc={r.returncode}"))

    rows.append(check("capture:live-bpf", _capture_probe, required=False))

    ok = all(r["ok"] for r in rows if r["required"])
    if "--json" in sys.argv:
        print(json.dumps({"ok": ok, "checks": rows}, indent=2))
    else:
        for r in rows:
            mark = "ok " if r["ok"] else ("FAIL" if r["required"] else "skip")
            print(f"[{mark}] {r['name']:28s} {r['detail']}")
        print(f"\nenvironment {'OK' if ok else 'NOT OK'}")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
