"""Detection-quality plane (nerrf_tpu/quality): sketch/PSI maths, profile
roundtrip + merge associativity, serve-side monitor windowing and
null-not-fake, the flight recorder's sustained-drift trigger, the
doctor's drift section, the registry/checkpoint sidecar path, and the
synth drift knob's determinism contract."""

import json
import os

import numpy as np
import pytest

from nerrf_tpu.models import JointConfig
from nerrf_tpu.flight.journal import EventJournal
from nerrf_tpu.observability import MetricsRegistry
from nerrf_tpu.quality import (
    COUNT_EDGES,
    SCORE_EDGES,
    ProfileBuilder,
    QualityConfig,
    QualityMonitor,
    QualityProfile,
    Sketch,
    merge_profiles,
    psi,
)


def _profile(threshold=0.5, windows=120, seed=0, beta=(2, 5)):
    """A reference profile over a known synthetic score distribution."""
    rng = np.random.default_rng(seed)
    pb = ProfileBuilder(threshold)
    for _ in range(windows):
        probs = rng.beta(*beta, 48)
        mask = np.ones(48, bool)
        ntype = (rng.random(48) < 0.6).astype(np.int32)
        pb.observe_window(probs, mask, ntype,
                          nodes=int(40 + rng.integers(20)),
                          edges=int(80 + rng.integers(40)),
                          files=int(8 + rng.integers(4)))
    return pb.finish()


def _observe(mon, stream, rng, beta=(2, 5), nodes=50, alerted=True):
    probs = rng.beta(*beta, 48)
    mon.observe_window(stream, "256n", probs, np.ones(48, bool),
                       (rng.random(48) < 0.6).astype(np.int32),
                       nodes=nodes, edges=100, files=9, alerted=alerted)


# -- sketch + PSI maths -------------------------------------------------------


def test_sketch_observe_quantile_and_roundtrip():
    s = Sketch.empty(SCORE_EDGES)
    rng = np.random.default_rng(0)
    s.observe(rng.beta(2, 5, 4000))
    assert s.total == 4000
    q = s.quantiles()
    assert 0.15 <= q["p50"] <= 0.45
    assert q["p50"] <= q["p90"] <= q["p99"]
    back = Sketch.from_dict(json.loads(json.dumps(s.to_dict())))
    assert back.edges == s.edges
    assert (back.counts == s.counts).all()


def test_sketch_merge_is_associative_and_commutative():
    rng = np.random.default_rng(1)
    a, b, c = (Sketch.empty(SCORE_EDGES) for _ in range(3))
    a.observe(rng.beta(2, 5, 500))
    b.observe(rng.beta(5, 2, 500))
    c.observe(rng.uniform(0, 1, 500))
    left = (a.merge(b)).merge(c)
    right = a.merge(b.merge(c))
    assert (left.counts == right.counts).all()
    assert (a.merge(b).counts == b.merge(a).counts).all()
    with pytest.raises(ValueError, match="different bin ladders"):
        a.merge(Sketch.empty(COUNT_EDGES))


def test_psi_identical_vs_shifted_distributions():
    rng = np.random.default_rng(2)
    ref, same, shifted = (Sketch.empty(SCORE_EDGES) for _ in range(3))
    ref.observe(rng.beta(2, 5, 6000))
    same.observe(rng.beta(2, 5, 6000))
    shifted.observe(rng.beta(5, 2, 6000))
    assert psi(ref, same) < 0.05
    assert psi(ref, shifted) > 0.25
    # Laplace smoothing: a modest same-distribution sample must not read
    # as drift just because it misses rare reference bins.  (PSI's null
    # expectation scales like (bins-1)/n — ~0.06 at n=300 over 20 bins —
    # which is exactly why the monitor's min_scores evidence gate exists)
    small = Sketch.empty(SCORE_EDGES)
    small.observe(rng.beta(2, 5, 300))
    assert psi(ref, small) < 0.25


def test_sketch_bin_counts_subtraction_supports_exact_trailing():
    s = Sketch.empty(SCORE_EDGES)
    inc1 = s.observe([0.1, 0.2, 0.3])
    inc2 = s.observe([0.7, 0.8])
    s.sub_counts(inc1)
    only2 = Sketch.empty(SCORE_EDGES)
    only2.add_counts(inc2)
    assert (s.counts == only2.counts).all()


# -- profile ------------------------------------------------------------------


def test_profile_roundtrip_and_summary():
    p = _profile()
    back = QualityProfile.from_dict(json.loads(json.dumps(p.to_dict())))
    assert back.windows == p.windows
    assert back.threshold == p.threshold
    assert (back.score.counts == p.score.counts).all()
    assert set(back.features) == set(p.features)
    for k in p.features:
        assert (back.features[k].counts == p.features[k].counts).all()
    s = p.summary()
    assert s["windows"] == 120 and s["schema"] == 1
    # a profile stamped by a NEWER writer must refuse to load silently
    newer = dict(p.to_dict(), schema=99)
    with pytest.raises(ValueError, match="newer version"):
        QualityProfile.from_dict(newer)


def test_profile_merge_is_associative_and_gates_operating_point():
    a, b, c = _profile(seed=1), _profile(seed=2), _profile(seed=3)
    left, right = merge_profiles(merge_profiles(a, b), c), \
        merge_profiles(a, merge_profiles(b, c))
    assert left.windows == right.windows == a.windows * 3
    assert (left.score.counts == right.score.counts).all()
    for k in left.features:
        assert (left.features[k].counts == right.features[k].counts).all()
    assert abs(left.margin_mass
               - np.mean([a.margin_mass, b.margin_mass, c.margin_mass])) \
        < 1e-9
    with pytest.raises(ValueError, match="different operating points"):
        merge_profiles(a, _profile(threshold=0.7, seed=4))


def test_checkpoint_quality_profile_sidecar_roundtrip(tmp_path):
    from nerrf_tpu.train.checkpoint import (
        load_quality_profile,
        save_checkpoint,
    )

    params = {"dense": {"w": np.full((2, 2), 0.5, np.float32)}}
    path = tmp_path / "ckpt"
    prof = _profile()
    save_checkpoint(path, params, JointConfig().small,
                    calibration={"node_threshold": 0.42},
                    quality_profile=prof.to_dict())
    got = load_quality_profile(path)
    assert got is not None
    assert QualityProfile.from_dict(got).windows == prof.windows
    # a checkpoint saved WITHOUT a profile reads None (null-not-fake)
    bare = tmp_path / "bare"
    save_checkpoint(bare, params, JointConfig().small)
    assert load_quality_profile(bare) is None
    # corrupt sidecar: one-line actionable error
    from nerrf_tpu.quality import PROFILE_FILENAME

    (path / PROFILE_FILENAME).write_text("{nope")
    with pytest.raises(ValueError, match="corrupt quality profile"):
        load_quality_profile(path)


# -- monitor ------------------------------------------------------------------


def test_monitor_null_not_fake_without_reference():
    reg = MetricsRegistry(namespace="t")
    jrn = EventJournal(registry=reg)
    mon = QualityMonitor(QualityConfig(min_windows=2, min_scores=10,
                                       journal_every=2),
                         registry=reg, journal=jrn)
    rng = np.random.default_rng(0)
    for _ in range(8):
        _observe(mon, "s0", rng)
    assert "quality_" not in reg.render()
    assert jrn.tail(kinds=("quality_stats",)) == []
    assert mon.snapshot() is None


def test_monitor_exports_and_journals_with_reference():
    reg = MetricsRegistry(namespace="t")
    jrn = EventJournal(registry=reg)
    mon = QualityMonitor(QualityConfig(min_windows=4, min_scores=100,
                                       journal_every=4),
                         registry=reg, journal=jrn)
    mon.set_reference(_profile(), version=3)
    rng = np.random.default_rng(1)
    for _ in range(12):
        _observe(mon, "s0", rng, beta=(5, 2))  # shifted scores
    assert reg.value("quality_score_psi",
                     labels={"stream": "s0"}) > 0.25
    assert reg.value("quality_feature_psi",
                     labels={"feature": "nodes"}) >= 0.0
    assert reg.value("quality_calibration_margin_mass") >= 0.0
    recs = jrn.tail(kinds=("quality_stats",))
    assert recs and recs[-1].data["version"] == "v3"
    assert recs[-1].data["worst_score_psi"] > 0.25
    assert recs[-1].data["worst_stream"] == "s0"
    snap = mon.snapshot()
    assert snap["per_stream"]["s0"]["score_psi"] > 0.25
    assert snap["reference"]["windows"] == 120


def test_monitor_trailing_window_evicts_exactly():
    mon = QualityMonitor(QualityConfig(trailing_windows=4, min_windows=2,
                                       min_scores=10, journal_every=100),
                         registry=MetricsRegistry(namespace="t"),
                         journal=EventJournal())
    mon.set_reference(_profile())
    rng = np.random.default_rng(2)
    for _ in range(10):
        _observe(mon, "s0", rng)
    snap = mon.snapshot()
    st = snap["per_stream"]["s0"]
    assert st["windows"] == 4          # trailing cap, not all 10
    assert st["observed"] == 10        # all-time count kept separately
    assert st["scores"] == 4 * 48      # sketch holds exactly the tail
    assert sum(st["score_sketch"]["counts"]) == 4 * 48


def test_monitor_evidence_gate_blocks_early_psi():
    reg = MetricsRegistry(namespace="t")
    mon = QualityMonitor(QualityConfig(min_windows=8, min_scores=300,
                                       journal_every=100),
                         registry=reg, journal=EventJournal())
    mon.set_reference(_profile())
    rng = np.random.default_rng(3)
    for _ in range(4):  # below min_windows
        _observe(mon, "s0", rng, beta=(5, 2))
    assert "quality_score_psi" not in reg.render()


def test_monitor_alert_rate_z_and_reference_clear():
    reg = MetricsRegistry(namespace="t")
    mon = QualityMonitor(QualityConfig(min_windows=4, min_scores=50,
                                       journal_every=100),
                         registry=reg, journal=EventJournal())
    # reference with a LOW alert rate: every live window alerting must
    # push the z-score far positive
    ref = _profile(threshold=0.97)
    mon.set_reference(ref)
    rng = np.random.default_rng(4)
    for _ in range(8):
        _observe(mon, "s0", rng, alerted=True)
    assert reg.value("quality_alert_rate_z", labels={"stream": "s0"}) > 3.0
    # clearing the reference retires every quality series (a profile-less
    # version must export NOTHING, not stale numbers; the registry keeps
    # the bare TYPE/HELP header, which carries no data)
    mon.set_reference(None)
    rendered = reg.render()
    assert "quality_alert_rate_z{" not in rendered
    assert "quality_score_psi{" not in rendered
    assert "\nt_quality_calibration_margin_mass " not in rendered
    assert mon.snapshot() is None


def test_monitor_lru_stream_cap_retires_series():
    reg = MetricsRegistry(namespace="t")
    mon = QualityMonitor(QualityConfig(max_streams=2, min_windows=2,
                                       min_scores=10, journal_every=100),
                         registry=reg, journal=EventJournal())
    mon.set_reference(_profile())
    rng = np.random.default_rng(5)
    for stream in ("s0", "s1", "s2"):
        for _ in range(4):
            _observe(mon, stream, rng)
    snap = mon.snapshot()
    assert set(snap["per_stream"]) == {"s1", "s2"}
    rendered = reg.render()
    assert 'stream="s0"' not in rendered


# -- flight trigger -----------------------------------------------------------


def _recorder(tmp_path, journal, registry, quality=None, breach=0.25,
              min_windows=10, records=2):
    from nerrf_tpu.flight import FlightConfig, FlightRecorder

    return FlightRecorder(
        FlightConfig(out_dir=str(tmp_path / "bundles"),
                     quality_psi_breach=breach,
                     quality_min_windows=min_windows,
                     quality_breach_records=records,
                     min_interval_sec=3600.0),
        registry=registry, journal=journal, quality=quality)


def _bundles(tmp_path):
    d = tmp_path / "bundles"
    return sorted(p for p in (os.listdir(d) if d.is_dir() else [])
                  if p.startswith("bundle-"))


def test_quality_drift_trigger_fires_exactly_once(tmp_path):
    reg = MetricsRegistry(namespace="t")
    jrn = EventJournal(registry=reg)
    snapshot = {"version": "v1", "per_stream": {}, "features": {},
                "reference": _profile().to_dict()}
    rec = _recorder(tmp_path, jrn, reg, quality=lambda: snapshot)
    try:
        # sustained breach: every cadence record hot → exactly ONE
        # bundle (streak fires at 2 consecutive, later streaks are
        # rate-limited)
        for i in range(6):
            jrn.record("quality_stats", windows=20 + i,
                       worst_score_psi=0.9, worst_feature_psi=0.4)
        names = _bundles(tmp_path)
        assert len(names) == 1
        assert names[0].endswith("quality_drift")
        assert reg.value("flight_triggers_suppressed_total",
                         labels={"trigger": "quality_drift"}) >= 1
        # the bundle embeds the quality snapshot (both sketch sets)
        from nerrf_tpu.flight.doctor import read_bundle

        b = read_bundle(tmp_path / "bundles" / names[0])
        assert b["quality"]["version"] == "v1"
        assert b["quality"]["reference"]["windows"] == 120
        assert b["manifest"]["quality"] == "quality.json"
    finally:
        rec.close()


def test_quality_drift_trigger_negatives(tmp_path):
    reg = MetricsRegistry(namespace="t")
    jrn = EventJournal(registry=reg)
    rec = _recorder(tmp_path, jrn, reg)
    try:
        # below threshold: never fires
        for i in range(6):
            jrn.record("quality_stats", windows=50, worst_score_psi=0.1,
                       worst_feature_psi=0.2)
        # hot but under the min-window evidence gate: never fires
        for i in range(6):
            jrn.record("quality_stats", windows=5, worst_score_psi=2.0)
        # hot records that never run CONSECUTIVELY: streak resets
        for i in range(6):
            jrn.record("quality_stats", windows=50,
                       worst_score_psi=(2.0 if i % 2 == 0 else 0.05))
        # None PSIs (monitor before any stream clears its gates)
        jrn.record("quality_stats", windows=50, worst_score_psi=None,
                   worst_feature_psi=None)
        assert _bundles(tmp_path) == []
    finally:
        rec.close()


# -- doctor -------------------------------------------------------------------


def test_doctor_drift_section_on_partial_bundle(tmp_path):
    from nerrf_tpu.flight.doctor import format_report, read_bundle

    # a torn bundle: manifest + quality.json only (crash mid-dump)
    b = tmp_path / "bundle-x"
    b.mkdir()
    (b / "manifest.json").write_text(json.dumps(
        {"schema": 1, "trigger": "quality_drift", "reason": "test",
         "created_unix": 0, "quality": "quality.json"}))
    ref = _profile()
    (b / "quality.json").write_text(json.dumps({
        "version": "v2", "windows_observed": 64, "margin_mass": 0.31,
        "per_stream": {"s0": {"windows": 32, "scores": 1500,
                              "score_psi": 0.61, "alert_rate_z": 4.2,
                              "score_quantiles": {"p50": 0.6, "p90": 0.8,
                                                  "p99": 0.9},
                              "score_sketch": ref.score.to_dict()}},
        "features": {"nodes": {"psi": 1.3,
                               "sketch": ref.features["nodes"].to_dict()}},
        "reference": ref.to_dict()}))
    bundle = read_bundle(b)
    assert set(bundle["missing"]) == {"journal.jsonl", "trace.json",
                                      "metrics.prom"}
    report = format_report(bundle)
    assert "detection quality (drift vs reference profile" in report
    assert "s0" in report and "0.61" in report
    assert "top drifting features: nodes=1.3" in report
    assert "MISSING from bundle" in report


def test_doctor_degrades_without_quality_json(tmp_path):
    from nerrf_tpu.flight.doctor import format_report, read_bundle

    b = tmp_path / "bundle-y"
    b.mkdir()
    (b / "manifest.json").write_text(json.dumps(
        {"schema": 1, "trigger": "p99_breach", "created_unix": 0}))
    report = format_report(read_bundle(b))
    assert "detection quality: no quality.json" in report


# -- registry + manager -------------------------------------------------------


def test_store_publishes_and_reads_quality_profile(tmp_path):
    from nerrf_tpu.registry import ModelRegistry
    from nerrf_tpu.train.checkpoint import save_checkpoint

    params = {"dense": {"w": np.full((2, 2), 0.5, np.float32)}}
    ck = tmp_path / "ck"
    save_checkpoint(ck, params, JointConfig().small,
                    quality_profile=_profile().to_dict())
    store = ModelRegistry(tmp_path / "reg", journal=EventJournal())
    v = store.publish("lin", ck)
    got = store.quality_profile("lin", v)
    assert got is not None and got["windows"] == 120
    status = store.status("lin")
    assert status["versions"][0]["quality_profile"] is True
    # a profile-less version reads None, and status says so
    bare = tmp_path / "bare"
    save_checkpoint(bare, params, JointConfig().small)
    v2 = store.publish("lin", bare)
    assert store.quality_profile("lin", v2) is None
    assert store.status("lin")["versions"][1]["quality_profile"] is False


def test_manager_pushes_profile_on_attach_and_swap(tmp_path):
    from nerrf_tpu.registry import ModelManager, ModelRegistry, RegistryConfig
    from nerrf_tpu.train.checkpoint import save_checkpoint

    params = {"dense": {"w": np.full((2, 2), 0.5, np.float32)}}
    ck = tmp_path / "ck"
    save_checkpoint(ck, params, JointConfig().small,
                    quality_profile=_profile().to_dict())
    store = ModelRegistry(tmp_path / "reg", journal=EventJournal())
    store.publish("lin", ck)
    store.promote("lin", 1)

    class _Svc:
        model_config = None

        def __init__(self):
            import threading

            self.pushed = []
            self._live_version = None
            self._swap_lock = threading.Lock()

        @property
        def live_version(self):
            return self._live_version

        def attach_manager(self, m):
            pass

        def set_quality_profile(self, profile, version=None):
            self.pushed.append((version,
                                profile["windows"] if profile else None))

        def swap_params(self, params, version=None, threshold=None):
            self._live_version = version

        def stop_shadow(self):
            pass

    svc = _Svc()
    mgr = ModelManager(store, "lin", cfg=RegistryConfig(auto_promote=False),
                       registry=MetricsRegistry(namespace="t"),
                       journal=EventJournal())
    mgr.boot()
    mgr.attach(svc)
    assert svc.pushed == [(1, 120)]
    # publish v2 WITHOUT a profile, promote it: the push must clear the
    # baseline (None), never leave v1's reference comparing v2's traffic
    bare = tmp_path / "bare"
    save_checkpoint(bare, params, JointConfig().small)
    store.publish("lin", bare)
    store.promote("lin", 2)
    mgr.poll()
    assert svc.pushed[-1] == (2, None)


def test_shadow_stats_snapshot_carries_score_quantiles():
    from nerrf_tpu.registry.guardrails import ShadowStats

    stats = ShadowStats(threshold=0.5)
    rng = np.random.default_rng(0)
    for _ in range(16):
        live = rng.beta(2, 5, 32)
        stats.observe(live, np.clip(live + 0.3, 0, 1), np.ones(32, bool))
    snap = stats.snapshot()
    lq, sq = snap["live_score_quantiles"], snap["shadow_score_quantiles"]
    assert lq["p50"] is not None and sq["p50"] is not None
    assert sq["p50"] > lq["p50"]  # the shadow's shifted tail is visible


# -- serve integration + alert counter ---------------------------------------


def test_service_demux_feeds_monitor_and_counts_alerts():
    from conftest import make_service_shell

    from nerrf_tpu.serve import ServeConfig
    from nerrf_tpu.serve.batcher import ScoredWindow

    cfg = ServeConfig(buckets=((16, 32, 8),), threshold=0.5)
    svc, reg = make_service_shell(cfg)
    mon = QualityMonitor(QualityConfig(min_windows=2, min_scores=20,
                                       journal_every=2),
                         registry=reg, journal=svc._journal)
    mon.set_reference(_profile())
    svc._quality = mon
    rng = np.random.default_rng(0)
    for i in range(6):
        probs = np.clip(rng.beta(5, 2, 16), 0, 1)
        svc._on_scored([ScoredWindow(
            stream="s0#3", window_idx=i, lo_ns=0, hi_ns=1,
            bucket=(16, 32, 8), probs=probs,
            node_type=np.zeros(16, np.int32),
            node_key=np.arange(16, dtype=np.int64),
            node_mask=np.ones(16, bool), t_admit=0.0, t_scored=0.0,
            late=False, nodes=12, edges=20, files=4)])
    # the monitor keyed on the BASE stream name, not the session name
    snap = mon.snapshot()
    assert list(snap["per_stream"]) == ["s0"]
    assert snap["per_stream"]["s0"]["windows"] == 6
    # the emitted-alert counter (satellite): base-stream labeled, one per
    # hot window — the contract-checked alert-rate numerator
    assert reg.value("serve_alerts_emitted_total",
                     labels={"stream": "s0"}) == 6


def test_batcher_carries_measured_window_structure():
    import queue as queue_mod

    from nerrf_tpu.serve import MicroBatcher, ServeConfig
    from nerrf_tpu.serve.batcher import WindowRequest

    got: "queue_mod.Queue" = queue_mod.Queue()
    cfg = ServeConfig(buckets=((4, 4, 1),), batch_size=2,
                      devtime_accounting=False)
    b = MicroBatcher(
        score_fn=lambda batch: np.zeros((2, 4), np.float32), cfg=cfg,
        registry=MetricsRegistry(namespace="t"),
        journal=EventJournal(),
        on_scored=lambda scored: [got.put(s) for s in scored])
    b.mark_warm((4, 4, 1))
    sample = {"node_mask": np.ones(4, bool),
              "node_type": np.zeros(4, np.int32),
              "node_key": np.zeros(4, np.int64)}
    now = 0.0
    for i in range(2):
        b.submit(WindowRequest(
            stream="s", window_idx=i, lo_ns=0, hi_ns=1, bucket=(4, 4, 1),
            sample=dict(sample), t_admit=now, deadline=now + 60,
            nodes=3 + i, edges=7, files=2))
    b.drain_once(force=True)
    for i in range(2):
        s = got.get(timeout=5)
        assert (s.nodes, s.edges, s.files) == (3 + s.window_idx, 7, 2)


# -- synth drift knob ---------------------------------------------------------


def test_synth_drift_zero_is_bit_identical_and_shift_shifts():
    from nerrf_tpu.data.synth import SimConfig, simulate_trace

    base = simulate_trace(SimConfig(duration_sec=30.0, seed=11,
                                    attack=False))
    again = simulate_trace(SimConfig(duration_sec=30.0, seed=11,
                                     attack=False, drift=0.0))
    for field in ("ts_ns", "syscall", "pid", "path_id", "bytes_count"):
        a, b = getattr(base.events, field, None), \
            getattr(again.events, field, None)
        if a is not None:
            assert (np.asarray(a) == np.asarray(b)).all()
    shifted = simulate_trace(SimConfig(duration_sec=30.0, seed=11,
                                       attack=False, drift=0.8))
    # the benign rate scales ~1.8x, the mix moves toward IO-heavy services
    assert shifted.events.num_valid > 1.5 * base.events.num_valid
    # the attack stream is untouched by drift: same labels semantics
    atk = simulate_trace(SimConfig(duration_sec=30.0, seed=11, attack=True,
                                   attack_start_sec=10.0, drift=0.8))
    assert atk.labels.sum() > 0


# -- the checked-in artifact of record ---------------------------------------


def test_checked_in_quality_artifact_meets_acceptance(repo_root):
    import sys

    sys.path.insert(0, str(repo_root / "benchmarks"))
    from run_quality_bench import gates

    art = json.loads((repo_root / "benchmarks" / "results" /
                      "quality_bench_cpu.json").read_text())
    failed = [name for name, ok in gates(art) if not ok]
    assert failed == []
    # the headline numbers behind the gates stay visible here: shifted
    # traffic drifts decisively, unshifted stays comfortably below
    assert art["shifted"]["worst_feature_psi"] > 1.0
    assert art["unshifted"]["worst_score_psi"] < 0.1
    assert art["reference"]["windows"] >= 100


@pytest.mark.slow
def test_quality_bench_smoke_live(repo_root):
    """The full drift-injection harness, live (slow: compiles the serve
    bucket + scores two legs through the wire path)."""
    import sys

    sys.path.insert(0, str(repo_root / "benchmarks"))
    from run_quality_bench import gates, run

    res = run(smoke=True, log=None)
    assert [name for name, ok in gates(res) if not ok] == []
