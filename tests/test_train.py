"""End-to-end slice: corpus → graphs+sequences → joint training → quality gates.

Mirrors the reference's specified CI gate (ROC-AUC ≥ 0.90 for the GNN edge
classifier, ROADMAP.md:26,69) at test scale: a small model on a small synthetic
corpus.  The full-size model only changes widths/depths, not code paths.
"""

import dataclasses

import numpy as np
import pytest

from nerrf_tpu.data import make_corpus
from nerrf_tpu.graph import GraphConfig
from nerrf_tpu.models import GraphSAGEConfig, JointConfig, LSTMConfig
from nerrf_tpu.train import TrainConfig, build_dataset, train_nerrfnet
from nerrf_tpu.train.data import DatasetConfig
from nerrf_tpu.train.metrics import best_f1, f1_score, roc_auc


def test_roc_auc_metric():
    labels = np.array([0, 0, 1, 1])
    assert roc_auc(labels, np.array([0.1, 0.2, 0.8, 0.9])) == 1.0
    assert roc_auc(labels, np.array([0.9, 0.8, 0.2, 0.1])) == 0.0
    assert roc_auc(labels, np.array([0.5, 0.5, 0.5, 0.5])) == 0.5
    assert roc_auc(np.zeros(4), np.arange(4)) == 0.5  # degenerate
    # ties get midranks
    assert abs(roc_auc(np.array([0, 1, 1]), np.array([0.5, 0.5, 0.9])) - 0.75) < 1e-9


def test_f1_metrics():
    labels = np.array([1, 1, 0, 0])
    assert f1_score(labels, np.array([1, 1, 0, 0])) == 1.0
    assert f1_score(labels, np.array([0, 0, 0, 0])) == 0.0
    f1, t = best_f1(labels, np.array([0.9, 0.8, 0.1, 0.2]))
    assert f1 == 1.0 and 0.2 <= t <= 0.8


_DS_CFG = DatasetConfig(
    graph=GraphConfig(window_sec=45.0, stride_sec=25.0, max_nodes=64, max_edges=128),
    seq_len=24, max_seqs=32,
)


@pytest.fixture(scope="module")
def small_dataset():
    corpus = make_corpus(6, attack_fraction=0.5, base_seed=21, duration_sec=120.0,
                         num_target_files=6, benign_rate_hz=25.0)
    return build_dataset(corpus, _DS_CFG)


def test_dataset_assembly(small_dataset):
    ds = small_dataset
    assert len(ds) >= 12
    a = ds.arrays
    assert a["node_feat"].shape[1:] == (64, a["node_feat"].shape[-1])
    assert a["seq_feat"].shape[1:3] == (32, 24)
    # routing: every routed sequence points at a valid file node slot
    for b in range(len(ds)):
        sni = a["seq_node_idx"][b]
        ok = sni >= 0
        assert np.all(a["node_mask"][b][sni[ok]])
    # both classes present across the dataset
    assert a["edge_label"].max() == 1.0
    assert (a["edge_label"][a["edge_mask"]] == 0).any()
    tr, ev = ds.split(0.3, seed=4)
    assert len(tr) + len(ev) == len(ds) and len(ev) >= 3


@pytest.mark.slow
def test_train_end_to_end_quality_gate():
    """Held-out-trace generalization: train on 9 runs, evaluate on 3 unseen
    runs.  Gates: GNN edge ROC-AUC ≥ 0.90 (ROADMAP.md:26,69) and LSTM
    F1 ≥ 0.95 (architecture.mdx:59), at test scale."""
    corpus = make_corpus(12, attack_fraction=0.5, base_seed=21, duration_sec=150.0,
                         num_target_files=8, benign_rate_hz=25.0)
    train_ds = build_dataset(corpus[:9], _DS_CFG)
    eval_ds = build_dataset(corpus[9:], _DS_CFG)
    # both splits must contain both classes for the gate to mean anything
    for d in (train_ds, eval_ds):
        el, em = d.arrays["edge_label"], d.arrays["edge_mask"]
        assert el[em].sum() > 0 and (el[em] == 0).any()
    cfg = TrainConfig(
        model=JointConfig(
            gnn=GraphSAGEConfig(hidden=32, num_layers=3, dropout=0.05),
            lstm=LSTMConfig(hidden=32, num_layers=1, dropout=0.05),
        ),
        batch_size=8,
        num_steps=300,
        learning_rate=3e-3,
        warmup_steps=30,
        eval_every=100,
    )
    result = train_nerrfnet(train_ds, eval_ds, cfg, log=print)
    m = result.metrics
    print("metrics:", m, "steps/s:", result.steps_per_sec)
    assert m["edge_auc"] >= 0.90, m
    assert m["seq_auc"] >= 0.90, m
    assert m["seq_f1"] >= 0.95, m
    assert m["node_f1"] >= 0.90, m
    assert result.steps_per_sec > 0.5


def test_threshold_at_precision():
    """KPI-aligned calibrator: max recall subject to a precision floor,
    cut centered in the local score gap (not on a cluster edge)."""
    import numpy as np

    from nerrf_tpu.train.metrics import threshold_at_precision

    # 6 positives at 0.99, a dense benign cluster at ~0.80, rest at ~0.1
    labels = np.array([1] * 6 + [0] * 6 + [0] * 10)
    scores = np.array([0.99] * 6 + [0.80, 0.801, 0.802, 0.803, 0.80, 0.799]
                      + [0.1] * 10)
    t = threshold_at_precision(labels, scores, target=0.98)
    # only the positives may flag: the cut must sit between the benign
    # cluster top (0.803) and the positive cluster (0.99) — centered
    assert 0.803 < t < 0.99
    assert t == (0.99 + 0.803) / 2

    # unreachable floor (positives fully under the negatives) → None
    assert threshold_at_precision(
        np.array([1, 0]), np.array([0.2, 0.9]), target=0.98) is None

    # degenerate: no positives → None
    assert threshold_at_precision(
        np.array([0, 0]), np.array([0.2, 0.9])) is None


def test_checkpoint_calibration_roundtrip(tmp_path):
    """The held-out-calibrated operating point travels with the weights and
    reaches the detector: save → load_calibration → DetectionResult
    threshold semantics (a checkpoint predating calibration yields {})."""
    import numpy as np

    from nerrf_tpu.config import JointConfig  # noqa: F401 (re-export check)
    from nerrf_tpu.models import GraphSAGEConfig, LSTMConfig
    from nerrf_tpu.models import JointConfig as JC
    from nerrf_tpu.pipeline import DetectionResult
    from nerrf_tpu.train.checkpoint import (
        load_calibration,
        load_checkpoint,
        save_checkpoint,
    )

    cfg = JC(gnn=GraphSAGEConfig(hidden=8, num_layers=1),
             lstm=LSTMConfig(hidden=8, num_layers=1))
    params = {"w": np.ones((2, 2), np.float32)}
    save_checkpoint(tmp_path / "m", params, cfg,
                    calibration={"node_threshold": 0.9})
    assert load_calibration(tmp_path / "m") == {"node_threshold": 0.9}
    p2, cfg2 = load_checkpoint(tmp_path / "m")
    assert cfg2.gnn.hidden == 8

    save_checkpoint(tmp_path / "m0", params, cfg)
    assert load_calibration(tmp_path / "m0") == {}

    # threshold semantics: the result's own operating point gates
    # flagged_files; an explicit argument still overrides
    det = DetectionResult({"/a": 0.95, "/b": 0.8}, {}, {}, threshold=0.9)
    assert set(det.flagged_files()) == {"/a"}
    assert set(det.flagged_files(0.5)) == {"/a", "/b"}


def test_checkpoint_feature_layout_gate(tmp_path):
    """NODE_FEATURE_DIM moved 22→24 in r4 and a stale checkpoint only failed
    at apply time with an opaque Flax shape error (r4 advisor, medium): the
    sidecar now records the feature layout and load_checkpoint fails FAST
    with a clear retrain message on mismatch or on an unstamped sidecar."""
    import json

    import numpy as np
    import pytest

    from nerrf_tpu.models import GraphSAGEConfig, LSTMConfig
    from nerrf_tpu.models import JointConfig as JC
    from nerrf_tpu.train.checkpoint import load_checkpoint, save_checkpoint

    cfg = JC(gnn=GraphSAGEConfig(hidden=8, num_layers=1),
             lstm=LSTMConfig(hidden=8, num_layers=1))
    params = {"w": np.ones((2, 2), np.float32)}
    save_checkpoint(tmp_path / "m", params, cfg)
    sidecar = tmp_path / "m" / "model_config.json"
    meta = json.loads(sidecar.read_text())
    assert meta["features"]["node"] == 24  # current layout stamped

    load_checkpoint(tmp_path / "m")  # current layout loads fine

    meta["features"]["node"] = 22  # a pre-r4 checkpoint's layout
    sidecar.write_text(json.dumps(meta))
    with pytest.raises(ValueError, match="retrain: feature layout changed"):
        load_checkpoint(tmp_path / "m")

    del meta["features"]  # a checkpoint predating the versioned sidecar
    sidecar.write_text(json.dumps(meta))
    with pytest.raises(ValueError, match="predates feature-layout"):
        load_checkpoint(tmp_path / "m")


def test_checkpoint_schema_version_gate(tmp_path):
    """The sidecar carries an explicit schema version: current checkpoints
    stamp it and round-trip; a sidecar from NEWER code fails fast instead of
    loading fields it cannot interpret; aggregation='fused' (same param
    tree as segment/dense_adj) round-trips through the config sidecar."""
    import dataclasses
    import json

    import numpy as np
    import pytest

    from nerrf_tpu.models import GraphSAGEConfig, LSTMConfig
    from nerrf_tpu.models import JointConfig as JC
    from nerrf_tpu.train.checkpoint import (
        SCHEMA_VERSION,
        load_checkpoint,
        save_checkpoint,
    )

    cfg = JC(gnn=GraphSAGEConfig(hidden=8, num_layers=1,
                                 aggregation="fused"),
             lstm=LSTMConfig(hidden=8, num_layers=1))
    params = {"w": np.ones((2, 2), np.float32)}
    save_checkpoint(tmp_path / "m", params, cfg)
    sidecar = tmp_path / "m" / "model_config.json"
    meta = json.loads(sidecar.read_text())
    assert meta["schema_version"] == SCHEMA_VERSION

    _, cfg2 = load_checkpoint(tmp_path / "m")
    assert cfg2.gnn.aggregation == "fused"

    meta["schema_version"] = SCHEMA_VERSION + 1
    sidecar.write_text(json.dumps(meta))
    with pytest.raises(ValueError, match="newer version"):
        load_checkpoint(tmp_path / "m")

    from nerrf_tpu.train.checkpoint import MIN_SCHEMA_VERSION

    meta["schema_version"] = MIN_SCHEMA_VERSION - 1
    sidecar.write_text(json.dumps(meta))
    with pytest.raises(ValueError, match="oldest supported"):
        load_checkpoint(tmp_path / "m")


def test_stream_checkpoint_threshold_space_stamped(tmp_path):
    """The stream sidecar records which space the calibrated cut lives in
    (raw logits — r4 advisor): stamped by default even when the caller's
    calibration dict omits it, and a caller-provided value wins."""
    import json

    import numpy as np

    from nerrf_tpu.models import StreamConfig
    from nerrf_tpu.train.checkpoint import save_stream_checkpoint

    params = {"w": np.ones((2, 2), np.float32)}
    save_stream_checkpoint(tmp_path / "s", params, StreamConfig(),
                           calibration={"stream_event_threshold": 1.25})
    meta = json.loads((tmp_path / "s" / "stream_config.json").read_text())
    assert meta["calibration"]["stream_event_threshold_space"] == "logit"


def test_evaluate_resident_matches_host_slicing(small_dataset):
    """Device-resident eval (one upload + index-driven batches) must produce
    identical metrics to the per-batch host-slicing path, including the
    clamped partial tail batch."""
    import jax

    from nerrf_tpu.models import NerrfNet
    from nerrf_tpu.train.loop import evaluate, init_state, make_eval_fn

    ds = small_dataset
    bs = max(2, len(ds) // 3)  # pick a size that leaves a partial tail batch
    while len(ds) % bs == 0:
        bs += 1
    assert len(ds) % bs != 0
    cfg = TrainConfig(model=JointConfig().small, num_steps=2)
    model = NerrfNet(cfg.model)
    state = init_state(model, cfg, ds.arrays, jax.random.PRNGKey(0))
    fn = make_eval_fn(model)
    host = evaluate(fn, state.params, ds, batch_size=bs, resident=False)
    res = evaluate(fn, state.params, ds, batch_size=bs, resident=True)
    assert host.keys() == res.keys()
    for k in host:
        np.testing.assert_allclose(host[k], res[k], rtol=1e-5, atol=1e-6)


def test_superstep_matches_scheduled_steps(small_dataset):
    """K supersteps must be the same training trajectory as K scheduled
    steps — the benchmark of record times the superstep flavor, so a
    divergence (schedule indexing, rng threading) would silently change
    what BENCH measures."""
    import jax

    from nerrf_tpu.models import NerrfNet
    from nerrf_tpu.train.loop import (
        init_state,
        make_idx_schedule,
        make_train_step_scheduled,
        make_train_superstep,
    )

    ds = small_dataset
    cfg = TrainConfig(
        model=JointConfig(
            gnn=GraphSAGEConfig(hidden=16, num_layers=2, dropout=0.0),
            lstm=LSTMConfig(hidden=16, num_layers=1, dropout=0.0),
        ),
        batch_size=4, num_steps=6, warmup_steps=2, seed=3,
    )
    model = NerrfNet(cfg.model)
    rng = jax.random.PRNGKey(7)
    idx = make_idx_schedule(len(ds), cfg)

    s1 = init_state(model, cfg, ds.arrays, rng)
    sched = make_train_step_scheduled(model, cfg, ds.arrays, idx)
    r = rng
    for _ in range(cfg.num_steps):
        s1, loss1, _aux, r = sched(s1, r)

    s2 = init_state(model, cfg, ds.arrays, rng)
    sup = make_train_superstep(model, cfg, ds.arrays, idx, cfg.num_steps)
    s2, losses, _r2 = sup(s2, rng)

    assert int(s1.step) == int(s2.step) == cfg.num_steps
    assert losses.shape == (cfg.num_steps,)
    np.testing.assert_allclose(float(losses[-1]), float(loss1),
                               rtol=2e-4, atol=2e-5)
    l1 = jax.tree_util.tree_leaves(s1.params)
    l2 = jax.tree_util.tree_leaves(s2.params)
    for a, b in zip(l1, l2):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=2e-3, atol=2e-4)
