"""AOT executable export: serialize the serve ladder at publish time.

The serve path's cold start is the bucket-ladder compile sweep.  This
module moves that sweep to PUBLISH time: `export_executables` compiles the
vmapped NerrfNet eval program for every configured bucket and serializes
each into an ``executables/`` directory — the sidecar
`ModelRegistry.publish` copies in next to the checkpoint.  (The stream
scorer's step programs reuse the same cache through the train-side
`StepCache` instead of riding the sidecar.)  A serve pod booting that version seeds its local
`CompileCache` from the sidecar and reaches readiness in seconds: no
tracing, no XLA, just deserialize-and-load per bucket.

Sidecar layout (one directory, content-addressed — literally a read-only
`CompileCache` root plus a manifest):

    executables/
        manifest.json        {"schema_version": 1, "env": {...},
                              "programs": {"<tag>": {"fingerprint": ...,
                                                     "program": ...,
                                                     "bytes": ...}}}
        <fingerprint>/       one cache entry per program
            executable.bin   serialized executable (serialize_executable)
            trees.pkl        pickled (in_tree, out_tree)
            meta.json        full key material (see compilecache.cache)

The manifest's ``env`` block records the jax/jaxlib/device identity the
executables were built for; a pod on ANY other identity simply misses (the
fingerprints differ) and compiles live — fail-open, like everything here.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Optional

from nerrf_tpu.compilecache.cache import CompileCache

MANIFEST = "manifest.json"
EXECUTABLES_DIR = "executables"


def serve_program_key(model_cfg, bucket_tag: str) -> dict:
    """The caller-side key material for one serve bucket program: the
    model architecture (same param pytree, different HLO — e.g. fuse mode
    or aggregation routing) plus the kernel switchboard state the lowered
    graph depends on.  Warmup and export MUST build keys through here or a
    published executable would never be found at boot."""
    from nerrf_tpu.ops.segment import active_impls

    return {
        "kind": "serve_eval",
        "bucket": bucket_tag,
        "model": repr(model_cfg),
        "ops": repr(sorted(active_impls().items())),
    }


def export_executables(out_dir, params, model, serve_cfg,
                       batch_size: Optional[int] = None,
                       journal=None, registry=None, log=None,
                       tuned_stamp: Optional[dict] = None) -> dict:
    """Compile + serialize the eval program for every ladder bucket into
    ``out_dir`` and return the manifest.  Buckets whose executable cannot
    be serialized on this backend are recorded in the manifest with an
    ``error`` instead of an entry (partial sidecars are still useful)."""
    import numpy as np

    from nerrf_tpu.serve.config import bucket_tag as tag_of
    from nerrf_tpu.train.data import windows_of_trace
    from nerrf_tpu.train.loop import make_eval_fn

    out_dir = Path(out_dir).absolute()
    cache = CompileCache(root=out_dir, max_bytes=1 << 62,
                         journal=journal, registry=registry, log=log)
    eval_fn = make_eval_fn(model)
    bs = batch_size or serve_cfg.batch_size
    # the same shape-donor recipe serve warmup uses — the fingerprint keys
    # on avals, so any tiny trace yielding one sample works
    from nerrf_tpu.serve.service import _tiny_trace

    tiny = _tiny_trace("aot-export")
    programs = {}
    for bucket in serve_cfg.buckets:
        tag = tag_of(bucket)
        samples = windows_of_trace(tiny, serve_cfg.dataset_config(bucket))
        if not samples:
            programs[tag] = {"error": "no shape-donor sample"}
            continue
        s0 = samples[0]
        batch = {k: np.broadcast_to(v, (bs,) + v.shape).copy()
                 for k, v in s0.items()}
        t0 = time.perf_counter()
        _, info = cache.load_or_compile(
            eval_fn, (params, batch), program=f"serve_eval[{tag}]",
            extra=serve_program_key(model.cfg, tag))
        # "absent" is the normal fresh-miss reason; anything else on a
        # fresh compile means the entry never landed on disk (backend
        # can't serialize, or out_dir unwritable) — no sidecar entry
        if info.source == "live" or (info.source == "fresh"
                                     and info.reason != "absent"):
            programs[tag] = {"error": info.reason}
        else:
            programs[tag] = {"fingerprint": info.fingerprint,
                             "program": f"serve_eval[{tag}]",
                             "compile_seconds": round(info.seconds, 3)}
        if log:
            log(f"aot export {tag}: {info.source} "
                f"({time.perf_counter() - t0:.1f}s)")
    manifest = {
        "schema_version": 1,
        "created_at": time.time(),
        "batch_size": bs,
        "env": cache.env(),
        "model": repr(model.cfg),
        "programs": programs,
    }
    if tuned_stamp is not None:
        # provenance for tuned-ladder sidecars: which artifact the
        # exported rung set + routing came from (corpus fingerprint +
        # expected win), so a sidecar is attributable to its fit
        manifest["tuned"] = tuned_stamp
    out_dir.mkdir(parents=True, exist_ok=True)
    # the manifest commits the sidecar: serve boot reads it to decide the
    # bundle is usable, so it must never be observable half-written
    tmp = out_dir / (MANIFEST + ".tmp")
    tmp.write_text(json.dumps(manifest, indent=2))
    tmp.replace(out_dir / MANIFEST)
    return manifest


def export_for_checkpoint(ckpt_dir, serve_cfg=None,
                          journal=None, log=None, tuned=None) -> dict:
    """Load a checkpoint and export its serve-ladder executables into
    ``<ckpt_dir>/executables/`` (the sidecar `ModelRegistry.publish`
    carries along).  Returns the manifest.

    ``tuned`` is an optional tuned-ladder artifact (the dict
    `tune.load_artifact` returns): the export then runs over the TUNED
    rung set with the artifact's routing table stamped into the model
    config — re-exporting a published version onto a fitted ladder is
    exactly this call at publish time (docs/tuning.md)."""
    from nerrf_tpu.models import NerrfNet
    from nerrf_tpu.serve.config import ServeConfig
    from nerrf_tpu.train.checkpoint import load_checkpoint

    ckpt_dir = Path(ckpt_dir).absolute()
    params, model_cfg = load_checkpoint(ckpt_dir)
    serve_cfg = serve_cfg or ServeConfig()
    tuned_stamp = None
    if tuned is not None:
        from nerrf_tpu.tune.artifact import (
            apply_to_model_config,
            apply_to_serve_config,
        )
        serve_cfg = apply_to_serve_config(tuned, serve_cfg)
        model_cfg = apply_to_model_config(tuned, model_cfg)
        tuned_stamp = {
            "corpus_fingerprint": tuned.get("corpus_fingerprint"),
            "expected": tuned.get("expected"),
            "routing": tuned.get("routing"),
        }
    return export_executables(
        ckpt_dir / EXECUTABLES_DIR, params, NerrfNet(model_cfg),
        serve_cfg, journal=journal, log=log, tuned_stamp=tuned_stamp)


def read_manifest(exe_dir) -> Optional[dict]:
    """The sidecar's manifest, or None when ``exe_dir`` is not a sidecar
    (missing/corrupt manifests read as absent — fail-open)."""
    p = Path(exe_dir) / MANIFEST
    try:
        return json.loads(p.read_text())
    except (OSError, ValueError):
        return None
