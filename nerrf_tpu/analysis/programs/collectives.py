"""collective-consistency: shard_map/pjit axis and sharding validation.

The pod-scale serving item will push the `parallel/` shims — today
exercised only on a virtual CPU mesh in tests — under real multi-host
meshes, where an invalid axis name or a rank-mismatched PartitionSpec
surfaces as a GSPMD partitioning error minutes into a pod boot.  This
rule runs the same validation abstractly on CPU:

  * **collective axes** — `ring_self_attention` (the one hand-written
    collective program) is traced to a jaxpr on a virtual 2-device mesh
    and every collective eqn reachable in it (psum/ppermute/axis_index,
    nested bodies included) must name only axes of the declared mesh;
    ppermute permutations must additionally be in-range bijections of the
    axis.  A trace *failure* is itself a finding — the crash the chip
    queue would otherwise hit.
  * **sharding ranks** — every declared pjit layout row from
    `parallel.train.sharding_contract` (built from the real
    batch_sharding/stream_shardings calls) must rank-fit the array it
    annotates and name only mesh axes.

Needs ≥ 2 devices for the trace leg; `prepare_backend` forces a virtual
8-device CPU host, and on an exotic single-device embedder the trace leg
degrades to the sharding-contract checks (noted on stderr, never a
silent pass of a failed trace).
"""

from __future__ import annotations

from typing import List, Optional

from nerrf_tpu.analysis.engine import Finding, Rule
from nerrf_tpu.analysis.programs.abstract import (
    CollectiveEntry,
    collectives_in,
    finding,
    locate,
    note,
)

_CONTRACT_PATH = "nerrf_tpu/parallel/train.py"


class CollectiveConsistency(Rule):
    id = "collective-consistency"
    description = ("collective axis names vs the mesh spec and "
                   "PartitionSpec rank-match over the shard_map/pjit shims")
    deep = True

    def __init__(self, entries: Optional[List[CollectiveEntry]] = None,
                 contracts: Optional[list] = None) -> None:
        self._entries = entries
        self._contracts = contracts

    def run(self, project) -> List[Finding]:
        import jax

        out: List[Finding] = []
        if self._entries is not None:
            entries = self._entries
        elif len(jax.devices()) >= 2:
            from nerrf_tpu.analysis.programs.entries import collective_entries

            entries = collective_entries()
        else:
            note("collective-consistency: <2 devices, skipping the "
                 "shard_map trace leg (sharding contracts still checked)")
            entries = []
        for entry in entries:
            out.extend(self._check_entry(project, entry))
        if self._contracts is not None:
            contracts = self._contracts
        else:
            from nerrf_tpu.analysis.programs.entries import sharding_contracts

            contracts = sharding_contracts()
        out.extend(self._check_contracts(project, contracts))
        return out

    def _check_entry(self, project, entry: CollectiveEntry) -> List[Finding]:
        import jax

        line = 1
        out: List[Finding] = []
        try:
            fn, args = entry.build()
            closed = jax.make_jaxpr(fn)(*args)
        except Exception as e:  # noqa: BLE001 — the finding IS the point
            out.append(finding(
                self.id, entry.path, line,
                anchor=f"collective:{entry.name}:trace",
                message=f"{entry.name}: abstract trace failed "
                        f"({type(e).__name__}: {e}) — this program would "
                        f"crash at partitioning time on a real mesh",
                hint="reproduce with jax.make_jaxpr over ShapeDtypeStructs "
                     "on a 2-device CPU mesh (XLA_FLAGS="
                     "--xla_force_host_platform_device_count=8)"))
            return out
        allowed = set(entry.mesh_axes)
        for prim, axes, params in collectives_in(closed):
            bad = [a for a in axes if a not in allowed]
            if bad:
                out.append(finding(
                    self.id, entry.path, line,
                    anchor=f"collective:{entry.name}:{prim}:"
                           f"{'+'.join(bad)}",
                    message=f"{entry.name}: collective `{prim}` names "
                            f"axis/axes {bad} not in the mesh spec "
                            f"{sorted(allowed)}",
                    hint="every axis a collective names must exist in "
                         "the Mesh the shard_map runs under"))
            if prim == "ppermute":
                out.extend(self._check_perm(entry, params, line))
        return out

    def _check_perm(self, entry, params, line) -> List[Finding]:
        out: List[Finding] = []
        perm = params.get("perm")
        axes = params.get("axis_name", ())
        if isinstance(axes, str):
            axes = (axes,)
        size = None
        for a in axes:
            size = entry.axis_sizes.get(str(a), size)
        if perm is None or size is None:
            return out
        srcs = [p[0] for p in perm]
        dsts = [p[1] for p in perm]
        in_range = all(0 <= v < size for v in srcs + dsts)
        bijective = len(set(srcs)) == len(srcs) and \
            len(set(dsts)) == len(dsts)
        if not (in_range and bijective):
            out.append(finding(
                self.id, entry.path, line,
                anchor=f"collective:{entry.name}:ppermute:perm",
                message=f"{entry.name}: ppermute permutation {perm} is "
                        f"not an in-range bijection of axis size {size} "
                        f"— shards would send to/receive from nowhere",
                hint="build the ring as [(j, (j+1) % size) for j in "
                     "range(size)]"))
        return out

    def _check_contracts(self, project, contracts) -> List[Finding]:
        path, line = _CONTRACT_PATH, 1
        if project is not None:
            path, line = locate(project, "nerrf_tpu.parallel.train",
                                "sharding_contract")
        out: List[Finding] = []
        for prog, array, spec, ndim, mesh_axes in contracts:
            entries = [a for a in tuple(spec) if a is not None]
            flat = []
            for a in entries:
                flat.extend(a if isinstance(a, (tuple, list)) else (a,))
            bad = [a for a in flat if a not in mesh_axes]
            if bad:
                out.append(finding(
                    self.id, path, line,
                    anchor=f"sharding:{prog}:{array}:axes",
                    message=f"{prog}: PartitionSpec for `{array}` names "
                            f"axis/axes {bad} not in the mesh "
                            f"{list(mesh_axes)}",
                    hint="specs must only name declared mesh axes"))
            if len(tuple(spec)) > ndim:
                out.append(finding(
                    self.id, path, line,
                    anchor=f"sharding:{prog}:{array}:rank",
                    message=f"{prog}: PartitionSpec {tuple(spec)} for "
                            f"`{array}` has rank {len(tuple(spec))} but "
                            f"the array is rank {ndim} — GSPMD rejects "
                            f"this at partitioning time",
                    hint="a spec may be shorter than the array rank "
                         "(trailing dims replicate) but never longer"))
        return out
