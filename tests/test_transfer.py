"""Sim→real parity: a detector trained purely on our synthetic corpus must
separate the *reference's* checked-in M1 attack trace from benign activity.

This is the strongest artifact-level parity check available: the reference
never built a detector, but it did capture a real attack run
(`/root/reference/benchmarks/m1/results/m1_trace.jsonl`, 149 events, 141 in
the labelled attack window).  Training on synthetic traces and evaluating on
that artifact (mixed with a held-out benign run for label contrast — the
log-scraped reference trace contains attack-phase events only) exercises the
full loader → labels → graph → model path on foreign data."""

import dataclasses

import pytest

from nerrf_tpu.config import get_experiment
from nerrf_tpu.data import (
    SimConfig,
    derive_event_labels,
    load_trace_jsonl,
    simulate_trace,
)
from nerrf_tpu.train import build_dataset
from nerrf_tpu.train.loop import train_nerrfnet


@pytest.mark.slow
def test_synthetic_detector_flags_reference_m1_attack(repo_root):
    ref = repo_root.parent / "reference" / "benchmarks" / "m1" / "results"
    if not ref.exists():
        pytest.skip("reference artifacts not mounted")

    exp = get_experiment("toy-graphsage")
    train_traces, _ = exp.build_corpus()
    train_ds = build_dataset(train_traces, exp.dataset)

    tr = load_trace_jsonl(ref / "m1_trace.jsonl",
                          ground_truth=ref / "m1_ground_truth.csv")
    tr.labels = derive_event_labels(tr)
    assert tr.events.num_valid == 149 and tr.labels.sum() > 100
    benign = simulate_trace(SimConfig(
        duration_sec=120.0, attack=False, num_target_files=8,
        benign_rate_hz=10.0, seed=99))
    mixed = build_dataset([tr, benign], exp.dataset)

    cfg = dataclasses.replace(exp.train, model=exp.train.model.small,
                              num_steps=120, eval_every=60, batch_size=4)
    res = train_nerrfnet(train_ds, eval_ds=mixed, cfg=cfg)
    # the spec's CI gate (ROC-AUC >= 0.90), applied to the real artifact
    assert res.metrics["edge_auc"] >= 0.90, res.metrics
    assert res.metrics["node_auc"] >= 0.85, res.metrics
