#!/usr/bin/env python3
"""Leave-one-scenario-out generalization eval (VERDICT r4 weak #3 / next #2).

The adversarial sweep's clean stealth wins are measured in-distribution:
the training corpus contains every scenario family (seeds held out,
generators not), so they show the model beats the heuristic, not that it
detects UNSEEN attack mechanics.  This harness measures exactly that: for
each stealth family, train a probe-scale detector on a corpus from which
that family's GENERATOR is excluded (`make_corpus(exclude_scenarios=…)`),
calibrate its operating threshold without the family
(`calibrate_file_thresholds(exclude_scenarios=…)` — a cut picked on
held-out-family victims would leak), then measure file-level detection on
fresh traces of the excluded family at that cut.

The honest deliverable is the per-family out-of-distribution detection
rate next to the in-distribution one — including families where OOD
detection DROPS.  A model that detects inplace-stealth only after training
on inplace-stealth is still useful (the corpus ships the family), but the
README claim must say which is which.

Reference hook: the reference's detection plan is indicator rules
(`/root/reference/docs/content/docs/detection/threat-model.mdx:275-319`);
its heuristics are definitionally 0% OOD on these families (they carry no
rename/extension/note indicators at all) — that column is the baseline.

Usage:
  python benchmarks/run_loso_eval.py --out benchmarks/results/loso_eval.json
  ... --steps 500 --train-traces 16 --eval-traces 6 [--families inplace-stealth ...]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
sys.path.insert(0, str(Path(__file__).resolve().parent))


def _log(msg):
    print(f"[loso] {msg}", file=sys.stderr, flush=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="benchmarks/results/loso_eval.json")
    ap.add_argument("--steps", type=int, default=500)
    ap.add_argument("--train-traces", type=int, default=16)
    ap.add_argument("--eval-traces", type=int, default=6)
    ap.add_argument("--seed", type=int, default=303)
    ap.add_argument("--families", nargs="*", default=None,
                    help="subset of stealth families (default: all four)")
    ap.add_argument("--platform", default=None,
                    help="force a JAX platform before backend init")
    args = ap.parse_args(argv)

    from nerrf_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from run_adversarial_eval import _file_metrics, _scenario_traces

    from nerrf_tpu.data.synth import STEALTH_SCENARIOS, make_corpus
    from nerrf_tpu.models import (
        GraphSAGEConfig,
        JointConfig,
        LSTMConfig,
        NerrfNet,
    )
    from nerrf_tpu.pipeline import (
        calibrate_file_thresholds,
        heuristic_detect,
        model_detect,
    )
    from nerrf_tpu.train import TrainConfig, build_dataset
    from nerrf_tpu.train.data import fit_dataset_config
    from nerrf_tpu.train.loop import train_nerrfnet

    t0 = time.time()
    backend = jax.default_backend()
    families = args.families or sorted(STEALTH_SCENARIOS)
    bad = set(families) - STEALTH_SCENARIOS
    if bad:
        ap.error(f"not stealth families: {sorted(bad)}")
    _log(f"backend={backend} families={families}")

    # probe scale — the same small-joint architecture as the
    # probe-corpus-cpu checkpoint; LOSO measures a generalization DELTA,
    # which probe scale resolves (VERDICT r4 next #2: "probe scale is fine")
    model_cfg = JointConfig(
        gnn=GraphSAGEConfig(hidden=64, num_layers=8),
        lstm=LSTMConfig(hidden=64, num_layers=1),
    )

    report = {"backend": backend, "steps": args.steps,
              "train_traces": args.train_traces,
              "eval_traces": args.eval_traces,
              "model": "small-joint 64h (probe scale)",
              "families": {}}
    for family in families:
        _log(f"=== hold out {family}: corpus without its generator")
        corpus = make_corpus(
            args.train_traces, attack_fraction=0.5,
            base_seed=args.seed, duration_sec=180.0,
            num_target_files=24, benign_rate_hz=40.0,
            hard_scenarios=True, exclude_scenarios=frozenset({family}),
        )
        cfg = TrainConfig(model=model_cfg, batch_size=8,
                          num_steps=args.steps,
                          eval_every=max(100, args.steps),
                          seed=args.seed)
        res = train_nerrfnet(build_dataset(corpus, fit_dataset_config(corpus)),
                             cfg=cfg, log=_log)
        params = res.state.params
        model = NerrfNet(cfg.model)
        cals = calibrate_file_thresholds(
            params, model, exclude_scenarios=frozenset({family}), log=_log)
        threshold = cals["max"].threshold if cals.get("max") else None
        _log(f"  calibrated cut (family excluded): {threshold}")

        # fresh traces of the EXCLUDED family — the model has never seen
        # this generator's mechanics, the threshold never saw its scores
        traces = _scenario_traces(family, args.eval_traces, args.seed + 5000)
        detections = [model_detect(tr, params, model, threshold=threshold)
                      for tr in traces]
        ood = _file_metrics(list(zip(traces, detections)), lambda td: td[1])
        heur = _file_metrics([(tr, None) for tr in traces],
                             lambda td: heuristic_detect(td[0]))
        # benign hard negatives at the same cut: OOD detection bought by a
        # cut low enough to also flag benign churn is not a win
        fp_entry = {}
        for neg in ("benign-mass-rename", "benign-atomic-rewrite"):
            ntraces = _scenario_traces(neg, 2, args.seed + 6000)
            ndet = [model_detect(tr, params, model, threshold=threshold)
                    for tr in ntraces]
            m = _file_metrics(list(zip(ntraces, ndet)), lambda td: td[1])
            fp_entry[neg] = m["fp_undo_rate"]
        entry = {
            "ood_detection_rate": ood["detection_rate"],
            "ood_fp_undo_rate": ood["fp_undo_rate"],
            "heuristic_detection_rate": heur["detection_rate"],
            "threshold": round(threshold, 4) if threshold else None,
            "benign_fp_undo_at_cut": fp_entry,
            "files_attacked": ood["files_attacked"],
        }
        report["families"][family] = entry
        _log(f"  {family}: {json.dumps(entry)}")

    rates = [e["ood_detection_rate"] or 0.0
             for e in report["families"].values()]
    report["summary"] = {
        "ood_detection_min": round(min(rates), 4),
        "ood_detection_mean": round(sum(rates) / len(rates), 4),
        "families_generalized": sorted(
            f for f, e in report["families"].items()
            if (e["ood_detection_rate"] or 0.0) >= 0.95
            and e["ood_fp_undo_rate"] < 0.05),
        "note": ("in-distribution numbers for the same families live in "
                 "the adversarial artifact (benchmarks/results/"
                 "adversarial_probe_cpu.json) — compare before claiming "
                 "generalization"),
    }
    report["provenance"] = "python benchmarks/run_loso_eval.py"
    report["wall_seconds"] = round(time.time() - t0, 1)
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(report, indent=2) + "\n")
    print(json.dumps(report["summary"]))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
