#!/usr/bin/env bash
# Cluster-free end-to-end test of the streaming pipeline.
#
# The runnable counterpart of the reference's minikube E2E
# (`/root/reference/tracker/scripts/test.sh` — broken as shipped: hardcoded
# /home/agasta paths, missing manifests): stream events over the real Tracker
# gRPC protocol, drain them through the native ingest bridge into the trace
# store, and pass iff at least EVENT_THRESHOLD ransomware-relevant events
# (.dat/.lockbit paths — same jq filter semantics as test.sh:76-82) arrive
# end-to-end.
#
# Source modes:
#   ./e2e.sh          — replay the toy trace (CI path: no privileges needed)
#   ./e2e.sh live     — LIVE kernel capture: the native nerrf-trackerd daemon
#                       attaches its eBPF program, a scripted "attack"
#                       (create/write/rename-to-.lockbit3/unlink) runs, and
#                       the same ingest path drains real kernel events.
#                       Skips cleanly (exit 0, "SKIP") without CAP_BPF or
#                       kernel support — mirrors the daemon's exit codes.
#   ./e2e.sh obj      — `live`, but the daemon loads the clang-compiled
#                       bpf/tracepoints.c object (make bpf → NERRF_BPF_OBJ)
#                       through the ELF loader (src/bpfobj.h) instead of the
#                       hand-assembled bytecode.  Skips cleanly when clang
#                       is not installed.  Proves the two program sources
#                       are interchangeable on the same kernel.
set -euo pipefail

MODE="${1:-replay}"
if [ "$MODE" = "obj" ]; then
    if ! command -v clang >/dev/null 2>&1; then
        echo "E2E SKIP: obj mode needs clang for make bpf"
        exit 0
    fi
    make -C native bpf >/dev/null
    export NERRF_BPF_OBJ="$(cd native && pwd)/build/tracepoints.o"
    MODE=live
fi
EVENT_THRESHOLD="${EVENT_THRESHOLD:-10}"
PORT="${PORT:-50199}"
WORK="$(mktemp -d)"
trap '[ -n "${SERVER_PID:-}" ] && kill "$SERVER_PID" 2>/dev/null || true; rm -rf "$WORK"' EXIT

cd "$(dirname "$0")/.."

# pre-flight: the repo's static analysis must be clean before any servers
# or daemons come up — an unbaselined finding fails in seconds here
# instead of surfacing as a race/recompile mid-stream
python scripts/nerrflint.py

# pre-flight: the deep (jaxpr-level) program contracts — signature
# closure of the serve ladder, donation discipline over the flat train
# step, collective/sharding consistency, Pallas VMEM budgets, cache-key
# coverage — proven abstractly on a virtual CPU backend (<30 s, no
# devices; docs/static-analysis.md "The deep pass").  Same timeout guard
# as the TPU queues: a wedged jax import must fail, not hang the e2e.
timeout 120 python scripts/nerrflint.py --deep

# pre-flight: the persistent compile cache must round-trip — warm one
# serve bucket into a scratch cache (fresh compile, persisted), then
# assert the second sweep DESERIALIZES it (source=cache for every
# bucket).  A cache-key-stability or executable-serialization regression
# fails here in seconds instead of costing every pod its cold boot back
# (docs/compile-cache.md).
NERRF_AOT_CACHE_DIR="$WORK/aot" python -m nerrf_tpu.cli cache warm \
    --no-probe --buckets 64x128x32 > "$WORK/cache_cold.json"
NERRF_AOT_CACHE_DIR="$WORK/aot" python -m nerrf_tpu.cli cache warm \
    --no-probe --buckets 64x128x32 --expect-cache > "$WORK/cache_warm.json"
echo "e2e: compile cache round-trips (second sweep source=cache)"

# pre-flight: chaos smoke — the serve path survives a short seeded fault
# schedule (window poison → bisection isolates exactly it, wire resets →
# backoff reconnect, ENOSPC'd bundle dump → retried, corrupt cache
# payload → fail-open recompile) with zero recompiles and unfaulted-
# stream bit-parity.  Exit 1 = a survival gate regressed (docs/chaos.md).
# Pinned to CPU: this must run (and fail fast) on a tunnel-wedged host.
timeout 560 env JAX_PLATFORMS=cpu python benchmarks/run_chaos_bench.py \
    --smoke > "$WORK/chaos_smoke.json"
echo "e2e: chaos smoke survival gates pass"

# pre-flight: quality drift-injection smoke — the detection-quality
# plane end to end on the real serve path: the unshifted leg stays below
# the PSI breach with single-stream bit-parity to model_detect, the
# shifted leg fires exactly one doctor-readable quality_drift bundle
# embedding both sketch sets (docs/quality.md).  Pinned to CPU: proves
# the drift edge before any chip time is spent.
timeout 560 env JAX_PLATFORMS=cpu python benchmarks/run_quality_bench.py \
    --smoke > "$WORK/quality_smoke.json"
echo "e2e: quality drift-injection smoke gates pass"

# pre-flight: trainwatch smoke — the training-health plane end to end on
# the real train loop: clean legs bit-identical loss history with zero
# bundles and a cache-deserialized step (zero recompiles), the injected
# nonfinite step fires exactly one doctor-readable train_divergence
# bundle and flips /readyz to 503 (docs/training-health.md).  Pinned to
# CPU: proves the divergence edge before any chip training relies on it.
timeout 560 env JAX_PLATFORMS=cpu python benchmarks/run_train_health_bench.py \
    --smoke > "$WORK/train_health_smoke.json"
echo "e2e: trainwatch divergence smoke gates pass"

# pre-flight: respond smoke — the incident-response tier end to end:
# all four adversarial families staged on disk, detected on the live
# router, planned in vmapped batches (B=1 bit-identical to the offline
# planner, zero recompiles after warmup), every plan sandbox-verified
# before surfacing and the contextless incident quarantined with a
# journaled reason (docs/response.md).  Pinned to CPU: the whole
# detect→plan→verify loop must hold on a tunnel-wedged host.
timeout 560 env JAX_PLATFORMS=cpu python benchmarks/run_respond_bench.py \
    --smoke > "$WORK/respond_smoke.json"
echo "e2e: respond smoke gates pass"

# pre-flight: continuous-learning smoke — the learn plane closed-loop
# on the real serve path: serve traffic feeds the replay buffer at the
# demux seam, an injected mid-run shift fires the quality_drift trigger,
# the supervisor retrains exactly once over replay+synth, the candidate
# publishes with provenance and the existing shadow/canary gates promote
# it, quality recovers on a held-out shifted eval set, and a divergent
# retrain aborts publishing nothing (docs/learning.md).  Pinned to CPU:
# the drift→retrain→promote edge must hold before any chip run trusts it.
timeout 900 env JAX_PLATFORMS=cpu python benchmarks/run_learn_bench.py \
    --smoke > "$WORK/learn_smoke.json"
echo "e2e: continuous-learning closed-loop smoke gates pass"

# pre-flight: archive smoke — the telemetry archive plane end to end on
# the real serve path: a short serve run spools journal + metrics +
# workload sketches into crash-safe segments, then `nerrf report` must
# reconstruct the run (windows scored, e2e quantiles) from the segments
# alone and `nerrf archive verify` must find them intact
# (docs/archive.md).  Pinned to CPU: archiving is jax-free and must
# work on a tunnel-wedged host.
timeout 300 env JAX_PLATFORMS=cpu python -m nerrf_tpu.cli serve-detect \
    --trace datasets/traces/toy_trace.csv --no-probe --metrics-port -1 \
    --archive-dir "$WORK/archive" --buckets 256x512x128 --no-aot-cache \
    > "$WORK/archive_serve.json" 2>> "$WORK/archive_serve.log"
timeout 120 env JAX_PLATFORMS=cpu python -m nerrf_tpu.cli archive verify \
    "$WORK/archive" > /dev/null
timeout 120 env JAX_PLATFORMS=cpu python -m nerrf_tpu.cli report \
    "$WORK/archive" --json > "$WORK/archive_report.json"
python - "$WORK/archive_report.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
assert r["span"]["records"] > 0, "archive spooled nothing"
assert r["slo"]["windows_scored"] > 0, "no windows reached the sketches"
assert (r["slo"]["e2e_ms"] or {}).get("p99") is not None, "no e2e sketch"
print(f"e2e: archive report reconstructs the run offline "
      f"({r['span']['records']} records, "
      f"{r['slo']['windows_scored']} windows)")
EOF

# pre-flight: tune smoke — the learned-ladder loop end to end on the
# archived toy serve run above: `nerrf tune` fits a tuned ladder +
# per-rung kernel routing from the segments alone (deterministic: same
# corpus, same artifact), and a fresh serve boot on the artifact must
# score windows with ZERO post-warmup recompiles (docs/tuning.md).
# Pinned to CPU: the fit is pure arithmetic over the corpus.
timeout 120 env JAX_PLATFORMS=cpu python -m nerrf_tpu.cli tune \
    "$WORK/archive" --out "$WORK/tuned.json" 2>> "$WORK/archive_serve.log"
timeout 300 env JAX_PLATFORMS=cpu python -m nerrf_tpu.cli serve-detect \
    --trace datasets/traces/toy_trace.csv --no-probe --metrics-port -1 \
    --tuned "$WORK/tuned.json" --no-aot-cache \
    > "$WORK/tuned_serve.json" 2>> "$WORK/archive_serve.log"
python - "$WORK/tuned_serve.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
assert r["windows_scored"] > 0, "tuned-ladder boot scored nothing"
assert r["recompiles_after_warmup"] == 0, "tuned boot recompiled post-warmup"
print(f"e2e: tuned-ladder boot scores {int(r['windows_scored'])} windows, "
      "zero post-warmup recompiles")
EOF

# pre-flight: archive-compare regression gate — the fresh archived smoke
# run above vs this host's banked artifact-of-record (docs/fleet.md).
# `nerrf report --compare --gate` exits nonzero when the candidate
# regressed beyond the CompareConfig tolerances (e2e p99, breach/drop
# rate, per-bucket device cost, drift, train loss), failing the run
# BEFORE any chip time; a missing bank (first run on a host) passes with
# a note, and a green gate re-banks the current run so every later run
# is measured against the best-known-good.  Pinned to CPU: the compare
# is pure arithmetic over the segments.
BASELINE="${NERRF_ARCHIVE_BASELINE:-$HOME/.cache/nerrf/archive_baseline}"
timeout 120 env JAX_PLATFORMS=cpu python -m nerrf_tpu.cli report \
    --compare "$BASELINE" "$WORK/archive" --gate
mkdir -p "$(dirname "$BASELINE")"
rm -rf "$BASELINE"
cp -r "$WORK/archive" "$BASELINE"
echo "e2e: archive-compare gate green (artifact-of-record banked at $BASELINE)"

# pre-flight: devtime smoke — the device-efficiency cost table (analytic
# FLOPs / byte floor / roofline intensity for the serve ladder + flat
# train step) resolves on CPU with every chip-relative column null
# (docs/device-efficiency.md).  The same command run on a chip prints
# the measured MFU table with zero extra work.
timeout 300 env JAX_PLATFORMS=cpu python -m nerrf_tpu.cli profile costs \
    --smoke --no-probe --json > "$WORK/devtime_smoke.json"
python - "$WORK/devtime_smoke.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
assert r["peaks"] is None, "CPU rig must not report chip peaks"
assert r["programs"], "cost table empty"
for name, p in r["programs"].items():
    assert p["flops"] > 0 and p["bytes_accessed"] > 0, name
    assert (p.get("measured") or {}).get("mfu") is None, \
        f"{name}: fabricated MFU on CPU"
print(f"e2e: devtime cost table resolves ({len(r['programs'])} programs, "
      "chip-relative columns null on CPU)")
EOF

if [ "$MODE" = "live" ]; then
    make -C native build/nerrf-trackerd >/dev/null
    rc=0
    native/build/nerrf-trackerd --probe || rc=$?
    if [ "$rc" = 2 ] || [ "$rc" = 3 ]; then
        echo "E2E SKIP: live capture unavailable (daemon probe rc=$rc)"
        exit 0
    elif [ "$rc" != 0 ]; then
        exit "$rc"
    fi
    # unix socket: peer-pid exclusion (SO_PEERCRED) works there, so the
    # ingest client's own store writes can't feed back into the capture
    SOCK="$WORK/tracker.sock"
    native/build/nerrf-trackerd --listen "unix:${SOCK}" \
        --max-seconds 90 2> "$WORK/trackerd.log" &
    SERVER_PID=$!
    # scripted attack: keeps emitting activity for the daemon to observe
    # until the (slow-to-import) ingest client has connected and drained
    ( V="$WORK/victim"; mkdir -p "$V"
      for round in $(seq 1 120); do
          for i in 1 2 3; do
              printf 'confidential payload %s.%s' "$round" "$i" \
                  > "$V/doc_${round}_$i.dat"
              mv "$V/doc_${round}_$i.dat" "$V/doc_${round}_$i.dat.lockbit3"
              rm "$V/doc_${round}_$i.dat.lockbit3"
          done
          sleep 0.5
      done ) &
    ATTACK_PID=$!
    trap '[ -n "${SERVER_PID:-}" ] && kill "$SERVER_PID" 2>/dev/null || true; [ -n "${ATTACK_PID:-}" ] && kill "$ATTACK_PID" 2>/dev/null || true; rm -rf "$WORK"' EXIT
elif [ "$MODE" = "container" ]; then
    # Run the IMAGE ENTRYPOINT itself (deploy/tracker-entrypoint.sh) against
    # the checkout — the contract a docker build of deploy/Dockerfile would
    # execute, minus the image filesystem (no docker in this environment).
    # The entrypoint probes for live capture and falls back to replay, so
    # this passes on both privileged and unprivileged hosts.
    make -C native build/nerrf-trackerd >/dev/null
    CONTAINER_LIVE=0
    native/build/nerrf-trackerd --probe >/dev/null 2>&1 && CONTAINER_LIVE=1
    NERRF_APP_ROOT="$(pwd)" TRACKER_LISTEN_ADDR="127.0.0.1:${PORT}" \
        TRACKER_MAX_SECONDS=90 sh deploy/tracker-entrypoint.sh \
        2> "$WORK/entrypoint.log" &
    SERVER_PID=$!
    if [ "$CONTAINER_LIVE" = 1 ]; then
        ( V="$WORK/victim"; mkdir -p "$V"
          for round in $(seq 1 120); do
              for i in 1 2 3; do
                  printf 'confidential payload %s.%s' "$round" "$i" \
                      > "$V/doc_${round}_$i.dat"
                  mv "$V/doc_${round}_$i.dat" "$V/doc_${round}_$i.dat.lockbit3"
                  rm "$V/doc_${round}_$i.dat.lockbit3"
              done
              sleep 0.5
          done ) &
        ATTACK_PID=$!
        trap '[ -n "${SERVER_PID:-}" ] && kill "$SERVER_PID" 2>/dev/null || true; [ -n "${ATTACK_PID:-}" ] && kill "$ATTACK_PID" 2>/dev/null || true; rm -rf "$WORK"' EXIT
    fi
else
    python -m nerrf_tpu.cli serve \
        --trace datasets/traces/toy_trace.csv \
        --address "127.0.0.1:${PORT}" --metrics-port -1 --duration 60 &
    SERVER_PID=$!
fi

if [ "$MODE" = "live" ]; then
    TARGET="unix:${SOCK}"
    for _ in $(seq 1 20); do [ -S "$SOCK" ] && break; sleep 0.5; done
else
    TARGET="127.0.0.1:${PORT}"
    for _ in $(seq 1 20); do
        if python - "$PORT" <<'EOF' 2>/dev/null
import socket, sys
s = socket.create_connection(("127.0.0.1", int(sys.argv[1])), timeout=0.5)
s.close()
EOF
        then break; fi
        sleep 0.5
    done
fi

# live capture is systemwide: every mv/rm spawn alone contributes ~10 benign
# libc/locale openats, so drain enough events for the attack to clear the
# threshold over the noise floor (realistic capture conditions, not a filter)
INGEST_ARGS=()
[ "$MODE" = "live" ] && INGEST_ARGS+=(--max-events 500 --timeout 45)
[ "${CONTAINER_LIVE:-0}" = 1 ] && INGEST_ARGS+=(--max-events 500 --timeout 45)
python -m nerrf_tpu.cli ingest \
    --target "$TARGET" --store-dir "$WORK/store" \
    --metrics-port -1 --timeout 30 "${INGEST_ARGS[@]+"${INGEST_ARGS[@]}"}" \
    > "$WORK/ingest.json"
cat "$WORK/ingest.json"

python - "$WORK" "$EVENT_THRESHOLD" <<'EOF'
import json, sys
from pathlib import Path

sys.path.insert(0, ".")
import jax

jax.config.update("jax_platforms", "cpu")
from nerrf_tpu.graph.store import TraceStore

work, threshold = Path(sys.argv[1]), int(sys.argv[2])
summary = json.loads((work / "ingest.json").read_text())
with TraceStore(work / "store") as st:
    ev, strings = st.query(0, 2**62)
    hits = 0
    for i in range(len(ev)):
        if not ev.valid[i]:
            continue
        path = strings.lookup(int(ev.path_id[i]))
        new = strings.lookup(int(ev.new_path_id[i]))
        if any(x in p for p in (path, new) for x in (".dat", ".lockbit")):
            hits += 1
print(f"e2e: {summary['events']} events ingested, {hits} ransomware-relevant "
      f"(threshold {threshold})")
if summary["events"] == 0 or hits < threshold:
    sys.exit(1)
print("E2E PASS")
EOF
