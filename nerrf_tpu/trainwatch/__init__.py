"""trainwatch: the training-health observability plane.

Every other plane (spans, SLO/flight, devtime, quality) faces the serve
path; this one faces the training run — in-step telemetry computed inside
the jitted step (`telemetry.step_telemetry`), a `TrainHealthMonitor`
exporting ``nerrf_train_*`` gauges + cadenced ``train_health`` journal
records, and train-side flight triggers (``train_divergence`` /
``train_starvation`` / ``train_stall``) dumping doctor-readable bundles
through the existing `FlightRecorder`.  See docs/training-health.md.

`training_health` is the one wiring point the CLIs share: it builds the
monitor, the train-aware `/readyz` metrics server, and the flight
recorder from two flags (``--metrics-port`` / ``--flight-dir``) and tears
everything down in order on exit.
"""

from __future__ import annotations

import contextlib

from nerrf_tpu.trainwatch.monitor import (  # noqa: F401
    TrainHealthConfig,
    TrainHealthMonitor,
)
from nerrf_tpu.trainwatch.telemetry import (  # noqa: F401
    global_norm,
    nonfinite_count,
    step_telemetry,
)


@contextlib.contextmanager
def training_health(metrics_port=None, flight_dir=None, archive_dir=None,
                    cfg=None, registry=None, journal=None, log=None):
    """Wire the training-health plane for one run; yields the monitor
    (None when every surface is disabled — the loop then pays nothing).

    * ``metrics_port`` ≥ 0 → a `MetricsServer` with the train-aware
      ``ready_check`` (503 before the first step and after a
      divergence halt);
    * ``flight_dir`` set → a `FlightRecorder` whose ``info()`` is the
      monitor's run identity; train triggers dump bundles there;
    * ``archive_dir`` set → a telemetry `ArchiveWriter`
      (docs/archive.md): the run's journal stream (train_start /
      train_health / train_done, exceptions, compiles), cadenced
      metrics snapshots and the train-step workload sketch spool to
      crash-safe segments `nerrf report` reads offline.  Bundles dumped
      by the recorder carry the archive position in their manifest.

    Teardown order matters and is owned here: monitor thread first (it
    may fire into the recorder), then the recorder's journal
    subscription, then the archive writer (it seals the tail), then the
    HTTP server.
    """
    if (metrics_port is None or metrics_port < 0) and not flight_dir \
            and not archive_dir:
        yield None
        return
    monitor = TrainHealthMonitor(cfg, registry=registry, journal=journal,
                                 log=log)
    recorder = None
    server = None
    archive = None
    try:
        if archive_dir:
            from nerrf_tpu.archive import ArchiveConfig, ArchiveWriter

            archive = ArchiveWriter(ArchiveConfig(out_dir=str(archive_dir)),
                                    registry=registry, journal=journal,
                                    log=log)
            if log:
                log(f"trainwatch: telemetry archive spooling to "
                    f"{archive_dir}")
        if flight_dir:
            from nerrf_tpu.flight import FlightConfig, FlightRecorder

            recorder = FlightRecorder(
                FlightConfig(out_dir=str(flight_dir)),
                registry=registry, journal=journal,
                info=monitor.flight_info, archive=archive, log=log)
            monitor.attach_flight(recorder)
            if log:
                log(f"trainwatch: flight recorder armed, bundles in "
                    f"{flight_dir}")
        if metrics_port is not None and metrics_port >= 0:
            from nerrf_tpu.observability import MetricsServer

            server = MetricsServer(registry=registry, host="0.0.0.0",
                                   port=metrics_port,
                                   ready_check=monitor.ready)
            if log:
                log(f"trainwatch: metrics on :{server.port} "
                    f"(/metrics, /healthz, /readyz)")
        monitor.start()
        yield monitor
    finally:
        monitor.stop()
        if recorder is not None:
            recorder.close()
        if archive is not None:
            archive.close()
        if server is not None:
            server.close()
