"""lock-discipline: shared state in the threaded planes stays under lock.

Scope: the threaded serve/registry/observability code — per-class analysis
of ``self.X`` accesses against the class's own ``threading.Lock`` /
``RLock`` / ``Condition`` attributes (constructor-assigned or dataclass
``field(default_factory=threading.Lock)``).

The discipline inferred, per class:

  * an attribute is **guarded** when it is written or mutated in place at
    least once while one of the class's locks is held — that lock set is
    its guard;
  * a **mutation or rebind** of a guarded attribute anywhere outside
    ``__init__`` without a guard lock held is a finding;
  * a **read** of a guarded attribute is a finding only when the attribute
    is a *container* mutated in place somewhere (``d[k]=``, ``.append``,
    ``.pop`` …): reading a container mid-mutation observes torn state.
    Attributes that are only ever *rebound* (pointer swaps — the live
    params pointer, the shadow tuple) read atomically under the GIL, so
    bare reads of those stay legal by design;
  * held-lock state propagates into private methods (``_name``) whose
    intra-class call sites all hold the lock (fixpoint) — how
    ``_poll_locked``-style bodies are understood to run under ``poll()``'s
    lock.  Public methods are always assumed callable bare.

Plus the **lock-acquisition-order graph**: an edge L→M whenever M is
acquired (lexically, or through a call to a uniquely-named method of a
scanned class that acquires M) while L is held.  A cycle means two
threads can deadlock batcher↔manager↔registry; any cycle is a finding.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from nerrf_tpu.analysis.astutil import ModuleInfo, dotted
from nerrf_tpu.analysis.engine import Finding, Rule

DEFAULT_SCOPE = ("nerrf_tpu/serve/", "nerrf_tpu/registry/",
                 "nerrf_tpu/observability.py")

_LOCK_TYPES = frozenset({"Lock", "RLock", "Condition"})
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "remove", "discard",
    "pop", "popleft", "popitem", "clear", "update", "setdefault",
})


@dataclasses.dataclass
class _Access:
    attr: str
    kind: str          # "read" | "mutate" | "rebind"
    line: int
    method: str
    held: FrozenSet[str]


@dataclasses.dataclass
class _ClassInfo:
    name: str
    mod: ModuleInfo
    locks: Set[str] = dataclasses.field(default_factory=set)
    methods: Dict[str, ast.AST] = dataclasses.field(default_factory=dict)
    accesses: List[_Access] = dataclasses.field(default_factory=list)
    # method → [(callee-or-None for foreign, name, held-at-site)]
    calls: List[Tuple[str, str, FrozenSet[str]]] = \
        dataclasses.field(default_factory=list)
    # acquisitions observed: (method, acquired-name, held-at-site, line)
    acquisitions: List[Tuple[str, str, FrozenSet[str], int]] = \
        dataclasses.field(default_factory=list)
    entry: Dict[str, FrozenSet[str]] = dataclasses.field(default_factory=dict)


def _is_lock_ctor(value: ast.AST) -> bool:
    if isinstance(value, ast.Call):
        d = dotted(value.func)
        if d is not None and d.split(".")[-1] in _LOCK_TYPES:
            return True
        # dataclasses.field(default_factory=threading.Lock)
        if d is not None and d.split(".")[-1] == "field":
            for kw in value.keywords:
                if kw.arg == "default_factory":
                    fd = dotted(kw.value)
                    if fd is not None and fd.split(".")[-1] in _LOCK_TYPES:
                        return True
    return False


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _collect_classes(mod: ModuleInfo) -> List[_ClassInfo]:
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        ci = _ClassInfo(node.name, mod)
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name) and \
                    stmt.value is not None and _is_lock_ctor(stmt.value):
                ci.locks.add(stmt.target.id)
            elif isinstance(stmt, ast.Assign) and _is_lock_ctor(stmt.value):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        ci.locks.add(t.id)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ci.methods[stmt.name] = stmt
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Assign) and \
                            _is_lock_ctor(sub.value):
                        for t in sub.targets:
                            attr = _self_attr(t)
                            if attr:
                                ci.locks.add(attr)
        out.append(ci)
    return out


def _walk_method(ci: _ClassInfo, name: str, node: ast.AST,
                 lock_attr_names: Set[str]) -> None:
    """Record accesses, intra/foreign calls and acquisitions with the
    lexically-held lock set."""

    def rec_target(t: ast.AST, held, kind: str) -> None:
        attr = _self_attr(t)
        if attr and attr not in ci.locks:
            ci.accesses.append(_Access(attr, kind, t.lineno, name, held))
        elif isinstance(t, ast.Subscript):
            attr = _self_attr(t.value)
            if attr and attr not in ci.locks:
                ci.accesses.append(
                    _Access(attr, "mutate", t.lineno, name, held))
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                rec_target(el, held, kind)

    def walk(n: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(n, ast.With):
            inner = set(held)
            for item in n.items:
                attr = _self_attr(item.context_expr)
                if attr and attr in ci.locks:
                    inner.add(attr)
                    ci.acquisitions.append(
                        (name, attr, held, item.context_expr.lineno))
                elif isinstance(item.context_expr, ast.Attribute) and \
                        item.context_expr.attr in lock_attr_names:
                    # with <obj>.<lockattr>: — a foreign acquisition,
                    # tracked for the order graph only
                    ci.acquisitions.append(
                        (name, item.context_expr.attr, held,
                         item.context_expr.lineno))
                    inner.add(f"~{item.context_expr.attr}")
                if item.optional_vars is not None:
                    walk(item.optional_vars, frozenset(inner))
                walk(item.context_expr, held)
            for stmt in n.body:
                walk(stmt, frozenset(inner))
            return
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return  # nested defs escape the held set (run later)
        if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            kind = "mutate" if isinstance(n, ast.AugAssign) else "rebind"
            for t in targets:
                rec_target(t, held, kind)
            if n.value is not None:
                walk(n.value, held)
            return
        if isinstance(n, ast.Delete):
            for t in n.targets:
                rec_target(t, held, "mutate")
            return
        if isinstance(n, ast.Call):
            d = dotted(n.func)
            if d is not None:
                parts = d.split(".")
                if parts[0] == "self" and len(parts) == 2:
                    ci.calls.append((name, parts[1], held))
                elif len(parts) >= 2:
                    ci.calls.append((name, f"*.{parts[-1]}", held))
                if len(parts) >= 2 and parts[-1] in _MUTATORS:
                    attr = _self_attr(n.func.value)
                    if attr and attr not in ci.locks:
                        ci.accesses.append(_Access(
                            attr, "mutate", n.lineno, name, held))
        if isinstance(n, ast.Attribute) and isinstance(n.ctx, ast.Load):
            attr = _self_attr(n)
            if attr and attr not in ci.locks:
                ci.accesses.append(_Access(attr, "read", n.lineno,
                                           name, held))
        for child in ast.iter_child_nodes(n):
            walk(child, held)

    for stmt in node.body:
        walk(stmt, frozenset())


class LockDiscipline(Rule):
    id = "lock-discipline"
    description = ("lock-guarded attribute access outside `with self.lock` "
                   "+ lock-acquisition-order cycles (serve/registry/"
                   "observability)")

    def __init__(self, scope: Optional[Tuple[str, ...]] = DEFAULT_SCOPE
                 ) -> None:
        self.scope = scope

    def _in_scope(self, mod: ModuleInfo) -> bool:
        if self.scope is None:
            return True
        return any(mod.path.startswith(s) or mod.path == s.rstrip("/")
                   for s in self.scope)

    def inventory(self, project) -> Dict[str, List[str]]:
        """Class → lock attrs, for docs/tests ('the module-level lock
        inventory')."""
        out: Dict[str, List[str]] = {}
        for mod in project.modules.values():
            if not self._in_scope(mod):
                continue
            for ci in _collect_classes(mod):
                if ci.locks:
                    out[f"{mod.path}:{ci.name}"] = sorted(ci.locks)
        return out

    def run(self, project) -> List[Finding]:
        classes: List[_ClassInfo] = []
        for mod in project.modules.values():
            if self._in_scope(mod):
                classes.extend(_collect_classes(mod))
        lock_attr_names = {lk for ci in classes for lk in ci.locks}
        for ci in classes:
            for mname, mnode in ci.methods.items():
                _walk_method(ci, mname, mnode, lock_attr_names)
        findings = []
        for ci in classes:
            if ci.locks:
                self._propagate_entry(ci)
                findings.extend(self._discipline(ci))
        findings.extend(self._order_cycles(classes))
        return findings

    # -- entry-held propagation ----------------------------------------------

    def _propagate_entry(self, ci: _ClassInfo) -> None:
        ci.entry = {m: frozenset() for m in ci.methods}
        sites: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {}
        for caller, callee, held in ci.calls:
            if callee in ci.methods:
                sites.setdefault(callee, []).append((caller, held))
        for _ in range(4):  # fixpoint over short call chains
            changed = False
            for m in ci.methods:
                if not m.startswith("_") or m.startswith("__") \
                        or m not in sites:
                    continue  # public or uncalled: assume callable bare
                new = None
                for caller, held in sites[m]:
                    eff = held | ci.entry.get(caller, frozenset())
                    new = eff if new is None else (new & eff)
                new = new or frozenset()
                if new != ci.entry[m]:
                    ci.entry[m] = new
                    changed = True
            if not changed:
                break

    # -- per-class discipline -------------------------------------------------

    def _discipline(self, ci: _ClassInfo) -> List[Finding]:
        guards: Dict[str, Set[str]] = {}
        containers: Set[str] = set()
        for a in ci.accesses:
            held = a.held | ci.entry.get(a.method, frozenset())
            if a.kind in ("mutate", "rebind"):
                if a.kind == "mutate":
                    containers.add(a.attr)
                if a.method != "__init__" and held:
                    guards.setdefault(a.attr, set()).update(
                        h for h in held if not h.startswith("~"))
        out: List[Finding] = []
        seen = set()
        for a in ci.accesses:
            if a.method == "__init__" or a.attr not in guards:
                continue
            held = a.held | ci.entry.get(a.method, frozenset())
            if held & guards[a.attr]:
                continue
            if a.kind == "read" and a.attr not in containers:
                continue  # rebound-only pointer: GIL-atomic snapshot read
            key = (ci.name, a.method, a.attr, a.kind)
            if key in seen:
                continue
            seen.add(key)
            lock = "/".join(sorted(guards[a.attr]))
            verb = {"read": "read", "mutate": "in-place mutation",
                    "rebind": "write"}[a.kind]
            out.append(Finding(
                rule=self.id, path=ci.mod.path, line=a.line,
                message=f"{verb} of {ci.name}.{a.attr} in "
                        f"{ci.name}.{a.method} without holding "
                        f"self.{lock} (guarded elsewhere)",
                hint=f"take `with self.{lock}:` around the access, or "
                     f"justify why this thread owns the value here",
                anchor=f"{ci.name}.{a.method}:{a.attr}:{a.kind}"))
        return out

    # -- acquisition-order graph ----------------------------------------------

    def _order_cycles(self, classes: List[_ClassInfo]) -> List[Finding]:
        # unique method name → acquisition set (transitive within class)
        method_owner: Dict[str, List[Tuple[_ClassInfo, str]]] = {}
        for ci in classes:
            for m in ci.methods:
                method_owner.setdefault(m, []).append((ci, m))
        acquires: Dict[Tuple[str, str], Set[str]] = {}
        for ci in classes:
            for m in ci.methods:
                acquires[(ci.name, m)] = {
                    f"{ci.name}.{a}" for mm, a, _h, _l in ci.acquisitions
                    if mm == m and a in ci.locks}
        for _ in range(4):  # transitive closure over intra-class calls
            for ci in classes:
                for caller, callee, _held in ci.calls:
                    if callee in ci.methods:
                        acquires[(ci.name, caller)] |= \
                            acquires[(ci.name, callee)]

        def qual(ci: _ClassInfo, held_name: str) -> Optional[str]:
            if held_name.startswith("~"):
                bare = held_name[1:]
                owners = [c.name for c in classes if bare in c.locks]
                return f"{owners[0]}.{bare}" if len(owners) == 1 else None
            return f"{ci.name}.{held_name}"

        edges: Dict[str, Set[str]] = {}
        edge_site: Dict[Tuple[str, str], str] = {}

        def add_edge(a: str, b: str, site: str) -> None:
            if a != b:
                edges.setdefault(a, set()).add(b)
                edge_site.setdefault((a, b), site)

        for ci in classes:
            for m, acq, held, line in ci.acquisitions:
                tgt = qual(ci, f"~{acq}" if acq not in ci.locks else acq)
                if tgt is None:
                    continue
                for h in held | ci.entry.get(m, frozenset()):
                    src = qual(ci, h)
                    if src:
                        add_edge(src, tgt, f"{ci.mod.path}:{line}")
            for m, callee, held in ci.calls:
                eff = held | ci.entry.get(m, frozenset())
                if not eff:
                    continue
                key = callee[2:] if callee.startswith("*.") else callee
                owners = method_owner.get(key, [])
                if callee.startswith("*.") and len(owners) != 1:
                    continue  # ambiguous foreign method: no edge
                for oci, om in (owners if callee.startswith("*.")
                                else [(ci, key)] if key in ci.methods
                                else []):
                    for tgt in acquires.get((oci.name, om), ()):  # noqa: B007
                        for h in eff:
                            src = qual(ci, h)
                            if src:
                                add_edge(src, tgt,
                                         f"{ci.mod.path}:{ci.name}.{m}")

        return self._find_cycles(edges, edge_site)

    def _find_cycles(self, edges, edge_site) -> List[Finding]:
        out: List[Finding] = []
        seen_cycles = set()
        state: Dict[str, int] = {}
        stack: List[str] = []

        def dfs(n: str) -> None:
            state[n] = 1
            stack.append(n)
            for m in sorted(edges.get(n, ())):
                if state.get(m, 0) == 0:
                    dfs(m)
                elif state.get(m) == 1:
                    cyc = stack[stack.index(m):] + [m]
                    lo = min(range(len(cyc) - 1), key=lambda i: cyc[i])
                    norm = tuple(cyc[lo:-1] + cyc[:lo])
                    if norm in seen_cycles:
                        continue
                    seen_cycles.add(norm)
                    site = edge_site.get((cyc[0], cyc[1]), "?")
                    out.append(Finding(
                        rule=self.id, path=site.split(":")[0],
                        line=int(site.split(":")[1])
                        if site.split(":")[1].isdigit() else 1,
                        message="lock-acquisition-order cycle: "
                                + " -> ".join(cyc)
                                + " — two threads taking these in opposite "
                                  "order deadlock",
                        hint="impose one global order (document it in "
                             "docs/static-analysis.md) or release before "
                             "calling across subsystems",
                        anchor="cycle:" + ">".join(norm)))
            stack.pop()
            state[n] = 2

        for n in sorted(edges):
            if state.get(n, 0) == 0:
                dfs(n)
        return out
