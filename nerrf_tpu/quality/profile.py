"""Reference quality profile: the distribution a published model expects.

Computed at calibration time over a held-out corpus scored through the
REAL eval path (same per-window lowering, same padded batching, same
sigmoid as `pipeline.model_detect` and the serve scorer — a profile built
through any other path would measure the path, not the model), and
stamped into the checkpoint as a ``quality_profile.json`` sidecar so the
registry publishes it with the weights.  Contents (all schema-versioned):

  * ``score``      — node-probability sketch over every real node;
  * ``features``   — per-window structural sketches: ``nodes`` / ``edges``
    / ``files`` (measured counts, the admission-side measure) and
    ``file_node_frac`` (event-type mix: file nodes over real nodes);
  * ``margin_mass`` — fraction of real-node scores within ``margin_eps``
    of the calibrated threshold: the calibration-health baseline (mass
    drifting INTO the margin means the operating point is eroding before
    a single decision flips);
  * ``alert_rate`` — fraction of windows with any node past the cut (the
    alert-rate z-score's reference numerator).

Profiles over the same ladders MERGE (count addition — associative), so
shard-built profiles and multi-host aggregates compose exactly.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np

from nerrf_tpu.quality.sketch import (
    COUNT_EDGES,
    FRACTION_EDGES,
    SCORE_EDGES,
    Sketch,
)

PROFILE_SCHEMA = 1
PROFILE_FILENAME = "quality_profile.json"

# the per-window structural features and their ladders — the ONE place
# the feature set is defined (builder, monitor and docs all key off it)
FEATURE_EDGES = {
    "nodes": COUNT_EDGES,
    "edges": COUNT_EDGES,
    "files": COUNT_EDGES,
    "file_node_frac": FRACTION_EDGES,
}


def window_features(node_mask, node_type, nodes: int, edges: int,
                    files: int) -> Dict[str, float]:
    """One window's feature values.  ``nodes``/``edges``/``files`` are the
    admission-side MEASURED counts (pre-truncation — what the window
    actually contained); the mix fraction comes from the lowered arrays."""
    from nerrf_tpu.graph.builder import NODE_TYPE_FILE

    mask = np.asarray(node_mask).astype(bool)
    real = int(mask.sum())
    file_frac = (float((np.asarray(node_type)[mask]
                        == NODE_TYPE_FILE).mean()) if real else 0.0)
    return {"nodes": float(nodes), "edges": float(edges),
            "files": float(files), "file_node_frac": file_frac}


@dataclasses.dataclass
class QualityProfile:
    """The reference distribution a version was calibrated against."""

    schema: int
    threshold: float
    margin_eps: float
    windows: int
    node_scores: int
    margin_hits: int        # real-node scores with |p - threshold| <= eps
    alert_windows: int      # windows with any real node >= threshold
    score: Sketch
    features: Dict[str, Sketch]

    @property
    def margin_mass(self) -> float:
        return self.margin_hits / self.node_scores if self.node_scores else 0.0

    @property
    def alert_rate(self) -> float:
        return self.alert_windows / self.windows if self.windows else 0.0

    def to_dict(self) -> dict:
        return {
            "schema": self.schema,
            "threshold": self.threshold,
            "margin_eps": self.margin_eps,
            "windows": self.windows,
            "node_scores": self.node_scores,
            "margin_hits": self.margin_hits,
            "alert_windows": self.alert_windows,
            "margin_mass": round(self.margin_mass, 6),
            "alert_rate": round(self.alert_rate, 6),
            "score": self.score.to_dict(),
            "features": {k: v.to_dict()
                         for k, v in sorted(self.features.items())},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "QualityProfile":
        schema = int(d.get("schema", 0))
        if schema > PROFILE_SCHEMA:
            raise ValueError(
                f"quality profile carries schema v{schema}, this code "
                f"reads v{PROFILE_SCHEMA} — written by a newer version")
        return cls(
            schema=schema,
            threshold=float(d["threshold"]),
            margin_eps=float(d["margin_eps"]),
            windows=int(d["windows"]),
            node_scores=int(d["node_scores"]),
            margin_hits=int(d["margin_hits"]),
            alert_windows=int(d["alert_windows"]),
            score=Sketch.from_dict(d["score"]),
            features={k: Sketch.from_dict(v)
                      for k, v in (d.get("features") or {}).items()},
        )

    def summary(self) -> dict:
        """The compact face (journal records, CLI tables, manifests)."""
        return {
            "schema": self.schema,
            "threshold": self.threshold,
            "windows": self.windows,
            "node_scores": self.node_scores,
            "score_quantiles": self.score.quantiles(),
            "margin_eps": self.margin_eps,
            "margin_mass": round(self.margin_mass, 4),
            "alert_rate": round(self.alert_rate, 4),
            "features": sorted(self.features),
        }


def merge_profiles(a: QualityProfile, b: QualityProfile) -> QualityProfile:
    """Count addition over every sketch and tally — associative and
    commutative, so shard-built profiles compose in any order.  Refuses
    mismatched operating points (merging distributions calibrated at
    different cuts would average two different questions)."""
    if (a.threshold, a.margin_eps) != (b.threshold, b.margin_eps):
        raise ValueError(
            f"cannot merge profiles at different operating points "
            f"(threshold/eps {a.threshold}/{a.margin_eps} vs "
            f"{b.threshold}/{b.margin_eps})")
    if set(a.features) != set(b.features):
        raise ValueError(
            f"cannot merge profiles with different feature sets "
            f"({sorted(a.features)} vs {sorted(b.features)})")
    return QualityProfile(
        schema=max(a.schema, b.schema),
        threshold=a.threshold, margin_eps=a.margin_eps,
        windows=a.windows + b.windows,
        node_scores=a.node_scores + b.node_scores,
        margin_hits=a.margin_hits + b.margin_hits,
        alert_windows=a.alert_windows + b.alert_windows,
        score=a.score.merge(b.score),
        features={k: a.features[k].merge(b.features[k])
                  for k in a.features},
    )


class ProfileBuilder:
    """Accumulates scored windows into a QualityProfile.  Pure host-side
    numpy — usable from the calibration path, a bench, or a test."""

    def __init__(self, threshold: float, margin_eps: float = 0.05) -> None:
        self.threshold = float(threshold)
        self.margin_eps = float(margin_eps)
        self._score = Sketch.empty(SCORE_EDGES)
        self._features = {k: Sketch.empty(e)
                          for k, e in FEATURE_EDGES.items()}
        self._windows = 0
        self._scores = 0
        self._margin = 0
        self._alerts = 0

    def observe_window(self, probs, node_mask, node_type,
                       nodes: int, edges: int, files: int) -> None:
        mask = np.asarray(node_mask).astype(bool)
        p = np.asarray(probs, np.float64)[mask]
        self._score.observe(p)
        feats = window_features(node_mask, node_type, nodes, edges, files)
        for k, v in feats.items():
            self._features[k].observe([v])
        self._windows += 1
        self._scores += int(p.size)
        self._margin += int((np.abs(p - self.threshold)
                             <= self.margin_eps).sum())
        self._alerts += int(bool(p.size and (p >= self.threshold).any()))

    def finish(self) -> QualityProfile:
        return QualityProfile(
            schema=PROFILE_SCHEMA,
            threshold=self.threshold, margin_eps=self.margin_eps,
            windows=self._windows, node_scores=self._scores,
            margin_hits=self._margin, alert_windows=self._alerts,
            score=self._score, features=dict(self._features))


def build_reference_profile(params, model, traces: List,
                            ds_cfg=None, threshold: Optional[float] = None,
                            margin_eps: float = 0.05, batch_size: int = 8,
                            log=None) -> QualityProfile:
    """Score ``traces`` through the real eval path and sketch the result.

    Mirrors the serve admission pipeline exactly: `snapshot_windows` →
    `measure_window` (the feature counts) → the shared
    `train.data.window_sample` lowering → `pipeline.pad_batch` → the
    vmapped eval → host sigmoid.  What the profile describes is therefore
    the distribution the serve monitor will actually observe."""
    import jax

    from nerrf_tpu.data.loaders import Trace
    from nerrf_tpu.graph.builder import measure_window, snapshot_windows
    from nerrf_tpu.pipeline import pad_batch
    from nerrf_tpu.train.data import DatasetConfig, window_sample
    from nerrf_tpu.train.loop import make_eval_fn

    ds_cfg = ds_cfg or DatasetConfig()
    thr = threshold if threshold is not None else 0.5
    builder = ProfileBuilder(thr, margin_eps=margin_eps)
    eval_fn = make_eval_fn(model)
    pending: list = []  # (sample, nodes, edges, files)

    def flush() -> None:
        if not pending:
            return
        batch = pad_batch([p[0] for p in pending], batch_size)
        out = jax.device_get(eval_fn(params, batch))
        probs = 1.0 / (1.0 + np.exp(-out["node_logit"]))
        for j, (s, n, e, f) in enumerate(pending):
            builder.observe_window(probs[j], s["node_mask"], s["node_type"],
                                   nodes=n, edges=e, files=f)
        pending.clear()

    for trace in traces:
        ev = trace.events
        if ev.num_valid == 0:
            continue
        unlabelled = Trace(events=ev, strings=trace.strings,
                           ground_truth=None, labels=None, name=trace.name)
        valid_ts = ev.ts_ns[ev.valid]
        for lo, hi in snapshot_windows(int(valid_ts.min()),
                                       int(valid_ts.max()), ds_cfg.graph):
            n, e = measure_window(ev, lo, hi)
            sel = ev.valid & (ev.ts_ns >= lo) & (ev.ts_ns < hi)
            files = len(np.unique(ev.inode[sel & (ev.inode > 0)]))
            sample, _stats = window_sample(unlabelled, lo, hi, ds_cfg)
            if sample is None:
                continue
            pending.append((sample, int(n), int(e), int(files)))
            if len(pending) >= batch_size:
                flush()
    flush()
    profile = builder.finish()
    if log:
        log(f"quality profile: {profile.windows} windows, "
            f"{profile.node_scores} node scores, margin mass "
            f"{profile.margin_mass:.4f}, alert rate {profile.alert_rate:.4f}")
    return profile


def load_profile(path) -> Optional[QualityProfile]:
    """Read a profile from a checkpoint dir (its ``quality_profile.json``
    sidecar) or a bare profile JSON file.  None when the checkpoint
    predates profiles (the null-not-fake contract starts here); corrupt
    JSON raises the one-line error the sidecar loaders use."""
    p = Path(path)
    if p.is_dir():
        p = p / PROFILE_FILENAME
        if not p.is_file():
            return None
    elif not p.is_file():
        return None
    try:
        return QualityProfile.from_dict(json.loads(p.read_text()))
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise ValueError(
            f"corrupt quality profile {p}: not valid JSON ({e})") from None
    except (KeyError, TypeError) as e:
        raise ValueError(
            f"corrupt quality profile {p}: missing or malformed field "
            f"({e!r})") from None
