"""Ingest layer: native C++ bridge vs Python fallback, wire round-trips,
and the gRPC Tracker service loop.

The native library is built on demand by bridge.py (make, ~1 s); tests that
need it skip cleanly if g++/make are unavailable.
"""

import numpy as np
import pytest

from nerrf_tpu.data import SimConfig, simulate_trace
from nerrf_tpu.ingest import (
    IngestBridge,
    RECORD_SIZE,
    encode_ring_records,
    events_to_batch_frames,
    native_available,
)
from nerrf_tpu.schema import EventArrays, StringTable, Syscall

needs_native = pytest.mark.skipif(
    not native_available(), reason="libnerrf_ingest.so not built"
)


@pytest.fixture(scope="module")
def trace():
    cfg = SimConfig(num_target_files=6, duration_sec=30.0, seed=7)
    return simulate_trace(cfg)


def _columns_equal(a: EventArrays, b: EventArrays):
    for name, col_a in a.columns().items():
        np.testing.assert_array_equal(col_a, b.columns()[name], err_msg=name)


def _resolve(events, strings):
    """Materialize records to compare across bridges with different id spaces."""
    return [r for r in events.iter_records(strings)]


# --- ring record path --------------------------------------------------------


def test_ring_roundtrip_python():
    ev, strings, _ = _make_small()
    buf = encode_ring_records(ev, strings)
    assert len(buf) == len(ev) * RECORD_SIZE
    bridge = IngestBridge(use_native=False)
    got = bridge.decode_ring(buf)
    # ring records only carry the binary-record fields
    for i in range(len(ev)):
        assert got.ts_ns[i] == ev.ts_ns[i]
        assert got.pid[i] == ev.pid[i]
        assert got.syscall[i] == ev.syscall[i]
        assert got.bytes[i] == ev.bytes[i]
    tbl = bridge.string_table()
    assert tbl.lookup(int(got.path_id[1])) == strings.lookup(int(ev.path_id[1]))


@needs_native
def test_ring_native_matches_python(trace):
    ev, strings = trace.events, trace.strings
    buf = encode_ring_records(ev, strings)
    nat = IngestBridge(use_native=True)
    py = IngestBridge(use_native=False)
    got_n = nat.decode_ring(buf, boot_epoch_ns=123)
    got_p = py.decode_ring(buf, boot_epoch_ns=123)
    recs_n = _resolve(got_n, nat.string_table())
    recs_p = _resolve(got_p, py.string_table())
    assert recs_n == recs_p


@needs_native
def test_ring_rejects_misaligned():
    nat = IngestBridge(use_native=True)
    with pytest.raises(ValueError):
        nat.decode_ring(b"\0" * (RECORD_SIZE + 1))


# --- protobuf wire path ------------------------------------------------------


@needs_native
def test_batch_native_matches_python(trace):
    ev, strings = trace.events, trace.strings
    frames = events_to_batch_frames(ev, strings, batch_size=50)
    assert len(frames) > 1  # real batching
    nat = IngestBridge(use_native=True)
    py = IngestBridge(use_native=False)
    recs_n, recs_p = [], []
    for f in frames:
        recs_n += _resolve(nat.decode_batch(f), nat.string_table())
        recs_p += _resolve(py.decode_batch(f), py.string_table())
    assert recs_n == recs_p
    # wire carries everything the jsonl format does
    src = _resolve(ev, strings)
    assert [r["path"] for r in recs_n] == [r["path"] for r in src]
    assert [r["ts_ns"] for r in recs_n] == [r["ts_ns"] for r in src]
    assert [r["ret_val"] for r in recs_n] == [r["ret_val"] for r in src]


@needs_native
def test_batch_negative_retval_zigzag():
    # sint64 on the wire — a sign bug would explode -9 into a huge varint
    ev, strings, _ = _make_small(ret_val=-9)
    frame = events_to_batch_frames(ev, strings)[0]
    nat = IngestBridge(use_native=True)
    got = nat.decode_batch(frame)
    assert int(got.ret_val[0]) == -9


@needs_native
def test_batch_malformed_frame_fails_closed():
    nat = IngestBridge(use_native=True)
    with pytest.raises(ValueError):
        nat.decode_batch(b"\x0a\xff\xff\xff\xff\x7f")  # length overruns buffer


# --- gRPC service loop -------------------------------------------------------


@pytest.mark.parametrize("use_native", [False, True])
def test_grpc_stream_end_to_end(trace, use_native):
    if use_native and not native_available():
        pytest.skip("native library not built")
    grpc = pytest.importorskip("grpc")
    from nerrf_tpu.ingest import TraceReplayServer, TrackerClient

    ev, strings = trace.events, trace.strings
    server = TraceReplayServer(ev, strings, batch_size=32)
    port = server.start()
    try:
        client = TrackerClient(
            f"127.0.0.1:{port}", IngestBridge(use_native=use_native)
        )
        got, tbl = client.stream(timeout=20.0)
    finally:
        server.stop()
    assert got.num_valid == ev.num_valid
    assert _resolve(got, tbl) == _resolve(ev, strings)


# --- helpers -----------------------------------------------------------------


def _make_small(ret_val: int = 3):
    strings = StringTable()
    ev = EventArrays.from_records(
        [
            {
                "ts_ns": 1_700_000_000_123_456_789,
                "pid": 41,
                "comm": "python3",
                "syscall": "openat",
                "path": "/app/uploads/a.dat",
                "ret_val": ret_val,
                "inode": 77,
            },
            {
                "ts_ns": 1_700_000_001_000_000_000,
                "pid": 41,
                "comm": "python3",
                "syscall": "rename",
                "path": "/app/uploads/a.dat",
                "new_path": "/app/uploads/a.dat.lockbit3",
                "inode": 77,
            },
        ],
        strings,
    )
    return ev, strings, None


def test_grpc_replay_exceeding_queue_slots_drops_nothing():
    pytest.importorskip("grpc")
    from nerrf_tpu.ingest import TraceReplayServer, TrackerClient

    strings = StringTable()
    ev = EventArrays.from_records(
        [{"ts_ns": i, "pid": 1, "syscall": "write", "path": f"/f{i}", "bytes": 1}
         for i in range(150)],
        strings,
    )
    server = TraceReplayServer(ev, strings, batch_size=1, queue_slots=100)
    port = server.start()
    try:
        got, _ = TrackerClient(f"127.0.0.1:{port}",
                               IngestBridge(use_native=False)).stream(timeout=20.0)
    finally:
        server.stop()
    assert got.num_valid == 150  # 150 frames > 100 slots: replay must not drop
