from nerrf_tpu.graph.builder import (
    GraphConfig,
    GraphBatch,
    WindowStats,
    build_window_graph,
    snapshot_windows,
    trace_snapshots,
    NODE_FEATURE_DIM,
    EDGE_FEATURE_DIM,
)
from nerrf_tpu.graph.store import TraceStore, store_native_available

__all__ = [
    "TraceStore",
    "store_native_available",
    "GraphConfig",
    "GraphBatch",
    "WindowStats",
    "build_window_graph",
    "snapshot_windows",
    "trace_snapshots",
    "NODE_FEATURE_DIM",
    "EDGE_FEATURE_DIM",
]
