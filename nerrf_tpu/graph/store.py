"""Time-bucketed trace store: ctypes binding + format-compatible fallback.

The persistence layer the reference specified as "RocksDB with 30 s delta
compaction" for its trace/graph data (`/root/reference/README.md:113`,
`ROADMAP.md:58`) but never implemented.  Here it is an embedded store whose
compaction unit *is* the graph constructor's time bucket, so the sliding
window of `architecture.mdx:32-43` reads only the segments it overlaps.

Two interchangeable engines over one on-disk format (byte-compatible, see
native/include/nerrf/tracestore.h):

  * native C++ (`libnerrf_tracestore.so`, built on demand) — the production
    path, keeping hot appends/queries off the Python heap;
  * pure-Python fallback — same files, used when no toolchain is available.

A store written by one engine opens under the other; tests assert this.
"""

from __future__ import annotations

import ctypes
import os
import re
import struct
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from nerrf_tpu.ingest.bridge import _Columns, _alloc_columns, load_native_lib
from nerrf_tpu.schema.events import EventArrays, StringTable
from nerrf_tpu.tracing import span as trace_span

_LIB_NAME = "libnerrf_tracestore.so"

DEFAULT_BUCKET_NS = 30 * 10**9
AUTO_FLUSH_ROWS = 1 << 18  # keep in sync with tracestore.cc kAutoFlushRows
_MAGIC = b"NRRFSEG1"

RECORD_DTYPE = np.dtype([
    ("ts_ns", "<i8"), ("pid", "<i4"), ("tid", "<i4"), ("comm_id", "<i4"),
    ("syscall", "<i4"), ("path_id", "<i4"), ("new_path_id", "<i4"),
    ("flags", "<i4"), ("ret_val", "<i8"), ("bytes", "<i8"), ("inode", "<i8"),
    ("mode", "<i4"), ("uid", "<i4"), ("gid", "<i4"),
])
assert RECORD_DTYPE.itemsize == 72


def _load_library(build: bool = True) -> Optional[ctypes.CDLL]:
    lib = load_native_lib(_LIB_NAME, build)
    if lib is None:
        return None
    lib.nerrf_store_open.restype = ctypes.c_void_p
    lib.nerrf_store_open.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.nerrf_store_close.argtypes = [ctypes.c_void_p]
    lib.nerrf_store_append.restype = ctypes.c_int64
    lib.nerrf_store_append.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(_Columns), ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_size_t,
    ]
    for name in ("flush", "num_strings", "num_segments", "delta_rows",
                 "total_rows"):
        fn = getattr(lib, f"nerrf_store_{name}")
        fn.restype = ctypes.c_int64
        fn.argtypes = [ctypes.c_void_p]
    lib.nerrf_store_query_count.restype = ctypes.c_int64
    lib.nerrf_store_query_count.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
    ]
    lib.nerrf_store_query.restype = ctypes.c_int64
    lib.nerrf_store_query.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(_Columns), ctypes.c_size_t,
    ]
    lib.nerrf_store_string.restype = ctypes.c_char_p
    lib.nerrf_store_string.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    return lib


_LIB: Optional[ctypes.CDLL] = None
_LIB_TRIED = False


def store_native_available() -> bool:
    global _LIB, _LIB_TRIED
    if not _LIB_TRIED:
        _LIB_TRIED = True
        if os.environ.get("NERRF_NO_NATIVE") != "1":
            _LIB = _load_library()
    return _LIB is not None


def _events_as_columns(events: EventArrays) -> Tuple[_Columns, list]:
    """EventArrays → a _Columns view (keeps the backing arrays alive)."""
    keep = []

    def ptr(arr, ctyp):
        arr = np.ascontiguousarray(arr)
        keep.append(arr)
        return arr.ctypes.data_as(ctypes.POINTER(ctyp))

    cols = _Columns(
        ts_ns=ptr(events.ts_ns, ctypes.c_int64),
        pid=ptr(events.pid, ctypes.c_int32),
        tid=ptr(events.tid, ctypes.c_int32),
        comm_id=ptr(events.comm_id, ctypes.c_int32),
        syscall_id=ptr(events.syscall, ctypes.c_int32),
        path_id=ptr(events.path_id, ctypes.c_int32),
        new_path_id=ptr(events.new_path_id, ctypes.c_int32),
        flags=ptr(events.flags, ctypes.c_int32),
        ret_val=ptr(events.ret_val, ctypes.c_int64),
        bytes=ptr(events.bytes, ctypes.c_int64),
        inode=ptr(events.inode, ctypes.c_int64),
        mode=ptr(events.mode, ctypes.c_int32),
        uid=ptr(events.uid, ctypes.c_int32),
        gid=ptr(events.gid, ctypes.c_int32),
        valid=ptr(events.valid.astype(np.uint8), ctypes.c_uint8),
    )
    return cols, keep


class TraceStore:
    """One store directory; see module docstring for the engine contract."""

    def __init__(self, root: str | Path, bucket_sec: float = 30.0,
                 use_native: Optional[bool] = None) -> None:
        self.root = Path(root)
        self.bucket_ns = int(bucket_sec * 1e9)
        # a stored BUCKET wins: bucket math must match the on-disk segments.
        # Tolerate a corrupt/empty file (crash mid-create) like the native
        # engine does — fall back to the caller's value.
        bpath = self.root / "BUCKET"
        if bpath.exists():
            try:
                stored = int(bpath.read_text().strip())
            except ValueError:
                stored = 0
            if stored > 0:
                self.bucket_ns = stored
        else:
            self.root.mkdir(parents=True, exist_ok=True)
            tmp = self.root / ".BUCKET.tmp"
            tmp.write_text(f"{self.bucket_ns}\n")
            tmp.rename(bpath)
        if use_native is None:
            use_native = store_native_available()
        elif use_native and not store_native_available():
            raise RuntimeError(f"native store library {_LIB_NAME} not available")
        self._native = bool(use_native)
        if self._native:
            handle = _LIB.nerrf_store_open(str(self.root).encode(), self.bucket_ns)
            if not handle:
                raise OSError(f"nerrf_store_open failed for {self.root}")
            self._handle = ctypes.c_void_p(handle)
        else:
            self._py = _PyStore(self.root, self.bucket_ns)
        # pool view handed to query() callers; the pool is append-only and ids
        # are stable, so the table is extended incrementally, never rebuilt
        self._pool_view = StringTable()

    # --- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        if self._native:
            if self._handle:
                _LIB.nerrf_store_close(self._handle)
                self._handle = None
        else:
            self._py.close()

    def __enter__(self) -> "TraceStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def is_native(self) -> bool:
        return self._native

    # --- writes -------------------------------------------------------------

    def append(self, events: EventArrays, strings: StringTable) -> int:
        if self._native:
            cols, keep = _events_as_columns(events)
            pool = [s.encode() for s in strings.strings()]
            arr = (ctypes.c_char_p * len(pool))(*pool)
            got = _LIB.nerrf_store_append(
                self._handle, ctypes.byref(cols), len(events), arr, len(pool)
            )
            del keep
            if got < 0:
                raise OSError("nerrf_store_append failed")
            return int(got)
        return self._py.append(events, strings)

    def flush(self) -> int:
        with trace_span("store_compact") as sp:
            if self._native:
                got = _LIB.nerrf_store_flush(self._handle)
                if got < 0:
                    raise OSError("nerrf_store_flush failed")
                got = int(got)
            else:
                got = self._py.flush()
            sp.args["segments"] = got
        from nerrf_tpu.observability import DEFAULT_REGISTRY

        DEFAULT_REGISTRY.counter_inc(
            "store_compactions_total", got,
            help="bucket segments written by delta compaction")
        DEFAULT_REGISTRY.gauge_set(
            "store_segments", self.num_segments,
            help="live segment files in the trace store")
        return got

    # --- reads --------------------------------------------------------------

    def query_count(self, start_ns: int, end_ns: int) -> int:
        if self._native:
            return int(_LIB.nerrf_store_query_count(self._handle, start_ns, end_ns))
        return self._py.query_count(start_ns, end_ns)

    def query(self, start_ns: int, end_ns: int) -> Tuple[EventArrays, StringTable]:
        """Events in [start_ns, end_ns) sorted by time, with a StringTable
        whose ids match the returned columns (identity view of the pool)."""
        with trace_span("store_query"):
            return self._query(start_ns, end_ns)

    def _query(self, start_ns: int, end_ns: int) -> Tuple[EventArrays, StringTable]:
        if self._native:
            # start with a window-sized guess; on -(needed)-1 retry with the
            # exact size.  Bounded by total rows so allocation never exceeds
            # the store; typical window queries never retry more than once.
            cap = min(int(_LIB.nerrf_store_total_rows(self._handle)), 1 << 16)
            while True:
                arrs, cols = _alloc_columns(cap)
                got = _LIB.nerrf_store_query(
                    self._handle, start_ns, end_ns, ctypes.byref(cols), cap
                )
                if got >= 0:
                    break
                if got == -1:
                    raise OSError("nerrf_store_query failed")
                cap = -int(got) - 1  # needed size reported by the store
            n = int(got)
            arrs = {k: v[:n] for k, v in arrs.items()}
            events = EventArrays(
                ts_ns=arrs["ts_ns"], pid=arrs["pid"], tid=arrs["tid"],
                comm_id=arrs["comm_id"], syscall=arrs["syscall_id"],
                path_id=arrs["path_id"], new_path_id=arrs["new_path_id"],
                flags=arrs["flags"], ret_val=arrs["ret_val"],
                bytes=arrs["bytes"], inode=arrs["inode"], mode=arrs["mode"],
                uid=arrs["uid"], gid=arrs["gid"],
                valid=arrs["valid"].astype(np.bool_),
            )
        else:
            events = self._py.query_events(start_ns, end_ns)
        return events, self._pool_table()

    def _pool_table(self) -> StringTable:
        """Extend the cached pool view up to the current pool size."""
        start = len(self._pool_view)
        if self._native:
            total = int(_LIB.nerrf_store_num_strings(self._handle))
            for i in range(start, total):
                s = _LIB.nerrf_store_string(self._handle, i)
                self._pool_view.intern(
                    s.decode("utf-8", "replace") if s is not None else "")
        else:
            for s in self._py.strings[start:]:
                self._pool_view.intern(s)
        return self._pool_view

    # --- observability ------------------------------------------------------

    @property
    def num_segments(self) -> int:
        if self._native:
            return int(_LIB.nerrf_store_num_segments(self._handle))
        return self._py.num_segments

    @property
    def delta_rows(self) -> int:
        if self._native:
            return int(_LIB.nerrf_store_delta_rows(self._handle))
        return sum(len(r) for r in self._py.delta)

    @property
    def num_strings(self) -> int:
        if self._native:
            return int(_LIB.nerrf_store_num_strings(self._handle))
        return len(self._py.strings)


# --------------------------------------------------------------------------
# pure-Python engine (same format)
# --------------------------------------------------------------------------

class _PyStore:
    def __init__(self, root: Path, bucket_ns: int) -> None:
        self.root = root
        self.bucket_ns = bucket_ns
        self.segdir = root / "segments"
        self.segdir.mkdir(parents=True, exist_ok=True)
        self.delta: list[np.ndarray] = []  # RECORD_DTYPE rows
        self.strings: list[str] = [""]
        self.index: dict[str, int] = {"": 0}
        self.next_seq = 0
        self.segments: dict[int, tuple[int, Path]] = {}  # bucket -> (seq, path)

        slog = root / "strings.log"
        if slog.exists():
            data = slog.read_bytes()
            off, good, pool = 0, 0, []
            while off + 4 <= len(data):
                (ln,) = struct.unpack_from("<I", data, off)
                if off + 4 + ln > len(data):
                    break  # truncated tail
                pool.append(data[off + 4:off + 4 + ln].decode("utf-8", "replace"))
                off += 4 + ln
                good = off
            for s in pool:
                if s not in self.index:
                    self.index[s] = len(self.strings)
                    self.strings.append(s)
            if good < len(data):
                # drop the torn bytes so appends land on a record boundary
                with open(slog, "r+b") as f:
                    f.truncate(good)
        self._slog = open(slog, "ab")
        if self._slog.tell() == 0:
            for s in self.strings:
                b = s.encode()
                self._slog.write(struct.pack("<I", len(b)) + b)

        stale = []
        # segment names are "<mn>-<mx>-<seq>.seg" where mn/mx may be negative
        # (bucket < 0 for pre-epoch ts_ns) — split from the right so leading
        # minus signs parse, matching the native engine's sscanf
        seg_re = re.compile(r"^(-?\d+)-(-?\d+)-(\d+)$")
        for p in sorted(self.segdir.glob("*.seg")):
            m = seg_re.match(p.stem)
            if not m:
                continue
            mn, mx, seq = (int(x) for x in m.groups())
            self.next_seq = max(self.next_seq, seq + 1)
            cur = self.segments.get(mn)
            if cur is None or seq > cur[0]:
                if cur is not None:
                    stale.append(cur[1])
                self.segments[mn] = (seq, p, mx)
            else:
                stale.append(p)
        for p in stale:
            p.unlink(missing_ok=True)

    def close(self) -> None:
        self.flush()
        self._slog.close()

    def _intern(self, s: str) -> int:
        got = self.index.get(s)
        if got is not None:
            return got
        idx = len(self.strings)
        self.index[s] = idx
        self.strings.append(s)
        b = s.encode()
        self._slog.write(struct.pack("<I", len(b)) + b)
        return idx

    def append(self, events: EventArrays, strings: StringTable) -> int:
        remap = np.array([self._intern(s) for s in strings.strings()], np.int32)

        def mapped(ids):
            ids = np.asarray(ids, np.int64)
            ok = (ids >= 0) & (ids < len(remap))
            return np.where(ok, remap[np.clip(ids, 0, len(remap) - 1)], 0)

        mask = events.valid.astype(bool)
        n = int(mask.sum())
        rec = np.zeros(n, RECORD_DTYPE)
        rec["ts_ns"] = events.ts_ns[mask]
        rec["pid"] = events.pid[mask]
        rec["tid"] = events.tid[mask]
        rec["comm_id"] = mapped(events.comm_id[mask])
        rec["syscall"] = events.syscall[mask]
        rec["path_id"] = mapped(events.path_id[mask])
        rec["new_path_id"] = mapped(events.new_path_id[mask])
        rec["flags"] = events.flags[mask]
        rec["ret_val"] = events.ret_val[mask]
        rec["bytes"] = events.bytes[mask]
        rec["inode"] = events.inode[mask]
        rec["mode"] = events.mode[mask]
        rec["uid"] = events.uid[mask]
        rec["gid"] = events.gid[mask]
        self.delta.append(rec)
        if sum(len(r) for r in self.delta) >= AUTO_FLUSH_ROWS:
            self.flush()
        return n

    def _read_segment(self, path: Path) -> np.ndarray:
        data = path.read_bytes()
        if len(data) < 16 or data[:8] != _MAGIC:
            return np.zeros(0, RECORD_DTYPE)
        (count,) = struct.unpack_from("<Q", data, 8)
        return np.frombuffer(
            data, RECORD_DTYPE, count=count, offset=16
        ).copy()

    def _write_segment(self, bucket: int, rec: np.ndarray) -> None:
        seq = self.next_seq
        self.next_seq += 1
        name = f"{bucket}-{bucket + self.bucket_ns - 1}-{seq}.seg"
        final = self.segdir / name
        tmp = final.with_suffix(".seg.tmp")
        with open(tmp, "wb") as f:
            f.write(_MAGIC + struct.pack("<Q", len(rec)) + rec.tobytes())
        tmp.rename(final)
        old = self.segments.get(bucket)
        if old is not None:
            old[1].unlink(missing_ok=True)
        self.segments[bucket] = (seq, final, bucket + self.bucket_ns - 1)

    def flush(self) -> int:
        if not self.delta:
            return 0
        self._slog.flush()
        rec = np.concatenate(self.delta)
        rec = rec[np.argsort(rec["ts_ns"], kind="stable")]
        buckets = rec["ts_ns"] - (rec["ts_ns"] % self.bucket_ns)
        written = 0
        for bucket in np.unique(buckets):
            merged = rec[buckets == bucket]
            old = self.segments.get(int(bucket))
            if old is not None:
                merged = np.concatenate([self._read_segment(old[1]), merged])
                merged = merged[np.argsort(merged["ts_ns"], kind="stable")]
            self._write_segment(int(bucket), merged)
            written += 1
        self.delta.clear()
        return written

    def _collect(self, start_ns: int, end_ns: int) -> np.ndarray:
        parts = []
        for bucket, (_, path, max_ts) in self.segments.items():
            # skip by the segment's own stored bounds, not current bucket_ns
            if max_ts < start_ns or bucket >= end_ns:
                continue
            rec = self._read_segment(path)
            parts.append(rec[(rec["ts_ns"] >= start_ns) & (rec["ts_ns"] < end_ns)])
        for rec in self.delta:
            parts.append(rec[(rec["ts_ns"] >= start_ns) & (rec["ts_ns"] < end_ns)])
        if not parts:
            return np.zeros(0, RECORD_DTYPE)
        out = np.concatenate(parts)
        return out[np.argsort(out["ts_ns"], kind="stable")]

    def query_count(self, start_ns: int, end_ns: int) -> int:
        return len(self._collect(start_ns, end_ns))

    def query_events(self, start_ns: int, end_ns: int) -> EventArrays:
        rec = self._collect(start_ns, end_ns)
        return EventArrays(
            ts_ns=rec["ts_ns"].copy(), pid=rec["pid"].copy(),
            tid=rec["tid"].copy(), comm_id=rec["comm_id"].copy(),
            syscall=rec["syscall"].copy(), path_id=rec["path_id"].copy(),
            new_path_id=rec["new_path_id"].copy(), flags=rec["flags"].copy(),
            ret_val=rec["ret_val"].copy(), bytes=rec["bytes"].copy(),
            inode=rec["inode"].copy(), mode=rec["mode"].copy(),
            uid=rec["uid"].copy(), gid=rec["gid"].copy(),
            valid=np.ones(len(rec), np.bool_),
        )

    @property
    def num_segments(self) -> int:
        return len(self.segments)
