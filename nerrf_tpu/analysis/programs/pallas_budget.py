"""pallas-budget: static VMEM and tiling audit of the Pallas kernels.

A Mosaic VMEM allocation failure is among the most expensive bug classes
this repo has: it surfaces minutes into a chip-queue step, after the
tunnel wait and the warmup sweep, as an opaque runtime error.  The
kernels' per-grid-cell VMEM residency is fully determined by their
BlockSpecs — static data — so it can be costed on CPU in microseconds.

`ops.pallas_segment.kernel_vmem_blocks` (kept next to the kernels, so a
tiling change and its budget model move in one diff) describes what each
kernel keeps resident per grid cell; this rule costs that inventory at
every serve-ladder bucket × the model feature widths and flags anything
over the per-core VMEM budget.  The fused SAGE kernel is the reason this
exists: its message block is *full height* ([N_pad, TF] f32, double-
buffered), so its footprint grows linearly with the node bucket — fine at
the deployed 4096-node rung (~2 MiB), over budget somewhere past 16k
nodes, and a learned-ladder tuner (ROADMAP) could propose exactly such a
rung.  Also checks grid divisibility: every tile constant must respect
the (8, 128) f32 tiling and divide its padded extent.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from nerrf_tpu.analysis.engine import Finding, Rule
from nerrf_tpu.analysis.programs.abstract import finding

_PALLAS_PATH = "nerrf_tpu/ops/pallas_segment.py"

# per-core VMEM on the TPU generations in scope (v4/v5e: 16 MiB; v5p is
# larger — the floor is the portable budget)
DEFAULT_VMEM_BYTES = 16 << 20

_ITEMSIZE = {"float32": 4, "bfloat16": 2, "int32": 4, "int64": 8,
             "bool": 1, "float16": 2}


def block_bytes(blocks) -> int:
    """Total VMEM residency of one kernel's block inventory."""
    total = 0
    for _name, shape, dtype, copies in blocks:
        n = 1
        for d in shape:
            n *= int(d)
        total += n * _ITEMSIZE.get(str(dtype), 4) * int(copies)
    return total


class PallasBudget(Rule):
    id = "pallas-budget"
    description = ("Pallas block shapes × dtype vs the per-core VMEM "
                   "budget, and tile/grid divisibility, at ladder shapes")
    deep = True

    def __init__(self, vmem_bytes: int = DEFAULT_VMEM_BYTES,
                 shapes: Optional[List[Tuple[int, int, int]]] = None) -> None:
        self._budget = int(vmem_bytes)
        self._shapes = shapes

    def _ladder_shapes(self) -> List[Tuple[int, int, int]]:
        """(nodes, edges, features) audit points: every serve bucket at
        the widest feature extent the model runs the kernels at."""
        from nerrf_tpu.graph.builder import NODE_FEATURE_DIM
        from nerrf_tpu.models import GraphSAGEConfig
        from nerrf_tpu.serve.config import ServeConfig

        width = max(GraphSAGEConfig().hidden, NODE_FEATURE_DIM)
        return [(n, e, width) for n, e, _s in ServeConfig().buckets]

    def run(self, project) -> List[Finding]:
        from nerrf_tpu.ops.pallas_segment import (
            kernel_vmem_blocks,
            tile_constants,
        )

        out: List[Finding] = []
        tiles = tile_constants()
        # TN and TF appear as LANE extents (the one-hot blocks are
        # (TE, TN); data/out blocks are (·, TF)) → multiples of 128;
        # TE only ever tiles the sublane axis → multiple of 8
        lane_mult = {"TN": 128, "TE": 8, "TF": 128}
        for name, t in tiles.items():
            mult = lane_mult.get(name, 128)
            if t % mult:
                out.append(finding(
                    self.id, _PALLAS_PATH, 1,
                    anchor=f"pallas:tile:{name}",
                    message=f"tile constant {name}={t} is not a "
                            f"multiple of {mult} — violates the "
                            f"(8, 128) f32 register tiling for the axes "
                            f"it spans",
                    hint="keep lane-extent tiles (TN, TF) multiples of "
                         "128 and sublane tiles (TE) multiples of 8"))
        shapes = self._shapes if self._shapes is not None \
            else self._ladder_shapes()
        for n, e, f in shapes:
            out.extend(self.audit(kernel_vmem_blocks(n, e, f),
                                  shape=(n, e, f)))
        return out

    def audit(self, inventories: dict, shape=None,
              budget: Optional[int] = None) -> List[Finding]:
        """Cost one ``{kernel: blocks}`` inventory against the budget —
        the fixture surface (tests feed synthetic inventories here)."""
        budget = self._budget if budget is None else int(budget)
        tag = "x".join(str(s) for s in shape) if shape else "fixture"
        out: List[Finding] = []
        for kernel, blocks in inventories.items():
            total = block_bytes(blocks)
            if total > budget:
                biggest = max(
                    blocks, key=lambda b: block_bytes([b]))
                out.append(finding(
                    self.id, _PALLAS_PATH, 1,
                    anchor=f"pallas:{kernel}:{tag}:vmem",
                    message=f"{kernel} at shape {tag}: "
                            f"{total / (1 << 20):.1f} MiB VMEM resident "
                            f"per grid cell exceeds the "
                            f"{budget / (1 << 20):.0f} MiB budget "
                            f"(dominant block: {biggest[0]} "
                            f"{biggest[1]} {biggest[2]} "
                            f"×{biggest[3]})",
                    hint="shrink the dominant block (tile the full-"
                         "height msg block, or cap the ladder rung) — "
                         "on chip this is a Mosaic allocation failure "
                         "minutes into a queue step"))
            for bname, bshape, _dtype, _copies in blocks:
                lanes = bshape[-1] if bshape else 0
                if len(bshape) >= 2 and lanes >= 128 and lanes % 128:
                    out.append(finding(
                        self.id, _PALLAS_PATH, 1,
                        anchor=f"pallas:{kernel}:{bname}:lanes",
                        message=f"{kernel}: block {bname} lane extent "
                                f"{lanes} is not a multiple of 128",
                        hint="pad the feature extent to the 128-lane "
                             "register shape"))
        return out
