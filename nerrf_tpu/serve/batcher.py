"""Continuous cross-stream micro-batcher: many streams, one device program.

The Podracer/Sebulba shape (arXiv:2104.06272) applied to detection: any
number of independent stream actors funnel window requests into per-bucket
pending queues, and a central scheduler packs same-bucket windows — from
*different* streams — into one shared padded batch for the vmapped NerrfNet
eval program.  TPU GNN throughput is won by batch occupancy, not per-call
latency (arXiv:2210.12247), so the scheduler's batch-close policy trades a
bounded deadline for occupancy:

    close bucket B's batch when  live(B) >= occupancy target
                            or   age(oldest pending in B) >= batch_close_sec
    (whichever first), subject to per-bucket in-flight limits.

Isolation properties (tested in tests/test_serve.py):
  * buckets are independent — a stalled stream starves only its own
    partial windows, never another bucket's batch close;
  * demux never blocks — scored windows are handed to a callback that the
    service keeps non-blocking (bounded alert queue, drop counted);
  * a request can be marked dropped while queued (stream backpressure or
    leave) and the scheduler skips it at assembly, so drop-oldest costs
    O(1) and never fences the device.

Spans: ``serve_batch_close`` (assembly), ``serve_device_score`` (device
program + fetch), ``serve_demux`` (per-window fan-back).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from nerrf_tpu import chaos
from nerrf_tpu.flight.journal import DEFAULT_JOURNAL
from nerrf_tpu.serve.config import Bucket, ServeConfig, bucket_tag
from nerrf_tpu.tracing import span as trace_span

# windows-per-batch occupancy ladder (batch sizes are small powers of two)
OCCUPANCY_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0)
# admit→demux latency ladder: sub-close-deadline up to multi-second stalls
LATENCY_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0)


@dataclasses.dataclass
class WindowRequest:
    """One lowered window waiting for a device slot."""

    stream: str
    window_idx: int
    lo_ns: int
    hi_ns: int
    bucket: Bucket
    sample: Optional[Dict[str, np.ndarray]]
    t_admit: float
    deadline: float
    dropped: bool = False
    # set (under the batcher lock) when assembled into a closing batch:
    # an in-flight request can no longer be dropped, only awaited
    inflight: bool = False
    # set by the batcher before on_failed when the failure is PROVEN
    # window-specific: bisection pinned it to this single window while a
    # sibling from the same original batch scored.  An all-fail batch
    # (device-wide fault) or an unbisected cohort never sets it — only
    # poison-proven windows strike their stream toward quarantine
    poison: bool = False
    # flight/SLO plane: the window's journal/span join key, plus the
    # per-stage event-time stamps (admit → packed → scorer pickup) the
    # SLO tracker turns into budget-burn attribution
    trace_id: str = ""
    t_packed: float = 0.0
    t_device: float = 0.0
    # quality plane: the admission-side MEASURED window structure
    # (pre-truncation node/edge/file counts) — the feature values the
    # drift monitor sketches, carried so demux never re-measures
    nodes: int = 0
    edges: int = 0
    files: int = 0


@dataclasses.dataclass
class ScoredWindow:
    """One window's demuxed result.  Holds only the node-level arrays the
    detection aggregation needs — the full padded sample (dominated by the
    [max_seqs, seq_len, F] sequence block) is released at scoring time so
    queued-but-unscored windows are the only ones paying full-sample RAM."""

    stream: str
    window_idx: int
    lo_ns: int
    hi_ns: int
    bucket: Bucket
    probs: np.ndarray       # float [max_nodes] node probabilities
    node_type: np.ndarray
    node_key: np.ndarray
    node_mask: np.ndarray
    t_admit: float
    t_scored: float
    late: bool
    # which registry model version scored this window (None without a
    # model manager) — the per-window stamp the swap bench asserts flips
    # at exactly one batch boundary
    model_version: Optional[int] = None
    # flight/SLO plane (mirrors WindowRequest): join key + stage stamps
    trace_id: str = ""
    t_packed: float = 0.0
    t_device: float = 0.0
    # quality plane (mirrors WindowRequest): measured window structure
    nodes: int = 0
    edges: int = 0
    files: int = 0


class MicroBatcher:
    """Per-bucket pending queues + closer/scorer threads (one device).

    ``score_fn(batch_dict) -> np.ndarray [batch_size, max_nodes]`` is the
    device program wrapper (the service's vmapped eval + sigmoid); the
    batcher itself is model-free so the packing/backpressure logic is
    testable without compiling anything.
    """

    def __init__(
        self,
        score_fn: Callable[[Dict[str, np.ndarray]], np.ndarray],
        cfg: ServeConfig,
        registry=None,
        on_scored: Optional[Callable[[List[ScoredWindow]], None]] = None,
        on_failed: Optional[Callable[[List[WindowRequest], BaseException], None]] = None,
        journal=None,
    ) -> None:
        if registry is None:
            from nerrf_tpu.observability import DEFAULT_REGISTRY

            registry = DEFAULT_REGISTRY
        self._score_fn = score_fn
        self._cfg = cfg
        self._reg = registry
        self._journal = journal if journal is not None else DEFAULT_JOURNAL
        self._on_scored = on_scored or (lambda scored: None)
        self._on_failed = on_failed or (lambda reqs, exc: None)
        self._lock = threading.Lock()
        self._kick = threading.Event()
        self._pending: Dict[Bucket, deque] = {}
        self._live: Dict[Bucket, int] = {}
        self._inflight: Dict[Bucket, int] = {}
        self._warmed: set = set()
        self._ready: "queue.Queue" = queue.Queue()
        self._running = False
        self._threads: List[threading.Thread] = []
        # scorer watchdog state (all under _lock): when one device call
        # has been stuck past cfg.scorer_wedge_sec the batcher is WEDGED —
        # readiness fails and leave() stops waiting, instead of every
        # stream hanging on a dead scorer thread.  Cleared the moment the
        # stuck call returns (journaled both ways).
        self._scoring_since: Optional[float] = None
        self._scoring_bucket: Optional[str] = None
        self._wedged = False

    # -- submission (stream threads) -----------------------------------------

    def submit(self, req: WindowRequest) -> None:
        with self._lock:
            self._pending.setdefault(req.bucket, deque()).append(req)
            self._live[req.bucket] = self._live.get(req.bucket, 0) + 1
            depth = self._live[req.bucket]
        self._reg.gauge_set(
            "serve_queue_depth", depth,
            labels={"bucket": bucket_tag(req.bucket)},
            help="windows pending per capacity bucket")
        self._kick.set()

    def mark_dropped(self, req: WindowRequest) -> bool:
        """Drop a queued request in place (drop-oldest backpressure, stream
        leave).  O(1): the scheduler skips dropped entries at assembly.
        Returns False when the request is already dropped or already
        assembled into an in-flight batch (then it must be awaited)."""
        with self._lock:
            if req.dropped or req.inflight:
                return False
            req.dropped = True
            req.sample = None
            self._live[req.bucket] = max(self._live.get(req.bucket, 1) - 1, 0)
            return True

    def mark_warm(self, bucket: Bucket) -> None:
        """Register a bucket whose device program is compiled; scoring any
        other bucket after start counts into serve_recompiles_total."""
        with self._lock:
            # nerrflint: ok[bounded-growth] one entry per bucket-ladder rung — warmup iterates the configured ladder and select_bucket cannot escape it, so the set is config-bounded
            self._warmed.add(tuple(bucket))

    def queue_depth(self, bucket: Bucket) -> int:
        with self._lock:
            return self._live.get(bucket, 0)

    @property
    def running(self) -> bool:
        return self._running

    @property
    def wedged(self) -> bool:
        with self._lock:
            return self._wedged

    @property
    def healthy(self) -> bool:
        """Running and not wedged — what readiness and leave() key off."""
        return self._running and not self.wedged

    # -- batch close ----------------------------------------------------------

    def _collect_ready(self, now: float, force: bool = False
                       ) -> List[Tuple[Bucket, List[WindowRequest], str]]:
        out = []
        with self._lock:
            for bucket, dq in self._pending.items():
                while dq and dq[0].dropped:
                    dq.popleft()
                if not dq:
                    continue
                if not force and \
                        self._inflight.get(bucket, 0) >= self._cfg.max_inflight_batches:
                    continue
                live = self._live.get(bucket, 0)
                age = now - dq[0].t_admit
                if not (force or live >= self._cfg.occupancy
                        or age >= self._cfg.batch_close_sec):
                    continue
                reqs: List[WindowRequest] = []
                while dq and len(reqs) < self._cfg.batch_size:
                    r = dq.popleft()
                    if not r.dropped:
                        r.inflight = True
                        r.t_packed = now  # SLO stage stamp: queue ends here
                        reqs.append(r)
                if not reqs:
                    continue
                self._live[bucket] = max(live - len(reqs), 0)
                self._inflight[bucket] = self._inflight.get(bucket, 0) + 1
                cause = ("flush" if force else
                         "occupancy" if len(reqs) >= self._cfg.occupancy
                         else "deadline")
                out.append((bucket, reqs, cause))
        return out

    def _emit_batch(self, bucket: Bucket, reqs: List[WindowRequest],
                    cause: str) -> None:
        tag = bucket_tag(bucket)
        with self._lock:
            # stream threads mutate _live concurrently; the post-close
            # depth must be a locked read, not a racy .get
            depth = self._live.get(bucket, 0)
        with trace_span("serve_batch_close", bucket=tag, cause=cause,
                        windows=len(reqs)) as sp:
            self._reg.counter_inc(
                "serve_batches_total", labels={"bucket": tag, "cause": cause},
                help="shared device batches closed, by bucket and close cause")
            self._reg.histogram_observe(
                "serve_batch_occupancy", float(len(reqs)),
                buckets=OCCUPANCY_BUCKETS, labels={"bucket": tag},
                help="real windows packed per shared device batch")
            self._reg.gauge_set(
                "serve_queue_depth", depth,
                labels={"bucket": tag},
                help="windows pending per capacity bucket")
            # the batch-close record the flight recorder's bundles key off:
            # bucket, close cause, occupancy vs padded slots, post-close
            # depth, and every packed window's trace ID (span join keys)
            rec = self._journal.record(
                "batch_close", bucket=tag, cause=cause,
                occupancy=len(reqs),
                padding=self._cfg.batch_size - len(reqs),
                depth_after=depth,
                streams=sorted({r.stream for r in reqs}),
                trace_ids=[r.trace_id for r in reqs if r.trace_id])
            sp.args["journal_seq"] = rec.seq
        self._ready.put((bucket, reqs, cause))

    # -- scoring --------------------------------------------------------------

    def _stack(self, reqs: List[WindowRequest]) -> Dict[str, np.ndarray]:
        """Exactly model_detect's fixed-shape batching (the shared
        `pipeline.pad_batch`): stack the window samples and zero-pad the
        tail so every launch shares one shape."""
        from nerrf_tpu.pipeline import pad_batch

        return pad_batch([r.sample for r in reqs], self._cfg.batch_size)

    def _score_batch(self, bucket: Bucket, reqs: List[WindowRequest]) -> None:
        tag = bucket_tag(bucket)
        with self._lock:
            warmed = tuple(bucket) in self._warmed
        if not warmed:
            self._reg.counter_inc(
                "serve_recompiles_total", labels={"bucket": tag},
                help="device batches scored at a bucket shape not compiled "
                     "during warmup (steady state must stay at 0)")
            # nerrflint: ok[atomicity-violation] benign split: set.add is idempotent and only the single scorer thread reaches here — worst case a racing drain_once double-counts one recompile
            self.mark_warm(bucket)
        failures: List[Tuple[List[WindowRequest], BaseException]] = []
        scored_n = self._score_cohort(bucket, tag, reqs, 0, failures)
        for f_reqs, exc in failures:
            # poison evidence needs ALL of: pinned to a single window,
            # a sibling from the same original batch scored (an all-fail
            # batch, or a lone occupancy-1 deadline batch, indicts the
            # device and strikes nobody), AND the window fails a CONFIRM
            # re-run — one failed retry on an intermittently-failing
            # device proves nothing about the window's stream
            if scored_n > 0 and len(f_reqs) == 1 \
                    and self._cfg.bisect_failed_batches:
                confirm: List[Tuple[List[WindowRequest],
                                    BaseException]] = []
                if self._score_cohort(bucket, tag, f_reqs, 0, confirm):
                    scored_n += 1  # intermittent fault: window delivered
                    continue
                for c_reqs, c_exc in confirm:
                    for r in c_reqs:
                        r.poison = True  # failed twice, siblings scored
                    self._on_failed(c_reqs, c_exc)
                continue
            self._on_failed(f_reqs, exc)

    def _score_cohort(self, bucket: Bucket, tag: str,
                      reqs: List[WindowRequest], depth: int,
                      failures: List[Tuple[List[WindowRequest],
                                           BaseException]]) -> int:
        """Score one cohort; on failure, bisect to isolate the poison.
        Returns how many windows SCORED; terminal failures are appended
        to ``failures`` (delivered by `_score_batch` once the whole
        original batch's outcome — the poison evidence — is known).

        A shared batch means one poisoned window (NaN-ing the program, or
        a genuine device fault its data provokes) used to cost every
        cohabiting stream's windows in the batch.  Instead: split the
        failed cohort in half and retry each half — retried cohorts
        re-pad to the same ``batch_size`` shape, so retries reuse the
        compiled program (zero-recompile contract intact) — until the
        failure is pinned to single windows.  Every window that did NOT
        provoke the fault scores normally.  Cost is logarithmic:
        isolating one poison window in a batch of B re-runs the program
        ~2·log2(B) times, only while failing."""
        batch = self._stack(reqs)
        t_device = time.perf_counter()
        for r in reqs:
            r.t_device = t_device  # SLO stage stamp: scorer pickup
        # watchdog window: ONE device call (this cohort's), not the whole
        # bisection recursion — each retry re-stamps, so a slow-but-
        # progressing isolation can never be mistaken for a wedge
        with self._lock:
            self._scoring_since = t_device
            self._scoring_bucket = tag
        try:
            with trace_span("serve_device_score", device=True, bucket=tag,
                            windows=len(reqs)):
                # chaos fault points (no-ops disarmed): a whole-batch
                # device fault / latency spike, and the per-window poison
                # (keyed by trace ID so bisection retries fire the same
                # way the first score did — that is what lets the split
                # isolate exactly the injected window)
                chaos.inject("serve.device_latency", bucket=tag,
                             windows=len(reqs))
                chaos.inject("serve.device_error", bucket=tag,
                             windows=len(reqs))
                for r in reqs:
                    chaos.inject("serve.poison_window", key=r.trace_id,
                                 stream=r.stream, window_idx=r.window_idx,
                                 bucket=tag)
                out = self._score_fn(batch)
                # a version-stamping score_fn (the registry-managed serve
                # path) returns (probs, model_version); plain score_fns
                # keep returning the bare array
                probs, version = out if isinstance(out, tuple) \
                    else (out, None)
                probs = np.asarray(probs)
        except Exception as exc:  # noqa: BLE001 — one bad batch must not
            # kill the scorer thread and wedge every stream behind it
            self._reg.counter_inc(
                "serve_batch_failures_total", labels={"bucket": tag},
                help="device batches whose scoring raised")
            self._journal.record(
                "batch_failed", bucket=tag, windows=len(reqs), depth=depth,
                error=f"{type(exc).__name__}: {exc}",
                trace_ids=[r.trace_id for r in reqs if r.trace_id])
            if len(reqs) > 1 and self._cfg.bisect_failed_batches:
                self._reg.counter_inc(
                    "serve_poison_bisections_total", labels={"bucket": tag},
                    help="failed shared batches split-and-retried to "
                         "isolate the poisoning window")
                self._journal.record(
                    "batch_bisect", bucket=tag, windows=len(reqs),
                    depth=depth,
                    trace_ids=[r.trace_id for r in reqs if r.trace_id])
                mid = len(reqs) // 2
                return (self._score_cohort(bucket, tag, reqs[:mid],
                                           depth + 1, failures)
                        + self._score_cohort(bucket, tag, reqs[mid:],
                                             depth + 1, failures))
            failures.append((list(reqs), exc))
            return 0
        finally:
            with self._lock:
                self._scoring_since = None
        now = time.perf_counter()
        scored: List[ScoredWindow] = []
        with trace_span("serve_demux", bucket=tag, windows=len(reqs)):
            for j, r in enumerate(reqs):
                late = now > r.deadline
                if late:
                    self._reg.counter_inc(
                        "serve_late_windows_total",
                        help="windows scored after their admit→alert "
                             "deadline (served, but SLO-late)")
                self._reg.histogram_observe(
                    "serve_window_latency_seconds", now - r.t_admit,
                    buckets=LATENCY_BUCKETS,
                    help="window admit→demux latency")
                s = r.sample
                scored.append(ScoredWindow(
                    stream=r.stream, window_idx=r.window_idx,
                    lo_ns=r.lo_ns, hi_ns=r.hi_ns, bucket=bucket,
                    probs=probs[j], node_type=s["node_type"],
                    node_key=s["node_key"], node_mask=s["node_mask"],
                    t_admit=r.t_admit, t_scored=now, late=late,
                    model_version=version, trace_id=r.trace_id,
                    t_packed=r.t_packed, t_device=r.t_device,
                    nodes=r.nodes, edges=r.edges, files=r.files))
                r.sample = None  # release the padded sample's memory
            self._reg.counter_inc(
                "serve_windows_scored_total", len(reqs),
                help="windows scored through shared device batches")
            self._on_scored(scored)
        return len(reqs)

    # -- threads --------------------------------------------------------------

    def _close_loop(self) -> None:
        tick = max(self._cfg.batch_close_sec / 4.0, 0.002)
        while self._running:
            self._kick.wait(timeout=tick)
            self._kick.clear()
            now = time.perf_counter()
            self._check_watchdog(now)
            for bucket, reqs, cause in self._collect_ready(now):
                self._emit_batch(bucket, reqs, cause)

    def _check_watchdog(self, now: float) -> None:
        """The closer thread doubles as the scorer's watchdog (it ticks on
        its own clock even when no batches close): one device call stuck
        past ``scorer_wedge_sec`` flips the batcher WEDGED — readiness
        fails (a probe can restart the pod) and `leave()` stops waiting —
        and the flip back is journaled the moment the call returns."""
        limit = self._cfg.scorer_wedge_sec
        if not limit:
            return
        with self._lock:
            since, bucket = self._scoring_since, self._scoring_bucket
            stuck = since is not None and now - since > limit
            flipped = None
            if stuck and not self._wedged:
                self._wedged = True
                flipped = ("scorer_wedged",
                           {"bucket": bucket,
                            "stuck_seconds": round(now - since, 2),
                            "limit_seconds": limit})
            elif self._wedged and not stuck:
                self._wedged = False
                flipped = ("scorer_recovered", {"bucket": bucket})
        if flipped is not None:
            kind, data = flipped
            self._reg.gauge_set(
                "serve_scorer_wedged", 1.0 if kind == "scorer_wedged"
                else 0.0,
                help="1 while a device call has been stuck past the "
                     "watchdog limit (readiness fails while set)")
            self._journal.record(kind, **data)

    def _score_loop(self) -> None:
        while True:
            item = self._ready.get()
            if item is None:
                return
            bucket, reqs, _cause = item
            try:
                self._score_batch(bucket, reqs)
            finally:
                with self._lock:
                    self._inflight[bucket] = max(
                        self._inflight.get(bucket, 1) - 1, 0)
                self._kick.set()  # an inflight slot freed: re-check closes

    def start(self) -> None:
        if self._running:
            return
        self._running = True
        # the wedge gauge must EXIST on a healthy pod — an alert on
        # serve_scorer_wedged == 1 has to read 0, not "no data"
        self._reg.gauge_set(
            "serve_scorer_wedged", 0.0,
            help="1 while a device call has been stuck past the "
                 "watchdog limit (readiness fails while set)")
        self._threads = [
            threading.Thread(target=self._close_loop,
                             name="nerrf-serve-closer", daemon=True),
            threading.Thread(target=self._score_loop,
                             name="nerrf-serve-scorer", daemon=True),
        ]
        for t in self._threads:
            t.start()

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        if not self._running:
            return
        self._running = False
        self._kick.set()
        self._threads[0].join(timeout=timeout)
        if drain:
            # repeat until empty: one pass closes at most batch_size per
            # bucket, and a deep queue abandoned here would be an
            # UNCOUNTED drop (every other loss path has a counter)
            while True:
                batches = self._collect_ready(time.perf_counter(),
                                              force=True)
                if not batches:
                    break
                for bucket, reqs, cause in batches:
                    self._emit_batch(bucket, reqs, cause)
        self._ready.put(None)
        self._threads[1].join(timeout=timeout)
        self._threads = []

    def drain_once(self, force: bool = False) -> int:
        """Synchronous single-threaded operation (tests, shutdown): close
        every due batch — all non-empty buckets when ``force`` — and score
        them inline.  Returns the number of batches scored."""
        batches = self._collect_ready(time.perf_counter(), force=force)
        for bucket, reqs, cause in batches:
            self._emit_batch(bucket, reqs, cause)
            item = self._ready.get()
            try:
                self._score_batch(item[0], item[1])
            finally:
                with self._lock:
                    self._inflight[item[0]] = max(
                        self._inflight.get(item[0], 1) - 1, 0)
        return len(batches)
