"""Per-event label derivation from window-level ground truth.

The reference's checked-in ground truth labels a single attack *window*
(`benchmarks/m1/results/m1_ground_truth.csv`), not individual events, while
its docs sketch per-event `is_attack` columns (`threat-model.mdx:108-119`).
This module bridges the two: given a window + target path, score each event by
the threat model's indicator heuristics (window membership, target-directory
writes/renames, suspicious extension, /proc recon reads, ransom-note names —
`docs/content/docs/architecture.mdx:112-120`).

Indicator logic lives in `schema.events.path_features` (one row per interned
string); here we only gather those rows by path id, so the per-event cost is a
vectorized lookup rather than Python string work — important at the ~25k
events/trace density the reference docs project (`threat-model.mdx:121-137`).
"""

from __future__ import annotations

import numpy as np

from nerrf_tpu.data.loaders import Trace
from nerrf_tpu.schema.events import Syscall

# Column indices into path_features() rows (see schema.events.path_features).
_F_PROC = 0
_F_SYSTEM = 2
_F_TARGETDIR = 3
_F_SUSPICIOUS = 4
_F_README = 5


def derive_event_labels(trace: Trace) -> np.ndarray:
    """float32 [N] per-event attack labels (1.0 = attack)."""
    if trace.labels is not None:
        return trace.labels
    if trace.ground_truth is None:
        return np.zeros(len(trace.events), np.float32)
    ev, st, gt = trace.events, trace.strings, trace.ground_truth
    in_window = gt.contains(ev.ts_ns)

    feats = st.features()  # [num_strings, PATH_FEATURE_DIM]
    pf = feats[ev.path_id]
    nf = feats[ev.new_path_id]

    suspicious = (pf[:, _F_SUSPICIOUS] > 0) | (nf[:, _F_SUSPICIOUS] > 0)
    ransom_note = pf[:, _F_README] > 0
    proc_read = pf[:, _F_PROC] > 0
    # target-directory membership: exact prefix match against the GT target,
    # not the generic /app heuristic feature
    under_target = np.array(
        [s.startswith(gt.target_path) for s in st.strings()], np.bool_
    )[ev.path_id]
    recon_files = np.array(
        [s == "/etc/passwd" for s in st.strings()], np.bool_
    )[ev.path_id]
    mutating = np.isin(
        ev.syscall,
        [int(Syscall.WRITE), int(Syscall.RENAME), int(Syscall.UNLINK), int(Syscall.OPENAT)],
    )

    label = in_window & (
        suspicious
        | ransom_note
        | (under_target & mutating)
        | proc_read
        | recon_files
    )
    return (label & ev.valid).astype(np.float32)
