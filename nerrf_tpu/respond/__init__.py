"""Online incident-response tier: live detections → batched MCTS planning
→ sandbox-verified undo plans (docs/response.md).

The serve plane detects; this package answers.  Alerts crossing the
demux's calibrated-severity gate become incidents in a bounded queue, a
micro-batcher packs them into padded root-state buckets for one vmapped
`DeviceMCTS` program per batch slot (warmed through the CompileCache —
zero recompiles after warmup), and every emitted plan is replayed through
the rollback sandbox gate before anything is surfaced.  Unverifiable
plans are quarantined with a journaled reason, never surfaced.
"""

from nerrf_tpu.respond.config import RespondConfig
from nerrf_tpu.respond.incidents import Incident, IncidentQueue
from nerrf_tpu.respond.planner import (BatchedDeviceMCTS,
                                       respond_program_key)
from nerrf_tpu.respond.router import ResponseRouter
from nerrf_tpu.respond.scenarios import (FAMILIES, ScheduledIncident,
                                         StagedIncident, schedule,
                                         sim_config, stage_incident)
from nerrf_tpu.respond.verify import (PlanVerifier, VerifiedPlan,
                                      VerifyContext)

__all__ = [
    "RespondConfig",
    "Incident",
    "IncidentQueue",
    "BatchedDeviceMCTS",
    "respond_program_key",
    "ResponseRouter",
    "FAMILIES",
    "ScheduledIncident",
    "StagedIncident",
    "schedule",
    "sim_config",
    "stage_incident",
    "PlanVerifier",
    "VerifiedPlan",
    "VerifyContext",
]
