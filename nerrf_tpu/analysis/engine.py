"""nerrflint: the repo's rule-based static analyzer over its own ASTs.

`scripts/check_metrics.py` proved the pattern — a repo-specific lint wired
into tier-1 catches whole regression classes for free.  This engine
generalizes it: every invariant the codebase enforces only by convention
(traced functions stay host-pure, the serve path never recompiles after
warmup, threaded code touches shared state under its locks, metric names
follow the contract) becomes a Rule producing structured Findings, and the
full ruleset runs on every test invocation and as a chip-queue pre-flight.

Surfaces:

    python scripts/nerrflint.py              # full ruleset over nerrf_tpu/
    python scripts/nerrflint.py --deep       # + jaxpr-level contracts
    python -m nerrf_tpu.cli lint [--json]    # same, as a CLI subcommand
    tests/test_analysis.py                   # the tier-1 gate (AST tier)
    tests/test_programs.py                   # the tier-1 gate (deep tier)

Suppression, two flavors (both REQUIRE a justification):

  * inline — append ``# nerrflint: ok[rule-id] why`` to the flagged line
    (or the line above).  Lives next to the code; survives refactors.
  * baseline — one line per accepted finding in ``.nerrflint-baseline``
    at the repo root: ``<rule> <path> <anchor>  # why``.  Anchors are
    content-derived (never line numbers), so baselines survive unrelated
    edits; stale entries are reported so the file stays honest.

Exit codes: 0 clean (or fully suppressed), 1 unbaselined findings,
2 usage/baseline-format errors.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import re
import sys
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from nerrf_tpu.analysis.astutil import Project, collect_files

REPO = Path(__file__).resolve().parents[2]
BASELINE_NAME = ".nerrflint-baseline"
DEFAULT_PATHS = ("nerrf_tpu",)

# schema version of the --json document (tests pin the key set).
# 1 → "1.1": each `rules` entry gained `elapsed_sec` (per-rule wall time,
# so the queue pre-flights can log which rule eats the budget).
JSON_SCHEMA_VERSION = "1.1"

_SUPPRESS = re.compile(r"#\s*nerrflint:\s*ok\[([a-z0-9-]+)\]\s*(\S.*)?")


@dataclasses.dataclass
class Finding:
    """One rule violation at one site.

    ``anchor`` is the stable identity used for baseline matching and
    dedup: rules derive it from names (function qualnames, attribute
    names, effect kinds) — never from line numbers, which churn."""

    rule: str
    path: str
    line: int
    message: str
    hint: str = ""
    anchor: str = ""

    @property
    def key(self) -> str:
        return f"{self.rule} {self.path} {self.anchor}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "hint": self.hint,
                "anchor": self.anchor}

    def render(self) -> str:
        out = f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


class Rule:
    """Base class: subclasses set ``id``/``description`` and implement
    ``run(project) -> list[Finding]``.  ``deep`` marks the jaxpr-level
    tier (`nerrf_tpu/analysis/programs/`): those rules import jax at run
    time and only load under ``--deep`` — the base engine stays
    stdlib-only."""

    id: str = ""
    description: str = ""
    deep: bool = False

    def run(self, project: Project) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


def default_rules() -> List[Rule]:
    """The full shipped ruleset (import here, not at module top, so the
    engine itself stays importable from rule modules)."""
    from nerrf_tpu.analysis.concurrency import (
        AtomicityViolation,
        BlockingUnderLock,
        CallbackUnderLock,
        ThreadLifecycle,
    )
    from nerrf_tpu.analysis.locks import LockDiscipline
    from nerrf_tpu.analysis.metrics_contract import MetricsContract
    from nerrf_tpu.analysis.operability import (
        AtomicWrite,
        BoundedGrowth,
        FailurePolicy,
        JournalContract,
    )
    from nerrf_tpu.analysis.purity import JaxPurity
    from nerrf_tpu.analysis.recompile import RecompileHazard
    from nerrf_tpu.analysis.syncs import SyncInHotLoop

    return [JaxPurity(), RecompileHazard(), SyncInHotLoop(),
            LockDiscipline(), AtomicityViolation(), CallbackUnderLock(),
            BlockingUnderLock(), ThreadLifecycle(), MetricsContract(),
            AtomicWrite(), JournalContract(), FailurePolicy(),
            BoundedGrowth()]


# -- baseline -----------------------------------------------------------------


@dataclasses.dataclass
class Baseline:
    entries: Dict[str, str]            # finding.key → justification
    errors: List[str]

    @classmethod
    def load(cls, path: Optional[Path]) -> "Baseline":
        entries: Dict[str, str] = {}
        errors: List[str] = []
        if path is None or not path.exists():
            return cls(entries, errors)
        for i, raw in enumerate(path.read_text().splitlines(), 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            body, _, why = line.partition("#")
            parts = body.split()
            if len(parts) != 3:
                errors.append(
                    f"{path.name}:{i}: expected '<rule> <path> <anchor>"
                    f"  # justification', got {raw!r}")
                continue
            if not why.strip():
                errors.append(
                    f"{path.name}:{i}: baseline entry for {parts[0]!r} has "
                    f"no justification — every suppression must say why")
                continue
            entries[" ".join(parts)] = why.strip()
        return cls(entries, errors)


def _inline_suppressed(project: Project, f: Finding) -> Optional[str]:
    """The justification text when the finding's line (or the line above)
    carries a ``# nerrflint: ok[rule]`` marker for this rule.  Files the
    AST scan never parsed (metrics-contract reaches bench.py/benchmarks/)
    are read from disk so inline markers work everywhere findings do."""
    mod = next((m for m in project.modules.values() if m.path == f.path),
               None)
    if mod is not None:
        lines = mod.lines
    else:
        try:
            lines = (project.root / f.path).read_text().splitlines()
        except OSError:
            return None
    for n in (f.line, f.line - 1):
        src = lines[n - 1] if 0 < n <= len(lines) else ""
        m = _SUPPRESS.search(src)
        if m and m.group(1) == f.rule:
            return (m.group(2) or "").strip() or "(no reason given)"
    return None


# -- runner -------------------------------------------------------------------


@dataclasses.dataclass
class Report:
    findings: List[Finding]            # unsuppressed, the failures
    suppressed: List[Finding]          # inline- or baseline-accepted
    stale: List[str]                   # baseline keys that matched nothing
    errors: List[str]                  # parse/baseline-format problems
    files: int
    elapsed: float
    rules: List[Rule]
    rule_elapsed: Dict[str, float] = dataclasses.field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.findings and not self.errors

    def to_json(self) -> dict:
        return {
            "schema": JSON_SCHEMA_VERSION,
            "ok": self.ok,
            "files": self.files,
            "elapsed_sec": round(self.elapsed, 3),
            "rules": [{"id": r.id, "description": r.description,
                       "elapsed_sec": round(
                           self.rule_elapsed.get(r.id, 0.0), 4)}
                      for r in self.rules],
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "stale_baseline": list(self.stale),
            "errors": list(self.errors),
        }


def analyze(root: Path = REPO, paths: Sequence[str] = DEFAULT_PATHS,
            rules: Optional[List[Rule]] = None,
            baseline_path: Optional[Path] = None) -> Report:
    """Run ``rules`` over ``paths`` under ``root`` and fold in baseline +
    inline suppressions.  ``baseline_path=None`` means the repo default
    (pass a nonexistent path to run baseline-free)."""
    t0 = time.perf_counter()
    root = Path(root)
    if baseline_path is None:
        baseline_path = root / BASELINE_NAME
    rules = default_rules() if rules is None else rules
    project = Project(root, collect_files(root, paths))
    baseline = Baseline.load(baseline_path)
    errors = list(project.errors) + list(baseline.errors)

    raw: List[Finding] = []
    rule_elapsed: Dict[str, float] = {}
    for rule in rules:
        r0 = time.perf_counter()
        try:
            raw.extend(rule.run(project))
        except Exception as e:  # noqa: BLE001 — a crashed rule is exit 2,
            # not a traceback: the pre-flights must distinguish "the
            # analyzer broke" from "the code has findings"
            errors.append(
                f"rule {rule.id or type(rule).__name__} crashed: "
                f"{type(e).__name__}: {e}")
        rule_elapsed[rule.id] = (rule_elapsed.get(rule.id, 0.0)
                                 + time.perf_counter() - r0)
    raw.sort(key=lambda f: (f.path, f.line, f.rule))

    seen_keys = set()
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    matched = set()
    for f in raw:
        if f.key in seen_keys:       # same anchor twice: report once
            continue
        seen_keys.add(f.key)
        if _inline_suppressed(project, f) is not None:
            suppressed.append(f)
        elif f.key in baseline.entries:
            matched.add(f.key)
            suppressed.append(f)
        else:
            findings.append(f)
    stale = sorted(set(baseline.entries) - matched)
    return Report(findings, suppressed, stale, errors,
                  len(project.modules), time.perf_counter() - t0, rules,
                  rule_elapsed)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="nerrflint",
        description="rule-based static analysis over the nerrf_tpu ASTs")
    ap.add_argument("--root", default=str(REPO),
                    help="repo root to analyze (default: this checkout)")
    ap.add_argument("--rule", action="append", default=None, metavar="ID",
                    help="run only this rule (repeatable)")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help=f"suppression file (default: <root>/{BASELINE_NAME})")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--deep", action="store_true",
                    help="also run the jaxpr-level program-contract rules "
                         "(signature closure, donation, collectives, "
                         "Pallas budgets, cache-key coverage) — imports "
                         "jax and forces a virtual multi-device CPU "
                         "backend; ~20 s instead of ~2 s")
    args = ap.parse_args(argv)

    rules = default_rules()
    if args.deep:
        # rule construction is jax-free; the backend setup (jax import,
        # XLA_FLAGS) waits until rules actually run, so --list-rules
        # stays instant even with --deep
        from nerrf_tpu.analysis.programs import deep_rules

        rules += deep_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.id:<20} {r.description}")
        return 0
    if args.rule:
        known = {r.id: r for r in rules}
        unknown = [rid for rid in args.rule if rid not in known]
        if unknown:
            print(f"nerrflint: unknown rule(s): {', '.join(unknown)} "
                  f"(--list-rules shows the catalog)", file=sys.stderr)
            return 2
        rules = [known[rid] for rid in args.rule]

    if any(getattr(r, "deep", False) for r in rules):
        from nerrf_tpu.analysis.programs import prepare_backend

        prepare_backend()
    report = analyze(
        Path(args.root), DEFAULT_PATHS, rules,
        Path(args.baseline) if args.baseline else None)

    if args.json:
        print(json.dumps(report.to_json(), indent=2))
    else:
        for e in report.errors:
            print(f"nerrflint: error: {e}", file=sys.stderr)
        for f in report.findings:
            print(f.render(), file=sys.stderr)
        for key in report.stale:
            print(f"nerrflint: stale baseline entry (no longer matches; "
                  f"delete it): {key}", file=sys.stderr)
        status = "clean" if report.ok else \
            f"{len(report.findings)} finding(s)"
        print(f"nerrflint: {report.files} files, {len(rules)} rules, "
              f"{len(report.suppressed)} suppressed, {status} "
              f"in {report.elapsed:.2f}s")
    if report.errors:
        return 2
    return 1 if report.findings else 0


if __name__ == "__main__":
    sys.exit(main())
