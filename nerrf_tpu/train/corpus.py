"""The 100 h corpus: streaming generation to disk shards + shard reader.

The reference's roadmap specifies a "100 h benign + 1 h labelled attack"
training corpus (`/root/reference/ROADMAP.md:50`) that was never built; the
north star (BASELINE.json) asks for detector ROC-AUC *on that corpus*.  At
production density (600 s traces, 40 Hz benign load ≈ 25 k events/trace)
100 h is ~600 traces → ~24 k window samples → ~16 GB of window tensors:
too big to hold in HBM, too big to regenerate per run.  So the corpus is
generated ONCE, streamed trace-by-trace to fixed-size shards on disk, and
training rotates shards through the chip (double-buffered uploads — see
train/loop.py:train_sharded_stream).

Layout (one directory per corpus):
    manifest.json              — hours, windows, shard list, configs, dtypes
    shard_0000/{node_feat.npy, ...}
    shard_0001/...             — each ≤ shard_windows samples, train split
    eval_0000/...              — held-out TRACES (split before windowing, so
                                 no window of an eval trace leaks into train)

float32 feature/label arrays are stored as float16 (counts, ratios, Δt and
{0,1} labels all fit comfortably): halves disk and — the real win — halves
host→device transfer on a ~0.5 GB/s tunnel.  Readers upcast on device.
"""

from __future__ import annotations

import dataclasses
import json
import time
from pathlib import Path
from typing import Dict, Iterator, List, Optional

import numpy as np

from nerrf_tpu.train.data import DatasetConfig, WindowDataset, windows_of_trace

# float arrays stored as f16 on disk; everything else (masks, int ids like
# node_aux/node_type — embedding inputs) keeps its dtype
_F16_KEYS = ("node_feat", "edge_feat", "seq_feat",
             "node_label", "edge_label", "seq_label")


@dataclasses.dataclass(frozen=True)
class CorpusSpec:
    """Generation parameters (mirrors config.CorpusConfig at scale).

    ``hard_scenarios`` mixes the adversarial variants from data/synth.py
    into the corpus — benign mass-renames and atomic-rewrite jobs among the
    benign traces, and the evasion variants (slow-drip / benign-comm /
    multi-process + the r4 stealth family: inplace-stealth /
    partial-encrypt / interleaved-backup / exfil-encrypt) among the attack
    traces — so the trained detector sees hard negatives *and* hard
    positives, not just the linearly-separable standard attack (the r1
    verdict's detector-difficulty critique; the r3 verdict's item 3 adds
    the stealth family: a detector that only ever sees rename-style
    attacks learns the same shortcut the heuristic hard-codes)."""

    hours: float = 100.0
    duration_sec: float = 600.0
    attack_fraction: float = 0.5
    num_target_files: int = 24
    benign_rate_hz: float = 40.0
    base_seed: int = 1000
    eval_fraction: float = 0.1     # fraction of TRACES held out
    shard_windows: int = 2000      # samples per shard (~0.7 GB at f16)
    hard_scenarios: bool = True
    # fraction of benign traces carrying a hard negative (split evenly
    # between mass-rename and atomic-rewrite), and of attack traces drawn
    # from the adversarial variants (split evenly across ATTACK_VARIANTS)
    benign_hard_fraction: float = 0.2
    attack_variant_fraction: float = 0.49  # 7 variants × 7%; standard keeps 51%
    # Zero-drop capacity fitting (r2 verdict weak #3: the r2 corpus was cut
    # at 256n/512e while its own densest training window needed 599n/639e —
    # attack bursts, exactly the signal, were silently truncated).  When on,
    # generation runs a cheap measuring pre-pass over every window of every
    # trace (re-simulating; traces are seed-deterministic), sizes capacities
    # to the corpus-wide max via GraphConfig.fit_counts (×headroom, next
    # pow2), then asserts the windowing pass dropped zero events.
    auto_fit: bool = True
    fit_headroom: float = 1.25


def _write_shard(out: Path, samples: List[dict], dtypes: Dict[str, str]) -> int:
    out.mkdir(parents=True, exist_ok=True)
    keys = samples[0].keys()
    for k in keys:
        arr = np.stack([s[k] for s in samples])
        dtypes.setdefault(k, str(arr.dtype))
        if k in _F16_KEYS:
            arr = arr.astype(np.float16)
        np.save(out / f"{k}.npy", arr)
    return len(samples)


def generate_corpus(
    out_dir: str | Path,
    spec: CorpusSpec = CorpusSpec(),
    dataset: Optional[DatasetConfig] = None,
    log=None,
) -> dict:
    """Stream-generate `spec.hours` of traces into shards under out_dir.

    Memory stays bounded at one shard of samples (+ one trace); wall clock
    is ~2 s per 600 s trace on one core, so 100 h ≈ 20 min.  Idempotent:
    an existing complete manifest short-circuits.
    """
    from nerrf_tpu.data.synth import SimConfig, simulate_trace

    out = Path(out_dir)
    man_path = out / "manifest.json"
    if man_path.exists():
        man = json.loads(man_path.read_text())
        if man.get("complete"):
            if log:
                log(f"corpus exists: {man['hours']:.1f}h, "
                    f"{man['train_windows']} train windows — skipping")
            return man
    out.mkdir(parents=True, exist_ok=True)
    dataset = dataset or DatasetConfig()

    n_traces = max(1, round(spec.hours * 3600.0 / spec.duration_sec))
    rng = np.random.default_rng(spec.base_seed)
    is_attack = rng.random(n_traces) < spec.attack_fraction
    is_eval = rng.random(n_traces) < spec.eval_fraction
    if spec.eval_fraction > 0 and n_traces >= 2 and not is_eval.any():
        is_eval[-1] = True  # small corpora must still have a held-out trace

    def sim_config(i: int) -> "SimConfig":
        """The per-trace SimConfig — pure function of (spec, i) so the
        measuring pre-pass and the windowing pass see identical traces."""
        trng = np.random.default_rng((spec.base_seed, i))
        scenario = "standard"
        if spec.hard_scenarios:
            u = trng.random()
            if is_attack[i]:
                from nerrf_tpu.data.synth import ATTACK_VARIANTS as variants

                slot = spec.attack_variant_fraction / len(variants)
                idx = int(u // slot) if slot > 0 else len(variants)
                if idx < len(variants):
                    scenario = variants[idx]
            elif u < spec.benign_hard_fraction / 2:
                scenario = "benign-mass-rename"
            elif u < spec.benign_hard_fraction:
                scenario = "benign-atomic-rewrite"
        return SimConfig(
            num_target_files=int(trng.integers(max(4, spec.num_target_files // 2),
                                               spec.num_target_files + 1)),
            duration_sec=spec.duration_sec,
            benign_rate_hz=float(trng.uniform(spec.benign_rate_hz * 0.5,
                                              spec.benign_rate_hz * 1.5)),
            attack_start_sec=float(trng.uniform(0.15, 0.7) * spec.duration_sec),
            seed=spec.base_seed + i,
            attack=bool(is_attack[i]),
            scenario=scenario,
        )

    fit_info = None
    if spec.auto_fit:
        # Pass 0: measure the densest window in the whole corpus, then size
        # graph capacities so NO window drops anything.  Re-simulating here
        # (traces are pure functions of (spec, i)) costs ~22% of total
        # generation wall-clock for the 100 h corpus (fit_seconds 271 of
        # 1238 in the r3 manifest) — accepted one-time cost; buffering all
        # ~600 traces' events to skip it would hold ~GBs on a small host.
        from nerrf_tpu.graph.builder import measure_window, snapshot_windows

        t_fit = time.time()
        max_n = max_e = 0
        for i in range(n_traces):
            tr = simulate_trace(sim_config(i))
            ev = tr.events
            if ev.num_valid == 0:
                continue
            ts = ev.ts_ns[ev.valid]
            for lo, hi in snapshot_windows(int(ts.min()), int(ts.max()),
                                           dataset.graph):
                n, e = measure_window(ev, lo, hi)
                max_n, max_e = max(max_n, n), max(max_e, e)
            if log and (i + 1) % 100 == 0:
                log(f"fit pass: {i + 1}/{n_traces} traces, "
                    f"max so far {max_n}n/{max_e}e")
        fitted = dataset.graph.fit_counts(max_n, max_e,
                                          headroom=spec.fit_headroom)
        dataset = dataclasses.replace(dataset, graph=fitted)
        fit_info = {
            "max_window_nodes": max_n,
            "max_window_edges": max_e,
            "headroom": spec.fit_headroom,
            "fitted_max_nodes": fitted.max_nodes,
            "fitted_max_edges": fitted.max_edges,
            "fit_seconds": round(time.time() - t_fit, 1),
        }
        if log:
            log(f"auto-fit: densest window {max_n}n/{max_e}e → capacities "
                f"{fitted.max_nodes}n/{fitted.max_edges}e "
                f"({fit_info['fit_seconds']:.0f}s)")

    dtypes: Dict[str, str] = {}
    shards: List[dict] = []
    buf: Dict[bool, List[dict]] = {True: [], False: []}  # eval? → samples
    counts = {"train": 0, "eval": 0}
    label_pos = {"edge": 0.0, "seq": 0.0}
    t0 = time.time()

    def flush(eval_split: bool, force: bool = False) -> None:
        b = buf[eval_split]
        limit = spec.shard_windows
        while len(b) >= limit or (force and b):
            chunk, buf[eval_split] = b[:limit], b[limit:]
            b = buf[eval_split]
            kind = "eval" if eval_split else "shard"
            name = f"{kind}_{sum(1 for s in shards if s['kind'] == kind):04d}"
            n = _write_shard(out / name, chunk, dtypes)
            shards.append({"name": name, "kind": kind, "windows": n})
            counts["eval" if eval_split else "train"] += n
            if log:
                log(f"  wrote {name}: {n} windows "
                    f"({time.time() - t0:.0f}s elapsed)")

    scenario_counts: Dict[str, int] = {}
    drop_tally = {"events": 0, "nodes": 0, "edges": 0, "windows": 0}
    for i in range(n_traces):
        # structural variety per trace (files, load, attack onset), not just
        # the sim seed — a fixed onset would be a trivially learnable clock
        sim = sim_config(i)
        scenario_counts[sim.scenario] = scenario_counts.get(sim.scenario, 0) + 1
        tr = simulate_trace(sim)
        wstats: list = []
        samples = windows_of_trace(tr, dataset, stats_out=wstats)
        for st in wstats:
            if st.dropped_events or st.dropped_nodes or st.dropped_edges:
                drop_tally["events"] += st.dropped_events
                drop_tally["nodes"] += st.dropped_nodes
                drop_tally["edges"] += st.dropped_edges
                drop_tally["windows"] += 1
        for s in samples:
            label_pos["edge"] += float(s["edge_label"].sum())
            label_pos["seq"] += float(s["seq_label"].sum())
        buf[bool(is_eval[i])].extend(samples)
        flush(bool(is_eval[i]))
        if log and (i + 1) % 50 == 0:
            log(f"corpus: {i + 1}/{n_traces} traces "
                f"({(i + 1) * spec.duration_sec / 3600:.1f}h)")
    flush(False, force=True)
    flush(True, force=True)
    if spec.auto_fit and drop_tally["windows"]:
        raise ValueError(
            f"corpus windowing dropped data despite auto-fit capacities "
            f"{dataset.graph.max_nodes}n/{dataset.graph.max_edges}e: "
            f"{drop_tally} — fit pass and windowing pass disagree (bug)")

    man = {
        "complete": True,
        "hours": n_traces * spec.duration_sec / 3600.0,
        "num_traces": n_traces,
        "train_windows": counts["train"],
        "eval_windows": counts["eval"],
        "shards": shards,
        "dtypes": dtypes,
        "spec": dataclasses.asdict(spec),
        "gen_seconds": round(time.time() - t0, 1),
        "label_pos": label_pos,
        "scenario_counts": scenario_counts,
        "graph_capacity": {"max_nodes": dataset.graph.max_nodes,
                           "max_edges": dataset.graph.max_edges},
        "auto_fit": fit_info,
        "dropped": drop_tally,
    }
    # the manifest commits the corpus (ShardedCorpus opens it first), so
    # it lands atomically after every shard is on disk
    tmp = man_path.with_name(man_path.name + ".tmp")
    tmp.write_text(json.dumps(man, indent=2) + "\n")
    tmp.replace(man_path)
    if log:
        log(f"corpus complete: {man['hours']:.1f}h, "
            f"{counts['train']} train / {counts['eval']} eval windows in "
            f"{man['gen_seconds']:.0f}s")
    return man


class ShardedCorpus:
    """Reader: shard-at-a-time access to a generated corpus directory."""

    def __init__(self, path: str | Path) -> None:
        self.path = Path(path)
        man_path = self.path / "manifest.json"
        if not man_path.exists():
            raise FileNotFoundError(
                f"no corpus manifest at {man_path}; generate it with "
                f"`python scripts/gen_corpus.py --out {self.path}`")
        self.manifest = json.loads(man_path.read_text())
        if not self.manifest.get("complete"):
            raise ValueError(f"corpus at {self.path} is incomplete")
        self.train_shards = [s["name"] for s in self.manifest["shards"]
                             if s["kind"] == "shard"]
        self.eval_shards = [s["name"] for s in self.manifest["shards"]
                            if s["kind"] == "eval"]

    @property
    def hours(self) -> float:
        return float(self.manifest["hours"])

    @property
    def train_windows(self) -> int:
        return int(self.manifest["train_windows"])

    def load_shard(self, name: str, upcast: bool = False) -> Dict[str, np.ndarray]:
        """Arrays of one shard.  f16 storage dtypes are preserved unless
        `upcast` (host-side f32, for eval paths that never hit the wire)."""
        d = self.path / name
        arrays = {p.stem: np.load(p) for p in sorted(d.glob("*.npy"))}
        if upcast:
            arrays = {
                k: v.astype(np.float32) if v.dtype == np.float16 else v
                for k, v in arrays.items()
            }
        return arrays

    def eval_dataset(self, max_windows: int = 4000) -> WindowDataset:
        """Held-out split as a WindowDataset (host RAM, f32)."""
        parts, total = [], 0
        for name in self.eval_shards:
            arrs = self.load_shard(name, upcast=True)
            parts.append(WindowDataset(arrs))
            total += len(parts[-1])
            if total >= max_windows:
                break
        if not parts:
            raise ValueError("corpus has no eval shards")
        ds = WindowDataset.concatenate(parts)
        if len(ds) > max_windows:
            ds = ds.take(np.arange(max_windows))
        return ds

    def iter_train_shards(self, epoch_seed: int) -> Iterator[Dict[str, np.ndarray]]:
        order = np.random.default_rng(epoch_seed).permutation(
            len(self.train_shards))
        for i in order:
            yield self.load_shard(self.train_shards[int(i)])
