// nerrf-trackerd: the live capture daemon — kernel ring buffer → gRPC.
//
// The working equivalent of the reference's tracker binary
// (`/root/reference/tracker/cmd/tracker/main.go:69-156`: load BPF, mmap the
// ring, decode, fan out `nerrf.trace.Tracker/StreamEvents` to all clients),
// as one self-contained native binary:
//
//   capture (src/capture.cc, raw bpf(2), no clang/libbpf needed)
//     → decode + monotonic→wall correction + sanitize
//     → protobuf EventBatch frames (real batching, 64 events/frame — the
//       reference sends 1 event per frame despite its envelope, main.go:252)
//     → per-subscriber bounded queues, drop-on-full (main.go:255-265 policy)
//     → minimal HTTP/2 gRPC server (src/h2grpc.cc)
//
// Exit codes: 0 ok · 2 no permission (CAP_BPF) · 3 kernel support missing —
// scripts skip cleanly on 2/3 instead of failing.
//
// Usage: nerrf-trackerd [--listen HOST:PORT] [--batch N] [--ringbuf BYTES]
//                       [--max-seconds S] [--capture-self] [--probe]
//                       [--synthetic HZ]
//   TRACKER_LISTEN_ADDR honored like the reference (main.go:113).
//
// --synthetic HZ serves a fabricated openat→write→rename workload at ~HZ
// events/s through the full encode→batch→broadcast→HTTP/2 path with NO
// kernel capture: the interop surface (hand-rolled h2grpc.cc vs stock gRPC
// clients) becomes testable on hosts without BPF permission, exactly like
// the reference exercises its daemon with grpcurl
// (`tracker/scripts/test.sh:76-82`).

#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

#include <atomic>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "h2grpc.h"
#include "nerrf/capture.h"

namespace {

#include "trace_desc.inc"  // kTraceDescriptorSet: reflection schema bytes

// ---- tiny protobuf writer (proto/trace.proto field numbers) ---------------

void put_varint(std::string &s, uint64_t v) {
  while (v >= 0x80) {
    s.push_back(static_cast<char>(v | 0x80));
    v >>= 7;
  }
  s.push_back(static_cast<char>(v));
}

void put_tag(std::string &s, int field, int wire) {
  put_varint(s, static_cast<uint64_t>(field) << 3 | wire);
}

void put_str(std::string &s, int field, const char *data, size_t len) {
  if (len == 0) return;
  put_tag(s, field, 2);
  put_varint(s, len);
  s.append(data, len);
}

void put_u64(std::string &s, int field, uint64_t v) {
  if (v == 0) return;
  put_tag(s, field, 0);
  put_varint(s, v);
}

void put_sint64(std::string &s, int field, int64_t v) {
  if (v == 0) return;
  put_tag(s, field, 0);
  put_varint(s, (static_cast<uint64_t>(v) << 1) ^
                    static_cast<uint64_t>(v >> 63));  // zigzag
}

// task comms / paths can carry control bytes; keep printable ASCII only
// (reference sanitizeString, main.go:327-334)
size_t sanitize(const char *in, size_t maxlen, char *out) {
  size_t n = 0;
  for (size_t i = 0; i < maxlen && in[i]; ++i)
    if (in[i] >= 0x20 && in[i] < 0x7f) out[n++] = in[i];
  return n;
}

const char *syscall_name(uint32_t sc) {
  // keep in sync with nerrf_tpu/schema/events.py::Syscall
  static const char *names[] = {"openat", "write",   "rename", "read",
                                "unlink", "close",   "exec",   "connect",
                                "stat",   "mkdir",   "chmod",  "fsync",
                                "marker", "other"};
  return sc < sizeof(names) / sizeof(names[0]) ? names[sc] : "other";
}

struct Stats {
  std::atomic<uint64_t> events{0};
  std::atomic<uint64_t> frames{0};
  std::atomic<uint64_t> frames_dropped{0};
};

class Broadcaster {
 public:
  std::shared_ptr<nerrf::FrameQueue> subscribe() {
    auto q = std::make_shared<nerrf::FrameQueue>(100);
    std::lock_guard<std::mutex> lock(mu_);
    queues_.push_back(q);
    return q;
  }

  void publish(const std::string &frame, Stats &st) {
    std::lock_guard<std::mutex> lock(mu_);
    for (auto it = queues_.begin(); it != queues_.end();) {
      auto q = it->lock();
      if (!q) {
        it = queues_.erase(it);
        continue;
      }
      if (!q->push(frame)) st.frames_dropped.fetch_add(1);
      ++it;
    }
  }

  void close_all() {
    std::lock_guard<std::mutex> lock(mu_);
    for (auto &w : queues_)
      if (auto q = w.lock()) q->close();
    queues_.clear();
  }

 private:
  std::mutex mu_;
  std::vector<std::weak_ptr<nerrf::FrameQueue>> queues_;
};

struct CaptureCtx {
  std::string batch;        // EventBatch under construction
  int events_in_batch = 0;
  int batch_size = 64;
  int64_t boot_wall_ns = 0;  // CLOCK_REALTIME - CLOCK_MONOTONIC at startup
  bool resolve_fd_paths = false;  // live capture only: /proc is the truth
  Broadcaster *bcast = nullptr;
  Stats *stats = nullptr;
};

void flush_batch(CaptureCtx *cx) {
  if (cx->events_in_batch == 0) return;
  // gRPC message framing: flag byte + 4-byte big-endian length + payload
  std::string msg;
  msg.reserve(cx->batch.size() + 5);
  msg.push_back(0);
  uint32_t len = static_cast<uint32_t>(cx->batch.size());
  msg.push_back(static_cast<char>((len >> 24) & 0xff));
  msg.push_back(static_cast<char>((len >> 16) & 0xff));
  msg.push_back(static_cast<char>((len >> 8) & 0xff));
  msg.push_back(static_cast<char>(len & 0xff));
  msg += cx->batch;
  cx->bcast->publish(msg, *cx->stats);
  cx->stats->frames.fetch_add(1);
  cx->batch.clear();
  cx->events_in_batch = 0;
}

void on_event(void *user, const struct nerrf_event_record *rec) {
  CaptureCtx *cx = static_cast<CaptureCtx *>(user);

  // fd→path resolution for fd-based syscalls (write/read): the entry
  // probe can only stash the fd (in ret_val's slot — capture.cc kSpecs);
  // the path lives in /proc/<pid>/fd while the fd is open.  Resolving
  // here, inside the ~100 ms poll round, catches every fd that lives
  // longer than the ring-buffer latency (a file being encrypted stays
  // open for its whole chunked rewrite).  Sub-poll-lifetime fds
  // (open→write→close in one breath) stay pathless — a documented gap
  // live capture shares with the reference's tracker.
  // LIVE CAPTURE ONLY (resolve_fd_paths): a replayed trace's pathless
  // events carry historical pids — readlinking /proc/<pid>/fd on the
  // replay host would attach some unrelated current process's fd target
  // as a phantom path in the detector's input.
  nerrf_event_record resolved;
  if (cx->resolve_fd_paths &&
      (rec->syscall_id == NERRF_SC_WRITE ||
       rec->syscall_id == NERRF_SC_READ) &&
      rec->path[0] == '\0' && rec->ret_val >= 0) {
    resolved = *rec;
    char link[64];
    snprintf(link, sizeof(link), "/proc/%u/fd/%lld", rec->pid,
             (long long)rec->ret_val);
    ssize_t n = readlink(link, resolved.path, sizeof(resolved.path) - 1);
    if (n > 0)
      resolved.path[n] = '\0';
    else
      resolved.path[0] = '\0';
    resolved.ret_val = 0;  // the stashed fd is NOT a syscall return value
    rec = &resolved;
  }

  std::string ev;
  ev.reserve(96);

  // ts: google.protobuf.Timestamp {1: seconds, 2: nanos}
  int64_t wall = cx->boot_wall_ns + static_cast<int64_t>(rec->ts_ns);
  std::string ts;
  put_u64(ts, 1, static_cast<uint64_t>(wall / 1000000000ll));
  put_u64(ts, 2, static_cast<uint64_t>(wall % 1000000000ll));
  put_str(ev, 1, ts.data(), ts.size());

  put_u64(ev, 2, rec->pid);
  put_u64(ev, 3, rec->tid);
  char buf[NERRF_PATH_LEN];
  put_str(ev, 4, buf, sanitize(rec->comm, NERRF_COMM_LEN, buf));
  const char *sc = syscall_name(rec->syscall_id);
  put_str(ev, 5, sc, strlen(sc));
  put_str(ev, 6, buf, sanitize(rec->path, NERRF_PATH_LEN, buf));
  put_str(ev, 7, buf, sanitize(rec->new_path, NERRF_PATH_LEN, buf));
  put_sint64(ev, 9, rec->ret_val);
  put_u64(ev, 10, rec->bytes);

  // EventBatch.events (field 1)
  put_tag(cx->batch, 1, 2);
  put_varint(cx->batch, ev.size());
  cx->batch += ev;
  cx->stats->events.fetch_add(1);
  if (++cx->events_in_batch >= cx->batch_size) flush_batch(cx);
}

std::atomic<bool> g_stop{false};
void on_signal(int) { g_stop.store(true); }

// ---- trace replay (--replay) ----------------------------------------------
// Stream a captured incident trace (the ND-JSON the Python side writes,
// schema/events.py events_to_jsonl) through the SAME encode→batch→broadcast
// path live capture uses.  This is how the end-to-end artifact gets a REAL
// incident through the real wire on hosts without CAP_BPF: `nerrf simulate`
// attacks real files and writes the trace; the daemon replays it; the
// detector consumes what crossed HTTP/2 — not the file on disk.

// Extract `"key": value` from one flat JSON line (our own writer: json.dumps
// with sort_keys, ": " separators, printable-sanitized strings).
bool json_field(const std::string &line, const char *key, std::string *out) {
  std::string pat = std::string("\"") + key + "\": ";
  size_t p = line.find(pat);
  if (p == std::string::npos) return false;
  p += pat.size();
  if (p >= line.size()) return false;
  if (line[p] == '"') {
    ++p;
    std::string s;
    while (p < line.size() && line[p] != '"') {
      if (line[p] == '\\' && p + 1 < line.size()) ++p;  // \" \\ escapes
      s.push_back(line[p++]);
    }
    *out = s;
  } else {
    size_t e = line.find_first_of(",}", p);
    *out = line.substr(p, e == std::string::npos ? e : e - p);
  }
  return true;
}

int64_t json_int(const std::string &line, const char *key) {
  std::string v;
  if (!json_field(line, key, &v)) return 0;
  return atoll(v.c_str());
}

// "2026-08-01T05:49:51.797079621Z" → epoch ns (0 on parse failure)
int64_t parse_rfc3339_ns(const std::string &s) {
  struct tm tm;
  memset(&tm, 0, sizeof(tm));
  const char *rest = strptime(s.c_str(), "%Y-%m-%dT%H:%M:%S", &tm);
  if (!rest) return 0;
  int64_t ns = static_cast<int64_t>(timegm(&tm)) * 1000000000ll;
  if (*rest == '.') {
    ++rest;
    int64_t frac = 0, scale = 100000000;
    while (*rest >= '0' && *rest <= '9' && scale > 0) {
      frac += (*rest++ - '0') * scale;
      scale /= 10;
    }
    ns += frac;
  }
  return ns;
}

uint32_t syscall_id_of(const std::string &name) {
  for (uint32_t i = 0; i <= NERRF_SC_OTHER; ++i)
    if (name == syscall_name(i)) return i;
  return NERRF_SC_OTHER;
}

bool load_replay(const std::string &path,
                 std::vector<nerrf_event_record> *out) {
  FILE *f = fopen(path.c_str(), "r");
  if (!f) return false;
  char *buf = nullptr;
  size_t cap = 0;
  ssize_t n;
  while ((n = getline(&buf, &cap, f)) > 0) {
    std::string line(buf, static_cast<size_t>(n));
    std::string ts, comm, sc, p1, p2;
    if (!json_field(line, "timestamp", &ts) ||
        !json_field(line, "syscall", &sc))
      continue;
    nerrf_event_record rec;
    memset(&rec, 0, sizeof(rec));
    rec.ts_ns = static_cast<uint64_t>(parse_rfc3339_ns(ts));
    rec.pid = static_cast<uint32_t>(json_int(line, "pid"));
    rec.tid = static_cast<uint32_t>(json_int(line, "tid"));
    rec.syscall_id = syscall_id_of(sc);
    rec.ret_val = json_int(line, "ret_val");
    rec.bytes = static_cast<uint64_t>(json_int(line, "bytes"));
    if (json_field(line, "comm", &comm))
      snprintf(rec.comm, sizeof(rec.comm), "%s", comm.c_str());
    if (json_field(line, "path", &p1))
      snprintf(rec.path, sizeof(rec.path), "%s", p1.c_str());
    if (json_field(line, "new_path", &p2) && !p2.empty())
      snprintf(rec.new_path, sizeof(rec.new_path), "%s", p2.c_str());
    out->push_back(rec);
  }
  free(buf);
  fclose(f);
  return !out->empty();
}

}  // namespace

int main(int argc, char **argv) {
  const char *env_addr = getenv("TRACKER_LISTEN_ADDR");
  std::string listen = env_addr ? env_addr : "127.0.0.1:50051";
  uint32_t ringbuf_bytes = 256 * 1024;
  int batch_size = 64;
  int max_seconds = 0;
  bool capture_self = false;
  bool probe_only = false;
  int synthetic_hz = 0;
  std::string replay_path;
  int replay_hz = 500;

  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto next = [&]() -> const char * {
      return i + 1 < argc ? argv[++i] : "";
    };
    if (a == "--listen") listen = next();
    else if (a == "--ringbuf") ringbuf_bytes = atoi(next());
    else if (a == "--batch") batch_size = atoi(next());
    else if (a == "--max-seconds") max_seconds = atoi(next());
    else if (a == "--capture-self") capture_self = true;
    else if (a == "--probe") probe_only = true;
    else if (a == "--synthetic") synthetic_hz = atoi(next());
    else if (a == "--replay") replay_path = next();
    else if (a == "--replay-rate") replay_hz = atoi(next());
    else {
      fprintf(stderr, "usage: %s [--listen H:P] [--ringbuf B] [--batch N] "
                      "[--max-seconds S] [--capture-self] [--probe] "
                      "[--synthetic HZ] [--replay TRACE.jsonl] "
                      "[--replay-rate HZ]\n",
              argv[0]);
      return 1;
    }
  }

  std::vector<nerrf_event_record> replay;
  if (!replay_path.empty()) {
    if (!load_replay(replay_path, &replay)) {
      fprintf(stderr, "[trackerd] replay load failed: %s\n",
              replay_path.c_str());
      return 1;
    }
    if (probe_only) {
      printf("replay ok (%zu events)\n", replay.size());
      return 0;
    }
  }

  char err[1024] = {0};
  nerrf_capture *cap = nullptr;
  if (synthetic_hz <= 0 && replay.empty()) {
    int st = nerrf_capture_probe(err, sizeof(err));
    if (st != NERRF_CAPTURE_OK) {
      fprintf(stderr, "[trackerd] capture unavailable: %s\n", err);
      return st == NERRF_CAPTURE_EPERM ? 2 : 3;
    }
    if (probe_only) {
      printf("capture ok\n");
      return 0;
    }
    cap = nerrf_capture_open(
        ringbuf_bytes, capture_self ? 0 : getpid(), err, sizeof(err));
    if (!cap) {
      fprintf(stderr, "[trackerd] capture open failed: %s\n", err);
      return 3;
    }
  } else if (probe_only) {
    printf("synthetic ok\n");
    return 0;
  }

  Broadcaster bcast;
  Stats stats;
  nerrf::GrpcStreamServer server(listen, "/nerrf.trace.Tracker/StreamEvents");
  // gRPC server reflection from the build-time descriptor set, so
  // `grpcurl list/describe` works schema-free like the reference tracker
  // (/root/reference/tracker/cmd/tracker/main.go:135)
  server.set_reflection_descriptor_set(std::string(
      reinterpret_cast<const char *>(kTraceDescriptorSet),
      kTraceDescriptorSetLen));
  server.set_subscribe([&] { return bcast.subscribe(); });
  server.set_on_peer([&](int pid) {
    if (pid > 0 && cap) nerrf_capture_exclude_pid(cap, pid);
  });
  int port = server.start();
  if (port < 0) {
    fprintf(stderr, "[trackerd] listen on %s failed\n", listen.c_str());
    nerrf_capture_close(cap);
    return 1;
  }
  // resolved port in the log line: clients of `--listen host:0` (tests
  // avoiding fixed-port collisions) parse it from here
  fprintf(stderr, "[trackerd] %s; serving StreamEvents on %s (port %d)\n",
          cap ? "capturing"
              : !replay.empty() ? "replay source" : "synthetic source",
          listen.c_str(), port);
  if (listen.rfind("unix:", 0) != 0)
    fprintf(stderr,
            "[trackerd] note: TCP clients cannot be pid-excluded "
            "(SO_PEERCRED is unix-socket-only); local subscribers should "
            "use --listen unix:/path to avoid capture feedback\n");

  struct timespec rt, mt;
  clock_gettime(CLOCK_REALTIME, &rt);
  clock_gettime(CLOCK_MONOTONIC, &mt);
  CaptureCtx cx;
  cx.batch_size = batch_size;
  cx.boot_wall_ns = (rt.tv_sec - mt.tv_sec) * 1000000000ll +
                    (rt.tv_nsec - mt.tv_nsec);
  cx.bcast = &bcast;
  cx.stats = &stats;
  cx.resolve_fd_paths = (cap != nullptr);

  signal(SIGINT, on_signal);
  signal(SIGTERM, on_signal);

  time_t start = time(nullptr);
  time_t last_log = start;
  uint64_t synth_seq = 0;
  size_t replay_pos = 0;
  time_t replay_done_at = 0;
  while (!g_stop.load()) {
    if (cap) {
      nerrf_capture_poll(cap, 100, on_event, &cx);
    } else if (!replay.empty()) {
      // replayed events carry ABSOLUTE wall-clock timestamps from the
      // incident (the monotonic→wall correction must not re-shift them)
      cx.boot_wall_ns = 0;
      if (replay_pos == 0 && server.subscribers() == 0) {
        // hold the replay for the first subscriber: a short trace at
        // replay-rate outruns any client's startup, and events broadcast
        // to zero queues are simply gone (observed: 172/172 lost to a
        // grpcio client that took 2 s to connect)
        struct timespec nap = {0, 50 * 1000000};
        nanosleep(&nap, nullptr);
        if (max_seconds > 0 && time(nullptr) - start >= max_seconds) break;
        continue;
      }
      if (replay_pos < replay.size()) {
        int burst = replay_hz / 20 + 1;  // 50 ms cadence, like synthetic
        for (int k = 0; k < burst && replay_pos < replay.size(); ++k)
          on_event(&cx, &replay[replay_pos++]);
        if (replay_pos >= replay.size()) {
          replay_done_at = time(nullptr);
          fprintf(stderr, "[trackerd] replay complete: %zu events\n",
                  replay.size());
          flush_batch(&cx);
          // closing the source queues lets the H2 write pass send
          // grpc-status 0 trailers once each subscriber drains — clients
          // get a clean end-of-stream instead of a mid-stream RST
          bcast.close_all();
        }
      } else {
        if (server.subscribers() == 0 ||
            time(nullptr) - replay_done_at >= 10)
          break;
      }
      struct timespec nap = {0, 50 * 1000000};
      nanosleep(&nap, nullptr);
    } else {
      // synthetic workload: ~synthetic_hz events/s of the canonical
      // openat→write→rename triple, through the SAME encode path live
      // capture uses — only the event source differs
      int burst = synthetic_hz / 20 + 1;  // 50 ms cadence
      struct timespec now_mt;
      for (int k = 0; k < burst; ++k) {
        clock_gettime(CLOCK_MONOTONIC, &now_mt);
        nerrf_event_record rec;
        memset(&rec, 0, sizeof(rec));
        rec.ts_ns = static_cast<uint64_t>(now_mt.tv_sec) * 1000000000ull +
                    static_cast<uint64_t>(now_mt.tv_nsec);
        rec.pid = 4242;
        rec.tid = 4242;
        snprintf(rec.comm, sizeof(rec.comm), "synthload");
        uint64_t file = synth_seq / 3;
        switch (synth_seq % 3) {
          case 0:
            rec.syscall_id = NERRF_SC_OPENAT;
            snprintf(rec.path, sizeof(rec.path),
                     "/app/uploads/doc_%llu.dat", (unsigned long long)file);
            break;
          case 1:
            rec.syscall_id = NERRF_SC_WRITE;
            rec.bytes = 4096;
            snprintf(rec.path, sizeof(rec.path),
                     "/app/uploads/doc_%llu.dat", (unsigned long long)file);
            break;
          default:
            rec.syscall_id = NERRF_SC_RENAME;
            snprintf(rec.path, sizeof(rec.path),
                     "/app/uploads/doc_%llu.dat", (unsigned long long)file);
            snprintf(rec.new_path, sizeof(rec.new_path),
                     "/app/uploads/doc_%llu.dat.lockbit3",
                     (unsigned long long)file);
            break;
        }
        ++synth_seq;
        on_event(&cx, &rec);
      }
      struct timespec nap = {0, 50 * 1000000};
      nanosleep(&nap, nullptr);
    }
    flush_batch(&cx);  // latency bound: ship partial batches every poll round
    time_t now = time(nullptr);
    if (max_seconds > 0 && now - start >= max_seconds) break;
    if (now - last_log >= 10) {
      fprintf(stderr,
              "[trackerd] events=%llu frames=%llu dropped_kernel=%llu "
              "dropped_frames=%llu subscribers=%llu\n",
              (unsigned long long)stats.events.load(),
              (unsigned long long)stats.frames.load(),
              (unsigned long long)(cap ? nerrf_capture_dropped(cap) : 0),
              (unsigned long long)stats.frames_dropped.load(),
              (unsigned long long)server.subscribers());
      last_log = now;
    }
  }

  fprintf(stderr, "[trackerd] shutting down: events=%llu kernel_dropped=%llu\n",
          (unsigned long long)stats.events.load(),
          (unsigned long long)(cap ? nerrf_capture_dropped(cap) : 0));
  bcast.close_all();
  server.stop();
  if (cap) nerrf_capture_close(cap);
  return 0;
}
