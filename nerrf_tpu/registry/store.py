"""File-backed model registry: immutable versioned checkpoints + an
atomically-renamed LIVE pointer.

Layout (one directory tree, shareable as a ReadWriteMany volume between
the trainer that publishes and the serve pods that poll):

    <root>/lineages/<lineage>/
        v1/                 immutable checkpoint dir (train/checkpoint.py
        v2/                 sidecar format: params/ + model_config.json)
        LIVE                JSON pointer {"version": N, "previous": M, ...}

Invariants:

  * a version directory appears atomically (copy → rename) and is never
    mutated after publish — rollback is a pointer move, never a rewrite;
  * schema/feature-layout gates run at PUBLISH time (the sidecar checks in
    `train.checkpoint`), not apply time: a stale-layout checkpoint is
    rejected before any serve pod can see it;
  * the LIVE pointer is written temp-then-`os.replace`, so a polling
    reader sees the old pointer or the new one, never a torn file;
  * concurrent publishers are safe: version numbers are claimed by the
    atomic rename itself (the loser of a race re-numbers and retries).
"""

from __future__ import annotations

import json
import os
import shutil
import time
from pathlib import Path
from typing import List, Optional

LIVE_POINTER = "LIVE"


class ModelRegistry:
    """The file-backed store.  Thread- and process-safe for its published
    surface: publish / promote / rollback / read."""

    def __init__(self, root: str | Path, journal=None) -> None:
        self.root = Path(root).absolute()
        # flight journal for publish records; None → the process-wide
        # DEFAULT_JOURNAL, resolved lazily at publish (keeps this module
        # import-light and lets embedders with an isolated journal — the
        # serve bench, tests — keep their records out of the shared ring)
        self._journal = journal

    def _journal_or_default(self):
        if self._journal is None:
            from nerrf_tpu.flight.journal import DEFAULT_JOURNAL

            self._journal = DEFAULT_JOURNAL
        return self._journal

    # -- paths ----------------------------------------------------------------

    def lineage_dir(self, lineage: str) -> Path:
        if not lineage or "/" in lineage or lineage.startswith("."):
            raise ValueError(f"invalid lineage name {lineage!r}")
        return self.root / "lineages" / lineage

    def version_dir(self, lineage: str, version: int) -> Path:
        return self.lineage_dir(lineage) / f"v{int(version)}"

    # -- read side ------------------------------------------------------------

    def lineages(self) -> List[str]:
        base = self.root / "lineages"
        if not base.is_dir():
            return []
        return sorted(p.name for p in base.iterdir() if p.is_dir())

    def versions(self, lineage: str) -> List[int]:
        d = self.lineage_dir(lineage)
        if not d.is_dir():
            return []
        out = []
        for p in d.iterdir():
            if p.is_dir() and p.name.startswith("v") and \
                    p.name[1:].isdigit():
                out.append(int(p.name[1:]))
        return sorted(out)

    def live(self, lineage: str) -> Optional[dict]:
        """The LIVE pointer record, or None when nothing is promoted."""
        p = self.lineage_dir(lineage) / LIVE_POINTER
        try:
            return json.loads(p.read_text())
        except FileNotFoundError:
            return None
        except json.JSONDecodeError as e:
            raise ValueError(f"corrupt LIVE pointer {p}: {e}") from None

    def live_version(self, lineage: str) -> Optional[int]:
        rec = self.live(lineage)
        return int(rec["version"]) if rec else None

    def load(self, lineage: str, version: Optional[int] = None):
        """→ (params, JointConfig, calibration, version).  ``version=None``
        loads LIVE (error when nothing is promoted)."""
        from nerrf_tpu.train.checkpoint import load_calibration, load_checkpoint

        if version is None:
            version = self.live_version(lineage)
            if version is None:
                raise FileNotFoundError(
                    f"lineage {lineage!r} has no LIVE version (publish then "
                    f"`nerrf models promote`)")
        path = self.version_dir(lineage, version)
        if not path.is_dir():
            raise FileNotFoundError(
                f"lineage {lineage!r} has no version v{version} "
                f"(have: {self.versions(lineage)})")
        params, cfg = load_checkpoint(path)
        return params, cfg, load_calibration(path), int(version)

    def executables_dir(self, lineage: str, version: int):
        """The version's AOT ``executables/`` sidecar path, or None when
        the version was published without one (readers treat absence as a
        plain cache miss — fail-open)."""
        d = self.version_dir(lineage, version) / "executables"
        return d if (d / "manifest.json").is_file() else None

    def quality_profile(self, lineage: str, version: int) -> Optional[dict]:
        """The version's reference quality profile (the checkpoint's
        ``quality_profile.json`` sidecar, published with the weights), or
        None when the version predates profiles OR the sidecar is
        unreadable — drift monitoring is advisory and must never block a
        swap the way a corrupt model sidecar blocks a load (the serve
        plane simply exports no quality series: null-not-fake)."""
        from nerrf_tpu.quality import PROFILE_FILENAME

        f = self.version_dir(lineage, int(version)) / PROFILE_FILENAME
        try:
            return json.loads(f.read_text())
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            return None

    def status(self, lineage: str) -> dict:
        live = self.live(lineage)
        versions = []
        for v in self.versions(lineage):
            meta = {}
            try:
                meta = json.loads(
                    (self.version_dir(lineage, v) / "model_config.json")
                    .read_text())
            except (OSError, json.JSONDecodeError):
                pass
            versions.append({
                "version": v,
                "live": bool(live and live.get("version") == v),
                "schema_version": meta.get("schema_version"),
                "calibration": meta.get("calibration"),
                "published_at": meta.get("published_at"),
                "source": meta.get("published_from"),
                "executables": self.executables_dir(lineage, v) is not None,
                "quality_profile":
                    self.quality_profile(lineage, v) is not None,
                # retrain provenance (nerrf_tpu/learn): None for a
                # human-published version, the trigger-seq/replay-
                # fingerprint/parent-version stamp for a supervisor one
                "provenance": meta.get("provenance"),
            })
        return {"lineage": lineage, "live": live, "versions": versions}

    # -- publish --------------------------------------------------------------

    def publish(self, lineage: str, src_dir: str | Path,
                source: Optional[str] = None,
                executables: Optional[str | Path] = None) -> int:
        """Copy a checkpoint directory into the lineage as the next
        immutable version and return its number.  The schema/feature-layout
        gates run HERE — a checkpoint the current code could not load is
        rejected at publish, never discovered at apply time by a serving
        pod.  Does NOT touch LIVE (promotion is a separate, guarded step).

        ``executables`` is an optional AOT sidecar (the directory
        `compilecache.export_executables` wrote): it is copied in as
        ``executables/`` next to ``params/`` inside the same atomic
        rename, so a serve pod booting this version can seed its compile
        cache from serialized executables and skip the bucket-ladder
        compile sweep entirely.  A source checkpoint that already carries
        its own ``executables/`` (export_for_checkpoint writes in place)
        rides along without this argument."""
        src = Path(src_dir).absolute()
        validate_checkpoint_dir(src)
        import errno

        from nerrf_tpu import chaos

        ldir = self.lineage_dir(lineage)
        ldir.mkdir(parents=True, exist_ok=True)
        tmp = ldir / f".publish.tmp-{os.getpid()}-{time.monotonic_ns()}"
        try:
            shutil.copytree(src, tmp)
            # chaos fault point (no-op disarmed): the store volume failing
            # mid-publish — the BaseException sweep below must leave no
            # tmp dir and no partial version behind
            chaos.inject("registry.store_io", lineage=lineage)
            if executables is not None:
                exe = Path(executables).absolute()
                if not (exe / "manifest.json").is_file():
                    raise FileNotFoundError(
                        f"not an executables sidecar: {exe} has no "
                        f"manifest.json (run compilecache.export_executables "
                        f"first)")
                dst = tmp / "executables"
                if dst.exists():  # explicit sidecar wins over a stale copy
                    shutil.rmtree(dst)
                shutil.copytree(exe, dst)
            # stamp provenance into the *copy*'s sidecar (the source
            # checkpoint stays untouched)
            sidecar = tmp / "model_config.json"
            meta = json.loads(sidecar.read_text())
            meta["published_at"] = time.time()
            meta["published_from"] = source or str(src)
            if (tmp / "executables" / "manifest.json").is_file():
                meta["executables"] = "executables/"
            # chaos fault point (no-op disarmed): a torn/bit-rotted
            # sidecar in the published copy — every later load must fail
            # with the one-line corrupt-sidecar error, never a traceback
            sidecar.write_bytes(chaos.mangle(
                "registry.corrupt_sidecar",
                json.dumps(meta, indent=2).encode(), lineage=lineage))
            while True:
                version = (max(self.versions(lineage), default=0)) + 1
                try:
                    # the atomic claim: rename fails when a concurrent
                    # publisher took this number first — re-scan and retry
                    os.rename(tmp, self.version_dir(lineage, version))
                    self._journal_or_default().record(
                        "registry_publish", lineage=lineage,
                        version=version, source=source or str(src))
                    return version
                except OSError as e:
                    # ONLY a lost race (the target exists) is retryable;
                    # anything else (read-only volume, a stray FILE named
                    # vN, permissions) would recompute the same number and
                    # spin forever
                    if e.errno not in (errno.EEXIST, errno.ENOTEMPTY):
                        raise
                    continue
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise

    def publish_params(self, lineage: str, params, cfg,
                       calibration: Optional[dict] = None,
                       source: Optional[str] = None) -> int:
        """Publish an in-memory param pytree (save → gate → copy-in)."""
        import tempfile

        from nerrf_tpu.train.checkpoint import save_checkpoint

        with tempfile.TemporaryDirectory(prefix="nerrf-publish-") as td:
            ckpt = Path(td) / "model"
            save_checkpoint(ckpt, params, cfg, calibration=calibration)
            return self.publish(lineage, ckpt, source=source or "in-memory")

    # -- promotion / rollback -------------------------------------------------

    def promote(self, lineage: str, version: int,
                kind: str = "manual") -> dict:
        """Repoint LIVE at ``version`` (temp-then-replace: atomic for every
        polling reader).  Returns the new pointer record."""
        version = int(version)
        if not self.version_dir(lineage, version).is_dir():
            raise FileNotFoundError(
                f"cannot promote: lineage {lineage!r} has no v{version} "
                f"(have: {self.versions(lineage)})")
        ldir = self.lineage_dir(lineage)
        prev = self.live_version(lineage)
        rec = {"version": version, "previous": prev,
               "promoted_at": time.time(), "kind": kind}
        tmp = ldir / f".{LIVE_POINTER}.tmp-{os.getpid()}-{time.monotonic_ns()}"
        tmp.write_text(json.dumps(rec, indent=2))
        os.replace(tmp, ldir / LIVE_POINTER)
        return rec

    def rollback(self, lineage: str,
                 version: Optional[int] = None) -> dict:
        """One-command rollback: repoint LIVE at ``version``, or at the
        pointer's recorded ``previous`` (falling back to the newest version
        below live).  A pointer move only — the bad version's directory
        stays for the post-mortem."""
        live = self.live(lineage)
        if live is None:
            raise FileNotFoundError(
                f"lineage {lineage!r} has no LIVE version to roll back from")
        if version is None:
            version = live.get("previous")
            if version is None:
                older = [v for v in self.versions(lineage)
                         if v < int(live["version"])]
                if not older:
                    raise ValueError(
                        f"lineage {lineage!r} has no version older than the "
                        f"live v{live['version']} to roll back to")
                version = older[-1]
        return self.promote(lineage, int(version), kind="rollback")


def validate_checkpoint_dir(path: str | Path) -> dict:
    """The publish-time gate: the sidecar must parse, carry a loadable
    schema version, and match the feature layout the current code produces
    — the same checks `load_checkpoint` runs, moved to where a bad
    checkpoint is cheap to reject.  Returns the parsed sidecar."""
    from nerrf_tpu.train.checkpoint import (
        _check_feature_layout,
        _check_schema_version,
        _read_sidecar,
    )

    path = Path(path).absolute()
    meta = _read_sidecar(path, "model_config.json")
    _check_schema_version(meta, path)
    _check_feature_layout(meta, path, keys=("node", "edge", "seq"))
    if not (path / "params").exists():
        raise FileNotFoundError(
            f"not a checkpoint: {path} has a sidecar but no params/ "
            f"directory (torn copy?)")
    for key in ("gnn", "lstm", "fuse"):
        if key not in meta:
            raise ValueError(
                f"corrupt checkpoint sidecar {path / 'model_config.json'}: "
                f"missing the {key!r} model-config field")
    return meta
