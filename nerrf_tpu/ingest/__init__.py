"""Host-side ingest: wire schema, native decode bridge, gRPC stream service.

The L1/L2 layers of the pipeline (SURVEY.md §1) — everything between the
kernel capture programs (native/bpf/) and the graph constructor: the
nerrf.trace wire schema (proto/trace.proto, stubs in trace_pb2.py), the C++
decode bridge (native/src/ingest.cc via bridge.py), and the Tracker
streaming service/client (service.py).
"""

from nerrf_tpu.ingest.bridge import (
    IngestBridge,
    RECORD_DTYPE,
    RECORD_SIZE,
    encode_ring_records,
    events_to_batch_frames,
    native_available,
)

__all__ = [
    "IngestBridge",
    "RECORD_DTYPE",
    "RECORD_SIZE",
    "encode_ring_records",
    "events_to_batch_frames",
    "native_available",
    "TraceReplayServer",
    "TrackerClient",
]


def __getattr__(name):  # grpc import deferred: the data path works without it
    if name in ("TraceReplayServer", "TrackerClient"):
        from nerrf_tpu.ingest import service

        return getattr(service, name)
    raise AttributeError(name)
