"""PyTorch reference implementation of NerrfNet for the bench baseline.

The reference planned its AI subsystem in PyTorch (`/root/reference/ROADMAP.md:62-69`,
`README.md:72-76` — PyTorch-Geometric GraphSAGE + LSTM) but never wrote it; the
north-star target is "match ROC-AUC at ≥2× train-steps/sec vs the PyTorch
implementation".  This module is that PyTorch implementation — the same
architecture, math and loss as `nerrf_tpu.models` — used to measure the
baseline steps/sec this environment can actually run (torch is CPU-only here;
no CUDA is present).
"""

from __future__ import annotations

import time
from typing import Dict

import numpy as np
import torch
import torch.nn as nn

from nerrf_tpu.graph.builder import AUX_VOCAB


def _segment_mean(msg: torch.Tensor, seg: torch.Tensor, num: int, w: torch.Tensor):
    total = torch.zeros(num, msg.shape[-1])
    total.index_add_(0, seg, msg * w[:, None])
    denom = torch.zeros(num, 1)
    denom.index_add_(0, seg, w[:, None])
    return total / denom.clamp_min(1e-6)


class SageBlock(nn.Module):
    def __init__(self, hidden: int):
        super().__init__()
        self.ln = nn.LayerNorm(hidden)
        self.w_msg = nn.Linear(hidden, hidden)
        self.w_self = nn.Linear(2 * hidden, hidden)
        self.dir_bias = nn.Parameter(torch.zeros(2, hidden))

    def forward(self, h, e_emb, src, dst, edge_w, n):
        hn = self.ln(h)
        msg = self.w_msg(hn)
        m_fwd = msg[src] + e_emb + self.dir_bias[0]
        m_rev = msg[dst] + e_emb + self.dir_bias[1]
        agg = _segment_mean(m_fwd, dst, n, edge_w) + _segment_mean(m_rev, src, n, edge_w)
        return h + torch.nn.functional.gelu(
            self.w_self(torch.cat([hn, agg], dim=-1))
        )


class TorchNerrfNet(nn.Module):
    """Same architecture as nerrf_tpu.models.joint.NerrfNet."""

    def __init__(self, node_dim, edge_dim, seq_dim, hidden=160, layers=28,
                 lstm_hidden=256, lstm_layers=2):
        super().__init__()
        self.type_emb = nn.Embedding(4, hidden)
        self.aux_emb = nn.Embedding(AUX_VOCAB, hidden)
        self.node_enc = nn.Linear(node_dim, hidden)
        self.edge_enc = nn.Linear(edge_dim, hidden)
        self.blocks = nn.ModuleList([SageBlock(hidden) for _ in range(layers)])
        self.final_ln = nn.LayerNorm(hidden)
        self.node_head = nn.Linear(hidden, 1)
        self.edge_head_1 = nn.Linear(4 * hidden, hidden)
        self.edge_head_2 = nn.Linear(hidden, 1)
        self.lstm_in = nn.Linear(seq_dim, lstm_hidden)
        self.lstm = nn.LSTM(lstm_hidden, lstm_hidden, num_layers=lstm_layers,
                            bidirectional=True, batch_first=True)
        self.lstm_merge = nn.Linear(2 * lstm_hidden, lstm_hidden)
        self.pool_ln = nn.LayerNorm(lstm_hidden)
        self.seq_head = nn.Linear(lstm_hidden, 1)
        self.seq_to_node = nn.Linear(lstm_hidden, node_dim)

    def forward(self, b: Dict[str, torch.Tensor]):
        # LSTM branch
        x = torch.nn.functional.gelu(self.lstm_in(b["seq_feat"]))
        x = x * b["seq_mask"][..., None]
        y, _ = self.lstm(x)
        y = torch.nn.functional.gelu(self.lstm_merge(y))
        m = b["seq_mask"][..., None]
        pooled = (y * m).sum(1) / m.sum(1).clamp_min(1.0)
        pooled = self.pool_ln(pooled)
        seq_logit = self.seq_head(pooled)[:, 0]

        # fusion into node features
        node_feat = b["node_feat"].clone()
        ok = b["seq_node_idx"] >= 0
        idx = b["seq_node_idx"].clamp_min(0)
        fused = self.seq_to_node(pooled) * ok[:, None]
        node_feat.index_add_(0, idx, fused)

        n = node_feat.shape[0]
        h = torch.nn.functional.gelu(
            self.node_enc(node_feat) + self.type_emb(b["node_type"]) + self.aux_emb(b["node_aux"])
        )
        h = h * b["node_mask"][:, None]
        e_emb = torch.nn.functional.gelu(self.edge_enc(b["edge_feat"]))
        edge_w = (b["edge_feat"][:, 12] + 0.1) * b["edge_mask"]
        for blk in self.blocks:
            h = blk(h, e_emb, b["edge_src"], b["edge_dst"], edge_w, n)
            h = h * b["node_mask"][:, None]
        h = self.final_ln(h)
        node_logit = self.node_head(h)[:, 0]
        hs, hd = h[b["edge_src"]], h[b["edge_dst"]]
        z = torch.nn.functional.gelu(
            self.edge_head_1(torch.cat([hs, hd, hs * hd, e_emb], dim=-1))
        )
        edge_logit = self.edge_head_2(z)[:, 0]
        return edge_logit, node_logit, seq_logit


def _to_torch(sample: Dict[str, np.ndarray]) -> Dict[str, torch.Tensor]:
    out = {}
    for k, v in sample.items():
        t = torch.from_numpy(np.ascontiguousarray(v))
        if t.dtype in (torch.float64,):
            t = t.float()
        if k in ("node_mask", "edge_mask", "seq_mask", "seq_valid"):
            t = t.float()
        if k in ("node_type", "node_aux", "edge_src", "edge_dst", "seq_node_idx"):
            t = t.long()
        out[k] = t
    return out


def _bce(logit, label, mask, pos_weight):
    loss = torch.nn.functional.binary_cross_entropy_with_logits(
        logit, label, reduction="none",
        pos_weight=torch.tensor(pos_weight),
    )
    return (loss * mask).sum() / mask.sum().clamp_min(1.0)


def measure_torch_steps_per_sec(
    arrays: Dict[str, np.ndarray], batch_size: int = 8, timed_steps: int = 5,
    pos_weight: float = 8.0, threads: int | None = None,
) -> float:
    """Train-steps/sec of the torch implementation on this host (CPU)."""
    if threads:
        torch.set_num_threads(threads)
    model = TorchNerrfNet(
        node_dim=arrays["node_feat"].shape[-1],
        edge_dim=arrays["edge_feat"].shape[-1],
        seq_dim=arrays["seq_feat"].shape[-1],
    )
    opt = torch.optim.AdamW(model.parameters(), lr=2e-3, weight_decay=1e-4)
    n = len(arrays["node_feat"])
    rng = np.random.default_rng(0)

    def one_step():
        idx = rng.choice(n, size=min(batch_size, n), replace=False)
        opt.zero_grad()
        total = 0.0
        for j in idx:  # per-window loop (torch lacks vmap-jit fusion here)
            b = _to_torch({k: v[j] for k, v in arrays.items()})
            e, nd, sq = model(b)
            loss = (
                _bce(e, b["edge_label"], b["edge_mask"], pos_weight)
                + 0.3 * _bce(nd, b["node_label"], b["node_mask"], pos_weight)
                + _bce(sq, b["seq_label"], b["seq_valid"], pos_weight)
            )
            total = total + loss
        (total / len(idx)).backward()
        opt.step()

    one_step()  # warmup
    t0 = time.perf_counter()
    for _ in range(timed_steps):
        one_step()
    return timed_steps / (time.perf_counter() - t0)
