"""nerrf_tpu.utils.probe_backend: the bounded backend probe every
terminating entry point (bench.py, env doctor, dryrun_multichip) relies on.
The `_code` hook substitutes the child program so these tests exercise the
probe machinery itself, not a backend."""

from nerrf_tpu.utils import probe_backend


def test_probe_parses_marker_amid_noise():
    ok, detail, count = probe_backend(
        timeout_sec=30,
        _code="print('runtime log line'); print('PROBE_OK 8 cpu x8 (cpu)'); "
              "print('trailing log')")
    assert ok and count == 8
    assert detail == "cpu x8 (cpu)"


def test_probe_timeout_kills_process_group():
    # the child spawns a grandchild inheriting stdout; with pipes this
    # would block past the timeout (the wedge this helper exists for)
    ok, detail, count = probe_backend(
        timeout_sec=2,
        _code="import subprocess, sys, time; "
              "subprocess.Popen([sys.executable, '-c', 'import time; "
              "time.sleep(60)']); time.sleep(60)")
    assert not ok and count == 0
    assert "did not respond" in detail


def test_probe_child_failure_reports_stderr_tail():
    ok, detail, count = probe_backend(
        timeout_sec=30,
        _code="import sys; print('boom: no backend', file=sys.stderr); "
              "sys.exit(3)")
    assert not ok and count == 0
    assert "boom: no backend" in detail


def test_probe_child_success_without_marker_is_failure():
    ok, detail, count = probe_backend(timeout_sec=30, _code="print('hi')")
    assert not ok and count == 0
