"""Elastic training: periodic full-state checkpoints, resume, fault injection.

The reference has no elastic-recovery story — its infra resilience is
"Kubernetes restarts the pod" (`/root/reference/docs/content/docs/architecture.mdx:29`;
SURVEY.md §5) and its only fault injection is the attack simulator itself.
For a TPU pod, preemption is routine, so training must be resumable with
*bit-identical* results: an interrupted-and-resumed run produces the same
parameters as an uninterrupted one.

Design for determinism under restart:
  * per-step randomness is *derived*, never threaded: batch order comes from
    ``np.random.default_rng((seed, step))`` and dropout keys from
    ``jax.random.fold_in(base, step)`` — so step N's randomness is identical
    no matter how many restarts preceded it;
  * checkpoints hold the full ``TrainState`` (params + optimizer state +
    step) via orbax, written step-dir-atomically: the ``meta.json`` sidecar
    is written last and is the scanner's commit marker;
  * a heartbeat file updated every few seconds of training (HEARTBEAT_SEC)
    and at every save supports external failure detection
    (`stale_heartbeat`), the host-side analogue of a missing DaemonSet
    liveness probe; supervisors should use timeout ≫ HEARTBEAT_SEC, not the
    checkpoint interval.

Fault injection for tests/drills: pass ``fault=Preemption.at(step)`` and the
loop raises mid-run exactly once, after the step's optimizer update but
before its checkpoint — the worst-case window.
"""

from __future__ import annotations

import dataclasses
import json
import time
from pathlib import Path
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from nerrf_tpu.utils import sync_result
import orbax.checkpoint as ocp

from nerrf_tpu.models.joint import NerrfNet
from nerrf_tpu.tracing import DEFAULT_TRACER
from nerrf_tpu.train.data import WindowDataset
from nerrf_tpu.train.loop import (
    TrainConfig,
    TrainResult,
    _fits_resident,
    evaluate,
    init_state,
    make_eval_fn,
    make_train_step,
    make_train_step_resident,
)


class Preemption(Exception):
    """Simulated preemption (fault injection for recovery drills)."""

    def __init__(self, step: int) -> None:
        super().__init__(f"simulated preemption at step {step}")
        self.step = step


@dataclasses.dataclass
class _FaultAt:
    fail_at: int
    fired: bool = False

    def __call__(self, step: int) -> None:
        if not self.fired and step == self.fail_at:
            self.fired = True
            raise Preemption(step)


def fault_at(step: int) -> _FaultAt:
    """A fault injector that preempts once at `step`."""
    return _FaultAt(step)


# --------------------------------------------------------------------------
# checkpoint dir layout: <dir>/step_<n>/{state/, meta.json}; meta last.
# --------------------------------------------------------------------------

def _save_full(ckpt_dir: Path, step: int, state) -> None:
    out = ckpt_dir / f"step_{step:08d}"
    with DEFAULT_TRACER.span("checkpoint", step=step):
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(out.absolute() / "state",
                       jax.device_get({"params": state.params,
                                       "opt_state": state.opt_state}),
                       force=True)
        # meta.json IS the commit marker (latest_step treats its presence
        # as "this checkpoint is complete"), so it must appear atomically:
        # a torn marker would crash every future restore's json.loads
        tmp = out / "meta.json.tmp"
        tmp.write_text(json.dumps({"step": step}) + "\n")
        tmp.replace(out / "meta.json")
    _heartbeat(ckpt_dir, step)


def latest_step(ckpt_dir: str | Path) -> Optional[int]:
    """Highest committed checkpoint step, or None."""
    best = None
    for p in Path(ckpt_dir).glob("step_*"):
        if (p / "meta.json").exists():
            step = json.loads((p / "meta.json").read_text())["step"]
            best = step if best is None else max(best, step)
    return best


def _restore_full(ckpt_dir: Path, step: int, template_state):
    target = jax.device_get({"params": template_state.params,
                             "opt_state": template_state.opt_state})
    with ocp.StandardCheckpointer() as ckptr:
        got = ckptr.restore(
            (ckpt_dir / f"step_{step:08d}").absolute() / "state", target)
    return template_state.replace(
        step=step, params=got["params"], opt_state=got["opt_state"])


HEARTBEAT_SEC = 5.0  # wall-clock heartbeat cadence during training


def _heartbeat(ckpt_dir: Path, step: int) -> None:
    tmp = ckpt_dir / ".heartbeat.tmp"
    tmp.write_text(json.dumps({"step": step, "ts": time.time()}) + "\n")
    tmp.rename(ckpt_dir / "heartbeat.json")


def stale_heartbeat(ckpt_dir: str | Path, timeout_sec: float) -> bool:
    """Failure detection: True if no heartbeat within `timeout_sec` (or none
    at all) — the signal an external supervisor uses to reschedule."""
    p = Path(ckpt_dir) / "heartbeat.json"
    if not p.exists():
        return True
    hb = json.loads(p.read_text())
    return (time.time() - hb["ts"]) > timeout_sec


# --------------------------------------------------------------------------

def train_elastic(
    train_ds: WindowDataset,
    eval_ds: Optional[WindowDataset] = None,
    cfg: Optional[TrainConfig] = None,
    ckpt_dir: str | Path = "checkpoints",
    save_every: int = 50,
    fault=None,
    log=None,
    compile_cache=None,
    monitor=None,
    full_history: bool = False,
) -> TrainResult:
    """Run (or resume) training to `cfg.num_steps` with periodic full-state
    checkpoints.  Restartable at any point; deterministic across restarts.

    ``compile_cache`` (a `compilecache.CompileCache`) matters most here:
    every supervisor restart re-pays the step compile before resuming, so
    an elastic run with the persistent cache resumes stepping in the time
    it takes to deserialize one executable.

    ``monitor`` (a `trainwatch.TrainHealthMonitor`) observes the loss +
    in-step telemetry at every checkpoint boundary — the cadence this
    loop already pays host syncs at — and a latched divergence halts the
    run BEFORE the diverged state overwrites the last good checkpoint
    (the restart pointer the divergence bundle carries)."""
    cfg = cfg or TrainConfig()
    ckpt_dir = Path(ckpt_dir)
    ckpt_dir.mkdir(parents=True, exist_ok=True)

    model = NerrfNet(cfg.model)
    base_rng = jax.random.PRNGKey(cfg.seed)
    # init key far outside the per-step fold_in range [0, num_steps)
    state = init_state(model, cfg, train_ds.arrays,
                       jax.random.fold_in(base_rng, 0x7FFFFFFF))
    start = latest_step(ckpt_dir)
    if start is not None:
        state = _restore_full(ckpt_dir, start, state)
        if log:
            log(f"resumed from step {start}")
    else:
        start = 0

    resident = _fits_resident(train_ds.arrays)
    train_step = (make_train_step_resident(model, cfg, train_ds.arrays)
                  if resident else make_train_step(model, cfg))
    if compile_cache is not None:
        from nerrf_tpu.train.loop import cache_train_step

        train_step = cache_train_step(compile_cache, train_step, model, cfg,
                                      "train_step_resident")
    if monitor is not None:
        from nerrf_tpu.flight.journal import fingerprint as _fp

        monitor.set_run(config_fingerprint=_fp(cfg),
                        model_fingerprint=_fp(cfg.model),
                        steps=cfg.num_steps, seed=cfg.seed)
        if start > 0:
            monitor.note_checkpoint(ckpt_dir / f"step_{start:08d}", start)
    from nerrf_tpu.train.loop import _history, _history_entry, \
        _loss_components, _telemetry_floats

    n = len(train_ds)
    history = _history(full_history)
    t_start = None
    loss = None
    halted = None
    # Heartbeat on a wall-clock cadence (HEARTBEAT_SEC), decoupled from the
    # checkpoint interval: keyed only to saves, a supervisor with
    # timeout < save_every × step-time would restart healthy runs.
    last_hb = 0.0
    completed = start
    for step in range(start, cfg.num_steps):
        # derived randomness: identical for step N on every (re)run
        order = np.random.default_rng((cfg.seed, step))
        idx = order.choice(n, size=min(cfg.batch_size, n), replace=False)
        step_rng = jax.random.fold_in(base_rng, step)
        if resident:
            state, loss, aux, _ = train_step(state, jnp.asarray(idx), step_rng)
        else:
            batch = {k: jnp.asarray(v[idx]) for k, v in train_ds.arrays.items()}
            state, loss, aux, _ = train_step(state, batch, step_rng)
        if t_start is None:
            # nerrflint: ok[sync-in-hot-loop] step-0 compile barrier:
            sync_result(loss)  # excludes compile from steps/s timing
            t_start = time.perf_counter()
        if fault is not None:
            fault(step)
        now = time.monotonic()
        if now - last_hb >= HEARTBEAT_SEC:
            _heartbeat(ckpt_dir, step)
            last_hb = now
        done = completed = step + 1
        if done % save_every == 0 or done == cfg.num_steps:
            entry = _history_entry(step, loss, aux)
            if monitor is not None:
                # observe BEFORE saving: a divergence latched here halts
                # the loop with the previous checkpoint still the newest
                # good one (the bundle's restart pointer)
                monitor.observe_step(step, entry["loss"],
                                     telemetry=_telemetry_floats(aux),
                                     components=_loss_components(aux))
                if monitor.should_halt:
                    halted = monitor.diverged
                    if log:
                        log(f"trainwatch: halting at step {step} — "
                            f"{halted[1]} (last good checkpoint kept)")
                    break
            _save_full(ckpt_dir, done, state)
            if monitor is not None:
                monitor.note_checkpoint(ckpt_dir / f"step_{done:08d}", done)
            history.append(entry)
            if log:
                log(f"step {step}: loss={entry['loss']:.4f} (checkpointed)")

    sync_result(state.params)
    if monitor is not None:
        monitor.finish()  # post-training eval must not read as a stall
    elapsed = time.perf_counter() - (t_start or time.perf_counter())
    # steps actually run (a divergence halt breaks out early — dividing
    # by the CONFIGURED count would overstate throughput by the skipped
    # fraction)
    steps = completed - start
    steps_per_sec = max(steps - 1, 1) / elapsed if elapsed > 0 else 0.0
    metrics = ({} if halted is not None else evaluate(
        make_eval_fn(model), state.params,
        eval_ds if eval_ds is not None else train_ds, cfg.batch_size))
    return TrainResult(state=state, metrics=metrics,
                       steps_per_sec=steps_per_sec, history=list(history))
