"""The deep nerrflint tier: jaxpr-level program-contract verification.

Where the base rules (`nerrf_tpu/analysis/*.py`) read source ASTs, these
rules abstractly trace the *real entry points* — the serve bucket ladder,
the flat train-step boundary, the shard_map/pjit shims, the Pallas
kernels — via `jax.eval_shape`/`jax.make_jaxpr`/`jit.lower` over
`ShapeDtypeStruct` avals (no devices, no data, no compiles) and verify
five contracts:

  ============================  ============================================
  program-closure               warmup-compiled set == admission-reachable
                                signature set (the zero-recompile proof)
  donation-discipline           donated-then-read, un-donated train state,
                                wasted/forbidden/double donation
  collective-consistency        collective axis names vs the mesh spec,
                                PartitionSpec rank-match
  pallas-budget                 block shapes × dtype vs the VMEM budget,
                                tile/grid divisibility
  cache-key-coverage            jaxpr dependencies the CompileCache
                                fingerprint cannot see
  ============================  ============================================

Surfaces: ``nerrf lint --deep`` / ``python scripts/nerrflint.py --deep``
(both force a virtual multi-device CPU backend first), the tier-1 gate
``tests/test_programs.py`` (which also asserts the <30 s CPU budget), and
the chip-queue pre-flights in scripts/.  Findings flow through the same
engine schema, suppressions and baseline as every other rule.

Import discipline: this package imports jax only inside rule execution —
the base engine (and plain ``nerrf lint``) must stay importable with no
jax on the path.
"""

from nerrf_tpu.analysis.programs.abstract import prepare_backend
from nerrf_tpu.analysis.programs.cachekey import CacheKeyCoverage
from nerrf_tpu.analysis.programs.closure import SignatureClosure
from nerrf_tpu.analysis.programs.collectives import CollectiveConsistency
from nerrf_tpu.analysis.programs.donation import DonationDiscipline
from nerrf_tpu.analysis.programs.pallas_budget import PallasBudget

DEEP_RULE_IDS = ("program-closure", "donation-discipline",
                 "collective-consistency", "pallas-budget",
                 "cache-key-coverage")


def deep_rules():
    """The deep ruleset, in contract order (engine.main --deep appends
    these to the base rules)."""
    return [SignatureClosure(), DonationDiscipline(),
            CollectiveConsistency(), PallasBudget(), CacheKeyCoverage()]


__all__ = [
    "CacheKeyCoverage",
    "CollectiveConsistency",
    "DEEP_RULE_IDS",
    "DonationDiscipline",
    "PallasBudget",
    "SignatureClosure",
    "deep_rules",
    "prepare_backend",
]
