import numpy as np
import pytest

from nerrf_tpu.pipeline import build_undo_domain, heuristic_detect
from nerrf_tpu.planner import MCTSConfig, MCTSPlanner
from nerrf_tpu.planner.domain import ActionKind, UndoAction, UndoPlan
from nerrf_tpu.planner.value_net import HeuristicValue
from nerrf_tpu.rollback import (
    FileSimConfig,
    RollbackExecutor,
    SandboxGate,
    SnapshotStore,
    run_file_attack,
)
from nerrf_tpu.rollback.filesim import seed_files
from nerrf_tpu.rollback.sandbox import FirecrackerDriver

CFG = FileSimConfig(num_files=6, min_file_bytes=4096, max_file_bytes=16384)


def _plan_for(paths, scores=0.95):
    return UndoPlan(
        actions=[UndoAction(ActionKind.REVERT_FILE, str(p), scores) for p in paths],
        expected_reward=1.0, rollouts=0, rollouts_per_sec=0.0, planning_seconds=0.0,
    )


def test_snapshot_store_roundtrip(tmp_path):
    victim = tmp_path / "v"
    seed_files(victim, CFG)
    store = SnapshotStore(tmp_path / "store")
    m = store.snapshot(victim, "s1")
    assert len(m.files) == 6
    assert store.list_manifests() == ["s1"]
    # mutate a file → diff sees it, restore fixes it bit-exactly
    target = next(victim.glob("*.dat"))
    orig = target.read_bytes()
    target.write_bytes(b"corrupted")
    rel = target.name
    assert store.diff(m, victim) == {rel: "modified"}
    store.restore_file(m, rel, victim)
    assert target.read_bytes() == orig
    assert store.verify_file(m, rel, victim)
    assert store.diff(m, victim) == {}
    # manifest json roundtrip
    m2 = store.load_manifest("s1")
    assert m2.files == m.files


def test_file_attack_destroys_and_traces(tmp_path):
    victim = tmp_path / "v"
    seed_files(victim, CFG)
    originals = {p.name: p.read_bytes() for p in victim.glob("*.dat")}
    trace, encrypted = run_file_attack(victim, CFG)
    assert len(encrypted) == 6
    assert not list(victim.glob("*.dat"))  # all renamed
    for enc in encrypted:
        orig_name = enc.name[: -len(CFG.ransom_ext)]
        assert enc.read_bytes() != originals[orig_name]  # content destroyed
    # trace carries the attack at syscall granularity with inodes
    assert trace.events.num_valid > 30
    assert (trace.events.inode > 0).sum() > 0
    assert trace.labels.min() == 1.0  # attack-only trace


def test_executor_restores_and_verifies(tmp_path):
    victim = tmp_path / "v"
    seed_files(victim, CFG)
    store = SnapshotStore(tmp_path / "store")
    m = store.snapshot(victim, "pre")
    originals = {p.name: p.read_bytes() for p in victim.glob("*.dat")}
    _, encrypted = run_file_attack(victim, CFG)

    rep = RollbackExecutor(store, m, victim).execute(_plan_for(encrypted))
    assert rep.files_restored == 6 and rep.files_failed == 0
    assert rep.verified
    for name, data in originals.items():
        assert (victim / name).read_bytes() == data
    assert not list(victim.glob(f"*{CFG.ransom_ext}"))  # artifacts removed


def test_executor_skips_unknown_targets(tmp_path):
    victim = tmp_path / "v"
    seed_files(victim, CFG)
    store = SnapshotStore(tmp_path / "store")
    m = store.snapshot(victim, "pre")
    rep = RollbackExecutor(store, m, victim).execute(
        _plan_for(["/nowhere/ghost.lockbit3"])
    )
    assert rep.files_skipped == 1 and rep.files_restored == 0
    assert not rep.verified


def test_sandbox_gate_approves_good_plan_and_leaves_victim_untouched(tmp_path):
    victim = tmp_path / "v"
    seed_files(victim, CFG)
    store = SnapshotStore(tmp_path / "store")
    m = store.snapshot(victim, "pre")
    _, encrypted = run_file_attack(victim, CFG)
    before = sorted(p.name for p in victim.iterdir())

    gate = SandboxGate(store, m).rehearse(_plan_for(encrypted), victim)
    assert gate.approved, gate.reason
    assert gate.rehearsal.files_restored == 6
    # rehearsal ran on a clone: victim still encrypted
    assert sorted(p.name for p in victim.iterdir()) == before


def test_sandbox_gate_rejects_incomplete_plan(tmp_path):
    victim = tmp_path / "v"
    seed_files(victim, CFG)
    store = SnapshotStore(tmp_path / "store")
    m = store.snapshot(victim, "pre")
    _, encrypted = run_file_attack(victim, CFG)
    gate = SandboxGate(store, m).rehearse(_plan_for(encrypted[:2]), victim)
    assert not gate.approved
    assert len(gate.residual_diff) > 0


def test_sandbox_gate_handles_nested_victim_layout(tmp_path):
    """Plan targets are absolute paths under the original victim; the gate
    executes against a clone at a different root — suffix matching must still
    resolve nested manifest keys."""
    victim = tmp_path / "v"
    sub = victim / "sub" / "deep"
    sub.mkdir(parents=True)
    (sub / "a.dat").write_bytes(b"alpha" * 1000)
    (victim / "b.dat").write_bytes(b"beta" * 1000)
    store = SnapshotStore(tmp_path / "store")
    m = store.snapshot(victim, "pre")
    assert "sub/deep/a.dat" in m.files
    # encrypt both by hand
    for p, rel in ((sub / "a.dat", "sub/deep/a.dat"), (victim / "b.dat", "b.dat")):
        p.write_bytes(b"X" * 100)
        p.rename(p.with_suffix(".dat.lockbit3"))
    plan = _plan_for([
        str(sub / "a.dat.lockbit3"), str(victim / "b.dat.lockbit3")
    ])
    gate = SandboxGate(store, m).rehearse(plan, victim)
    assert gate.approved, (gate.reason, gate.residual_diff)
    rep = RollbackExecutor(store, m, victim).execute(plan)
    assert rep.files_restored == 2 and rep.verified
    assert (sub / "a.dat").read_bytes() == b"alpha" * 1000


def test_executor_fails_closed_on_path_escape(tmp_path):
    """A manifest rel that resolves outside the sandbox root (hostile or
    corrupted manifest) must refuse THAT step with a one-line journaled
    reason — not raise, not write outside root, not strand the rest of
    the plan."""
    from nerrf_tpu.flight.journal import EventJournal
    from nerrf_tpu.observability import MetricsRegistry

    victim = tmp_path / "inner" / "v"
    seed_files(victim, CFG)
    outside = tmp_path / "inner" / "loot.dat"  # where ../loot.dat lands
    store = SnapshotStore(tmp_path / "store")
    m = store.snapshot(victim, "pre")
    _, encrypted = run_file_attack(victim, CFG)
    # graft a hostile entry reusing a legitimate blob digest
    any_rel = next(iter(m.files))
    m.files["../loot.dat"] = m.files[any_rel]
    plan = _plan_for(["../loot.dat"] + [str(p) for p in encrypted])

    jr = EventJournal(registry=MetricsRegistry())
    rep = RollbackExecutor(store, m, victim, journal=jr).execute(plan)
    assert rep.files_failed == 1 and rep.files_restored == 6
    assert not outside.exists()  # nothing was written outside root
    refused = [d for d in rep.details
               if d["result"].startswith("refused:")]
    assert len(refused) == 1
    assert "escapes sandbox root" in refused[0]["result"]
    recs = jr.tail(kinds=("rollback_step_failed",))
    assert len(recs) == 1 and "escapes" in recs[0].data["reason"]


def test_executor_fails_closed_on_corrupt_blob(tmp_path):
    """A snapshot blob whose bytes no longer hash to the manifest digest
    (bit rot, tampering) must never reach the victim tree: the step fails
    closed BEFORE writing, is journaled, and the rest of the plan still
    executes."""
    from nerrf_tpu.flight.journal import EventJournal
    from nerrf_tpu.observability import MetricsRegistry

    victim = tmp_path / "v"
    seed_files(victim, CFG)
    store = SnapshotStore(tmp_path / "store")
    m = store.snapshot(victim, "pre")
    _, encrypted = run_file_attack(victim, CFG)
    poisoned = encrypted[0]
    rel = poisoned.name[: -len(CFG.ransom_ext)]
    digest = m.files[rel][0]
    (store.dir / "blobs" / digest).write_bytes(b"rotten")
    before = poisoned.read_bytes()

    jr = EventJournal(registry=MetricsRegistry())
    rep = RollbackExecutor(store, m, victim, journal=jr).execute(
        _plan_for([str(p) for p in encrypted]))
    assert rep.files_failed == 1 and rep.files_restored == 5
    assert not rep.verified
    assert poisoned.read_bytes() == before  # corrupt bytes never landed
    recs = jr.tail(kinds=("rollback_step_failed",))
    assert len(recs) == 1
    assert "pre-image hash mismatch" in recs[0].data["reason"]
    assert recs[0].data["rel"] == rel


def test_firecracker_driver_gated():
    assert not FirecrackerDriver.available()  # no KVM in this container
    with pytest.raises(RuntimeError):
        FirecrackerDriver().rehearse()


def test_pipeline_detect_and_domain(tmp_path):
    victim = tmp_path / "v"
    seed_files(victim, CFG)
    store = SnapshotStore(tmp_path / "store")
    m = store.snapshot(victim, "pre")
    trace, encrypted = run_file_attack(victim, CFG)
    det = heuristic_detect(trace)
    flagged = det.flagged_files()
    # every encrypted file flagged high
    for enc in encrypted:
        assert det.file_scores.get(str(enc), 0) >= 0.9
    # the attacking process flagged
    assert max(det.proc_scores.values()) > 0.9
    domain = build_undo_domain(det, m, root=str(victim))
    assert domain.F >= 6
    # manifest-derived loss is the real file size (up to the 0.01 MB floor)
    loss_of = dict(zip(domain.file_paths, domain.file_loss_mb))
    for enc in encrypted:
        rel = enc.name[: -len(CFG.ransom_ext)]
        expected = max(m.files[rel][1] / 1e6, 0.01)
        assert abs(loss_of[str(enc)] - expected) < 1e-6

    plan = MCTSPlanner(domain, HeuristicValue(),
                       MCTSConfig(num_simulations=200, batch_size=16)).plan()
    targets = {a.target for a in plan.actions}
    assert {str(e) for e in encrypted} <= targets


def test_gate_replay_validates_determinism(tmp_path):
    """clone → REPLAY → rehearse (architecture.mdx:75-87): when the captured
    trace fully explains the observed damage, the gate approves."""
    victim = tmp_path / "v"
    seed_files(victim, CFG)
    store = SnapshotStore(tmp_path / "store")
    m = store.snapshot(victim, "pre")
    trace, encrypted = run_file_attack(victim, CFG)

    gate = SandboxGate(store, m).rehearse(_plan_for(encrypted), victim,
                                          trace=trace)
    assert gate.replay_ops > 0
    assert gate.replay_divergence == {}
    assert gate.approved, gate.reason
    assert "replay deterministic" in gate.reason


def test_gate_replay_catches_nondeterministic_side_effect(tmp_path):
    """An attacker action the trace does NOT capture (here: an extra file
    deleted after capture) must fail the gate — an undo plan validated
    against an incomplete story cannot be trusted."""
    victim = tmp_path / "v"
    seed_files(victim, CFG)
    store = SnapshotStore(tmp_path / "store")
    m = store.snapshot(victim, "pre")
    trace, encrypted = run_file_attack(victim, CFG)
    # off-trace side effect: one encrypted artifact vanishes untraced
    encrypted[0].unlink()

    gate = SandboxGate(store, m).rehearse(_plan_for(encrypted[1:]), victim,
                                          trace=trace)
    assert not gate.approved
    assert gate.replay_divergence, "divergence should have been detected"
    assert any("missing-from-victim" in v
               for v in gate.replay_divergence.values())


def test_gate_replay_catches_uncaptured_write(tmp_path):
    """A file the attacker wrote without the tracer seeing it (trace cannot
    reproduce it) is flagged as unexplained."""
    victim = tmp_path / "v"
    seed_files(victim, CFG)
    store = SnapshotStore(tmp_path / "store")
    m = store.snapshot(victim, "pre")
    trace, encrypted = run_file_attack(victim, CFG)
    (victim / "exfil_staging.bin").write_bytes(b"Z" * 512)  # untraced write

    gate = SandboxGate(store, m).rehearse(_plan_for(encrypted), victim,
                                          trace=trace)
    assert not gate.approved
    assert gate.replay_divergence.get("exfil_staging.bin") == "unexplained-by-trace"
