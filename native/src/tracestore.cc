/* Embedded time-bucketed trace store; see include/nerrf/tracestore.h for the
 * format contract (shared with the Python fallback).  Single-writer,
 * in-process — the durability model is "crash loses at most the un-flushed
 * delta", matching the reference's planned 30 s delta compaction window
 * (`/root/reference/README.md:113`). */

#include "nerrf/tracestore.h"

#include <algorithm>
#include <cerrno>
#include <cstdio>
#include <cstring>
#include <filesystem>
#include <string>
#include <unordered_map>
#include <vector>

namespace fs = std::filesystem;

namespace {

constexpr int64_t kDefaultBucketNs = 30LL * 1000 * 1000 * 1000;
constexpr size_t kAutoFlushRows = 1u << 18;  // bound delta memory + crash loss
constexpr char kMagic[8] = {'N', 'R', 'R', 'F', 'S', 'E', 'G', '1'};

#pragma pack(push, 1)
struct Record {
  int64_t ts_ns;
  int32_t pid, tid, comm_id, syscall_id, path_id, new_path_id, flags;
  int64_t ret_val, bytes, inode;
  int32_t mode, uid, gid;
};
#pragma pack(pop)
static_assert(sizeof(Record) == NERRF_STORE_RECORD_SIZE, "record layout");

struct Segment {
  int64_t min_ts = 0;  // inclusive
  int64_t max_ts = 0;  // inclusive
  int64_t seq = 0;
  int64_t count = 0;
  fs::path path;
};

bool ts_less(const Record &a, const Record &b) { return a.ts_ns < b.ts_ns; }

}  // namespace

struct nerrf_store {
  fs::path dir;
  int64_t bucket_ns = kDefaultBucketNs;
  int64_t next_seq = 0;

  std::vector<std::string> strings;               // global pool, [0] = ""
  std::unordered_map<std::string, int32_t> index; // string -> global id
  FILE *strings_log = nullptr;

  std::vector<Record> delta;
  std::vector<Segment> segments;  // live (highest-seq per bucket) only

  ~nerrf_store() {
    if (strings_log) fclose(strings_log);
  }

  int32_t intern(const std::string &s) {
    auto it = index.find(s);
    if (it != index.end()) return it->second;
    int32_t id = static_cast<int32_t>(strings.size());
    uint32_t len = static_cast<uint32_t>(s.size());
    // log first, cache only on success: a failed write must not leave an id
    // cached in memory that later appends would persist without a log entry
    if (fwrite(&len, 4, 1, strings_log) != 1 ||
        (len && fwrite(s.data(), 1, len, strings_log) != len))
      return -1;
    strings.push_back(s);
    index.emplace(s, id);
    return id;
  }

  bool load_strings() {
    fs::path p = dir / "strings.log";
    FILE *f = fopen(p.c_str(), "rb");
    long good_bytes = 0;  // offset of the last fully-parsed record
    if (f) {
      uint32_t len;
      std::string s;
      while (fread(&len, 4, 1, f) == 1) {
        s.resize(len);
        if (len && fread(&s[0], 1, len, f) != len) break;  // truncated tail
        good_bytes += 4 + static_cast<long>(len);
        if (index.find(s) == index.end()) {
          index.emplace(s, static_cast<int32_t>(strings.size()));
          strings.push_back(s);
        }
      }
      fclose(f);
      // drop any torn tail so appended records parse from a clean boundary
      std::error_code ec;
      if (good_bytes < static_cast<long>(fs::file_size(p, ec)) && !ec)
        fs::resize_file(p, good_bytes, ec);
    }
    if (strings.empty()) {
      strings.push_back("");
      index.emplace("", 0);
    }
    strings_log = fopen(p.c_str(), "ab");
    if (!strings_log) return false;
    if (ftell(strings_log) == 0) {
      // fresh log: persist the implicit "" so replays see identical ids
      uint32_t zero = 0;
      if (fwrite(&zero, 4, 1, strings_log) != 1) return false;
      for (size_t i = 1; i < strings.size(); ++i) {
        uint32_t len = static_cast<uint32_t>(strings[i].size());
        if (fwrite(&len, 4, 1, strings_log) != 1 ||
            fwrite(strings[i].data(), 1, len, strings_log) != len)
          return false;
      }
    }
    return true;
  }

  bool scan_segments() {
    fs::path segdir = dir / "segments";
    std::error_code ec;
    fs::create_directories(segdir, ec);
    if (ec) return false;
    // bucket start -> best segment
    std::unordered_map<int64_t, Segment> best;
    std::vector<fs::path> stale;
    for (const auto &ent : fs::directory_iterator(segdir)) {
      if (ent.path().extension() != ".seg") continue;
      Segment s;
      s.path = ent.path();
      long long mn, mx, seq;
      if (sscanf(ent.path().filename().c_str(), "%lld-%lld-%lld.seg", &mn, &mx,
                 &seq) != 3)
        continue;
      s.min_ts = mn;
      s.max_ts = mx;
      s.seq = seq;
      FILE *f = fopen(s.path.c_str(), "rb");
      if (!f) return false;
      char magic[8];
      uint64_t count = 0;
      bool ok = fread(magic, 8, 1, f) == 1 &&
                memcmp(magic, kMagic, 8) == 0 && fread(&count, 8, 1, f) == 1;
      fclose(f);
      if (!ok) continue;  // corrupt segment: ignore
      s.count = static_cast<int64_t>(count);
      next_seq = std::max(next_seq, s.seq + 1);
      int64_t bucket = s.min_ts;
      auto it = best.find(bucket);
      if (it == best.end()) {
        best.emplace(bucket, s);
      } else if (s.seq > it->second.seq) {
        stale.push_back(it->second.path);
        it->second = s;
      } else {
        stale.push_back(s.path);
      }
    }
    for (const auto &p : stale) fs::remove(p, ec);
    for (auto &kv : best) segments.push_back(kv.second);
    std::sort(segments.begin(), segments.end(),
              [](const Segment &a, const Segment &b) {
                return a.min_ts < b.min_ts;
              });
    return true;
  }

  bool read_segment(const Segment &s, std::vector<Record> *out) const {
    FILE *f = fopen(s.path.c_str(), "rb");
    if (!f) return false;
    char magic[8];
    uint64_t count = 0;
    bool ok = fread(magic, 8, 1, f) == 1 && memcmp(magic, kMagic, 8) == 0 &&
              fread(&count, 8, 1, f) == 1;
    if (ok) {
      // bound by the actual file size: a corrupt count must not drive a
      // giant resize (bad_alloc would unwind across the C ABI and abort)
      std::error_code ec;
      uint64_t max_records =
          (fs::file_size(s.path, ec) - 16) / sizeof(Record);
      if (ec || count > max_records) ok = false;
    }
    if (ok) {
      size_t base = out->size();
      out->resize(base + count);
      ok = fread(out->data() + base, sizeof(Record), count, f) == count;
      if (!ok) out->resize(base);
    }
    fclose(f);
    return ok;
  }

  bool write_segment(int64_t bucket_start, const std::vector<Record> &recs) {
    int64_t min_ts = bucket_start;
    int64_t max_ts = bucket_start + bucket_ns - 1;
    int64_t seq = next_seq++;
    char name[96];
    snprintf(name, sizeof(name), "%lld-%lld-%lld.seg",
             static_cast<long long>(min_ts), static_cast<long long>(max_ts),
             static_cast<long long>(seq));
    fs::path final_path = dir / "segments" / name;
    fs::path tmp_path = final_path;
    tmp_path += ".tmp";
    FILE *f = fopen(tmp_path.c_str(), "wb");
    if (!f) return false;
    uint64_t count = recs.size();
    bool ok = fwrite(kMagic, 8, 1, f) == 1 && fwrite(&count, 8, 1, f) == 1 &&
              fwrite(recs.data(), sizeof(Record), count, f) == count;
    ok = (fclose(f) == 0) && ok;
    if (!ok) return false;
    std::error_code ec;
    fs::rename(tmp_path, final_path, ec);
    if (ec) return false;

    // supersede any previous segment for this bucket
    for (auto it = segments.begin(); it != segments.end(); ++it) {
      if (it->min_ts == min_ts) {
        fs::remove(it->path, ec);
        segments.erase(it);
        break;
      }
    }
    Segment s;
    s.min_ts = min_ts;
    s.max_ts = max_ts;
    s.seq = seq;
    s.count = static_cast<int64_t>(count);
    s.path = final_path;
    segments.insert(std::upper_bound(segments.begin(), segments.end(), s,
                                     [](const Segment &a, const Segment &b) {
                                       return a.min_ts < b.min_ts;
                                     }),
                    s);
    return true;
  }

  int64_t flush() {
    if (delta.empty()) return 0;
    fflush(strings_log);
    std::stable_sort(delta.begin(), delta.end(), ts_less);
    int64_t written = 0;
    size_t i = 0;
    while (i < delta.size()) {
      int64_t bucket = delta[i].ts_ns - (((delta[i].ts_ns % bucket_ns) +
                                          bucket_ns) % bucket_ns);
      std::vector<Record> recs;
      // existing segment for this bucket merges with the new delta slice
      for (const auto &s : segments)
        if (s.min_ts == bucket && !read_segment(s, &recs)) return -1;
      size_t j = i;
      while (j < delta.size() && delta[j].ts_ns < bucket + bucket_ns) ++j;
      recs.insert(recs.end(), delta.begin() + i, delta.begin() + j);
      std::stable_sort(recs.begin(), recs.end(), ts_less);
      if (!write_segment(bucket, recs)) return -1;
      ++written;
      i = j;
    }
    delta.clear();
    return written;
  }

  void collect(int64_t start_ns, int64_t end_ns,
               std::vector<Record> *out) const {
    for (const auto &s : segments) {
      if (s.max_ts < start_ns || s.min_ts >= end_ns) continue;
      std::vector<Record> recs;
      if (!read_segment(s, &recs)) continue;
      for (const auto &r : recs)
        if (r.ts_ns >= start_ns && r.ts_ns < end_ns) out->push_back(r);
    }
    for (const auto &r : delta)
      if (r.ts_ns >= start_ns && r.ts_ns < end_ns) out->push_back(r);
    std::stable_sort(out->begin(), out->end(), ts_less);
  }
};

extern "C" {

nerrf_store_t *nerrf_store_open(const char *dir, int64_t bucket_ns) {
  auto *st = new (std::nothrow) nerrf_store();
  if (!st) return nullptr;
  st->dir = dir;
  st->bucket_ns = bucket_ns > 0 ? bucket_ns : kDefaultBucketNs;
  std::error_code ec;
  fs::create_directories(st->dir, ec);
  if (ec) {
    delete st;
    return nullptr;
  }
  // The bucket size is a property of the segments already on disk: a stored
  // BUCKET file wins over the caller's request (mismatched bucket math would
  // silently skip segments during queries).
  fs::path bpath = st->dir / "BUCKET";
  FILE *bf = fopen(bpath.c_str(), "rb");
  if (bf) {
    long long stored = 0;
    if (fscanf(bf, "%lld", &stored) == 1 && stored > 0)
      st->bucket_ns = stored;
    fclose(bf);
  } else {
    bf = fopen(bpath.c_str(), "wb");
    if (!bf) {
      delete st;
      return nullptr;
    }
    fprintf(bf, "%lld\n", static_cast<long long>(st->bucket_ns));
    fclose(bf);
  }
  if (!st->load_strings() || !st->scan_segments()) {
    delete st;
    return nullptr;
  }
  return st;
}

void nerrf_store_close(nerrf_store_t *st) {
  if (!st) return;
  st->flush();
  delete st;
}

int64_t nerrf_store_append(nerrf_store_t *st, const nerrf_columns_t *cols,
                           size_t n, const char *const *strings,
                           size_t n_strings) {
  if (!st || !cols) return -1;
  // caller id -> global id, resolved once per append
  std::vector<int32_t> remap(n_strings, 0);
  for (size_t i = 0; i < n_strings; ++i) {
    int32_t id = st->intern(strings[i] ? strings[i] : "");
    if (id < 0) return -1;
    remap[i] = id;
  }
  auto mapped = [&](int32_t id) -> int32_t {
    return (id >= 0 && static_cast<size_t>(id) < n_strings) ? remap[id] : 0;
  };
  int64_t accepted = 0;
  for (size_t i = 0; i < n; ++i) {
    if (cols->valid && !cols->valid[i]) continue;
    Record r;
    r.ts_ns = cols->ts_ns[i];
    r.pid = cols->pid[i];
    r.tid = cols->tid[i];
    r.comm_id = mapped(cols->comm_id[i]);
    r.syscall_id = cols->syscall_id[i];
    r.path_id = mapped(cols->path_id[i]);
    r.new_path_id = mapped(cols->new_path_id[i]);
    r.flags = cols->flags[i];
    r.ret_val = cols->ret_val[i];
    r.bytes = cols->bytes[i];
    r.inode = cols->inode[i];
    r.mode = cols->mode[i];
    r.uid = cols->uid[i];
    r.gid = cols->gid[i];
    st->delta.push_back(r);
    ++accepted;
  }
  if (st->delta.size() >= kAutoFlushRows && st->flush() < 0) return -1;
  return accepted;
}

int64_t nerrf_store_flush(nerrf_store_t *st) {
  if (!st) return -1;
  return st->flush();
}

int64_t nerrf_store_query_count(nerrf_store_t *st, int64_t start_ns,
                                int64_t end_ns) {
  if (!st) return -1;
  std::vector<Record> out;
  st->collect(start_ns, end_ns, &out);
  return static_cast<int64_t>(out.size());
}

int64_t nerrf_store_query(nerrf_store_t *st, int64_t start_ns, int64_t end_ns,
                          nerrf_columns_t *cols, size_t cap) {
  if (!st || !cols) return -1;
  std::vector<Record> out;
  st->collect(start_ns, end_ns, &out);
  if (out.size() > cap)  // tell the caller the size it needs: -(needed)-1
    return -static_cast<int64_t>(out.size()) - 1;
  for (size_t i = 0; i < out.size(); ++i) {
    const Record &r = out[i];
    cols->ts_ns[i] = r.ts_ns;
    cols->pid[i] = r.pid;
    cols->tid[i] = r.tid;
    cols->comm_id[i] = r.comm_id;
    cols->syscall_id[i] = r.syscall_id;
    cols->path_id[i] = r.path_id;
    cols->new_path_id[i] = r.new_path_id;
    cols->flags[i] = r.flags;
    cols->ret_val[i] = r.ret_val;
    cols->bytes[i] = r.bytes;
    cols->inode[i] = r.inode;
    cols->mode[i] = r.mode;
    cols->uid[i] = r.uid;
    cols->gid[i] = r.gid;
    if (cols->valid) cols->valid[i] = 1;
  }
  return static_cast<int64_t>(out.size());
}

int64_t nerrf_store_num_strings(const nerrf_store_t *st) {
  return st ? static_cast<int64_t>(st->strings.size()) : -1;
}

const char *nerrf_store_string(const nerrf_store_t *st, int64_t id) {
  if (!st || id < 0 || static_cast<size_t>(id) >= st->strings.size())
    return nullptr;
  return st->strings[id].c_str();
}

int64_t nerrf_store_num_segments(const nerrf_store_t *st) {
  return st ? static_cast<int64_t>(st->segments.size()) : -1;
}

int64_t nerrf_store_delta_rows(const nerrf_store_t *st) {
  return st ? static_cast<int64_t>(st->delta.size()) : -1;
}

int64_t nerrf_store_total_rows(const nerrf_store_t *st) {
  if (!st) return -1;
  int64_t total = static_cast<int64_t>(st->delta.size());
  for (const auto &s : st->segments) total += s.count;
  return total;
}

}  // extern "C"
