from nerrf_tpu.planner.device_mcts import DeviceMCTS
from nerrf_tpu.planner.domain import UndoAction, UndoDomain, UndoPlan, ActionKind
from nerrf_tpu.planner.mcts import MCTSConfig, MCTSPlanner


def make_planner(domain, value, cfg: MCTSConfig, kind: str = "auto"):
    """One constructor for both planner families.

    ``kind='host'`` → batched-leaf :class:`MCTSPlanner` (``value`` used as
    the batch evaluator); ``kind='device'`` → single-program
    :class:`DeviceMCTS`, handed the value net as the pure
    ``(value.apply_fn, value.params)`` pair so the weights ride the
    compiled search's runtime arguments — embedding a params-closed
    callable would recompile per incident and forfeit the program cache.
    ``value=None`` falls back to the heuristic either way.

    ``kind='auto'`` (default) picks ``device`` on EVERY working backend,
    CPU included: MTTR is planner-bound (m1 recovery artifact: plan time
    dominates), and the single-XLA-program search beats the Python host
    loop even without an accelerator — measured 11,583 vs 2,766
    rollouts/s on the CPU backend (BENCH_r03), i.e. the compiled search
    is the right KPI path everywhere, not a chip-only opt-in.  The host
    planner remains for explicit comparison runs and as the fallback when
    the device program cannot be built — jax compiles lazily, so auto
    forces the compile via ``warmup()`` INSIDE the guard; construction
    alone succeeding proves nothing.  (Hang protection against a wedged
    accelerator tunnel is the entry points' job: every CLI/bench path
    runs ``ensure_backend_or_cpu`` before any jax op, so by the time a
    planner is built the in-process backend has already answered a real
    compile round-trip.)"""
    if kind == "auto":
        try:
            planner = DeviceMCTS(
                domain, cfg,
                value_apply=value.apply_fn if value else None,
                value_params=value.params if value else None)
            planner.warmup()  # the real compile — the failure we guard
            return planner
        except Exception as e:  # noqa: BLE001 — planning must degrade, not die
            import sys

            print(f"[planner] device planner unavailable "
                  f"({type(e).__name__}: {e}); using host search",
                  file=sys.stderr, flush=True)
            kind = "host"
    if kind == "device":
        return DeviceMCTS(
            domain, cfg,
            value_apply=value.apply_fn if value else None,
            value_params=value.params if value else None)
    if kind != "host":
        raise ValueError(f"unknown planner kind {kind!r}")
    return MCTSPlanner(domain, value, cfg)


__all__ = [
    "make_planner",
    "UndoAction",
    "UndoDomain",
    "UndoPlan",
    "ActionKind",
    "MCTSConfig",
    "MCTSPlanner",
    "DeviceMCTS",
]
