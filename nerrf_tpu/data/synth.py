"""Synthetic trace corpus generator.

The reference ships only two tiny captured traces (88 and 149 events,
`benchmarks/m0,m1/results/*_trace.jsonl`) and *specifies* a "100 h benign +
1 h labelled attack" training corpus that was never built
(`/root/reference/ROADMAP.md:50`, `README.md:87,103`).  This module is that
corpus's generator: a benign multi-service workload interleaved with a
LockBit-style five-phase attack whose structure follows the reference
simulator (`benchmarks/m1/scripts/sim_lockbit_m1.py`: recon → seed → chunked
encrypt+rename at a rate limit → ransom note → idle) and threat model
(`docs/content/docs/architecture.mdx:96-120`).

Everything is generated at syscall granularity (the ~25k-event density the
docs project for real eBPF capture, `threat-model.mdx:121-137`), with exact
per-event labels — which the reference's window-level ground truth cannot
provide — plus the window-level `GroundTruth` for format parity.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from nerrf_tpu.data.loaders import GroundTruth, Trace
from nerrf_tpu.schema.events import EventArrays, InodeTable, OpenFlags, StringTable, Syscall

_NS = 1_000_000_000


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Knobs for one simulated run.  Defaults approximate the reference M1
    scale (45-50 files of 2-5 MB, ~2 MB/s encrypt rate — sim_lockbit_m1.py:15-22)
    but at syscall granularity."""

    duration_sec: float = 300.0
    attack: bool = True
    attack_start_sec: float = 120.0
    num_target_files: int = 45
    min_file_bytes: int = 2 * 1024 * 1024
    max_file_bytes: int = 5 * 1024 * 1024
    encrypt_rate_bps: float = 2.0 * 1024 * 1024
    chunk_bytes: int = 256 * 1024
    target_dir: str = "/app/uploads"
    ransom_ext: str = ".lockbit3"
    # Benign workload intensity: mean syscall events per second across services.
    benign_rate_hz: float = 60.0
    seed: int = 0
    # Distribution-shift knob (the quality plane's drift-injection bench
    # leg): 0.0 = the historical generator, bit-identical traces.  d > 0
    # shifts the BENIGN population the way a real deployment drifts
    # without a single attack changing — event rate scales by (1 + d)
    # (denser windows: the node/edge-count distributions walk up the
    # bucket rungs) and the service mix interpolates toward an
    # IO-heavy profile (_DRIFT_SERVICE_WEIGHTS: backup/database-dominated
    # instead of web-dominated), moving the event-type mix and the score
    # distribution the reference profile was calibrated against.  Labels
    # and the attack stream are untouched: drift is a property of the
    # traffic, not of the threat.
    drift: float = 0.0
    # Adversarial/hard-negative scenario (VERDICT r1 item 5 — the quality
    # gates mean little if the attack is linearly separable):
    #   "standard"            — the default five-phase attack
    #   "benign-mass-rename"  — NO attack; a backup archive job bulk-renames
    #                           every target file (.dat → .dat.bak) with
    #                           heavy reads/writes: the structural shape of
    #                           ransomware with benign intent (FP-undo probe)
    #   "slow-drip"           — attack spread across ~80% of the trace, one
    #                           file at a time, aggregate rate far below any
    #                           rate-limit detector
    #   "benign-comm"         — attack runs under the SAME pid+comm as the
    #                           benign python3 app worker, so identity
    #                           features carry zero signal
    #   "multi-process"       — attack sharded over 4 interleaved worker
    #                           pids, each encrypting a subset concurrently
    #
    # r4 stealth scenarios, each aimed at a specific blind spot of the
    # indicator heuristic (VERDICT r3 item 3 — build an eval the heuristic
    # *fails*; indicator set: threat-model.mdx:176-189):
    #   "inplace-stealth"     — encrypt in place: O_RDWR chunked read/write
    #                           sweeps, NO rename, extensions kept, recovery
    #                           note named nothing like README.  Kills the
    #                           suspicious-extension rule, the write→rename
    #                           motif and the note-name rule at once.
    #   "partial-encrypt"     — in-place encryption of only the head ~12% of
    #                           each file (enough to destroy most formats):
    #                           stays under any bytes-moved / rate trigger.
    #   "interleaved-backup"  — in-place encryption racing the benign backup
    #                           sweep over the SAME files; the backup then
    #                           archives ciphertext and renames victims to
    #                           .bak names no attack event ever wrote.
    #   "exfil-encrypt"       — staged: full read-only exfil sweep to a /tmp
    #                           staging file, a quiet dwell, then a partial
    #                           in-place encrypt pass.
    #   "benign-atomic-rewrite" — NO attack; an indexer rewrites every file
    #                           via the atomic-save idiom (write .tmp, rename
    #                           .tmp → file): the write→rename motif fires on
    #                           every file, so the heuristic mass-flags a
    #                           benign maintenance job (FP-undo probe).
    #
    # Incident-response families (the respond tier's scenario corpus,
    # nerrf_tpu/respond/scenarios.py — these exercise the detect→plan→
    # verify loop on damage that is NOT encryption):
    #   "cron-persistence"    — the attacker trojanizes the host agent's
    #                           plugin binaries via the atomic-replace idiom
    #                           (write payload tmp, rename onto the plugin)
    #                           and drops a hidden cron entry for boot
    #                           persistence; no victim data files touched.
    #   "log-tamper"          — anti-forensics: every application log is
    #                           scrubbed by rewriting it through a tmp copy
    #                           (same size, incriminating entries gone) and
    #                           renaming the copy over the original.
    scenario: str = "standard"


# Scenarios with no attack stream at all (hard-negative probes).
BENIGN_SCENARIOS = frozenset({"benign-mass-rename", "benign-atomic-rewrite"})
# Attack variants that never rename victims and keep extensions: invisible
# to every indicator the heuristic implements.
STEALTH_SCENARIOS = frozenset(
    {"inplace-stealth", "partial-encrypt", "interleaved-backup",
     "exfil-encrypt"})

# Incident-response families: damage that is persistence/anti-forensics
# rather than encryption.  Kept OUT of ATTACK_VARIANTS on purpose — the
# hard-corpus slot arithmetic in make_corpus (0.49/len) is frozen so the
# historical corpus mix stays bit-identical; the respond tier's scenario
# schedules (nerrf_tpu/respond/scenarios.py) draw these explicitly.
PERSISTENCE_VARIANTS = ("cron-persistence", "log-tamper")

# Where the persistence families do their damage (shared with the on-disk
# incident simulators in respond/scenarios.py so trace paths and disk paths
# agree).
PLUGIN_DIR = "/usr/lib/sysagent"
CRON_DROP = "/etc/cron.d/.sysupdate"
TAMPER_LOG_DIR = "/var/log/app"


_BENIGN_SERVICES = (
    # (comm, uid, weight) — a web stack with monitoring and backups, so benign
    # traffic includes /proc reads, renames, and python3 (non-separable comm).
    ("nginx", 33, 0.30),
    ("postgres", 70, 0.20),
    ("python3", 1000, 0.25),
    ("node-exporter", 65534, 0.10),
    ("backup-agent", 0, 0.10),
    ("logrotate", 0, 0.05),
)

_DOC_PREFIXES = ("report", "proposal", "analysis", "budget", "customer", "invoice")

# The drifted service mix (same service set, IO-heavy weighting): what a
# deployment looks like after a backup/ETL rollout the model never saw.
# SimConfig.drift interpolates the _BENIGN_SERVICES weights toward this.
_DRIFT_SERVICE_WEIGHTS = (0.05, 0.30, 0.10, 0.05, 0.40, 0.10)


def _target_file_names(rng: np.random.Generator, n: int) -> List[str]:
    return [
        f"{rng.choice(_DOC_PREFIXES)}_{rng.integers(2020, 2027)}_{i:03d}.dat"
        for i in range(n)
    ]


class _Emitter:
    def __init__(self):
        self.records: list[dict] = []
        self.labels: list[float] = []
        self.victims: list[bool] = []  # content-destroying attack events

    def emit(
        self,
        ts_ns: int,
        syscall: Syscall,
        path: str,
        *,
        pid: int,
        comm: str,
        attack: bool,
        new_path: str = "",
        nbytes: int = 0,
        flags: int = 0,
        uid: int = 0,
        ret_val: int = 0,
        victim: bool = False,
    ) -> None:
        # inode is assigned later, in TIME order (simulate_trace): the benign
        # and attack streams are emitted sequentially, so assigning here
        # would let a post-rename benign open of the old name alias the
        # renamed file's inode (emission order ≠ causal order)
        self.records.append(
            {
                "ts_ns": ts_ns,
                "pid": pid,
                "tid": pid,
                "comm": comm,
                "syscall": syscall,
                "path": path,
                "new_path": new_path,
                "flags": flags,
                "ret_val": ret_val,
                "bytes": nbytes,
                "inode": 0,
                "uid": uid,
            }
        )
        self.labels.append(1.0 if attack else 0.0)
        self.victims.append(bool(victim and attack))


def _emit_benign(em: _Emitter, cfg: SimConfig, rng: np.random.Generator, t0: int) -> None:
    # drift == 0 keeps the arithmetic AND the rng call sequence of the
    # historical generator, so existing seeds reproduce bit-identically.
    # The knob's whole domain is [0, 1] — clamp ONCE so the rate scale
    # and the mix interpolation can never disagree about an out-of-range
    # value (a negative raw drift would hand poisson a negative lambda)
    d = min(max(float(cfg.drift), 0.0), 1.0)
    n = rng.poisson(cfg.benign_rate_hz * (1.0 + d) * cfg.duration_sec)
    ts = np.sort(rng.uniform(0, cfg.duration_sec, n))
    weights = np.array([w for _, _, w in _BENIGN_SERVICES])
    if d:
        weights = (1.0 - d) * weights + d * np.asarray(_DRIFT_SERVICE_WEIGHTS)
    svc = rng.choice(len(_BENIGN_SERVICES), size=n, p=weights / weights.sum())
    pids = {i: 200 + i for i in range(len(_BENIGN_SERVICES))}
    log_seq = 0
    for i in range(n):
        comm, uid, _ = _BENIGN_SERVICES[svc[i]]
        pid = pids[int(svc[i])]
        t = t0 + int(ts[i] * _NS)
        r = rng.random()
        if comm == "nginx":
            if r < 0.5:
                em.emit(t, Syscall.OPENAT, f"/var/www/static/page_{rng.integers(50)}.html",
                        pid=pid, comm=comm, uid=uid, attack=False,
                        flags=int(OpenFlags.O_RDONLY))
            else:
                em.emit(t, Syscall.WRITE, "/var/log/nginx/access.log", pid=pid,
                        comm=comm, uid=uid, attack=False, nbytes=int(rng.integers(80, 400)))
        elif comm == "postgres":
            if r < 0.6:
                db = f"/var/lib/pg/base/{rng.integers(20)}.db"
                if r < 0.12:
                    # databases legitimately open data files O_RDWR — keeps
                    # the access mode informative but not attack-sufficient
                    em.emit(t, Syscall.OPENAT, db, pid=pid, comm=comm,
                            uid=uid, attack=False,
                            flags=int(OpenFlags.O_RDWR))
                em.emit(t, Syscall.WRITE, db,
                        pid=pid, comm=comm, uid=uid, attack=False,
                        nbytes=int(rng.integers(512, 8192)))
            elif r < 0.8:
                em.emit(t, Syscall.READ, f"/var/lib/pg/base/{rng.integers(20)}.db",
                        pid=pid, comm=comm, uid=uid, attack=False,
                        nbytes=int(rng.integers(512, 8192)))
            else:
                em.emit(t, Syscall.FSYNC, "/var/lib/pg/wal/000001.log", pid=pid,
                        comm=comm, uid=uid, attack=False)
        elif comm == "python3":
            # An app worker that legitimately touches the target directory.
            fname = f"{cfg.target_dir}/{rng.choice(_DOC_PREFIXES)}_{rng.integers(2020, 2027)}_{rng.integers(cfg.num_target_files):03d}.dat"
            if r < 0.45:
                em.emit(t, Syscall.OPENAT, fname, pid=pid, comm=comm, uid=uid,
                        attack=False, flags=int(OpenFlags.O_RDONLY))
            elif r < 0.75:
                em.emit(t, Syscall.READ, fname, pid=pid, comm=comm, uid=uid,
                        attack=False, nbytes=int(rng.integers(1024, 65536)))
            else:
                em.emit(t, Syscall.WRITE, f"{cfg.target_dir}/.tmp_upload_{rng.integers(9)}",
                        pid=pid, comm=comm, uid=uid, attack=False,
                        nbytes=int(rng.integers(1024, 262144)))
        elif comm == "node-exporter":
            proc = rng.choice(["/proc/stat", "/proc/meminfo", "/proc/net/dev", "/proc/loadavg"])
            em.emit(t, Syscall.OPENAT, str(proc), pid=pid, comm=comm, uid=uid,
                    attack=False, flags=int(OpenFlags.O_RDONLY))
        elif comm == "backup-agent":
            if r < 0.7:
                em.emit(t, Syscall.READ,
                        f"{cfg.target_dir}/{rng.choice(_DOC_PREFIXES)}_{rng.integers(2020, 2027)}_{rng.integers(cfg.num_target_files):03d}.dat",
                        pid=pid, comm=comm, uid=uid, attack=False,
                        nbytes=int(rng.integers(65536, 1 << 20)))
            else:
                em.emit(t, Syscall.WRITE, f"/backup/snap_{rng.integers(10)}.bak",
                        pid=pid, comm=comm, uid=uid, attack=False,
                        nbytes=int(rng.integers(65536, 1 << 20)))
        else:  # logrotate: benign rename traffic
            idx = log_seq % 5
            log_seq += 1
            em.emit(t, Syscall.RENAME, f"/var/log/app/service_{idx}.log", pid=pid,
                    comm=comm, uid=uid, attack=False,
                    new_path=f"/var/log/app/service_{idx}.log.1")


def _emit_benign_mass_rename(em: _Emitter, cfg: SimConfig,
                             rng: np.random.Generator, t0: int) -> None:
    """Hard negative: a backup archive job sweeps the target directory —
    open/read every file, write an archive copy, rename to .dat.bak — in one
    tight burst.  Mass renames + extension change + high IO in the attack's
    own directory, but benign (uid 0, no recon, reads-then-copies instead of
    in-place overwrite).  This is what the <5% FP-undo KPI is measured on."""
    pid = 208
    comm = "backup-agent"
    t = t0 + int(cfg.attack_start_sec * _NS)
    names = _target_file_names(rng, cfg.num_target_files)
    for nm in names:
        src = f"{cfg.target_dir}/{nm}"
        em.emit(t, Syscall.OPENAT, src, pid=pid, comm=comm, attack=False,
                flags=int(OpenFlags.O_RDONLY))
        t += int(rng.uniform(1, 5) * 1e6)
        size = int(rng.integers(cfg.min_file_bytes, cfg.max_file_bytes))
        for _ in range(max(1, size // cfg.chunk_bytes)):
            em.emit(t, Syscall.READ, src, pid=pid, comm=comm, attack=False,
                    nbytes=cfg.chunk_bytes)
            t += int(rng.uniform(1, 3) * 1e6)
            em.emit(t, Syscall.WRITE, f"/backup/archive/{nm}.gz", pid=pid,
                    comm=comm, attack=False, nbytes=cfg.chunk_bytes // 2)
            t += int(rng.uniform(1, 3) * 1e6)
        em.emit(t, Syscall.RENAME, src, pid=pid, comm=comm, attack=False,
                new_path=src + ".bak")
        t += int(rng.uniform(2, 10) * 1e6)


def _emit_attack(em: _Emitter, cfg: SimConfig, rng: np.random.Generator, t0: int) -> tuple[int, int]:
    """Five-phase LockBit-style attack; returns (start_ns, end_ns)."""
    if cfg.scenario == "multi-process":
        return _emit_attack_multiprocess(em, cfg, rng, t0)
    if cfg.scenario in STEALTH_SCENARIOS:
        return _emit_attack_stealth(em, cfg, rng, t0)
    if cfg.scenario == "cron-persistence":
        return _emit_attack_cron_persistence(em, cfg, rng, t0)
    if cfg.scenario == "log-tamper":
        return _emit_attack_log_tamper(em, cfg, rng, t0)
    # benign-comm: reuse the benign python3 app worker's identity (pid 202,
    # the pids[] entry _emit_benign uses), so comm/pid features are useless
    pid = 202 if cfg.scenario == "benign-comm" else 4567
    comm = "python3"
    t = t0 + int(cfg.attack_start_sec * _NS)
    start = t
    # slow-drip: spread file encryptions across most of the remaining trace
    drip_gap_ns = 0
    if cfg.scenario == "slow-drip":
        window = (cfg.duration_sec - cfg.attack_start_sec) * 0.85 * _NS
        drip_gap_ns = int(max(0.0, window) / max(cfg.num_target_files, 1))

    def step(lo_ms=2, hi_ms=40):
        nonlocal t
        t += int(rng.uniform(lo_ms, hi_ms) * 1e6)
        return t

    # P1 recon: burst of /proc + system enumeration (threat-model.mdx "Burst of /proc reads")
    for p in ("/proc/self/status", "/proc/net/tcp", "/etc/passwd", "/proc/diskstats",
              "/proc/mounts", "/proc/stat"):
        for _ in range(int(rng.integers(2, 6))):
            em.emit(step(), Syscall.OPENAT, p, pid=pid, comm=comm, attack=True,
                    flags=int(OpenFlags.O_RDONLY))
            em.emit(step(), Syscall.READ, p, pid=pid, comm=comm, attack=True,
                    nbytes=int(rng.integers(512, 4096)))

    # P2 target discovery
    em.emit(step(), Syscall.OPENAT, cfg.target_dir, pid=pid, comm=comm, attack=True,
            flags=int(OpenFlags.O_RDONLY))
    names = _target_file_names(rng, cfg.num_target_files)
    for nm in names:
        em.emit(step(1, 4), Syscall.STAT, f"{cfg.target_dir}/{nm}", pid=pid,
                comm=comm, attack=True)

    # P3 encrypt loop: per file open→read/write chunks→rename→unlink, rate-limited
    for nm in names:
        src = f"{cfg.target_dir}/{nm}"
        dst = src[: -len(".dat")] + cfg.ransom_ext if src.endswith(".dat") else src + cfg.ransom_ext
        size = int(rng.integers(cfg.min_file_bytes, cfg.max_file_bytes))
        em.emit(step(), Syscall.OPENAT, src, pid=pid, comm=comm, attack=True,
                flags=int(OpenFlags.O_RDWR))
        nchunks = max(1, size // cfg.chunk_bytes)
        for _ in range(nchunks):
            em.emit(step(1, 3), Syscall.READ, src, pid=pid, comm=comm, attack=True,
                    nbytes=cfg.chunk_bytes)
            em.emit(step(1, 3), Syscall.WRITE, src, pid=pid, comm=comm, attack=True,
                    nbytes=cfg.chunk_bytes, victim=True)
            # rate limit: advance wall clock to respect encrypt_rate_bps
            t += int(cfg.chunk_bytes / cfg.encrypt_rate_bps * 1e9)
        # in-place rename to the ransom extension; the inode survives under
        # dst (no unlink — neither the reference simulator's rename-by-rewrite
        # endstate nor real LockBit leaves a deleted old name behind)
        em.emit(step(), Syscall.RENAME, src, pid=pid, comm=comm, attack=True,
                new_path=dst, victim=True)
        t += drip_gap_ns  # slow-drip: long quiet gap before the next file

    # P4 ransom note
    note = f"{cfg.target_dir}/README_LOCKBIT.txt"
    em.emit(step(), Syscall.OPENAT, note, pid=pid, comm=comm, attack=True,
            flags=int(OpenFlags.O_WRONLY))
    em.emit(step(), Syscall.WRITE, note, pid=pid, comm=comm, attack=True, nbytes=1337)
    # P5 idle (no events)
    return start, t


def _emit_attack_multiprocess(em: _Emitter, cfg: SimConfig,
                              rng: np.random.Generator,
                              t0: int) -> tuple[int, int]:
    """The same five phases sharded over 4 worker pids whose encrypt loops
    run concurrently — per-pid rates look 4× lower and file ordering
    interleaves, defeating single-process burst heuristics."""
    comm = "python3"
    leader = 4567
    workers = [4567, 4568, 4569, 4570]
    t = t0 + int(cfg.attack_start_sec * _NS)
    start = t

    # leader does recon + discovery (as in the single-process path)
    for p in ("/proc/self/status", "/proc/net/tcp", "/etc/passwd"):
        for _ in range(int(rng.integers(2, 5))):
            t += int(rng.uniform(2, 30) * 1e6)
            em.emit(t, Syscall.OPENAT, p, pid=leader, comm=comm, attack=True,
                    flags=int(OpenFlags.O_RDONLY))
    names = _target_file_names(rng, cfg.num_target_files)
    for nm in names:
        t += int(rng.uniform(1, 4) * 1e6)
        em.emit(t, Syscall.STAT, f"{cfg.target_dir}/{nm}", pid=leader,
                comm=comm, attack=True)

    # workers encrypt interleaved shards on independent clocks
    cursors = {w: t + int(rng.uniform(5, 50) * 1e6) for w in workers}
    for i, nm in enumerate(names):
        w = workers[i % len(workers)]
        tw = cursors[w]
        src = f"{cfg.target_dir}/{nm}"
        dst = (src[: -len(".dat")] + cfg.ransom_ext
               if src.endswith(".dat") else src + cfg.ransom_ext)
        size = int(rng.integers(cfg.min_file_bytes, cfg.max_file_bytes))
        em.emit(tw, Syscall.OPENAT, src, pid=w, comm=comm, attack=True,
                flags=int(OpenFlags.O_RDWR))
        for _ in range(max(1, size // cfg.chunk_bytes)):
            tw += int(rng.uniform(1, 3) * 1e6)
            em.emit(tw, Syscall.READ, src, pid=w, comm=comm, attack=True,
                    nbytes=cfg.chunk_bytes)
            tw += int(rng.uniform(1, 3) * 1e6)
            em.emit(tw, Syscall.WRITE, src, pid=w, comm=comm, attack=True,
                    nbytes=cfg.chunk_bytes, victim=True)
            # each worker honors the rate limit independently (aggregate is
            # 4× — fast attacks are the easy case; interleaving is the test)
            tw += int(cfg.chunk_bytes / cfg.encrypt_rate_bps * 1e9)
        tw += int(rng.uniform(2, 10) * 1e6)
        em.emit(tw, Syscall.RENAME, src, pid=w, comm=comm, attack=True,
                new_path=dst, victim=True)
        cursors[w] = tw
    end = max(cursors.values())
    note = f"{cfg.target_dir}/README_LOCKBIT.txt"
    em.emit(end + int(1e7), Syscall.OPENAT, note, pid=leader, comm=comm,
            attack=True, flags=int(OpenFlags.O_WRONLY))
    em.emit(end + int(2e7), Syscall.WRITE, note, pid=leader, comm=comm,
            attack=True, nbytes=1337)
    return start, end + int(2e7)


def _emit_attack_stealth(em: _Emitter, cfg: SimConfig,
                         rng: np.random.Generator, t0: int) -> tuple[int, int]:
    """The r4 stealth family: no rename, extensions kept, no README-style
    note — every indicator the closed-form heuristic keys on
    (threat-model.mdx:176-189) is absent, so detection must come from the
    access *structure*: one process O_RDWR-sweeping a directory with paired
    read/write chunks in place, after a stat-discovery pass.

    Variants (SimConfig.scenario):
      inplace-stealth     full-file in-place encryption + an innocuously
                          named recovery note
      partial-encrypt     only the head ~12% of each file is overwritten
                          (headers gone ⇒ file destroyed; bytes moved stay
                          far below any volume trigger); no note
      interleaved-backup  the benign backup sweep trails the encryptor over
                          the same files, archiving ciphertext and renaming
                          victims to .bak — the only renames in the trace
                          are benign
      exfil-encrypt       staged: read-only exfil of every file into a /tmp
                          staging blob, a quiet dwell, then partial in-place
                          encryption

    The attacker runs as comm "python3" (the benign app worker's comm, a
    compromised-app story) under its own pid, so neither comm nor open
    flags alone can carry the class — postgres legitimately opens O_RDWR
    (_emit_benign) and python3 is the densest benign identity.
    """
    scenario = cfg.scenario
    pid, comm = 4821, "python3"
    t = t0 + int(cfg.attack_start_sec * _NS)
    start = t

    def step(lo_ms=2, hi_ms=40):
        nonlocal t
        t += int(rng.uniform(lo_ms, hi_ms) * 1e6)
        return t

    # Light recon: two /proc touches — deliberately below the heuristic's
    # burst weighting; the model's process head may still use it.
    for p in ("/proc/self/status", "/proc/mounts"):
        em.emit(step(), Syscall.OPENAT, p, pid=pid, comm=comm, attack=True,
                flags=int(OpenFlags.O_RDONLY))
        em.emit(step(), Syscall.READ, p, pid=pid, comm=comm, attack=True,
                nbytes=int(rng.integers(512, 2048)))

    # Target discovery (unavoidable for any file-targeting payload).
    names = _target_file_names(rng, cfg.num_target_files)
    for nm in names:
        em.emit(step(1, 4), Syscall.STAT, f"{cfg.target_dir}/{nm}", pid=pid,
                comm=comm, attack=True)

    sizes = {nm: int(rng.integers(cfg.min_file_bytes, cfg.max_file_bytes))
             for nm in names}

    if scenario == "exfil-encrypt":
        # Stage A: full read-only sweep, compressing into one staging blob.
        stage = "/tmp/.sess_cache.bin"
        for nm in names:
            src = f"{cfg.target_dir}/{nm}"
            em.emit(step(1, 5), Syscall.OPENAT, src, pid=pid, comm=comm,
                    attack=True, flags=int(OpenFlags.O_RDONLY))
            for _ in range(max(1, sizes[nm] // cfg.chunk_bytes)):
                em.emit(step(1, 3), Syscall.READ, src, pid=pid, comm=comm,
                        attack=True, nbytes=cfg.chunk_bytes)
                em.emit(step(1, 3), Syscall.WRITE, stage, pid=pid, comm=comm,
                        attack=True, nbytes=cfg.chunk_bytes // 3)
        # Quiet dwell before the destructive stage (staged campaigns pause
        # between exfil and impact).
        t += int(min(0.15 * cfg.duration_sec, 30.0) * _NS)

    frac = 0.12 if scenario in ("partial-encrypt", "exfil-encrypt") else 1.0
    bk_pid, bk_comm = 208, "backup-agent"
    bk_t = t  # trailing benign sweep's clock (interleaved-backup only)
    for nm in names:
        src = f"{cfg.target_dir}/{nm}"
        em.emit(step(), Syscall.OPENAT, src, pid=pid, comm=comm, attack=True,
                flags=int(OpenFlags.O_RDWR))
        nchunks = max(1, int(sizes[nm] * frac) // cfg.chunk_bytes)
        for _ in range(nchunks):
            em.emit(step(1, 3), Syscall.READ, src, pid=pid, comm=comm,
                    attack=True, nbytes=cfg.chunk_bytes)
            em.emit(step(1, 3), Syscall.WRITE, src, pid=pid, comm=comm,
                    attack=True, nbytes=cfg.chunk_bytes, victim=True)
            t += int(cfg.chunk_bytes / cfg.encrypt_rate_bps * 1e9)
        if scenario == "interleaved-backup":
            # The backup job reaches each file only after the encryptor
            # leaves it (it archives ciphertext), but its event stream — on
            # its own clock — interleaves with the attacker's work on later
            # files.  Its rename is the ONLY rename the trace contains, and
            # it is benign: labels say so, and the victim set follows the
            # inode to the .bak name (simulate_trace).
            bk_t = max(bk_t, t + int(rng.uniform(5, 30) * 1e6))
            em.emit(bk_t, Syscall.OPENAT, src, pid=bk_pid, comm=bk_comm,
                    attack=False, flags=int(OpenFlags.O_RDONLY))
            for _ in range(max(1, sizes[nm] // cfg.chunk_bytes)):
                bk_t += int(rng.uniform(1, 3) * 1e6)
                em.emit(bk_t, Syscall.READ, src, pid=bk_pid, comm=bk_comm,
                        attack=False, nbytes=cfg.chunk_bytes)
                bk_t += int(rng.uniform(1, 3) * 1e6)
                em.emit(bk_t, Syscall.WRITE, f"/backup/archive/{nm}.gz",
                        pid=bk_pid, comm=bk_comm, attack=False,
                        nbytes=cfg.chunk_bytes // 2)
            bk_t += int(rng.uniform(2, 10) * 1e6)
            em.emit(bk_t, Syscall.RENAME, src, pid=bk_pid, comm=bk_comm,
                    attack=False, new_path=src + ".bak")

    end = max(t, bk_t)
    if scenario == "inplace-stealth":
        # A recovery note that matches no indicator: not README*, benign
        # extension.
        note = f"{cfg.target_dir}/how_to_recover.html"
        em.emit(step(), Syscall.OPENAT, note, pid=pid, comm=comm, attack=True,
                flags=int(OpenFlags.O_WRONLY))
        em.emit(step(), Syscall.WRITE, note, pid=pid, comm=comm, attack=True,
                nbytes=2048)
        end = t
    return start, end


def _emit_attack_cron_persistence(em: _Emitter, cfg: SimConfig,
                                  rng: np.random.Generator,
                                  t0: int) -> tuple[int, int]:
    """Persistence family: the attacker trojanizes the host agent's plugin
    binaries via the atomic-replace idiom (write the payload to a dotfile
    tmp, rename it onto the plugin — the write→rename motif, but aimed at
    *code*, not documents) and drops a hidden cron entry for boot
    persistence.  No victim data file is touched and nothing is encrypted:
    the undo plan the respond tier must produce is "restore the trojanized
    binaries from snapshot", and the cron drop is attack residue the
    rollback gate's leaves-behind policy has to account for."""
    pid, comm = 4913, "python3"
    t = t0 + int(cfg.attack_start_sec * _NS)
    start = t

    def step(lo_ms=2, hi_ms=40):
        nonlocal t
        t += int(rng.uniform(lo_ms, hi_ms) * 1e6)
        return t

    # Light recon: privilege + persistence-surface survey.
    for p in ("/proc/self/status", "/etc/passwd", "/proc/mounts"):
        em.emit(step(), Syscall.OPENAT, p, pid=pid, comm=comm, attack=True,
                flags=int(OpenFlags.O_RDONLY))
        em.emit(step(), Syscall.READ, p, pid=pid, comm=comm, attack=True,
                nbytes=int(rng.integers(512, 2048)))

    n = max(4, min(cfg.num_target_files, 12))
    names = [f"{PLUGIN_DIR}/plugin_{i:02d}.bin" for i in range(n)]
    em.emit(step(), Syscall.OPENAT, PLUGIN_DIR, pid=pid, comm=comm,
            attack=True, flags=int(OpenFlags.O_RDONLY))
    for nm in names:
        em.emit(step(1, 4), Syscall.STAT, nm, pid=pid, comm=comm, attack=True)

    for i, nm in enumerate(names):
        tmp = f"{PLUGIN_DIR}/.tmp_{i:02d}.bin"
        size = int(rng.integers(cfg.min_file_bytes, cfg.max_file_bytes))
        em.emit(step(), Syscall.OPENAT, nm, pid=pid, comm=comm, attack=True,
                flags=int(OpenFlags.O_RDONLY))
        for _ in range(max(1, size // cfg.chunk_bytes)):
            em.emit(step(1, 3), Syscall.READ, nm, pid=pid, comm=comm,
                    attack=True, nbytes=cfg.chunk_bytes)
            em.emit(step(1, 3), Syscall.WRITE, tmp, pid=pid, comm=comm,
                    attack=True, nbytes=cfg.chunk_bytes, victim=True)
        # the tmp's inode (already marked victim) is carried onto the plugin
        # name by the rename — the canonical final path is the binary itself
        em.emit(step(), Syscall.RENAME, tmp, pid=pid, comm=comm, attack=True,
                new_path=nm, victim=True)

    # Boot persistence: one small hidden cron entry (attack residue — a
    # path the snapshot manifest has never seen).
    em.emit(step(), Syscall.OPENAT, CRON_DROP, pid=pid, comm=comm,
            attack=True, flags=int(OpenFlags.O_WRONLY))
    em.emit(step(), Syscall.WRITE, CRON_DROP, pid=pid, comm=comm,
            attack=True, nbytes=142)
    return start, t


def _emit_attack_log_tamper(em: _Emitter, cfg: SimConfig,
                            rng: np.random.Generator,
                            t0: int) -> tuple[int, int]:
    """Anti-forensics family: audit logs are scrubbed by rewriting each one
    through a same-size tmp copy (incriminating entries replaced, byte count
    preserved so log-size monitors see nothing) and renaming the copy over
    the original.  No recon burst — the actor is already inside — and the
    touched directory is one benign services write to constantly, so the
    only signal is the write→rename motif on files nothing benign ever
    renames onto."""
    pid, comm = 5102, "python3"
    t = t0 + int(cfg.attack_start_sec * _NS)
    start = t

    def step(lo_ms=2, hi_ms=40):
        nonlocal t
        t += int(rng.uniform(lo_ms, hi_ms) * 1e6)
        return t

    n = max(3, min(cfg.num_target_files, 10))
    logs = [f"{TAMPER_LOG_DIR}/audit_{i:02d}.log" for i in range(n)]
    for i, lg in enumerate(logs):
        em.emit(step(1, 4), Syscall.STAT, lg, pid=pid, comm=comm, attack=True)
        tmp = f"{TAMPER_LOG_DIR}/.audit_{i:02d}.swp"
        size = int(rng.integers(cfg.min_file_bytes, cfg.max_file_bytes))
        em.emit(step(), Syscall.OPENAT, lg, pid=pid, comm=comm, attack=True,
                flags=int(OpenFlags.O_RDONLY))
        for _ in range(max(1, size // cfg.chunk_bytes)):
            em.emit(step(1, 3), Syscall.READ, lg, pid=pid, comm=comm,
                    attack=True, nbytes=cfg.chunk_bytes)
            # same-size scrub copy: bytes out == bytes in
            em.emit(step(1, 3), Syscall.WRITE, tmp, pid=pid, comm=comm,
                    attack=True, nbytes=cfg.chunk_bytes, victim=True)
        em.emit(step(), Syscall.RENAME, tmp, pid=pid, comm=comm, attack=True,
                new_path=lg, victim=True)
        t += int(rng.uniform(5, 20) * 1e6)
    return start, t


def _emit_benign_atomic_rewrite(em: _Emitter, cfg: SimConfig,
                                rng: np.random.Generator, t0: int) -> None:
    """Hard negative: an indexer refreshes every target file via the
    atomic-save idiom — read src, write ``.tmp_reindex_NNN``, rename the
    tmp over src.  The write→rename-by-the-same-process motif fires on
    EVERY file (the tmp inode is written, then carried onto the target name
    by the rename), so the indicator heuristic mass-flags a routine
    maintenance job; labels mark all of it benign.  This is the FP-undo
    probe aimed at the motif rule specifically, the counterpart of
    benign-mass-rename (which targets extension/rename-volume rules)."""
    pid, comm = 209, "python3"
    t = t0 + int(cfg.attack_start_sec * _NS)
    names = _target_file_names(rng, cfg.num_target_files)
    for i, nm in enumerate(names):
        src = f"{cfg.target_dir}/{nm}"
        tmp = f"{cfg.target_dir}/.tmp_reindex_{i:03d}"
        em.emit(t, Syscall.OPENAT, src, pid=pid, comm=comm, attack=False,
                flags=int(OpenFlags.O_RDONLY))
        size = int(rng.integers(cfg.min_file_bytes, cfg.max_file_bytes))
        for _ in range(max(1, size // cfg.chunk_bytes)):
            t += int(rng.uniform(1, 3) * 1e6)
            em.emit(t, Syscall.READ, src, pid=pid, comm=comm, attack=False,
                    nbytes=cfg.chunk_bytes)
            t += int(rng.uniform(1, 3) * 1e6)
            em.emit(t, Syscall.WRITE, tmp, pid=pid, comm=comm, attack=False,
                    nbytes=cfg.chunk_bytes)
        t += int(rng.uniform(2, 8) * 1e6)
        em.emit(t, Syscall.RENAME, tmp, pid=pid, comm=comm, attack=False,
                new_path=src)
        t += int(rng.uniform(5, 20) * 1e6)


def simulate_trace(cfg: SimConfig, name: str = "") -> Trace:
    """Generate one labelled trace."""
    rng = np.random.default_rng(cfg.seed)
    strings = StringTable()
    em = _Emitter()
    t0 = 1_700_000_000 * _NS + int(cfg.seed) * 10_000 * _NS
    _emit_benign(em, cfg, rng, t0)
    gt = None
    if cfg.scenario == "benign-mass-rename":
        # hard negative: structurally attack-like, labelled benign throughout
        _emit_benign_mass_rename(em, cfg, rng, t0)
    elif cfg.scenario == "benign-atomic-rewrite":
        _emit_benign_atomic_rewrite(em, cfg, rng, t0)
    elif cfg.attack:
        start, end = _emit_attack(em, cfg, rng, t0)
        family, tgt = {
            # the persistence families damage fixed system paths, not the
            # configurable document directory
            "cron-persistence": ("CronPersistenceSynthetic", PLUGIN_DIR),
            "log-tamper": ("LogTamperSynthetic", TAMPER_LOG_DIR),
        }.get(cfg.scenario, ("LockBitSynthetic", cfg.target_dir))
        gt = GroundTruth(
            start_ns=start,
            end_ns=end,
            attack_family=family,
            target_path=tgt,
            platform="synthetic",
            scale=f"{cfg.num_target_files}f",
        )
    # sort by time FIRST, then assign inodes walking causally: a rename
    # invalidates its source name, so later opens of it get a fresh inode
    order = sorted(range(len(em.records)), key=lambda i: em.records[i]["ts_ns"])
    inodes = InodeTable()
    recs = []
    victim_inos: set = set()
    ino_final: dict = {}  # inode → canonical final path (rename dest wins)
    for i in order:
        r = em.records[i]
        r["inode"] = (
            inodes.carry_rename(r["path"], r["new_path"])
            if r["new_path"] else inodes.get(r["path"])
        )
        if r["inode"]:
            ino_final[r["inode"]] = r["new_path"] or r["path"]
            if em.victims[i]:
                victim_inos.add(r["inode"])
        recs.append(r)
    events = EventArrays.from_records(recs, strings)
    labels = np.asarray([em.labels[i] for i in order], np.float32)
    return Trace(
        events=events,
        strings=strings,
        ground_truth=gt,
        labels=labels,
        name=name or f"synth-seed{cfg.seed}",
        # exact file-level truth, following each victim inode to its FINAL
        # name (a benign rename may move it — interleaved-backup) — this is
        # the same canonicalization rule pipeline._inode_to_path applies, so
        # detection keys and ground-truth keys cannot drift
        victim_paths=frozenset(ino_final[i] for i in victim_inos),
    )


# The adversarial attack variants a hard-scenario corpus draws from, and
# the fraction of attack traces they collectively take (split evenly);
# mirrored by train/corpus.py for the sharded 100 h corpus.
ATTACK_VARIANTS = ("slow-drip", "benign-comm", "multi-process",
                   "inplace-stealth", "partial-encrypt",
                   "interleaved-backup", "exfil-encrypt")


def make_corpus(
    n_traces: int,
    attack_fraction: float = 0.5,
    base_seed: int = 0,
    duration_sec: float = 240.0,
    num_target_files: int | tuple[int, int] = 12,
    benign_rate_hz: float | tuple[float, float] = 40.0,
    hard_scenarios: bool = False,
    exclude_scenarios: frozenset = frozenset(),
) -> List[Trace]:
    """A corpus of independent runs (the ROADMAP.md:50 corpus, scaled by args).

    `num_target_files` / `benign_rate_hz` may be (lo, hi) ranges, drawn per
    trace, so corpus traces vary structurally and not just by sim seed.

    ``hard_scenarios`` draws ~49% of attack traces from ATTACK_VARIANTS and
    ~20% of benign traces from the two hard negatives, mirroring the
    sharded corpus mix (train/corpus.py) — the in-memory path for training
    a deployable detector (`nerrf train-detector`, the adversarial eval's
    fresh-model leg).  Off by default: unit tests assume the standard
    scenario's structure.

    ``exclude_scenarios`` removes families from the variant pool — the
    leave-one-scenario-out generalization eval's training corpora
    (VERDICT r4 weak #3: seeds were held out, generators were not; only a
    corpus that has never seen a family's mechanics can measure
    out-of-distribution detection of it)."""
    out = []
    for i in range(n_traces):
        # Bresenham-spread attack traces through the corpus so any contiguous
        # train/eval split keeps both classes
        attack = round((i + 1) * attack_fraction) - round(i * attack_fraction) == 1
        rng = np.random.default_rng(base_seed + i)
        files = (
            int(rng.integers(num_target_files[0], num_target_files[1]))
            if isinstance(num_target_files, tuple) else num_target_files
        )
        rate = (
            float(rng.uniform(benign_rate_hz[0], benign_rate_hz[1]))
            if isinstance(benign_rate_hz, tuple) else benign_rate_hz
        )
        scenario = "standard"
        if hard_scenarios:
            u = rng.random()
            if attack:
                # the excluded family's probability mass folds into
                # "standard" rather than re-normalizing over the survivors,
                # keeping the remaining variants' absolute rates unchanged
                slot = 0.49 / len(ATTACK_VARIANTS)
                idx = int(u // slot)
                if (idx < len(ATTACK_VARIANTS)
                        and ATTACK_VARIANTS[idx] not in exclude_scenarios):
                    scenario = ATTACK_VARIANTS[idx]
            elif u < 0.1 and "benign-mass-rename" not in exclude_scenarios:
                scenario = "benign-mass-rename"
            elif 0.1 <= u < 0.2 and "benign-atomic-rewrite" not in exclude_scenarios:
                scenario = "benign-atomic-rewrite"
        assert scenario not in exclude_scenarios
        cfg = SimConfig(
            duration_sec=duration_sec,
            attack=attack,
            attack_start_sec=duration_sec * float(rng.uniform(0.2, 0.6)),
            num_target_files=files,
            min_file_bytes=64 * 1024,
            max_file_bytes=256 * 1024,
            chunk_bytes=32 * 1024,
            benign_rate_hz=rate,
            seed=base_seed + i,
            scenario=scenario,
        )
        out.append(simulate_trace(cfg, name=f"corpus-{i}-{'atk' if attack else 'benign'}"))
    return out
