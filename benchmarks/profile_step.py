#!/usr/bin/env python3
"""Bisect the flagship train step's wall time by component, on the chip.

Times each piece as a K-iteration ``lax.scan`` inside ONE XLA program with
fetch-based sync (block_until_ready is a no-op on the axon platform), so
per-call dispatch overhead is out of every number.  Prints a JSON report:
fwd/bwd wall per component (LSTM, GNN, fuse, full), at the flagship
1024n/2048e bucket and the deployed 4096n/8192e bucket.

Usage: python benchmarks/profile_step.py [--platform cpu] [--k 16]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None)
    ap.add_argument("--k", type=int, default=64)
    ap.add_argument("--buckets", default="1024,4096")
    ap.add_argument("--trace-out", default=None, metavar="FILE",
                    help="write the run's host spans as Chrome-trace JSON")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    from nerrf_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    import jax.numpy as jnp
    import numpy as np

    from nerrf_tpu.bench.flops import analytic_flops
    from nerrf_tpu.data import make_corpus
    from nerrf_tpu.graph import GraphConfig
    from nerrf_tpu.models import JointConfig, NerrfNet
    from nerrf_tpu.models.graphsage import GraphSAGET
    from nerrf_tpu.models.lstm import ImpactLSTM
    from nerrf_tpu.tracing import DEFAULT_TRACER
    from nerrf_tpu.train import TrainConfig, build_dataset
    from nerrf_tpu.train.data import (DatasetConfig, padding_waste_fractions)
    from nerrf_tpu.train.loop import make_loss_fn, model_inputs

    log = lambda *a: print(*a, file=sys.stderr, flush=True)

    from nerrf_tpu.utils import fetch_value as fetch

    # constant per-call overhead (tunnel RTT + runtime dispatch), measured
    # on a warm tiny program and subtracted from every timed leg below
    _tf = jax.jit(lambda x: x + 1.0)
    _tx = _tf(jnp.zeros((8,), jnp.float32))
    fetch(_tx)
    _t0 = time.perf_counter()
    for _ in range(4):
        fetch(_tf(_tx))
    rtt = (time.perf_counter() - _t0) / 4
    log(f"[profile] per-call overhead (warm RTT): {rtt * 1e3:.0f} ms")

    def timed(fn, *fargs, k=args.k, tag=""):
        """Wall seconds per iteration of fn, scanned k times in one program.

        fn must map its args to a pytree; we thread a float carry through a
        cheap dependency (sum of first output leaf) so XLA cannot hoist the
        body out of the scan, then fetch the carry.
        """

        @jax.jit
        def run(*xs):
            def body(c, _):
                # feed the carry back into an INPUT so the body is not
                # loop-invariant (else XLA's LICM could hoist fn out of the
                # scan and the timing would measure k float-adds): perturb
                # the first float leaf by c * 1e-30 — numerically nothing,
                # but data-dependent on the previous iteration
                def bump(leaf, done):
                    if not done[0] and hasattr(leaf, "dtype") and \
                            jnp.issubdtype(leaf.dtype, jnp.floating):
                        done[0] = True
                        return leaf + (c * 1e-30).astype(leaf.dtype)
                    return leaf

                flag = [False]
                xs_p = jax.tree_util.tree_map(lambda l: bump(l, flag), xs)
                out = fn(*xs_p)
                # consume EVERY output leaf: grad legs return a params-sized
                # pytree, and feeding only one leaf into the carry lets XLA
                # dead-code-eliminate the other parameters' backward matmuls
                # (r5 review catch — it underreported bwd by ~10x once)
                tot = sum(jnp.sum(l).astype(jnp.float32)
                          for l in jax.tree_util.tree_leaves(out)
                          if hasattr(l, "dtype"))
                return c + tot * 1e-9, ()

            c, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=k)
            return c

        # spans around both legs so --trace-out shows the timeline behind
        # every reported number (compile vs steady-state, per leg)
        slug = tag.replace(" ", "_").replace("+", "")
        t0 = time.perf_counter()
        with DEFAULT_TRACER.span(f"profile_compile_{slug}", k=k):
            fetch(run(*fargs))
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        with DEFAULT_TRACER.span(f"profile_{slug}", device=True, k=k):
            fetch(run(*fargs))
        per = max(time.perf_counter() - t0 - rtt, 1e-9) / k
        log(f"  {tag}: {per * 1e3:8.2f} ms/iter (compile {compile_s:.0f}s)")
        return per

    corpus = make_corpus(8, attack_fraction=0.5, base_seed=42,
                         duration_sec=180.0, num_target_files=24,
                         benign_rate_hz=40.0)
    report = {"backend": jax.default_backend(), "k": args.k,
              "per_call_overhead_ms": round(rtt * 1e3, 2), "buckets": {}}
    cfg = TrainConfig(model=JointConfig(), batch_size=8, num_steps=8, seed=0)
    model = NerrfNet(cfg.model)
    loss_fn = make_loss_fn(model, cfg)

    for bucket in (int(b) for b in args.buckets.split(",")):
        mn, me = bucket, bucket * 2
        log(f"[profile] bucket {mn}n/{me}e")
        ds = build_dataset(corpus, DatasetConfig(
            graph=GraphConfig(window_sec=45.0, stride_sec=15.0,
                              max_nodes=mn, max_edges=me),
            seq_len=100, max_seqs=128))
        arrs = ds.arrays
        batch = {k: jax.device_put(v[:8]) for k, v in arrs.items()}
        rng = jax.random.PRNGKey(0)
        params = model.init(
            rng, *(np.asarray(v[0]) for v in model_inputs(batch)),
            deterministic=True)["params"]
        params = jax.device_put(params)

        r = {}

        # full forward (loss)
        r["fwd_full_ms"] = timed(
            lambda p, b: loss_fn(p, b, rng)[0], params, batch,
            tag="fwd full") * 1e3
        # full fwd+bwd
        grad_fn = jax.grad(lambda p, b: loss_fn(p, b, rng)[0])
        r["step_fwdbwd_ms"] = timed(grad_fn, params, batch,
                                    tag="fwd+bwd full") * 1e3

        # LSTM alone (batched like the joint model: vmap over windows)
        lstm = ImpactLSTM(cfg.model.lstm)
        lp = jax.device_put(lstm.init(
            rng, np.asarray(batch["seq_feat"][0]),
            np.asarray(batch["seq_mask"][0]))["params"])

        def lstm_fwd(p, sf, sm):
            return jax.vmap(
                lambda f, m: lstm.apply({"params": p}, f, m)["seq_logit"]
            )(sf, sm).sum()

        r["fwd_lstm_ms"] = timed(lstm_fwd, lp, batch["seq_feat"],
                                 batch["seq_mask"], tag="fwd lstm") * 1e3
        r["bwd_lstm_ms"] = timed(jax.grad(lstm_fwd), lp, batch["seq_feat"],
                                 batch["seq_mask"], tag="fwd+bwd lstm") * 1e3

        # GNN alone
        gnn = GraphSAGET(cfg.model.gnn)
        gin = ("node_feat", "node_type", "node_aux", "node_mask", "edge_src",
               "edge_dst", "edge_feat", "edge_mask")
        gp = jax.device_put(gnn.init(
            rng, *(np.asarray(batch[k][0]) for k in gin))["params"])

        def gnn_fwd(p, *xs):
            return jax.vmap(
                lambda *a: gnn.apply({"params": p}, *a)["edge_logit"]
            )(*xs).sum()

        gxs = tuple(batch[k] for k in gin)
        r["fwd_gnn_ms"] = timed(gnn_fwd, gp, *gxs, tag="fwd gnn") * 1e3
        r["bwd_gnn_ms"] = timed(jax.grad(gnn_fwd), gp, *gxs,
                                tag="fwd+bwd gnn") * 1e3

        f = analytic_flops(grad_fn, params, batch)
        r["analytic_step_gflops"] = round(f / 1e9, 1) if f else None
        cell = {k: (round(v, 2) if isinstance(v, float) else v)
                for k, v in r.items()}
        # padded capacity IS compute cost at static shapes — the waste
        # fraction travels with every per-bucket time it explains
        cell["padding_waste"] = padding_waste_fractions(arrs)
        report["buckets"][f"{mn}n/{me}e"] = cell

    if args.trace_out:
        path = DEFAULT_TRACER.write(args.trace_out)
        log(f"[profile] host spans written to {path}")
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
