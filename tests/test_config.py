"""Config layer: dataclass ⇄ JSON round-trips and the experiment registry."""

import json

import pytest

from nerrf_tpu.config import (
    CONFIG_DIR,
    EXPERIMENTS,
    Experiment,
    from_dict,
    get_experiment,
    to_dict,
)
from nerrf_tpu.models.graphsage import GraphSAGEConfig
from nerrf_tpu.train.loop import TrainConfig


def test_registry_matches_baseline_configs():
    assert set(EXPERIMENTS) == {
        "toy-graphsage", "lstm-impact", "joint-100h", "joint-dense",
        "mcts-lockbit", "multihost-online",
    }


@pytest.mark.parametrize("name", sorted(EXPERIMENTS))
def test_experiment_json_roundtrip(name):
    exp = EXPERIMENTS[name]
    back = Experiment.from_json(exp.to_json())
    assert back == exp
    # JSON form is pure data
    json.loads(exp.to_json())


def test_dtype_roundtrips_as_name():
    import jax.numpy as jnp

    cfg = GraphSAGEConfig(dtype=jnp.float32)
    d = to_dict(cfg)
    assert d["dtype"] == "float32"
    assert from_dict(GraphSAGEConfig, d).dtype is jnp.float32
    # default bfloat16 too
    d2 = to_dict(GraphSAGEConfig())
    assert d2["dtype"] == "bfloat16"
    assert from_dict(GraphSAGEConfig, d2).dtype is jnp.bfloat16


def test_unknown_key_raises():
    d = to_dict(TrainConfig())
    d["not_a_field"] = 1
    with pytest.raises(KeyError, match="not_a_field"):
        from_dict(TrainConfig, d)


def test_checked_in_configs_match_registry():
    """configs/*.json must stay in sync with the registry (run `config sync`)."""
    for name, exp in EXPERIMENTS.items():
        path = CONFIG_DIR / f"{name}.json"
        assert path.exists(), f"missing {path}; run python -m nerrf_tpu.config sync"
        assert Experiment.load(path) == exp, f"{path} is stale"


def test_build_corpus_uses_corpus_config():
    exp = get_experiment("toy-graphsage")
    train, evals = exp.build_corpus()
    assert len(train) + len(evals) == exp.corpus.num_traces
    assert len(evals) == round(exp.corpus.num_traces * exp.corpus.eval_fraction)
    # both classes present in the train split (Bresenham spread)
    assert any(t.ground_truth is not None for t in train)
    assert any(t.ground_truth is None for t in train)


def test_get_experiment_by_name_and_path(tmp_path):
    exp = get_experiment("toy-graphsage")
    assert exp.name == "toy-graphsage"
    p = tmp_path / "x.json"
    exp.save(p)
    assert get_experiment(str(p)) == exp
    with pytest.raises(KeyError):
        get_experiment("no-such-experiment")
