#!/usr/bin/env python3
"""Drift-injection bench: the detection-quality plane end to end.

Proves the quality plane's one-sentence contract on the REAL serve path:
a model serving the traffic it was calibrated on stays quiet; the same
model serving shifted traffic fires exactly one drift bundle.

Flow (one service, one warmup — zero recompiles across both legs):

  1. build a reference quality profile over a held-out corpus scored
     through the real eval path (what `calibrate_and_resave` stamps into
     a published checkpoint);
  2. **unshifted leg** — N wire streams drawn from the same generator
     family (fresh seeds) through the full serve path with the monitor
     armed: every PSI must stay below the breach threshold, zero
     ``quality_drift`` bundles, and stream 0's DetectionResult must stay
     bit-identical to offline `model_detect` (the drift plane rides the
     demux boundary — it must never perturb scoring);
  3. **shifted leg** — the same load with `SimConfig.drift` injected
     (denser, IO-heavy benign mix): the sustained-PSI trigger must fire
     EXACTLY once (rate-limited), the bundle must embed both sketch sets
     (live + reference, ``quality.json``) and be `nerrf doctor`-readable
     offline.

    python benchmarks/run_quality_bench.py           # 4 streams/leg
    python benchmarks/run_quality_bench.py --smoke   # 2 streams/leg
    python benchmarks/run_quality_bench.py --out results/quality_bench_cpu.json

Prints ONE JSON line (the artifact); exit 1 if any gate fails.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

BUCKET = (256, 512, 128)
DRIFT = 0.8


def _worst_psis(snapshot: dict) -> tuple:
    """(worst stream score PSI, worst feature PSI) from a monitor
    snapshot — None when no stream/feature cleared its evidence gate."""
    score = [s["score_psi"] for s in (snapshot.get("per_stream") or
                                      {}).values()
             if s.get("score_psi") is not None]
    feat = [f["psi"] for f in (snapshot.get("features") or {}).values()
            if f.get("psi") is not None]
    return (max(score) if score else None, max(feat) if feat else None)


def run(streams: int = 4, sim_seconds: float = 180.0,
        smoke: bool = False,
        log=lambda *a: print(*a, file=sys.stderr, flush=True)) -> dict:
    """Importable harness body (the slow-marked tier-1 smoke calls this
    in-process).  Returns the artifact dict."""
    if smoke:
        streams = 2
    log = log or (lambda *a: None)
    import jax

    from nerrf_tpu.data.loaders import Trace
    from nerrf_tpu.data.synth import SimConfig, simulate_trace
    from nerrf_tpu.flight import FlightConfig, FlightRecorder
    from nerrf_tpu.flight.doctor import format_report, read_bundle
    from nerrf_tpu.flight.journal import EventJournal
    from nerrf_tpu.ingest.service import TraceReplayServer, TrackerClient
    from nerrf_tpu.models import JointConfig, NerrfNet
    from nerrf_tpu.observability import MetricsRegistry
    from nerrf_tpu.pipeline import model_detect
    from nerrf_tpu.quality import (
        QualityConfig,
        QualityMonitor,
        build_reference_profile,
    )
    from nerrf_tpu.serve import (
        OnlineDetectionService,
        ServeConfig,
        bucket_tag,
        init_untrained_params,
    )

    backend = jax.default_backend()
    cfg = ServeConfig(
        buckets=(BUCKET,), batch_size=8, batch_close_sec=0.1,
        window_sec=15.0, stride_sec=5.0,
        stream_queue_slots=512, alert_queue_slots=4096,
        window_deadline_sec=2.0)
    model = NerrfNet(JointConfig().small)
    params = init_untrained_params(model, cfg)
    registry = MetricsRegistry(namespace="qbench")
    journal = EventJournal(capacity=8192, registry=registry)
    # bench-scale evidence gates: the legs see ~20 windows per stream, so
    # the monitor must judge on that much evidence (production defaults
    # wait for 32 windows / 256 scores per stream)
    monitor = QualityMonitor(
        QualityConfig(min_windows=10, min_scores=150, journal_every=4,
                      trailing_windows=1024,
                      feature_trailing_windows=1024),
        registry=registry, journal=journal)
    svc = OnlineDetectionService(params, model, cfg=cfg, registry=registry,
                                 journal=journal, quality_monitor=monitor)
    t0 = time.perf_counter()
    svc.start(log=log)
    log(f"[quality-bench] service warm in {time.perf_counter() - t0:.1f}s")

    # the reference profile: the distribution this (model, threshold)
    # pair expects — held-out seeds, same generator family as the
    # unshifted leg, scored through the real eval path
    def sim(seed: int, drift: float, attack: bool) -> "SimConfig":
        return SimConfig(duration_sec=sim_seconds, attack=attack,
                         attack_start_sec=sim_seconds / 3,
                         num_target_files=4, benign_rate_hz=6.0,
                         seed=seed, drift=drift)

    ref_traces = [simulate_trace(sim(500 + i, 0.0, attack=(i % 2 == 0)))
                  for i in range(max(streams, 4))]
    profile = build_reference_profile(
        params, model, ref_traces, ds_cfg=cfg.dataset_config(BUCKET),
        threshold=(cfg.threshold if cfg.threshold is not None else 0.5),
        log=log)

    # the trigger may only judge once the trailing population spans most
    # of a full traffic cycle per stream: the synthetic traffic is
    # non-stationary WITHIN a trace (benign prefix → attack burst), so a
    # young trailing set is a genuinely biased subsample of the reference
    # and PSI reads high on identical distributions (measured 1.1 at 30
    # of 60 windows, 0.1 at the full leg).  80% of the expected windows
    # is past the transient with margin on both sides of the 0.25 cut
    windows_per_stream = int((sim_seconds - cfg.window_sec)
                             / cfg.stride_sec) + 1
    flight_cfg = dict(
        quality_psi_breach=0.25,
        quality_min_windows=int(streams * windows_per_stream * 0.8),
        quality_breach_records=2, min_interval_sec=3600.0,
        # only the drift trigger is under test: park the others
        drop_burst_n=10 ** 6, p99_breach_sec=None)
    work = tempfile.mkdtemp(prefix="nerrf-quality-bench-")

    def leg(name: str, drift: float, seed_base: int,
            check_parity: bool) -> dict:
        out_dir = os.path.join(work, name)
        svc.set_quality_profile(profile.to_dict(), version=1)
        recorder = FlightRecorder(
            FlightConfig(out_dir=out_dir, **flight_cfg),
            registry=registry, journal=journal, slo=svc.slo,
            info=svc.flight_info, quality=svc.quality_snapshot, log=log)
        traces, servers, targets = [], [], []
        for i in range(streams):
            tr = simulate_trace(sim(seed_base + 97 * i, drift,
                                    attack=(i % 2 == 0)))
            srv = TraceReplayServer(tr.events, tr.strings, batch_size=256)
            port = srv.start()
            traces.append(tr)
            servers.append(srv)
            targets.append(f"127.0.0.1:{port}")
        t0 = time.perf_counter()
        runs = [svc.connect(f"{name}{i}", targets[i], timeout=300.0)
                for i in range(streams)]
        for r in runs:
            r.done.wait(timeout=600.0)
        wall = time.perf_counter() - t0
        errors = {r.stream: repr(r.error) for r in runs if r.error}
        parity = None
        if check_parity:
            # the drift plane must never perturb scoring: stream 0 vs
            # offline model_detect on the same decoded bytes, exactly the
            # serve bench's parity leg
            ev, strings = TrackerClient(targets[0]).stream(timeout=60.0)
            offline = model_detect(
                Trace(events=ev, strings=strings, ground_truth=None,
                      labels=None, name=f"{name}0"),
                params, model, ds_cfg=cfg.dataset_config(BUCKET),
                auto_capacity=False, batch_size=cfg.batch_size)
            served = runs[0].result
            parity = (
                served is not None
                and served.file_scores == offline.file_scores
                and served.file_window_scores == offline.file_window_scores
                and served.proc_scores == offline.proc_scores
                and served.threshold == offline.threshold)
        snapshot = svc.quality_snapshot() or {}
        recorder.close()
        for srv in servers:
            srv.stop()
        worst_score, worst_feat = _worst_psis(snapshot)
        bundles = sorted(p for p in (os.listdir(out_dir)
                                     if os.path.isdir(out_dir) else [])
                         if p.startswith("bundle-"))
        result = {
            "drift": drift,
            "wall_seconds": round(wall, 2),
            "windows_observed": snapshot.get("windows_observed", 0),
            "worst_score_psi": worst_score,
            "worst_feature_psi": worst_feat,
            "margin_mass": snapshot.get("margin_mass"),
            "bundles": len(bundles),
            "bundle_names": bundles,
            "stream_errors": errors or None,
        }
        if check_parity:
            result["parity_bit_identical_to_model_detect"] = bool(parity)
        if bundles:
            # the drift bundle must be self-contained, offline-readable
            # evidence: doctor renders it, quality.json embeds BOTH
            # sketch sets (live trailing + the full reference profile)
            b = read_bundle(os.path.join(out_dir, bundles[0]))
            report = format_report(b)
            q = b.get("quality") or {}
            result["bundle_trigger"] = bundles[0].rsplit("-", 1)[-1]
            result["bundle_doctor_ok"] = (
                not b["missing"]
                and "detection quality (drift" in report
                and "incident timeline" in report)
            result["bundle_has_live_sketches"] = any(
                s.get("score_sketch") for s in
                (q.get("per_stream") or {}).values())
            result["bundle_has_reference_profile"] = bool(
                (q.get("reference") or {}).get("score"))
        log(f"[quality-bench] leg {name}: {result['windows_observed']} "
            f"windows, worst score PSI {worst_score}, worst feature PSI "
            f"{worst_feat}, bundles {len(bundles)}")
        return result

    try:
        unshifted = leg("u", 0.0, seed_base=1000, check_parity=True)
        shifted = leg("d", DRIFT, seed_base=3000, check_parity=False)
    finally:
        svc.stop()
        shutil.rmtree(work, ignore_errors=True)

    tag = bucket_tag(BUCKET)
    recompiles = int(registry.value("serve_recompiles_total",
                                    labels={"bucket": tag}))
    result = {
        "metric": "quality_drift_detection",
        "value": shifted.get("worst_score_psi"),
        "unit": "worst trailing score PSI under injected drift "
                f"(threshold {flight_cfg['quality_psi_breach']})",
        "backend": backend,
        "smoke": smoke or None,
        "streams": streams,
        "psi_breach": flight_cfg["quality_psi_breach"],
        "reference": profile.summary(),
        "unshifted": unshifted,
        "shifted": shifted,
        "recompiles_after_warmup": recompiles,
        "alerts_emitted": int(sum(
            registry.value("serve_alerts_emitted_total",
                           labels={"stream": f"{leg_name}{i}"})
            for leg_name in ("u", "d") for i in range(streams))),
        "provenance": "python benchmarks/run_quality_bench.py"
                      + (" --smoke" if smoke else ""),
    }
    return result


def gates(result: dict) -> list:
    """Every acceptance gate, as (name, ok) — shared by main() and the
    artifact-of-record test."""
    u, d = result["unshifted"], result["shifted"]
    breach = result["psi_breach"]
    below = [v for v in (u.get("worst_score_psi"),
                         u.get("worst_feature_psi")) if v is not None]
    return [
        ("unshifted_no_bundles", u["bundles"] == 0),
        ("unshifted_psi_below_breach",
         bool(below) and max(below) < breach),
        ("unshifted_parity_bit_identical",
         u.get("parity_bit_identical_to_model_detect") is True),
        ("unshifted_no_stream_errors", u.get("stream_errors") is None),
        ("shifted_exactly_one_bundle", d["bundles"] == 1),
        ("shifted_bundle_is_quality_drift",
         d.get("bundle_trigger") == "quality_drift"),
        ("shifted_bundle_doctor_ok", d.get("bundle_doctor_ok") is True),
        ("shifted_bundle_embeds_both_sketch_sets",
         d.get("bundle_has_live_sketches") is True
         and d.get("bundle_has_reference_profile") is True),
        ("shifted_no_stream_errors", d.get("stream_errors") is None),
        ("zero_recompiles", result["recompiles_after_warmup"] == 0),
    ]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--streams", type=int, default=4)
    ap.add_argument("--seconds", type=float, default=180.0,
                    help="simulated seconds of trace per stream")
    ap.add_argument("--smoke", action="store_true",
                    help="2 streams per leg, short traces")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="also write the artifact JSON here")
    args = ap.parse_args(argv)

    result = run(streams=args.streams, sim_seconds=args.seconds,
                 smoke=args.smoke)
    print(json.dumps(result))
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as f:
            f.write(json.dumps(result, indent=2) + "\n")
    failed = [name for name, ok in gates(result) if not ok]
    for name in failed:
        print(f"[quality-bench] GATE FAILED: {name}", file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
