"""Shared plumbing for the deep (jaxpr-level) nerrflint tier.

The AST rules (`nerrf_tpu/analysis/*.py`) see source text; these rules see
the *programs XLA would compile*: every entry point is traced abstractly —
`jax.eval_shape` / `jax.make_jaxpr` / `jit(...).lower(...)` over
`ShapeDtypeStruct` avals, no devices touched, no data materialized — the
execution-free tensor-program regime of TpuGraphs (arXiv:2308.13490) and
the configuration cross-attention predictor (arXiv:2405.16623).  That lets
the chip-queue pre-flight *prove* contracts on CPU in seconds that today
only surface by burning accelerator minutes: warmup signature closure,
donation aliasing, collective axis validity, Pallas VMEM budgets, and
compile-cache key coverage.

Everything here defers its jax import to call time: the base engine (and
the plain ``nerrf lint`` tier-1 gate) must stay importable with no jax on
the path.  `prepare_backend` is called by `engine.main --deep` before any
rule runs — it forces the CPU platform and a virtual multi-device host so
the shard_map shims can be traced on any machine, including one whose
accelerator tunnel is wedged (which is exactly when a pre-flight matters).
"""

from __future__ import annotations

import dataclasses
import os
import re
import sys
from typing import Callable, List, Optional, Sequence, Tuple

from nerrf_tpu.analysis.engine import Finding

# virtual host devices for the shard_map trace legs (conftest.py uses the
# same count; any value ≥ 2 works — the ring entry uses two)
_VIRTUAL_DEVICES = 8


def prepare_backend() -> None:
    """Force the deep pass onto a virtual multi-device CPU backend.

    Must run before jax's backend initializes.  Env vars alone are not
    enough on hosts whose sitecustomize imports jax at interpreter start
    (the axon TPU plugin registration — see tests/conftest.py), so the
    platform choice also goes through jax.config; backend init is lazy, so
    this works as long as nothing has traced yet.  Best-effort by design:
    if a backend is already up (an embedder running lint in-process), the
    rules still trace correctly on whatever platform is live — only the
    multi-device legs may degrade (they check `jax.device_count()`)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count"
                    f"={_VIRTUAL_DEVICES}").strip()
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — backend already initialized
        pass


def aval(shape: Sequence[int], dtype) -> "jax.ShapeDtypeStruct":  # noqa: F821
    import jax

    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def avals_of_spec(spec: dict, batch: int = 0) -> dict:
    """`train.data.sample_spec`-style ``{k: (shape, dtype)}`` → aval dict,
    optionally with a leading batch axis."""
    lead = (batch,) if batch else ()
    return {k: aval(lead + tuple(shape), dtype)
            for k, (shape, dtype) in spec.items()}


# -- micro model: tracing cost control ----------------------------------------


def micro_train_config():
    """A minimal TrainConfig: same program *structure* as the flagship
    (same jit boundaries, donation spec, loss composition — what the deep
    contracts are about), smallest tensors, so each abstract trace costs
    ~1 s instead of ~6 s and the whole pass stays inside its 30 s budget."""
    from nerrf_tpu.models import GraphSAGEConfig, JointConfig, LSTMConfig
    from nerrf_tpu.train.loop import TrainConfig

    model = JointConfig(
        gnn=GraphSAGEConfig(hidden=8, num_layers=1, aggregation="segment"),
        lstm=LSTMConfig(hidden=8, num_layers=1))
    return TrainConfig(model=model, batch_size=2, num_steps=4,
                       warmup_steps=1)


def micro_serve_model():
    """The micro NerrfNet for serve-program traces (shape-polymorphic, so
    the closure/cache-key proofs transfer to any deployed architecture)."""
    from nerrf_tpu.models import NerrfNet

    return NerrfNet(micro_train_config().model)


_PARAM_AVALS_MEMO: dict = {}


def param_avals(model, sample_avals: dict):
    """Abstract param tree for ``model`` at one window sample's shapes —
    `jax.eval_shape` over init: no RNG drawn, no buffer allocated.
    Memoized per (architecture, sample signature): several entries build
    the same micro model, and each eval_shape costs ~0.5 s of the deep
    pass's 30 s budget."""
    import jax
    import jax.numpy as jnp

    from nerrf_tpu.train.loop import model_inputs

    memo_key = (repr(getattr(model, "cfg", model)), tuple(sorted(
        (k, tuple(v.shape), str(v.dtype))
        for k, v in sample_avals.items())))
    hit = _PARAM_AVALS_MEMO.get(memo_key)
    if hit is not None:
        return hit

    def init_fn(rng):
        # canonicalize up front (int64 → int32 under default x64-off) so
        # the zeros don't warn on every bucket traced
        one = {k: jnp.zeros(v.shape, jax.dtypes.canonicalize_dtype(v.dtype))
               for k, v in sample_avals.items()}
        return model.init(rng, *model_inputs(one),
                          deterministic=True)["params"]

    out = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    _PARAM_AVALS_MEMO[memo_key] = out
    return out


# -- lowered-program inspection -----------------------------------------------

_MAIN_SIG = re.compile(
    r"func\.func\s+public\s+@main\((?P<args>.*?)\)\s*->", re.DOTALL)
_ARG_START = re.compile(r"%arg(\d+):")

# markers jax stamps on an argument whose buffer WILL be reused for an
# output: plain lowerings carry ``tf.aliasing_output``; lowerings under
# shardings carry ``jax.buffer_donor`` instead
_DONATED_MARKERS = ("tf.aliasing_output", "jax.buffer_donor")


def alias_attrs(lowered_text: str) -> Optional[List[bool]]:
    """Per-flat-argument donation verdicts from a lowered StableHLO module:
    ``True`` where jax committed the input's buffer to an output, ``False``
    otherwise.  None when the main signature cannot be found (caller
    degrades gracefully).

    Parses by ``%argN`` chunk rather than a brace-matched attr dict:
    sharded lowerings embed nested braces inside quoted attr strings
    (``mhlo.sharding = "{devices=[2,1]<=[2]}"``), which no flat regex over
    ``{...}`` survives."""
    m = _MAIN_SIG.search(lowered_text)
    if m is None:
        return None
    args_text = m.group("args")
    starts = list(_ARG_START.finditer(args_text))
    out: List[bool] = []
    for i, am in enumerate(starts):
        end = starts[i + 1].start() if i + 1 < len(starts) else len(args_text)
        chunk = args_text[am.start():end]
        out.append(any(marker in chunk for marker in _DONATED_MARKERS))
    return out or None


def leaf_paths(tree) -> List[str]:
    """Human-readable path strings for a pytree's leaves, in flatten order
    (names donation findings by the actual buffer, not a flat index)."""
    import jax

    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [jax.tree_util.keystr(kp) or "<leaf>" for kp, _ in flat]


# -- jaxpr walking ------------------------------------------------------------

COLLECTIVE_PRIMS = {
    "psum", "psum2", "pmax", "pmin", "pbroadcast", "ppermute",
    "all_gather", "all_to_all", "reduce_scatter", "axis_index",
    "psum_invariant",
}


def iter_eqns(jaxpr):
    """Every eqn in ``jaxpr`` and all jaxprs nested in its params (scan
    bodies, cond branches, shard_map bodies, custom-vjp calls...)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            inner = getattr(v, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                yield from iter_eqns(inner)
            elif hasattr(v, "eqns"):
                yield from iter_eqns(v)
            elif isinstance(v, (tuple, list)):
                for w in v:
                    inner = getattr(w, "jaxpr", None)
                    if inner is not None and hasattr(inner, "eqns"):
                        yield from iter_eqns(inner)
                    elif hasattr(w, "eqns"):
                        yield from iter_eqns(w)


def collectives_in(closed_jaxpr) -> List[Tuple[str, Tuple[str, ...], dict]]:
    """(primitive, axis-names, params) for every collective eqn reachable
    in the jaxpr, nested bodies included."""
    out = []
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name not in COLLECTIVE_PRIMS:
            continue
        axes = eqn.params.get("axis_name", eqn.params.get("axes", ()))
        if isinstance(axes, (str, type(None))):
            axes = (axes,) if axes else ()
        out.append((eqn.primitive.name,
                    tuple(str(a) for a in axes), dict(eqn.params)))
    return out


def program_identity(closed_jaxpr) -> Tuple[str, str]:
    """(jaxpr text, digest of captured constant VALUES) — what actually
    distinguishes one lowered program from another.  ``str(jaxpr)`` alone
    shows constvar *names*, not values, so two programs differing only in
    a small captured array would compare equal without the digest."""
    import hashlib

    import numpy as np

    h = hashlib.blake2s()
    for c in closed_jaxpr.consts:
        try:
            arr = np.asarray(c)
            h.update(str(arr.dtype).encode())
            h.update(str(arr.shape).encode())
            h.update(arr.tobytes())
        except Exception:  # noqa: BLE001 — non-array const: repr is best
            h.update(repr(c).encode())
    return str(closed_jaxpr.jaxpr), h.hexdigest()


def big_consts(closed_jaxpr, min_bytes: int) -> List[Tuple[tuple, str, int]]:
    """(shape, dtype, nbytes) of every closure-captured constant of at
    least ``min_bytes`` baked into the jaxpr — the material a cache
    fingerprint cannot see (it hashes argument avals, and a capture is not
    an argument)."""
    out = []
    for c in closed_jaxpr.consts:
        nbytes = int(getattr(c, "nbytes", 0) or 0)
        if nbytes >= min_bytes:
            out.append((tuple(getattr(c, "shape", ())),
                        str(getattr(c, "dtype", type(c).__name__)), nbytes))
    return out


# -- entry descriptors (rules consume these; entries.py builds the real ones) --


@dataclasses.dataclass
class DonationEntry:
    """One jitted program whose donation discipline is verified from its
    lowered module.  ``build() -> (jit_fn, args)`` with abstract avals;
    ``donate`` = argnums the jit declares donated; ``must_donate`` =
    argnums holding large reusable state (params/opt_state) that MUST be
    donated or peak memory doubles at flagship shapes."""

    name: str
    path: str                     # repo-relative anchor file
    build: Callable[[], tuple]
    donate: Tuple[int, ...] = ()
    must_donate: Tuple[int, ...] = ()


@dataclasses.dataclass
class CollectiveEntry:
    """One shard_map/pjit program traced to a jaxpr whose collectives must
    only name axes of ``mesh_axes``."""

    name: str
    path: str
    build: Callable[[], tuple]    # () -> (fn, args) for make_jaxpr
    mesh_axes: Tuple[str, ...] = ()
    axis_sizes: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class CacheKeyEntry:
    """One cache-keyed program with variants along a config axis.  Each
    variant is ``(label, build, extra)`` where ``build() -> (fn, args)``;
    the rule traces the jaxpr of each and requires: whenever two variants
    lower different programs, their CompileCache fingerprints differ."""

    name: str
    path: str
    variants: List[tuple]
    min_const_bytes: int = 4096


def finding(rule_id: str, path: str, line: int, anchor: str, message: str,
            hint: str = "") -> Finding:
    return Finding(rule=rule_id, path=path, line=line, message=message,
                   hint=hint, anchor=anchor)


def locate(project, module_name: str, qualname: str) -> Tuple[str, int]:
    """(path, line) anchor for a function in the scanned project; falls
    back to the module path (line 1) or a synthesized path so deep rules
    work even when the AST project was built over a subset."""
    mod = project.modules.get(module_name) if project is not None else None
    if mod is None:
        return module_name.replace(".", "/") + ".py", 1
    for fi in mod.functions:
        if fi.qualname == qualname:
            return mod.path, fi.line
    return mod.path, 1


def note(msg: str) -> None:
    print(f"nerrflint: deep: {msg}", file=sys.stderr)
