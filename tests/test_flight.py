"""Flight plane: journal ring, SLO accounting, anomaly-triggered recorder,
crash hooks, offline doctor, and the serve-path integration."""

import json
import os
import threading
import time

import numpy as np
import pytest

from nerrf_tpu.flight import (
    EventJournal,
    FlightConfig,
    FlightRecorder,
    SLOTracker,
    install_crash_handlers,
    make_trace_id,
)
from nerrf_tpu.flight.doctor import doctor_main, format_report, read_bundle
from nerrf_tpu.flight.journal import load_journal
from nerrf_tpu.observability import MetricsRegistry
from nerrf_tpu.tracing import Tracer


def _recorder(tmp_path, reg=None, journal=None, slo=None, **cfg_kw):
    reg = reg or MetricsRegistry(namespace="t")
    journal = journal or EventJournal(registry=reg)
    cfg_kw.setdefault("out_dir", str(tmp_path / "bundles"))
    cfg_kw.setdefault("min_interval_sec", 300.0)
    rec = FlightRecorder(FlightConfig(**cfg_kw), registry=reg,
                         journal=journal, tracer=Tracer(registry=reg),
                         slo=slo)
    return rec, journal, reg


# -- journal ------------------------------------------------------------------

def test_journal_ring_is_bounded_with_monotonic_seq():
    reg = MetricsRegistry(namespace="t")
    j = EventJournal(capacity=8, registry=reg)
    for i in range(20):
        j.record("batch_close", bucket="b", occupancy=i)
    tail = j.tail()
    assert len(tail) == 8
    assert [r.seq for r in tail] == list(range(13, 21))  # newest 8, in order
    assert tail[-1].data["occupancy"] == 19
    assert j.seq == 20
    assert reg.value("flight_journal_records_total",
                     labels={"kind": "batch_close"}) == 20


def test_journal_tail_filters_and_jsonl_roundtrip(tmp_path):
    j = EventJournal(registry=MetricsRegistry())
    j.record("batch_close", bucket="b")
    j.record("admission_drop", stream="s0", window_id=3,
             trace_id="w-abc", reason="backpressure")
    j.record("readiness", ready=True)
    assert [r.kind for r in j.tail(kinds=("admission_drop",))] \
        == ["admission_drop"]
    assert [r.seq for r in j.tail(since_seq=2)] == [3]
    path = j.write(tmp_path / "journal.jsonl")
    back = load_journal(path)
    assert [(r.seq, r.kind) for r in back] == \
        [(1, "batch_close"), (2, "admission_drop"), (3, "readiness")]
    assert back[1].stream == "s0" and back[1].trace_id == "w-abc"
    assert back[1].data == {"reason": "backpressure"}


def test_journal_listeners_fire_outside_lock_and_swallow_errors():
    j = EventJournal(registry=MetricsRegistry())
    got = []

    def listener(rec):
        # re-entrancy: a listener may itself record (the recorder journals
        # its own bundles) — deadlock here means the lock is held
        if rec.kind != "echo":
            j.record("echo")
        got.append(rec.kind)

    def boom(rec):
        raise RuntimeError("listener exploded")

    j.subscribe(boom)
    j.subscribe(listener)
    j.record("batch_close")
    assert "batch_close" in got and "echo" in got
    j.unsubscribe(listener)
    j.record("batch_close")
    assert got.count("batch_close") == 1


def test_make_trace_id_is_deterministic_and_distinct():
    a = make_trace_id("s0", 3, 1000)
    assert a == make_trace_id("s0", 3, 1000)
    assert a != make_trace_id("s0", 4, 1000)
    assert a != make_trace_id("s1", 3, 1000)
    assert a.startswith("w-")


# -- SLO tracker --------------------------------------------------------------

def test_slo_tracker_exports_histograms_burn_and_exemplar():
    reg = MetricsRegistry(namespace="t")
    j = EventJournal(registry=reg)
    slo = SLOTracker(deadline_sec=1.0, registry=reg, journal=j)
    for i in range(10):
        slo.observe("s0", f"w-{i:03d}", i,
                    stages={"queue": 0.02, "pack": 0.01, "device": 0.05,
                            "demux": 0.02},
                    e2e_sec=0.1)
    # the slowest window becomes the stream's exemplar
    slo.observe("s0", "w-slow", 99,
                stages={"queue": 0.2, "pack": 0.1, "device": 1.5,
                        "demux": 0.2},
                e2e_sec=2.0)
    assert reg.value("slo_e2e_seconds", labels={"stream": "s0"},
                     stat="count") == 11
    assert reg.value("slo_stage_seconds", labels={"stage": "device"},
                     stat="count") == 11
    burn = reg.value("slo_budget_burn_ratio",
                     labels={"stream": "s0", "stage": "device"})
    assert 0 < burn < 1  # mean device share of the 1 s budget
    assert reg.value("slo_breaches_total", labels={"stream": "s0"}) == 1
    assert slo.exemplar("s0") == ("w-slow", 2.0)
    assert slo.exemplar("missing") == (None, None)
    # the breach journaled with its trace id (the alert→span join key)
    breaches = j.tail(kinds=("slo_breach",))
    assert len(breaches) == 1 and breaches[0].trace_id == "w-slow"
    snap = slo.snapshot()
    s0 = snap["per_stream"]["s0"]
    assert s0["count"] == 11 and s0["breaches"] == 1
    assert s0["p50_ms"] == 100.0 and s0["p99_ms"] == 2000.0
    assert s0["exemplar_trace_id"] == "w-slow"
    assert set(s0["budget_burn"]) == {"queue", "pack", "device", "demux"}
    rendered = reg.render()
    assert "t_slo_e2e_seconds_bucket" in rendered
    assert 'stream="s0"' in rendered


def test_slo_budget_burn_and_exemplar_are_trailing():
    """A regression must move the burn gauge within ONE trailing window
    (not fight a day of history), and the exemplar must age out with its
    window so its trace ID always joins to evidence the rings still hold."""
    reg = MetricsRegistry(namespace="t")
    slo = SLOTracker(deadline_sec=1.0, registry=reg,
                     journal=EventJournal(registry=reg), trailing=4)
    for i in range(4):
        slo.observe("s", f"w-slow{i}", i, stages={"device": 1.0},
                    e2e_sec=1.0 + 0.1 * i)
    assert reg.value("slo_budget_burn_ratio",
                     labels={"stream": "s", "stage": "device"}) \
        == pytest.approx(1.0)
    assert slo.exemplar("s")[0] == "w-slow3"
    # recovery: 4 fast windows fully displace the slow history
    for i in range(4):
        slo.observe("s", f"w-fast{i}", 10 + i, stages={"device": 0.0},
                    e2e_sec=0.01)
    assert reg.value("slo_budget_burn_ratio",
                     labels={"stream": "s", "stage": "device"}) \
        == pytest.approx(0.0)
    trace, e2e = slo.exemplar("s")
    assert trace.startswith("w-fast") and e2e == 0.01  # slow spike aged out
    # count stays all-time (the snapshot's volume figure), window does not
    assert slo.snapshot()["per_stream"]["s"]["count"] == 8


def test_slo_tracker_bounds_stream_cardinality():
    """A resident pod's reconnect sessions mint stream IDs forever; beyond
    max_streams the LRU stream's state AND registry series are retired."""
    reg = MetricsRegistry(namespace="t")
    slo = SLOTracker(deadline_sec=1.0, registry=reg,
                     journal=EventJournal(registry=reg), max_streams=2)
    for sid in ("s#0", "s#1", "s#2"):
        slo.observe(sid, f"w-{sid}", 0, stages={"device": 0.1}, e2e_sec=2.0)
    assert slo.exemplar("s#0") == (None, None)  # evicted (LRU)
    assert set(slo.snapshot()["per_stream"]) == {"s#1", "s#2"}
    text = reg.render()
    assert 'stream="s#0"' not in text  # series retired, not just frozen
    assert 'stream="s#1"' in text and 'stream="s#2"' in text
    # touching s#1 refreshes it: s#2 becomes the LRU victim next
    slo.observe("s#1", "w2", 1, stages={}, e2e_sec=0.1)
    slo.observe("s#3", "w3", 0, stages={}, e2e_sec=0.1)
    assert set(slo.snapshot()["per_stream"]) == {"s#1", "s#3"}


def test_registry_remove_series_drops_one_labeled_series():
    reg = MetricsRegistry(namespace="t")
    reg.histogram_observe("lat_seconds", 0.1, labels={"stream": "a"},
                          help="lat")
    reg.histogram_observe("lat_seconds", 0.2, labels={"stream": "b"})
    reg.gauge_set("g", 1.0, labels={"stream": "a"}, help="g")
    assert reg.remove_series("lat_seconds", {"stream": "a"}) is True
    assert reg.remove_series("lat_seconds", {"stream": "a"}) is False
    text = reg.render()
    assert 't_lat_seconds_bucket{le="0.5",stream="b"} 1' in text
    assert 't_lat_seconds_count{stream="a"}' not in text
    assert 't_g{stream="a"} 1' in text  # other metrics untouched
    assert reg.value("lat_seconds", labels={"stream": "a"},
                     stat="count") == 0
    assert reg.value("lat_seconds", labels={"stream": "b"},
                     stat="count") == 1


def test_slo_tracker_clamps_negative_stage_jitter():
    slo = SLOTracker(deadline_sec=1.0, registry=MetricsRegistry(),
                     journal=EventJournal(registry=MetricsRegistry()))
    slo.observe("s", None, 0, stages={"queue": -1e-6}, e2e_sec=-0.001)
    snap = slo.snapshot()["per_stream"]["s"]
    assert snap["p50_ms"] == 0.0
    assert all(v >= 0 for v in snap["budget_burn"].values())


# -- recorder triggers --------------------------------------------------------

def test_p99_breach_fires_exactly_one_rate_limited_bundle(tmp_path):
    rec, journal, reg = _recorder(tmp_path, p99_breach_sec=0.5,
                                  p99_min_count=8)
    journal.record("batch_close", bucket="b", occupancy=4,
                   trace_ids=["w-slow"])
    for _ in range(16):
        rec.observe_window("s0", "w-slow", 2.5)
    bundles = [p for p in os.listdir(tmp_path / "bundles")
               if p.startswith("bundle-")]
    assert len(bundles) == 1 and bundles[0].endswith("p99_breach")
    assert reg.value("flight_bundles_total",
                     labels={"trigger": "p99_breach"}) == 1
    assert reg.value("flight_triggers_suppressed_total",
                     labels={"trigger": "p99_breach"}) >= 1
    bundle = read_bundle(tmp_path / "bundles" / bundles[0])
    assert bundle["manifest"]["trigger"] == "p99_breach"
    assert bundle["manifest"]["context"]["trace_id"] == "w-slow"
    # the offending batch-close record is in the journal tail
    assert any(r.kind == "batch_close"
               and "w-slow" in r.data.get("trace_ids", [])
               for r in bundle["records"])
    rec.close()


def test_p99_trigger_needs_min_count_and_disabled_without_threshold(tmp_path):
    rec, _, _ = _recorder(tmp_path, p99_breach_sec=0.5, p99_min_count=8)
    for _ in range(7):
        rec.observe_window("s0", None, 9.0)  # under the min-count gate
    assert not (tmp_path / "bundles").exists()
    rec.close()
    rec2, _, _ = _recorder(tmp_path, p99_breach_sec=None)
    for _ in range(50):
        rec2.observe_window("s0", None, 9.0)  # trigger disarmed
    assert not (tmp_path / "bundles").exists()
    rec2.close()


def test_drop_burst_trigger(tmp_path):
    rec, journal, _ = _recorder(tmp_path, drop_burst_n=5, drop_burst_sec=10.0)
    for i in range(4):
        journal.record("admission_drop", stream="s0", window_id=i,
                       reason="backpressure")
    assert not (tmp_path / "bundles").exists()  # below the burst threshold
    journal.record("demux_drop", stream="s0", window_id=4,
                   reason="sink_full")  # both drop kinds count
    bundles = os.listdir(tmp_path / "bundles")
    assert len(bundles) == 1 and bundles[0].endswith("drop_burst")
    rec.close()


def test_veto_and_disagreement_triggers(tmp_path):
    rec, journal, _ = _recorder(tmp_path, disagreement_spike=0.3,
                                disagreement_min_windows=8)
    journal.record("registry_veto", lineage="default", version=3,
                   reason="disagreement_rate 0.41 > 0.25")
    journal.record("registry_shadow_stats", lineage="default", version=4,
                   windows=1, disagreement_rate=0.9,
                   score_drift=0.4)  # first-window noise: min-windows gated
    journal.record("registry_shadow_stats", lineage="default", version=4,
                   windows=64, disagreement_rate=0.55, score_drift=0.2)
    journal.record("registry_shadow_stats", lineage="default", version=4,
                   windows=96, disagreement_rate=0.01,
                   score_drift=0.0)  # below spike
    names = sorted(os.listdir(tmp_path / "bundles"))
    assert len(names) == 2
    assert {n.rsplit("-", 1)[-1] for n in names} \
        == {"guardrail_veto", "shadow_disagreement"}
    # the bundle that fired is the sustained one, not the noise spike
    man = json.loads((tmp_path / "bundles"
                      / [n for n in names if n.endswith("disagreement")][0]
                      / "manifest.json").read_text())
    assert man["context"]["windows"] == 64
    rec.close()


def test_bundles_are_atomic_bounded_and_self_contained(tmp_path):
    rec, journal, reg = _recorder(tmp_path, max_bundles=3,
                                  min_interval_sec=0.0)
    reg.counter_inc("windows_total", 7, help="windows")
    journal.record("config", config_fingerprint="abc123")
    for i in range(6):
        rec.trigger("p99_breach", f"incident {i}", context={"i": i})
    root = tmp_path / "bundles"
    names = sorted(os.listdir(root))
    assert not [n for n in names if n.endswith(".tmp")]  # atomic: no torn dir
    bundles = [n for n in names if n.startswith("bundle-")]
    assert len(bundles) == 3  # disk bound enforced, oldest deleted
    for name in bundles:
        files = set(os.listdir(root / name))
        assert {"manifest.json", "journal.jsonl", "trace.json",
                "metrics.prom"} <= files
        man = json.loads((root / name / "manifest.json").read_text())
        assert man["env"]["python"] and man["env"]["pid"] == os.getpid()
        assert "windows_total 7" in (root / name / "metrics.prom").read_text()
    # the newest bundle survived (retention deletes from the old end)
    newest = json.loads((root / bundles[-1] / "manifest.json").read_text())
    assert newest["context"]["i"] == 5
    rec.close()


def test_failed_dump_leaves_no_tmp_behind(tmp_path, monkeypatch):
    """A dump that dies mid-write (ENOSPC) must remove its partial .tmp —
    each dump mints a fresh name, so an orphan would evade retention and
    erode the disk bound forever."""
    rec, journal, reg = _recorder(tmp_path, min_interval_sec=0.0)
    monkeypatch.setattr(rec._tracer, "chrome_trace",
                        lambda: (_ for _ in ()).throw(OSError("disk full")))
    assert rec.trigger("p99_breach", "spike") is None  # swallowed, logged
    root = tmp_path / "bundles"
    assert not any(e.endswith(".tmp") for e in os.listdir(root))
    assert reg.value("flight_bundles_total",
                     labels={"trigger": "p99_breach"}) == 0
    # recovery: the next dump (disk freed) succeeds normally
    monkeypatch.undo()
    assert rec.trigger("p99_breach", "spike again") is not None
    rec.close()


def test_failed_dump_does_not_consume_the_rate_limit(tmp_path, monkeypatch):
    """A dump that fails (volume not mounted yet at pod start) must leave
    the per-trigger interval unconsumed: the next firing retries instead
    of taking the suppressed path for min_interval_sec with zero bundles
    on disk while the journal/span rings wrap past the evidence."""
    rec, journal, reg = _recorder(tmp_path, min_interval_sec=3600.0)
    monkeypatch.setattr(rec._tracer, "chrome_trace",
                        lambda: (_ for _ in ()).throw(OSError("disk full")))
    assert rec.trigger("p99_breach", "spike") is None
    monkeypatch.undo()  # disk freed — the re-fire must dump, not suppress
    assert rec.trigger("p99_breach", "spike sustained") is not None
    assert reg.value("flight_bundles_total",
                     labels={"trigger": "p99_breach"}) == 1
    # and the interval IS consumed by the successful dump
    assert rec.trigger("p99_breach", "still breaching") is None
    assert reg.value("flight_triggers_suppressed_total",
                     labels={"trigger": "p99_breach"}) == 1
    rec.close()


def test_journal_exception_helper_produces_a_bundle(tmp_path):
    """The shared capture path the serve CLI uses for MAIN-thread crashes
    (whose finally uninstalls the excepthook before it could ever fire):
    journaling the exception directly must still produce the bundle."""
    from nerrf_tpu.flight.recorder import journal_exception

    rec, journal, _ = _recorder(tmp_path)
    try:
        raise RuntimeError("main thread died in the summary writer")
    except RuntimeError as e:
        journal_exception(journal, type(e), e, e.__traceback__, "main")
    recs = journal.tail(kinds=("exception",))
    assert len(recs) == 1 and recs[0].stream == "main"
    assert "summary writer" in recs[0].data["message"]
    names = [n for n in os.listdir(tmp_path / "bundles")
             if n.startswith("bundle-")]
    assert len(names) == 1 and names[0].endswith("exception")
    rec.close()


def test_recorder_survives_undumpable_out_dir(tmp_path):
    target = tmp_path / "not-a-dir"
    target.write_text("file in the way")
    rec, journal, reg = _recorder(tmp_path, out_dir=str(target))
    journal.record("registry_veto", version=1, reason="x")  # must not raise
    assert reg.value("flight_bundles_total",
                     labels={"trigger": "guardrail_veto"}) == 0
    rec.close()


def test_crash_handlers_journal_and_bundle_uncaught_exceptions(tmp_path):
    # no journal arg: the hooks must default to the RECORDER'S (isolated)
    # journal, not DEFAULT_JOURNAL — else this recorder never sees the
    # exception record and no crash bundle is written
    rec, journal, _ = _recorder(tmp_path)
    uninstall = install_crash_handlers(rec)
    try:
        def die():
            raise ValueError("thread died at 2am")

        t = threading.Thread(target=die, name="scorer")
        t.start()
        t.join()
        recs = journal.tail(kinds=("exception",))
        assert len(recs) == 1
        assert recs[0].data["type"] == "ValueError"
        assert "2am" in recs[0].data["message"]
        assert "die" in recs[0].data["traceback"]
        assert recs[0].stream == "scorer"
        names = [n for n in os.listdir(tmp_path / "bundles")
                 if n.startswith("bundle-")]
        assert len(names) == 1 and names[0].endswith("exception")
        assert (tmp_path / "bundles" / "faulthandler.log").exists() or \
            os.path.exists(os.path.join(rec.cfg.out_dir, "faulthandler.log"))
    finally:
        uninstall()
        rec.close()
    # uninstalled: a thread exception no longer journals
    t = threading.Thread(target=lambda: 1 / 0)
    t.start()
    t.join()
    assert len(journal.tail(kinds=("exception",))) == 1


# -- doctor -------------------------------------------------------------------

def _make_bundle(tmp_path):
    reg = MetricsRegistry(namespace="t")
    journal = EventJournal(registry=reg)
    tracer = Tracer(registry=reg)
    slo = SLOTracker(deadline_sec=0.5, registry=reg, journal=journal)
    with tracer.span("serve_batch_close", bucket="256n/512e/128s"):
        time.sleep(0.001)
    journal.record("config", config_fingerprint="cfg123")
    journal.record("batch_close", bucket="256n/512e/128s", cause="deadline",
                   occupancy=3, padding=5, trace_ids=["w-aaa", "w-bbb"])
    journal.record("admission_drop", stream="s1", window_id=7,
                   trace_id="w-ccc", reason="backpressure")
    slo.observe("s1", "w-bbb", 2, stages={"queue": 0.4, "device": 0.3},
                e2e_sec=0.8)
    rec = FlightRecorder(
        FlightConfig(out_dir=str(tmp_path / "bundles")),
        registry=reg, journal=journal, tracer=tracer, slo=slo,
        info=lambda: {"lineage": "default", "model_version": "v2"})
    path = rec.trigger("drop_burst", "3 drops in 1s", context={"drops": 3})
    rec.close()
    return path


def test_doctor_reconstructs_timeline_offline(tmp_path, capsys):
    path = _make_bundle(tmp_path)
    assert path is not None
    report = format_report(read_bundle(path))
    # header, timeline with the batch-close record, attribution, SLO state
    assert "trigger=drop_burst" in report
    assert "model: lineage=default model_version=v2" in report
    assert "batch_close" in report and "w-aaa,w-bbb" in report
    assert "admission_drop" in report and "reason=backpressure" in report
    assert "serve_batch_close" in report  # span table
    assert "s1" in report and "w-bbb" in report  # SLO exemplar
    assert "burn:" in report

    # the CLI surface, from the bundle alone (no live process)
    from nerrf_tpu.cli import main

    assert main(["doctor", str(path)]) == 0
    out = capsys.readouterr().out
    assert "incident timeline" in out and "batch_close" in out
    assert main(["doctor", str(path), "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["manifest"]["trigger"] == "drop_burst"
    assert any(r["kind"] == "batch_close" for r in parsed["records"])


def test_doctor_fails_politely_on_non_bundles(tmp_path, capsys):
    from nerrf_tpu.cli import main

    assert main(["doctor", str(tmp_path / "absent")]) == 2
    (tmp_path / "partial").mkdir()
    (tmp_path / "partial" / "manifest.json").write_text(
        json.dumps({"trigger": "exception", "reason": "crashed mid-dump"}))
    # partial bundle: report what exists, exit 1 (evidence incomplete)
    assert main(["doctor", str(tmp_path / "partial")]) == 1
    out = capsys.readouterr().out
    assert "MISSING" in out


# -- serve-path integration ---------------------------------------------------

def test_batcher_emits_batch_close_records_with_trace_ids():
    from nerrf_tpu.serve import MicroBatcher, ServeConfig, WindowRequest

    reg = MetricsRegistry(namespace="t")
    journal = EventJournal(registry=reg)
    bucket = (64, 128, 16)
    cfg = ServeConfig(buckets=(bucket,), batch_size=4, batch_close_sec=0.01)
    scored_out = []
    mb = MicroBatcher(
        score_fn=lambda b: np.full(b["node_mask"].shape, 0.9, np.float64),
        cfg=cfg, registry=reg, on_scored=scored_out.extend,
        journal=journal)
    mb.mark_warm(bucket)
    sample = {
        "node_mask": np.ones(bucket[0], bool),
        "node_type": np.zeros(bucket[0], np.int32),
        "node_key": np.arange(bucket[0], dtype=np.int64),
    }
    t0 = time.perf_counter()
    for i in range(3):
        mb.submit(WindowRequest(
            stream="s0", window_idx=i, lo_ns=0, hi_ns=1, bucket=bucket,
            sample=dict(sample), t_admit=t0, deadline=t0 + 5.0,
            trace_id=make_trace_id("s0", i, 0)))
    assert mb.drain_once(force=True) == 1
    recs = journal.tail(kinds=("batch_close",))
    assert len(recs) == 1
    r = recs[0]
    assert r.data["occupancy"] == 3 and r.data["padding"] == 1
    assert r.data["cause"] == "flush" and r.data["streams"] == ["s0"]
    assert r.data["trace_ids"] == [make_trace_id("s0", i, 0)
                                   for i in range(3)]
    # demuxed windows carry the id + the stage stamps the SLO plane needs
    assert len(scored_out) == 3
    for s in scored_out:
        assert s.trace_id and s.t_packed >= t0 and s.t_device >= s.t_packed


def test_alert_sink_journals_the_evicted_alert():
    from nerrf_tpu.serve.alerts import AlertSink, WindowAlert

    reg = MetricsRegistry(namespace="t")
    journal = EventJournal(registry=reg)
    sink = AlertSink(slots=2, registry=reg, journal=journal)

    def alert(i):
        return WindowAlert(stream="s0", window_idx=i, lo_ns=0, hi_ns=1,
                           max_prob=0.9, hot=[], t_admit=0.0, t_scored=0.1,
                           late=False, trace_id=f"w-{i}")

    assert sink.emit(alert(0)) and sink.emit(alert(1))
    assert not sink.emit(alert(2))  # evicts alert 0
    drops = journal.tail(kinds=("demux_drop",))
    assert len(drops) == 1
    assert drops[0].window_id == 0 and drops[0].trace_id == "w-0"
    assert drops[0].data["reason"] == "sink_full"
