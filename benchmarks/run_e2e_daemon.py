#!/usr/bin/env python3
"""End-to-end artifact with the NATIVE DAEMON in the loop (VERDICT r4 #3).

Until r5, every trace a detector consumed was handed over in-process; no
model had ever scored bytes that crossed the real wire.  This harness
closes that: a real-file incident (`nerrf simulate` attacks actual files
on disk) is streamed by `nerrf-trackerd --replay` through its hand-rolled
HTTP/2 gRPC server, drained by the deployed ingest CLI (stock grpcio →
native C++ decode → time-bucketed trace store), read back OUT of the
store, and only THAT copy drives detect → plan → sandbox gate → undo on
the still-encrypted files.

  simulate ──> trace.jsonl ──> trackerd --replay ══HTTP/2══> nerrf ingest
       │                                                        │
       └─ victim files (encrypted, on disk)          wire_store segments
                                                              │
          undo <── wire_trace.jsonl <── TraceStore.query ─────┘

This is the reference's tracker-in-loop intent (`tracker/scripts/test.sh:
76-82` drives the Go daemon with grpcurl) carried through to recovery —
which the reference never built.  Live CAP_BPF capture replaces --replay
on hosts that allow it (`tests/test_capture.py` covers that path).

Usage:
  python benchmarks/run_e2e_daemon.py --out benchmarks/results/e2e_daemon.json
  ... [--files 20] [--rate 500] [--model-dir runs/probe-corpus-cpu/model]
"""

from __future__ import annotations

import argparse
import json
import shutil
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def _log(msg):
    print(f"[e2e] {msg}", file=sys.stderr, flush=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="benchmarks/results/e2e_daemon.json")
    ap.add_argument("--incident", default="/tmp/nerrf_e2e_daemon")
    ap.add_argument("--files", type=int, default=20)
    ap.add_argument("--rate", type=int, default=500,
                    help="replay pacing, events/s (VERDICT asks ~500)")
    ap.add_argument("--model-dir", default=None,
                    help="detector checkpoint; default: probe checkpoint "
                         "when present, else heuristic")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--live", action="store_true",
                    help="LIVE kernel capture (CAP_BPF): the daemon "
                         "captures the attack's real syscalls system-wide "
                         "while it runs, instead of replaying the "
                         "simulator's trace file")
    args = ap.parse_args(argv)

    daemon = REPO / "native" / "build" / "nerrf-trackerd"
    if not daemon.exists():
        r = subprocess.run(["make", "-C", str(REPO / "native"),
                            "build/nerrf-trackerd"],
                           capture_output=True, text=True)
        if r.returncode != 0:
            _log(f"daemon build failed: {r.stderr[-400:]}")
            return 1

    model_dir = args.model_dir
    if model_dir is None:
        probe = REPO / "runs" / "probe-corpus-cpu" / "model"
        model_dir = str(probe) if probe.exists() else None

    t0 = time.time()
    inc = Path(args.incident)
    if inc.exists():
        shutil.rmtree(inc)

    from nerrf_tpu.ingest.service import spawn_trackerd

    def start_daemon(extra):
        return spawn_trackerd(extra, daemon_path=daemon)

    def simulate():
        _log(f"simulate: {args.files} files under {inc}/victim")
        r = subprocess.run(
            [sys.executable, "-m", "nerrf_tpu.cli", "simulate",
             "--incident", str(inc), "--files", str(args.files),
             "--seed", str(args.seed)],
            cwd=REPO, capture_output=True, text=True)
        assert r.returncode == 0, r.stderr[-800:]
        return sum(1 for _ in open(inc / "trace.jsonl"))

    t_ing = time.time()
    if args.live:
        # --- live: daemon captures the REAL attack syscalls system-wide --
        proc, port = start_daemon(["--max-seconds", "120"])
        _log(f"trackerd LIVE capture on :{port}")
        ing = subprocess.Popen(
            [sys.executable, "-m", "nerrf_tpu.cli", "ingest",
             "--target", f"127.0.0.1:{port}",
             "--store-dir", str(inc / "wire_store"),
             "--metrics-port", "-1", "--timeout", "45"],
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        time.sleep(5)  # let the subscription settle before the attack
        n_src = simulate()
        out, err = ing.communicate(timeout=180)
        proc.terminate()
        proc.wait(timeout=10)
        assert ing.returncode == 0, err[-800:]
        ingest = json.loads(out)
    else:
        # --- replay: daemon streams the simulator's trace file -----------
        n_src = simulate()
        proc, port = start_daemon(["--replay", str(inc / "trace.jsonl"),
                                   "--replay-rate", str(args.rate)])
        _log(f"trackerd replaying {n_src} events at ~{args.rate}/s on :{port}")
        r = subprocess.run(
            [sys.executable, "-m", "nerrf_tpu.cli", "ingest",
             "--target", f"127.0.0.1:{port}",
             "--store-dir", str(inc / "wire_store"),
             "--metrics-port", "-1", "--timeout", "120"],
            cwd=REPO, capture_output=True, text=True, timeout=180)
        proc.terminate()
        proc.wait(timeout=10)
        assert r.returncode == 0, r.stderr[-800:]
        ingest = json.loads(r.stdout)
    wire_seconds = round(time.time() - t_ing, 1)
    _log(f"ingest: {ingest['events']} events, "
         f"{ingest['segments_written']} segments in {wire_seconds}s")

    # --- 4. read back out of the store; wire parity --------------------------
    from nerrf_tpu.graph.store import TraceStore
    from nerrf_tpu.schema.events import events_to_jsonl

    with TraceStore(inc / "wire_store") as st:
        events, strings = st.query(0, 2**63 - 1)
    n_wire = int(events.num_valid)
    (inc / "wire_trace.jsonl").write_text(events_to_jsonl(events, strings))
    _log(f"store read-back: {n_wire} events (source {n_src})")
    n_victim = None
    if args.live:
        # live capture is system-wide: parity is "the attack is IN there",
        # not an exact count — the victim's renames must have crossed the
        # kernel → ring buffer → HTTP/2 → store path
        victim_prefix = str(inc / "victim")
        idx = [i for i in range(len(events))
               if events.valid[i]
               and strings.lookup(int(events.path_id[i]))
                          .startswith(victim_prefix)]
        n_victim = len(idx)
        renames = sum(
            1 for i in idx
            if strings.lookup(int(events.new_path_id[i]))
                      .endswith(".lockbit3"))
        _log(f"live capture: {n_victim} victim-path events, "
             f"{renames} .lockbit3 renames (of {args.files} encrypted)")
        assert renames >= args.files, \
            f"live capture missed renames: {renames}/{args.files}"
    else:
        assert n_wire == n_src, f"wire loss: {n_src} sent, {n_wire} stored"

    # --- 5. detect -> plan -> gate -> undo on the WIRE copy ------------------
    model_on_live = None
    if args.live and model_dir:
        # On live full-system capture the probe model ranks the victims at
        # the TOP of its scores but below its synthetic-corpus-calibrated
        # cut (measured: victims ≈0.67 vs cut 0.42 — flagged — but the
        # planner's FP-cost model rationally declines 0.67-confidence
        # restores of 0.1 MB files).  Record that ranking as data; let the
        # indicator heuristic drive the undo — live capture delivers the
        # rename indicators intact, and indicator detection is precisely
        # the reference's deployed design (threat-model.mdx:275-319).
        try:
            import jax

            jax.config.update("jax_platforms", "cpu")
            from nerrf_tpu.data.loaders import load_trace_jsonl
            from nerrf_tpu.models import NerrfNet
            from nerrf_tpu.pipeline import model_detect
            from nerrf_tpu.train.checkpoint import (
                load_calibration,
                load_checkpoint,
            )

            tr = load_trace_jsonl(inc / "wire_trace.jsonl")
            params, mcfg = load_checkpoint(model_dir)
            cal = load_calibration(model_dir)
            det = model_detect(tr, params, NerrfNet(mcfg),
                               threshold=cal.get("node_threshold"))
            ranked = sorted(det.file_scores.items(), key=lambda kv: -kv[1])
            victim_prefix = str(inc / "victim")
            top = [p for p, _ in ranked[: args.files]]
            victims_in_top = sum(1 for p in top
                                 if p.startswith(victim_prefix))
            model_on_live = {
                "victims_in_top_k": victims_in_top,
                "k": args.files,
                "top_score": round(float(ranked[0][1]), 4) if ranked else None,
                "threshold": det.threshold,
                "flagged": len(det.flagged_files()),
                "note": "ranking quality only; heuristic drives the undo "
                        "on live capture",
            }
            _log(f"model on live wire: {victims_in_top}/{args.files} "
                 f"victims in top-{args.files}")
        except Exception as e:  # noqa: BLE001 — stats leg must not sink e2e
            model_on_live = {"error": f"{type(e).__name__}: {e}"}
    undo_cmd = [sys.executable, "-m", "nerrf_tpu.cli", "undo",
                "--incident", str(inc),
                "--trace", str(inc / "wire_trace.jsonl")]
    if model_dir and not args.live:
        undo_cmd += ["--model-dir", model_dir]
    t_undo = time.time()
    r = subprocess.run(undo_cmd, cwd=REPO, capture_output=True, text=True,
                       timeout=1200)
    undo_log = r.stderr[-2000:]
    _log(undo_log.strip().splitlines()[-1] if undo_log.strip() else "(no log)")
    gate_note = None
    if args.live and r.returncode == 3:
        # rc 3 = the sandbox gate refused.  EXPECTED for live capture: a
        # kernel-captured trace is not content-complete (fd-based writes
        # of sub-poll-lifetime fds have no path; an fd renamed mid-write
        # resolves to its new name), so deterministic replay cannot fully
        # explain the damage.  The gate catching that is the gate WORKING.
        # The snapshot-hash restore path doesn't need the trace at all —
        # rerun ungated and let executor verification be the proof.
        gate = json.loads((inc / "gate.json").read_text())
        gate_note = gate.get("reason")
        _log(f"gate refused (expected for live capture): {gate_note}")
        _log("re-running ungated: snapshot-hash restore needs no replay")
        r = subprocess.run(undo_cmd + ["--no-gate"], cwd=REPO,
                           capture_output=True, text=True, timeout=1200)
        undo_log = r.stderr[-2000:]
        _log(undo_log.strip().splitlines()[-1]
             if undo_log.strip() else "(no log)")
    assert r.returncode == 0, undo_log

    report = json.loads((inc / "report.json").read_text())
    gate = json.loads((inc / "gate.json").read_text())
    plan = json.loads((inc / "plan.json").read_text())

    artifact = {
        "flow": ("simulate (attack) + trackerd LIVE kernel capture "
                 "(HTTP/2) -> ingest -> store -> detect -> plan -> gate "
                 "-> undo" if args.live else
                 "simulate -> trackerd --replay (HTTP/2) -> ingest -> "
                 "store -> detect -> plan -> gate -> undo"),
        "daemon": "native/build/nerrf-trackerd (hand-rolled h2grpc)",
        "capture": "live raw-bpf(2) kernel capture" if args.live
                   else "trace replay",
        "detector": ("heuristic (indicator rules; see model_on_live)"
                     if args.live else
                     f"checkpoint:{model_dir}" if model_dir else "heuristic"),
        "model_on_live": model_on_live,
        "events": ({"source": n_src, "wire_total": n_wire,
                    "wire_victim": n_victim} if args.live else
                   {"source": n_src, "wire": n_wire,
                    "lost": n_src - n_wire}),
        "replay_rate_hz": None if args.live else args.rate,
        "wire_seconds": wire_seconds,
        "store_segments": ingest["segments_written"],
        "detection_flagged": len(plan.get("actions", [])),
        "gate_approved": gate.get("approved"),
        "gate_note": gate_note,
        "undo": {
            "files_restored": report.get("files_restored"),
            "verified": report.get("verified"),
            "data_loss_bytes": report.get("data_loss_bytes", 0),
            "mttr_seconds": report.get("mttr_seconds"),
            "undo_wall_seconds": round(time.time() - t_undo, 1),
        },
        "provenance": "python benchmarks/run_e2e_daemon.py",
        "wall_seconds": round(time.time() - t0, 1),
    }
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(artifact, indent=2) + "\n")
    print(json.dumps({"events_wire": n_wire,
                      "verified": report.get("verified"),
                      "mttr_seconds": report.get("mttr_seconds")}))
    return 0 if report.get("verified") else 1


if __name__ == "__main__":
    raise SystemExit(main())
