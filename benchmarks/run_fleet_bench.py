#!/usr/bin/env python3
"""Fleet control plane soak: autoscaling, SLO-aware shedding, and the
archive-compare regression gate, end to end over REAL multi-process
replicas (docs/fleet.md).

Every replica is ``python -m nerrf_tpu.fleet.replica`` — the production
`OnlineDetectionService` behind a `MetricsServer`, scraped over HTTP
exactly as Prometheus would.  The load legs pin the device program to a
deterministic known-cost scorer (``--synthetic-cost``: sleep per REAL
window, zero compiles), so the saturation point is analytic
(1/(rate x cost) streams) and the gates are exact:

  A. **measured saturation** — one replica, streams added until the
     delivered/offered ratio collapses: k* (the measured saturation
     stream count) must match the analytic prediction's neighborhood.
  B. **takeover + autoscale** — `ReplicaSet` + `FleetController`: two
     placed streams must trigger scale-OUT strictly BELOW k* (the
     predicted headroom leads the measured collapse — that is the whole
     point of autoscaling on the prediction), rebalance one stream
     through the deterministic slot map with it still scoring on its
     new replica, and scale back IN on sustained slack (the emptied
     replica's frozen gauge read as slack, not trusted).
  C. **SLO-aware shedding** — an overloaded replica with one physically
     expensive budget-burner (dense windows on the big-bucket rung, 4x
     the device cost) and one healthy small-bucket stream: every shed
     victim must be the burner (top of the recorded burn ranking),
     never the healthy stream, which keeps delivering.
  D. **warm boot + parity** — two real-model replicas through one shared
     compile cache: the second boots with every bucket from cache, zero
     post-warmup recompiles, and both hold bit-parity to the offline
     `model_detect` — the standing serve contracts survive fleet
     orchestration.
  E. **compare gate** — two archived known-cost runs, the candidate 3x
     the device cost: `nerrf report --compare --gate` must exit nonzero
     on the regression, zero on self-compare, and zero when the CLI
     tolerance knobs are loosened.

    python benchmarks/run_fleet_bench.py            # full soak
    python benchmarks/run_fleet_bench.py --smoke    # short probes
    python benchmarks/run_fleet_bench.py --out results/fleet_bench_cpu.json

Prints ONE JSON line (the artifact); exit 1 if any gate fails.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

# known-cost leg shape: 0.05 s/window → 20 windows/s capacity; at 6
# windows/s per stream the analytic saturation is 1/(6 × 0.05) ≈ 3.3
# streams, so the measured collapse lands at k*=4 and the controller
# (band edge at 1.5 streams of headroom) must fire at 2
COST = 0.05
RATE = 6.0
DEVTIME_WINDOW = 8.0
BUCKET = "256x512x64"


def _log(*a) -> None:
    print("[fleet-bench]", *a, file=sys.stderr, flush=True)


def _boot(name: str, **spec):
    from nerrf_tpu.fleet import ReplicaProcess, replica_args

    spec.setdefault("buckets", BUCKET)
    spec.setdefault("devtime_window_sec", DEVTIME_WINDOW)
    return ReplicaProcess(name, args=replica_args(**spec),
                          env={"JAX_PLATFORMS": "cpu"}, log=_log)


def _scored(stats: dict) -> int:
    return int(stats.get("windows_scored") or 0)


def part_a_saturation(probe_sec: float, max_streams: int = 6) -> dict:
    """Measured saturation: add streams until delivered/offered < 0.85."""
    rep = _boot("sat", synthetic_cost=COST, queue_slots=64,
                deadline_sec=2.0)
    ratios, k_star = [], None
    try:
        for k in range(1, max_streams + 1):
            rep.cmd("assign", stream=f"probe{k}", rate_hz=RATE)
            time.sleep(2.0)  # settle: feeder up, first windows closing
            before = _scored(rep.cmd("stats"))
            time.sleep(probe_sec)
            delivered = _scored(rep.cmd("stats")) - before
            offered = k * RATE * probe_sec
            ratio = delivered / offered
            ratios.append(round(ratio, 3))
            _log(f"saturation probe k={k}: {delivered}/{offered:.0f} "
                 f"windows ({ratio:.2f})")
            if ratio < 0.85:
                k_star = k
                break
    finally:
        rep.stop()
    return {"cost_sec_per_window": COST, "rate_hz": RATE,
            "delivered_ratio_by_streams": ratios,
            "analytic_saturation_streams": round(1.0 / (RATE * COST), 2),
            "measured_saturation_streams": k_star}


def part_b_autoscale(k_star: int, work: Path) -> dict:
    """Two offered streams under the real controller: out strictly below
    k*, rebalance with the moved stream still scoring, in on slack.

    The streams are registered and PLACED (one manual reconciliation
    poll) before the controller's own loop starts — the controller then
    watches the measured headroom sink as the feeders ramp, exactly the
    takeover-a-running-pod scenario the production controller faces."""
    from nerrf_tpu.flight.journal import EventJournal
    from nerrf_tpu.fleet import FleetConfig, FleetController, ReplicaSet
    from nerrf_tpu.observability import MetricsRegistry

    def spawn(name):
        return _boot(name, synthetic_cost=COST, queue_slots=64,
                     deadline_sec=2.0)

    reg = MetricsRegistry()
    jrn = EventJournal(registry=reg)
    rs = ReplicaSet(spawn, max_replicas=2, log=_log)
    rs.scale_out()  # r0: the steady-state single replica
    ctl = FleetController(
        rs, FleetConfig(poll_sec=0.5, scale_out_below=1.5,
                        scale_in_above=4.0, scale_out_sustain=2,
                        scale_in_sustain=4, cooldown_sec=4.0,
                        max_replicas=2),
        registry=reg, journal=jrn, log=_log)
    out = {"streams_at_scale_out": None, "scale_in": False,
           "rebalance_moved": [], "moved_stream_scoring": False,
           "decisions": []}

    def scale_events(direction):
        return [d for d in ctl.decisions if d["kind"] == "fleet_scale"
                and d["direction"] == direction]

    # load0 → slot 0, load1 → slot 1 under a 2-replica map: the
    # scale-out is guaranteed a real move to record
    rs.add_stream("load0", RATE)
    rs.add_stream("load1", RATE)
    ctl.poll_once()  # manual reconciliation: place both on r0
    ctl.start()
    try:
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if scale_events("out"):
                out["streams_at_scale_out"] = 2
                break
            time.sleep(0.25)
        # rebalance follows the membership change within a poll
        deadline = time.monotonic() + 12.0
        while time.monotonic() < deadline:
            rebs = [d for d in ctl.decisions
                    if d["kind"] == "fleet_rebalance"]
            if rebs:
                out["rebalance_moved"] = rebs[-1]["moved"]
                break
            time.sleep(0.25)
        if out["rebalance_moved"]:
            moved = out["rebalance_moved"][0]
            target = [d for d in ctl.decisions
                      if d["kind"] == "fleet_rebalance"][-1]["slots"][moved]
            rep = rs.replicas().get(target)
            if rep is not None:
                def moved_count():
                    per = (rep.cmd("stats")["slo"].get("per_stream")
                           or {})
                    return sum(v.get("count", 0)
                               for k, v in per.items()
                               if k.split("#", 1)[0] == moved)
                base = moved_count()
                deadline = time.monotonic() + 20.0
                while time.monotonic() < deadline:
                    if moved_count() > base:
                        out["moved_stream_scoring"] = True
                        break
                    time.sleep(0.5)
        # slack: drop the load, keep a trickle on r0 — r1 goes idle
        # (stale gauge, read as pure slack) and must be retired
        rs.remove_stream("load0")
        rs.remove_stream("load1")
        rs.add_stream("cool", 1.0)
        deadline = time.monotonic() + 45.0
        while time.monotonic() < deadline:
            if scale_events("in"):
                out["scale_in"] = True
                break
            time.sleep(0.5)
    finally:
        ctl.stop()
        rs.stop_all()
    out["decisions"] = [
        {k: v for k, v in d.items() if k != "evidence"}
        for d in ctl.decisions if d["kind"] == "fleet_scale"]
    out["k_star"] = k_star
    return out


def part_c_shed(soak_sec: float) -> dict:
    """Overload with one budget-burner + one healthy stream: every shed
    victim must be the burner, top of the recorded ranking.

    The burner is physically expensive, not just fast: its dense windows
    (events_hz=120) climb to the 1024-node bucket, where the known-cost
    device charges 4x the device seconds per window — so its trailing
    SLO budget burn (queue+pack+device) genuinely dominates the healthy
    stream's, which keeps scoring cheap small-bucket windows.  (A
    same-bucket burner would NOT rank worst: drop-oldest keeps its
    scored windows fresh, laundering its queue latency — the ranking
    needs a real cost asymmetry, which is exactly what it is for.)"""
    rep = _boot("shed", synthetic_cost=COST, queue_slots=4,
                deadline_sec=1.0, shed_margin=1.0,
                buckets="160x320x64,1024x2048x64")
    try:
        rep.cmd("assign", stream="burn", rate_hz=30.0, events_hz=120.0)
        rep.cmd("assign", stream="heal", rate_hz=8.0)
        time.sleep(soak_sec)
        stats = rep.cmd("stats")
    finally:
        rep.stop()
    sheds = stats.get("shed_records") or []
    victims = sorted({r["stream"].split("#", 1)[0] for r in sheds})
    ranking_ok = all(
        (r["data"].get("ranking") or [["?"]])[0][0] == "burn"
        for r in sheds)
    per = stats["slo"].get("per_stream") or {}
    heal_scored = sum(v.get("count", 0) for k, v in per.items()
                     if k.split("#", 1)[0] == "heal")
    return {"shed_records": len(sheds), "victims": victims,
            "ranking_all_topped_by_burner": ranking_ok,
            "healthy_windows_scored": int(heal_scored),
            "dropped": stats.get("dropped")}


def part_d_warmboot(work: Path) -> dict:
    """Two real-model replicas through one shared compile cache: the
    second boots warm; both hold offline bit-parity."""
    cache = str(work / "aot_cache")
    out = {}
    for name in ("r0", "r1"):
        rep = _boot(name, synthetic_cost=0.0, compile_cache=cache,
                    queue_slots=64, deadline_sec=5.0)
        try:
            parity = rep.cmd("parity", timeout=300.0)
            stats = rep.cmd("stats")
        finally:
            rep.stop()
        out[name] = {
            "parity_bit_identical_to_model_detect":
                parity.get("parity") is True,
            "parity_windows": parity.get("windows"),
            "warmup_source": stats.get("warmup_source"),
            "recompiles_after_warmup":
                stats.get("recompiles_after_warmup"),
        }
        _log(f"warmboot {name}: sources={out[name]['warmup_source']} "
             f"parity={out[name]['parity_bit_identical_to_model_detect']}")
    return out


def part_e_compare_gate(work: Path, soak_sec: float) -> dict:
    """Two archived runs, candidate at 3x device cost: the gate must
    fail the regression, pass self-compare, pass with loose knobs."""
    from nerrf_tpu import cli

    dirs = {}
    for name, cost in (("base", 0.02), ("cand", 0.06)):
        adir = str(work / f"archive_{name}")
        rep = _boot(name, synthetic_cost=cost, queue_slots=64,
                    deadline_sec=2.0, archive_dir=adir, snapshot_sec=1.0)
        try:
            rep.cmd("assign", stream="a0", rate_hz=5.0)
            rep.cmd("assign", stream="a1", rate_hz=5.0)
            time.sleep(soak_sec)
        finally:
            rep.stop()
        dirs[name] = adir
    rc_regress = cli.main(["report", dirs["cand"], "--compare",
                           dirs["base"], dirs["cand"], "--gate"])
    rc_self = cli.main(["report", dirs["base"], "--compare",
                        dirs["base"], dirs["base"], "--gate"])
    rc_loose = cli.main(["report", dirs["cand"], "--compare",
                         dirs["base"], dirs["cand"], "--gate",
                         "--cost-ratio", "10", "--p99-ratio", "10"])
    return {"rc_regression": rc_regress, "rc_self_compare": rc_self,
            "rc_loose_knobs": rc_loose}


def run(smoke: bool = False, log=_log) -> dict:
    probe_sec = 5.0 if smoke else 10.0
    shed_sec = 10.0 if smoke else 25.0
    archive_sec = 8.0 if smoke else 20.0
    work = Path(tempfile.mkdtemp(prefix="fleet_bench_"))
    try:
        log("part A: measured saturation")
        sat = part_a_saturation(probe_sec)
        k_star = sat["measured_saturation_streams"] or 4
        log(f"part B: controlled ramp (k*={k_star})")
        autoscale = part_b_autoscale(k_star, work)
        log("part C: SLO-aware shedding")
        shed = part_c_shed(shed_sec)
        log("part D: warm boot + parity")
        warmboot = part_d_warmboot(work)
        log("part E: compare gate")
        compare = part_e_compare_gate(work, archive_sec)
    finally:
        shutil.rmtree(work, ignore_errors=True)
    n_at_out = autoscale.get("streams_at_scale_out")
    return {
        "metric": "fleet_scale_out_lead_streams",
        "value": (None if n_at_out is None else k_star - n_at_out),
        "unit": "streams of lead between controller scale-out and the "
                "measured saturation point",
        "backend": "cpu",  # multi-process soak is CPU-only by design
        "smoke": smoke or None,
        "saturation": sat,
        "autoscale": autoscale,
        "shed": shed,
        "warmboot": warmboot,
        "compare_gate": compare,
        "recompiles_after_warmup": sum(
            warmboot[r]["recompiles_after_warmup"] or 0
            for r in ("r0", "r1")),
        "provenance": "python benchmarks/run_fleet_bench.py"
                      + (" --smoke" if smoke else ""),
    }


def gates(result: dict) -> list:
    """Every acceptance gate, as (name, ok) — shared by main() and the
    artifact-of-record test."""
    sat, auto = result["saturation"], result["autoscale"]
    shed, warm = result["shed"], result["warmboot"]
    cmp_ = result["compare_gate"]
    k_star = sat["measured_saturation_streams"]
    n_out = auto["streams_at_scale_out"]
    return [
        ("saturation_measured", k_star is not None),
        ("scale_out_before_measured_saturation",
         n_out is not None and k_star is not None and n_out < k_star),
        ("rebalance_recorded", bool(auto["rebalance_moved"])),
        ("moved_stream_keeps_scoring",
         auto["moved_stream_scoring"] is True),
        ("scale_in_on_sustained_slack", auto["scale_in"] is True),
        ("shed_fired_under_overload", shed["shed_records"] > 0),
        ("shed_victims_only_the_burner", shed["victims"] == ["burn"]),
        ("shed_ranking_topped_by_burner",
         shed["ranking_all_topped_by_burner"] is True),
        ("healthy_stream_kept_scoring",
         shed["healthy_windows_scored"] > 0),
        ("warm_replica_boots_from_cache",
         bool(warm["r1"]["warmup_source"]) and all(
             s == "cache" for s in warm["r1"]["warmup_source"].values())),
        ("zero_recompiles_per_replica",
         result["recompiles_after_warmup"] == 0),
        ("parity_bit_identical_both_replicas", all(
            warm[r]["parity_bit_identical_to_model_detect"]
            for r in ("r0", "r1"))),
        ("gate_fails_injected_regression",
         cmp_["rc_regression"] == 1),
        ("gate_passes_self_compare", cmp_["rc_self_compare"] == 0),
        ("gate_respects_cli_knobs", cmp_["rc_loose_knobs"] == 0),
    ]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="short probes/soaks")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="also write the artifact JSON here")
    args = ap.parse_args(argv)

    result = run(smoke=args.smoke)
    print(json.dumps(result))
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as f:
            f.write(json.dumps(result, indent=2) + "\n")
    failed = [name for name, ok in gates(result) if not ok]
    for name in failed:
        print(f"[fleet-bench] GATE FAILED: {name}", file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
