"""Capacity headroom: how many more streams fit before the device saturates.

The admission plane already sheds load when it must (drop-oldest,
quarantine, deadline close); this module answers the question operators
need BEFORE that happens: at the observed per-stream arrival rates and
the measured per-bucket device cost, how many more average streams does
this device absorb?  Exported as ``nerrf_capacity_headroom_streams`` and
journaled as a ``capacity_saturation`` record when the prediction says
the next stream would not fit — evidence *ahead* of the first drop burst.

The math is deliberately first-order queue-free utilization accounting:

    util               = Σ_streams  rate_s · Σ_buckets mix_s[b] · cost[b]
    mean_demand        = util / num_streams          (device-sec per sec,
                                                      per average stream)
    headroom_streams   = (1 − util) / mean_demand
    saturation_streams = num_streams + headroom      (= 1/mean_demand for
                                                      a homogeneous mix)

Per-window cost is MEASURED under the live occupancy (total device-busy
seconds / windows scored, per bucket), so batching efficiency is already
inside ``cost[b]`` — the prediction extrapolates the current operating
point, it does not model the occupancy curve.  That makes it honest near
the current load and a band estimate far from it, which is exactly what
the serve bench's ramp leg gates (prediction within a band of measured
saturation).

Degenerate cases return ``None`` — zero traffic, unknown buckets, no
measured cost — never a fabricated number (same null-not-fake contract
as the MFU plane).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Dict, Optional


@dataclasses.dataclass(frozen=True)
class HeadroomEstimate:
    """One headroom prediction at one instant."""

    streams: int                      # streams observed arriving
    util: float                       # predicted device-busy fraction
    mean_stream_demand: float         # device-sec/sec per average stream
    headroom_streams: float           # additional average streams that fit
    saturation_streams: float         # streams + headroom
    per_bucket_util: Dict[str, float]

    def to_dict(self) -> dict:
        return {
            "streams": self.streams,
            "util": round(self.util, 4),
            "mean_stream_demand": round(self.mean_stream_demand, 6),
            "headroom_streams": round(self.headroom_streams, 2),
            "saturation_streams": round(self.saturation_streams, 2),
            "per_bucket_util": {k: round(v, 4)
                                for k, v in sorted(self.per_bucket_util
                                                   .items())},
        }


def predict_headroom(
        stream_rates: Dict[str, float],
        stream_mix: Dict[str, Dict[str, float]],
        cost_per_window: Dict[str, float]) -> Optional[HeadroomEstimate]:
    """Pure headroom math (the unit-testable core).

    ``stream_rates``: stream → windows/sec arriving.
    ``stream_mix``:   stream → {bucket tag → fraction of its windows}.
    ``cost_per_window``: bucket tag → measured device-seconds per window.

    Returns None (never a fake number) when there is no traffic, when a
    stream's windows land in a bucket with no measured cost (unknown
    bucket), or when any input is degenerate.
    """
    streams = [s for s, r in stream_rates.items() if r > 0]
    if not streams:
        return None
    util = 0.0
    per_bucket: Dict[str, float] = {}
    for s in streams:
        mix = stream_mix.get(s)
        if not mix:
            return None
        for tag, frac in mix.items():
            if frac <= 0:
                continue
            cost = cost_per_window.get(tag)
            if cost is None or cost <= 0:
                return None  # unknown bucket: no honest prediction
            u = stream_rates[s] * frac * cost
            util += u
            per_bucket[tag] = per_bucket.get(tag, 0.0) + u
    if util <= 0:
        return None
    mean_demand = util / len(streams)
    headroom = (1.0 - util) / mean_demand
    return HeadroomEstimate(
        streams=len(streams), util=util, mean_stream_demand=mean_demand,
        headroom_streams=headroom,
        saturation_streams=len(streams) + headroom,
        per_bucket_util=per_bucket)


class HeadroomTracker:
    """Windowed arrival/cost observer feeding `predict_headroom`.

    Fed from the serve hot path (an admit record per window, a device
    record per batch) and read on a cadence; all state is trailing
    (``window_sec``), so the estimate follows the live traffic mix, not
    the pod's whole history."""

    def __init__(self, window_sec: float = 60.0) -> None:
        self.window_sec = max(float(window_sec), 1e-3)
        self._lock = threading.Lock()
        self._admits: deque = deque()     # (t, stream, tag)
        self._batches: deque = deque()    # (t, tag, device_sec, windows)

    def observe_admit(self, stream: str, tag: str,
                      t: Optional[float] = None) -> None:
        t = time.monotonic() if t is None else t
        with self._lock:
            self._admits.append((t, stream, tag))
            self._evict(t)

    def observe_batch(self, tag: str, device_sec: float, windows: int,
                      t: Optional[float] = None) -> None:
        t = time.monotonic() if t is None else t
        with self._lock:
            self._batches.append((t, tag, float(device_sec), int(windows)))
            self._evict(t)

    def _evict(self, now: float) -> None:
        lo = now - self.window_sec
        while self._admits and self._admits[0][0] < lo:
            self._admits.popleft()
        while self._batches and self._batches[0][0] < lo:
            self._batches.popleft()

    def estimate(self, now: Optional[float] = None
                 ) -> Optional[HeadroomEstimate]:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._evict(now)
            admits = list(self._admits)
            batches = list(self._batches)
        if not admits or not batches:
            return None
        # the observation span: clamp to the data actually seen so a
        # freshly started tracker doesn't divide a second of traffic by
        # the full window and under-read every rate
        t0 = min(admits[0][0], batches[0][0])
        span = max(now - t0, 1e-3)
        counts: Dict[str, Dict[str, int]] = {}
        for _t, stream, tag in admits:
            per = counts.setdefault(stream, {})
            per[tag] = per.get(tag, 0) + 1
        rates = {s: sum(tags.values()) / span for s, tags in counts.items()}
        mix = {s: {tag: n / sum(tags.values())
                   for tag, n in tags.items()}
               for s, tags in counts.items()}
        busy: Dict[str, float] = {}
        scored: Dict[str, int] = {}
        for _t, tag, dev, win in batches:
            busy[tag] = busy.get(tag, 0.0) + dev
            scored[tag] = scored.get(tag, 0) + win
        cost = {tag: busy[tag] / scored[tag]
                for tag in busy if scored.get(tag, 0) > 0}
        return predict_headroom(rates, mix, cost)
